#!/usr/bin/env python
"""Per-operator micro-benchmark harness (reference ``benchmark/opperf/``).

Times forward (and, for differentiable ops, forward+backward) of
registered ops on synthetic inputs and prints a table + JSON. The
reference runs each op through its imperative path with the profiler;
here each op runs through the same `mx.np`/`npx` dispatch the user calls,
timed with the two-loop difference method (see bench.py) so the numbers
hold on lazy/tunnelled runtimes too.

Usage::

    python benchmark/opperf.py                 # default op set
    python benchmark/opperf.py --ops add,dot,tanh --shape 512,512
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_OPS = ("add multiply divide dot tanh exp log sqrt sum mean max "
               "argsort softmax relu sigmoid matmul transpose concatenate "
               "where clip")


def _timed(fn, fetch, k1=5, k2=25):
    from bench import _timed_diff  # repo-root bench.py: shared timer

    return _timed_diff(fn, fetch, k1, k2)


def bench_op(name, shape):
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu import np as mnp
    from mxnet_tpu import npx

    rng = onp.random.RandomState(0)
    a = mnp.array(rng.uniform(0.5, 2, shape).astype("float32"))
    b = mnp.array(rng.uniform(0.5, 2, shape).astype("float32"))

    fn = getattr(mnp, name, None) or getattr(npx, name, None)
    if fn is None:
        return None
    try:
        sig_args = (a, b) if name in (
            "add", "multiply", "divide", "dot", "matmul",
        ) else (a,)
        if name == "concatenate":
            sig_args = ([a, b],)
        if name == "where":
            sig_args = (a > 1, a, b)
        if name == "clip":
            sig_args = (a, 0.8, 1.5)
        fn(*sig_args).wait_to_read()
    except Exception as e:  # noqa: BLE001
        return {"op": name, "error": f"{type(e).__name__}: {e}"}

    fwd = _timed(lambda: fn(*sig_args), lambda r: r.asnumpy())

    bwd = None
    try:
        a.attach_grad()
        with autograd.record():
            out = fn(*sig_args)
        out.backward()

        def step():
            with autograd.record():
                o = fn(*sig_args)
            o.backward()
            return a.grad

        bwd = _timed(step, lambda r: r.asnumpy())
    except Exception:  # non-differentiable / int-valued
        bwd = None
    row = {"op": name, "shape": list(shape),
           "fwd_us": round(fwd * 1e6, 1)}
    if bwd is not None:
        row["fwd_bwd_us"] = round(bwd * 1e6, 1)
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description="per-op perf harness")
    ap.add_argument("--ops", default=None,
                    help="comma-separated op names (default: common set)")
    ap.add_argument("--shape", default="256,256")
    ap.add_argument("--json", action="store_true", help="JSON lines only")
    args = ap.parse_args(argv)
    ops = (args.ops.split(",") if args.ops else DEFAULT_OPS.split())
    shape = tuple(int(x) for x in args.shape.split(","))
    rows = []
    for name in ops:
        row = bench_op(name, shape)
        if row is None:
            continue
        rows.append(row)
        if args.json:
            print(json.dumps(row), flush=True)
        else:
            err = row.get("error")
            msg = (f"{row['op']:<14} " +
                   (f"ERROR {err}" if err else
                    f"fwd {row['fwd_us']:>9.1f} us" +
                    (f"   fwd+bwd {row['fwd_bwd_us']:>9.1f} us"
                     if "fwd_bwd_us" in row else "")))
            print(msg, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
