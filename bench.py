"""Headline benchmark suite: training MFU, inference, KVStore bandwidth.

North star (BASELINE.md targets): ResNet-50 + BERT-base *training* at
>=50% MFU with `dist_tpu_sync`/SPMD step, plus KVStore push/pull bandwidth.
Reference protocol: `docs/.../perf.md:252-254` (train_imagenet.py, synthetic
data) and `benchmark_score.py` for inference; V100 fp32 numbers are the
`vs_baseline` denominators (BASELINE.md).

MFU accounting: numerator = XLA `cost_analysis()['flops']` of the compiled
step (exact algebraic FLOPs of the program actually executed), denominator =
chip peak (bf16 MXU rate, by `device_kind`, overridable via
MXNET_TPU_PEAK_FLOPS).

Timing methodology: the TPU here sits behind a tunnel whose
`block_until_ready` returns before execution finishes and whose
device->host fetch costs ~100 ms RTT. Every measurement therefore runs the
SAME loop at two iteration counts, each ended by an actual host fetch, and
takes the difference — the fetch RTT, dispatch tails, and any lazy-execution
slack cancel exactly.

Prints one JSON row per metric as it completes; the FINAL line is the
headline (bf16 ResNet-50 training) row with an `extra` dict carrying all
rows, for the driver's single-line parse.

Round-3 findings baked into the rows (per-op device profiles via
profiler.device_op_table):

* ResNet-50 train bs256@224 sits at the efficiency ceiling of XLA's
  conv kernels for these shapes on v5e (round-4 finding,
  exp/conv_chain_probe.py): per-shape isolated measurements put the
  forward 3x3 stage convs at 52-87% MXU and the 1x1 bottleneck pairs at
  22-41%, all below BOTH rooflines. The round-3 "HBM-saturated, bound
  0.294" reading was an artifact: cost-analysis 'bytes accessed' counts
  convolutions at ~2x their fusion-boundary traffic (elementwise: 1.0x),
  so the step's true arithmetic intensity is ~2x the raw figure. Rows
  carry `cost_analysis_mfu_floor` (the raw, conservative figure) and the
  fused row names the real limiter.
* BERT-base seq128 is MXU-bound and hits >=0.5 MFU once per-step host
  dispatch is amortized (`step_n` fused rows): matmul fusions run at ~83%
  of peak; dropout uses the rbg hardware RNG; attention at seq 128 takes
  the XLA path (flash kernel wins only past the ~1024-token crossover).
* Single-dispatch rows pay the tunnel's per-execute RTT — 0.7-30 ms in
  healthy sessions, 117 ms observed in r4 — that a non-tunneled host
  would pipeline; fused rows amortize it 8-16x. Rows whose rtt_ms
  exceeds WEATHER_RTT_THRESHOLD_MS are flagged `weather_dominated` and
  must not be compared across rounds.
* Round-5: the llama long-seq rows are where the Pallas flash kernel is
  ACTIVE in a headline workload (seq 2048/4096 > the 1024-crossover;
  the route is asserted, and each row carries its own XLA-attention
  ablation arm: flash wins 1.7x at seq 2048, 2.4x at 4096 end-to-end).
"""
from __future__ import annotations

import json
import sys
import time

# chip peaks + MFU accounting live in the telemetry subsystem
# (mxnet_tpu/profiler/metrics.py) since the telemetry PR; these are the
# bench-local spellings older rows referenced.
from mxnet_tpu.profiler.metrics import (  # noqa: E402
    TrainingMetrics,
    chip_peak as _chip_peak,
    peak_flops as _peak_flops,
)

BASE_INFER_IMG_S = 1076.81   # V100 fp32 bs32 inference, perf.md:193
BASE_TRAIN_IMG_S = 363.69    # V100 fp32 bs128 training, perf.md:254


def _emit(row):
    # every row carries the unified telemetry snapshot (OBSERVABILITY.md):
    # the cache/collective/serve/resilience counters that explain the
    # number ride along with it instead of needing a re-run to recover
    try:
        from mxnet_tpu.profiler import export as _export

        row["export_snapshot"] = _export.snapshot(include_aggregates=False)
    except Exception as e:  # noqa: BLE001 -- telemetry must not kill a row
        print(f"# export snapshot unavailable: {e}", file=sys.stderr)
    print(json.dumps(row), flush=True)
    return row


_LAST_SAMPLES = None  # per-iteration seconds of the most recent _timed_diff


def _timed_diff(step, fetch, k1, k2, repeats=3):
    """Per-iteration seconds of `step`, by the two-loop difference: run k1
    iterations + fetch, then k2, and divide the extra time by (k2-k1).
    Cancels fetch RTT / lazy-dispatch artifacts of the tunnel runtime.

    Returns the median of ``repeats`` samples; all samples land in
    ``_LAST_SAMPLES`` so rows can report n/spread (r3 verdict item 4:
    a reader must be able to tell regression from tunnel weather)."""
    global _LAST_SAMPLES

    def run(k):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = step()
        fetch(r)
        return time.perf_counter() - t0
    diffs = []
    for _ in range(repeats):
        d1 = run(k1)
        d2 = run(k2)
        if d2 > d1:
            diffs.append((d2 - d1) / (k2 - k1))
    if not diffs:
        raise RuntimeError(
            f"degenerate timing: {k2}-iter loops never exceeded {k1}-iter "
            f"loops — queue not drained before timing?")
    diffs.sort()
    _LAST_SAMPLES = list(diffs)
    return diffs[len(diffs) // 2]


def _spread(unit_scale=1.0, invert_for=None):
    """n/min/max of the last timing's samples, in the row's own unit.
    ``invert_for=X`` reports X/dt rates (min rate from max dt)."""
    if not _LAST_SAMPLES:
        return {}
    s = sorted(_LAST_SAMPLES)
    if invert_for is not None:
        return {"n": len(s),
                "spread": [round(invert_for / s[-1], 2),
                           round(invert_for / s[0], 2)]}
    return {"n": len(s), "spread": [round(s[0] * unit_scale, 4),
                                    round(s[-1] * unit_scale, 4)]}


_RTT_MS = None

# single-dispatch rows are tunnel-weather-dominated above this RTT: the
# healthy band observed across r1-r3 was 0.7-30 ms; r4 recorded 117 ms
# and its fp32-infer spread swung -47%. Above 10 ms the per-step
# dispatch tax, not the chip, sets the number — such rows must not be
# compared across rounds (PERF.md "Benchmark variance").
WEATHER_RTT_THRESHOLD_MS = 10.0


def _dispatch_meta():
    """rtt_ms + weather_dominated flag for single-dispatch rows, making
    the JSON self-interpreting (r4 verdict Next #7)."""
    rtt = _measure_rtt_ms()
    meta = {"rtt_ms": rtt}
    if rtt is not None:
        meta["weather_dominated"] = bool(rtt > WEATHER_RTT_THRESHOLD_MS)
    return meta


def _memory_meta():
    """Allocator peak SINCE PROCESS START (jax memory_stats never resets),
    from the telemetry subsystem — an upper bound on the row's footprint,
    named accordingly; empty on backends that don't report (CPU)."""
    from mxnet_tpu.profiler.metrics import process_peak_bytes_in_use

    try:
        peak = process_peak_bytes_in_use()
    except Exception:
        peak = 0
    return {"process_peak_hbm_gb": round(peak / 2**30, 2)} if peak else {}


def _measure_rtt_ms():
    """Median host<->device fetch round-trip of a 4-byte scalar: the
    dispatch tax every single-dispatch row pays per step on the tunnel
    runtime. Reported once per bench run on dispatch-bound rows so their
    variance can be attributed (r3 verdict item 4)."""
    global _RTT_MS
    if _RTT_MS is not None:
        return _RTT_MS
    try:
        import jax
        import jax.numpy as jnp
        import numpy as onp

        x = jnp.zeros(())
        x.block_until_ready()
        ts = []
        for _ in range(7):
            t0 = time.perf_counter()
            onp.asarray(x + 1.0)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        _RTT_MS = round(ts[len(ts) // 2] * 1e3, 2)
    except Exception:
        _RTT_MS = None
    return _RTT_MS


def _chain_diff(run, n_fuse, repeats=3):
    """Two-loop differential timing of a scan-chained dispatch: ``run(m)``
    must execute m chained device iterations and block on a host fetch.
    Times n_fuse- vs 4*n_fuse-iteration dispatches and divides the
    difference — fetch RTT and dispatch tails cancel. Returns
    per-iteration seconds (median of ``repeats``); samples land in
    ``_LAST_SAMPLES`` for the row's n/spread. ONE definition: three bench
    rows share this protocol, and a prior review round caught a bug born
    of it being copy-pasted."""
    import time

    run(n_fuse)          # compile + drain both static signatures
    run(4 * n_fuse)
    diffs = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run(n_fuse)
        d1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(4 * n_fuse)
        d2 = time.perf_counter() - t0
        if d2 > d1:
            diffs.append((d2 - d1) / (3 * n_fuse))
    if not diffs:
        raise RuntimeError("degenerate chained timing")
    diffs.sort()
    global _LAST_SAMPLES
    _LAST_SAMPLES = list(diffs)
    return diffs[len(diffs) // 2]


def _infer_rate_fused(net, x_host, n_fuse=16):
    """Per-inference seconds with n_fuse forwards fused into ONE dispatch
    (lax.scan on device). Single-dispatch inference at bs32 is tunnel-RTT
    bound (~10 ms of dispatch against ~2-5 ms of device work), so the
    un-fused rows under-report the chip; the scan chains each forward on a
    negligible function of the previous logits so XLA cannot elide or
    reorder the iterations."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as onp

    from mxnet_tpu.parallel.functional import functionalize

    apply_fn, params = functionalize(net, train_mode=False)

    @functools.partial(jax.jit, static_argnums=2)
    def run(params, x, m):
        def body(carry, _):
            out = apply_fn(params, x + carry)
            logits = jax.tree_util.tree_leaves(out)[0]
            # serialize iterations: next input nudged by the last logits
            return jnp.mean(logits).astype(x.dtype) * 1e-12, None

        c, _ = jax.lax.scan(body, jnp.zeros((), x.dtype), None, length=m)
        return c

    x = jnp.asarray(x_host)
    return _chain_diff(lambda m: onp.asarray(run(params, x, m)), n_fuse)


def bench_resnet_infer():
    """ResNet-50 v1 fp32 inference, batch 32 — benchmark_score.py protocol
    through the user-facing path: model_zoo net -> hybridize() -> XLA."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp

    BATCH, SIZE = 32, 224
    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()

    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=mx.cpu())
    small = mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"), ctx=mx.cpu())
    with autograd.predict_mode():
        net(small)
    if ctx.device_type != "cpu":
        net.reset_ctx(ctx)
    net.hybridize(static_alloc=True)

    x = mnp.array(
        onp.random.uniform(-1, 1, (BATCH, 3, SIZE, SIZE)).astype("float32"),
        ctx=ctx)
    with autograd.predict_mode():
        net(x).asnumpy()  # compile AND drain (lazy runtime: fetch forces it)
        dt = _timed_diff(lambda: net(x),
                         lambda out: out.asnumpy(), 3, 18)
    img_s = BATCH / dt
    row = _emit({
        "metric": "resnet50_v1_infer_bs32_fp32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASE_INFER_IMG_S, 3),
        **_dispatch_meta(),
        **_spread(invert_for=BATCH),
    })
    # fused probe AFTER the stable row is out, and non-fatal: a
    # fused-timing flake must not cost the protocol metric
    global _FP32_INFER_FUSED_S
    try:
        with autograd.predict_mode():
            dt_fused = _infer_rate_fused(net, x._data)
        _FP32_INFER_FUSED_S = dt_fused
        _emit({
            "metric": "resnet50_v1_infer_bs32_fp32_fused16",
            "value": round(BATCH / dt_fused, 2),
            "unit": "img/s",
            "vs_baseline": round(BATCH / dt_fused / BASE_INFER_IMG_S, 3),
            **_spread(invert_for=BATCH),
        })
    except Exception as e:
        print(f"# fp32 fused probe failed: {e}", file=sys.stderr)
    return row


_FP32_INFER_FUSED_S = None


def bench_resnet_infer_int8():
    """ResNet-50 INT8 inference, batch 32 (contrib.quantization int8 path;
    v5e MXU int8 peak is 2x bf16). vs_baseline: the V100 fp16 row
    (perf.md:208, 2085.51 img/s) — the reference's reduced-precision
    inference analog."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.contrib.quantization import quantize_net

    BATCH, SIZE = 32, 224
    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=mx.cpu())
    # materialize + calibrate on CPU (eager resnet over the tunnel would
    # pay per-op RTT), then move to the chip for the timed int8 path
    with autograd.predict_mode():
        net(mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"),
                      ctx=mx.cpu()))
    xc = mnp.array(
        onp.random.uniform(-1, 1, (8, 3, SIZE, SIZE)).astype("float32"),
        ctx=mx.cpu())
    # bf16 inter-layer activations: the reference's reduced-precision
    # protocol feeds fp16 inputs to its fp16 rows (perf.md:208); same here
    quantize_net(net, calib_data=xc, calib_mode="naive",
                 activation_dtype="bfloat16")
    try:
        ctx = mx.tpu()
        ctx.jax_device()
        net.reset_ctx(ctx)
    except Exception:
        ctx = mx.cpu()
    x = mnp.array(
        onp.random.uniform(-1, 1, (BATCH, 3, SIZE, SIZE)).astype("float32"),
        ctx=ctx).astype("bfloat16")
    net.hybridize(static_alloc=True)
    with autograd.predict_mode():
        net(x).asnumpy()  # compile + drain
        dt = _timed_diff(lambda: net(x), lambda out: out.asnumpy(), 3, 18)
    img_s = BATCH / dt
    _emit({
        "metric": "resnet50_v1_infer_bs32_int8",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / 2085.51, 3),
        **_dispatch_meta(),
        **_spread(invert_for=BATCH),
    })
    with autograd.predict_mode():
        dt_fused = _infer_rate_fused(net, x._data)
    int8_spread = _spread(invert_for=BATCH)  # snapshot BEFORE any fp32
    # fallback probe below overwrites _LAST_SAMPLES (review finding r4)
    # the perf contract int8 exists for: >=1.5x the fp32 rate measured the
    # same (fused, dispatch-amortized) way — a slower int8 path FAILS the
    # bench rather than shipping a number that quietly lost to fp32. If
    # the fp32 bench didn't leave its fused rate (row order / flake), the
    # gate measures it here rather than silently waiving the contract.
    fp32_s = _FP32_INFER_FUSED_S
    if fp32_s is None:
        fnet = gluon.model_zoo.vision.resnet50_v1()
        fnet.initialize(ctx=mx.cpu())
        with autograd.predict_mode():
            fnet(mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"),
                           ctx=mx.cpu()))
        if ctx.device_type != "cpu":
            fnet.reset_ctx(ctx)
        with autograd.predict_mode():
            fp32_s = _infer_rate_fused(
                fnet, x._data.astype("float32"))
    speedup = (fp32_s / dt_fused) if fp32_s else None
    row = _emit({
        "metric": "resnet50_v1_infer_bs32_int8_fused16",
        "value": round(BATCH / dt_fused, 2),
        "unit": "img/s",
        "vs_baseline": round(BATCH / dt_fused / 2085.51, 3),
        "speedup_vs_fp32": round(speedup, 3) if speedup else None,
        **int8_spread,
    })
    if speedup is not None and speedup < 1.5:
        raise RuntimeError(
            f"int8 fused inference is only {speedup:.2f}x fp32 (>=1.5x "
            f"required): the int8 path is not earning its existence")
    return row


def bench_resnet_infer_pallas_fused(n_fuse=16):
    """ResNet-50 bf16 inference through contrib.pallas_fuse (NHWC
    trunk, folded BN) — the transform is the headline (13.7k+ img/s vs
    5.9k plain fp32); the conv1x1_pair-kernel boundary arm
    (use_pallas=True) is re-measured as `pallas_kernel_img_s` each
    round with its measured in-graph verdict: the kernel wins 2.52x on
    the isolated probe shape but LOSES end-to-end because a custom-call
    is a fusion barrier (PERF.md round-5). Scan-chained dispatch (same
    n_fuse protocol as the int8 row)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as onp

    from mxnet_tpu.contrib.pallas_fuse import fuse_resnet_v1

    BATCH, SIZE = 32, 224
    net = _make_resnet()  # initialized + shapes materialized
    x = jnp.asarray(onp.random.uniform(
        -1, 1, (BATCH, 3, SIZE, SIZE)).astype("float32"))

    def rate(fused):
        @functools.partial(jax.jit, static_argnums=1)
        def run(xd, m):
            def body(carry, _):
                logits = fused._forward(xd + carry)
                return jnp.mean(logits).astype(xd.dtype) * 1e-12, None

            c, _ = jax.lax.scan(body, jnp.zeros((), xd.dtype), None,
                                length=m)
            return c

        return _chain_diff(lambda m: onp.asarray(run(x, m)), n_fuse)

    dt_pal = rate(fuse_resnet_v1(net, use_pallas=True))
    pal_spread = _spread(invert_for=BATCH)
    dt_xla = rate(fuse_resnet_v1(net))  # default: XLA boundaries
    return _emit({
        "metric": f"resnet50_v1_infer_bs32_bf16_fusedpairs{n_fuse}",
        "value": round(BATCH / dt_xla, 2),
        "unit": "img/s",
        "vs_baseline": round(BATCH / dt_xla / BASE_INFER_IMG_S, 3),
        "pallas_kernel_img_s": round(BATCH / dt_pal, 2),
        "pallas_kernel_ratio": round(dt_xla / dt_pal, 3),
        "pallas_kernel_spread": pal_spread.get("spread"),
        **_spread(invert_for=BATCH),
    })


def _train_bench(net, loss_fn, optimizer, opt_params, data, labels,
                 rules=None, dtype=None, k1=3, k2=15, fuse=None):
    """Shared training-step timer: ShardedTrainer (SPMD step over the device
    mesh — the dist_tpu_sync execution model), XLA-counted FLOPs -> MFU.

    ``fuse=N``: time ``step_n`` windows of N steps in one dispatch (the
    bulk-exec path); the returned dt is per WINDOW (divide by N for
    per-step)."""
    import jax
    import numpy as onp

    from mxnet_tpu.parallel import ShardedTrainer, ShardingRules, make_mesh

    mesh = make_mesh({"dp": len(jax.devices())})
    trainer = ShardedTrainer(net, loss_fn, optimizer, opt_params, mesh=mesh,
                             rules=rules or ShardingRules(default_axis=None),
                             dtype=dtype)
    # place the synthetic batch on the mesh ONCE — steps must time the chip,
    # not host->device transfers of the same bytes every iteration
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place_tree(tree, spec):
        sh = NamedSharding(mesh, spec)
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)

    if fuse:
        stack = lambda a: onp.broadcast_to(  # noqa: E731
            a[None], (fuse,) + a.shape).copy()
        data = jax.tree_util.tree_map(stack, data)
        labels = jax.tree_util.tree_map(stack, labels)
        data = place_tree(data, P(None, "dp"))
        labels = place_tree(labels, P(None, "dp"))
        step = lambda: trainer.step_n(data, labels)  # noqa: E731
        fetch = lambda ls: float(ls.asnumpy().reshape(-1)[-1])  # noqa: E731
    else:
        data = place_tree(data, P("dp"))
        labels = place_tree(labels, P("dp"))
        step = lambda: trainer.step(data, labels)  # noqa: E731
        fetch = lambda loss: float(loss.asnumpy().reshape(-1)[0])  # noqa: E731
    # compile AND drain: on the lazy tunnel runtime only a host fetch
    # guarantees compilation + execution happened before the timed loops
    fetch(step())
    dt = _timed_diff(step, fetch, k1, k2)
    # MFU accounting via the telemetry subsystem: feed every timing sample
    # into a TrainingMetrics (median step time x XLA-counted FLOPs against
    # the chip peak) so BENCH rows and profiler.step_marker agree by
    # construction. step_flops is per-step; a fused window executes
    # `fuse` steps per dt.
    flops = (trainer.step_flops or 0) * (fuse or 1)
    tm = TrainingMetrics(flops_per_step=flops or None)
    for d in (_LAST_SAMPLES or [dt]):
        tm.record_step(d)
    return dt, tm.mfu, trainer


def _roofline(trainer):
    """MFU bound from XLA cost-analysis arithmetic intensity — WITH the
    round-4 correction (exp/conv_chain_probe.py): 'bytes accessed'
    counts convolutions at ~2x their fusion-boundary traffic (measured:
    conv+relu reports 392 MiB for 196 MiB of boundary bytes, while
    elementwise fusions count exactly 1.0x), so the RAW cost-analysis AI
    UNDERSTATES conv-dominated programs and the r3 'bound 0.294, chip
    HBM-saturated' reading was wrong. The r4 per-shape probe shows the
    actual limiter is XLA conv-kernel efficiency at these shapes
    (fwd 3x3: 52-87% MXU; 1x1 pairs: 22-41%; stem: 7% — all well below
    BOTH rooflines in isolation). The raw figure is still emitted, as
    `cost_analysis_mfu_floor`: a conservative floor on the HBM bound,
    not a ceiling the program has hit.
    """
    try:
        ca = trainer.step_cost_analysis
        flops = ca.get("flops")
        bytes_acc = ca.get("bytes accessed")
        peak = _peak_flops()
        hbm = _chip_peak("hbm")
        if not (flops and bytes_acc and peak and hbm):
            return None
        return round(min(1.0, (flops / bytes_acc) / (peak / hbm)), 3)
    except Exception:
        return None


def _make_resnet():
    import numpy as onp

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp

    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize()
    with autograd.predict_mode():
        net(mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32")))
    return net


def bench_resnet_train(dtype=None):
    """ResNet-50 v1 training step, batch 256, SGD+momentum —
    train_imagenet.py protocol (synthetic data; the reference's largest
    published train batch is 128, perf.md:254, which stays the
    vs_baseline denominator). With dtype='bfloat16': AMP bf16 compute,
    fp32 master weights. Batch 256 measured ~28%% MFU on v5e vs ~20%% at
    128 (deeper per-step pipeline amortizes dispatch + memory stalls)."""
    import numpy as onp

    from mxnet_tpu import gluon

    BATCH = 256
    net = _make_resnet()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = onp.random.uniform(-1, 1, (BATCH, 3, 224, 224)).astype("float32")
    y = onp.random.randint(0, 1000, (BATCH,)).astype("int32")
    dt, mfu, trainer = _train_bench(
        net, loss_fn, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}, x, y,
        dtype=dtype)
    img_s = BATCH / dt
    tag = "bf16_amp" if dtype else "fp32"
    return _emit({
        "metric": f"resnet50_v1_train_bs256_{tag}",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASE_TRAIN_IMG_S, 3),
        "mfu": round(mfu, 4) if mfu else None,
        "cost_analysis_mfu_floor": _roofline(trainer),
        **_dispatch_meta(),
        **_memory_meta(),
        **_spread(invert_for=BATCH),
    })


def bench_resnet_train_fused(n_fuse=8):
    """ResNet-50 bf16 training with N steps fused into one dispatch
    (`ShardedTrainer.step_n` lax.scan window — the bulk-exec path):
    removes per-step host dispatch (the tunnel runtime pays a per-execute
    RTT that a non-tunneled TPU host would overlap), showing the
    framework's compute ceiling. The measured MFU lands at ~90% of the
    program's HBM roofline bound (see `_roofline`): this workload is
    memory-bandwidth-bound on v5e, not compute- or dispatch-bound."""
    import numpy as onp

    from mxnet_tpu import gluon

    BATCH = 256
    net = _make_resnet()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = onp.random.uniform(-1, 1, (BATCH, 3, 224, 224)).astype("float32")
    y = onp.random.randint(0, 1000, (BATCH,)).astype("int32")
    dt, mfu, trainer = _train_bench(
        net, loss_fn, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}, x, y,
        dtype="bfloat16", fuse=n_fuse, k1=2, k2=8)
    img_s = n_fuse * BATCH / dt
    return _emit({
        "metric": f"resnet50_v1_train_bs256_bf16_fused{n_fuse}",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASE_TRAIN_IMG_S, 3),
        "mfu": round(mfu, 4) if mfu else None,
        "cost_analysis_mfu_floor": _roofline(trainer),
        "limiter": "xla-conv-kernel-efficiency at these shapes, NOT HBM "
                   "saturation (exp/conv_chain_probe.json; the r3 "
                   "roofline_mfu_bound read cost-analysis bytes that "
                   "double-count convs)",
        **_memory_meta(),
        **_spread(invert_for=n_fuse * BATCH),
    })


def _bert_setup():
    """BERT-base MLM+NSP pretraining pieces, batch 64, seq 128, Adam, AMP
    bf16 — the GluonNLP pretraining config named in BASELINE.json.

    Attention at seq 128 runs the XLA path by design: the Pallas flash
    kernel only wins past the ~1024-token crossover (see
    ops/pallas/flash_attention._supports_pallas for measured numbers);
    dropout masks ride the rbg hardware RNG (3x over threefry, see
    mxnet_tpu/__init__). Batch 64 is the measured MFU sweet spot on v5e
    (bs128 fused8 measured 0.513 vs 0.591 at bs64)."""
    import numpy as onp

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.models.bert import BERTForPretrain, get_bert_model

    BATCH, SEQ = 64, 128

    class PretrainStep(HybridBlock):
        """Single-input wrapper: derives valid_length from the pad mask so
        the whole example (tokens only) flows through one SPMD step."""

        def __init__(self, model):
            super().__init__()
            self.model = model

        def forward(self, tokens):
            valid_length = (tokens != 0).sum(axis=1)
            return self.model(tokens, valid_length=valid_length)

    net = PretrainStep(BERTForPretrain(get_bert_model("bert_12_768_12")))
    net.initialize()
    tokens = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
    # a few padded tails so the valid-length mask path is exercised
    tokens[::4, SEQ - 16:] = 0
    with autograd.predict_mode():
        net(mnp.array(tokens[:1, :16]))  # tiny: just materializes shapes

    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_fn(outs, labels):
        mlm_scores, nsp_scores = outs
        mlm_labels, nsp_labels = labels
        return ce(mlm_scores, mlm_labels).mean() + \
            ce(nsp_scores, nsp_labels).mean()

    mlm_labels = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
    nsp_labels = onp.random.randint(0, 2, (BATCH,)).astype("int32")
    return net, loss_fn, tokens, (mlm_labels, nsp_labels), BATCH


def bench_bert_train():
    """Single-dispatch-per-step BERT row. No published reference BERT
    throughput exists in-repo (BASELINE.md), so ``vs_baseline`` is null;
    ``vs_mfu_target`` is mfu / 0.5 against the BASELINE.json >=50% MFU
    north star (the label Weak #9 of the r2 verdict asked for)."""
    net, loss_fn, tokens, labels, BATCH = _bert_setup()
    dt, mfu, _tr = _train_bench(
        net, loss_fn, "adam", {"learning_rate": 1e-4}, tokens,
        labels, dtype="bfloat16")
    samples_s = BATCH / dt
    return _emit({
        "metric": "bert_base_train_bs64_seq128_bf16_amp",
        "value": round(samples_s, 2),
        "unit": "samples/s",
        "vs_baseline": None,
        "vs_mfu_target": round(mfu / 0.5, 3) if mfu else None,
        "mfu": round(mfu, 4) if mfu else None,
        **_dispatch_meta(),
        **_memory_meta(),
        **_spread(invert_for=BATCH),
    })


def bench_bert_train_fused(n_fuse=8):
    """BERT with N steps fused into one dispatch (`step_n` lax.scan
    window). The compiled step's device time is ~47 ms (per-op profile:
    matmul fusions at ~83% of MXU peak); single-dispatch rows additionally
    pay the tunnel's per-execute RTT, which the fused window amortizes —
    this row is the chip's real per-step rate."""
    net, loss_fn, tokens, labels, BATCH = _bert_setup()
    dt, mfu, _tr = _train_bench(
        net, loss_fn, "adam", {"learning_rate": 1e-4}, tokens,
        labels, dtype="bfloat16", fuse=n_fuse, k1=2, k2=8)
    samples_s = n_fuse * BATCH / dt
    return _emit({
        "metric": f"bert_base_train_bs64_seq128_bf16_fused{n_fuse}",
        "value": round(samples_s, 2),
        "unit": "samples/s",
        "vs_baseline": None,
        "vs_mfu_target": round(mfu / 0.5, 3) if mfu else None,
        "mfu": round(mfu, 4) if mfu else None,
        **_memory_meta(),
        **_spread(invert_for=n_fuse * BATCH),
    })


def _llama_lm_setup(seq, batch):
    """Decoder-only llama-block LM for the long-context row: 12 layers,
    units 1024 (16 heads x d64), SwiGLU 2816, vocab 32k, per-layer remat
    — sized so fp32 masters + Adam states + seq-2048 activations fit one
    v5e chip. Causal LM loss over shifted tokens."""
    import numpy as onp

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.models.llama import get_llama

    net = get_llama("llama2_7b", units=1024, hidden_size=2816,
                    num_layers=12, num_heads=16, num_kv_heads=16,
                    vocab_size=32000, remat=True)
    net.initialize()
    rng = onp.random.RandomState(7)
    tokens = rng.randint(1, 32000, (batch, seq)).astype("int32")
    labels = onp.concatenate(
        [tokens[:, 1:], tokens[:, :1]], axis=1).astype("int32")
    with autograd.predict_mode():
        net(mnp.array(tokens[:1, :16]))  # materialize shapes
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_fn(logits, y):
        return ce(logits, y).mean()

    return net, loss_fn, tokens, labels


def _llama_lm_flops(seq, batch, layers=12, units=1024, hidden=2816,
                    vocab=32000):
    """Analytic per-step train FLOPs (fwd x3 for fwd+bwd), PaLM-style
    counting: projections 8BTU^2, attention scores+AV 4BT^2U (full T^2;
    causality not discounted — identical in both arms), SwiGLU 6BTUH,
    LM head 2BTUV. Used for MFU instead of XLA cost_analysis because the
    flash path's pallas custom-call FLOPs are invisible to cost_analysis
    — the analytic count is the only denominator that treats the flash
    and ablation arms identically (remat recompute is NOT counted:
    model FLOPs, not hardware FLOPs)."""
    b, t, u = batch, seq, units
    fwd = layers * (8 * b * t * u * u + 4 * b * t * t * u
                    + 6 * b * t * u * hidden) + 2 * b * t * u * vocab
    return 3.0 * fwd


def bench_llama_long_seq(n_fuse=4, seq=2048, batch=4):
    """Long-context training row (VERDICT r4 Next #2): a llama-block LM
    at seq 2048 where attention ACTUALLY routes to the Pallas flash
    kernel (tq*tk = 4x the crossover), trained end-to-end with the
    ShardedTrainer fused-window path, plus the same model with
    `force_path('xla')` as the ablation arm. The route is asserted from
    `flash_attention.last_path()` after the traced step executes — if
    the router stops picking the kernel this row FAILS, it does not
    silently degrade. Emits tokens/s + MFU (analytic FLOPs; see
    `_llama_lm_flops`) and the flash-vs-XLA end-to-end speedup."""
    from mxnet_tpu.ops.pallas import flash_attention as fa

    flops = _llama_lm_flops(seq, batch)
    peak = _peak_flops()
    arms = {}
    for arm, forced in (("flash", None), ("xla_ablation", "xla")):
        fa.force_path(forced)
        try:
            net, loss_fn, tokens, labels = _llama_lm_setup(seq, batch)
            dt, _mfu, _tr = _train_bench(
                net, loss_fn, "adam", {"learning_rate": 1e-4}, tokens,
                labels, dtype="bfloat16", fuse=n_fuse, k1=1, k2=5)
            want = "pallas" if forced is None else "xla"
            got = fa.last_path()
            if got != want:
                raise RuntimeError(
                    f"attention path assertion failed: arm {arm!r} "
                    f"traced {got!r}, wanted {want!r}")
            # dt is per DISPATCH = n_fuse steps; flops is per step.
            # tokens/s + MFU via the telemetry subsystem's accounting.
            tm = TrainingMetrics(flops_per_step=n_fuse * flops,
                                 tokens_per_step=n_fuse * batch * seq,
                                 peak_flops=peak)
            for d in (_LAST_SAMPLES or [dt]):
                tm.record_step(d)
            arms[arm] = {
                "tokens_s": round(tm.tokens_per_sec, 1),
                "mfu": round(tm.mfu, 4) if tm.mfu else None,
                **_spread(invert_for=n_fuse * batch * seq),
            }
        finally:
            fa.force_path(None)
    row = {
        "metric": f"llama12L_train_bs{batch}_seq{seq}_bf16_fused{n_fuse}",
        "value": arms["flash"]["tokens_s"],
        "unit": "tokens/s",
        "vs_baseline": None,
        "mfu": arms["flash"]["mfu"],
        "attention_path": "pallas (asserted from last_path())",
        "flash_speedup_vs_xla": round(
            arms["flash"]["tokens_s"] / arms["xla_ablation"]["tokens_s"],
            3),
        "n": arms["flash"].get("n"),
        "spread": arms["flash"].get("spread"),
        "xla_ablation": arms["xla_ablation"],
    }
    return _emit(row)


def bench_lenet_eager():
    """Imperative (non-hybridized) LeNet training — the reference's eager
    LeNet/MNIST config. Exercises per-op dispatch + the eager jit cache
    (SURVEY §7 hard part 2); reports the cached rate and the uncached rate.

    Diagnosis of the r2 eager gap (the measurement this round's >=2x fix
    came from): the r2 bench built its arrays on the DEFAULT context, i.e.
    jax-CPU, where a single LeNet conv *backward* costs ~7 ms of genuine
    single-host compute (the 129 ms step was device-bound, not
    dispatch-bound — the jit cache rightly bought only 8%). On the TPU
    context the per-op device time is negligible and the cost structure
    inverts: the tunnel runtime drains ~0.7-4 ms per executed op, so the
    step is dispatch-round-trip-bound, exactly SURVEY §7 hard part 2's
    prediction. Two fixes: (1) this bench now runs on mx.tpu() like every
    other row; (2) recorded ops now run their forward through the cached
    per-op executable and their backward through a cached compiled vjp
    (registry._make_cached_vjp) instead of per-step jax.vjp retracing +
    Python transpose interpretation — 2.3x the r2 rate; the remaining time
    is ~50 tunnel round-trips that only op-graph batching could remove."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.ops import registry

    BATCH = 64
    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"), gluon.nn.Dense(10))
    net.initialize(ctx=ctx)
    x = mnp.array(onp.random.randn(BATCH, 1, 28, 28).astype("float32"),
                  ctx=ctx)
    y = mnp.array(onp.random.randint(0, 10, (BATCH,)), ctx=ctx)
    with autograd.predict_mode():
        net(x)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})

    def step():
        with autograd.record():
            l = loss_fn(net(x), y).mean()
        l.backward()
        tr.step(1)
        return l

    def dispatches_per_step():
        from mxnet_tpu import engine

        float(step().asnumpy())  # settle caches for THIS config
        before = engine.dispatch_count()
        float(step().asnumpy())
        return engine.dispatch_count() - before

    rates = {}
    prev_enabled = registry._eager_jit_enabled
    from mxnet_tpu import engine as _engine

    prev_bulk = _engine.set_bulk_size(0)  # this row measures PER-OP dispatch
    try:
        for flag in (False, True):
            registry.set_eager_jit(flag)
            registry._EAGER_JIT_CACHE.clear()
            registry._EAGER_BWD_CACHE.clear()
            for _ in range(3):
                float(step().asnumpy())  # drain + warm fwd AND bwd caches
            dt = _timed_diff(step, lambda l: float(l.asnumpy()), 3, 18)
            rates[flag] = BATCH / dt
        dps = dispatches_per_step()
    finally:
        registry.set_eager_jit(prev_enabled)
        _engine.set_bulk_size(prev_bulk)
    return _emit({
        "metric": "lenet_eager_train_bs64",
        "value": round(rates[True], 2),
        "unit": "img/s",
        "vs_baseline": None,
        "uncached_img_s": round(rates[False], 2),
        "dispatches_per_step": dps,
        **_dispatch_meta(),
        **_spread(invert_for=BATCH),
    })


def bench_lenet_eager_bulk():
    """Eager LeNet training under ``engine.bulk(16)`` — deferred eager
    dispatch collapses ~tens of per-op tunnel RTTs per step into one
    compiled segment executable per flush (fwd segment + segment vjp at
    backward). The dispatches_per_step columns quantify the collapse; on
    the tunnel each dispatch costs one RTT (see rtt_ms), so the ratio
    bounds the RTT win the next real-TPU round should measure."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, engine, gluon
    from mxnet_tpu import np as mnp

    BATCH = 64
    BULK = 16
    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"), gluon.nn.Dense(10))
    net.initialize(ctx=ctx)
    x = mnp.array(onp.random.randn(BATCH, 1, 28, 28).astype("float32"),
                  ctx=ctx)
    y = mnp.array(onp.random.randint(0, 10, (BATCH,)), ctx=ctx)
    with autograd.predict_mode():
        net(x)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})

    def step_bulk():
        with engine.bulk(BULK):
            with autograd.record():
                l = loss_fn(net(x), y).mean()
            l.backward()
            tr.step(1)
            return l

    def step_plain():
        # pin deferral OFF: this is the honest unbulked comparison arm
        # even when MXNET_ENGINE_BULK_SIZE is set globally
        prev = engine.set_bulk_size(0)
        try:
            with autograd.record():
                l = loss_fn(net(x), y).mean()
            l.backward()
            tr.step(1)
            return l
        finally:
            engine.set_bulk_size(prev)

    def dispatches(step):
        float(step().asnumpy())
        before = engine.dispatch_count()
        float(step().asnumpy())
        return engine.dispatch_count() - before

    for _ in range(3):
        float(step_bulk().asnumpy())  # compile the segment executables
    dt = _timed_diff(step_bulk, lambda l: float(l.asnumpy()), 3, 18)
    d_bulk = dispatches(step_bulk)
    d_plain = dispatches(step_plain)
    stats = engine.bulk_stats(reset=True)
    return _emit({
        "metric": "lenet_eager_train_bs64_bulk16",
        "value": round(BATCH / dt, 2),
        "unit": "img/s",
        "vs_baseline": None,
        "dispatches_per_step": d_bulk,
        "dispatches_per_step_unbulked": d_plain,
        "dispatch_collapse": round(d_plain / max(d_bulk, 1), 1),
        "ops_per_flush": round(stats["ops_per_flush"], 1),
        "seg_cache_hit_rate": round(
            stats["cache_hits"] /
            max(stats["cache_hits"] + stats["cache_misses"], 1), 3),
        **_dispatch_meta(),
        **_spread(invert_for=BATCH),
    })


def bench_trace_overhead():
    """Observability cost contract (OBSERVABILITY.md): the eager LeNet
    microloop under the production-default stack — profiler hooks
    installed but stopped, flight recorder ON, request tracing disabled —
    vs the fully unhooked baseline. The two arms are interleaved
    (min-of-rounds) so machine drift hits both equally; the row ASSERTS
    <5% overhead, mirroring tests/test_observability.py, so a hot-path
    regression fails a BENCH round loudly instead of shaving every
    other row quietly."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, engine, gluon, profiler
    from mxnet_tpu import np as mnp
    from mxnet_tpu.ops import registry
    from mxnet_tpu.profiler import recorder, trace

    BATCH = 64
    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"), gluon.nn.Dense(10))
    net.initialize(ctx=ctx)
    x = mnp.array(onp.random.randn(BATCH, 1, 28, 28).astype("float32"),
                  ctx=ctx)
    y = mnp.array(onp.random.randint(0, 10, (BATCH,)), ctx=ctx)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})

    def step():
        with autograd.record():
            l = loss_fn(net(x), y).mean()
        l.backward()
        tr.step(1)
        return l

    def loop(n=12):
        t0 = time.perf_counter()
        for _ in range(n):
            l = step()
        float(l.asnumpy())
        return time.perf_counter() - t0

    saved = registry._PROF, engine._PROF
    was_traced, was_recording = trace.ENABLED, recorder.ENABLED

    def measure(rounds=5):
        base = hooked = float("inf")
        for _ in range(rounds):
            registry._PROF = None
            engine._PROF = None
            trace.disable()
            recorder.disable()
            base = min(base, loop())
            profiler.set_state("run")
            profiler.set_state("stop")
            recorder.enable()  # production default; trace stays disabled
            hooked = min(hooked, loop())
        return base, hooked

    try:
        loop(4)  # warm fwd/bwd caches before either arm
        base, hooked = measure()
        if hooked > base * 1.05:  # timing noise: one clean re-measure
            base, hooked = measure(rounds=7)
    finally:
        registry._PROF, engine._PROF = saved
        (trace.enable if was_traced else trace.disable)()
        (recorder.enable if was_recording else recorder.disable)()
    overhead = hooked / base - 1.0
    assert overhead <= 0.05, (
        f"disabled trace+recorder overhead {overhead:.1%} on the eager "
        f"LeNet microloop (baseline {base:.3f}s, hooked {hooked:.3f}s)")
    return _emit({
        "metric": "trace_overhead_lenet_eager",
        "value": round(overhead * 100, 2),
        "unit": "%",
        "vs_baseline": None,
        "base_steps_s": round(12 / base, 1),
        "hooked_steps_s": round(12 / hooked, 1),
        "arm": "recorder on + trace off (production default) vs unhooked",
    })


def bench_guardrail_overhead():
    """Numerical-guardrail cost on a small dense train step (PERF.md
    'measured guardrail overhead'): baseline trainer vs one running the
    full sentinel stack — LossScaler overflow check + global-norm clip per
    step (the two per-step device-sync guardrails). The *disabled* cost
    (no scaler, no clip — the production default) is a pair of `is None`
    tests and is bounded separately by
    tests/test_guardrails.py::test_disabled_guardrail_overhead_under_5pct."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import amp, autograd, gluon
    from mxnet_tpu import np as mnp

    BATCH = 32
    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()
    x = mnp.array(onp.random.randn(BATCH, 64).astype("float32"), ctx=ctx)
    y = mnp.array(onp.random.randn(BATCH, 1).astype("float32"), ctx=ctx)
    loss_fn = gluon.loss.L2Loss()

    def make(guarded):
        net = gluon.nn.Dense(1, in_units=64)
        net.initialize(ctx=ctx)
        net(x)
        kw = {"loss_scaler": amp.LossScaler(),
              "clip_global_norm": 1e6} if guarded else {}
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 1e-3}, **kw)

        def step():
            with autograd.record():
                l = tr.scale_loss(loss_fn(net(x), y).mean())
            l.backward()
            tr.step(1)
            return l
        return step

    rates = {}
    for guarded in (False, True):
        step = make(guarded)
        for _ in range(5):
            float(step().asnumpy())
        dt = _timed_diff(step, lambda l: float(l.asnumpy()), 5, 30)
        rates[guarded] = 1.0 / dt
    overhead = rates[False] / rates[True] - 1.0
    return _emit({
        "metric": "guardrail_overhead_dense_step",
        "value": round(overhead * 100, 2),
        "unit": "%",
        "vs_baseline": None,
        "base_steps_s": round(rates[False], 1),
        "guarded_steps_s": round(rates[True], 1),
        **_spread(),
    })


def bench_ckpt_stall():
    """Async-checkpoint stall row (resilience.checkpoint): the training
    stall of an ``async_write=True`` save — the synchronous host-snapshot
    phase — vs the full synchronous save wall time, over a llama-8B-class
    parameter census (same tensor count/shape mix: embedding, per-layer
    qkv/out/mlp/norm) scaled to a dev box (~220 MB fp32). Reports the
    async stall in ms (lower is better; the perf gate treats ``ms`` rows
    as lower-better automatically) and fails loudly if the stall exceeds
    10% of the sync save — the acceptance bound async checkpointing
    exists to hold."""
    import os
    import tempfile

    import numpy as onp

    from mxnet_tpu import nd
    from mxnet_tpu.resilience import checkpoint as ckpt

    rng = onp.random.RandomState(0)
    H, V, L = 512, 8192, 16
    params = {"embed.weight": nd.array(rng.randn(V, H).astype("float32"))}
    for i in range(L):
        for nme, shape in (("attn_qkv", (3 * H, H)), ("attn_out", (H, H)),
                           ("mlp_up", (4 * H, H)), ("mlp_down", (H, 4 * H)),
                           ("norm", (H,))):
            params[f"layers.{i}.{nme}.weight"] = nd.array(
                rng.randn(*shape).astype("float32"))
    nbytes = sum(int(onp.prod(s)) for s in
                 [v.shape for v in params.values()]) * 4

    d = tempfile.mkdtemp(prefix="bench_ckpt_stall_")
    sync_ms, stall_ms = [], []
    for r in range(3):
        t0 = time.perf_counter()
        ckpt.save_checkpoint(os.path.join(d, f"sync{r}.ckpt"),
                             params=params, meta={"step": r})
        sync_ms.append((time.perf_counter() - t0) * 1e3)
        h = ckpt.save_checkpoint(os.path.join(d, f"async{r}.ckpt"),
                                 params=params, meta={"step": r},
                                 async_write=True)
        if not h.join():
            raise RuntimeError(f"async checkpoint write failed: {h.error}")
        stall_ms.append(h.stall_ms)
    sync = sorted(sync_ms)[1]
    stall = sorted(stall_ms)[1]
    frac = stall / sync
    if frac > 0.10:
        raise RuntimeError(
            f"async save stall {stall:.1f}ms is {frac:.1%} of the "
            f"{sync:.0f}ms sync save — the <10% stall bound regressed")
    return _emit({
        "metric": "ckpt_stall_ms",
        "value": round(stall, 3),
        "unit": "ms",
        "vs_baseline": None,
        "sync_save_ms": round(sync, 1),
        "stall_frac": round(frac, 4),
        "params_mb": round(nbytes / 1e6, 1),
    })


def bench_elastic_resume():
    """MULTICHIP elastic row (resilience.elastic): a dp8 training run on
    the 8-device mesh killed mid-step by an injected chip_loss, resumed
    at dp4 from its own sharded checkpoint. Reports the recovery
    wall-time (MeshDegraded catch → mesh shrink → kvstore rebind →
    reshard-on-resume restore) and the steps lost to the kill; the
    bitwise dp4-reference parity check runs inside the leg and fails the
    row loudly on any divergence."""
    from tools.elastic_soak import run_kill_reshard

    violations, row = run_kill_reshard(seed=7, n_batches=12)
    if violations:
        raise RuntimeError(f"elastic kill-and-reshard violated: "
                           f"{violations}")
    return _emit({
        "metric": "elastic_kill_reshard_recovery_ms",
        "value": round(row["recovery_wall_s"] * 1e3, 2),
        "unit": "ms",
        "vs_baseline": None,
        "steps_lost": row["steps_lost"],
        "dp": f"{row['dp_from']}->{row['dp_to']}",
        "killed_replica": row["killed_replica"],
        "parity": "bitwise",
    })


def bench_elastic_resume_3d():
    """MULTICHIP composed-mesh elastic row (resilience.elastic): a
    dp2×tp2 ShardedTrainer run killed mid-step by a coordinate-addressed
    chip_loss, rebuilt to dp1×tp2 (tp extent pinned, the touched
    dp-group dropped) and resumed from its layout-carrying sharded
    checkpoint resharded onto the survivor mesh. Reports the recovery
    wall-time (classify → rebuild_mesh → trainer rebind → cross-layout
    restore) and steps lost; the bitwise parity check against a clean
    dp1×tp2 run from the same checkpoint runs inside the leg and fails
    the row loudly on any divergence."""
    from tools.elastic_soak import run_kill_reshard_3d

    violations, row = run_kill_reshard_3d(seed=7, n_batches=10)
    if violations:
        raise RuntimeError(f"elastic 3d kill-and-reshard violated: "
                           f"{violations}")
    return _emit({
        "metric": "elastic_resume_3d_recovery_ms",
        "value": round(row["recovery_wall_s"] * 1e3, 2),
        "unit": "ms",
        "vs_baseline": None,
        "steps_lost": row["steps_lost"],
        "dp": f"{row['dp_from']}->{row['dp_to']}",
        "tp": row["tp"],
        "killed_device": row["killed_device"],
        "parity": row["resume_parity"],
    })


def bench_collective_overlap():
    """MULTICHIP collective row (kvstore.bucketing): the bucketing ×
    overlap × compression ablation grid over a dp4 training loop —
    unbucketed baseline, bucketed (sync per bucket), bucketed+overlapped
    (one grouped priority-ordered dispatch), and bucketed+overlapped+
    2-bit. Parity is asserted inside the leg (bitwise for the
    uncompressed points, bounded for 2-bit) along with ZERO steady-state
    recompiles at every point. On the CPU sim the fusion buffers can run
    FLAT-to-slower vs per-param pushpull: host emulation pays the
    concat/slice-back but hides no interconnect latency (there is none
    to hide) — the collapse that matters is collective COUNT (the
    llama-8B ZeRO lowering pins 1829 → ~131 all-gathers), which turns
    into step time only on a real ICI fabric. See PERF.md."""
    from tools.overlap_smoke import run_ablation

    violations, rows = run_ablation(steps=10, seed=0)
    if violations:
        raise RuntimeError(f"collective overlap ablation violated: "
                           f"{violations}")
    base = rows["base"]["step_ms"]
    bo = rows["bucket_overlap"]["step_ms"]
    return _emit({
        "metric": "collective_overlap_step_ms",
        "value": bo,
        "unit": "ms",
        "vs_baseline": round(base / bo, 3) if bo else None,
        "ablation": rows,
        "parity": rows["bucket_overlap"].get("parity"),
        "recompiles": sum(r["recompiles"] for r in rows.values()),
    })


def bench_llama_decode(max_new=32, reps=3, batch=16, spec_k=4):
    """Serving row (mxnet_tpu.serve): the ``decode_tokens_s`` ladder —
    every decode rung measured on the same 12L llama serve config, same
    prompts, same (batch, seq) bucket:

    * ``baseline`` — PR-5 strict path (shape-stable mul+reduce attention
      on the pinned deterministic runtime; the bitwise-parity contract)
    * ``pallas``   — fused Pallas decode-attention kernel
    * ``int8``     — pallas + int8 KV-cache rings (plus int8 projection
      weights on backends with int8 matrix units)
    * ``spec``     — SpeculativeGenerator (2-layer draft, k proposals per
      round) stacked on the int8 rung

    Rates are steady-state (the prefill-sampled first token of each row
    is excluded; decode wall only). The target model's layers >= 2 get
    zeroed o_proj/down_proj: runtime call args XLA cannot constant-fold,
    so every rung still pays the full 12-deep gemm/cache cost, while the
    2-layer copied-prefix draft predicts the (now 2-layer-equivalent)
    target almost perfectly — the spec rung's acceptance rate reflects
    draft quality, which a synthetic random model cannot provide.
    Each rung asserts ZERO recompiles after warmup — a recompile here is
    a perf bug, not noise, and fails the row loudly."""
    import numpy as onp

    from mxnet_tpu import numpy as mnp
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.profiler import attribution as _attr
    from mxnet_tpu.serve import Generator, SpeculativeGenerator

    attr_was_on = _attr.ENABLED
    _attr.enable()
    target = get_llama("llama_serve_12l_test")
    target.initialize()
    for blk in target._blocks[2:]:
        for p in (blk.attention.o_proj.weight, blk.ffn.down_proj.weight):
            p.set_data(mnp.zeros(p.shape, dtype="float32"))
    draft = get_llama("llama_serve_12l_test", num_layers=2)
    draft.initialize()
    tparams = dict(target.collect_params().items())
    for name, p in draft.collect_params().items():
        p.set_data(tparams[name].data())

    rng = onp.random.RandomState(0)
    prompts = [rng.randint(1, 500, size=int(rng.randint(4, 13))).tolist()
               for _ in range(batch)]

    def measure(gen):
        warm = gen.warmup()
        best, extra = 0.0, {}
        for _ in range(reps):
            outs, info = gen.generate(prompts, max_new_tokens=max_new)
            # steady-state rate: each row's FIRST token is sampled from
            # prefill logits, so it rides prefill wall, not decode wall
            toks = sum(len(o) for o in outs) - len(outs)
            rate = toks / (info["decode_ms"] / 1e3)
            if rate > best:
                best = rate
                extra = {k: info[k] for k in ("acceptance_rate", "rounds")
                         if k in info}
        gen.assert_no_recompiles()
        # critical-path attribution (Generator rungs only: the spec
        # round loop is not a fixed-width decode, its ledger stays
        # empty): one reconcile rep on a FRESH ledger so the 4-phase
        # sum + schedule bucket must cover THAT rep's decode wall —
        # >10% daylight means the partition is lying, fail loudly
        # exactly like a recompile
        attr = None
        if type(gen) is Generator:
            gen.ledger = _attr.Ledger(gen.ledger.name)
            _, info = gen.generate(prompts, max_new_tokens=max_new)
            snap = gen.ledger.snapshot()
            phase_ms = (snap["host_ms"] + snap["dispatch_ms"]
                        + snap["device_ms"] + snap["wait_ms"])
            coverage = ((phase_ms + snap["schedule_ms"])
                        / info["decode_ms"]) if info["decode_ms"] else 0.0
            assert 0.90 <= coverage <= 1.10, (
                f"{gen.ledger.name}: attribution phases cover "
                f"{coverage:.1%} of the decode wall (want 90-110%)")
            attr = {
                "host_overhead_fraction":
                    round(snap["host_overhead_fraction"], 4),
                "device_ms_per_token":
                    round(snap["device_ms_per_token"], 4),
                "phase_coverage": round(coverage, 3),
            }
        return round(best, 1), extra, round(warm["wall_s"], 2), attr

    ladder, warm_s, spec_extra, attribution = {}, {}, {}, {}
    for path in ("baseline", "pallas", "int8"):
        gen = Generator(target, max_seq=64, batch_buckets=(batch,),
                        prompt_buckets=(16,), name=f"llama_decode_{path}",
                        decode_path=path)
        ladder[path], _, warm_s[path], attribution[path] = measure(gen)
    spec = SpeculativeGenerator(
        target, draft, k=spec_k, max_seq=64, batch_buckets=(batch,),
        prompt_buckets=(16,), name="llama_decode_spec", decode_path="int8")
    ladder["spec"], spec_extra, warm_s["spec"], _ = measure(spec)
    attribution.pop("spec", None)
    if not attr_was_on:
        _attr.disable()

    base = ladder["baseline"]
    order = ("baseline", "pallas", "int8", "spec")
    speedups = {p: round(ladder[p] / base, 2) if base else None
                for p in order}
    # 2% tolerance: adjacent rungs can sit within run-to-run CPU noise
    monotone = all(ladder[b] >= ladder[a] * 0.98
                   for a, b in zip(order, order[1:]))
    return _emit({
        "metric": "llama_decode_tokens_s",
        "value": ladder["spec"],
        "unit": "tokens/s",
        "vs_baseline": speedups["spec"],
        "ladder": ladder,
        "speedups": speedups,
        "monotone": monotone,
        "acceptance_rate": round(spec_extra.get("acceptance_rate", 0.0), 3),
        "spec_k": spec_k,
        "batch": batch,
        "max_new_tokens": max_new,
        "warmup_s": warm_s,
        # critical-path readout from the fastest fixed-width rung: how
        # much of each decode iteration is host overhead vs device work
        "host_overhead_fraction":
            attribution["int8"]["host_overhead_fraction"],
        "device_ms_per_token":
            attribution["int8"]["device_ms_per_token"],
        "attribution": attribution,
    })


def bench_llama_multistep_decode(max_new=32, reps=2, batch=16, spec_k=4):
    """Serving row (tentpole PR 19): the device-side multi-step decode
    ladder — the same 12L llama serve config, prompts, and (batch, seq)
    bucket as ``bench_llama_decode``, but the token loop runs as one
    compiled ``while_loop`` super-step of N decode iterations per host
    visit (``MXNET_SERVE_MULTISTEP`` / ``MXNET_SERVE_DECODE_STEPS``):

    * ``baseline``/``pallas``/``int8`` x N in {1, 4, 8} — each multistep
      rung must be greedy token-identical to its single-step Generator,
      compile exactly one extra signature (the super-step), and never
      recompile
    * ``spec`` — SpeculativeGenerator with the whole draft-propose phase
      of a round as ONE draft super-step (2 host visits per round
      instead of k+2), stacked on the int8 rung

    ``host_visits_per_token`` is the ladder's reason to exist: at N=8 a
    32-token row takes ~4 device visits instead of ~31, and the row
    asserts visits/token <= 1/4 AND tokens/s strictly above the same
    path's single-step rate — if killing the host round-trip doesn't
    show up in the rate, the super-step is broken, fail loudly."""
    import numpy as onp

    from mxnet_tpu import numpy as mnp
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import Generator, SpeculativeGenerator

    target = get_llama("llama_serve_12l_test")
    target.initialize()
    for blk in target._blocks[2:]:
        for p in (blk.attention.o_proj.weight, blk.ffn.down_proj.weight):
            p.set_data(mnp.zeros(p.shape, dtype="float32"))
    draft = get_llama("llama_serve_12l_test", num_layers=2)
    draft.initialize()
    tparams = dict(target.collect_params().items())
    for name, p in draft.collect_params().items():
        p.set_data(tparams[name].data())

    rng = onp.random.RandomState(0)
    prompts = [rng.randint(1, 500, size=int(rng.randint(4, 13))).tolist()
               for _ in range(batch)]

    def measure(gen, ref_outs=None, label=""):
        warm = gen.warmup()
        best, hv, outs = 0.0, None, None
        for _ in range(reps):
            outs, info = gen.generate(prompts, max_new_tokens=max_new)
            if ref_outs is not None:
                assert outs == ref_outs, (
                    f"{label}: multistep greedy output diverged from "
                    f"the single-step reference")
            # steady-state: each row's first token rides prefill wall
            toks = sum(len(o) for o in outs) - len(outs)
            rate = toks / (info["decode_ms"] / 1e3)
            best = max(best, rate)
            if "decode_visits" in info:
                hv = info["decode_visits"] / max(toks, 1)
        gen.assert_no_recompiles()
        return round(best, 1), hv, outs, round(warm["wall_s"], 2)

    steps_ladder = (1, 4, 8)
    ladder, visits, warm_s, refs = {}, {}, {}, {}
    for path in ("baseline", "pallas", "int8"):
        single = Generator(target, max_seq=64, batch_buckets=(batch,),
                           prompt_buckets=(16,),
                           name=f"llama_ms_{path}_single",
                           decode_path=path, multistep=False)
        rate1, _, ref_outs, w = measure(single, label=f"{path}/single")
        ladder[path] = {"single": rate1}
        visits[path] = {"single": 1.0}
        warm_s[f"{path}_single"] = w
        refs[path] = ref_outs
        for n in steps_ladder:
            gen = Generator(target, max_seq=64, batch_buckets=(batch,),
                            prompt_buckets=(16,),
                            name=f"llama_ms_{path}_n{n}",
                            decode_path=path, multistep=True,
                            decode_steps=n)
            rate, hv, _, w = measure(gen, ref_outs=ref_outs,
                                     label=f"{path}/N={n}")
            ladder[path][f"n{n}"] = rate
            visits[path][f"n{n}"] = round(hv, 4)
            warm_s[f"{path}_n{n}"] = w
        assert visits[path]["n8"] <= 0.25, (
            f"{path}: N=8 host_visits_per_token "
            f"{visits[path]['n8']:.3f} > 1/4 — the super-step is not "
            f"amortizing the host round-trip")
        # the headline rung (int8) must be STRICTLY faster than
        # single-step; the others get the same 2% run-to-run noise
        # tolerance as bench_llama_decode's monotone check
        floor = ladder[path]["single"] * (1.0 if path == "int8" else 0.98)
        assert ladder[path]["n8"] > floor, (
            f"{path}: N=8 rate {ladder[path]['n8']} tok/s not above the "
            f"single-step rate {ladder[path]['single']} — killing the "
            f"host round-trip must show up in throughput")

    # spec rung: draft-round-as-super-step, stacked on int8. Greedy
    # speculative decoding is defined by emitting the target's greedy
    # sequence, so the int8 single-step reference is its identity oracle.
    spec = SpeculativeGenerator(
        target, draft, k=spec_k, max_seq=64, batch_buckets=(batch,),
        prompt_buckets=(16,), name="llama_ms_spec", decode_path="int8",
        multistep=True)
    spec_warm = spec.warmup()
    spec_best, spec_info = 0.0, {}
    for _ in range(reps):
        outs, info = spec.generate(prompts, max_new_tokens=max_new)
        assert outs == refs["int8"], (
            "spec: draft-super-step output diverged from the int8 "
            "single-step greedy reference")
        toks = sum(len(o) for o in outs) - len(outs)
        spec_best = max(spec_best, toks / (info["decode_ms"] / 1e3))
        spec_info = info
    spec.assert_no_recompiles()
    ladder["spec"] = {"single": ladder["int8"]["single"],
                      "n8": round(spec_best, 1)}
    warm_s["spec"] = round(spec_warm["wall_s"], 2)

    speedup_vs_single = {
        p: round(ladder[p]["n8"] / ladder[p]["single"], 2)
        for p in ("baseline", "pallas", "int8")}
    return _emit({
        "metric": "llama_multistep_decode_tokens_s",
        "value": ladder["int8"]["n8"],
        "unit": "tokens/s",
        "vs_baseline": round(ladder["int8"]["n8"]
                             / ladder["baseline"]["single"], 2),
        "decode_steps": 8,
        "ladder": ladder,
        "host_visits_per_token": visits["int8"]["n8"],
        "visits": visits,
        "speedup_vs_single": speedup_vs_single,
        "acceptance_rate": round(spec_info.get("acceptance_rate", 0.0), 3),
        "spec_k": spec_k,
        "batch": batch,
        "max_new_tokens": max_new,
        "warmup_s": warm_s,
    })


def bench_llama_continuous_batching(reps=2):
    """Serving row (serve.scheduler): continuous batching vs the static
    bucket ladder on the same 12L llama serve config and the same mixed
    open-ended traffic — a burst of 32 requests interleaved
    ``[long, short, short, short] x 8`` (8 batch-class 48-token decodes
    among 24 interactive 4-token requests).

    The static side is the PR-6/PR-10 stack at its best bucket: batches
    of 8 in arrival order, each batch running until its LONGEST request
    finishes — the interactive shorts ride out all 48 steps
    (head-of-line blocking) and their lanes decode dead air after step 4.
    The continuous side admits/retires between decode steps over 8 paged
    slots, so a retired short's slot immediately decodes the next
    request. Same decode-rung executables on both sides, per rung.

    Reported per rung: aggregate USEFUL tokens/s (requested tokens only —
    the static side gets no credit for dead-lane tokens) and client-side
    interactive p99 from burst arrival. The row hard-fails unless
    continuous batching beats static on BOTH metrics on every rung, and
    every engine asserts zero recompiles."""
    import threading

    import numpy as onp

    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import ContinuousEngine, Generator, percentile

    net = get_llama("llama_serve_12l_test")
    net.initialize()

    rng = onp.random.RandomState(0)
    reqs = []  # (prompt, max_new, priority) in arrival order
    for _ in range(8):
        reqs.append((rng.randint(1, 500, size=8).tolist(), 48, "batch"))
        for _ in range(3):
            reqs.append((rng.randint(
                1, 500, size=int(rng.randint(4, 13))).tolist(), 4,
                "interactive"))
    useful = sum(m for _, m, _ in reqs)

    ladder = {}
    for path in ("baseline", "pallas", "int8"):
        gen = Generator(net, max_seq=64, batch_buckets=(8,),
                        prompt_buckets=(16,), decode_path=path,
                        name=f"cb_static_{path}")
        gen.warmup()
        st_rate, st_p99 = 0.0, None
        for _ in range(reps):
            t0 = time.monotonic()
            lat = []
            for g in range(0, len(reqs), 8):
                grp = reqs[g:g + 8]
                gen.generate([p for p, _, _ in grp],
                             max_new_tokens=max(m for _, m, _ in grp))
                done = (time.monotonic() - t0) * 1e3
                lat += [done for _, _, pr in grp if pr == "interactive"]
            rate = useful / (time.monotonic() - t0)
            if rate > st_rate:
                st_rate, st_p99 = rate, percentile(lat, 99)
        gen.assert_no_recompiles()

        eng = ContinuousEngine(net, max_seq=64, num_slots=8, page_size=16,
                               prefill_chunk=16, decode_path=path,
                               name=f"cb_engine_{path}", max_queue=64)
        eng.start()
        cb_rate, cb_p99 = 0.0, None
        for _ in range(reps):
            done_t, lock = {}, threading.Lock()

            def stamp(i):
                def cb(_f):
                    with lock:
                        done_t[i] = time.monotonic()
                return cb

            t0 = time.monotonic()
            futs = []
            for i, (p, m, pr) in enumerate(reqs):
                f = eng.submit(p, max_new_tokens=m, priority=pr)
                f.add_done_callback(stamp(i))
                futs.append(f)
            for f in futs:
                f.result(timeout=600)
            rate = useful / (time.monotonic() - t0)
            lat = [(done_t[i] - t0) * 1e3
                   for i, (_, _, pr) in enumerate(reqs)
                   if pr == "interactive"]
            if rate > cb_rate:
                cb_rate, cb_p99 = rate, percentile(lat, 99)
        eng.assert_no_recompiles()
        eng.close()

        if cb_rate <= st_rate or cb_p99 >= st_p99:
            raise RuntimeError(
                f"continuous batching lost to static buckets on the "
                f"{path} rung: tokens/s {cb_rate:.1f} vs {st_rate:.1f}, "
                f"interactive p99 {cb_p99:.0f}ms vs {st_p99:.0f}ms")
        ladder[path] = {
            "cb_tokens_s": round(cb_rate, 1),
            "static_tokens_s": round(st_rate, 1),
            "speedup": round(cb_rate / st_rate, 2),
            "cb_interactive_p99_ms": round(cb_p99, 1),
            "static_interactive_p99_ms": round(st_p99, 1),
            "p99_improvement": round(st_p99 / cb_p99, 2),
        }

    best = ladder["int8"]
    return _emit({
        "metric": "llama_cb_tokens_s",
        "value": best["cb_tokens_s"],
        "unit": "tokens/s",
        "vs_baseline": best["speedup"],
        "ladder": ladder,
        "traffic": "8x[48-tok batch] + 24x[4-tok interactive], burst",
        "slots": 8,
        "page_size": 16,
    })


def bench_llama_prefix_cache(reps=2):
    """Serving row (serve.prefix_cache + mxnet_tpu.compile_cache): the
    PR-14 "never redo prior work" stack on the 12L llama serve config.

    Traffic is the prefix-cache sweet spot production chat exhibits: a
    burst of 32 requests sharing one 32-token system prompt with 8
    unique tail tokens each (80 % shared). Reported: TTFT p99 with the
    radix trie on vs off (same engine config, same burst — the on-side
    skips the shared prefill), the prefill tokens skipped, and the
    cold-start split — warming the same engine lattice twice against
    one persistent compile cache dir, where the second warmup must
    replay entirely from disk (disk hits, no new compiles) and beat the
    cold wall time. Hard-fails unless the trie actually hits, TTFT p99
    improves, outputs stay token-identical, and the disk-warm run
    compiles nothing new."""
    import os
    import shutil
    import subprocess
    import tempfile

    import numpy as onp

    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import ContinuousEngine, percentile

    net = get_llama("llama_serve_12l_test")
    net.initialize()

    rng = onp.random.RandomState(0)
    system = rng.randint(1, 500, size=32).tolist()
    reqs = [system + rng.randint(1, 500, size=8).tolist()
            for _ in range(32)]

    def build(name, prefix_on):
        eng = ContinuousEngine(net, max_seq=64, num_slots=8, page_size=16,
                               prefill_chunk=16, decode_path="pallas",
                               prefix_cache=prefix_on, name=name,
                               max_queue=64)
        eng.start()
        return eng

    def drive(prefix_on):
        eng = build("px_bench", prefix_on)
        best_p99, tokens = None, None
        for _ in range(reps):
            if prefix_on:
                # one settled request seeds the trie before the burst
                eng.submit(reqs[0], max_new_tokens=8).result(600)
            futs = [eng.submit(p, max_new_tokens=8) for p in reqs]
            outs = [f.result(600) for f in futs]
            p99 = percentile([o["ttft_ms"] for o in outs], 99)
            if best_p99 is None or p99 < best_p99:
                best_p99 = p99
            tokens = [o["tokens"] for o in outs]
        eng.assert_no_recompiles()
        snap = eng.metrics.snapshot()
        eng.close()
        return best_p99, tokens, snap

    base_p99, base_tokens, _ = drive(False)
    px_p99, px_tokens, snap = drive(True)
    if px_tokens != base_tokens:
        raise RuntimeError(
            "prefix-cache-on greedy output diverged from cache-off")
    if not snap["prefix_hit_rate"] > 0 or not snap["prefix_tokens_skipped"]:
        raise RuntimeError(
            f"80%-shared burst produced no trie reuse: "
            f"hit_rate={snap['prefix_hit_rate']} "
            f"skipped={snap['prefix_tokens_skipped']}")
    if px_p99 >= base_p99:
        raise RuntimeError(
            f"prefix cache lost on TTFT p99: {px_p99:.0f}ms on vs "
            f"{base_p99:.0f}ms off")

    # cold-start split: same lattice, one persistent cache dir, two
    # FRESH processes — in-process remeasurement would be flattered by
    # jax's in-memory compilation memo (identical HLO never reaches the
    # disk layer twice in one process), so each start pays exactly what
    # a scaled-up replica or reloaded tenant pays
    child_code = (
        "import json, os, sys, time\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import compile_cache\n"
        "from mxnet_tpu.models.llama import get_llama\n"
        "from mxnet_tpu.serve import ContinuousEngine\n"
        "compile_cache.enable(sys.argv[1])\n"
        "mx.random.seed(0)\n"
        "net = get_llama('llama_serve_12l_test')\n"
        "net.initialize()\n"
        "t0 = time.monotonic()\n"
        "eng = ContinuousEngine(net, max_seq=64, num_slots=8,\n"
        "                       page_size=16, prefill_chunk=16,\n"
        "                       decode_path='pallas', name='px_cold',\n"
        "                       max_queue=64)\n"
        "eng.start()\n"
        "warmup_s = time.monotonic() - t0\n"
        "eng.close()\n"
        "print('PX_COLD=' + json.dumps({\n"
        "    'warmup_s': warmup_s,\n"
        "    'disk_hits': compile_cache.disk_hits(),\n"
        "    'disk_misses': compile_cache.disk_misses()}))\n")
    d = tempfile.mkdtemp(prefix="mxtpu_ccbench_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   [os.path.dirname(os.path.abspath(__file__))]
                   + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
    try:
        docs = []
        for _ in range(2):
            proc = subprocess.run(
                [sys.executable, "-c", child_code, d], env=env,
                capture_output=True, text=True, timeout=600)
            line = [ln for ln in proc.stdout.splitlines()
                    if ln.startswith("PX_COLD=")]
            if proc.returncode != 0 or not line:
                raise RuntimeError(
                    f"cold-start child failed rc={proc.returncode}: "
                    f"{proc.stderr[-2000:]}")
            docs.append(json.loads(line[0].split("=", 1)[1]))
    finally:
        shutil.rmtree(d, ignore_errors=True)
    cold, warm = docs
    cold_s, warm_s = cold["warmup_s"], warm["warmup_s"]
    cold_misses = cold["disk_misses"]
    warm_hits, warm_misses = warm["disk_hits"], warm["disk_misses"]
    if not warm_hits or warm_misses:
        raise RuntimeError(
            f"disk-warm engine did not replay the lattice from the "
            f"persistent cache: hits={warm_hits} misses={warm_misses}")
    if warm_s >= cold_s:
        raise RuntimeError(
            f"disk-warm start ({warm_s:.2f}s) did not beat cold "
            f"({cold_s:.2f}s)")

    return _emit({
        "metric": "llama_prefix_ttft_p99_ms",
        "value": round(px_p99, 1),
        "unit": "ms",
        "vs_baseline": round(base_p99 / px_p99, 2),
        "ttft_p99_cache_off_ms": round(base_p99, 1),
        "prefill_tokens_skipped": snap["prefix_tokens_skipped"],
        "prefix_hit_rate": round(snap["prefix_hit_rate"], 3),
        "traffic": "32 reqs, 32-tok shared system + 8-tok unique tails",
        "cold_start": {
            "cold_warmup_s": round(cold_s, 2),
            "disk_warmup_s": round(warm_s, 2),
            "speedup": round(cold_s / warm_s, 2),
            "cold_disk_misses": cold_misses,
            "warm_disk_hits": warm_hits,
        },
    })


def bench_bandwidth():
    """KVStore push/pull bandwidth (tools/bandwidth parity, perf.md:263).

    On a 1-chip run the all-reduce degenerates to an HBM read+write of the
    buffer, so the row is labeled ``hbm_roundtrip`` and ``vs_peak`` compares
    against the chip's HBM bandwidth; on a real multi-chip mesh the label
    becomes ``ici_collective`` and ``vs_peak`` is vs ICI. The probe raises
    on degenerate timings instead of clamping (the r2 number was
    bytes/1e-9 garbage; see measure_pushpull_bandwidth)."""
    import jax

    from mxnet_tpu.kvstore.dist_tpu import measure_pushpull_bandwidth

    # 512 MB: bigger than VMEM, so the scanned reduce really rides HBM (a
    # 64 MB carry stays VMEM-resident and reads >HBM-peak "bandwidth");
    # iters sized so the loop holds the device ~0.3 s per measurement —
    # the two-loop difference must dwarf tunnel RTT jitter
    gbs = measure_pushpull_bandwidth(size_mb=512, iters=200)
    n = len(jax.devices())
    if n == 1:
        kind = "hbm_roundtrip"
        peak = _chip_peak("hbm")
    else:
        kind = "ici_collective"
        peak = _chip_peak("ici")
    return _emit({
        "metric": "kvstore_pushpull_bw_512mb",
        "value": round(gbs, 2),
        "unit": "GB/s",
        "vs_baseline": None,
        "kind": kind,
        "vs_peak": round(gbs * 1e9 / peak, 3) if peak else None,
    })


def bench_resnet_input_pipeline(batch=32, n_batches=12, size=128, reps=3):
    """ResNet-50 forward fed live by the sharded RecordIO pipeline
    (RecordPipeline decode workers -> DeviceFeeder double-buffer) vs the
    SAME batches pre-materialized on device — the PR-20 input-pipeline
    overhead row. The feeder issues batch k+1's host pull + H2D before
    returning batch k, so with the model compute dominating, the
    pipeline-fed rate must land within a few percent of pre-materialized
    and the steady-state input stall near zero (what the overlap could
    not hide is `input_stall_ms`, also attributed to the profiler's
    `input` phase)."""
    import os
    import tempfile

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu import recordio
    from mxnet_tpu.io.pipeline import DeviceFeeder, RecordPipeline

    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()

    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=mx.cpu())
    small = mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"),
                      ctx=mx.cpu())
    with autograd.predict_mode():
        net(small)
    if ctx.device_type != "cpu":
        net.reset_ctx(ctx)
    net.hybridize(static_alloc=True)

    # raw uint8 CHW images in the .rec (a realistic decode: bytes ->
    # float32/255 on the worker pool), crc-indexed
    rng = onp.random.RandomState(0)
    imgs = rng.randint(0, 256, (batch * n_batches, 3, size, size),
                       dtype=onp.uint8)

    def decode(payload):
        return onp.frombuffer(payload, dtype=onp.uint8) \
            .reshape(3, size, size).astype("float32") / 255.0

    def batchify(items):
        return mnp.array(onp.stack(items), ctx=mx.cpu())

    def run_epoch(batches):
        out = None
        for xb in batches:
            with autograd.predict_mode():
                out = net(xb)
        out.asnumpy()  # drain: the lazy runtime settles at the fetch

    with tempfile.TemporaryDirectory(prefix="bench_io.") as d:
        recf = os.path.join(d, "bench.rec")
        w = recordio.MXIndexedRecordIO(os.path.join(d, "bench.idx"),
                                       recf, "w")
        for i, img in enumerate(imgs):
            w.write_idx(i, img.tobytes())
        w.close()

        # pre-materialized arm: every batch already resident on device
        device = [mnp.array(imgs[i * batch:(i + 1) * batch]
                            .astype("float32") / 255.0, ctx=ctx)
                  for i in range(n_batches)]
        run_epoch(device)  # compile
        pre_walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            run_epoch(device)
            pre_walls.append(time.perf_counter() - t0)

        pipe = RecordPipeline([recf], batch_size=batch,
                              decode_fn=decode, batchify_fn=batchify,
                              name="bench-input")
        feeder = DeviceFeeder(pipe, ctx=ctx, name="bench-input-feeder")
        run_epoch(feeder)  # same program, warm; also warms the pool
        pipe_walls, stalls = [], []
        for _ in range(reps):
            feeder.reset()
            s0 = feeder.stats()["stall_ms"]
            t0 = time.perf_counter()
            run_epoch(feeder)
            pipe_walls.append(time.perf_counter() - t0)
            stalls.append(feeder.stats()["stall_ms"] - s0)
        pipe_stats = pipe.stats()
        pipe.close()

    n_img = batch * n_batches
    pre_img_s = n_img / min(pre_walls)
    pipe_img_s = n_img / min(pipe_walls)
    stall_ms = sorted(stalls)[len(stalls) // 2]
    row = _emit({
        "metric": f"resnet50_v1_input_pipeline_bs{batch}",
        "value": round(pipe_img_s, 2),
        "unit": "img/s",
        "vs_baseline": None,
        "pre_materialized_img_s": round(pre_img_s, 2),
        "vs_pre_materialized": round(pipe_img_s / pre_img_s, 4),
        "io_workers": pipe_stats["workers"],
        "io_worker_utilization": pipe_stats["worker_utilization"],
        "io_bytes_per_s": pipe_stats["bytes_per_s"],
        **_dispatch_meta(),
    })
    _emit({
        "metric": f"resnet50_v1_input_pipeline_bs{batch}_stall_ms",
        "value": round(stall_ms, 3),
        "unit": "ms",
        "vs_baseline": None,
        "per_batch_stall_ms": round(stall_ms / n_batches, 3),
    })
    return row


def main():
    rows = {}
    failures = {}
    for name, fn in [("infer", bench_resnet_infer),
                     ("infer_int8", bench_resnet_infer_int8),
                     ("infer_pallas_fused", bench_resnet_infer_pallas_fused),
                     ("bandwidth", bench_bandwidth),
                     ("guardrail_overhead", bench_guardrail_overhead),
                     ("ckpt_stall", bench_ckpt_stall),
                     ("elastic_resume", bench_elastic_resume),
                     ("elastic_resume_3d", bench_elastic_resume_3d),
                     ("collective_overlap", bench_collective_overlap),
                     ("lenet_eager", bench_lenet_eager),
                     ("trace_overhead", bench_trace_overhead),
                     ("lenet_eager_bulk16", bench_lenet_eager_bulk),
                     ("bert", bench_bert_train),
                     ("bert_fused", bench_bert_train_fused),
                     ("llama_decode", bench_llama_decode),
                     ("llama_multistep_decode", bench_llama_multistep_decode),
                     ("llama_continuous_batching",
                      bench_llama_continuous_batching),
                     ("llama_prefix_cache", bench_llama_prefix_cache),
                     ("llama_long_seq", bench_llama_long_seq),
                     ("llama_long_seq4k",
                      lambda: bench_llama_long_seq(seq=4096, batch=2)),
                     ("resnet_input_pipeline", bench_resnet_input_pipeline),
                     ("resnet_train_bf16",
                      lambda: bench_resnet_train("bfloat16")),
                     ("resnet_train_fused", bench_resnet_train_fused)]:
        try:
            rows[name] = fn()
        except Exception as e:  # keep the suite alive; report what ran
            msg = f"{type(e).__name__}: {e}"
            # tunnel-transport drops (remote_compile connection resets)
            # are transient — one retry before recording a failure
            if "remote_compile" in str(e) or "INTERNAL" in str(e):
                print(f"# bench {name}: tunnel error, retrying once: {msg}",
                      file=sys.stderr)
                try:
                    rows[name] = fn()
                    continue
                except Exception as e2:
                    msg = f"{type(e2).__name__}: {e2}"
            failures[name] = msg
            print(f"# bench {name} failed: {failures[name]}", file=sys.stderr)
    head = rows.get("resnet_train_fused") or rows.get("resnet_train_bf16") \
        or rows.get("bert_fused") or rows.get("bert") or rows.get("infer")
    if head is None:
        _emit({"metric": "bench_failed", "value": 0, "unit": "",
               "vs_baseline": 0, "errors": failures})
        return 1
    final = dict(head)
    final["extra"] = {k: v for k, v in rows.items()}
    if failures:
        final["errors"] = failures
    # resilience counters next to the telemetry numbers: BENCH rounds track
    # robustness cost (retries/degradations should be 0 on a healthy chip;
    # nonzero values explain a slow row before anyone re-runs it)
    try:
        from mxnet_tpu.resilience import resilience_stats

        final["resilience"] = resilience_stats()
    except Exception as e:
        print(f"# resilience stats unavailable: {e}", file=sys.stderr)
    _emit(final)
    return 0


if __name__ == "__main__":
    sys.exit(main())
