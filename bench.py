"""Headline benchmark suite: training MFU, inference, KVStore bandwidth.

North star (BASELINE.md targets): ResNet-50 + BERT-base *training* at
>=50% MFU with `dist_tpu_sync`/SPMD step, plus KVStore push/pull bandwidth.
Reference protocol: `docs/.../perf.md:252-254` (train_imagenet.py, synthetic
data) and `benchmark_score.py` for inference; V100 fp32 numbers are the
`vs_baseline` denominators (BASELINE.md).

MFU accounting: numerator = XLA `cost_analysis()['flops']` of the compiled
step (exact algebraic FLOPs of the program actually executed), denominator =
chip peak (bf16 MXU rate, by `device_kind`, overridable via
MXNET_TPU_PEAK_FLOPS).

Timing methodology: the TPU here sits behind a tunnel whose
`block_until_ready` returns before execution finishes and whose
device->host fetch costs ~100 ms RTT. Every measurement therefore runs the
SAME loop at two iteration counts, each ended by an actual host fetch, and
takes the difference — the fetch RTT, dispatch tails, and any lazy-execution
slack cancel exactly.

Prints one JSON row per metric as it completes; the FINAL line is the
headline (bf16 ResNet-50 training) row with an `extra` dict carrying all
rows, for the driver's single-line parse.
"""
from __future__ import annotations

import json
import os
import sys
import time

# bf16 MXU peak per chip, by jax device_kind
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

BASE_INFER_IMG_S = 1076.81   # V100 fp32 bs32 inference, perf.md:193
BASE_TRAIN_IMG_S = 363.69    # V100 fp32 bs128 training, perf.md:254


def _peak_flops():
    import jax

    env = os.environ.get("MXNET_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    kind = jax.devices()[0].device_kind
    for k, v in _PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return None


def _emit(row):
    print(json.dumps(row), flush=True)
    return row


def _timed_diff(step, fetch, k1, k2):
    """Per-iteration seconds of `step`, by the two-loop difference: run k1
    iterations + fetch, then k2, and divide the extra time by (k2-k1).
    Cancels fetch RTT / lazy-dispatch artifacts of the tunnel runtime."""
    def run(k):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = step()
        fetch(r)
        return time.perf_counter() - t0
    diffs = []
    for _ in range(3):
        d1 = run(k1)
        d2 = run(k2)
        if d2 > d1:
            diffs.append((d2 - d1) / (k2 - k1))
    if not diffs:
        raise RuntimeError(
            f"degenerate timing: {k2}-iter loops never exceeded {k1}-iter "
            f"loops — queue not drained before timing?")
    diffs.sort()
    return diffs[len(diffs) // 2]


def bench_resnet_infer():
    """ResNet-50 v1 fp32 inference, batch 32 — benchmark_score.py protocol
    through the user-facing path: model_zoo net -> hybridize() -> XLA."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp

    BATCH, SIZE = 32, 224
    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()

    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=mx.cpu())
    small = mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"), ctx=mx.cpu())
    with autograd.predict_mode():
        net(small)
    if ctx.device_type != "cpu":
        net.reset_ctx(ctx)
    net.hybridize(static_alloc=True)

    x = mnp.array(
        onp.random.uniform(-1, 1, (BATCH, 3, SIZE, SIZE)).astype("float32"),
        ctx=ctx)
    with autograd.predict_mode():
        net(x).asnumpy()  # compile AND drain (lazy runtime: fetch forces it)
        dt = _timed_diff(lambda: net(x),
                         lambda out: out.asnumpy(), 3, 18)
    img_s = BATCH / dt
    return _emit({
        "metric": "resnet50_v1_infer_bs32_fp32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASE_INFER_IMG_S, 3),
    })


def bench_resnet_infer_int8():
    """ResNet-50 INT8 inference, batch 32 (contrib.quantization int8 path;
    v5e MXU int8 peak is 2x bf16). vs_baseline: the V100 fp16 row
    (perf.md:208, 2085.51 img/s) — the reference's reduced-precision
    inference analog."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.contrib.quantization import quantize_net

    BATCH, SIZE = 32, 224
    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=mx.cpu())
    # materialize + calibrate on CPU (eager resnet over the tunnel would
    # pay per-op RTT), then move to the chip for the timed int8 path
    with autograd.predict_mode():
        net(mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"),
                      ctx=mx.cpu()))
    xc = mnp.array(
        onp.random.uniform(-1, 1, (8, 3, SIZE, SIZE)).astype("float32"),
        ctx=mx.cpu())
    quantize_net(net, calib_data=xc, calib_mode="naive")
    try:
        ctx = mx.tpu()
        ctx.jax_device()
        net.reset_ctx(ctx)
    except Exception:
        ctx = mx.cpu()
    x = mnp.array(
        onp.random.uniform(-1, 1, (BATCH, 3, SIZE, SIZE)).astype("float32"),
        ctx=ctx)
    net.hybridize(static_alloc=True)
    with autograd.predict_mode():
        net(x).asnumpy()  # compile + drain
        dt = _timed_diff(lambda: net(x), lambda out: out.asnumpy(), 3, 18)
    img_s = BATCH / dt
    return _emit({
        "metric": "resnet50_v1_infer_bs32_int8",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / 2085.51, 3),
    })


def _train_bench(net, loss_fn, optimizer, opt_params, data, labels,
                 rules=None, dtype=None, k1=3, k2=15, fuse=None):
    """Shared training-step timer: ShardedTrainer (SPMD step over the device
    mesh — the dist_tpu_sync execution model), XLA-counted FLOPs -> MFU.

    ``fuse=N``: time ``step_n`` windows of N steps in one dispatch (the
    bulk-exec path); the returned dt is per WINDOW (divide by N for
    per-step)."""
    import jax
    import numpy as onp

    from mxnet_tpu.parallel import ShardedTrainer, ShardingRules, make_mesh

    mesh = make_mesh({"dp": len(jax.devices())})
    trainer = ShardedTrainer(net, loss_fn, optimizer, opt_params, mesh=mesh,
                             rules=rules or ShardingRules(default_axis=None),
                             dtype=dtype)
    # place the synthetic batch on the mesh ONCE — steps must time the chip,
    # not host->device transfers of the same bytes every iteration
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place_tree(tree, spec):
        sh = NamedSharding(mesh, spec)
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)

    if fuse:
        stack = lambda a: onp.broadcast_to(  # noqa: E731
            a[None], (fuse,) + a.shape).copy()
        data = jax.tree_util.tree_map(stack, data)
        labels = jax.tree_util.tree_map(stack, labels)
        data = place_tree(data, P(None, "dp"))
        labels = place_tree(labels, P(None, "dp"))
        step = lambda: trainer.step_n(data, labels)  # noqa: E731
        fetch = lambda ls: float(ls.asnumpy().reshape(-1)[-1])  # noqa: E731
    else:
        data = place_tree(data, P("dp"))
        labels = place_tree(labels, P("dp"))
        step = lambda: trainer.step(data, labels)  # noqa: E731
        fetch = lambda loss: float(loss.asnumpy().reshape(-1)[0])  # noqa: E731
    # compile AND drain: on the lazy tunnel runtime only a host fetch
    # guarantees compilation + execution happened before the timed loops
    fetch(step())
    dt = _timed_diff(step, fetch, k1, k2)
    peak = _peak_flops()
    # step_flops is per-step; a fused window executes `fuse` steps per dt
    flops = (trainer.step_flops or 0) * (fuse or 1)
    mfu = (flops / dt / peak) if (peak and flops) else None
    return dt, mfu


def _make_resnet():
    import numpy as onp

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp

    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize()
    with autograd.predict_mode():
        net(mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32")))
    return net


def bench_resnet_train(dtype=None):
    """ResNet-50 v1 training step, batch 256, SGD+momentum —
    train_imagenet.py protocol (synthetic data; the reference's largest
    published train batch is 128, perf.md:254, which stays the
    vs_baseline denominator). With dtype='bfloat16': AMP bf16 compute,
    fp32 master weights. Batch 256 measured ~28%% MFU on v5e vs ~20%% at
    128 (deeper per-step pipeline amortizes dispatch + memory stalls)."""
    import numpy as onp

    from mxnet_tpu import gluon

    BATCH = 256
    net = _make_resnet()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = onp.random.uniform(-1, 1, (BATCH, 3, 224, 224)).astype("float32")
    y = onp.random.randint(0, 1000, (BATCH,)).astype("int32")
    dt, mfu = _train_bench(
        net, loss_fn, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}, x, y,
        dtype=dtype)
    img_s = BATCH / dt
    tag = "bf16_amp" if dtype else "fp32"
    return _emit({
        "metric": f"resnet50_v1_train_bs256_{tag}",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASE_TRAIN_IMG_S, 3),
        "mfu": round(mfu, 4) if mfu else None,
    })


def bench_resnet_train_fused(n_fuse=4):
    """ResNet-50 bf16 training with N steps fused into one dispatch
    (`ShardedTrainer.step_n` lax.scan window — the bulk-exec path):
    removes per-step host dispatch from the measurement, showing the
    framework's compute ceiling."""
    import numpy as onp

    from mxnet_tpu import gluon

    BATCH = 256
    net = _make_resnet()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = onp.random.uniform(-1, 1, (BATCH, 3, 224, 224)).astype("float32")
    y = onp.random.randint(0, 1000, (BATCH,)).astype("int32")
    dt, mfu = _train_bench(
        net, loss_fn, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}, x, y,
        dtype="bfloat16", fuse=n_fuse, k1=2, k2=8)
    img_s = n_fuse * BATCH / dt
    return _emit({
        "metric": f"resnet50_v1_train_bs256_bf16_fused{n_fuse}",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASE_TRAIN_IMG_S, 3),
        "mfu": round(mfu, 4) if mfu else None,
    })


def bench_bert_train():
    """BERT-base MLM+NSP training step, batch 64, seq 128, Adam, AMP bf16 —
    the GluonNLP pretraining config named in BASELINE.json. Runs the Pallas
    flash-attention path (valid_length in-kernel masking). Batch 64 is the
    measured MFU sweet spot on v5e (bs32 underfills, bs128 hits memory
    pressure on the fp32 MLM logits)."""
    import numpy as onp

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.models.bert import BERTForPretrain, get_bert_model

    BATCH, SEQ = 64, 128

    class PretrainStep(HybridBlock):
        """Single-input wrapper: derives valid_length from the pad mask so
        the whole example (tokens only) flows through one SPMD step."""

        def __init__(self, model):
            super().__init__()
            self.model = model

        def forward(self, tokens):
            valid_length = (tokens != 0).sum(axis=1)
            return self.model(tokens, valid_length=valid_length)

    net = PretrainStep(BERTForPretrain(get_bert_model("bert_12_768_12")))
    net.initialize()
    tokens = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
    # a few padded tails so the valid-length mask path is exercised
    tokens[::4, SEQ - 16:] = 0
    with autograd.predict_mode():
        net(mnp.array(tokens[:1, :16]))  # tiny: just materializes shapes

    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_fn(outs, labels):
        mlm_scores, nsp_scores = outs
        mlm_labels, nsp_labels = labels
        return ce(mlm_scores, mlm_labels).mean() + \
            ce(nsp_scores, nsp_labels).mean()

    mlm_labels = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
    nsp_labels = onp.random.randint(0, 2, (BATCH,)).astype("int32")
    dt, mfu = _train_bench(
        net, loss_fn, "adam", {"learning_rate": 1e-4}, tokens,
        (mlm_labels, nsp_labels), dtype="bfloat16")
    samples_s = BATCH / dt
    return _emit({
        "metric": "bert_base_train_bs64_seq128_bf16_amp",
        "value": round(samples_s, 2),
        "unit": "samples/s",
        "vs_baseline": round(mfu / 0.5, 3) if mfu else None,  # vs 50%-MFU target
        "mfu": round(mfu, 4) if mfu else None,
    })


def bench_lenet_eager():
    """Imperative (non-hybridized) LeNet training — the reference's eager
    LeNet/MNIST config. Exercises per-op dispatch + the eager jit cache
    (SURVEY §7 hard part 2); reports the cached rate and the uncached rate."""
    import numpy as onp

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.ops import registry

    BATCH = 64
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"), gluon.nn.Dense(10))
    net.initialize()
    x = mnp.array(onp.random.randn(BATCH, 1, 28, 28).astype("float32"))
    y = mnp.array(onp.random.randint(0, 10, (BATCH,)))
    with autograd.predict_mode():
        net(x)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})

    def step():
        with autograd.record():
            l = loss_fn(net(x), y).mean()
        l.backward()
        tr.step(1)
        return l

    rates = {}
    prev_enabled = registry._eager_jit_enabled
    try:
        for flag in (False, True):
            registry.set_eager_jit(flag)
            registry._EAGER_JIT_CACHE.clear()
            float(step().asnumpy())  # drain
            dt = _timed_diff(step, lambda l: float(l.asnumpy()), 2, 8)
            rates[flag] = BATCH / dt
    finally:
        registry.set_eager_jit(prev_enabled)
    return _emit({
        "metric": "lenet_eager_train_bs64",
        "value": round(rates[True], 2),
        "unit": "img/s",
        "vs_baseline": None,
        "uncached_img_s": round(rates[False], 2),
    })


def bench_bandwidth():
    """KVStore push/pull bandwidth (tools/bandwidth parity, perf.md:263)."""
    from mxnet_tpu.kvstore.dist_tpu import measure_pushpull_bandwidth

    gbs = measure_pushpull_bandwidth(size_mb=64, iters=10)
    return _emit({
        "metric": "kvstore_pushpull_bw_64mb",
        "value": round(gbs, 2),
        "unit": "GB/s",
        "vs_baseline": None,
    })


def main():
    rows = {}
    failures = {}
    for name, fn in [("infer", bench_resnet_infer),
                     ("infer_int8", bench_resnet_infer_int8),
                     ("bandwidth", bench_bandwidth),
                     ("lenet_eager", bench_lenet_eager),
                     ("bert", bench_bert_train),
                     ("resnet_train_bf16",
                      lambda: bench_resnet_train("bfloat16")),
                     ("resnet_train_fused", bench_resnet_train_fused)]:
        try:
            rows[name] = fn()
        except Exception as e:  # keep the suite alive; report what ran
            failures[name] = f"{type(e).__name__}: {e}"
            print(f"# bench {name} failed: {failures[name]}", file=sys.stderr)
    head = rows.get("resnet_train_fused") or rows.get("resnet_train_bf16") \
        or rows.get("bert") or rows.get("infer")
    if head is None:
        _emit({"metric": "bench_failed", "value": 0, "unit": "",
               "vs_baseline": 0, "errors": failures})
        return 1
    final = dict(head)
    final["extra"] = {k: v for k, v in rows.items()}
    if failures:
        final["errors"] = failures
    _emit(final)
    return 0


if __name__ == "__main__":
    sys.exit(main())
