"""Headline benchmark: ResNet-50 v1 inference throughput, batch 32.

Reference baseline (BASELINE.md, ``docs/.../perf.md:193``): 1,076.81 img/s
on a V100 (MXNet 1.2 + cuDNN, ``example/image-classification/
benchmark_score.py`` protocol: synthetic data, fp32, batch 32). Same
protocol here through the user-facing path: model-zoo net → ``hybridize()``
→ one XLA executable per signature, run on the TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import sys
import time

BASELINE_IMG_S = 1076.81  # V100 fp32 bs32, perf.md:193
BATCH = 32
SIZE = 224
WARMUP = 3
ITERS = 30


def main():
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp

    try:
        ctx = mx.tpu()
        ctx.jax_device()
    except Exception:
        ctx = mx.cpu()

    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(ctx=mx.cpu())
    # materialize deferred param shapes with one cheap eager CPU forward,
    # then move weights to the accelerator and compile there
    small = mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"), ctx=mx.cpu())
    with autograd.predict_mode():
        net(small)
    if ctx.device_type != "cpu":
        net.reset_ctx(ctx)
    net.hybridize(static_alloc=True)

    x = mnp.array(
        onp.random.uniform(-1, 1, (BATCH, 3, SIZE, SIZE)).astype("float32"),
        ctx=ctx)
    with autograd.predict_mode():
        for _ in range(WARMUP):
            out = net(x)
        out.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = net(x)
        out.wait_to_read()
        dt = time.perf_counter() - t0

    img_s = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_v1_infer_bs32_fp32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
