#!/usr/bin/env python
"""Validate (and repair) a RecordIO ``.idx`` against its ``.rec`` file.

A stale or hand-mangled index turns into silently-wrong training data, and
a torn ``.rec`` tail (partial last record after a crashed writer) makes the
sequential reader blow up mid-epoch. This tool scans the ``.rec`` framing
front to back — the ground truth — and compares it with the sidecar index:

    python tools/recordio_check.py data.rec            # validate
    python tools/recordio_check.py data.rec --repair   # rewrite .idx
    python tools/recordio_check.py data.rec --repair --crc   # + checksums

``--crc`` writes the extended three-column ``key\\tpos\\tcrc`` format
(crc32 of each record's payload); readers that know the column
(``MXIndexedRecordIO``, ``io.pipeline``) verify it on every read and
quarantine/refuse mismatching records.

Exit status: 0 — index matches (or was repaired); 1 — problems found and
not repaired; 2 — the ``.rec`` itself is unreadable.
"""
from __future__ import annotations

import argparse
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.base import MXNetError  # noqa: E402
from mxnet_tpu.recordio import (  # noqa: E402
    _LREC_MASK,
    _MAGIC,
    compute_crc,
    load_index,
)


def scan_rec(path):
    """Walk the ``.rec`` framing front to back. Returns
    ``(records, torn_at)``: ``records`` is ``[(pos, payload_bytes), ...]``
    for every complete record, ``torn_at`` the byte offset of a torn tail
    (``None`` when the file ends cleanly on a record boundary)."""
    size = os.path.getsize(path)
    records = []
    with open(path, "rb") as fh:
        pos = 0
        while pos < size:
            start = pos
            parts = []
            try:
                while True:  # one (possibly multi-part) record
                    head = fh.read(8)
                    if len(head) < 8:
                        raise MXNetError("truncated header")
                    magic, lrec = struct.unpack("<II", head)
                    if magic != _MAGIC:
                        raise MXNetError(f"bad magic {magic:#x}")
                    n = lrec & _LREC_MASK
                    cflag = lrec >> 29
                    data = fh.read(n)
                    if len(data) < n:
                        raise MXNetError("truncated payload")
                    pad = (4 - (n & 3)) & 3
                    if pad:
                        fh.read(pad)
                    parts.append(data)
                    if cflag in (0, 3):
                        break
            except MXNetError:
                return records, start
            records.append((start, b"".join(parts)))
            pos = fh.tell()
    return records, None


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="validate/repair a RecordIO .idx from its .rec")
    ap.add_argument("rec", help="path to the .rec file")
    ap.add_argument("--idx", default=None,
                    help="index path (default: <rec stem>.idx)")
    ap.add_argument("--repair", action="store_true",
                    help="rewrite the .idx from the .rec scan")
    ap.add_argument("--crc", action="store_true",
                    help="write per-record crc32 as a third index column")
    args = ap.parse_args(argv)

    rec = args.rec
    idx = args.idx or os.path.splitext(rec)[0] + ".idx"
    if not os.path.isfile(rec):
        print(f"recordio_check: {rec}: no such file", file=sys.stderr)
        return 2

    try:
        records, torn_at = scan_rec(rec)
    except OSError as e:
        print(f"recordio_check: {rec}: {e}", file=sys.stderr)
        return 2

    problems = []
    if torn_at is not None:
        problems.append(
            f"torn tail: framing breaks at offset {torn_at} "
            f"({len(records)} complete records before it)")

    existing = load_index(idx) if os.path.isfile(idx) else None
    if existing is None:
        problems.append(f"index {idx} is missing")
    else:
        if len(existing) != len(records):
            problems.append(
                f"entry count mismatch: index has {len(existing)}, "
                f".rec holds {len(records)} complete records")
        scanned = {pos: payload for pos, payload in records}
        for key, pos, crc in existing:
            payload = scanned.get(pos)
            if payload is None:
                problems.append(
                    f"key {key}: offset {pos} is not a record boundary")
                continue
            if crc is not None and compute_crc(payload) != crc:
                problems.append(
                    f"key {key}: crc mismatch at offset {pos} "
                    f"(index {crc:#010x}, payload "
                    f"{compute_crc(payload):#010x})")

    for p in problems:
        print(f"recordio_check: {rec}: {p}")

    if args.repair:
        # ground truth is the scan; keep the old keys when the counts
        # line up (labels often live in the key), else renumber 0..n-1
        keys = ([k for k, _, _ in existing]
                if existing is not None and len(existing) == len(records)
                else list(range(len(records))))
        with open(idx, "w") as fout:
            for key, (pos, payload) in zip(keys, records):
                if args.crc:
                    fout.write(f"{key}\t{pos}\t{compute_crc(payload)}\n")
                else:
                    fout.write(f"{key}\t{pos}\n")
        print(f"recordio_check: wrote {idx}: {len(records)} entries"
              + (" with crc32" if args.crc else ""))
        if torn_at is not None:
            print(f"recordio_check: NOTE: the torn tail at offset "
                  f"{torn_at} is still in {rec}; the repaired index "
                  "simply does not reference it")
        return 0

    if problems:
        print(f"recordio_check: {len(problems)} problem(s); "
              "re-run with --repair to rewrite the index")
        return 1
    print(f"recordio_check: {rec}: OK ({len(records)} records, "
          f"index verified{', crc' if existing and existing[0][2] is not None else ''})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
