#!/usr/bin/env python
"""Collective bucketing/overlap smoke (the ``TIER1_OVERLAP=1`` rung).

Drives a small dp4 MLP through the ``gluon.Trainer`` + ``dist_tpu``
allreduce path in four configurations and asserts the PR-15 contract:

1. **Bitwise parity** — bucketing (``MXNET_KVSTORE_BUCKET_MB``) with
   overlap on AND off must land on *bitwise identical* parameters vs the
   unbucketed baseline after the same seeded batches. The flat fusion
   buffer sums replicas in the same order per element as the per-param
   path, so any divergence is a packing/slice-back bug, not fp noise.
2. **Zero steady-state recompiles** — after a warmup window, further
   steps must trigger ZERO XLA backend compiles in every configuration
   (counted via the ``/jax/core/compile/backend_compile_duration``
   monitoring event). The bucket plan is deterministic and trace-static,
   so a recompile means bucket shapes churned.
3. **Priority settle order** — the store's flush log must show every
   bucket settling front-first (descending priority), the overlap
   scheduler's one observable promise.
4. **2-bit compression** (config 4) runs the same loop with
   ``MXNET_GRADIENT_COMPRESSION=2bit`` and asserts bounded divergence
   from the exact run (error feedback keeps it close, not bitwise) plus
   a nonzero ``compressed_bytes_saved`` counter.

Importable: ``bench.py``'s MULTICHIP ablation row calls
:func:`run_ablation` for the bucketing×overlap×compression step-time
grid. Exit status is nonzero on any violation (smoke-gate discipline,
like ``tools/elastic_soak.py``).

Usage::

    python tools/overlap_smoke.py            # full smoke
    python tools/overlap_smoke.py --steps 12
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DP = 4
DIM = 64
N_LAYERS = 4
BATCH = 8
WARM = 3

# env keys the configs toggle; saved/restored around every run so the
# smoke composes with whatever the caller's environment says
_KNOBS = ("MXNET_KVSTORE_BUCKET_MB", "MXNET_KVSTORE_OVERLAP",
          "MXNET_GRADIENT_COMPRESSION")

_compile_events = [0]
_listener_installed = [False]


def _install_compile_listener():
    if _listener_installed[0]:
        return
    from jax import monitoring

    def _on_duration(name, dur, **kw):  # pylint: disable=unused-argument
        if name == "/jax/core/compile/backend_compile_duration":
            _compile_events[0] += 1

    monitoring.register_event_duration_secs_listener(_on_duration)
    _listener_installed[0] = True


def _ctxs():
    from mxnet_tpu.device import Context

    return [Context("cpu", i) for i in range(DP)]


def _fresh(ctxs, seed):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync
    from mxnet_tpu.parallel import mesh as mesh_mod

    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.Sequential()
    for _ in range(N_LAYERS - 1):
        net.add(gluon.nn.Dense(DIM, in_units=DIM, activation="relu"))
    net.add(gluon.nn.Dense(1, in_units=DIM))
    net.initialize(ctx=ctxs)
    mesh = mesh_mod.make_mesh(
        {"dp": len(ctxs)}, devices=[c.jax_device() for c in ctxs])
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05},
                       kvstore=KVStoreDistTPUSync(mesh=mesh))
    return net, tr


def _train(net, tr, ctxs, steps, seed):
    """Seeded per-replica forward/backward/step loop; returns the final
    params, the mean steady-state step wall, and the number of backend
    compiles AFTER the warmup window."""
    from mxnet_tpu import autograd
    from mxnet_tpu import np as mnp
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.parameter import replica_context

    loss_fn = gloss.L2Loss()
    rng = np.random.RandomState(seed * 977 + 3)
    walls, compiles_after_warm = [], 0
    for step in range(steps):
        xs = [mnp.array(rng.randn(BATCH, DIM).astype("float32"))
              for _ in ctxs]
        ys = [mnp.array(rng.randn(BATCH, 1).astype("float32"))
              for _ in ctxs]
        if step == WARM:
            compiles_after_warm = _compile_events[0]
        t0 = time.perf_counter()
        losses = []
        for i, c in enumerate(ctxs):
            with replica_context(c):
                with autograd.record():
                    out = net(xs[i].as_in_context(c))
                    losses.append(loss_fn(out, ys[i].as_in_context(c))
                                  .mean())
        for l in losses:
            l.backward()
        tr.step(BATCH * len(ctxs))
        for p in tr._params:
            for d in p.list_data():
                d._data.block_until_ready()
        if step >= WARM:
            walls.append(time.perf_counter() - t0)
    recompiles = _compile_events[0] - compiles_after_warm
    params = {k: p.data().asnumpy().copy()
              for k, p in sorted(net.collect_params().items())}
    step_ms = float(np.mean(walls) * 1e3) if walls else 0.0
    return params, step_ms, recompiles


def run_config(bucket_mb, overlap, compression, steps=10, seed=0):
    """One grid point: returns ``(params, step_ms, recompiles, store)``."""
    _install_compile_listener()
    saved = {k: os.environ.get(k) for k in _KNOBS}
    try:
        if bucket_mb:
            # tiny target so the 4-layer MLP actually splits into
            # multiple buckets (every Dense pair is ~16-33 KB)
            os.environ["MXNET_KVSTORE_BUCKET_MB"] = str(bucket_mb)
        else:
            os.environ.pop("MXNET_KVSTORE_BUCKET_MB", None)
        os.environ["MXNET_KVSTORE_OVERLAP"] = "1" if overlap else "0"
        if compression:
            os.environ["MXNET_GRADIENT_COMPRESSION"] = compression
        else:
            os.environ.pop("MXNET_GRADIENT_COMPRESSION", None)
        ctxs = _ctxs()
        net, tr = _fresh(ctxs, seed)
        params, step_ms, recompiles = _train(net, tr, ctxs, steps, seed)
        return params, step_ms, recompiles, tr.kvstore
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_ablation(steps=10, seed=0, say=lambda m: None):
    """The bucketing×overlap×compression grid (bench.py MULTICHIP row).

    Returns ``(violations, rows)``: rows keyed ``base`` / ``bucket`` /
    ``bucket_overlap`` / ``bucket_overlap_2bit``, each carrying
    ``step_ms`` and ``recompiles`` (steady-state, must be 0), plus the
    parity outcome against ``base``.
    """
    violations = []
    grid = [
        ("base", dict(bucket_mb=0, overlap=False, compression=None)),
        ("bucket", dict(bucket_mb=0.02, overlap=False, compression=None)),
        ("bucket_overlap",
         dict(bucket_mb=0.02, overlap=True, compression=None)),
        ("bucket_overlap_2bit",
         dict(bucket_mb=0.02, overlap=True, compression="2bit")),
    ]
    rows, base_params = {}, None
    for name, cfg in grid:
        say(f"config {name}: {cfg}")
        params, step_ms, recompiles, kv = run_config(
            steps=steps, seed=seed, **cfg)
        row = {"step_ms": round(step_ms, 3), "recompiles": recompiles}
        if recompiles:
            violations.append(
                f"{name}: {recompiles} steady-state recompile(s) — the "
                "bucket plan must be trace-static")
        if name == "base":
            base_params = params
        elif cfg["compression"] is None:
            exact = all((base_params[k] == params[k]).all()
                        for k in base_params)
            row["parity"] = "bitwise" if exact else "DIVERGED"
            if not exact:
                worst = max(float(np.abs(base_params[k] - params[k]).max())
                            for k in base_params)
                violations.append(
                    f"{name}: parameters diverged from the unbucketed "
                    f"baseline (max |delta| {worst:.3e}) — bucketing "
                    "must be bitwise-neutral")
        else:
            worst = max(float(np.abs(base_params[k] - params[k]).max())
                        for k in base_params)
            row["parity"] = f"max|delta|={worst:.3e}"
            # error feedback keeps 2-bit near the exact trajectory on
            # this small problem; an unbounded gap means the residual
            # accounting broke (e.g. residual dropped between steps)
            if not np.isfinite(worst) or worst > 1.0:
                violations.append(
                    f"{name}: 2-bit divergence unbounded "
                    f"(max |delta| {worst:.3e})")
            saved_b = kv._stats.get("compressed_bytes_saved", 0)
            row["compressed_bytes_saved"] = int(saved_b)
            if saved_b <= 0:
                violations.append(
                    f"{name}: compression ran but saved 0 bytes — the "
                    "quantize path never fired")
        if name == "bucket_overlap":
            # flush log must show descending bucket priority per step
            log = [e for e in kv._flush_log if e[0].startswith("__zb")]
            if not log:
                violations.append(
                    "bucket_overlap: no bucket flushes logged")
            else:
                n_buckets = len({k for k, _ in log})
                for s in range(0, len(log) - n_buckets + 1, n_buckets):
                    prios = [p for _, p in log[s:s + n_buckets]]
                    if prios != sorted(prios, reverse=True):
                        violations.append(
                            f"bucket_overlap: flush order not front-first "
                            f"at step {s // n_buckets}: {prios}")
                        break
        rows[name] = row
    return violations, rows


def check_zero_lowering(zero_bucket_mb=0.05):
    """Lowering-inspection pin for ZeRO flat buckets: the bucketed
    tiny-llama fsdp8 step must lower to exactly ONE all-gather
    instruction per bucket, strictly fewer than the packed param count
    (the per-param floor the unbucketed layout pays). Returns a list of
    violation strings. Counted at the instruction level — a plain
    substring count also matches sharding metadata and overcounts ~30x.
    """
    import re

    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import Mesh, PartitionSpec as P

    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.parallel.functional import ShardedTrainer, ShardingRules

    devs = jax.devices()
    if len(devs) < 8:
        return [f"zero_lowering: needs 8 devices, have {len(devs)}"]
    mesh = Mesh(onp.array(devs[:8]).reshape(8), ("fsdp",))
    tr = ShardedTrainer(
        get_llama("llama_tiny_test", remat=True),
        lambda o, l: gloss.SoftmaxCrossEntropyLoss(sparse_label=True)(o, l),
        "adam", {"learning_rate": 1e-4}, mesh=mesh,
        rules=ShardingRules((), default_axis="fsdp"),
        batch_spec=P("fsdp"), abstract=True, zero_bucket_mb=zero_bucket_mb)
    compiled = tr.aot_lower(jax.ShapeDtypeStruct((8, 64), jnp.int32),
                            jax.ShapeDtypeStruct((8, 64), jnp.int32))
    gathers = len(re.findall(r"= \S+ all-gather(?:-start)?\(",
                             compiled.as_text()))
    specs = tr._zb_specs or ()
    n_buckets, n_params = len(specs), sum(len(s.names) for s in specs)
    out = []
    if n_buckets <= 1:
        out.append(f"zero_lowering: plan degenerate ({n_buckets} buckets)")
    if gathers != n_buckets:
        out.append(f"zero_lowering: {gathers} all-gather instructions for "
                   f"{n_buckets} buckets (want exactly one per bucket)")
    if n_buckets >= n_params:
        out.append(f"zero_lowering: {n_buckets} buckets did not collapse "
                   f"below the {n_params}-param per-param floor")
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    def say(msg):
        print(f"# overlap_smoke: {msg}", flush=True)

    t0 = time.perf_counter()
    violations, rows = run_ablation(steps=args.steps, seed=args.seed,
                                    say=say)
    for name, row in rows.items():
        say(f"{name}: {row}")
    zl = check_zero_lowering()
    violations.extend(zl)
    if not zl:
        say("zero_lowering: gathers == buckets < params (collapse holds)")
    say(f"wall {time.perf_counter() - t0:.1f}s")
    if violations:
        for v in violations:
            print(f"OVERLAP_SMOKE VIOLATION: {v}", file=sys.stderr)
        return 1
    print("OVERLAP_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
