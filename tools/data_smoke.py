#!/usr/bin/env python
"""Input-pipeline smoke (the ``TIER1_DATA=1`` rung).

Writes a synthetic ``.rec``/``.idx`` pair (extended 3-column index with
per-record crc32), then drives the sharded RecordIO pipeline through its
fault contract and the device-feed path:

1. **Exactly-once under faults** — two shard pipelines × 4 decode
   workers each stream the epoch under a seeded ``io:read`` plan
   (one transient error, one torn record, one worker kill). Asserts
   delivered ∪ quarantined == the full sample multiset with no
   duplicates, the killed worker's range was requeued and a replacement
   thread respawned, and the ``resilience.io_records_quarantined``
   counter matches.
2. **Determinism** — the same ``(seed, epoch)`` must yield an identical
   delivery order regardless of worker count (1 vs 4); a different seed
   must not.
3. **Resume / reshard** — cut after a few batches, ``merge_states``
   across both shards, restore onto ONE surviving shard; the survivor
   must finish exactly the remainder (sample-exact, no dupes).
4. **Zero recompiles through DeviceFeeder** — a tiny jitted step
   consumes double-buffered batches; after the first compile, further
   batches must trigger ZERO XLA backend compiles (counted via the
   ``/jax/core/compile/backend_compile_duration`` monitoring event) —
   the feeder must hand over stable shapes/dtypes.
5. **Export surface** — ``profiler.export.snapshot()`` must carry the
   ``io.<name>.*`` gauges for the live pipeline and feeder.

Re-run under ``MXNET_LOCKDEP=1`` by ``tools/run_tier1.sh``; the
``__main__`` block routes the exit status through ``lockdep.smoke_gate``
so a lock-order cycle in the worker pool fails the rung.

Usage::

    python tools/data_smoke.py
    python tools/data_smoke.py --records 96 --batch 4
"""
import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_compile_events = [0]
_listener_installed = [False]


def _install_compile_listener():
    if _listener_installed[0]:
        return
    from jax import monitoring

    def _on_duration(name, dur, **kw):  # pylint: disable=unused-argument
        if name == "/jax/core/compile/backend_compile_duration":
            _compile_events[0] += 1

    monitoring.register_event_duration_secs_listener(_on_duration)
    _listener_installed[0] = True


def _write_dataset(d, n_records):
    """Synthetic ``.rec`` with a crc-bearing 3-column ``.idx``; payload
    encodes the sample id so exactly-once is checkable by content."""
    from mxnet_tpu import recordio

    rec = os.path.join(d, "smoke.rec")
    idx = os.path.join(d, "smoke.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n_records):
        w.write_idx(i, b"sample-%05d|" % i + b"x" * (i % 17))
    w.close()
    # rewrite the index in the extended key\tpos\tcrc format so the CRC
    # verification path is exercised on every read
    import tools.recordio_check as rcheck

    rc = rcheck.main([rec, "--repair", "--crc"])
    if rc != 0:
        raise RuntimeError("recordio_check --repair --crc failed")
    return rec


def _sample_id(payload):
    return int(payload.split(b"|", 1)[0].split(b"-")[1])


def _drain(pipe):
    """Consume a pipeline to epoch end; returns the sample ids seen."""
    seen = []
    for batch in pipe:
        seen.extend(_sample_id(p) for p in batch)
    return seen


def leg_faults(rec, n_records, batch, say):
    """Exactly-once multiset under transient + torn + worker-kill."""
    from mxnet_tpu.io.pipeline import RecordPipeline
    from mxnet_tpu.resilience import counters as rescounters
    from mxnet_tpu.resilience import faults

    violations = []
    base = rescounters.snapshot().get(
        "resilience.io_records_quarantined", 0)
    faults.install_plan({"seed": 11, "rules": [
        {"site": "io:read", "kind": "transient", "at": [4]},
        {"site": "io:read", "kind": "torn", "at": [9]},
        {"site": "io:read", "kind": "die", "at": [17]},
    ]})
    try:
        pipes = [RecordPipeline([rec], batch_size=batch, shard_index=s,
                                num_shards=2, num_workers=4, shuffle=True,
                                seed=3, name=f"smoke-faults-s{s}")
                 for s in range(2)]
        seen = []
        for p in pipes:
            seen.extend(_drain(p))
        quarantined = sum(p.stats()["records_quarantined"] for p in pipes)
        respawns = sum(p.stats()["worker_respawns"] for p in pipes)
        for p in pipes:
            p.close()
    finally:
        faults.clear_plan()
    if len(seen) != len(set(seen)):
        violations.append(
            f"faults: duplicate samples delivered "
            f"({len(seen) - len(set(seen))} dupes)")
    if len(seen) + quarantined != n_records:
        violations.append(
            f"faults: delivered {len(seen)} + quarantined {quarantined} "
            f"!= {n_records} — samples went missing")
    if quarantined < 2:
        violations.append(
            f"faults: expected >=2 quarantined (transient + torn), "
            f"got {quarantined}")
    if respawns < 1:
        violations.append(
            "faults: worker kill produced no respawn")
    delta = rescounters.snapshot().get(
        "resilience.io_records_quarantined", 0) - base
    if delta != quarantined:
        violations.append(
            f"faults: resilience.io_records_quarantined moved {delta}, "
            f"pipeline stats say {quarantined}")
    say(f"faults: delivered {len(seen)} quarantined {quarantined} "
        f"respawns {respawns}")
    return violations


def leg_determinism(rec, batch, say):
    from mxnet_tpu.io.pipeline import RecordPipeline

    violations = []
    orders = {}
    for workers in (1, 4):
        p = RecordPipeline([rec], batch_size=batch, num_workers=workers,
                           shuffle=True, seed=5,
                           name=f"smoke-det-w{workers}")
        orders[workers] = _drain(p)
        p.close()
    if orders[1] != orders[4]:
        violations.append(
            "determinism: delivery order depends on worker count")
    p = RecordPipeline([rec], batch_size=batch, num_workers=4,
                       shuffle=True, seed=6, name="smoke-det-seed6")
    other = _drain(p)
    p.close()
    if other == orders[4]:
        violations.append("determinism: different seed, same order")
    say(f"determinism: order stable across 1/4 workers "
        f"({len(orders[4])} samples), seed-sensitive")
    return violations


def leg_reshard(rec, n_records, batch, say):
    """Cut 2 shards mid-epoch, merge, resume on 1 survivor."""
    from mxnet_tpu.io.pipeline import RecordPipeline

    violations = []
    pipes = [RecordPipeline([rec], batch_size=batch, shard_index=s,
                            num_shards=2, num_workers=2, shuffle=True,
                            seed=9, name=f"smoke-cut-s{s}")
             for s in range(2)]
    head = []
    for p in pipes:
        for _ in range(2):
            head.extend(_sample_id(x) for x in next(p))
    states = [p.state_dict() for p in pipes]
    for p in pipes:
        p.close()
    merged = RecordPipeline.merge_states(states)
    survivor = RecordPipeline([rec], batch_size=batch, shard_index=0,
                              num_shards=1, num_workers=2, shuffle=True,
                              seed=9, name="smoke-cut-survivor")
    survivor.load_state_dict(merged)
    tail = _drain(survivor)
    survivor.close()
    got = sorted(head + tail)
    if got != list(range(n_records)):
        dupes = len(got) - len(set(got))
        violations.append(
            f"reshard: head+tail multiset wrong ({len(got)} samples, "
            f"{dupes} dupes, want {n_records} exact)")
    say(f"reshard: 2->1 shards sample-exact "
        f"({len(head)} before cut + {len(tail)} after)")
    return violations


def leg_device_feed(rec, batch, say):
    """Double-buffered device feed into a jitted step: zero recompiles
    after the first compile, and input-stall attribution stays sane."""
    import jax
    import numpy as np

    from mxnet_tpu.io.pipeline import DeviceFeeder, RecordPipeline
    from mxnet_tpu.profiler import attribution

    _install_compile_listener()
    attribution.enable()  # so feeder stalls land in wait_ms[input]
    violations = []

    def decode(payload):
        sid = _sample_id(payload)
        return np.full((8,), sid, dtype=np.float32)

    def batchify(items):
        return np.stack(items)

    pipe = RecordPipeline([rec], batch_size=batch, num_workers=2,
                          decode_fn=decode, batchify_fn=batchify,
                          name="smoke-feed")
    feeder = DeviceFeeder(pipe, depth=2, name="smoke-feeder")

    @jax.jit
    def step(x):
        return (x * 2.0).sum()

    total = 0.0
    compiles_at_warm = None
    for i, x in enumerate(feeder):
        total += float(step(x))
        if i == 0:
            compiles_at_warm = _compile_events[0]
    recompiles = _compile_events[0] - compiles_at_warm
    if recompiles:
        violations.append(
            f"device_feed: {recompiles} recompile(s) after warmup — "
            "feeder batches changed shape/dtype")
    fstats = feeder.stats()
    if fstats["batches"] != len(pipe):
        violations.append(
            f"device_feed: feeder served {fstats['batches']} batches, "
            f"pipeline holds {len(pipe)}")

    # export surface: the live pipeline/feeder must be visible as io.*
    from mxnet_tpu.profiler import export

    snap = export.snapshot()
    for key in ("io.smoke-feed.batches_served",
                "io.smoke-feeder.batches",
                "attribution.wait_ms[input]"):
        if key not in snap:
            violations.append(f"device_feed: {key} missing from "
                              "export.snapshot()")
    attribution.disable()
    pipe.close()
    say(f"device_feed: {fstats['batches']} batches, sum {total:.0f}, "
        f"recompiles {recompiles}, stall_ms {fstats['stall_ms']}")
    return violations


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--records", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    def say(msg):
        print(f"# data_smoke: {msg}", flush=True)

    t0 = time.perf_counter()
    violations = []
    with tempfile.TemporaryDirectory(prefix="data_smoke.") as d:
        rec = _write_dataset(d, args.records)
        say(f"dataset: {args.records} records, crc index")
        violations += leg_faults(rec, args.records, args.batch, say)
        violations += leg_determinism(rec, args.batch, say)
        violations += leg_reshard(rec, args.records, args.batch, say)
        violations += leg_device_feed(rec, args.batch, say)
    say(f"wall {time.perf_counter() - t0:.1f}s")
    if violations:
        for v in violations:
            print(f"DATA_SMOKE VIOLATION: {v}", file=sys.stderr)
        return 1
    print("DATA_SMOKE_OK")
    return 0


if __name__ == "__main__":
    rc = main()
    try:
        from mxnet_tpu.resilience.lockdep import smoke_gate
    except ImportError:
        pass
    else:
        rc = smoke_gate(rc)
    sys.exit(rc)
