#!/usr/bin/env python
"""Chaos soak for the serving stack: concurrent mixed-priority traffic
through a seeded fault plan, asserting the overload-safety invariants.

What it drives
--------------
An ``InferenceSession`` behind a ``DynamicBatcher`` takes sustained
two-class traffic (interactive with deadlines, batch flooding well past
capacity) while a seeded ``FaultPlan`` injects admission failures
(``serve:queue``), execution failures and hangs (``serve:execute``), and
dispatch faults (``op:dispatch``). An optional decode leg pushes a tiny
llama ``Generator`` through ``serve:decode`` faults with per-row
deadlines.

Invariants asserted (exit 0 = all hold; nonzero prints the violation):

1. **Exactly-once settle** — every admitted future is done when the soak
   ends; client accounting sees exactly one outcome per request (no
   leaks, no double-settle, no deadlock).
2. **No silent late completions** — no delivered result lands past its
   request's deadline + grace (measured client-side at completion).
3. **Outcome taxonomy is closed** — every settle is ok / 503 shed-or-
   reject / 504 deadline / an injected fault error; anything else fails
   the soak.
4. **Priority isolation** — pressure/rate/share sheds land ONLY on the
   batch class, and interactive p99 stays under
   ``--p99-factor`` x the uncontended interactive p99 (measured first,
   same session, no faults, no batch flood).
5. **Clean drain** — ``drain()`` returns True with an empty queue and no
   in-flight batch; a post-drain ``swap()`` to a same-signature model is
   warm (``assert_no_recompiles`` still passes); ``close()`` joins the
   flusher.

Usage::

    python tools/chaos_soak.py                  # ~15s tier-1 smoke
    python tools/chaos_soak.py --duration 60 --clients 128   # full soak
    python tools/chaos_soak.py --no-decode      # skip the Generator leg

The run is deterministic per ``--seed`` up to thread scheduling: the
fault plan's prob-rules draw from the seed, so the same faults fire at
the same per-site hit indices.
"""
import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentile(values, pct):
    from mxnet_tpu.serve import percentile

    return percentile(values, pct)


def _build_session(name="chaos"):
    from mxnet_tpu import gluon
    from mxnet_tpu.serve import InferenceSession

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize()
    sess = InferenceSession(net, batch_buckets=(1, 2, 4, 8), name=name)
    sess.warmup(np.zeros((1, 16), np.float32))
    return net, sess


def _uncontended_p99(batcher, n=48, deadline_ms=2000.0):
    """Interactive-only baseline p99 (ms), measured client-side through
    the same batcher — the denominator of the overload SLO bound."""
    lat = []
    x = np.zeros(16, np.float32)
    for _ in range(n):
        t0 = time.monotonic()
        batcher.submit(x, priority="interactive",
                       deadline_ms=deadline_ms).result(timeout=30)
        lat.append((time.monotonic() - t0) * 1e3)
    return _percentile(lat, 99)


class _ClientStats:
    """Per-request client-side accounting shared by the soak threads."""

    #: scheduling slack for the client-side late check: the batcher's own
    #: settle boundary is exact (anything past deadline + grace settles
    #: as 504), but a client thread waking from ``Future.result`` under a
    #: contended GIL observes the delivery some scheduler quanta later —
    #: without slack the check measures the OS, not the server.
    SCHED_SLACK_S = 0.2

    def __init__(self):
        self.lock = threading.Lock()
        self.outcomes = {"ok": 0, "shed_503": 0, "deadline_504": 0,
                         "injected": 0, "unexpected": 0}
        self.unexpected = []          # (priority, repr(exc))
        self.late_completions = 0     # delivered past deadline + grace
        self.interactive_lat = []     # ms, successful interactive only
        self.settled = 0
        self.admitted = 0

    def record(self, priority, t0, deadline, grace_s, outcome, exc=None,
               lat_ms=None):
        with self.lock:
            self.settled += 1
            self.outcomes[outcome] += 1
            if outcome == "unexpected":
                self.unexpected.append((priority, repr(exc)))
            if outcome == "ok":
                done = time.monotonic()
                if deadline is not None \
                        and done > deadline + grace_s + self.SCHED_SLACK_S:
                    self.late_completions += 1
                if priority == "interactive" and lat_ms is not None:
                    self.interactive_lat.append(lat_ms)


def run_soak(duration_s=10.0, clients=64, seed=7, p99_factor=3.0,
             p99_floor_ms=250.0, decode=True, grace_ms=50.0,
             interactive_deadline_ms=3000.0, batch_deadline_ms=120.0,
             verbose=True):
    """Run the chaos soak; returns a report dict with ``ok`` (bool),
    ``violations`` (list of strings), and the raw numbers. Importable —
    ``tests/test_serve_chaos.py`` runs the same machinery."""
    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.faults import (InjectedFaultError,
                                             TransientFaultError)
    from mxnet_tpu.serve import (DeadlineExceeded, DynamicBatcher,
                                 ServiceUnavailable)

    def say(msg):
        if verbose:
            print(f"CHAOS_SOAK {msg}", flush=True)

    violations = []
    grace_s = grace_ms / 1e3
    net, sess = _build_session()

    def runner(payloads):
        out = sess.predict(np.stack(payloads)).asnumpy()
        return [out[i] for i in range(len(payloads))]

    batcher = DynamicBatcher(runner, max_batch_size=8, timeout_ms=3.0,
                             max_queue=32, metrics=sess.metrics,
                             name="chaos")
    # batch-class pressure valve: cap its queue share + rate-limit it so
    # the flood sheds instead of starving interactive traffic
    batcher.batch_queue_cap = 16
    batcher.rate_limiter.rate = 400.0
    batcher.rate_limiter.burst = 32.0
    batcher.deadline_grace_s = grace_s

    say("measuring uncontended interactive p99 (no faults, no flood)")
    base_p99 = _uncontended_p99(batcher)
    say(f"uncontended interactive p99 = {base_p99:.1f}ms")

    plan = faults.install_plan({"seed": int(seed), "rules": [
        {"site": "serve:queue", "kind": "transient", "prob": 0.02},
        {"site": "serve:execute", "kind": "transient", "prob": 0.02},
        {"site": "serve:execute", "kind": "fatal", "prob": 0.005},
        # slow executions back the queue up so request deadlines really
        # expire at the queue and settle boundaries
        {"site": "serve:execute", "kind": "delay", "seconds": 0.15,
         "prob": 0.01},
        {"site": "op:dispatch", "kind": "transient", "prob": 0.002},
    ]})

    stats = _ClientStats()
    stop_at = time.monotonic() + float(duration_s)
    n_interactive = max(2, clients // 4)
    n_batch = clients - n_interactive
    x = np.zeros(16, np.float32)
    barrier = threading.Barrier(clients)

    def classify(exc):
        if isinstance(exc, DeadlineExceeded):
            return "deadline_504"
        if isinstance(exc, ServiceUnavailable):
            return "shed_503"
        if isinstance(exc, (TransientFaultError, InjectedFaultError)):
            return "injected"
        return "unexpected"

    def client(priority, deadline_ms, pause_s):
        barrier.wait(timeout=30)
        while time.monotonic() < stop_at:
            t0 = time.monotonic()
            deadline = t0 + deadline_ms / 1e3
            try:
                fut = batcher.submit(x, priority=priority,
                                     deadline_ms=deadline_ms)
            except Exception as exc:  # noqa: BLE001 — sync rejects
                stats.record(priority, t0, deadline, grace_s,
                             classify(exc), exc)
                # a real client backs off on a 503 — a pure spin on the
                # admission path measures GIL contention, not serving
                time.sleep(max(pause_s, 0.003))
                continue
            with stats.lock:
                stats.admitted += 1
            try:
                fut.result(timeout=60)
                lat = (time.monotonic() - t0) * 1e3
                stats.record(priority, t0, deadline, grace_s, "ok",
                             lat_ms=lat)
            except Exception as exc:  # noqa: BLE001
                stats.record(priority, t0, deadline, grace_s,
                             classify(exc), exc)
            time.sleep(pause_s)

    threads = [threading.Thread(
        target=client, args=("interactive", interactive_deadline_ms, 0.01),
        daemon=True, name=f"chaos-hi-{i}") for i in range(n_interactive)]
    threads += [threading.Thread(
        target=client, args=("batch", batch_deadline_ms, 0.001),
        daemon=True, name=f"chaos-lo-{i}") for i in range(n_batch)]
    say(f"soaking: {n_interactive} interactive + {n_batch} batch clients "
        f"for {duration_s:.0f}s under seeded fault plan (seed={seed})")
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 90)
        if t.is_alive():
            violations.append(f"client thread {t.name} wedged (deadlock?)")

    # -- drain + swap + shutdown --------------------------------------------
    faults.clear_plan()
    drained = batcher.drain(timeout=30.0)
    qd = batcher.queue_depth()
    if not drained or qd != 0:
        violations.append(
            f"drain() failed: drained={drained} queue_depth={qd}")
    batcher.resume()

    from mxnet_tpu import gluon

    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(32, activation="relu"))
    net2.add(gluon.nn.Dense(8))
    net2.initialize()
    swap_mode = sess.swap(net2, example=np.zeros((1, 16), np.float32))
    if swap_mode != "warm":
        violations.append(
            f"same-signature swap took the {swap_mode!r} path, not warm")
    try:
        batcher.submit(x, priority="interactive").result(timeout=30)
        sess.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        violations.append(f"post-swap serving violated zero-recompile: "
                          f"{type(exc).__name__}: {exc}")
    batcher.close()
    if batcher._thread.is_alive():
        violations.append("flusher thread survived close()")

    # -- invariants ----------------------------------------------------------
    snap = sess.metrics.snapshot()
    total_seen = sum(stats.outcomes.values())
    if stats.unexpected:
        violations.append(
            f"{len(stats.unexpected)} unexpected outcome(s), e.g. "
            f"{stats.unexpected[:3]}")
    if stats.late_completions:
        violations.append(
            f"{stats.late_completions} silent late completion(s) past "
            f"deadline + {grace_ms:.0f}ms grace")
    # exactly-once: every recorded settle is one future outcome; a leak
    # would have wedged a client thread on fut.result (caught above), a
    # double-settle is structurally impossible through Future + the
    # guarded _settle_future (asserted here via the books balancing)
    if stats.settled != total_seen:
        violations.append(
            f"settle books don't balance: {stats.settled} settles vs "
            f"{total_seen} outcomes")
    sheds = snap["sheds"]
    if any(k != "batch" for k in sheds):
        violations.append(f"sheds landed outside the batch class: {sheds}")
    if stats.outcomes["ok"] == 0:
        violations.append("zero successful requests — soak served nothing")
    hi_p99 = _percentile(stats.interactive_lat, 99)
    bound = max(p99_factor * base_p99, p99_floor_ms)
    if hi_p99 > bound:
        violations.append(
            f"interactive p99 {hi_p99:.1f}ms exceeds bound {bound:.1f}ms "
            f"({p99_factor}x uncontended {base_p99:.1f}ms)")

    # -- decode leg: serve:decode faults + mid-decode deadline retirement ---
    decode_report = None
    if decode:
        decode_report = _decode_leg(seed, violations, say)

    report = {
        "ok": not violations,
        "violations": violations,
        "outcomes": dict(stats.outcomes),
        "admitted": stats.admitted,
        "uncontended_p99_ms": base_p99,
        "interactive_p99_ms": hi_p99,
        "p99_bound_ms": bound,
        "sheds": dict(sheds),
        "deadline_expired": dict(snap["deadline_expired"]),
        "goodput": snap["goodput"],
        "late_completions_client": stats.late_completions,
        "faults_fired": plan.fired_total(),
        "swap_mode": swap_mode,
        "decode": decode_report,
    }
    say(f"outcomes={report['outcomes']} sheds={report['sheds']} "
        f"deadline_expired={report['deadline_expired']} "
        f"faults_fired={report['faults_fired']} "
        f"interactive_p99={hi_p99:.1f}ms (bound {bound:.1f}ms)")
    return report


def _decode_leg(seed, violations, say):
    """Generator under serve:decode faults + per-row deadlines: a stream
    killed mid-decode is a clean error, an expired row retires with its
    partial output, and the session survives both."""
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.serve import Generator

    say("decode leg: serve:decode faults + mid-decode deadlines")
    net = get_llama("llama_tiny_test")
    net.initialize()
    gen = Generator(net, max_seq=32, batch_buckets=(1, 2),
                    prompt_buckets=(8,), name="chaos_decode")
    gen.warmup()
    report = {"faulted": 0, "expired_rows": 0, "ok": 0}
    faults.install_plan({"seed": int(seed) + 1, "rules": [
        {"site": "serve:decode", "kind": "transient", "prob": 0.1},
    ]})
    try:
        for i in range(8):
            try:
                outs, info = gen.generate([[3, 5, 7], [9, 2]],
                                          max_new_tokens=6)
                report["ok"] += 1
            except Exception:  # noqa: BLE001 — injected decode kill
                report["faulted"] += 1
    finally:
        faults.clear_plan()
    if report["faulted"] == 0:
        violations.append("decode leg: no serve:decode fault ever fired")
    # deadline retirement: row 0 gets an already-tight budget, row 1 none
    t_now = time.monotonic()
    outs, info = gen.generate([[3, 5, 7], [9, 2]], max_new_tokens=6,
                              deadlines=[t_now, t_now + 60.0])
    report["expired_rows"] = len(info["deadline_expired"])
    if info["deadline_expired"] != [0]:
        violations.append(
            f"decode leg: expected row 0 to expire, got "
            f"{info['deadline_expired']}")
    if len(outs[1]) != 6:
        violations.append(
            f"decode leg: live row got {len(outs[1])}/6 tokens")
    try:
        gen.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        violations.append(f"decode leg recompiled: {exc}")
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--duration", type=float, default=10.0,
                    help="soak seconds (default 10; full soak: 60+)")
    ap.add_argument("--clients", type=int, default=64,
                    help="concurrent client threads (>= 64 = acceptance)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--p99-factor", type=float, default=3.0,
                    help="interactive p99 bound as a multiple of the "
                         "uncontended p99")
    ap.add_argument("--p99-floor-ms", type=float, default=250.0,
                    help="absolute floor for the p99 bound (CI jitter)")
    ap.add_argument("--no-decode", action="store_true",
                    help="skip the Generator/serve:decode leg")
    args = ap.parse_args(argv)

    report = run_soak(duration_s=args.duration, clients=args.clients,
                      seed=args.seed, p99_factor=args.p99_factor,
                      p99_floor_ms=args.p99_floor_ms,
                      decode=not args.no_decode)
    if report["ok"]:
        print(f"CHAOS_SOAK=PASS outcomes={report['outcomes']} "
              f"faults_fired={report['faults_fired']} "
              f"p99={report['interactive_p99_ms']:.1f}ms "
              f"swap={report['swap_mode']}")
        return 0
    for v in report["violations"]:
        print(f"CHAOS_SOAK=FAIL {v}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
