#!/usr/bin/env python
"""Chaos soak for the serving stack: concurrent mixed-priority traffic
through a seeded fault plan, asserting the overload-safety invariants.

What it drives
--------------
An ``InferenceSession`` behind a ``DynamicBatcher`` takes sustained
two-class traffic (interactive with deadlines, batch flooding well past
capacity) while a seeded ``FaultPlan`` injects admission failures
(``serve:queue``), execution failures and hangs (``serve:execute``), and
dispatch faults (``op:dispatch``). An optional decode leg pushes a tiny
llama ``Generator`` through ``serve:decode`` faults with per-row
deadlines.

Invariants asserted (exit 0 = all hold; nonzero prints the violation):

1. **Exactly-once settle** — every admitted future is done when the soak
   ends; client accounting sees exactly one outcome per request (no
   leaks, no double-settle, no deadlock).
2. **No silent late completions** — no delivered result lands past its
   request's deadline + grace (measured client-side at completion).
3. **Outcome taxonomy is closed** — every settle is ok / 503 shed-or-
   reject / 504 deadline / an injected fault error; anything else fails
   the soak.
4. **Priority isolation** — pressure/rate/share sheds land ONLY on the
   batch class, and interactive p99 stays under
   ``--p99-factor`` x the uncontended interactive p99 (measured first,
   same session, no faults, no batch flood).
5. **Clean drain** — ``drain()`` returns True with an empty queue and no
   in-flight batch; a post-drain ``swap()`` to a same-signature model is
   warm (``assert_no_recompiles`` still passes); ``close()`` joins the
   flusher.

Usage::

    python tools/chaos_soak.py                  # ~15s tier-1 smoke
    python tools/chaos_soak.py --duration 60 --clients 128   # full soak
    python tools/chaos_soak.py --no-decode      # skip the Generator leg
    python tools/chaos_soak.py --fleet          # Router over N replicas
    python tools/chaos_soak.py --cb             # continuous batching

The run is deterministic per ``--seed`` up to thread scheduling: the
fault plan's prob-rules draw from the seed, so the same faults fire at
the same per-site hit indices.
"""
import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentile(values, pct):
    from mxnet_tpu.serve import percentile

    return percentile(values, pct)


def _build_session(name="chaos"):
    from mxnet_tpu import gluon
    from mxnet_tpu.serve import InferenceSession

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize()
    sess = InferenceSession(net, batch_buckets=(1, 2, 4, 8), name=name)
    sess.warmup(np.zeros((1, 16), np.float32))
    return net, sess


def _uncontended_p99(batcher, n=48, deadline_ms=2000.0):
    """Interactive-only baseline p99 (ms), measured client-side through
    the same batcher — the denominator of the overload SLO bound."""
    lat = []
    x = np.zeros(16, np.float32)
    for _ in range(n):
        t0 = time.monotonic()
        batcher.submit(x, priority="interactive",
                       deadline_ms=deadline_ms).result(timeout=30)
        lat.append((time.monotonic() - t0) * 1e3)
    return _percentile(lat, 99)


class _ClientStats:
    """Per-request client-side accounting shared by the soak threads."""

    #: scheduling slack for the client-side late check: the batcher's own
    #: settle boundary is exact (anything past deadline + grace settles
    #: as 504), but a client thread waking from ``Future.result`` under a
    #: contended GIL observes the delivery some scheduler quanta later —
    #: without slack the check measures the OS, not the server.
    SCHED_SLACK_S = 0.2

    def __init__(self):
        self.lock = threading.Lock()
        self.outcomes = {"ok": 0, "shed_503": 0, "deadline_504": 0,
                         "injected": 0, "unexpected": 0}
        self.unexpected = []          # (priority, repr(exc))
        self.late_completions = 0     # delivered past deadline + grace
        self.interactive_lat = []     # ms, successful interactive only
        self.settled = 0
        self.admitted = 0

    def record(self, priority, t0, deadline, grace_s, outcome, exc=None,
               lat_ms=None):
        with self.lock:
            self.settled += 1
            self.outcomes[outcome] += 1
            if outcome == "unexpected":
                self.unexpected.append((priority, repr(exc)))
            if outcome == "ok":
                done = time.monotonic()
                if deadline is not None \
                        and done > deadline + grace_s + self.SCHED_SLACK_S:
                    self.late_completions += 1
                if priority == "interactive" and lat_ms is not None:
                    self.interactive_lat.append(lat_ms)


def run_soak(duration_s=10.0, clients=64, seed=7, p99_factor=3.0,
             p99_floor_ms=250.0, decode=True, grace_ms=50.0,
             interactive_deadline_ms=3000.0, batch_deadline_ms=120.0,
             verbose=True):
    """Run the chaos soak; returns a report dict with ``ok`` (bool),
    ``violations`` (list of strings), and the raw numbers. Importable —
    ``tests/test_serve_chaos.py`` runs the same machinery."""
    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.faults import (InjectedFaultError,
                                             TransientFaultError)
    from mxnet_tpu.serve import (DeadlineExceeded, DynamicBatcher,
                                 ServiceUnavailable)

    def say(msg):
        if verbose:
            print(f"CHAOS_SOAK {msg}", flush=True)

    violations = []
    grace_s = grace_ms / 1e3
    net, sess = _build_session()

    def runner(payloads):
        out = sess.predict(np.stack(payloads)).asnumpy()
        return [out[i] for i in range(len(payloads))]

    batcher = DynamicBatcher(runner, max_batch_size=8, timeout_ms=3.0,
                             max_queue=32, metrics=sess.metrics,
                             name="chaos")
    # batch-class pressure valve: cap its queue share + rate-limit it so
    # the flood sheds instead of starving interactive traffic
    batcher.batch_queue_cap = 16
    batcher.rate_limiter.rate = 400.0
    batcher.rate_limiter.burst = 32.0
    batcher.deadline_grace_s = grace_s

    say("measuring uncontended interactive p99 (no faults, no flood)")
    base_p99 = _uncontended_p99(batcher)
    say(f"uncontended interactive p99 = {base_p99:.1f}ms")

    plan = faults.install_plan({"seed": int(seed), "rules": [
        {"site": "serve:queue", "kind": "transient", "prob": 0.02},
        {"site": "serve:execute", "kind": "transient", "prob": 0.02},
        {"site": "serve:execute", "kind": "fatal", "prob": 0.005},
        # slow executions back the queue up so request deadlines really
        # expire at the queue and settle boundaries
        {"site": "serve:execute", "kind": "delay", "seconds": 0.15,
         "prob": 0.01},
        {"site": "op:dispatch", "kind": "transient", "prob": 0.002},
    ]})

    stats = _ClientStats()
    stop_at = time.monotonic() + float(duration_s)
    n_interactive = max(2, clients // 4)
    n_batch = clients - n_interactive
    x = np.zeros(16, np.float32)
    barrier = threading.Barrier(clients)

    def classify(exc):
        if isinstance(exc, DeadlineExceeded):
            return "deadline_504"
        if isinstance(exc, ServiceUnavailable):
            return "shed_503"
        if isinstance(exc, (TransientFaultError, InjectedFaultError)):
            return "injected"
        return "unexpected"

    def client(priority, deadline_ms, pause_s):
        barrier.wait(timeout=30)
        while time.monotonic() < stop_at:
            t0 = time.monotonic()
            deadline = t0 + deadline_ms / 1e3
            try:
                fut = batcher.submit(x, priority=priority,
                                     deadline_ms=deadline_ms)
            except Exception as exc:  # noqa: BLE001 — sync rejects
                stats.record(priority, t0, deadline, grace_s,
                             classify(exc), exc)
                # a real client backs off on a 503 — a pure spin on the
                # admission path measures GIL contention, not serving
                time.sleep(max(pause_s, 0.003))
                continue
            with stats.lock:
                stats.admitted += 1
            try:
                fut.result(timeout=60)
                lat = (time.monotonic() - t0) * 1e3
                stats.record(priority, t0, deadline, grace_s, "ok",
                             lat_ms=lat)
            except Exception as exc:  # noqa: BLE001
                stats.record(priority, t0, deadline, grace_s,
                             classify(exc), exc)
            time.sleep(pause_s)

    threads = [threading.Thread(
        target=client, args=("interactive", interactive_deadline_ms, 0.01),
        daemon=True, name=f"chaos-hi-{i}") for i in range(n_interactive)]
    threads += [threading.Thread(
        target=client, args=("batch", batch_deadline_ms, 0.001),
        daemon=True, name=f"chaos-lo-{i}") for i in range(n_batch)]
    say(f"soaking: {n_interactive} interactive + {n_batch} batch clients "
        f"for {duration_s:.0f}s under seeded fault plan (seed={seed})")
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 90)
        if t.is_alive():
            violations.append(f"client thread {t.name} wedged (deadlock?)")

    # -- drain + swap + shutdown --------------------------------------------
    faults.clear_plan()
    drained = batcher.drain(timeout=30.0)
    qd = batcher.queue_depth()
    if not drained or qd != 0:
        violations.append(
            f"drain() failed: drained={drained} queue_depth={qd}")
    batcher.resume()

    from mxnet_tpu import gluon

    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(32, activation="relu"))
    net2.add(gluon.nn.Dense(8))
    net2.initialize()
    swap_mode = sess.swap(net2, example=np.zeros((1, 16), np.float32))
    if swap_mode != "warm":
        violations.append(
            f"same-signature swap took the {swap_mode!r} path, not warm")
    try:
        batcher.submit(x, priority="interactive").result(timeout=30)
        sess.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        violations.append(f"post-swap serving violated zero-recompile: "
                          f"{type(exc).__name__}: {exc}")
    batcher.close()
    if batcher._thread.is_alive():
        violations.append("flusher thread survived close()")

    # -- invariants ----------------------------------------------------------
    snap = sess.metrics.snapshot()
    total_seen = sum(stats.outcomes.values())
    if stats.unexpected:
        violations.append(
            f"{len(stats.unexpected)} unexpected outcome(s), e.g. "
            f"{stats.unexpected[:3]}")
    if stats.late_completions:
        violations.append(
            f"{stats.late_completions} silent late completion(s) past "
            f"deadline + {grace_ms:.0f}ms grace")
    # exactly-once: every recorded settle is one future outcome; a leak
    # would have wedged a client thread on fut.result (caught above), a
    # double-settle is structurally impossible through Future + the
    # guarded _settle_future (asserted here via the books balancing)
    if stats.settled != total_seen:
        violations.append(
            f"settle books don't balance: {stats.settled} settles vs "
            f"{total_seen} outcomes")
    sheds = snap["sheds"]
    if any(k != "batch" for k in sheds):
        violations.append(f"sheds landed outside the batch class: {sheds}")
    if stats.outcomes["ok"] == 0:
        violations.append("zero successful requests — soak served nothing")
    hi_p99 = _percentile(stats.interactive_lat, 99)
    bound = max(p99_factor * base_p99, p99_floor_ms)
    if hi_p99 > bound:
        violations.append(
            f"interactive p99 {hi_p99:.1f}ms exceeds bound {bound:.1f}ms "
            f"({p99_factor}x uncontended {base_p99:.1f}ms)")

    # -- decode leg: serve:decode faults + mid-decode deadline retirement ---
    decode_report = None
    if decode:
        decode_report = _decode_leg(seed, violations, say)

    report = {
        "ok": not violations,
        "violations": violations,
        "outcomes": dict(stats.outcomes),
        "admitted": stats.admitted,
        "uncontended_p99_ms": base_p99,
        "interactive_p99_ms": hi_p99,
        "p99_bound_ms": bound,
        "sheds": dict(sheds),
        "deadline_expired": dict(snap["deadline_expired"]),
        "goodput": snap["goodput"],
        "late_completions_client": stats.late_completions,
        "faults_fired": plan.fired_total(),
        "swap_mode": swap_mode,
        "decode": decode_report,
    }
    say(f"outcomes={report['outcomes']} sheds={report['sheds']} "
        f"deadline_expired={report['deadline_expired']} "
        f"faults_fired={report['faults_fired']} "
        f"interactive_p99={hi_p99:.1f}ms (bound {bound:.1f}ms)")
    return report


def _decode_leg(seed, violations, say):
    """Generator under serve:decode faults + per-row deadlines: a stream
    killed mid-decode is a clean error, an expired row retires with its
    partial output, and the session survives both."""
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.serve import Generator

    say("decode leg: serve:decode faults + mid-decode deadlines")
    net = get_llama("llama_tiny_test")
    net.initialize()
    gen = Generator(net, max_seq=32, batch_buckets=(1, 2),
                    prompt_buckets=(8,), name="chaos_decode")
    gen.warmup()
    report = {"faulted": 0, "expired_rows": 0, "ok": 0}
    faults.install_plan({"seed": int(seed) + 1, "rules": [
        {"site": "serve:decode", "kind": "transient", "prob": 0.1},
    ]})
    try:
        for i in range(8):
            try:
                outs, info = gen.generate([[3, 5, 7], [9, 2]],
                                          max_new_tokens=6)
                report["ok"] += 1
            except Exception:  # noqa: BLE001 — injected decode kill
                report["faulted"] += 1
    finally:
        faults.clear_plan()
    if report["faulted"] == 0:
        violations.append("decode leg: no serve:decode fault ever fired")
    # deadline retirement: row 0 gets an already-tight budget, row 1 none
    t_now = time.monotonic()
    outs, info = gen.generate([[3, 5, 7], [9, 2]], max_new_tokens=6,
                              deadlines=[t_now, t_now + 60.0])
    report["expired_rows"] = len(info["deadline_expired"])
    if info["deadline_expired"] != [0]:
        violations.append(
            f"decode leg: expected row 0 to expire, got "
            f"{info['deadline_expired']}")
    if len(outs[1]) != 6:
        violations.append(
            f"decode leg: live row got {len(outs[1])}/6 tokens")
    try:
        gen.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        violations.append(f"decode leg recompiled: {exc}")
    return report


def _build_fleet_replica(index, name_prefix="fleet"):
    """One fleet replica: its own tiny Dense session behind its own
    batcher (independent flusher thread + metrics window)."""
    from mxnet_tpu.serve import InferenceSession
    from mxnet_tpu.serve.replica import Replica

    net, _ = _fleet_net()
    sess = InferenceSession(net, batch_buckets=(1, 2, 4),
                            name=f"{name_prefix}_r{index}")
    sess.warmup(np.zeros((1, 16), np.float32))

    def runner(payloads):
        out = sess.predict(np.stack(payloads)).asnumpy()
        return [out[i] for i in range(len(payloads))]

    rep = Replica(runner, index=index, session=sess, max_batch_size=4,
                  timeout_ms=3.0, max_queue=32,
                  name=f"{name_prefix}_r{index}")
    # the same pressure valves run_soak uses, per replica
    rep.batcher.batch_queue_cap = 16
    rep.batcher.rate_limiter.rate = 400.0
    rep.batcher.rate_limiter.burst = 32.0
    return rep


def _fleet_net():
    from mxnet_tpu import gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize()
    return net, 16


def run_fleet_soak(duration_s=10.0, clients=64, replicas=3, seed=11,
                   p99_factor=4.0, p99_floor_ms=600.0, grace_ms=50.0,
                   interactive_deadline_ms=4000.0, batch_deadline_ms=150.0,
                   verbose=True):
    """Fleet-level chaos soak: 64+ mixed-priority clients over a Router
    of N replicas, a seeded FaultPlan on the dispatch/admission/execute
    sites, and one deterministic replica kill mid-traffic. Asserts:

    1. exactly-once settlement FLEET-WIDE — client books balance; the
       killed replica's in-flight work is requeued to survivors, its
       dying settles are fenced, and no request is delivered twice;
    2. the outcome taxonomy stays closed (ok / 503 / 504 / injected);
    3. sheds land only on the batch class on every replica;
    4. interactive p99 stays bounded vs the uncontended fleet baseline;
    5. the fleet recovers: the survivors keep serving after the kill,
       a zero-downtime rollout (all-warm swaps, zero recompiles, zero
       dropped requests) succeeds, and scale up/down drains gracefully.

    Importable — ``tests/test_fleet.py`` sweeps it over seeds."""
    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.faults import (InjectedFaultError,
                                             TransientFaultError)
    from mxnet_tpu.serve import DeadlineExceeded, ServiceUnavailable
    from mxnet_tpu.serve.fleet import Router

    def say(msg):
        if verbose:
            print(f"FLEET_SOAK {msg}", flush=True)

    violations = []
    grace_s = grace_ms / 1e3
    say(f"building {replicas} replicas")
    reps = [_build_fleet_replica(i) for i in range(int(replicas))]
    sessions = [r.session for r in reps]
    router = Router(reps, factory=_build_fleet_replica, name="fleet",
                    probe_ms=10.0, hedge_ms=50.0, straggler_ms=100.0)

    say("measuring uncontended fleet interactive p99 (no faults)")
    lat = []
    x = np.zeros(16, np.float32)
    for _ in range(48):
        t0 = time.monotonic()
        router.submit(x, priority="interactive",
                      deadline_ms=4000.0).result(timeout=30)
        lat.append((time.monotonic() - t0) * 1e3)
    base_p99 = _percentile(lat, 99)
    say(f"uncontended fleet p99 = {base_p99:.1f}ms")

    plan = faults.install_plan({"seed": int(seed), "rules": [
        {"site": "serve:queue", "kind": "transient", "prob": 0.01},
        {"site": "serve:execute", "kind": "transient", "prob": 0.01},
        {"site": "serve:execute", "kind": "delay", "seconds": 0.1,
         "prob": 0.005},
        {"site": "replica:dispatch", "kind": "transient", "prob": 0.005},
    ]})

    stats = _ClientStats()
    stop_at = time.monotonic() + float(duration_s)
    n_interactive = max(2, clients // 4)
    n_batch = clients - n_interactive
    barrier = threading.Barrier(clients + 1)
    kseq = threading.Lock()
    kill_done = {"ok_after": 0, "killed": None}

    def classify(exc):
        if isinstance(exc, DeadlineExceeded):
            return "deadline_504"
        if isinstance(exc, ServiceUnavailable):
            return "shed_503"
        if isinstance(exc, (TransientFaultError, InjectedFaultError)):
            return "injected"
        return "unexpected"

    def client(cid, priority, deadline_ms, pause_s):
        barrier.wait(timeout=30)
        n = 0
        while time.monotonic() < stop_at:
            n += 1
            t0 = time.monotonic()
            deadline = t0 + deadline_ms / 1e3
            try:
                fut = router.submit(x, priority=priority,
                                    deadline_ms=deadline_ms,
                                    key=f"c{cid}-{n}")
            except Exception as exc:  # noqa: BLE001 — sync rejects
                stats.record(priority, t0, deadline, grace_s,
                             classify(exc), exc)
                time.sleep(max(pause_s, 0.003))
                continue
            with stats.lock:
                stats.admitted += 1
            try:
                fut.result(timeout=60)
                lat_ms = (time.monotonic() - t0) * 1e3
                stats.record(priority, t0, deadline, grace_s, "ok",
                             lat_ms=lat_ms)
                if kill_done["killed"] is not None:
                    with kseq:
                        kill_done["ok_after"] += 1
            except Exception as exc:  # noqa: BLE001
                stats.record(priority, t0, deadline, grace_s,
                             classify(exc), exc)
            time.sleep(pause_s)

    def killer():
        """Deterministic mid-traffic replica kill."""
        barrier.wait(timeout=30)
        time.sleep(duration_s / 2.0)
        with router._lock:
            live = sorted(st.index for st in router._states.values()
                          if not st.dead)
        if live:
            victim = live[int(seed) % len(live)]
            say(f"killing replica {victim} mid-traffic")
            router.kill_replica(victim, reason="soak_kill")
            kill_done["killed"] = victim

    threads = [threading.Thread(
        target=client, args=(i, "interactive", interactive_deadline_ms,
                             0.01),
        daemon=True, name=f"fleet-hi-{i}") for i in range(n_interactive)]
    threads += [threading.Thread(
        target=client, args=(n_interactive + i, "batch",
                             batch_deadline_ms, 0.001),
        daemon=True, name=f"fleet-lo-{i}") for i in range(n_batch)]
    threads.append(threading.Thread(target=killer, daemon=True,
                                    name="fleet-killer"))
    say(f"soaking: {n_interactive} interactive + {n_batch} batch clients "
        f"over {replicas} replicas for {duration_s:.0f}s (seed={seed})")
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 90)
        if t.is_alive():
            violations.append(f"thread {t.name} wedged (deadlock?)")
    faults.clear_plan()

    # -- invariants ----------------------------------------------------------
    total_seen = sum(stats.outcomes.values())
    if stats.unexpected:
        violations.append(
            f"{len(stats.unexpected)} unexpected outcome(s), e.g. "
            f"{stats.unexpected[:3]}")
    if stats.settled != total_seen:
        violations.append(
            f"settle books don't balance: {stats.settled} settles vs "
            f"{total_seen} outcomes")
    if stats.late_completions:
        violations.append(
            f"{stats.late_completions} silent late completion(s)")
    if stats.outcomes["ok"] == 0:
        violations.append("zero successful requests — fleet served nothing")
    if kill_done["killed"] is None:
        violations.append("the mid-traffic replica kill never happened")
    if router.counters["kills"] < 1:
        violations.append("router recorded no replica kill")
    if kill_done["ok_after"] == 0:
        violations.append(
            "no successful request after the replica kill — no recovery")
    for rep in reps:
        sheds = rep.metrics.snapshot()["sheds"]
        if any(k != "batch" for k in sheds):
            violations.append(
                f"replica {rep.index}: sheds outside batch class: {sheds}")
    hi_p99 = _percentile(stats.interactive_lat, 99)
    bound = max(p99_factor * base_p99, p99_floor_ms)
    if hi_p99 > bound:
        violations.append(
            f"interactive p99 {hi_p99:.1f}ms exceeds bound {bound:.1f}ms "
            f"({p99_factor}x uncontended {base_p99:.1f}ms)")

    # -- zero-downtime rollout under live traffic ---------------------------
    say("rollout: walking live replicas through warm swaps under traffic")
    roll_stats = _ClientStats()
    roll_stop = {"at": time.monotonic() + 60.0}

    def roll_client(cid):
        n = 0
        while time.monotonic() < roll_stop["at"]:
            n += 1
            t0 = time.monotonic()
            try:
                router.submit(x, priority="interactive", deadline_ms=4000.0,
                              key=f"roll{cid}-{n}").result(timeout=30)
                roll_stats.record("interactive", t0, None, grace_s, "ok")
            except Exception as exc:  # noqa: BLE001
                roll_stats.record("interactive", t0, None, grace_s,
                                  classify(exc), exc)
            time.sleep(0.005)

    roll_threads = [threading.Thread(target=roll_client, args=(i,),
                                     daemon=True) for i in range(8)]
    for t in roll_threads:
        t.start()
    new_net, _ = _fleet_net()
    modes = router.rollout(new_net, example=np.zeros((1, 16), np.float32),
                           timeout=30.0)
    roll_stop["at"] = time.monotonic()
    for t in roll_threads:
        t.join(30)
    live_modes = [m for m in modes if m != "dead"]
    if not live_modes or any(m != "warm" for m in live_modes):
        violations.append(
            f"rollout was not all-warm across live replicas: {modes}")
    dropped = sum(v for k, v in roll_stats.outcomes.items() if k != "ok")
    if dropped:
        violations.append(
            f"rollout dropped {dropped} request(s): "
            f"{roll_stats.outcomes} e.g. {roll_stats.unexpected[:2]}")
    for st in list(router._states.values()):
        if st.dead:
            continue
        try:
            st.replica.session.assert_no_recompiles()
        except Exception as exc:  # noqa: BLE001
            violations.append(
                f"replica {st.index} recompiled during rollout: {exc}")

    # -- autoscaling: grow through the factory, shrink by graceful drain ----
    n_before = router.replica_count()
    say(f"scale: {n_before} -> {n_before + 1} -> 2")
    router.scale_to(n_before + 1)
    if router.replica_count() != n_before + 1:
        violations.append(
            f"scale up failed: {router.replica_count()} != {n_before + 1}")
    router.scale_to(2)
    if router.replica_count() != 2:
        violations.append(
            f"scale down failed: {router.replica_count()} != 2")
    try:
        router.submit(x, priority="interactive",
                      deadline_ms=4000.0).result(timeout=30)
    except Exception as exc:  # noqa: BLE001
        violations.append(f"post-scale serving failed: {exc!r}")

    counters = dict(router.counters)
    router.close()
    report = {
        "ok": not violations,
        "violations": violations,
        "outcomes": dict(stats.outcomes),
        "admitted": stats.admitted,
        "uncontended_p99_ms": base_p99,
        "interactive_p99_ms": hi_p99,
        "p99_bound_ms": bound,
        "killed_replica": kill_done["killed"],
        "ok_after_kill": kill_done["ok_after"],
        "rollout_modes": modes,
        "rollout_outcomes": dict(roll_stats.outcomes),
        "counters": counters,
        "faults_fired": plan.fired_total(),
    }
    say(f"outcomes={report['outcomes']} counters(failovers="
        f"{counters['failovers']}, requeued={counters['requeued']}, "
        f"hedges={counters['hedges']}, fenced={counters['fenced_results']}"
        f", dup_settles={counters['duplicate_settles']}) "
        f"p99={hi_p99:.1f}ms (bound {bound:.1f}ms) rollout={modes}")
    return report


def run_cb_soak(duration_s=8.0, seed=13, num_slots=8, verbose=True):
    """Continuous-batching chaos soak: a :class:`ContinuousEngine` under
    sustained mixed-length traffic — long batch-class decodes resubmitted
    the moment they finish, interactive shorts arriving the whole time —
    plus a ``serve:decode`` fault sub-leg. Asserts:

    1. **No head-of-line blocking** — with free slots available, no
       interactive short ever waits more than ONE scheduler iteration
       for admission while the long decodes run (the headline
       iteration-level-scheduling property the static batcher cannot
       provide);
    2. **Exactly-once settlement** — client books balance, every future
       settles exactly once, no wedged client thread;
    3. **Trace-static steady state** — zero recompiles across the whole
       soak (hundreds of admit/retire cycles);
    4. **Pages recycle** — the pool owns zero pages after drain;
    5. **Fault isolation** — an injected ``serve:decode`` fault fails
       only the requests in flight at that step; the engine keeps
       serving new submissions afterwards.

    Importable — ``tests/test_serve_chaos.py`` can drive the same
    machinery."""
    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.serve import ContinuousEngine

    def say(msg):
        if verbose:
            print(f"CB_SOAK {msg}", flush=True)

    violations = []
    rng = np.random.default_rng(seed)
    net = get_llama("llama_tiny_test")
    net.initialize()
    eng = ContinuousEngine(net, max_seq=64, num_slots=num_slots,
                           page_size=16, prefill_chunk=16,
                           decode_path="baseline", name="cb_soak",
                           max_queue=256)
    eng.start()

    stop_at = time.monotonic() + float(duration_s)
    books = {"long_ok": 0, "short_ok": 0, "errors": 0}
    waits = []          # admit_wait_steps of every interactive short
    lock = threading.Lock()

    def long_feeder(fid):
        """One lane of continuous long batch-class work: resubmit the
        moment the previous long decode finishes, so long decodes are
        ALWAYS in flight while the shorts arrive."""
        while time.monotonic() < stop_at:
            try:
                r = eng.submit([7 + fid] * 8, max_new_tokens=48,
                               priority="batch").result(timeout=120)
                with lock:
                    books["long_ok"] += 1
                    assert len(r["tokens"]) == 48
            except Exception:  # noqa: BLE001
                with lock:
                    books["errors"] += 1

    def short_feeder(fid):
        """Interactive shorts, one at a time per feeder — there are
        always free slots for them next to the long lanes."""
        while time.monotonic() < stop_at:
            try:
                r = eng.submit([int(rng.integers(2, 50)), 3 + fid],
                               max_new_tokens=int(rng.integers(2, 5)),
                               priority="interactive").result(timeout=60)
                with lock:
                    books["short_ok"] += 1
                    waits.append(r["admit_wait_steps"])
            except Exception:  # noqa: BLE001
                with lock:
                    books["errors"] += 1
            time.sleep(float(rng.uniform(0.0, 0.01)))

    threads = [threading.Thread(target=long_feeder, args=(i,),
                                daemon=True, name=f"cb-long-{i}")
               for i in range(2)]
    threads += [threading.Thread(target=short_feeder, args=(i,),
                                 daemon=True, name=f"cb-short-{i}")
                for i in range(3)]
    say(f"soaking: 2 long lanes (48-token decodes) + 3 interactive "
        f"feeders over {num_slots} slots for {duration_s:.0f}s")
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 180)
        if t.is_alive():
            violations.append(f"client thread {t.name} wedged (deadlock?)")

    if not eng.drain(timeout=60.0):
        violations.append("drain() failed with work still queued")
    eng.resume()

    # -- invariants ----------------------------------------------------------
    if books["errors"]:
        violations.append(f"{books['errors']} unexpected request "
                          f"error(s) during the clean soak")
    if books["short_ok"] == 0 or books["long_ok"] == 0:
        violations.append(f"soak starved a class: {books}")
    bad_waits = [w for w in waits if w > 1]
    if bad_waits:
        violations.append(
            f"{len(bad_waits)}/{len(waits)} interactive shorts waited "
            f"> 1 scheduler step for admission with free slots "
            f"(head-of-line blocking): worst={max(bad_waits)}")
    try:
        eng.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        violations.append(f"soak recompiled: {exc}")
    st = eng.stats()
    if st["pool"]["pages_owned"] != 0:
        violations.append(
            f"pool leaked {st['pool']['pages_owned']} page(s) after drain")

    # -- fault sub-leg: serve:decode kill is per-request, engine survives ---
    say("fault sub-leg: one fatal serve:decode step")
    faults.install_plan({"seed": int(seed) + 1, "rules": [
        {"site": "serve:decode", "kind": "fatal", "times": 1}]})
    try:
        eng.submit([5, 6], max_new_tokens=8).result(timeout=60)
        violations.append("serve:decode fault never surfaced")
    except Exception:  # noqa: BLE001 — the injected kill
        pass
    finally:
        faults.clear_plan()
    try:
        r = eng.submit([5, 6], max_new_tokens=4).result(timeout=60)
        if len(r["tokens"]) != 4:
            violations.append("post-fault request came back short")
    except Exception as exc:  # noqa: BLE001
        violations.append(f"engine did not survive the decode fault: "
                          f"{exc!r}")
    if eng.stats()["pool"]["pages_owned"] != 0:
        violations.append("faulted request leaked its pages")

    snap = eng.metrics.snapshot()
    eng.close()
    report = {
        "ok": not violations,
        "violations": violations,
        "books": dict(books),
        "admit_wait_max": max(waits) if waits else 0,
        "ttft_p99_ms": snap.get("ttft_p99_ms", 0.0),
        "itl_p99_ms": snap.get("itl_p99_ms", 0.0),
        "steps": st["steps"],
        "pool_high_water": st["pool"]["high_water"],
    }
    say(f"books={books} admit_wait_max={report['admit_wait_max']} "
        f"steps={report['steps']} ttft_p99={report['ttft_p99_ms']:.1f}ms "
        f"itl_p99={report['itl_p99_ms']:.2f}ms")
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--duration", type=float, default=10.0,
                    help="soak seconds (default 10; full soak: 60+)")
    ap.add_argument("--clients", type=int, default=64,
                    help="concurrent client threads (>= 64 = acceptance)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--p99-factor", type=float, default=3.0,
                    help="interactive p99 bound as a multiple of the "
                         "uncontended p99")
    ap.add_argument("--p99-floor-ms", type=float, default=250.0,
                    help="absolute floor for the p99 bound (CI jitter)")
    ap.add_argument("--no-decode", action="store_true",
                    help="skip the Generator/serve:decode leg")
    ap.add_argument("--fleet", action="store_true",
                    help="run the fleet soak (Router over N replicas + "
                         "mid-traffic replica kill) instead of the "
                         "single-server soak")
    ap.add_argument("--replicas", type=int, default=3,
                    help="fleet soak: number of replicas (default 3)")
    ap.add_argument("--cb", action="store_true",
                    help="run the continuous-batching soak "
                         "(ContinuousEngine under mixed-length traffic) "
                         "instead of the single-server soak")
    args = ap.parse_args(argv)

    if args.cb:
        report = run_cb_soak(duration_s=args.duration, seed=args.seed)
        if report["ok"]:
            print(f"CB_SOAK=PASS books={report['books']} "
                  f"admit_wait_max={report['admit_wait_max']} "
                  f"steps={report['steps']} "
                  f"ttft_p99={report['ttft_p99_ms']:.1f}ms "
                  f"itl_p99={report['itl_p99_ms']:.2f}ms")
            return 0
        for v in report["violations"]:
            print(f"CB_SOAK=FAIL {v}")
        return 1

    if args.fleet:
        report = run_fleet_soak(
            duration_s=args.duration, clients=args.clients,
            replicas=args.replicas, seed=args.seed,
            p99_factor=max(args.p99_factor, 4.0),
            p99_floor_ms=max(args.p99_floor_ms, 600.0))
        if report["ok"]:
            print(f"FLEET_SOAK=PASS outcomes={report['outcomes']} "
                  f"killed=r{report['killed_replica']} "
                  f"failovers={report['counters']['failovers']} "
                  f"requeued={report['counters']['requeued']} "
                  f"p99={report['interactive_p99_ms']:.1f}ms "
                  f"rollout={report['rollout_modes']}")
            return 0
        for v in report["violations"]:
            print(f"FLEET_SOAK=FAIL {v}")
        return 1

    report = run_soak(duration_s=args.duration, clients=args.clients,
                      seed=args.seed, p99_factor=args.p99_factor,
                      p99_floor_ms=args.p99_floor_ms,
                      decode=not args.no_decode)
    if report["ok"]:
        print(f"CHAOS_SOAK=PASS outcomes={report['outcomes']} "
              f"faults_fired={report['faults_fired']} "
              f"p99={report['interactive_p99_ms']:.1f}ms "
              f"swap={report['swap_mode']}")
        return 0
    for v in report["violations"]:
        print(f"CHAOS_SOAK=FAIL {v}")
    return 1


if __name__ == "__main__":
    rc = main()
    try:
        from mxnet_tpu.resilience.lockdep import smoke_gate
    except ImportError:
        pass
    else:
        rc = smoke_gate(rc)
    sys.exit(rc)
