#!/usr/bin/env bash
# Tier-1 verify gate — the EXACT command from ROADMAP.md, wrapped so
# builders and CI invoke the same gate (same pipefail discipline, same
# DOTS_PASSED report) instead of each reassembling it by hand.
#
# Usage:  tools/run_tier1.sh [extra pytest args...]
#   e.g.  tools/run_tier1.sh tests/test_guardrails.py
# Exit status is pytest's (pipefail-preserved through the tee).
set -u
set -o pipefail

cd "$(dirname "$0")/.."

LOG="${TIER1_LOG:-/tmp/_t1.log}"
TIMEOUT_S="${TIER1_TIMEOUT:-870}"

rm -f "$LOG"
timeout -k 10 "$TIMEOUT_S" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"

# Opt-in second pass (TIER1_BULK=1): re-run the eager-path test files with
# deferred bulk dispatch force-enabled, so a bulking regression can't hide
# behind the default-off MXNET_ENGINE_BULK_SIZE knob.
if [[ "${TIER1_BULK:-0}" != "0" ]]; then
    BULK_LOG="${TIER1_BULK_LOG:-/tmp/_t1_bulk.log}"
    rm -f "$BULK_LOG"
    timeout -k 10 "$TIMEOUT_S" env JAX_PLATFORMS=cpu \
        MXNET_ENGINE_BULK_SIZE=16 \
        python -m pytest \
        tests/test_engine_bulk.py tests/test_eager_jit.py \
        tests/test_ndarray.py tests/test_autograd.py tests/test_gluon.py \
        -q -m 'not slow' \
        --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly \
        2>&1 | tee "$BULK_LOG"
    bulk_rc=${PIPESTATUS[0]}
    echo "BULK_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$BULK_LOG" | tr -cd . | wc -c)"
    if [[ "$rc" -eq 0 && "$bulk_rc" -ne 0 ]]; then
        rc=$bulk_rc
    fi
fi
# Serve smoke pass (TIER1_SERVE=0 to skip): one InferenceSession behind a
# DynamicBatcher, 32 concurrent requests — asserts correct results, a p99
# latency bound, zero recompiles after warmup, and clean shutdown.
if [[ "${TIER1_SERVE:-1}" != "0" ]]; then
    timeout -k 10 120 env JAX_PLATFORMS=cpu \
        python tools/serve_smoke.py
    serve_rc=$?
    if [[ "$rc" -eq 0 && "$serve_rc" -ne 0 ]]; then
        rc=$serve_rc
    fi
fi
# Chaos soak smoke (TIER1_CHAOS=0 to skip): ~15s of 64 concurrent
# mixed-priority clients under a seeded fault plan — asserts exactly-once
# future settlement, no silent late completions, batch-class-only sheds,
# bounded interactive p99, clean drain, and a warm (zero-recompile) hot
# swap. The full soak lives in tests/test_serve_chaos.py behind -m slow.
if [[ "${TIER1_CHAOS:-1}" != "0" ]]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/chaos_soak.py --duration "${TIER1_CHAOS_S:-6}" --clients 64
    chaos_rc=$?
    if [[ "$rc" -eq 0 && "$chaos_rc" -ne 0 ]]; then
        rc=$chaos_rc
    fi
fi
# Trace pass (TIER1_TRACE=1 to enable): re-run the serve smoke with
# request tracing + the flight recorder on. Asserts (a) the injected
# serve:execute fault leaves a recorder dump naming the failing site
# (serve_smoke --trace-out exits nonzero otherwise) and (b) the dumped
# chrome trace is well-formed with one connected per-request lane
# (tools/trace_check.py --expect-lane).
if [[ "${TIER1_TRACE:-0}" != "0" ]]; then
    TRACE_DIR="$(mktemp -d /tmp/_t1_trace.XXXXXX)"
    timeout -k 10 120 env JAX_PLATFORMS=cpu \
        MXNET_TRACE=1 MXNET_FLIGHT_RECORDER=1 \
        MXNET_FLIGHT_RECORDER_DIR="$TRACE_DIR" \
        python tools/serve_smoke.py --trace-out "$TRACE_DIR/trace.json"
    trace_rc=$?
    if [[ "$trace_rc" -eq 0 ]]; then
        python tools/trace_check.py --expect-lane "$TRACE_DIR/trace.json"
        trace_rc=$?
    fi
    if [[ "$rc" -eq 0 && "$trace_rc" -ne 0 ]]; then
        rc=$trace_rc
    fi
fi
# Decode-rung pass (TIER1_DECODE=1 to enable): run the serve smoke's
# --decode-path mode over every rung of the decode ladder — baseline
# (strict PR-5 ops), pallas (fused decode-attention), int8 (int8 KV
# rings), spec (speculative decoding). Each rung drives 8 concurrent
# generate() clients and asserts identical greedy output, zero
# recompiles, and the 503 (drain/resume) + 504 (past-deadline) taxonomy.
if [[ "${TIER1_DECODE:-0}" != "0" ]]; then
    for dp in baseline pallas int8 spec; do
        timeout -k 10 180 env JAX_PLATFORMS=cpu \
            python tools/serve_smoke.py --decode-path "$dp"
        decode_rc=$?
        if [[ "$rc" -eq 0 && "$decode_rc" -ne 0 ]]; then
            rc=$decode_rc
        fi
    done
fi
# Prefix-cache pass (TIER1_PREFIX=1 to enable): serve_smoke --prefix —
# 8 ContinuousEngine clients sharing a 20-token system prompt must get
# token-identical greedy output with the radix prefix cache on vs off,
# with prefix_hit_rate > 0, zero recompiles, and no page leaks; then
# two fresh subprocesses warm one MXNET_COMPILE_CACHE_DIR and the
# second must replay the whole lattice from disk (disk_hits > 0,
# disk_misses == 0) with identical stable signature keys. Re-run under
# MXNET_LOCKDEP=1 to pin the trie-outside-pool lock order.
if [[ "${TIER1_PREFIX:-0}" != "0" ]]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
        python tools/serve_smoke.py --prefix
    prefix_rc=$?
    if [[ "$rc" -eq 0 && "$prefix_rc" -ne 0 ]]; then
        rc=$prefix_rc
    fi
    timeout -k 10 600 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/serve_smoke.py --prefix
    prefix_rc=$?
    if [[ "$rc" -eq 0 && "$prefix_rc" -ne 0 ]]; then
        rc=$prefix_rc
    fi
fi
# Multi-step decode pass (TIER1_MULTISTEP=1 to enable): serve_smoke
# --multistep — 8 concurrent ContinuousEngine clients on the PR-19
# device-side super-step loop (MXNET_SERVE_DECODE_STEPS iterations per
# host visit) must get greedy output token-identical to the classic
# one-visit-per-token engine, with exactly one compiled super-step
# signature, zero recompiles, and a mid-stream deadline settling as 504
# within one super-step (not one request). Re-run under MXNET_LOCKDEP=1:
# the settle loop walks pool + metrics locks per super-step and must
# stay cycle-free.
if [[ "${TIER1_MULTISTEP:-0}" != "0" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
        python tools/serve_smoke.py --multistep
    ms_rc=$?
    if [[ "$rc" -eq 0 && "$ms_rc" -ne 0 ]]; then
        rc=$ms_rc
    fi
    timeout -k 10 300 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/serve_smoke.py --multistep
    ms_rc=$?
    if [[ "$rc" -eq 0 && "$ms_rc" -ne 0 ]]; then
        rc=$ms_rc
    fi
fi
# Fleet soak smoke (TIER1_FLEET=0 to skip): ~8s of 64 mixed-priority
# clients through a Router over 3 replicas under a seeded fault plan,
# with one deterministic replica kill mid-traffic — asserts fleet-wide
# exactly-once settlement (failover requeue + generation fencing), a
# closed outcome taxonomy, batch-only sheds, bounded interactive p99,
# an all-warm zero-drop rollout, and graceful-drain scale down. The
# 8-seed kill-phase sweep lives in tests/test_fleet.py behind -m slow.
if [[ "${TIER1_FLEET:-1}" != "0" ]]; then
    timeout -k 10 240 env JAX_PLATFORMS=cpu \
        python tools/chaos_soak.py --fleet \
        --duration "${TIER1_FLEET_S:-6}" --clients 64
    fleet_rc=$?
    if [[ "$rc" -eq 0 && "$fleet_rc" -ne 0 ]]; then
        rc=$fleet_rc
    fi
fi
# Continuous-batching soak smoke (TIER1_CB=1 to enable): a
# ContinuousEngine over 8 slots takes ~4s of mixed-length traffic (two
# always-on 48-token batch-class decode lanes + interactive shorts) and
# a fatal serve:decode sub-leg — asserts no interactive short ever waits
# more than one scheduler iteration for admission (no head-of-line
# blocking), exactly-once settlement, zero recompiles across hundreds of
# admit/retire cycles, full KV-page recycling, and per-request fault
# isolation. The assertion-level suite is tests/test_continuous_batching.py.
if [[ "${TIER1_CB:-0}" != "0" ]]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/chaos_soak.py --cb --duration "${TIER1_CB_S:-4}"
    cb_rc=$?
    if [[ "$rc" -eq 0 && "$cb_rc" -ne 0 ]]; then
        rc=$cb_rc
    fi
fi
# Static-analysis gate (TIER1_LINT=0 to skip): tools/mxlint over the
# whole tree — lock-order cycles (L001), blocking calls under held locks
# (L002), flag/fault-site/counter registry drift (L003), and thread
# hygiene (L004). Exits nonzero on any finding not covered by
# tools/mxlint/baseline.json; see TOOLING.md for the rule catalog.
if [[ "${TIER1_LINT:-1}" != "0" ]]; then
    timeout -k 10 120 env JAX_PLATFORMS=cpu \
        python -m tools.mxlint mxnet_tpu tools bench.py
    lint_rc=$?
    if [[ "$rc" -eq 0 && "$lint_rc" -ne 0 ]]; then
        rc=$lint_rc
    fi
fi
# Lockdep pass (TIER1_LOCKDEP=0 to skip): re-run the serve smoke and the
# fleet + continuous-batching soaks with the runtime lock-order
# sanitizer on (MXNET_LOCKDEP=1). Every threading.Lock/RLock/Condition
# created after startup is wrapped; the sanitizer records the
# acquisition-order graph, dumps any cycle or blocking-under-lock
# violation through the flight recorder, and smoke_gate() escalates the
# exit status on cycles (the LOCKDEP= summary line is printed either
# way).
if [[ "${TIER1_LOCKDEP:-1}" != "0" ]]; then
    timeout -k 10 120 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/serve_smoke.py
    ld_rc=$?
    if [[ "$rc" -eq 0 && "$ld_rc" -ne 0 ]]; then
        rc=$ld_rc
    fi
    timeout -k 10 240 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/chaos_soak.py --fleet \
        --duration "${TIER1_FLEET_S:-6}" --clients 64
    ld_rc=$?
    if [[ "$rc" -eq 0 && "$ld_rc" -ne 0 ]]; then
        rc=$ld_rc
    fi
    timeout -k 10 180 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/chaos_soak.py --cb --duration "${TIER1_CB_S:-4}"
    ld_rc=$?
    if [[ "$rc" -eq 0 && "$ld_rc" -ne 0 ]]; then
        rc=$ld_rc
    fi
fi
# SLO smoke (TIER1_SLO=1 to enable): the healthy 32-client serve smoke
# with a declarative SLO monitor attached (itl/ttft p99, goodput,
# error-rate burn objectives) — asserts no objective burns, the monitor
# health stays "ok", and the flight recorder produces zero slo_burn
# dumps (the guard's false-positive contract). Re-run under
# MXNET_LOCKDEP=1: the monitor's observe/evaluate path runs on the
# metrics-observing threads and must stay cycle-free.
if [[ "${TIER1_SLO:-0}" != "0" ]]; then
    timeout -k 10 120 env JAX_PLATFORMS=cpu \
        python tools/serve_smoke.py --slo
    slo_rc=$?
    if [[ "$rc" -eq 0 && "$slo_rc" -ne 0 ]]; then
        rc=$slo_rc
    fi
    timeout -k 10 120 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/serve_smoke.py --slo
    slo_rc=$?
    if [[ "$rc" -eq 0 && "$slo_rc" -ne 0 ]]; then
        rc=$slo_rc
    fi
fi
# Perf-regression gate (TIER1_PERFGUARD=1 to enable): the spread-aware
# gate over the checked-in BENCH_r*/MULTICHIP_r* history
# (tools/perf_regression.py). With TIER1_PERFGUARD_FRESH=<file> the
# gate compares that fresh bench emission against the full history;
# without it the newest checked-in round plays the candidate
# (self-check — must stay green on the committed files). The tool
# SKIPs cleanly (exit 0) when there is nothing to compare.
if [[ "${TIER1_PERFGUARD:-0}" != "0" ]]; then
    if [[ -n "${TIER1_PERFGUARD_FRESH:-}" ]]; then
        timeout -k 10 60 python tools/perf_regression.py \
            --fresh "$TIER1_PERFGUARD_FRESH"
    else
        timeout -k 10 60 python tools/perf_regression.py
    fi
    perf_rc=$?
    if [[ "$rc" -eq 0 && "$perf_rc" -ne 0 ]]; then
        rc=$perf_rc
    fi
fi
# Collective overlap smoke (TIER1_OVERLAP=1 to enable): a dp4 training
# loop with gradient bucketing + overlapped priority-ordered flushes on
# (MXNET_KVSTORE_BUCKET_MB / MXNET_KVSTORE_OVERLAP) — asserts bitwise
# parameter parity vs the unbucketed baseline, zero steady-state
# recompiles at every ablation point, front-first bucket settle order,
# and bounded 2-bit compression divergence. The assertion-level suite is
# tests/test_bucketing.py.
if [[ "${TIER1_OVERLAP:-0}" != "0" ]]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/overlap_smoke.py
    overlap_rc=$?
    if [[ "$rc" -eq 0 && "$overlap_rc" -ne 0 ]]; then
        rc=$overlap_rc
    fi
fi
# Elastic soak smoke (TIER1_ELASTIC=0 to skip): one seeded
# kill/lag/corrupt sweep through a dp8 training loop — asserts the
# chip-loss dp8->dp4 resume lands bitwise on the dp4 reference run,
# straggler blame, and desync detection within the audit cadence. The
# full 8-seed sweep lives in tests/test_elastic.py behind -m slow.
if [[ "${TIER1_ELASTIC:-1}" != "0" ]]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/elastic_soak.py --seeds "${TIER1_ELASTIC_SEEDS:-1}"
    elastic_rc=$?
    if [[ "$rc" -eq 0 && "$elastic_rc" -ne 0 ]]; then
        rc=$elastic_rc
    fi
fi
# Composed-mesh elastic smoke (TIER1_ELASTIC3D=1 to enable): the
# kill-one-chip dp2xtp2 leg alone — a coordinate-addressed chip_loss
# rebuilds the mesh to dp1xtp2 (tp extent pinned, touched dp-group
# dropped) and reshards the layout-carrying sharded checkpoint onto the
# survivors; asserts no MeshDegraded escapes and the resumed run lands
# bitwise on a clean dp1xtp2 run from the same checkpoint. Re-run under
# MXNET_LOCKDEP=1: recovery walks checkpoint-manager and mesh-registry
# locks from the failure path and must stay cycle-free.
if [[ "${TIER1_ELASTIC3D:-0}" != "0" ]]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/elastic_soak.py --legs 3d \
        --seeds "${TIER1_ELASTIC_SEEDS:-1}"
    e3d_rc=$?
    if [[ "$rc" -eq 0 && "$e3d_rc" -ne 0 ]]; then
        rc=$e3d_rc
    fi
    timeout -k 10 180 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/elastic_soak.py --legs 3d \
        --seeds "${TIER1_ELASTIC_SEEDS:-1}"
    e3d_rc=$?
    if [[ "$rc" -eq 0 && "$e3d_rc" -ne 0 ]]; then
        rc=$e3d_rc
    fi
fi
# Preemption smoke (TIER1_PREEMPT=1 to enable): interrupt a training
# epoch mid-way via the deterministic preempt:deliver site (the
# SIGTERM-equivalent), force-save through the async checkpoint writer,
# resume in a fresh estimator/iterator — asserts the epoch's sample
# sequence is consumed exactly once across the cut and the final params
# land bitwise on the uninterrupted reference. The assertion-level suite
# is tests/test_preemption.py.
if [[ "${TIER1_PREEMPT:-0}" != "0" ]]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/preempt_smoke.py --seeds "${TIER1_PREEMPT_SEEDS:-1}"
    preempt_rc=$?
    if [[ "$rc" -eq 0 && "$preempt_rc" -ne 0 ]]; then
        rc=$preempt_rc
    fi
fi
# Input-pipeline smoke (TIER1_DATA=1 to enable): a synthetic crc-indexed
# .rec streamed through sharded RecordPipelines ×4 decode workers under
# a seeded io:read plan (transient + torn + worker kill) — asserts
# exactly-once sample delivery (delivered ∪ quarantined, no dupes, kill
# requeued + respawned), worker-count-independent delivery order,
# sample-exact 2->1 reshard resume, zero recompiles through the
# DeviceFeeder double-buffer, and the io.* export surface. Re-run under
# MXNET_LOCKDEP=1: the worker pool's queue/lock traffic must stay
# cycle-free with no blocking calls under the pipeline lock.
if [[ "${TIER1_DATA:-0}" != "0" ]]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/data_smoke.py
    data_rc=$?
    if [[ "$rc" -eq 0 && "$data_rc" -ne 0 ]]; then
        rc=$data_rc
    fi
    timeout -k 10 180 env JAX_PLATFORMS=cpu MXNET_LOCKDEP=1 \
        python tools/data_smoke.py
    data_rc=$?
    if [[ "$rc" -eq 0 && "$data_rc" -ne 0 ]]; then
        rc=$data_rc
    fi
fi
exit "$rc"
