#!/usr/bin/env bash
# Tier-1 verify gate — the EXACT command from ROADMAP.md, wrapped so
# builders and CI invoke the same gate (same pipefail discipline, same
# DOTS_PASSED report) instead of each reassembling it by hand.
#
# Usage:  tools/run_tier1.sh [extra pytest args...]
#   e.g.  tools/run_tier1.sh tests/test_guardrails.py
# Exit status is pytest's (pipefail-preserved through the tee).
set -u
set -o pipefail

cd "$(dirname "$0")/.."

LOG="${TIER1_LOG:-/tmp/_t1.log}"
TIMEOUT_S="${TIER1_TIMEOUT:-870}"

rm -f "$LOG"
timeout -k 10 "$TIMEOUT_S" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
exit "$rc"
