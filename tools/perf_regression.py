#!/usr/bin/env python
"""Perf-regression gate over the checked-in bench history.

``BENCH_r*.json`` / ``MULTICHIP_r*.json`` record each growth round's
bench emission: the driver stores ``{"n", "cmd", "rc", "tail"}`` where
``tail`` is the (possibly mid-JSON truncated) stdout tail containing one
JSON metric row per line::

    {"metric": "resnet50_v1_train_bs256_bf16_amp", "value": 2707.31,
     "unit": "img/s", "n": 5, "spread": [2609.86, 2780.03], ...}

This tool recovers every intact row by scanning for ``{"metric":`` and
``raw_decode``-ing from there (truncated final rows are dropped, not
fatal), builds a per-metric series across rounds, and gates a candidate
emission against it:

* ``--fresh FILE``  gate a fresh emission (bench stdout or a JSON list
  of rows) against the full history.
* default (no ``--fresh``)  self-check: the NEWEST round plays the
  candidate and every earlier round is history — this must stay green
  on the checked-in r01..r05 files, so the gate itself is regression-
  tested by the repo state.

Noise model (spread-aware): a metric regresses only when the candidate
value falls outside the reference round's ``spread`` envelope AND past
the relative slack (``--tol``, default 10%).  When either side is
``weather_dominated`` (the bench marked the round as shared-machine
noise) the slack is widened by ``--weather-factor``.  Direction comes
from the unit: ``*/s`` throughput is higher-better, ``ms``/``s``/``us``
latency is lower-better.

Exit status: 0 green (or clean SKIP when there is nothing to compare),
1 with a line naming the regressed row otherwise.  Importable: tests
drive :func:`extract_rows`, :func:`load_history`, and :func:`main`.
"""
import argparse
import glob
import json
import os
import sys

_DECODER = json.JSONDecoder()

# latency-flavoured units (lower is better); anything "per second" or
# unknown is treated as throughput (higher is better)
_LOWER_BETTER_UNITS = ("ms", "us", "ns", "s", "s/iter", "ms/token",
                       "ms/step")

# metric-name fallback for rows whose unit went missing in an old
# emission: elastic recovery time (elastic_resume/_3d) is lower-better
_LOWER_BETTER_METRIC_SUFFIXES = ("recovery_ms", "stall_ms")


def extract_rows(text):
    """Every intact ``{"metric": ...}`` JSON object in ``text``.

    Tolerates arbitrary surrounding log noise and a truncated final
    object (the driver keeps only a byte-bounded tail).  Rows that nest
    the full row set under ``"extra"`` (the bench's final summary line)
    are kept too — callers dedupe by metric name.
    """
    rows = []
    i = 0
    while True:
        j = text.find('{"metric"', i)
        if j < 0:
            break
        try:
            obj, end = _DECODER.raw_decode(text[j:])
        except ValueError:
            i = j + 1
            continue
        if isinstance(obj.get("metric"), str) \
                and isinstance(obj.get("value"), (int, float)):
            rows.append(obj)
        i = j + end
    return rows


def _round_key(path):
    """Sort key: (rNN, family) so BENCH_r02 precedes BENCH_r03 and the
    bench/multichip files of one round stay adjacent."""
    base = os.path.basename(path)
    digits = "".join(c for c in base if c.isdigit())
    return (int(digits) if digits else 0, base)


def load_history(root):
    """``[(label, [row, ...]), ...]`` oldest-first from the checked-in
    ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` files under ``root``.

    Within one round, later duplicates of a metric are dropped (the
    bench's final summary line repeats the last row with an ``extra``
    payload).  Rounds with no recoverable rows (e.g. every MULTICHIP
    file — their tails carry no metric lines) are skipped, not fatal.
    """
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))
                   + glob.glob(os.path.join(root, "MULTICHIP_r*.json")),
                   key=_round_key)
    out = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        rows, seen = [], set()
        for r in extract_rows(doc.get("tail") or ""):
            if r["metric"] in seen:
                continue
            seen.add(r["metric"])
            rows.append(r)
        if rows:
            out.append((os.path.basename(p), rows))
    return out


def load_fresh(path):
    """Candidate rows from ``path``: a JSON list of rows, a driver-style
    ``{"tail": ...}`` doc, or raw bench stdout — whichever parses."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, list):
        return [r for r in doc if isinstance(r, dict) and "metric" in r]
    if isinstance(doc, dict) and "tail" in doc:
        text = doc.get("tail") or ""
    rows, seen = [], set()
    for r in extract_rows(text):
        if r["metric"] not in seen:
            seen.add(r["metric"])
            rows.append(r)
    return rows


def _higher_is_better(unit, metric=None):
    if metric and str(metric).endswith(_LOWER_BETTER_METRIC_SUFFIXES):
        return False
    u = (unit or "").strip().lower()
    return u not in _LOWER_BETTER_UNITS


def _band(row, tol, weather_factor):
    """Acceptance band ``(lo, hi)`` around a reference row: the wider of
    the measured spread envelope and the relative slack, weather-widened
    when the round was marked noise-dominated."""
    v = float(row["value"])
    slack = tol * (weather_factor if row.get("weather_dominated") else 1.0)
    lo, hi = v * (1.0 - slack), v * (1.0 + slack)
    spread = row.get("spread")
    if isinstance(spread, (list, tuple)) and len(spread) == 2:
        try:
            lo = min(lo, float(spread[0]) * (1.0 - slack))
            hi = max(hi, float(spread[1]) * (1.0 + slack))
        except (TypeError, ValueError):
            pass
    return lo, hi


def _candidate_edge(row, higher_better):
    """The candidate's most favourable defensible value: its own spread
    edge toward the reference (a noisy-but-overlapping run is not a
    regression)."""
    v = float(row["value"])
    spread = row.get("spread")
    if isinstance(spread, (list, tuple)) and len(spread) == 2:
        try:
            return max(v, float(spread[1])) if higher_better \
                else min(v, float(spread[0]))
        except (TypeError, ValueError):
            pass
    return v


def compare(history, fresh_rows, tol=0.10, weather_factor=3.0):
    """Gate ``fresh_rows`` against ``history``; returns
    ``(regressions, checked)`` where each regression is a dict naming
    the row, both values, and the violated band."""
    ref = {}  # metric -> (round_label, row); last occurrence wins
    for label, rows in history:
        for r in rows:
            ref[r["metric"]] = (label, r)
    regressions, checked = [], 0
    for row in fresh_rows:
        got = ref.get(row["metric"])
        if got is None:
            continue  # new metric: nothing to regress against
        label, base = got
        checked += 1
        higher = _higher_is_better(row.get("unit") or base.get("unit"),
                                   metric=row["metric"])
        # weather widening applies when EITHER side is noise-dominated;
        # _band handles the reference's own flag
        eff_tol = tol * (weather_factor
                         if row.get("weather_dominated") else 1.0)
        lo, hi = _band(base, eff_tol, weather_factor)
        edge = _candidate_edge(row, higher)
        bad = edge < lo if higher else edge > hi
        if bad:
            regressions.append({
                "metric": row["metric"],
                "value": float(row["value"]),
                "unit": row.get("unit") or base.get("unit"),
                "reference": float(base["value"]),
                "reference_round": label,
                "band": [round(lo, 4), round(hi, 4)],
                "direction": "higher" if higher else "lower",
            })
    return regressions, checked


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="spread-aware perf-regression gate over the "
                    "checked-in BENCH_r*/MULTICHIP_r* history")
    ap.add_argument("--history-dir", default=None,
                    help="directory holding BENCH_r*.json "
                         "(default: repo root above tools/)")
    ap.add_argument("--fresh", default=None,
                    help="candidate emission (bench stdout / JSON rows); "
                         "omitted -> self-check newest round vs the rest")
    ap.add_argument("--tol", type=float, default=0.10,
                    help="relative slack outside the spread envelope "
                         "(default 0.10)")
    ap.add_argument("--weather-factor", type=float, default=3.0,
                    help="slack multiplier for weather_dominated rounds "
                         "(default 3.0)")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable verdict object")
    args = ap.parse_args(argv)

    root = args.history_dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    history = load_history(root)

    if args.fresh is not None:
        try:
            fresh = load_fresh(args.fresh)
        except OSError as e:
            print(f"PERFGUARD SKIP (fresh emission unreadable: {e})")
            return 0
        if not fresh:
            print("PERFGUARD SKIP (fresh emission has no metric rows)")
            return 0
        label = args.fresh
    else:
        if len(history) < 2:
            print("PERFGUARD SKIP (need >= 2 history rounds for "
                  "self-check, have %d)" % len(history))
            return 0
        label, fresh = history[-1]
        history = history[:-1]

    if not history:
        print("PERFGUARD SKIP (no bench history rows)")
        return 0

    regressions, checked = compare(history, fresh, tol=args.tol,
                                   weather_factor=args.weather_factor)
    if args.json:
        print(json.dumps({"candidate": label, "checked": checked,
                          "regressions": regressions}, indent=2))
    if regressions:
        for r in regressions:
            print("PERF_REGRESSION: %s = %g %s vs %g (%s, %s-is-better, "
                  "band [%g, %g])"
                  % (r["metric"], r["value"], r["unit"], r["reference"],
                     r["reference_round"], r["direction"],
                     r["band"][0], r["band"][1]))
        return 1
    print("PERFGUARD PASS (%s: %d row%s checked against %d round%s)"
          % (label, checked, "" if checked == 1 else "s",
             len(history), "" if len(history) == 1 else "s"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
