#!/usr/bin/env python
"""Multi-process / multi-host launcher (reference ``tools/launch.py``).

The reference launcher starts a dmlc-core tracker plus N server and N
worker processes with ``DMLC_ROLE``/``DMLC_PS_ROOT_URI`` env
(`tools/launch.py:67-72`, `docs .../distributed_training.md:262`). The TPU
build has no scheduler or server roles — every process is an SPMD worker —
so launching means: start N processes that each call
``mxnet_tpu.parallel.initialize_distributed()`` (→
``jax.distributed.initialize``) with a shared coordinator address.

Usage::

    # N local processes (CPU collectives via Gloo; or one process per TPU
    # host when run under a TPU pod's per-host scheduler):
    python tools/launch.py -n 4 python train.py --my-args

    # multi-host over ssh, one process per host in the hostfile:
    python tools/launch.py -n 8 -H hosts.txt --launcher ssh \
        python train.py

Each process gets MXNET_TPU_COORDINATOR / MXNET_TPU_NUM_PROCS /
MXNET_TPU_PROC_ID (plus the DMLC_* aliases for scripts written against
the reference), which ``initialize_distributed()`` reads automatically.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env_for(rank, args, coordinator):
    host, _, port = coordinator.partition(":")
    port = port or str(args.port)
    coordinator = f"{host}:{port}"
    env = dict(os.environ)
    env.update({
        "MXNET_TPU_COORDINATOR": coordinator,
        "MXNET_TPU_NUM_PROCS": str(args.num_workers),
        "MXNET_TPU_PROC_ID": str(rank),
        # reference-compat aliases (DMLC tracker naming)
        "DMLC_PS_ROOT_URI": host,
        "DMLC_PS_ROOT_PORT": port,
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
    })
    return env


def launch_local(args, command):
    coordinator = args.coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    try:
        for rank in range(args.num_workers):
            procs.append(subprocess.Popen(
                command, env=_env_for(rank, args, coordinator)))
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


def launch_ssh(args, command):
    """One process per host line (reference ssh launcher parity)."""
    hosts = [h.strip() for h in open(args.hostfile)
             if h.strip() and not h.startswith("#")]
    if len(hosts) < args.num_workers:
        raise SystemExit(f"hostfile has {len(hosts)} hosts, need "
                         f"{args.num_workers}")
    coordinator = args.coordinator or f"{hosts[0]}:{args.port}"
    procs = []
    try:
        for rank in range(args.num_workers):
            env = _env_for(rank, args, coordinator)
            exports = " ".join(
                f"{k}={v!r}" for k, v in env.items()
                if k.startswith(("MXNET_TPU_", "DMLC_")))
            remote = f"cd {os.getcwd()!r} && env {exports} " + \
                " ".join(command)
            procs.append(subprocess.Popen(["ssh", hosts[rank], remote]))
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="launch N SPMD worker processes "
                    "(reference tools/launch.py parity)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("--launcher", choices=("local", "ssh"), default="local")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (default: auto)")
    ap.add_argument("--port", type=int, default=9091)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    if args.launcher == "ssh" or args.hostfile:
        return launch_ssh(args, args.command)
    return launch_local(args, args.command)


if __name__ == "__main__":
    sys.exit(main())
