"""mxlint — codebase-specific static analysis for mxnet-tpu.

Rules (catalog in TOOLING.md):

* **L001** lock-order cycles in the static acquisition graph
* **L002** blocking calls (sleep / Future.result / join / device sync)
  inside a held-lock region
* **L003** registry drift (config flags vs reads vs docs; fault sites
  vs KNOWN_SITES vs RESILIENCE.md; counter namespaces vs
  export.snapshot())
* **L004** thread hygiene (swallowing ``except BaseException``,
  unnamed threads, unsupervised daemon loops)

Usage::

    python -m tools.mxlint mxnet_tpu tools bench.py

Exit status 0 iff no non-baselined findings. Suppress per line with
``# mxlint: disable=L002`` or per finding in
``tools/mxlint/baseline.json``.
"""
from .engine import (  # noqa: F401
    DEFAULT_BASELINE,
    Finding,
    Project,
    collect,
    load_baseline,
    main,
    run,
)
