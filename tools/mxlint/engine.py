"""mxlint core: file collection, AST parsing, rule dispatch, inline
``# mxlint: disable=LNNN`` comments, and the checked-in suppression
baseline (``tools/mxlint/baseline.json``).

A finding is identified by ``(rule, path, key)`` where ``key`` is a
*symbolic* handle chosen by the rule (e.g. ``unregistered-read:
MXNET_FOO`` or a cycle signature) rather than a line number, so
baselines survive unrelated edits to the file.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys

_DISABLE_RE = re.compile(r"#\s*mxlint:\s*disable=([A-Z0-9,\s]+)")

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    key: str           # symbolic identity for baseline matching
    message: str

    @property
    def ident(self):
        return (self.rule, self.path, self.key)

    def render(self):
        return "%s %s:%d [%s] %s" % (
            self.rule, self.path, self.line, self.key, self.message)


class SourceFile:
    """One parsed file: source text, AST, and per-line rule disables."""

    def __init__(self, path, relpath):
        self.path = path
        self.relpath = relpath
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        try:
            self.tree = ast.parse(self.source, filename=relpath)
        except SyntaxError as exc:
            self.tree = None
            self.syntax_error = exc
        self.disabled = {}  # lineno -> set of rule ids
        for i, line in enumerate(self.lines, 1):
            m = _DISABLE_RE.search(line)
            if m:
                self.disabled[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}

    def is_disabled(self, rule, line):
        return rule in self.disabled.get(line, ())


class Project:
    """The scanned file set plus the repo root (for reading docs and
    registry files that live outside the scanned paths)."""

    def __init__(self, root, files):
        self.root = root
        self.files = files  # relpath -> SourceFile

    def read_doc(self, name):
        """Text of a root-level doc file ('' when absent)."""
        p = os.path.join(self.root, name)
        if not os.path.exists(p):
            return ""
        with open(p, "r", encoding="utf-8", errors="replace") as f:
            return f.read()


def collect(paths, root):
    """Expand ``paths`` (files or directories, relative to ``root``)
    into a Project."""
    files = {}
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in sorted(dirnames)
                               if not d.startswith(".")
                               and d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        fp = os.path.join(dirpath, fn)
                        rel = os.path.relpath(fp, root).replace(os.sep, "/")
                        files[rel] = SourceFile(fp, rel)
        elif full.endswith(".py") and os.path.exists(full):
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            files[rel] = SourceFile(full, rel)
    return Project(root, files)


def load_baseline(path):
    """[{rule, path, key, why}, ...]; missing file -> empty."""
    if not path or not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("suppressions", [])
    for e in entries:
        for field in ("rule", "path", "key", "why"):
            if field not in e:
                raise ValueError(
                    "baseline entry missing %r: %r" % (field, e))
    return entries


def run(paths, root, baseline_path=DEFAULT_BASELINE, rules=None):
    """Run all rules. Returns (findings, suppressed, unused_baseline)
    where ``findings`` are the non-suppressed ones."""
    from . import locks, registry, hygiene

    project = collect(paths, root)
    all_rules = rules or (locks.check, registry.check, hygiene.check)
    raw = []
    for sf in project.files.values():
        if sf.tree is None:
            raw.append(Finding(
                "L000", sf.relpath, sf.syntax_error.lineno or 0,
                "syntax-error", "file does not parse: %s" % sf.syntax_error))
    for rule in all_rules:
        raw.extend(rule(project))
    # inline disables + dedupe (one finding per (rule,path,key,line))
    visible, seen = [], set()
    for f in raw:
        sf = project.files.get(f.path)
        if sf is not None and sf.is_disabled(f.rule, f.line):
            continue
        if (f.ident, f.line) in seen:
            continue
        seen.add((f.ident, f.line))
        visible.append(f)
    # baseline
    entries = load_baseline(baseline_path)
    suppress = {(e["rule"], e["path"], e["key"]): e for e in entries}
    used = set()
    findings, suppressed = [], []
    for f in visible:
        if f.ident in suppress:
            used.add(f.ident)
            suppressed.append(f)
        else:
            findings.append(f)
    unused = [e for e in entries
              if (e["rule"], e["path"], e["key"]) not in used]
    findings.sort(key=lambda f: (f.rule, f.path, f.line, f.key))
    return findings, suppressed, unused


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="mxlint",
        description="mxnet-tpu codebase linter (rules L001-L004; see "
                    "TOOLING.md)")
    ap.add_argument("paths", nargs="+",
                    help="files or directories to scan (repo-relative)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: cwd)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression baseline JSON (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root or os.getcwd())
    baseline = None if args.no_baseline else args.baseline
    findings, suppressed, unused = run(args.paths, root,
                                       baseline_path=baseline)
    for f in findings:
        print(f.render())
    if suppressed:
        print("mxlint: %d finding(s) suppressed by baseline" %
              len(suppressed), file=sys.stderr)
    for e in unused:
        print("mxlint: warning: unused baseline entry %s %s [%s]" %
              (e["rule"], e["path"], e["key"]), file=sys.stderr)
    if findings:
        print("mxlint: %d finding(s)" % len(findings), file=sys.stderr)
        return 1
    print("mxlint: clean (%d file(s) scanned)" % len(
        collect(args.paths, root).files), file=sys.stderr)
    return 0
