"""L001 (static lock-order cycles) + L002 (blocking calls under a held
lock). Both share one lexical lock-region analysis:

* a *lock node* is identified by the class that owns the attribute
  (``DynamicBatcher._cond``) — resolved through one hop of
  ``self.x = ClassName(...)`` attribute-type inference — or by the
  module for module-level locks (``mxnet_tpu/engine.py::_pending_lock``);
* ``with <lockish>:`` items open a region; nesting records an
  acquisition-order edge (nearest enclosing holder -> new lock);
* one interprocedural hop: a call to a method whose body acquires locks
  adds edges from the current holder to those locks;
* nested ``def``/``lambda`` bodies are analyzed with an EMPTY held set
  (closures run later, not necessarily under the enclosing lock).

Lockish = the terminal name matches ``lock|cond|quiesce|mutex``
(case-insensitive), which covers ``_lock``, ``_cond``, ``_quiesce``,
``_slock``, ``_TRACE_LOCK``, ``_pending_lock`` etc.
"""
from __future__ import annotations

import ast
import re

from .engine import Finding

_LOCKISH = re.compile(r"lock|cond|quiesce|mutex", re.I)

_BLOCKING_SYNC_ATTRS = ("asnumpy", "wait_to_read", "block_until_ready")


def _is_lockish(name):
    return bool(_LOCKISH.search(name))


def _terminal(expr):
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


class _FileIndex:
    """Per-file symbol info: classes, their attr types, functions."""

    def __init__(self, sf):
        self.sf = sf
        self.attr_type = {}   # (classname, attr) -> type name
        self.functions = []   # (classname|None, funcname, node)
        if sf.tree is None:
            return
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                self._index_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append((None, node.name, node))

    def _index_class(self, cls):
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self.functions.append((cls.name, node.name, node))
            for stmt in ast.walk(node):
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Attribute)
                        and isinstance(stmt.targets[0].value, ast.Name)
                        and stmt.targets[0].value.id == "self"
                        and isinstance(stmt.value, ast.Call)):
                    callee = _terminal(stmt.value.func)
                    if callee and callee[:1].isupper():
                        self.attr_type[(cls.name, stmt.targets[0].attr)] \
                            = callee


class _Analysis:
    def __init__(self, project):
        self.project = project
        self.indexes = {rel: _FileIndex(sf)
                        for rel, sf in project.files.items()}
        # (classname|module, funcname) -> set of lock keys acquired
        self.fn_locks = {}
        # (classname|module, funcname) -> [(kind, line)] blocking ops
        # performed OUTSIDE any lock region of their own (they become
        # blocking-under-lock when a caller holds a lock — one hop)
        self.fn_blocking = {}
        # (a_key, b_key) -> (path, line, via)
        self.edges = {}
        self.findings = []

    # -- lock-node resolution -------------------------------------------
    def resolve(self, expr, rel, classname):
        """Lock-node key for a lockish ``with`` context expr, or None."""
        term = _terminal(expr)
        if term is None or not _is_lockish(term):
            return None
        if isinstance(expr, ast.Name):
            return "%s::%s" % (rel, term)
        # attribute chain
        parts = []
        cur = expr
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        parts.reverse()
        if isinstance(cur, ast.Name) and cur.id == "self" and classname:
            # self.a.b...._lock: resolve first hop through attr types
            idx = self.indexes[rel]
            owner = classname
            for hop in parts[:-1]:
                owner_t = idx.attr_type.get((owner, hop))
                if owner_t is None:
                    owner = "%s.%s" % (owner, hop)
                else:
                    owner = owner_t
                    # allow the next hop to resolve in the owning class's
                    # file too (cross-module): merge is implicit since
                    # attr_type is per-file; fall back to dotted name
            return "%s.%s" % (owner, parts[-1])
        if isinstance(cur, ast.Name):
            return "%s::%s.%s" % (rel, cur.id, ".".join(parts))
        return None

    def _attr_type_any(self, classname, attr):
        for idx in self.indexes.values():
            t = idx.attr_type.get((classname, attr))
            if t is not None:
                return t
        return None

    # -- pass 1: per-function acquired-lock sets ------------------------
    def build_fn_locks(self):
        for rel, idx in self.indexes.items():
            for classname, fname, node in idx.functions:
                acquired = set()
                for stmt in ast.walk(node):
                    if isinstance(stmt, (ast.With, ast.AsyncWith)):
                        for item in stmt.items:
                            key = self.resolve(item.context_expr, rel,
                                               classname)
                            if key:
                                acquired.add(key)
                owner = classname or rel
                self.fn_locks.setdefault((owner, fname), set()).update(
                    acquired)
                blocking = self._unlocked_blocking_ops(node.body)
                if blocking:
                    self.fn_blocking.setdefault(
                        (owner, fname), []).extend(blocking)

    def _unlocked_blocking_ops(self, stmts):
        """Blocking ops in these statements that are NOT inside a
        lockish ``with`` of their own (those are flagged in place)."""
        out = []
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                if any(_is_lockish(_terminal(it.context_expr) or "")
                       for it in stmt.items):
                    continue  # its own region: analyzed lexically
                out.extend(self._unlocked_blocking_ops(stmt.body))
                continue
            for call in self._iter_calls(stmt):
                term = _terminal(call.func)
                if term == "sleep":
                    out.append(("sleep", call.lineno))
                elif term == "result" and isinstance(call.func,
                                                     ast.Attribute) \
                        and not self._zero_timeout(call):
                    out.append(("future-result", call.lineno))
                elif term in ("set_result", "set_exception") \
                        and isinstance(call.func, ast.Attribute):
                    out.append(("future-settle", call.lineno))
                elif term in _BLOCKING_SYNC_ATTRS:
                    out.append(("device-sync", call.lineno))
            for body in self._child_bodies(stmt):
                out.extend(self._unlocked_blocking_ops(body))
        return out

    # -- pass 2: lexical walk with a held stack -------------------------
    def analyze_all(self):
        for rel, idx in self.indexes.items():
            for classname, fname, node in idx.functions:
                self._walk_stmts(node.body, [], rel, classname, fname)

    def _walk_stmts(self, stmts, held, rel, classname, fname):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closure body runs later: fresh held set, and it is
                # already registered as its own function when at class/
                # module level; nested defs get analyzed here
                self._walk_stmts(stmt.body, [], rel, classname,
                                 "%s.%s" % (fname, stmt.name))
                continue
            if held:
                self._scan_blocking(stmt, held, rel, classname, fname)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in stmt.items:
                    key = self.resolve(item.context_expr, rel, classname)
                    if key:
                        if held and key != held[-1][0]:
                            self._edge(held[-1][0], key, rel,
                                       stmt.lineno, via="with")
                        acquired.append((key, stmt.lineno))
                self._walk_stmts(stmt.body, held + acquired, rel,
                                 classname, fname)
                continue
            for body in self._child_bodies(stmt):
                self._walk_stmts(body, held, rel, classname, fname)
        # interprocedural hop: calls made while holding a lock
        # (handled inside _scan_blocking to share the call walk)

    @staticmethod
    def _child_bodies(stmt):
        for field in ("body", "orelse", "finalbody"):
            b = getattr(stmt, field, None)
            if b:
                yield b
        for h in getattr(stmt, "handlers", ()) or ():
            yield h.body

    def _iter_exprs(self, node):
        """Expression nodes belonging to this statement only: nested
        functions and child statements are pruned (child statements are
        visited by _walk_stmts with the right held set)."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if n is not node and isinstance(n, ast.stmt):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _iter_calls(self, node):
        for n in self._iter_exprs(node):
            if isinstance(n, ast.Call):
                yield n

    def _scan_blocking(self, stmt, held, rel, classname, fname):
        where = "%s.%s" % (classname, fname) if classname else fname
        holder = held[-1][0]
        for node in self._iter_exprs(stmt):
            # ._data loads force/inspect the device buffer — a sync
            # hazard when the array is pending (ISSUE: device syncs
            # under a held lock)
            if isinstance(node, ast.Attribute) and node.attr == "_data" \
                    and isinstance(node.ctx, ast.Load):
                self._l002(rel, node.lineno, "data-sync:%s" % where,
                           "._data access while holding %s" % holder)
        for call in self._iter_calls(stmt):
            func = call.func
            term = _terminal(func)
            if term is None:
                continue
            line = call.lineno
            # ---- L002: blocking calls ------------------------------
            if term == "sleep":
                self._l002(rel, line, "sleep:%s" % where,
                           "time.sleep() while holding %s" % holder)
            elif term == "result" and isinstance(func, ast.Attribute):
                if not self._zero_timeout(call):
                    self._l002(rel, line, "future-result:%s" % where,
                               "Future.result() while holding %s"
                               % holder)
            elif term == "join" and isinstance(func, ast.Attribute) \
                    and isinstance(stmt, ast.Expr) and stmt.value is call:
                self._l002(rel, line, "join:%s" % where,
                           "Thread.join() while holding %s" % holder)
            elif term in _BLOCKING_SYNC_ATTRS or term in ("wait_all",
                                                          "waitall"):
                self._l002(rel, line, "device-sync:%s:%s" % (term, where),
                           "device sync %s() while holding %s"
                           % (term, holder))
            elif term in ("set_result", "set_exception") \
                    and isinstance(func, ast.Attribute):
                self._l002(rel, line, "future-settle:%s" % where,
                           "future %s() while holding %s — done-"
                           "callbacks run under the lock" % (term, holder))
            elif term == "wait" and isinstance(func, ast.Attribute):
                key = self.resolve(func.value, rel, classname)
                held_keys = [k for k, _l in held]
                if key is not None and key in held_keys \
                        and len(held_keys) > 1:
                    others = [k for k in held_keys if k != key]
                    self._l002(rel, line, "wait-under-lock:%s" % where,
                               "Condition.wait(%s) while holding %s"
                               % (key, ", ".join(others)))
            # ---- one-hop interprocedural: edges + blocking ---------
            callee = self._callee_owner(func, rel, classname)
            if callee is not None:
                for lock in sorted(self.fn_locks.get(callee, ())):
                    held_keys = [k for k, _l in held]
                    if lock not in held_keys and lock != holder:
                        self._edge(holder, lock, rel, line,
                                   via="call %s.%s" % callee)
                for kind, _bline in self.fn_blocking.get(callee, ()):
                    self._l002(
                        rel, line,
                        "via-%s:%s->%s.%s" % (kind, where,
                                              callee[0], callee[1]),
                        "%s.%s() performs a %s and is called here "
                        "while holding %s" % (callee[0], callee[1],
                                              kind, holder))

    @staticmethod
    def _zero_timeout(call):
        for kw in call.keywords:
            if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value == 0:
                return True
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value == 0:
            return True
        return False

    def _callee_owner(self, func, rel, classname):
        """(owner, methodname) for self.m(...), self.x.m(...), or a
        module-level f(...) — None when unresolvable."""
        if isinstance(func, ast.Name):
            key = (rel, func.id)
            return key if key in self.fn_locks else None
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and classname:
                key = (classname, func.attr)
                return key if key in self.fn_locks else None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and classname:
                t = self._attr_type_any_local(rel, classname, base.attr)
                if t is not None:
                    key = (t, func.attr)
                    return key if key in self.fn_locks else None
        return None

    def _attr_type_any_local(self, rel, classname, attr):
        t = self.indexes[rel].attr_type.get((classname, attr))
        if t is not None:
            return t
        return self._attr_type_any(classname, attr)

    def _l002(self, rel, line, key, message):
        self.findings.append(Finding("L002", rel, line, key, message))

    def _edge(self, a, b, rel, line, via):
        if a == b:
            return
        self.edges.setdefault((a, b), (rel, line, via))

    # -- cycle reporting -------------------------------------------------
    def report_cycles(self):
        adj = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            nodes = sorted(scc)
            involved = [(e, meta) for e, meta in self.edges.items()
                        if e[0] in scc and e[1] in scc]
            rel, line, _via = involved[0][1]
            detail = "; ".join(
                "%s->%s (%s:%d via %s)" % (a, b, r, ln, v)
                for (a, b), (r, ln, v) in sorted(involved))
            self.findings.append(Finding(
                "L001", rel, line, "cycle:%s" % "->".join(nodes),
                "lock-order cycle between {%s}: %s"
                % (", ".join(nodes), detail)))


def _sccs(adj):
    """Tarjan SCCs (iterative) over a {node: set(node)} digraph."""
    index = {}
    low = {}
    onstack = set()
    stack = []
    out = []
    counter = [0]
    nodes = set(adj)
    for vs in adj.values():
        nodes |= vs

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        onstack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in onstack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = set()
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.add(w)
                    if w == v:
                        break
                out.append(scc)
    return out


def check(project):
    an = _Analysis(project)
    an.build_fn_locks()
    an.analyze_all()
    an.report_cycles()
    return an.findings
