"""L003 registry drift: the string-keyed registries (config flags,
fault sites, counter names) must agree with their read sites and docs.

Checks:

* ``unregistered-read:NAME`` — a direct ``os.environ`` /
  ``os.getenv`` read of an ``MXNET_*`` variable inside ``mxnet_tpu/``
  that is not registered in ``config.py``;
* ``unknown-flag:NAME`` — ``config.get("NAME")`` / ``is_set`` of an
  unregistered name (would ``KeyError`` at runtime);
* ``dead-flag:NAME`` — a registered flag no scanned file reads;
* ``undocumented-flag:NAME`` — a registered flag with no knob row in
  any doc file (README.md / SERVING.md / RESILIENCE.md /
  OBSERVABILITY.md / PERF.md / TRAINING.md / TOOLING.md);
* ``undeclared-site:SITE`` — a fired fault site missing from
  ``resilience/faults.py`` ``KNOWN_SITES``;
* ``undocumented-site:SITE`` — a fired fault site absent from
  RESILIENCE.md;
* ``bad-counter:NAME`` — an ``incr_counter``/``counters.incr`` name
  that is not namespaced, or whose namespace ``export.snapshot()``
  does not merge;
* ``export-namespace-drift:NS`` — the rule's namespace allow-list no
  longer matches ``profiler/export.py`` (keeps this rule honest).
"""
from __future__ import annotations

import ast
import re

from .engine import Finding

CONFIG_FILE = "mxnet_tpu/config.py"
FAULTS_FILE = "mxnet_tpu/resilience/faults.py"
EXPORT_FILE = "mxnet_tpu/profiler/export.py"

DOC_FILES = ("README.md", "SERVING.md", "RESILIENCE.md",
             "OBSERVABILITY.md", "PERF.md", "TRAINING.md", "TOOLING.md")

# namespaces profiler/export.snapshot() merges into one surface; each
# must literally appear (as "<ns>.") in export.py or we flag drift
COUNTER_NAMESPACES = ("profiler", "engine", "cachedop", "kvstore",
                      "resilience", "serve", "fleet", "recorder", "trace",
                      "registry", "slo", "attribution", "io")

_FLAG_TOKEN = re.compile(r"^MXNET_[A-Z0-9_]+$")


def _str_arg(call, i=0):
    if len(call.args) > i and isinstance(call.args[i], ast.Constant) \
            and isinstance(call.args[i].value, str):
        return call.args[i].value
    return None


def _fstr_prefix(call, i=0):
    """Literal prefix of an f-string first arg ('' when none)."""
    if len(call.args) > i and isinstance(call.args[i], ast.JoinedStr):
        vals = call.args[i].values
        if vals and isinstance(vals[0], ast.Constant):
            return str(vals[0].value)
        return ""
    return None


def _terminal(func):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _receiver(func):
    if isinstance(func, ast.Attribute):
        if isinstance(func.value, ast.Name):
            return func.value.id
        if isinstance(func.value, ast.Attribute):
            return func.value.attr
    return None


def check(project):
    findings = []

    # -- registered flags -------------------------------------------------
    registered = {}   # name -> lineno
    cfg = project.files.get(CONFIG_FILE)
    if cfg is not None and cfg.tree is not None:
        for node in ast.walk(cfg.tree):
            if isinstance(node, ast.Call) \
                    and _terminal(node.func) == "register_flag":
                name = _str_arg(node)
                if name:
                    registered[name] = node.lineno

    # -- declared fault sites --------------------------------------------
    declared_sites = set()
    faults = project.files.get(FAULTS_FILE)
    if faults is not None and faults.tree is not None:
        for node in faults.tree.body:
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "KNOWN_SITES"
                            for t in node.targets) \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        declared_sites.add(elt.value)

    # -- walk all scanned files ------------------------------------------
    env_reads = {}     # NAME -> (path, line) first read via os.environ
    flag_reads = set()  # names read via config.get/is_set or environ
    fired_sites = {}   # SITE -> (path, line)
    counter_uses = {}  # NAME-or-prefix -> (path, line, is_prefix)

    for rel, sf in project.files.items():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            # any exact MXNET_* string literal outside config.py counts
            # as a *use* for dead-flag purposes: reads route through
            # helpers (`_flag("MXNET_X")`, `_env_policy("MXNET_X")`),
            # and launcher-side environ writes are the producer half
            if rel != CONFIG_FILE and isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _FLAG_TOKEN.match(node.value):
                flag_reads.add(node.value)
            # environ["X"] subscript reads
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and _terminal(node.value) == "environ" \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str) \
                    and node.slice.value.startswith("MXNET_"):
                env_reads.setdefault(node.slice.value, (rel, node.lineno))
                flag_reads.add(node.slice.value)
            if not isinstance(node, ast.Call):
                continue
            term = _terminal(node.func)
            recv = _receiver(node.func)
            arg = _str_arg(node)
            if term in ("get", "getenv") and recv in ("environ", "os",
                                                      "_os"):
                if arg and arg.startswith("MXNET_"):
                    env_reads.setdefault(arg, (rel, node.lineno))
                    flag_reads.add(arg)
            elif term in ("get", "is_set") \
                    and recv in ("config", "_cfg", "_config", "cfg"):
                if arg and arg.startswith("MXNET_"):
                    flag_reads.add(arg)
                    if arg not in registered:
                        findings.append(Finding(
                            "L003", rel, node.lineno,
                            "unknown-flag:%s" % arg,
                            "config.%s(%r): flag is not registered in "
                            "config.py" % (term, arg)))
            elif term == "fault_point" or (
                    term == "check" and recv
                    and ("fault" in recv.lower() or recv == "plan")):
                if arg and ":" in arg:
                    fired_sites.setdefault(arg, (rel, node.lineno))
            elif term in ("incr_counter", "set_counter") \
                    or (term == "incr" and recv
                        and "counter" in recv.lower()):
                if arg is not None:
                    counter_uses.setdefault(
                        arg, (rel, node.lineno, False))
                else:
                    pre = _fstr_prefix(node)
                    if pre is not None:
                        counter_uses.setdefault(
                            pre, (rel, node.lineno, True))

    # -- flag checks ------------------------------------------------------
    for name, (rel, line) in sorted(env_reads.items()):
        if rel.startswith("mxnet_tpu/") and name not in registered:
            findings.append(Finding(
                "L003", rel, line, "unregistered-read:%s" % name,
                "os.environ read of %s which is not registered in "
                "config.py" % name))
    docs = "\n".join(project.read_doc(d) for d in DOC_FILES)
    for name, line in sorted(registered.items()):
        if name not in flag_reads:
            findings.append(Finding(
                "L003", CONFIG_FILE, line, "dead-flag:%s" % name,
                "registered flag %s is never read in the scanned tree"
                % name))
        if name not in docs:
            findings.append(Finding(
                "L003", CONFIG_FILE, line, "undocumented-flag:%s" % name,
                "registered flag %s has no knob row in any of %s"
                % (name, ", ".join(DOC_FILES))))

    # -- fault-site checks ------------------------------------------------
    resilience_md = project.read_doc("RESILIENCE.md")
    for site, (rel, line) in sorted(fired_sites.items()):
        if site not in declared_sites:
            findings.append(Finding(
                "L003", rel, line, "undeclared-site:%s" % site,
                "fault site %r fired here is not in faults.KNOWN_SITES"
                % site))
        elif site not in resilience_md:
            findings.append(Finding(
                "L003", rel, line, "undocumented-site:%s" % site,
                "fault site %r is not documented in RESILIENCE.md"
                % site))

    # -- counter-namespace checks ----------------------------------------
    export_src = ""
    exp = project.files.get(EXPORT_FILE)
    if exp is not None:
        export_src = exp.source
    for ns in COUNTER_NAMESPACES:
        if export_src and ("%s." % ns) not in export_src:
            findings.append(Finding(
                "L003", EXPORT_FILE, 1,
                "export-namespace-drift:%s" % ns,
                "namespace %r in the mxlint allow-list no longer "
                "appears in export.py" % ns))
    for name, (rel, line, is_prefix) in sorted(counter_uses.items()):
        ns = name.split(".", 1)[0] if "." in name else None
        if ns is None and is_prefix:
            continue  # f-string with dynamic namespace: give up
        if ns is None or ns not in COUNTER_NAMESPACES:
            findings.append(Finding(
                "L003", rel, line, "bad-counter:%s" % (name or "<dyn>"),
                "counter %r is not namespaced under one of %s (the "
                "namespaces profiler/export.snapshot() merges)"
                % (name, "/".join(COUNTER_NAMESPACES))))
    return findings
