"""L004 thread hygiene:

* ``baseexcept:<func>[#n]`` — an ``except BaseException`` handler whose
  body neither re-raises nor stores/uses the caught exception. Die-kind
  fault injection raises ``SimulatedWorkerDeath`` (a ``BaseException``
  precisely so ``except Exception`` can't swallow it); a silent
  ``except BaseException: pass`` defeats that design. The
  store-and-rethrow pattern (``box["exc"] = exc``) is allowed.
* ``unnamed-thread:<func>`` — a ``threading.Thread`` created in a
  ``mxnet_tpu/`` module that never calls
  ``profiler.register_thread_name`` (flight-recorder entries and trace
  lanes from that thread would be anonymous).
* ``daemon-liveness:<func>`` — a ``daemon=True`` thread in a module
  with no liveness probe at all (no ``is_alive``/``alive()`` check, no
  ``join``, no ``register_health_provider``): a silently-dead daemon
  loop is invisible until its work stops happening.
"""
from __future__ import annotations

import ast

from .engine import Finding

_LIVENESS_MARKERS = ("is_alive", ".alive(", "register_health_provider",
                     ".join(")


def _terminal(func):
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _enclosing_functions(tree):
    """Yield (qualname, node) for every function, with class prefix."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, prefix + child.name + ".")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield prefix + child.name, child
                yield from walk(child, prefix + child.name + ".")
            else:
                yield from walk(child, prefix)
    yield "<module>", tree
    yield from walk(tree, "")


def _scope_nodes(node):
    """Nodes belonging to this scope only: nested function bodies are
    pruned (they are their own scopes), class bodies are not."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _handler_ok(handler):
    """True when the BaseException handler re-raises or stores/uses
    the caught exception (the deliberate rethrow-later pattern)."""
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name:
            return True
    return False


def check(project):
    findings = []
    for rel, sf in project.files.items():
        if sf.tree is None or not rel.startswith("mxnet_tpu/"):
            continue
        has_thread_name = "register_thread_name" in sf.source
        has_liveness = any(m in sf.source for m in _LIVENESS_MARKERS)
        for qualname, fn in _enclosing_functions(sf.tree):
            n_be = 0
            for node in _scope_nodes(fn):
                if isinstance(node, ast.ExceptHandler) \
                        and node.type is not None \
                        and _terminal(node.type) == "BaseException":
                    if not _handler_ok(node):
                        suffix = "" if n_be == 0 else "#%d" % n_be
                        n_be += 1
                        findings.append(Finding(
                            "L004", rel, node.lineno,
                            "baseexcept:%s%s" % (qualname, suffix),
                            "except BaseException that neither re-raises "
                            "nor stores the exception would swallow "
                            "die-kind fault injection"))
                elif isinstance(node, ast.Call) \
                        and _terminal(node.func) == "Thread":
                    if not has_thread_name:
                        findings.append(Finding(
                            "L004", rel, node.lineno,
                            "unnamed-thread:%s" % qualname,
                            "thread created in a module that never calls "
                            "profiler.register_thread_name — its "
                            "recorder/trace entries will be anonymous"))
                    daemon = any(
                        kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in node.keywords)
                    if daemon and not has_liveness:
                        findings.append(Finding(
                            "L004", rel, node.lineno,
                            "daemon-liveness:%s" % qualname,
                            "daemon thread in a module with no liveness "
                            "probe (is_alive/alive()/join/health "
                            "provider) — a dead loop here is invisible"))
    return findings
