#!/usr/bin/env python
"""Image -> RecordIO packer (reference ``tools/im2rec.py``).

Two modes, matching the reference CLI:
* ``--list``: walk an image directory and write a ``.lst`` file
  (``index\\tlabel\\trelative-path`` lines).
* pack (default): read a ``.lst`` file and write ``.rec`` + ``.idx``
  (``MXIndexedRecordIO``), each record an ``IRHeader`` + encoded image
  bytes, loadable by ``ImageRecordDataset`` / ``ImageRecordIter``.

PIL replaces the reference's OpenCV for decode/resize/re-encode;
``--pass-through`` stores the original file bytes untouched.
"""
from __future__ import annotations

import argparse
import io
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(args):
    """Write .lst: one `index<TAB>label<TAB>relpath` line per image, one
    label per subdirectory (reference make_list behavior)."""
    root = args.root
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    entries = []
    if classes:
        for c in classes:
            for dirpath, _, files in os.walk(os.path.join(root, c)):
                for f in sorted(files):
                    if os.path.splitext(f)[1].lower() in _EXTS:
                        rel = os.path.relpath(os.path.join(dirpath, f), root)
                        entries.append((label_of[c], rel))
    else:  # flat directory: label 0
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                entries.append((0, f))
    if args.shuffle:
        random.Random(args.seed).shuffle(entries)
    lst = args.prefix + ".lst"
    with open(lst, "w") as fh:
        for i, (label, rel) in enumerate(entries):
            fh.write(f"{i}\t{label}\t{rel}\n")
    print(f"wrote {len(entries)} entries to {lst}")
    return 0


def read_list(path):
    with open(path) as fh:
        for line in fh:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(args):
    from mxnet_tpu import recordio

    rec = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                     args.prefix + ".rec", "w")
    n = 0
    for idx, labels, rel in read_list(args.prefix + ".lst"):
        path = os.path.join(args.root, rel)
        with open(path, "rb") as fh:
            raw = fh.read()
        if not args.pass_through:
            from PIL import Image

            img = Image.open(io.BytesIO(raw)).convert("RGB")
            if args.resize:
                w, h = img.size
                s = args.resize / min(w, h)
                img = img.resize((max(1, round(w * s)),
                                  max(1, round(h * s))))
            buf = io.BytesIO()
            img.save(buf, format="JPEG", quality=args.quality)
            raw = buf.getvalue()
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, raw))
        n += 1
    rec.close()
    print(f"packed {n} records into {args.prefix}.rec")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="im2rec: image folder -> .lst / RecordIO "
                    "(reference tools/im2rec.py parity)")
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate the .lst file instead of packing")
    ap.add_argument("--shuffle", action="store_true", default=True)
    ap.add_argument("--no-shuffle", dest="shuffle", action="store_false")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter side to this many pixels")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--pass-through", action="store_true",
                    help="store original bytes without re-encoding")
    args = ap.parse_args(argv)
    if args.list:
        return make_list(args)
    return pack(args)


if __name__ == "__main__":
    sys.exit(main())
