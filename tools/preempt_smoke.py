#!/usr/bin/env python
"""Preemption smoke: interrupt mid-epoch, resume, assert exact parity.

The tier-1 opt-in leg behind ``TIER1_PREEMPT=1`` in
``tools/run_tier1.sh`` — the end-to-end proof that preemption-safe
training actually is safe:

1. **Reference**: an uninterrupted single-epoch run over a shuffled
   :class:`~mxnet_tpu.io.NDArrayIter` records its final parameters and
   the exact sequence of sample indices it consumed.
2. **Interrupted**: the identical run with a
   :class:`~mxnet_tpu.resilience.preemption.PreemptionHandler` over a
   :class:`~mxnet_tpu.resilience.checkpoint.ResilientCheckpointHandler`
   (``async_write=True``, iterator state in every save) is preempted at
   a seeded mid-epoch batch via the deterministic ``preempt:deliver``
   fault site — the SIGTERM-equivalent with no real signal. Training
   finishes the delivered batch, force-saves through the async writer,
   fences the commit, and stops.
3. **Resumed**: a FRESH process-equivalent (new net with different init,
   new iterator with a different shuffle draw) resumes from the
   checkpoint and finishes the epoch.

Asserted: the interrupted+resumed halves consume the epoch's sample
sequence exactly once (the resumed iterator continues the interrupted
permutation, not its own fresh draw), the final parameters are
**bitwise** equal to the uninterrupted reference, and the preemption
counters recorded one delivery + one force-save.

Usage::

    python tools/preempt_smoke.py              # one-seed tier-1 smoke
    python tools/preempt_smoke.py --seeds 4    # sweep
"""
import argparse
import os
import sys
import warnings

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_BATCHES = 12
BATCH = 4
DIM = 3


def _fresh_estimator(seed):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.gluon.contrib.estimator import Estimator

    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.Dense(1)
    net.initialize()
    net(mnp.ones((BATCH, DIM)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    est = Estimator(net, gluon.loss.L2Loss(), trainer=tr,
                    train_metrics=[gluon.metric.MAE()])
    return est


def _make_iter(data_seed, shuffle_seed):
    """Shuffled NDArrayIter over a fixed dataset; the permutation comes
    from the global RNG at construction, seeded explicitly so reference
    and interrupted runs draw the SAME epoch order while the resumed run
    can prove it restored the interrupted order rather than its own."""
    import mxnet_tpu as mx

    rng = np.random.RandomState(data_seed)
    x = rng.randn(N_BATCHES * BATCH, DIM).astype("float32")
    y = rng.randn(N_BATCHES * BATCH, 1).astype("float32")
    np.random.seed(shuffle_seed)
    return mx.io.NDArrayIter(x, y, batch_size=BATCH, shuffle=True)


def _stream(it, consumed):
    """Adapt a DataIter to the estimator's (data, label) batch stream,
    recording the source-sample indices of every batch served."""
    while True:
        try:
            b = it.next()
        except StopIteration:
            return
        consumed.extend(int(i) for i in b.index)
        yield b.data[0], b.label[0]


def _params_np(est):
    return {k: v.data().asnumpy()
            for k, v in est.net.collect_params().items()}


def run_preempt_smoke(seed=7, say=lambda m: None):
    """Importable one-seed leg; returns ``(violations, row)``."""
    import tempfile

    from mxnet_tpu.resilience import counters, faults
    from mxnet_tpu.resilience import preemption as pre
    from mxnet_tpu.resilience.checkpoint import ResilientCheckpointHandler
    from mxnet_tpu.resilience.preemption import PreemptionHandler

    violations = []
    rng = np.random.RandomState(seed * 31 + 7)
    preempt_batch = int(rng.randint(2, N_BATCHES - 1))
    say(f"preempt at batch {preempt_batch} of {N_BATCHES} (seed {seed})")

    # 1. uninterrupted reference
    ref_consumed = []
    est_ref = _fresh_estimator(seed)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        est_ref.fit(_stream(_make_iter(seed, seed + 5), ref_consumed),
                    batches=N_BATCHES)
    p_ref = _params_np(est_ref)

    # 2. interrupted run: injected preemption mid-epoch, async force-save
    d = tempfile.mkdtemp(prefix="preempt_smoke_")
    pre.clear()
    counters.reset()
    it1 = _make_iter(seed, seed + 5)
    est1 = _fresh_estimator(seed)
    rh = ResilientCheckpointHandler(d, batch_period=None, epoch_period=None,
                                    data_iter=it1, async_write=True)
    ph = PreemptionHandler(ckpt_handler=rh)
    cut_consumed = []
    faults.install_plan({"seed": seed, "rules": [
        {"site": "preempt:deliver", "kind": "preempt",
         "at": [preempt_batch]}]})
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est1.fit(_stream(it1, cut_consumed), batches=N_BATCHES,
                     event_handlers=[rh, ph])
    finally:
        faults.clear_plan()
    if not ph.preempted:
        violations.append("interrupted run was never preempted")
        return violations, {}
    # `at` hit indices are 0-based: at=[k] delivers on the (k+1)-th
    # batch_end, i.e. after k+1 completed batches
    done = preempt_batch + 1
    if len(cut_consumed) != done * BATCH:
        violations.append(
            f"interrupted run consumed {len(cut_consumed)} samples, "
            f"expected {done * BATCH} (stop after the delivered batch)")
    stats = {k: counters.get("resilience." + k)
             for k in ("preemptions", "preempt_saves", "ckpt_async_saves")}
    if stats["preemptions"] != 1 or stats["preempt_saves"] != 1:
        violations.append(f"preemption counters off: {stats}")
    if stats["ckpt_async_saves"] < 1:
        violations.append(
            f"force-save did not go through the async writer: {stats}")
    stall = rh.manager.last_stall_ms

    # 3. resume in a fresh "process": different init, different shuffle
    # draw — everything that matters must come from the checkpoint
    pre.clear()
    it2 = _make_iter(seed, seed + 99)
    est2 = _fresh_estimator(seed + 1000)
    rh2 = ResilientCheckpointHandler(d, batch_period=None,
                                     epoch_period=None, data_iter=it2)
    resume_consumed = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        start = rh2.resume(est2)
        est2.fit(_stream(it2, resume_consumed),
                 batches=N_BATCHES - start, event_handlers=[rh2])
    if start != done:
        violations.append(
            f"resumed at batch {start}, force-save was after {done}")
    p_res = _params_np(est2)

    # parity: exact sample sequence across the cut, bitwise params
    if cut_consumed + resume_consumed != ref_consumed:
        violations.append(
            "sample sequence across the preemption differs from the "
            f"uninterrupted epoch (cut={len(cut_consumed)} "
            f"resumed={len(resume_consumed)} ref={len(ref_consumed)}; "
            "replay, skip, or a fresh shuffle leaked in)")
    if sorted(cut_consumed + resume_consumed) != \
            list(range(N_BATCHES * BATCH)):
        violations.append(
            "epoch sample multiset is not exactly-once after resume")
    for k in p_ref:
        if not np.array_equal(p_ref[k], p_res[k]):
            violations.append(
                f"param {k} differs bitwise from the uninterrupted "
                "reference after resume")
    row = {"seed": seed, "preempt_batch": preempt_batch,
           "resumed_at": start, "stall_ms": stall,
           "param_parity": "bitwise", "data_parity": "exact"}
    say(f"resume parity: params=bitwise samples=exact "
        f"stall={stall if stall is None else round(stall, 3)}ms")
    return violations, row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--seeds", type=int, default=1,
                    help="sweep seed..seed+N-1 (tier-1 smoke: 1)")
    args = ap.parse_args(argv)

    failures = []
    for s in range(args.seed, args.seed + args.seeds):
        say = lambda m: print(f"PREEMPT_SMOKE {m}", flush=True)  # noqa: E731
        violations, row = run_preempt_smoke(seed=s, say=say)
        if violations:
            failures.append((s, violations))
        else:
            print(f"PREEMPT_SMOKE=PASS seed={s} "
                  f"preempt_batch={row['preempt_batch']} "
                  f"stall_ms={row['stall_ms']}")
    if failures:
        for s, v in failures:
            for msg in v:
                print(f"PREEMPT_SMOKE=FAIL seed={s} {msg}")
        return 1
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
