#!/usr/bin/env python
"""Tier-1 serving smoke (tools/run_tier1.sh): spin up an
``InferenceSession`` behind a ``DynamicBatcher``, push 32 concurrent
client requests, and assert the serving SLO surface end to end:

* every request completes with the right answer (vs an unbatched
  reference forward),
* p99 whole-request latency stays under ``SERVE_SMOKE_P99_MS``
  (default 5000 ms — generous for CPU CI, tight enough to catch a
  recompile storm or a wedged flusher),
* zero XLA recompiles after warmup (``assert_no_recompiles``),
* the batcher shuts down cleanly (flusher thread joins, late submits
  are fast-rejected with 503).

With ``--trace-out PATH`` (the ``TIER1_TRACE=1`` pass) the same smoke
runs with request tracing + the flight recorder on, then additionally:

* injects fatal ``serve:execute`` faults until the session breaker
  opens and asserts a non-empty flight-recorder dump whose ring names
  the failing site,
* dumps the chrome trace to PATH for ``tools/trace_check.py``
  (``--expect-lane`` asserts one connected per-request lane there).

With ``--decode-path {baseline,pallas,int8,spec}`` (the
``TIER1_DECODE=1`` pass) the smoke instead exercises one decode rung of
the llama generation stack under concurrent clients:

* 8 threads drive ``generate()`` on a shared Generator (spec =
  SpeculativeGenerator over a 1-layer draft); every thread must get the
  same greedy continuation as an unthreaded reference call,
* zero recompiles across the whole run (``assert_no_recompiles``),
* 503 taxonomy: ``drain()`` makes the next generate fast-reject with
  ``ServiceUnavailable``; ``resume()`` serves again,
* 504 taxonomy: already-passed deadlines retire every row between
  decode steps and land in ``info["deadline_expired"]`` plus the
  ``deadline_expired["decode"]`` metric.

With ``--slo`` (the ``TIER1_SLO=1`` pass) the same healthy 32-client
run executes with a declarative SLO monitor attached to the session
metrics (itl/ttft p99, goodput, error-rate objectives at generous CI
targets): after the run NO objective may be burning, the monitor state
must be ``ok``, and the flight recorder must have produced zero
``slo_burn`` dumps — the guard's false-positive contract on a healthy
service.

With ``--multistep`` (the ``TIER1_MULTISTEP=1`` pass) the smoke drives
the PR-19 device-side multi-step decode loop on a ``ContinuousEngine``:

* 8 concurrent clients on an 8-step super-step engine must get greedy
  output token-identical to the classic one-visit-per-token engine,
* exactly two compiled signatures (chunked prefill + the super-step)
  and zero recompiles across every admit/retire cycle,
* a deadline that expires mid-stream settles as 504
  (``DeadlineExceeded`` with partial tokens) within a bounded wall —
  retirement latency is one super-step, not one request.

With ``--prefix`` (the ``TIER1_PREFIX=1`` pass) the smoke drives the
PR-14 "never redo prior work" stack:

* 8 clients share a 20-token system prompt on a ``ContinuousEngine``
  with the radix prefix cache on: outputs must be token-identical to
  the cache-off run, ``prefix_hit_rate > 0``, zero recompiles, and
  every non-free pool page accounted for by the trie after retirement,
* two ``--prefix-child`` subprocesses warm the same
  ``MXNET_COMPILE_CACHE_DIR``: identical stable signature keys +
  greedy tokens, and the second must replay the lattice entirely from
  disk (``disk_hits > 0, disk_misses == 0``).

Exit status 0 on pass; nonzero with a one-line reason otherwise.
"""
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _trace_epilogue(sess, batcher_cls, runner, x, trace_out):
    """Injected-fault forensics + trace dump (the --trace-out half)."""
    import json

    from mxnet_tpu import profiler
    from mxnet_tpu.profiler import recorder
    from mxnet_tpu.resilience import faults

    faults.install_plan({"rules": [
        {"site": "serve:execute", "kind": "fatal", "times": 8}]})
    try:
        with batcher_cls(runner, max_batch_size=8, timeout_ms=2.0,
                         max_queue=64, metrics=sess.metrics,
                         name="smoke-fault") as fb:
            # sequential submits: each is its own failing batch, so the
            # session breaker sees consecutive failures and trips open
            for _ in range(5):
                try:
                    fb.submit(x).result(timeout=30)
                except Exception:  # noqa: BLE001 (the injected fault)
                    pass
    finally:
        faults.clear_plan()
    dump_path = recorder.last_dump_path()
    if not dump_path or not os.path.exists(dump_path):
        print("SERVE_SMOKE=FAIL injected serve:execute fault left no "
              "flight-recorder dump")
        return 1
    doc = json.load(open(dump_path))
    ring_names = {e.get("name") for e in doc.get("ring", [])}
    if "serve:execute" not in ring_names:
        print(f"SERVE_SMOKE=FAIL flight-recorder dump {dump_path} does "
              f"not name the failing site (ring: {sorted(ring_names)})")
        return 1
    profiler.set_state("stop")
    profiler.core.dump(trace_out)
    print(f"SERVE_SMOKE_TRACE=PASS trace={trace_out} "
          f"flightrec={dump_path} reason={doc.get('reason')}")
    return 0


def main():
    if "--prefix-child" in sys.argv:
        cache_dir = sys.argv[sys.argv.index("--prefix-child") + 1]
        return _run_prefix_child(cache_dir)
    if "--prefix" in sys.argv:
        return _run_prefix()
    if "--multistep" in sys.argv:
        return _run_multistep()
    if "--decode-path" in sys.argv:
        path = sys.argv[sys.argv.index("--decode-path") + 1]
        return _run_decode(path)
    trace_out = None
    if "--trace-out" in sys.argv:
        trace_out = sys.argv[sys.argv.index("--trace-out") + 1]
        os.environ.setdefault("MXNET_TRACE", "1")
        os.environ.setdefault("MXNET_FLIGHT_RECORDER", "1")
    if "--slo" in sys.argv:
        os.environ.setdefault("MXNET_FLIGHT_RECORDER", "1")
        return _run(trace_out, slo=True)
    return _run(trace_out)


def _run_prefix_child(cache_dir):
    """Subprocess half of --prefix: enable the persistent compile cache
    BEFORE any build, warm a ContinuousEngine over the standard tiny
    lattice, decode one request, and print a greppable JSON line with
    the disk hit/miss counters, the stable signature keys, and the
    tokens — the parent asserts process 2 compiles nothing new and both
    processes agree on keys + output."""
    import json

    import mxnet_tpu as mx
    from mxnet_tpu import cachedop, compile_cache
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import ContinuousEngine

    compile_cache.enable(cache_dir)
    mx.random.seed(0)
    model = get_llama("llama_tiny_test")
    model.initialize()
    eng = ContinuousEngine(model, max_seq=64, num_slots=4, page_size=8,
                           prefill_chunk=8, decode_path="baseline",
                           name="smoke_prefix_child")
    eng.start()
    try:
        out = eng.submit([5, 9, 2, 4], max_new_tokens=6).result(60)
    finally:
        eng.close()
    keys = sorted({k for op in list(cachedop._instances)
                   for k in op.signature_keys()})
    print("SERVE_SMOKE_PREFIX_CHILD=" + json.dumps({
        "disk_hits": compile_cache.disk_hits(),
        "disk_misses": compile_cache.disk_misses(),
        "keys": keys, "tokens": out["tokens"]}), flush=True)
    return 0


def _run_prefix():
    import json
    import subprocess
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import ContinuousEngine

    mx.random.seed(0)
    model = get_llama("llama_tiny_test")
    model.initialize()

    system = list(range(3, 23))  # 20-token shared system prompt
    prompts = [system + [30 + i, 40 + i, 50 + i] for i in range(8)]

    def run_engine(prefix_on):
        eng = ContinuousEngine(model, max_seq=64, num_slots=4, page_size=8,
                               prefill_chunk=8, decode_path="baseline",
                               prefix_cache=prefix_on,
                               name=f"smoke_prefix_{int(bool(prefix_on))}")
        eng.start()
        try:
            # first client retires (donating its prefix to the trie)
            # before the concurrent wave arrives
            first = eng.submit(prompts[0], max_new_tokens=8).result(60)
            futs = [eng.submit(p, max_new_tokens=8) for p in prompts[1:]]
            outs = [first["tokens"]] + [f.result(60)["tokens"]
                                        for f in futs]
            eng.assert_no_recompiles()
            return outs, eng.metrics.snapshot(), eng.stats()
        finally:
            eng.close()

    ref, _, _ = run_engine(False)
    got, snap, stats = run_engine(True)
    if got != ref:
        print(f"SERVE_SMOKE_PREFIX=FAIL prefix-cache-on outputs diverged "
              f"from cache-off: {got} != {ref}")
        return 1
    if not snap["prefix_hit_rate"] > 0:
        print(f"SERVE_SMOKE_PREFIX=FAIL shared system prompt produced no "
              f"trie hits (snapshot={snap})")
        return 1
    if stats["pool"]["pages_used"] != stats["prefix"]["pages_held"]:
        print(f"SERVE_SMOKE_PREFIX=FAIL retired engine leaks pages "
              f"beyond the trie: pool={stats['pool']} "
              f"prefix={stats['prefix']}")
        return 1

    # disk half: two fresh processes over one cache dir — the second
    # must warm entirely from disk (no new compiles) with identical
    # stable signature keys and identical greedy output
    child = [sys.executable, os.path.abspath(__file__)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    docs = []
    with tempfile.TemporaryDirectory() as d:
        for i in (1, 2):
            proc = subprocess.run(
                child + ["--prefix-child", d], env=env,
                capture_output=True, text=True, timeout=600)
            line = [ln for ln in proc.stdout.splitlines()
                    if ln.startswith("SERVE_SMOKE_PREFIX_CHILD=")]
            if proc.returncode != 0 or not line:
                print(f"SERVE_SMOKE_PREFIX=FAIL child {i} rc="
                      f"{proc.returncode}\n{proc.stdout}\n{proc.stderr}")
                return 1
            docs.append(json.loads(
                line[0].split("=", 1)[1]))
    p1, p2 = docs
    if p1["keys"] != p2["keys"] or not p1["keys"]:
        print(f"SERVE_SMOKE_PREFIX=FAIL stable signature keys differ "
              f"across processes: {p1['keys']} != {p2['keys']}")
        return 1
    if p1["tokens"] != p2["tokens"]:
        print(f"SERVE_SMOKE_PREFIX=FAIL disk-warmed process output "
              f"diverged: {p2['tokens']} != {p1['tokens']}")
        return 1
    if p1["disk_misses"] == 0:
        print(f"SERVE_SMOKE_PREFIX=FAIL cold process reported no disk "
              f"misses (doc={p1})")
        return 1
    if not (p2["disk_hits"] > 0 and p2["disk_misses"] == 0):
        print(f"SERVE_SMOKE_PREFIX=FAIL warm process did not replay the "
              f"lattice from disk: hits={p2['disk_hits']} "
              f"misses={p2['disk_misses']}")
        return 1
    print(f"SERVE_SMOKE_PREFIX=PASS clients={len(prompts)} "
          f"hit_rate={snap['prefix_hit_rate']:.3f} "
          f"tokens_skipped={snap['prefix_tokens_skipped']} "
          f"signatures={len(p1['keys'])} "
          f"cold_disk_misses={p1['disk_misses']} "
          f"warm_disk_hits={p2['disk_hits']}")
    return 0


def _run_multistep():
    import time

    import mxnet_tpu as mx  # noqa: F401  (framework init)
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import ContinuousEngine, DeadlineExceeded

    mx.random.seed(0)
    model = get_llama("llama_tiny_test")
    model.initialize()
    prompts = [[5 + i, 9, 2, (3 * i) % 11 + 1] for i in range(8)]

    # reference: classic one-visit-per-token engine, sequential requests
    ref_eng = ContinuousEngine(model, max_seq=64, num_slots=4, page_size=8,
                               prefill_chunk=8, decode_path="baseline",
                               multistep=False, name="smoke_ms_ref")
    ref_eng.start()
    try:
        refs = [ref_eng.submit(p, max_new_tokens=12).result(120)["tokens"]
                for p in prompts]
    finally:
        ref_eng.close()

    eng = ContinuousEngine(model, max_seq=64, num_slots=4, page_size=8,
                           prefill_chunk=8, decode_path="baseline",
                           multistep=True, decode_steps=8, name="smoke_ms")
    eng.start()
    try:
        outs = [None] * len(prompts)
        errors = []

        def client(i):
            try:
                outs[i] = eng.submit(
                    prompts[i], max_new_tokens=12).result(120)["tokens"]
            except Exception as exc:  # noqa: BLE001
                errors.append((i, exc))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        if errors:
            i, exc = errors[0]
            print(f"SERVE_SMOKE_MULTISTEP=FAIL client {i}: "
                  f"{type(exc).__name__}: {exc}")
            return 1
        for i, o in enumerate(outs):
            if o != refs[i]:
                print(f"SERVE_SMOKE_MULTISTEP=FAIL client {i} diverged "
                      f"from the classic engine: {o} != {refs[i]}")
                return 1
        try:
            eng.assert_no_recompiles()
        except Exception as exc:  # noqa: BLE001
            print(f"SERVE_SMOKE_MULTISTEP=FAIL {exc}")
            return 1
        n_super = eng._msession.signature_count()
        if n_super != 1:
            print(f"SERVE_SMOKE_MULTISTEP=FAIL expected exactly one "
                  f"super-step signature, got {n_super}")
            return 1

        # 504: a deadline that expires mid-stream settles as
        # DeadlineExceeded with partial tokens, and retirement is
        # bounded by one super-step -- not by the request's remaining
        # budget.  Budget half of a measured 12-token wall so expiry
        # lands mid-decode on any host speed.
        t0 = time.monotonic()
        eng.submit(prompts[0], max_new_tokens=12).result(120)
        t12 = time.monotonic() - t0
        budget_ms = max(20.0, t12 * 1e3 * 0.5)
        t0 = time.monotonic()
        fut = eng.submit(prompts[1], max_new_tokens=48,
                         deadline_ms=budget_ms)
        try:
            fut.result(120)
            print("SERVE_SMOKE_MULTISTEP=FAIL mid-stream deadline did "
                  "not settle as 504")
            return 1
        except DeadlineExceeded as exc:
            settled_s = time.monotonic() - t0
            partial = list(getattr(exc, "partial", []))
        if len(partial) >= 48:
            print(f"SERVE_SMOKE_MULTISTEP=FAIL expired request ran to "
                  f"completion ({len(partial)} tokens)")
            return 1
        slack_s = budget_ms / 1e3 + max(2.0, 2.0 * t12)
        if settled_s > slack_s:
            print(f"SERVE_SMOKE_MULTISTEP=FAIL 504 settled {settled_s:.2f}s "
                  f"after submit (> {slack_s:.2f}s): retirement not "
                  f"bounded by one super-step")
            return 1
        snap = eng.metrics.snapshot()
        if not snap["deadline_expired"].get("decode"):
            print(f"SERVE_SMOKE_MULTISTEP=FAIL no decode-stage "
                  f"deadline_expired metric "
                  f"({dict(snap['deadline_expired'])})")
            return 1
        stats = eng.stats()
        print(f"SERVE_SMOKE_MULTISTEP=PASS clients={len(prompts)} "
              f"decode_steps={stats['decode_steps']} "
              f"super_signatures={n_super} "
              f"partial_504={len(partial)} "
              f"deadline_expired={dict(snap['deadline_expired'])}")
        return 0
    finally:
        eng.close()


def _run_decode(path):
    import time

    import mxnet_tpu as mx  # noqa: F401  (framework init)
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import (Generator, ServiceUnavailable,
                                 SpeculativeGenerator)

    mx.random.seed(0)
    model = get_llama("llama_tiny_test")
    model.initialize()
    if path == "spec":
        draft = get_llama("llama_tiny_test", num_layers=1)
        draft.initialize()
        gen = SpeculativeGenerator(model, draft, k=2, max_seq=48,
                                   batch_buckets=(2,), prompt_buckets=(8,),
                                   name="smoke_spec")
        sess = gen.target.session
    else:
        gen = Generator(model, max_seq=48, batch_buckets=(2,),
                        prompt_buckets=(8,), name=f"smoke_{path}",
                        decode_path=path)
        sess = gen.session
    gen.warmup()
    prompts = [[5, 9, 2], [7, 3, 3, 1]]
    ref, _ = gen.generate(prompts, max_new_tokens=8)

    n_clients = 8
    outs = [None] * n_clients
    errors = []

    def client(i):
        try:
            outs[i], _ = gen.generate(prompts, max_new_tokens=8)
        except Exception as exc:  # noqa: BLE001
            errors.append((i, exc))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        i, exc = errors[0]
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} client {i}: "
              f"{type(exc).__name__}: {exc}")
        return 1
    for i, o in enumerate(outs):
        if o != ref:
            print(f"SERVE_SMOKE_DECODE=FAIL path={path} client {i} "
                  f"diverged from the unthreaded reference: {o} != {ref}")
            return 1
    try:
        gen.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} {exc}")
        return 1

    # 503 taxonomy: a drained session fast-rejects, resume() reopens
    sess.drain()
    try:
        gen.generate(prompts, max_new_tokens=4)
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} drained session "
              f"accepted a generate()")
        return 1
    except ServiceUnavailable:
        pass
    finally:
        sess.resume()
    again, _ = gen.generate(prompts, max_new_tokens=8)
    if again != ref:
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} post-resume output "
              f"diverged: {again} != {ref}")
        return 1

    # 504 taxonomy: already-passed deadlines retire every row and count
    # as decode-stage deadline_expired
    _, info = gen.generate(prompts, max_new_tokens=8,
                           deadlines=time.monotonic() - 1.0)
    expired = info["deadline_expired"]
    snap = gen.metrics.snapshot()
    if sorted(expired) != [0, 1] or not snap["deadline_expired"].get(
            "decode"):
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} past deadlines did "
              f"not expire rows (info={expired}, "
              f"metric={snap['deadline_expired']})")
        return 1
    print(f"SERVE_SMOKE_DECODE=PASS path={path} "
          f"decode_path={snap['decode_path']} clients={n_clients} "
          f"kv_cache_bytes={snap['kv_cache_bytes']} "
          f"deadline_expired={dict(snap['deadline_expired'])}")
    return 0


def _run(trace_out=None, slo=False):
    import mxnet_tpu as mx  # noqa: F401  (framework init)
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import numpy as mnp
    from mxnet_tpu.serve import (DynamicBatcher, InferenceSession,
                                 ServiceUnavailable)

    if trace_out is not None:
        from mxnet_tpu import profiler
        profiler.set_state("run")

    # MXNET_METRICS_PORT=<p> started the /metrics endpoint at import
    # (=0 binds an ephemeral port); surface where it actually landed so
    # the harness driving this smoke can scrape it.
    from mxnet_tpu.profiler import export as _export
    mport = _export.server_port()
    if mport is not None:
        print(f"SERVE_SMOKE metrics endpoint: "
              f"http://127.0.0.1:{mport}/metrics", flush=True)

    p99_bound_ms = float(os.environ.get("SERVE_SMOKE_P99_MS", "5000"))
    n_clients = 32

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize()

    sess = InferenceSession(net, batch_buckets=(1, 2, 4, 8), name="smoke")
    monitor = None
    if slo:
        from mxnet_tpu.profiler import recorder as _recorder
        from mxnet_tpu.profiler.slo import SLO, SLOMonitor
        _recorder.reset()
        monitor = SLOMonitor("smoke", [
            SLO("itl_p99_ms", 500.0),
            SLO("ttft_p99_ms", 2000.0),
            SLO("goodput", 0.95),
            SLO("error_rate", 0.05),
        ])
        monitor.attach(sess.metrics)
    sess.warmup(np.zeros((1, 16), np.float32))

    def runner(payloads):
        out = sess.predict(np.stack(payloads)).asnumpy()
        return [out[i] for i in range(len(payloads))]

    rng = np.random.RandomState(7)
    xs = [rng.randn(16).astype(np.float32) for _ in range(n_clients)]
    results = [None] * n_clients
    errors = []

    with DynamicBatcher(runner, max_batch_size=8, timeout_ms=5.0,
                        max_queue=64, metrics=sess.metrics,
                        name="smoke") as batcher:
        def client(i):
            try:
                results[i] = batcher.submit(xs[i]).result(timeout=60)
            except Exception as exc:  # noqa: BLE001
                errors.append((i, exc))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
    # context exit = clean shutdown; verify the flusher actually died
    if batcher._thread.is_alive():
        print("SERVE_SMOKE=FAIL flusher thread survived close()")
        return 1
    try:
        batcher.submit(xs[0])
        print("SERVE_SMOKE=FAIL late submit after close() was accepted")
        return 1
    except ServiceUnavailable:
        pass

    if errors:
        i, exc = errors[0]
        print(f"SERVE_SMOKE=FAIL request {i}: {type(exc).__name__}: {exc}")
        return 1
    with autograd.predict_mode():
        ref = net(mnp.array(np.stack(xs))).asnumpy()
    got = np.stack(results)
    if not np.allclose(got, ref, rtol=1e-5, atol=1e-6):
        print(f"SERVE_SMOKE=FAIL wrong results "
              f"(maxdiff {np.abs(got - ref).max():.3g})")
        return 1
    try:
        sess.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        print(f"SERVE_SMOKE=FAIL {exc}")
        return 1
    snap = sess.metrics.snapshot()
    if snap["p99_ms"] > p99_bound_ms:
        print(f"SERVE_SMOKE=FAIL p99 {snap['p99_ms']:.1f}ms "
              f"> bound {p99_bound_ms}ms")
        return 1
    print(f"SERVE_SMOKE=PASS requests={snap['requests']} "
          f"p50={snap['p50_ms']:.1f}ms p99={snap['p99_ms']:.1f}ms "
          f"occupancy={snap['batch_occupancy']:.2f} "
          f"signatures={sess.signature_count()} "
          f"serve_hits={sess.cache_stats()['serve_hits']}")
    if monitor is not None:
        from mxnet_tpu.profiler import recorder as _recorder
        rows = monitor.evaluate()
        burning = [r["metric"] for r in rows if r["burning"]]
        health = monitor.health()
        if burning or health["state"] != "ok" or monitor.burns > 0:
            print(f"SLO_SMOKE=FAIL healthy run tripped the burn guard: "
                  f"burning={burning} health={health} rows={rows}")
            return 1
        if _recorder.dump_count() > 0:
            print(f"SLO_SMOKE=FAIL healthy run produced "
                  f"{_recorder.dump_count()} flight-recorder dump(s): "
                  f"{_recorder.last_dump_path()}")
            return 1
        print(f"SLO_SMOKE=PASS objectives={len(rows)} state="
              f"{health['state']} burns={monitor.burns} "
              f"events={[r['events_slow'] for r in rows]}")
    if trace_out is not None:
        return _trace_epilogue(sess, DynamicBatcher, runner, xs[0],
                               trace_out)
    return 0


if __name__ == "__main__":
    rc = main()
    try:
        from mxnet_tpu.resilience.lockdep import smoke_gate
    except ImportError:
        pass
    else:
        rc = smoke_gate(rc)
    sys.exit(rc)
