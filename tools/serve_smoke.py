#!/usr/bin/env python
"""Tier-1 serving smoke (tools/run_tier1.sh): spin up an
``InferenceSession`` behind a ``DynamicBatcher``, push 32 concurrent
client requests, and assert the serving SLO surface end to end:

* every request completes with the right answer (vs an unbatched
  reference forward),
* p99 whole-request latency stays under ``SERVE_SMOKE_P99_MS``
  (default 5000 ms — generous for CPU CI, tight enough to catch a
  recompile storm or a wedged flusher),
* zero XLA recompiles after warmup (``assert_no_recompiles``),
* the batcher shuts down cleanly (flusher thread joins, late submits
  are fast-rejected with 503).

With ``--trace-out PATH`` (the ``TIER1_TRACE=1`` pass) the same smoke
runs with request tracing + the flight recorder on, then additionally:

* injects fatal ``serve:execute`` faults until the session breaker
  opens and asserts a non-empty flight-recorder dump whose ring names
  the failing site,
* dumps the chrome trace to PATH for ``tools/trace_check.py``
  (``--expect-lane`` asserts one connected per-request lane there).

With ``--decode-path {baseline,pallas,int8,spec}`` (the
``TIER1_DECODE=1`` pass) the smoke instead exercises one decode rung of
the llama generation stack under concurrent clients:

* 8 threads drive ``generate()`` on a shared Generator (spec =
  SpeculativeGenerator over a 1-layer draft); every thread must get the
  same greedy continuation as an unthreaded reference call,
* zero recompiles across the whole run (``assert_no_recompiles``),
* 503 taxonomy: ``drain()`` makes the next generate fast-reject with
  ``ServiceUnavailable``; ``resume()`` serves again,
* 504 taxonomy: already-passed deadlines retire every row between
  decode steps and land in ``info["deadline_expired"]`` plus the
  ``deadline_expired["decode"]`` metric.

Exit status 0 on pass; nonzero with a one-line reason otherwise.
"""
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _trace_epilogue(sess, batcher_cls, runner, x, trace_out):
    """Injected-fault forensics + trace dump (the --trace-out half)."""
    import json

    from mxnet_tpu import profiler
    from mxnet_tpu.profiler import recorder
    from mxnet_tpu.resilience import faults

    faults.install_plan({"rules": [
        {"site": "serve:execute", "kind": "fatal", "times": 8}]})
    try:
        with batcher_cls(runner, max_batch_size=8, timeout_ms=2.0,
                         max_queue=64, metrics=sess.metrics,
                         name="smoke-fault") as fb:
            # sequential submits: each is its own failing batch, so the
            # session breaker sees consecutive failures and trips open
            for _ in range(5):
                try:
                    fb.submit(x).result(timeout=30)
                except Exception:  # noqa: BLE001 (the injected fault)
                    pass
    finally:
        faults.clear_plan()
    dump_path = recorder.last_dump_path()
    if not dump_path or not os.path.exists(dump_path):
        print("SERVE_SMOKE=FAIL injected serve:execute fault left no "
              "flight-recorder dump")
        return 1
    doc = json.load(open(dump_path))
    ring_names = {e.get("name") for e in doc.get("ring", [])}
    if "serve:execute" not in ring_names:
        print(f"SERVE_SMOKE=FAIL flight-recorder dump {dump_path} does "
              f"not name the failing site (ring: {sorted(ring_names)})")
        return 1
    profiler.set_state("stop")
    profiler.core.dump(trace_out)
    print(f"SERVE_SMOKE_TRACE=PASS trace={trace_out} "
          f"flightrec={dump_path} reason={doc.get('reason')}")
    return 0


def main():
    if "--decode-path" in sys.argv:
        path = sys.argv[sys.argv.index("--decode-path") + 1]
        return _run_decode(path)
    trace_out = None
    if "--trace-out" in sys.argv:
        trace_out = sys.argv[sys.argv.index("--trace-out") + 1]
        os.environ.setdefault("MXNET_TRACE", "1")
        os.environ.setdefault("MXNET_FLIGHT_RECORDER", "1")
    return _run(trace_out)


def _run_decode(path):
    import time

    import mxnet_tpu as mx  # noqa: F401  (framework init)
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import (Generator, ServiceUnavailable,
                                 SpeculativeGenerator)

    mx.random.seed(0)
    model = get_llama("llama_tiny_test")
    model.initialize()
    if path == "spec":
        draft = get_llama("llama_tiny_test", num_layers=1)
        draft.initialize()
        gen = SpeculativeGenerator(model, draft, k=2, max_seq=48,
                                   batch_buckets=(2,), prompt_buckets=(8,),
                                   name="smoke_spec")
        sess = gen.target.session
    else:
        gen = Generator(model, max_seq=48, batch_buckets=(2,),
                        prompt_buckets=(8,), name=f"smoke_{path}",
                        decode_path=path)
        sess = gen.session
    gen.warmup()
    prompts = [[5, 9, 2], [7, 3, 3, 1]]
    ref, _ = gen.generate(prompts, max_new_tokens=8)

    n_clients = 8
    outs = [None] * n_clients
    errors = []

    def client(i):
        try:
            outs[i], _ = gen.generate(prompts, max_new_tokens=8)
        except Exception as exc:  # noqa: BLE001
            errors.append((i, exc))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        i, exc = errors[0]
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} client {i}: "
              f"{type(exc).__name__}: {exc}")
        return 1
    for i, o in enumerate(outs):
        if o != ref:
            print(f"SERVE_SMOKE_DECODE=FAIL path={path} client {i} "
                  f"diverged from the unthreaded reference: {o} != {ref}")
            return 1
    try:
        gen.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} {exc}")
        return 1

    # 503 taxonomy: a drained session fast-rejects, resume() reopens
    sess.drain()
    try:
        gen.generate(prompts, max_new_tokens=4)
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} drained session "
              f"accepted a generate()")
        return 1
    except ServiceUnavailable:
        pass
    finally:
        sess.resume()
    again, _ = gen.generate(prompts, max_new_tokens=8)
    if again != ref:
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} post-resume output "
              f"diverged: {again} != {ref}")
        return 1

    # 504 taxonomy: already-passed deadlines retire every row and count
    # as decode-stage deadline_expired
    _, info = gen.generate(prompts, max_new_tokens=8,
                           deadlines=time.monotonic() - 1.0)
    expired = info["deadline_expired"]
    snap = gen.metrics.snapshot()
    if sorted(expired) != [0, 1] or not snap["deadline_expired"].get(
            "decode"):
        print(f"SERVE_SMOKE_DECODE=FAIL path={path} past deadlines did "
              f"not expire rows (info={expired}, "
              f"metric={snap['deadline_expired']})")
        return 1
    print(f"SERVE_SMOKE_DECODE=PASS path={path} "
          f"decode_path={snap['decode_path']} clients={n_clients} "
          f"kv_cache_bytes={snap['kv_cache_bytes']} "
          f"deadline_expired={dict(snap['deadline_expired'])}")
    return 0


def _run(trace_out=None):
    import mxnet_tpu as mx  # noqa: F401  (framework init)
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import numpy as mnp
    from mxnet_tpu.serve import (DynamicBatcher, InferenceSession,
                                 ServiceUnavailable)

    if trace_out is not None:
        from mxnet_tpu import profiler
        profiler.set_state("run")

    # MXNET_METRICS_PORT=<p> started the /metrics endpoint at import
    # (=0 binds an ephemeral port); surface where it actually landed so
    # the harness driving this smoke can scrape it.
    from mxnet_tpu.profiler import export as _export
    mport = _export.server_port()
    if mport is not None:
        print(f"SERVE_SMOKE metrics endpoint: "
              f"http://127.0.0.1:{mport}/metrics", flush=True)

    p99_bound_ms = float(os.environ.get("SERVE_SMOKE_P99_MS", "5000"))
    n_clients = 32

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize()

    sess = InferenceSession(net, batch_buckets=(1, 2, 4, 8), name="smoke")
    sess.warmup(np.zeros((1, 16), np.float32))

    def runner(payloads):
        out = sess.predict(np.stack(payloads)).asnumpy()
        return [out[i] for i in range(len(payloads))]

    rng = np.random.RandomState(7)
    xs = [rng.randn(16).astype(np.float32) for _ in range(n_clients)]
    results = [None] * n_clients
    errors = []

    with DynamicBatcher(runner, max_batch_size=8, timeout_ms=5.0,
                        max_queue=64, metrics=sess.metrics,
                        name="smoke") as batcher:
        def client(i):
            try:
                results[i] = batcher.submit(xs[i]).result(timeout=60)
            except Exception as exc:  # noqa: BLE001
                errors.append((i, exc))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
    # context exit = clean shutdown; verify the flusher actually died
    if batcher._thread.is_alive():
        print("SERVE_SMOKE=FAIL flusher thread survived close()")
        return 1
    try:
        batcher.submit(xs[0])
        print("SERVE_SMOKE=FAIL late submit after close() was accepted")
        return 1
    except ServiceUnavailable:
        pass

    if errors:
        i, exc = errors[0]
        print(f"SERVE_SMOKE=FAIL request {i}: {type(exc).__name__}: {exc}")
        return 1
    with autograd.predict_mode():
        ref = net(mnp.array(np.stack(xs))).asnumpy()
    got = np.stack(results)
    if not np.allclose(got, ref, rtol=1e-5, atol=1e-6):
        print(f"SERVE_SMOKE=FAIL wrong results "
              f"(maxdiff {np.abs(got - ref).max():.3g})")
        return 1
    try:
        sess.assert_no_recompiles()
    except Exception as exc:  # noqa: BLE001
        print(f"SERVE_SMOKE=FAIL {exc}")
        return 1
    snap = sess.metrics.snapshot()
    if snap["p99_ms"] > p99_bound_ms:
        print(f"SERVE_SMOKE=FAIL p99 {snap['p99_ms']:.1f}ms "
              f"> bound {p99_bound_ms}ms")
        return 1
    print(f"SERVE_SMOKE=PASS requests={snap['requests']} "
          f"p50={snap['p50_ms']:.1f}ms p99={snap['p99_ms']:.1f}ms "
          f"occupancy={snap['batch_occupancy']:.2f} "
          f"signatures={sess.signature_count()} "
          f"serve_hits={sess.cache_stats()['serve_hits']}")
    if trace_out is not None:
        return _trace_epilogue(sess, DynamicBatcher, runner, xs[0],
                               trace_out)
    return 0


if __name__ == "__main__":
    rc = main()
    try:
        from mxnet_tpu.resilience.lockdep import smoke_gate
    except ImportError:
        pass
    else:
        rc = smoke_gate(rc)
    sys.exit(rc)
