#!/usr/bin/env python
"""Validate a dumped chrome://tracing JSON (tools/run_tier1.sh
``TIER1_TRACE`` pass, and importable from tests).

Checks, in order:

* the file is valid JSON with a non-empty ``traceEvents`` list;
* every event carries a ``ph`` and (except metadata) a numeric,
  non-negative ``ts``; complete ('X') events carry ``name``/``dur``/
  ``pid``/``tid`` with ``dur >= 0``;
* per-thread 'X' end-times are monotonic (events append in completion
  order — a violation means a torn dump);
* async begin/end match: per (cat, id, name) the 'b' and 'e' counts are
  equal and, walked in ts order, the open-depth never goes negative;
* no orphan flow ids: every flow id has exactly one start ('s') and one
  finish ('f'), with ``f.ts >= s.ts``;
* ``--expect-lane``: at least one async id forms a connected per-request
  lane — >= min-span distinct span names across >= min-threads threads
  (the serving submit -> flush -> settle handoff made visible);
* ``--expect-attribution``: the trace contains ``serve::decode_step``
  spans and EVERY one carries the four critical-path ledger args
  (``host_ms``/``dispatch_ms``/``device_ms``/``wait_ms``) whose sum
  reconciles with the span's own wall time within 10% (floor 0.05 ms)
  — the profiler/attribution contract that the phase partition covers
  the iteration exactly. Multi-step super-step spans (PR 19) carry a
  ``tokens`` arg on top: it must be a non-negative number bounded by
  ``steps x live`` (one visit cannot emit more tokens than iterations
  times live rows), and the PASS line reports the window's
  ``tokens_per_visit`` so the amortization shows up in CI logs.

Exit 0 on pass; 1 with one reason line per failure.
"""
import argparse
import collections
import json
import sys

_LEDGER_KEYS = ("host_ms", "dispatch_ms", "device_ms", "wait_ms")


def check_trace(path, expect_lane=False, min_spans=3, min_threads=2,
                expect_attribution=False, stats=None):
    """Returns a list of failure strings (empty = pass). ``stats``, if a
    dict, receives summary readouts (``decode_spans``,
    ``decode_tokens``, ``tokens_per_visit``) for the caller's report."""
    failures = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable trace JSON: {e}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]

    by_tid_end = collections.defaultdict(list)
    async_evs = collections.defaultdict(list)   # (cat,id,name) -> [(ts,ph)]
    async_by_id = collections.defaultdict(list)  # id -> events
    flow_s = collections.defaultdict(list)
    flow_f = collections.defaultdict(list)
    decode_evs = collections.defaultdict(list)  # (cat,id,name) -> (ts,ph,ev)

    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph is None:
            failures.append(f"event #{i} has no ph: {ev}")
            continue
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            failures.append(f"event #{i} ({ph} {ev.get('name')!r}) has "
                            f"bad ts {ts!r}")
            continue
        if ph == "X":
            missing = {"name", "dur", "pid", "tid"} - set(ev)
            if missing:
                failures.append(f"X event #{i} missing {sorted(missing)}")
                continue
            if not isinstance(ev["dur"], (int, float)) or ev["dur"] < 0:
                failures.append(f"X event #{i} ({ev['name']!r}) has bad "
                                f"dur {ev['dur']!r}")
                continue
            by_tid_end[ev["tid"]].append((i, ts + ev["dur"], ev["name"]))
        elif ph in ("b", "e"):
            key = (ev.get("cat"), ev.get("id"), ev.get("name"))
            if key[1] is None:
                failures.append(f"async event #{i} ({ev.get('name')!r}) "
                                "has no id")
                continue
            async_evs[key].append((ts, ph))
            async_by_id[key[1]].append(ev)
            if ev.get("name") == "serve::decode_step":
                decode_evs[key].append((ts, ph, ev))
        elif ph == "s":
            flow_s[ev.get("id")].append(ts)
        elif ph == "f":
            flow_f[ev.get("id")].append(ts)

    # per-thread monotonic completion order ('X' events append at range
    # end; ts rounds to 3 decimals -> tolerate that quantum)
    for tid, rows in by_tid_end.items():
        last_end, last_i, last_name = -1.0, None, None
        for i, end, name in rows:
            if end < last_end - 0.002:
                failures.append(
                    f"tid {tid}: X event #{i} ({name!r}) ends at "
                    f"{end:.3f}us, before #{last_i} ({last_name!r}) at "
                    f"{last_end:.3f}us — non-monotonic dump")
                break
            last_end, last_i, last_name = end, i, name

    # matched async begin/end
    for (cat, aid, name), rows in sorted(async_evs.items(),
                                         key=lambda kv: str(kv[0])):
        n_b = sum(1 for _, ph in rows if ph == "b")
        n_e = len(rows) - n_b
        if n_b != n_e:
            failures.append(f"async {cat}/{aid}/{name}: {n_b} begin vs "
                            f"{n_e} end events")
            continue
        depth = 0
        for _, ph in sorted(rows):
            depth += 1 if ph == "b" else -1
            if depth < 0:
                failures.append(f"async {cat}/{aid}/{name}: end before "
                                "begin (ts order)")
                break

    # orphan flow ids
    for fid in sorted(set(flow_s) | set(flow_f), key=str):
        ns, nf = len(flow_s.get(fid, ())), len(flow_f.get(fid, ()))
        if ns != 1 or nf != 1:
            failures.append(f"flow id {fid}: {ns} start / {nf} finish "
                            "(want exactly 1/1)")
        elif flow_f[fid][0] < flow_s[fid][0]:
            failures.append(f"flow id {fid}: finish at "
                            f"{flow_f[fid][0]:.3f}us precedes start at "
                            f"{flow_s[fid][0]:.3f}us")

    if expect_lane:
        best = (0, 0, None)
        for aid, evs in async_by_id.items():
            names = {e.get("name") for e in evs}
            tids = {e.get("tid") for e in evs}
            if len(names) >= min_spans and len(tids) >= min_threads:
                best = (len(names), len(tids), aid)
                break
            if (len(names), len(tids)) > best[:2]:
                best = (len(names), len(tids), aid)
        if best[0] < min_spans or best[1] < min_threads:
            failures.append(
                f"no connected per-request lane: best async id "
                f"{best[2]!r} has {best[0]} span name(s) across "
                f"{best[1]} thread(s); want >= {min_spans} spans on "
                f">= {min_threads} threads")

    if expect_attribution:
        n_spans, n_bad, n_tokens = 0, 0, 0
        for key, rows in sorted(decode_evs.items(),
                                key=lambda kv: str(kv[0])):
            # pair b/e in ts order (LIFO — spans of one name on one lane
            # never interleave, but be defensive about nesting)
            stack = []
            for ts, ph, ev in sorted(rows, key=lambda r: (r[0],
                                                          r[1] == "b")):
                if ph == "b":
                    stack.append((ts, ev))
                    continue
                if not stack:
                    continue  # mismatch already reported above
                t0, b_ev = stack.pop()
                n_spans += 1
                args = b_ev.get("args") or {}
                missing = [k for k in _LEDGER_KEYS if not isinstance(
                    args.get(k), (int, float))]
                if missing:
                    n_bad += 1
                    if n_bad <= 5:
                        failures.append(
                            f"decode_step span (id {key[1]}) at "
                            f"{t0:.3f}us missing ledger args {missing}")
                    continue
                wall_ms = (ts - t0) / 1e3  # ts is in us
                ledger_ms = sum(args[k] for k in _LEDGER_KEYS)
                tol = max(0.10 * wall_ms, 0.05)
                if abs(ledger_ms - wall_ms) > tol:
                    n_bad += 1
                    if n_bad <= 5:
                        failures.append(
                            f"decode_step span (id {key[1]}) at "
                            f"{t0:.3f}us: ledger sum {ledger_ms:.3f}ms "
                            f"vs wall {wall_ms:.3f}ms (tol {tol:.3f}ms)")
                    continue
                # multi-step super-step accounting (PR 19): a span that
                # carries ``tokens`` settled that many tokens in ONE
                # host visit — non-negative, and never more than
                # steps x live (iterations times live rows). Single-step
                # spans carry no tokens arg and default to 1.
                toks = args.get("tokens")
                if toks is None:
                    n_tokens += 1
                    continue
                if not isinstance(toks, (int, float)) or toks < 0:
                    n_bad += 1
                    if n_bad <= 5:
                        failures.append(
                            f"decode_step span (id {key[1]}) at "
                            f"{t0:.3f}us has bad tokens arg {toks!r}")
                    continue
                n_tokens += int(toks)
                steps = args.get("steps")
                live = args.get("live")
                if isinstance(steps, (int, float)) \
                        and isinstance(live, (int, float)) \
                        and toks > steps * live:
                    n_bad += 1
                    if n_bad <= 5:
                        failures.append(
                            f"decode_step span (id {key[1]}) at "
                            f"{t0:.3f}us emitted {toks} tokens from "
                            f"{steps} steps x {live} live rows — "
                            "over-emission is impossible")
        if n_spans == 0:
            failures.append("no serve::decode_step spans found "
                            "(attribution expected)")
        elif n_bad > 5:
            failures.append(f"... and {n_bad - 5} more decode_step "
                            "attribution mismatches")
        if isinstance(stats, dict):
            stats["decode_spans"] = n_spans
            stats["decode_tokens"] = n_tokens
            stats["tokens_per_visit"] = (round(n_tokens / n_spans, 3)
                                         if n_spans else 0.0)
    return failures


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="chrome trace JSON to validate")
    ap.add_argument("--expect-lane", action="store_true",
                    help="require one connected per-request async lane")
    ap.add_argument("--min-spans", type=int, default=3)
    ap.add_argument("--min-threads", type=int, default=2)
    ap.add_argument("--expect-attribution", action="store_true",
                    help="require serve::decode_step spans carrying the "
                         "four ledger args summing to the span wall")
    args = ap.parse_args(argv)
    stats = {}
    failures = check_trace(args.trace, expect_lane=args.expect_lane,
                           min_spans=args.min_spans,
                           min_threads=args.min_threads,
                           expect_attribution=args.expect_attribution,
                           stats=stats)
    if failures:
        for f in failures:
            print(f"TRACE_CHECK=FAIL {f}")
        return 1
    extra = ""
    if stats.get("decode_spans"):
        extra = (f" decode_spans={stats['decode_spans']}"
                 f" tokens_per_visit={stats['tokens_per_visit']}")
    print(f"TRACE_CHECK=PASS {args.trace}{extra}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
