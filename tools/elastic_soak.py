#!/usr/bin/env python
"""Elastic-training soak: seeded kill/lag/corrupt plans through a dp8
training loop, asserting the closed recovery taxonomy.

What it drives (mirroring ``tools/chaos_soak.py`` for the serving stack):
a data-parallel training run on the virtual 8-device mesh — per-replica
forward/backward through ``ElasticBatchProcessor``, compiled-collective
gradient allreduce through ``dist_tpu``, per-replica fused optimizer
updates — under three seeded fault legs:

1. **kill** (``chip_loss`` at ``kvstore:allreduce``): a device group dies
   mid-step; ``MXNET_ELASTIC=1`` classifies it as :class:`MeshDegraded`,
   the :class:`ElasticTrainingHandler` shrinks dp8 → dp4 and resumes
   from its own sharded checkpoint. Asserted: exactly one restart, one
   step lost, the finished dp4 run matches — **bitwise** — a reference
   dp4 run continued from the same checkpoint over the same remaining
   batches (no silent divergence), and recovery wall-time is reported
   (the MULTICHIP kill-and-reshard row).
2. **lag** (``replica_delay`` at ``trainer:replica_step``): one replica
   straggles deterministically; the :class:`StragglerMonitor` must blame
   exactly that replica, and the final parameters must be bitwise equal
   to an undelayed run (a straggler slows the mesh, never changes it).
3. **corrupt** (``param_corrupt`` at ``trainer:param``): one replica's
   parameters silently drift; the :class:`DesyncAuditHandler` must
   detect it within its check cadence, blame the right replica, resync
   it from a peer, and leave every replica fingerprint-identical.

Outcome taxonomy is CLOSED: each leg either completes with its
assertions holding or the soak fails with the violation — no hang (the
run is bounded by construction: no retries on chip loss, watchdogged
collectives) and no silent divergence (every leg ends with a
cross-replica fingerprint agreement check and a finiteness check).

Usage::

    python tools/elastic_soak.py              # one-seed tier-1 smoke
    python tools/elastic_soak.py --seeds 8    # full sweep (-m slow analog)
"""
import argparse
import os
import sys
import time
import warnings

import numpy as np

# env/jax setup happens ONLY on the script path (__main__ below):
# importers (tests via conftest, bench.py on a real TPU) own their
# platform/mesh setup, and mutating JAX_PLATFORMS/XLA_FLAGS at import
# time would silently retarget every later benchmark to CPU.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DP = 8
BATCH = 8
DIM = 3


def _make_batches(n, seed):
    from mxnet_tpu import np as mnp

    rng = np.random.RandomState(seed)
    return [(mnp.array(rng.randn(BATCH, DIM).astype("float32")),
             mnp.array(rng.randn(BATCH, 1).astype("float32")))
            for _ in range(n)]


def _fresh(ctxs, seed):
    """Net + trainer + estimator on an explicit context list, with a
    dist_tpu store on the matching mesh."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.resilience.elastic import ElasticBatchProcessor

    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.Dense(1, in_units=DIM)
    net.initialize(ctx=ctxs)
    mesh = mesh_mod.make_mesh(
        {"dp": len(ctxs)}, devices=[c.jax_device() for c in ctxs])
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=KVStoreDistTPUSync(mesh=mesh))
    est = Estimator(net, gluon.loss.L2Loss(), trainer=tr,
                    train_metrics=[gluon.metric.MAE()],
                    batch_processor=ElasticBatchProcessor())
    return net, tr, est


def _params_np(net):
    return {k: v.data().asnumpy()
            for k, v in net.collect_params().items()}


def _agree_and_finite(tr, violations, leg):
    from mxnet_tpu.resilience.elastic import replica_fingerprints
    from mxnet_tpu.resilience.guardrails import all_finite

    fps = replica_fingerprints(tr._params)
    if len(set(fps)) != 1:
        violations.append(f"{leg}: replicas ended desynced: {fps}")
    if not all_finite([p.data() for p in tr._params]):
        violations.append(f"{leg}: non-finite parameters at end")
    if not all(np.isfinite(v).all() for fp in fps for v in fp):
        violations.append(f"{leg}: non-finite fingerprint: {fps}")


def run_kill_reshard(seed=7, n_batches=12, say=lambda m: None):
    """The kill-and-reshard leg, importable (bench.py's MULTICHIP row):
    returns ``(violations, row)`` where ``row`` carries ``steps_lost``
    and ``recovery_wall_s``."""
    # self-contained (bench.py calls this leg directly): the kvstore
    # reads the flag at construction, so it must be set before _fresh()
    prev_elastic = os.environ.get("MXNET_ELASTIC")
    os.environ["MXNET_ELASTIC"] = "1"
    try:
        return _run_kill_reshard_inner(seed, n_batches, say)
    finally:
        if prev_elastic is None:
            os.environ.pop("MXNET_ELASTIC", None)
        else:
            os.environ["MXNET_ELASTIC"] = prev_elastic


class _ShadowAdvance:
    """BatchEnd handler consuming one batch of a shadow index iterator
    per training batch — runs BEFORE the ElasticTrainingHandler's save
    (priority -2000 < -1400), so each checkpoint's datastate records the
    position the params correspond to. Skips the absorbed (lost) batch:
    its samples rewound with the restore and are re-served on the next
    real batch, keeping applied-sample delivery exactly-once."""

    priority = -2000

    def __init__(self, it, eh=None):
        self.it = it
        self.eh = eh
        self.consumed = []

    def batch_end(self, estimator, *args, **kwargs):
        if self.eh is not None and getattr(self.eh, "_just_restarted",
                                           False):
            return
        b = self.it.next()
        self.consumed.extend(
            int(v) for v in b.data[0].asnumpy().ravel().tolist())


def _make_shadow_advance(it, eh=None):
    from mxnet_tpu.gluon.contrib.estimator.event_handler import BatchEnd

    cls = type("_ShadowAdvanceH", (_ShadowAdvance, BatchEnd), {})
    return cls(it, eh)


def _run_kill_reshard_inner(seed, n_batches, say):
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.resilience import checkpoint as ckpt, faults
    from mxnet_tpu.resilience.elastic import ElasticTrainingHandler

    violations = []
    rng = np.random.RandomState(seed * 131 + 1)
    kill_replica = int(rng.randint(0, DP))
    kill_step = int(rng.randint(2, n_batches - 2))
    # Dense(1) carries 2 reduced params (weight, bias): 2 allreduce
    # calls per step, so hit index 2*k is the first reduce of step k —
    # "killed mid-step", after backward, inside the collective
    kill_hit = 2 * kill_step
    say(f"kill leg: chip_loss replica {kill_replica} during batch "
        f"{kill_step} (seed {seed})")

    m8 = mesh_mod.make_mesh({"dp": DP})
    ctxs8 = mesh_mod.mesh_contexts(m8)
    prev_mesh = mesh_mod.get_mesh()
    batches = _make_batches(n_batches, seed)
    d = tempfile.mkdtemp(prefix="elastic_soak_")
    t0 = time.perf_counter()
    # shadow data iterator: one index per sample, consumed in lockstep
    # with the training batches and checkpointed through the handler's
    # data_iter — the kill leg asserts DATA-POSITION parity alongside
    # the bitwise param parity
    idx_all = np.arange(n_batches * BATCH, dtype="float32").reshape(-1, 1)
    try:
        shadow = mx.io.NDArrayIter(idx_all, batch_size=BATCH)
        net, tr, est = _fresh(ctxs8, seed)
        eh = ElasticTrainingHandler(d, batch_period=1,
                                    max_keep=n_batches + 2,
                                    data_iter=shadow)
        advance = _make_shadow_advance(shadow, eh)
        faults.install_plan({"seed": seed, "rules": [
            {"site": "kvstore:allreduce", "kind": "chip_loss",
             "replica": kill_replica, "at": [kill_hit]}]})
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est.fit(batches, batches=n_batches,
                    event_handlers=[advance, eh])
    except Exception as exc:  # noqa: BLE001 — taxonomy violation
        violations.append(f"kill: training raised {type(exc).__name__}: "
                          f"{exc}")
        return violations, {}
    finally:
        faults.clear_plan()
        mesh_mod.set_mesh(prev_mesh)
    wall = time.perf_counter() - t0

    if eh.stats["restarts"] != 1:
        violations.append(f"kill: expected 1 restart, got {eh.stats}")
        return violations, {}
    if eh.stats["dp_history"] != [(DP, DP // 2)]:
        violations.append(
            f"kill: expected dp{DP}->dp{DP // 2}, got "
            f"{eh.stats['dp_history']}")
    _agree_and_finite(tr, violations, "kill")
    p_elastic = _params_np(net)

    # bitwise reference: dp4 on the SAME surviving devices, continued
    # from the SAME checkpoint the elastic run restored, over the same
    # remaining batches
    m4 = mesh_mod.shrink_mesh(m8, [kill_replica], axis="dp")
    ctxs4 = mesh_mod.mesh_contexts(m4)
    try:
        net2, tr2, est2 = _fresh(ctxs4, seed + 1000)  # init must not matter
        shadow_ref = mx.io.NDArrayIter(idx_all, batch_size=BATCH)
        advance_ref = _make_shadow_advance(shadow_ref)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ckpt.load_checkpoint(eh.manager._path(kill_step), net=net2,
                                 trainer=tr2, data_iter=shadow_ref)
            est2.fit(batches[kill_step + 1:],
                     batches=n_batches - kill_step - 1,
                     event_handlers=[advance_ref])
    except Exception as exc:  # noqa: BLE001
        violations.append(
            f"kill: dp4 reference run raised {type(exc).__name__}: {exc}")
        return violations, {}
    finally:
        mesh_mod.set_mesh(prev_mesh)
    p_ref = _params_np(net2)
    for k in p_elastic:
        if not np.array_equal(p_elastic[k], p_ref[k]):
            violations.append(
                f"kill: param {k} differs from the uninterrupted dp4 "
                "reference (silent divergence)")
    # data-position parity: the reshard rewound the data iterator in
    # lockstep with the params — applied samples are served exactly once
    # (the lost step's batch re-served after recovery, nothing replayed
    # or skipped), and the resumed run ends at the same position a clean
    # dp4 continuation restored from the same checkpoint ends at
    data_parity = True
    expect = list(range((n_batches - 1) * BATCH))
    if advance.consumed != expect:
        data_parity = False
        violations.append(
            "kill: elastic run consumed samples "
            f"{advance.consumed[:6]}...{advance.consumed[-3:]} — not the "
            "exactly-once epoch sequence (replay or skip across the "
            "reshard)")
    if advance.consumed[kill_step * BATCH:] != advance_ref.consumed:
        data_parity = False
        violations.append(
            "kill: post-checkpoint sample stream differs from the clean "
            "dp4 reference restored from the same checkpoint")
    if shadow.state_dict() != shadow_ref.state_dict():
        data_parity = False
        violations.append(
            f"kill: final data position {shadow.state_dict()['cursor']} "
            f"!= reference {shadow_ref.state_dict()['cursor']}")
    row = {"steps_lost": eh.stats["steps_lost"],
           "recovery_wall_s": eh.stats["last_recovery_s"],
           "dp_from": DP, "dp_to": DP // 2,
           "killed_replica": kill_replica, "killed_step": kill_step,
           "data_parity": "exact" if data_parity else "DIVERGED",
           "leg_wall_s": wall}
    say(f"kill leg: steps_lost={row['steps_lost']} "
        f"recovery={row['recovery_wall_s'] * 1e3:.0f}ms parity=EXACT "
        f"data={row['data_parity']}")
    return violations, row


BATCH3D = 8
DIM3D = 4


def _make_3d_trainer(seed, dp, tp=2, mesh=None):
    """Dense(2) ShardedTrainer over a declarative dp×tp ParallelConfig:
    weight tensor-split P(None, 'tp'), bias in a dp-sharded ZeRO bucket,
    sgd+momentum — the smallest model exercising every reshard case
    (tp layout slice, bucket flat, replicated scalar state)."""
    from jax.sharding import PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import (ParallelConfig, ShardedTrainer,
                                    ShardingRules)

    net = gluon.nn.Dense(2, in_units=DIM3D)
    net.initialize()
    pd = net.collect_params()
    names = list(pd)
    rng = np.random.RandomState(seed)
    pd[names[0]].set_data(
        mx.nd.array(rng.randn(2, DIM3D).astype("float32")))
    pd[names[1]].set_data(mx.nd.array(np.zeros(2, "float32")))

    def loss_fn(out, label):
        d = out - label
        return d * d

    tr = ShardedTrainer(net, loss_fn, "sgd",
                        {"learning_rate": 0.05, "momentum": 0.9},
                        mesh=mesh,
                        parallel=ParallelConfig(dp=dp, tp=tp),
                        rules=ShardingRules([(r"weight", P(None, "tp"))],
                                            default_axis="dp"),
                        zero_bucket_mb=1.0)
    return net, tr


def run_kill_reshard_3d(seed=7, n_batches=10, say=lambda m: None):
    """Kill-one-chip under a COMPOSED dp2×tp2 mesh (importable —
    bench.py's ``elastic_resume_3d`` MULTICHIP row): a coordinate
    -addressed ``chip_loss`` at ``trainer:sharded_step`` takes down one
    chip; ``ElasticTrainingHandler.recover_sharded`` rebuilds the mesh
    to dp1×tp2 (tp pinned, the touched dp-group dropped) and reshards
    the newest layout-carrying sharded checkpoint onto the survivors.
    Asserted: recovery WITHOUT MeshDegraded escaping, exactly one
    restart / one step lost, and the resumed run bitwise-equal (losses
    and final params) to a clean dp1×tp2 run continued from the same
    checkpoint. Returns ``(violations, row)``."""
    prev = os.environ.get("MXNET_ELASTIC")
    os.environ["MXNET_ELASTIC"] = "1"
    try:
        return _run_kill_reshard_3d_inner(seed, n_batches, say)
    finally:
        if prev is None:
            os.environ.pop("MXNET_ELASTIC", None)
        else:
            os.environ["MXNET_ELASTIC"] = prev


def _run_kill_reshard_3d_inner(seed, n_batches, say):
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.resilience import checkpoint as ckpt, faults
    from mxnet_tpu.resilience.elastic import (ElasticTrainingHandler,
                                              MeshDegraded, is_mesh_loss)

    DP3, TP3 = 2, 2
    violations = []
    rng = np.random.RandomState(seed * 131 + 4)
    kill_group = int(rng.randint(0, DP3))
    kill_tp = int(rng.randint(0, TP3))
    kill_step = int(rng.randint(2, n_batches - 2))
    # both coordinate forms rebuild_mesh accepts, seeded: an axis-index
    # dict naming the dp-group, or a flat index into the mesh array
    # (row-major dp×tp, so group*TP+j) naming one specific chip
    if rng.randint(0, 2):
        device = {"axis": "dp", "index": kill_group}
    else:
        device = kill_group * TP3 + kill_tp
    say(f"3d kill leg: chip_loss device {device} during batch "
        f"{kill_step} on dp{DP3}x tp{TP3} (seed {seed})")

    bx = np.random.RandomState(seed).randn(
        n_batches, BATCH3D, DIM3D).astype("float32")
    by = np.random.RandomState(seed + 1).randn(
        n_batches, BATCH3D, 2).astype("float32")
    prev_mesh = mesh_mod.get_mesh()
    d = tempfile.mkdtemp(prefix="elastic_soak3d_")
    eh = ElasticTrainingHandler(d, max_keep=n_batches + 2)
    net, tr = _make_3d_trainer(seed, dp=DP3, tp=TP3)
    faults.install_plan({"seed": seed, "rules": [
        {"site": "trainer:sharded_step", "kind": "chip_loss",
         "device": device, "at": [kill_step]}]})
    t0 = time.perf_counter()
    losses = []
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            i = 0
            while i < n_batches:
                try:
                    losses.append(float(
                        tr.step(mx.nd.array(bx[i]),
                                mx.nd.array(by[i])).asnumpy()))
                except Exception as exc:  # noqa: BLE001 — recovery path
                    if isinstance(exc, MeshDegraded):
                        violations.append(
                            "3d kill: MeshDegraded escaped the rebuild "
                            f"path: {exc}")
                        return violations, {}
                    if not is_mesh_loss(exc):
                        raise

                    def make_trainer(new_mesh, _s=seed + 500):
                        _net, _tr = _make_3d_trainer(
                            _s, dp=int(new_mesh.shape["dp"]), tp=TP3,
                            mesh=new_mesh)
                        return _tr

                    rec = eh.recover_sharded(tr, exc, make_trainer)
                    if rec is None:
                        raise
                    tr, restored = rec
                    i = restored + 1
                    continue
                eh.save_sharded_trainer(tr, i)
                i += 1
    except Exception as exc:  # noqa: BLE001 — taxonomy violation
        violations.append(
            f"3d kill: training raised {type(exc).__name__}: {exc}")
        return violations, {}
    finally:
        faults.clear_plan()
        mesh_mod.set_mesh(prev_mesh)
    wall = time.perf_counter() - t0

    if eh.stats["restarts"] != 1:
        violations.append(f"3d kill: expected 1 restart, got {eh.stats}")
        return violations, {}
    if eh.stats["dp_history"] != [(DP3, 1)]:
        violations.append(
            f"3d kill: expected dp{DP3}->dp1 (tp pinned), got "
            f"{eh.stats['dp_history']}")
    if eh.stats["steps_lost"] != 1:
        violations.append(
            f"3d kill: expected 1 step lost, got "
            f"{eh.stats['steps_lost']}")
    if int(tr.mesh.shape.get("tp", 0)) != TP3:
        violations.append(
            f"3d kill: tp extent changed: {dict(tr.mesh.shape)}")

    # bitwise reference: a CLEAN dp1×tp2 trainer continued from the SAME
    # sharded checkpoint over the same remaining batches — the resumed
    # elastic run and this run execute the identical compiled program
    # from identical state, so any difference is silent divergence
    try:
        net_r, tr_r = _make_3d_trainer(seed + 999, dp=1, tp=TP3)
        ref_losses = []
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            params, _meta = ckpt.load_checkpoint(
                eh.manager._path(kill_step - 1), trainer=tr_r,
                mesh_axes={"dp": 1, "tp": TP3})
            tr_r.import_params(params)
            for i in range(kill_step, n_batches):
                ref_losses.append(float(
                    tr_r.step(mx.nd.array(bx[i]),
                              mx.nd.array(by[i])).asnumpy()))
    except Exception as exc:  # noqa: BLE001
        violations.append(
            f"3d kill: dp1x tp2 reference raised "
            f"{type(exc).__name__}: {exc}")
        return violations, {}
    finally:
        mesh_mod.set_mesh(prev_mesh)
    parity = True
    if losses[kill_step:] != ref_losses:
        parity = False
        violations.append(
            f"3d kill: resumed losses {losses[kill_step:]} differ from "
            f"the clean dp1x tp2 reference {ref_losses}")
    p_elastic = tr.export_state()["params"]
    p_ref = tr_r.export_state()["params"]
    for k in p_elastic:
        if not np.array_equal(p_elastic[k], p_ref[k]):
            parity = False
            violations.append(
                f"3d kill: param {k} differs from the clean dp1x tp2 "
                "reference (silent divergence)")
    row = {"steps_lost": eh.stats["steps_lost"],
           "recovery_wall_s": eh.stats["last_recovery_s"],
           "dp_from": DP3, "dp_to": 1, "tp": TP3,
           "killed_device": str(device), "killed_step": kill_step,
           "resume_parity": "bitwise" if parity else "DIVERGED",
           "leg_wall_s": wall}
    say(f"3d kill leg: steps_lost={row['steps_lost']} "
        f"recovery={(row['recovery_wall_s'] or 0) * 1e3:.0f}ms "
        f"dp{DP3}->dp1 tp{TP3} pinned parity={row['resume_parity']}")
    return violations, row


def _run_lag_leg(seed, n_batches, say):
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.elastic import StragglerMonitor

    violations = []
    rng = np.random.RandomState(seed * 131 + 2)
    lag_replica = int(rng.randint(0, DP))
    say(f"lag leg: replica_delay on replica {lag_replica}")
    m8 = mesh_mod.make_mesh({"dp": DP})
    ctxs8 = mesh_mod.mesh_contexts(m8)
    batches = _make_batches(n_batches, seed)

    def run(with_lag):
        net, tr, est = _fresh(ctxs8, seed)
        if with_lag:
            faults.install_plan({"seed": seed, "rules": [
                {"site": "trainer:replica_step", "kind": "replica_delay",
                 "replica": lag_replica, "seconds": 0.02,
                 "times": n_batches}]})
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                est.fit(batches, batches=n_batches)
        finally:
            faults.clear_plan()
        return net, tr

    mon = StragglerMonitor(threshold_ms=8.0).install()
    try:
        net_lag, tr_lag = run(with_lag=True)
    except Exception as exc:  # noqa: BLE001
        violations.append(f"lag: training raised {type(exc).__name__}: "
                          f"{exc}")
        StragglerMonitor.uninstall()
        return violations, {}
    StragglerMonitor.uninstall()
    if mon.stats["flags"] < 1:
        violations.append(
            f"lag: straggler never flagged ({mon.snapshot()})")
    elif mon.stats["last_straggler"] != lag_replica:
        violations.append(
            f"lag: blamed replica {mon.stats['last_straggler']}, "
            f"injected lag on {lag_replica}")
    _agree_and_finite(tr_lag, violations, "lag")
    try:
        net_ref, _tr_ref = run(with_lag=False)
    except Exception as exc:  # noqa: BLE001
        violations.append(f"lag: reference run raised "
                          f"{type(exc).__name__}: {exc}")
        return violations, {}
    p_lag, p_ref = _params_np(net_lag), _params_np(net_ref)
    for k in p_lag:
        if not np.array_equal(p_lag[k], p_ref[k]):
            violations.append(
                f"lag: param {k} changed under pure delay faults — a "
                "straggler must slow the mesh, never change it")
    say(f"lag leg: flags={mon.stats['flags']} "
        f"blamed={mon.stats['last_straggler']} numerics=EXACT")
    return violations, {"flags": mon.stats["flags"],
                        "blamed": mon.stats["last_straggler"]}


def _run_corrupt_leg(seed, n_batches, say):
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.elastic import DesyncAuditHandler

    violations = []
    rng = np.random.RandomState(seed * 131 + 3)
    bad_replica = int(rng.randint(0, DP))
    corrupt_step = int(rng.randint(1, n_batches // 2))
    cadence = int(rng.randint(1, 4))
    say(f"corrupt leg: param_corrupt replica {bad_replica} at step "
        f"{corrupt_step}, audit cadence {cadence}")
    m8 = mesh_mod.make_mesh({"dp": DP})
    ctxs8 = mesh_mod.mesh_contexts(m8)
    batches = _make_batches(n_batches, seed)
    net, tr, est = _fresh(ctxs8, seed)
    audit = DesyncAuditHandler(check_steps=cadence)
    faults.install_plan({"seed": seed, "rules": [
        {"site": "trainer:param", "kind": "param_corrupt",
         "replica": bad_replica, "at": [corrupt_step]}]})
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est.fit(batches, batches=n_batches, event_handlers=[audit])
    except Exception as exc:  # noqa: BLE001
        violations.append(f"corrupt: training raised "
                          f"{type(exc).__name__}: {exc}")
        return violations, {}
    finally:
        faults.clear_plan()
    if audit.stats["trips"] < 1:
        violations.append(
            f"corrupt: audit never tripped (cadence {cadence}, stats "
            f"{audit.stats}) — SILENT single-replica divergence")
        return violations, {}
    if audit.stats["last_blamed"] != [bad_replica]:
        violations.append(
            f"corrupt: blamed {audit.stats['last_blamed']}, corrupted "
            f"{bad_replica}")
    if audit.stats["resyncs"] < 1:
        violations.append(
            f"corrupt: no resync performed ({audit.stats})")
    _agree_and_finite(tr, violations, "corrupt")
    say(f"corrupt leg: detected within cadence, blamed="
        f"{audit.stats['last_blamed']} resyncs={audit.stats['resyncs']}")
    return violations, {"trips": audit.stats["trips"],
                        "blamed": audit.stats["last_blamed"],
                        "cadence": cadence}


def _run_data_leg(seed, say):
    """Sharded-input reshard leg: four shard-owning RecordPipelines
    stream one epoch of a synthetic crc-indexed ``.rec``; after a few
    batches two shards are killed, the survivors ``merge_states`` +
    ``load_state_dict`` (dp4 -> dp2 on the data axis) and finish the
    epoch. Asserted: the epoch's sample multiset is delivered exactly
    once across the cut — nothing replayed, nothing skipped — which is
    the ``data_parity=exact`` contract of the PR-20 reshard rule."""
    import tempfile

    from mxnet_tpu import recordio
    from mxnet_tpu.io.pipeline import RecordPipeline

    violations = []
    n_records, batch = 96, 4
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="elastic_data.") as d:
        rec = os.path.join(d, "soak.rec")
        w = recordio.MXIndexedRecordIO(os.path.join(d, "soak.idx"),
                                       rec, "w")
        for i in range(n_records):
            w.write_idx(i, b"%d" % i)
        w.close()

        def mk(shard, shards, tag):
            return RecordPipeline(
                [rec], batch_size=batch, shard_index=shard,
                num_shards=shards, num_workers=2, shuffle=True,
                seed=seed, name=f"soak-data-{tag}{shard}")

        pipes = [mk(s, 4, "pre") for s in range(4)]
        head = []
        for p in pipes:
            for _ in range(2):
                head.extend(int(x) for x in next(p))
        states = [p.state_dict() for p in pipes]
        for p in pipes:
            p.close()
        merged = RecordPipeline.merge_states(states)
        survivors = [mk(s, 2, "post") for s in range(2)]
        tail = []
        for p in survivors:
            p.load_state_dict(merged)
            for b in p:
                tail.extend(int(x) for x in b)
            p.close()
    got = sorted(head + tail)
    parity = got == list(range(n_records))
    if not parity:
        dupes = len(got) - len(set(got))
        violations.append(
            f"data: reshard multiset wrong — {len(got)} samples with "
            f"{dupes} dupes across the 4->2 cut (want {n_records} "
            "exactly once)")
    row = {"records": n_records, "shards_from": 4, "shards_to": 2,
           "delivered_pre": len(head), "delivered_post": len(tail),
           "data_parity": "exact" if parity else "DIVERGED",
           "leg_wall_s": time.perf_counter() - t0}
    say(f"data leg: 4->2 shard reshard data={row['data_parity']} "
        f"({len(head)} pre-cut + {len(tail)} post-cut)")
    return violations, row


def run_soak(seed=7, n_batches=12, verbose=True, legs="all"):
    """One full seeded kill/lag/corrupt/kill-3d sweep; returns a report
    dict with ``ok``/``violations`` plus the per-leg numbers.
    Importable — ``tests/test_elastic.py`` runs the same machinery.
    ``legs="3d"`` runs only the composed-mesh kill leg (the opt-in
    ``TIER1_ELASTIC3D`` tier-1 gate)."""
    import mxnet_tpu as mx  # noqa: F401

    def say(msg):
        if verbose:
            print(f"ELASTIC_SOAK {msg}", flush=True)

    if legs == "3d":
        violations, kill3d_row = run_kill_reshard_3d(seed, n_batches, say)
        report = {"ok": not violations, "violations": violations,
                  "seed": seed, "kill_3d": kill3d_row}
        say(f"seed {seed}: {'PASS' if report['ok'] else 'FAIL'} "
            f"kill_3d={kill3d_row}")
        return report

    prev = os.environ.get("MXNET_ELASTIC")
    os.environ["MXNET_ELASTIC"] = "1"
    try:
        violations, kill_row = run_kill_reshard(seed, n_batches, say)
        v2, lag_row = _run_lag_leg(seed, n_batches, say)
        v3, corrupt_row = _run_corrupt_leg(seed, n_batches, say)
    finally:
        if prev is None:
            os.environ.pop("MXNET_ELASTIC", None)
        else:
            os.environ["MXNET_ELASTIC"] = prev
    v4, kill3d_row = run_kill_reshard_3d(seed, n_batches, say)
    v5, data_row = _run_data_leg(seed, say)
    violations += v2 + v3 + v4 + v5
    report = {"ok": not violations, "violations": violations,
              "seed": seed, "kill": kill_row, "lag": lag_row,
              "corrupt": corrupt_row, "kill_3d": kill3d_row,
              "data": data_row}
    say(f"seed {seed}: {'PASS' if report['ok'] else 'FAIL'} "
        f"kill={kill_row} corrupt={corrupt_row} kill_3d={kill3d_row} "
        f"data={data_row}")
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--seeds", type=int, default=1,
                    help="sweep seed..seed+N-1 (tier-1 smoke: 1; "
                         "full sweep: 8)")
    ap.add_argument("--batches", type=int, default=12)
    ap.add_argument("--legs", choices=("all", "3d"), default="all",
                    help="'3d' runs only the composed dp2xtp2 "
                         "kill-one-chip leg (TIER1_ELASTIC3D gate)")
    args = ap.parse_args(argv)

    failures = []
    for s in range(args.seed, args.seed + args.seeds):
        report = run_soak(seed=s, n_batches=args.batches, legs=args.legs)
        if not report["ok"]:
            failures.append((s, report["violations"]))
        else:
            k = report.get("kill") or report["kill_3d"]
            print(f"ELASTIC_SOAK=PASS seed={s} "
                  f"steps_lost={k.get('steps_lost')} "
                  f"recovery_ms={(k.get('recovery_wall_s') or 0) * 1e3:.0f} "
                  f"dp={k.get('dp_from')}->{k.get('dp_to')}")
    if failures:
        for s, v in failures:
            for msg in v:
                print(f"ELASTIC_SOAK=FAIL seed={s} {msg}")
        return 1
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _FLAG = "--xla_force_host_platform_device_count=8"
    if _FLAG not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " " + _FLAG).strip()
    sys.exit(main())
