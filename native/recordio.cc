// Native RecordIO scanner/reader (reference: dmlc-core's C++ recordio
// implementation behind src/io/ — the reference does all record IO in C++;
// this library provides the same hot paths for the TPU build's Python
// recordio module: full-file index scans and batched random reads, with a
// background prefetch thread for sequential pipelines).
//
// Format (see mxnet_tpu/recordio.py): [magic u32][cflag:3b|len:29b][payload]
// padded to 4 bytes; multi-part records use cflag start=1/middle=2/end=3.
//
// Build: g++ -O3 -shared -fPIC -o librecordio.so recordio.cc -lpthread

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

inline uint32_t cflag(uint32_t lrec) { return lrec >> 29; }
inline uint32_t length(uint32_t lrec) { return lrec & kLenMask; }
inline long pad4(long n) { return (4 - (n & 3)) & 3; }

}  // namespace

extern "C" {

// Scan the whole file, writing the byte offset of each *logical* record
// (multi-part records count once, at their first part) into out_offsets
// and its total payload size into out_sizes. Returns the record count, or
// -1 on IO/framing error. Pass max_n=0 with null outputs to count only.
long rio_build_index(const char* path, int64_t* out_offsets,
                     int64_t* out_sizes, long max_n) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  long count = 0;
  long logical_start = -1;
  int64_t logical_size = 0;
  uint32_t head[2];
  for (;;) {
    long pos = std::ftell(f);
    size_t got = std::fread(head, sizeof(uint32_t), 2, f);
    if (got == 0) break;           // clean EOF
    if (got != 2 || head[0] != kMagic) { std::fclose(f); return -1; }
    uint32_t n = length(head[1]);
    uint32_t fl = cflag(head[1]);
    if (std::fseek(f, static_cast<long>(n) + pad4(n), SEEK_CUR) != 0) {
      std::fclose(f);
      return -1;
    }
    if (fl == 0) {                  // complete record
      if (out_offsets && count < max_n) {
        out_offsets[count] = pos;
        out_sizes[count] = n;
      }
      ++count;
    } else if (fl == 1) {           // start of multi-part
      logical_start = pos;
      logical_size = n;
    } else {                        // middle/end
      logical_size += n;
      if (fl == 3) {
        if (out_offsets && count < max_n) {
          out_offsets[count] = logical_start;
          out_sizes[count] = logical_size;
        }
        ++count;
        logical_start = -1;
        logical_size = 0;
      }
    }
  }
  std::fclose(f);
  return count;
}

// Read one logical record starting at `offset` into buf (payload only,
// multi-part reassembled). Returns payload length, -1 on error, or the
// required size (> bufsize) if the buffer is too small.
long rio_read_at(const char* path, int64_t offset, uint8_t* buf,
                 long bufsize) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (std::fseek(f, static_cast<long>(offset), SEEK_SET) != 0) {
    std::fclose(f);
    return -1;
  }
  long total = 0;
  for (;;) {
    uint32_t head[2];
    if (std::fread(head, sizeof(uint32_t), 2, f) != 2 ||
        head[0] != kMagic) {
      std::fclose(f);
      return -1;
    }
    uint32_t n = length(head[1]);
    uint32_t fl = cflag(head[1]);
    if (buf && total + static_cast<long>(n) <= bufsize) {
      if (std::fread(buf + total, 1, n, f) != n) { std::fclose(f); return -1; }
      if (pad4(n)) std::fseek(f, pad4(n), SEEK_CUR);
    } else {  // size probe / overflow: skip payload
      std::fseek(f, static_cast<long>(n) + pad4(n), SEEK_CUR);
    }
    total += n;
    if (fl == 0 || fl == 3) break;
  }
  std::fclose(f);
  return total;
}

// Batched read: records at offsets[i] land back-to-back in buf; lengths[i]
// receives each payload size. Returns total bytes used, or -1 on error /
// overflow (lengths[] still filled with required sizes for resizing).
long rio_read_batch(const char* path, const int64_t* offsets, long n_rec,
                    uint8_t* buf, long bufsize, int64_t* lengths) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  long used = 0;
  bool overflow = false;
  for (long i = 0; i < n_rec; ++i) {
    if (std::fseek(f, static_cast<long>(offsets[i]), SEEK_SET) != 0) {
      std::fclose(f);
      return -1;
    }
    long total = 0;
    for (;;) {
      uint32_t head[2];
      if (std::fread(head, sizeof(uint32_t), 2, f) != 2 ||
          head[0] != kMagic) {
        std::fclose(f);
        return -1;
      }
      uint32_t n = length(head[1]);
      uint32_t fl = cflag(head[1]);
      if (!overflow && used + total + static_cast<long>(n) <= bufsize) {
        if (std::fread(buf + used + total, 1, n, f) != n) {
          std::fclose(f);
          return -1;
        }
        if (pad4(n)) std::fseek(f, pad4(n), SEEK_CUR);
      } else {
        overflow = true;
        std::fseek(f, static_cast<long>(n) + pad4(n), SEEK_CUR);
      }
      total += n;
      if (fl == 0 || fl == 3) break;
    }
    lengths[i] = total;
    used += total;
  }
  std::fclose(f);
  return overflow ? -1 : used;
}

// ---------------------------------------------------------------------------
// Background sequential prefetcher: a reader thread pulls records into a
// bounded queue (the role of src/io/iter_prefetcher.h's double buffering).
// ---------------------------------------------------------------------------

struct Prefetcher {
  FILE* f = nullptr;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  std::deque<std::vector<uint8_t>> queue;
  size_t capacity = 16;
  bool done = false;
  bool stop = false;

  void run() {
    for (;;) {
      std::vector<uint8_t> rec;
      uint32_t head[2];
      bool ok = true;
      long total = 0;
      for (;;) {
        if (std::fread(head, sizeof(uint32_t), 2, f) != 2 ||
            head[0] != kMagic) {
          ok = false;
          break;
        }
        uint32_t n = length(head[1]);
        uint32_t fl = cflag(head[1]);
        rec.resize(total + n);
        if (std::fread(rec.data() + total, 1, n, f) != n) {
          ok = false;
          break;
        }
        if (pad4(n)) std::fseek(f, pad4(n), SEEK_CUR);
        total += n;
        if (fl == 0 || fl == 3) break;
      }
      std::unique_lock<std::mutex> lk(mu);
      if (!ok || stop) {
        done = true;
        cv_pop.notify_all();
        return;
      }
      cv_push.wait(lk, [&] { return queue.size() < capacity || stop; });
      if (stop) {
        done = true;
        cv_pop.notify_all();
        return;
      }
      queue.emplace_back(std::move(rec));
      cv_pop.notify_one();
    }
  }
};

void* rio_prefetch_open(const char* path, long queue_depth) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* p = new Prefetcher();
  p->f = f;
  if (queue_depth > 0) p->capacity = static_cast<size_t>(queue_depth);
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Pop the next record. Returns length, 0 at end-of-file, -1 if buf too
// small (record stays queued; call again with a bigger buffer).
long rio_prefetch_next(void* handle, uint8_t* buf, long bufsize) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_pop.wait(lk, [&] { return !p->queue.empty() || p->done; });
  if (p->queue.empty()) return 0;
  auto& rec = p->queue.front();
  long n = static_cast<long>(rec.size());
  if (n > bufsize) return -1;
  std::memcpy(buf, rec.data(), rec.size());
  p->queue.pop_front();
  p->cv_push.notify_one();
  return n;
}

void rio_prefetch_close(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv_push.notify_all();
  if (p->worker.joinable()) p->worker.join();
  std::fclose(p->f);
  delete p;
}

}  // extern "C"
