// Native text-format parsers for the data pipeline.
//
// Reference: the framework parses CSV and LibSVM in C++ iterators
// (src/io/iter_csv.cc:218, src/io/iter_libsvm.cc:200) with dmlc-core's
// threaded text parsers. This is the TPU build's equivalent: mmap'd
// input, line-boundary chunking, one parser thread per chunk, writing
// straight into caller-owned float buffers (numpy arrays via ctypes).
// Beats numpy 2.x loadtxt (itself a C parser) via threading +
// an inline fast-path float decoder.
//
// Contract (all functions return -1 on I/O error):
//   txt_count_rows(path)                      -> row count
//   csv_parse(path, out, cap, ncols)          -> values written; out may be
//       null to probe ncols (written through ncols_out semantics below)
//   csv_ncols(path)                           -> columns in first row
//   libsvm_parse(path, data, label, rows, ncols) -> rows parsed; `data`
//       is a zero-initialized (rows, ncols) dense buffer, `label` (rows)
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// Fast decimal float parse: handles [-+]ddd[.ddd][e[-+]dd] inline (the
// overwhelming case in numeric CSV); anything else falls back to strtof.
// strtof's locale machinery costs ~10x more per value.
inline float parse_float(const char* p, const char** next) {
  const char* s = p;
  bool neg = false;
  if (*s == '-') { neg = true; ++s; }
  else if (*s == '+') { ++s; }
  if (!isdigit(static_cast<unsigned char>(*s)) && *s != '.') {
    char* e = nullptr;
    float v = strtof(p, &e);
    *next = e;
    return v;
  }
  double mant = 0.0;
  while (isdigit(static_cast<unsigned char>(*s)))
    mant = mant * 10.0 + (*s++ - '0');
  int frac = 0;
  if (*s == '.') {
    ++s;
    while (isdigit(static_cast<unsigned char>(*s))) {
      mant = mant * 10.0 + (*s++ - '0');
      ++frac;
    }
  }
  int exp = 0;
  if (*s == 'e' || *s == 'E') {
    const char* save = s;
    ++s;
    bool eneg = false;
    if (*s == '-') { eneg = true; ++s; }
    else if (*s == '+') { ++s; }
    if (!isdigit(static_cast<unsigned char>(*s))) {
      s = save;  // stray 'e': not an exponent
    } else {
      while (isdigit(static_cast<unsigned char>(*s)))
        exp = exp * 10 + (*s++ - '0');
      if (eneg) exp = -exp;
    }
  }
  static const double pow10[] = {
      1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11, 1e12,
      1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};
  int net = exp - frac;
  double v = mant;
  if (net > 0) {
    v = (net <= 22) ? v * pow10[net] : v * __builtin_pow(10.0, net);
  } else if (net < 0) {
    int m = -net;
    v = (m <= 22) ? v / pow10[m] : v / __builtin_pow(10.0, m);
  }
  *next = s;
  return static_cast<float>(neg ? -v : v);
}

struct Mapped {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  char* heap = nullptr;  // non-null when read() path was used
  bool ok() const { return data != nullptr; }
};

Mapped map_file(const char* path) {
  Mapped m;
  m.fd = ::open(path, O_RDONLY);
  if (m.fd < 0) return m;
  struct stat st;
  if (fstat(m.fd, &st) != 0 || st.st_size == 0) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  if (st.st_size % page == 0) {
    // page-multiple file with no trailing newline: a token parser at EOF
    // would read one byte past the mapping (SIGBUS). Use read() with an
    // explicit NUL sentinel instead of relying on kernel tail zero-fill.
    m.heap = static_cast<char*>(malloc(st.st_size + 1));
    if (!m.heap) { ::close(m.fd); m.fd = -1; return m; }
    size_t got = 0;
    while (got < static_cast<size_t>(st.st_size)) {
      ssize_t r = ::read(m.fd, m.heap + got, st.st_size - got);
      if (r <= 0) { free(m.heap); m.heap = nullptr; ::close(m.fd);
                    m.fd = -1; return m; }
      got += r;
    }
    m.heap[st.st_size] = 0;
    m.data = m.heap;
    m.size = st.st_size;
    return m;
  }
  void* p = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, m.fd, 0);
  if (p == MAP_FAILED) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  m.data = static_cast<const char*>(p);
  m.size = st.st_size;
  return m;
}

void unmap(Mapped& m) {
  if (m.heap) free(m.heap);
  else if (m.data) ::munmap(const_cast<char*>(m.data), m.size);
  if (m.fd >= 0) ::close(m.fd);
}

int n_threads(size_t size) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  // small files: threading overhead isn't worth it
  size_t per = 1 << 20;
  size_t want = size / per + 1;
  return static_cast<int>(want < hw ? want : hw);
}

// split [0, size) into chunks ending on '\n'
std::vector<size_t> chunk_bounds(const char* data, size_t size, int n) {
  std::vector<size_t> bounds{0};
  for (int i = 1; i < n; ++i) {
    size_t pos = size * i / n;
    while (pos < size && data[pos] != '\n') ++pos;
    if (pos < size) ++pos;
    bounds.push_back(pos);
  }
  bounds.push_back(size);
  return bounds;
}

size_t count_lines(const char* p, const char* end) {
  size_t n = 0;
  bool content = false;
  bool comment = false;  // '#' as first non-space char: numpy loadtxt skip
  for (; p < end; ++p) {
    if (*p == '\n') {
      if (content) ++n;
      content = false;
      comment = false;
    } else if (comment) {
      continue;
    } else if (*p == ',') {
      // separator-only lines (",,") carry no values: not content, keeping
      // the row count consistent with what csv_parse actually writes
      continue;
    } else if (!isspace(static_cast<unsigned char>(*p))) {
      if (*p == '#' && !content) comment = true;
      else content = true;
    }
  }
  if (content) ++n;
  return n;
}

}  // namespace

extern "C" {

long txt_count_rows(const char* path) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  int nt = n_threads(m.size);
  auto bounds = chunk_bounds(m.data, m.size, nt);
  std::vector<size_t> counts(nt, 0);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; ++i) {
    threads.emplace_back([&, i] {
      counts[i] = count_lines(m.data + bounds[i], m.data + bounds[i + 1]);
    });
  }
  for (auto& t : threads) t.join();
  long total = 0;
  for (size_t c : counts) total += static_cast<long>(c);
  unmap(m);
  return total;
}

long csv_ncols(const char* path) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  size_t i = 0;
  // skip blank and comment lines to the first data line
  while (i < m.size) {
    size_t j = i;
    while (j < m.size && (m.data[j] == ' ' || m.data[j] == '\t' ||
                          m.data[j] == '\r')) ++j;
    if (j < m.size && m.data[j] != '\n' && m.data[j] != '#') { i = j; break; }
    while (j < m.size && m.data[j] != '\n') ++j;
    i = j + 1;
  }
  long cols = 1;
  for (; i < m.size && m.data[i] != '\n'; ++i)
    if (m.data[i] == ',') ++cols;
  unmap(m);
  return cols;
}

// Parse the whole CSV into out (row-major floats). Rows must be uniform
// width `ncols`; returns values written or -1 (error / overflow / ragged).
long csv_parse(const char* path, float* out, long cap, long ncols) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  int nt = n_threads(m.size);
  auto bounds = chunk_bounds(m.data, m.size, nt);
  // per-chunk row counts give each thread its output offset
  std::vector<size_t> rows(nt, 0);
  {
    std::vector<std::thread> threads;
    for (int i = 0; i < nt; ++i)
      threads.emplace_back([&, i] {
        rows[i] = count_lines(m.data + bounds[i], m.data + bounds[i + 1]);
      });
    for (auto& t : threads) t.join();
  }
  std::vector<size_t> row_off(nt + 1, 0);
  for (int i = 0; i < nt; ++i) row_off[i + 1] = row_off[i] + rows[i];
  if (static_cast<long>(row_off[nt]) * ncols > cap) {
    unmap(m);
    return -1;
  }
  std::vector<int> errs(nt, 0);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; ++i) {
    threads.emplace_back([&, i] {
      const char* p = m.data + bounds[i];
      const char* end = m.data + bounds[i + 1];
      float* dst = out + row_off[i] * ncols;
      long col = 0;
      size_t written = 0;
      bool any = false;
      while (p < end) {
        if (*p == '\n') {
          if (any && col != ncols) { errs[i] = 1; return; }
          if (any) { col = 0; ++written; }
          any = false;
          ++p;
          continue;
        }
        if (*p == ',' || isspace(static_cast<unsigned char>(*p))) {
          ++p;
          continue;
        }
        if (*p == '#' && !any) {  // comment line (numpy loadtxt skip)
          while (p < end && *p != '\n') ++p;
          continue;
        }
        const char* next = nullptr;
        float v = parse_float(p, &next);
        if (next == p) { errs[i] = 1; return; }
        if (col >= ncols) { errs[i] = 1; return; }
        *dst++ = v;
        ++col;
        any = true;
        p = next;
      }
      if (any) {
        if (col != ncols) { errs[i] = 1; return; }
        ++written;
      }
      // every counted row must have been written — anything else would
      // leave uninitialized tail rows in the caller's buffer
      if (written != rows[i]) errs[i] = 1;
    });
  }
  for (auto& t : threads) t.join();
  long total = static_cast<long>(row_off[nt]) * ncols;
  unmap(m);
  for (int e : errs)
    if (e) return -1;
  return total;
}

// LibSVM "label idx:val idx:val ..." -> dense (rows, ncols) + labels.
long libsvm_parse(const char* path, float* data, float* label, long rows,
                  long ncols) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  int nt = n_threads(m.size);
  auto bounds = chunk_bounds(m.data, m.size, nt);
  std::vector<size_t> rcount(nt, 0);
  {
    std::vector<std::thread> threads;
    for (int i = 0; i < nt; ++i)
      threads.emplace_back([&, i] {
        rcount[i] = count_lines(m.data + bounds[i], m.data + bounds[i + 1]);
      });
    for (auto& t : threads) t.join();
  }
  std::vector<size_t> roff(nt + 1, 0);
  for (int i = 0; i < nt; ++i) roff[i + 1] = roff[i] + rcount[i];
  if (static_cast<long>(roff[nt]) > rows) {
    unmap(m);
    return -1;
  }
  std::vector<int> errs(nt, 0);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; ++i) {
    threads.emplace_back([&, i] {
      const char* p = m.data + bounds[i];
      const char* end = m.data + bounds[i + 1];
      size_t row = roff[i];
      while (p < end) {
        while (p < end && (*p == '\n' || *p == '\r')) ++p;
        if (p >= end) break;
        const char* next = nullptr;
        float lab = parse_float(p, &next);
        if (next == p) { errs[i] = 1; return; }
        p = next;
        label[row] = lab;
        float* drow = data + row * ncols;
        while (p < end && *p != '\n') {
          while (p < end && (*p == ' ' || *p == '\t' ||
                             *p == '\r')) ++p;
          if (p >= end || *p == '\n') break;
          char* inext = nullptr;
          long idx = strtol(p, &inext, 10);
          if (inext == p || *inext != ':') { errs[i] = 1; return; }
          p = inext + 1;
          float v = parse_float(p, &next);
          if (next == p) { errs[i] = 1; return; }
          p = next;
          if (idx < 0 || idx >= ncols) { errs[i] = 1; return; }
          drow[idx] = v;
        }
        ++row;
      }
    });
  }
  for (auto& t : threads) t.join();
  long total = static_cast<long>(roff[nt]);
  unmap(m);
  for (int e : errs)
    if (e) return -1;
  return total;
}

}  // extern "C"
