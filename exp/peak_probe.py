"""Probe: achievable bf16 matmul/conv rates on the real chip.

Microbench discipline for the tunnel runtime: loop ON DEVICE via lax.scan
(output fed back as input to serialize), run at two scan lengths, and take
the time difference — one dispatch per measurement, RTT cancels, device time
dominates.
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as onp

PEAK = 197e12


def scan_rate(make_step, x0, flops_per_iter, m1=20, m2=120, reps=3):
    """make_step: x -> x (same shape/dtype). Returns seconds/iter."""

    @functools.partial(jax.jit, static_argnums=1)
    def run(x, m):
        def body(c, _):
            return make_step(c), None
        out, _ = jax.lax.scan(body, x, None, length=m)
        return out

    # compile both lengths, drain
    onp.asarray(jax.tree_util.tree_leaves(run(x0, m1))[0].reshape(-1)[0])
    onp.asarray(jax.tree_util.tree_leaves(run(x0, m2))[0].reshape(-1)[0])

    def t(m):
        t0 = time.perf_counter()
        r = run(x0, m)
        onp.asarray(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0])
        return time.perf_counter() - t0

    diffs = []
    for _ in range(reps):
        d1 = t(m1)
        d2 = t(m2)
        if d2 > d1:
            diffs.append((d2 - d1) / (m2 - m1))
    diffs.sort()
    dt = diffs[len(diffs) // 2]
    return dt, flops_per_iter / dt


def probe_matmul():
    n = 4096
    a = jnp.array(onp.random.randn(n, n), dtype=jnp.bfloat16)

    w = jnp.array(onp.random.randn(n, n), dtype=jnp.bfloat16)

    def step(x):
        y = x @ w
        return y * (1.0 / n)  # keep magnitudes sane

    dt, rate = scan_rate(step, a, 2 * n**3)
    print(f"matmul {n} bf16: {dt*1e3:.3f} ms/iter {rate/1e12:.1f} TF/s "
          f"({rate/PEAK*100:.1f}%)")


def probe_conv(layout, B=256, C=256, H=14, ksz=3):
    if layout == "NCHW":
        x = jnp.array(onp.random.randn(B, C, H, H), dtype=jnp.bfloat16)
        dn = ("NCHW", "OIHW", "NCHW")
        w = jnp.array(onp.random.randn(C, C, ksz, ksz), dtype=jnp.bfloat16)
    else:
        x = jnp.array(onp.random.randn(B, H, H, C), dtype=jnp.bfloat16)
        dn = ("NHWC", "HWIO", "NHWC")
        w = jnp.array(onp.random.randn(ksz, ksz, C, C), dtype=jnp.bfloat16)
    p = ksz // 2

    def conv(x):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), [(p, p), (p, p)], dimension_numbers=dn)

    def step(x):
        return conv(x) * 0.01

    fl = 2 * B * H * H * C * C * ksz * ksz
    dt, rate = scan_rate(step, x, fl)
    print(f"conv {layout} B{B} C{C} H{H} k{ksz}: {dt*1e3:.3f} ms "
          f"{rate/1e12:.1f} TF/s ({rate/PEAK*100:.1f}%)")

    # fwd+bwd via vjp inside scan: carry x, apply grad-shaped update
    def stepg(x):
        y, vjp = jax.vjp(conv, x)
        (dx,) = vjp(y)
        return x + dx * 1e-6

    dt, rate = scan_rate(stepg, x, 3 * fl)
    print(f"conv {layout} f+b: {dt*1e3:.3f} ms {rate/1e12:.1f} TF/s "
          f"({rate/PEAK*100:.1f}%)")


if __name__ == "__main__":
    print("device:", jax.devices()[0].device_kind)
    probe_matmul()
    for lay in ("NCHW", "NHWC"):
        probe_conv(lay)
    # first resnet conv: 7x7 s2 C3 -> poor MXU fit
    for lay in ("NCHW", "NHWC"):
        B, H = 256, 224
        if lay == "NCHW":
            x = jnp.array(onp.random.randn(B, 3, H, H), dtype=jnp.bfloat16)
            dn = ("NCHW", "OIHW", "NCHW")
            w = jnp.array(onp.random.randn(64, 3, 7, 7), dtype=jnp.bfloat16)
        else:
            x = jnp.array(onp.random.randn(B, H, H, 3), dtype=jnp.bfloat16)
            dn = ("NHWC", "HWIO", "NHWC")
            w = jnp.array(onp.random.randn(7, 7, 3, 64), dtype=jnp.bfloat16)

        def conv0(x, w=w, dn=dn):
            return jax.lax.conv_general_dilated(
                x, w, (2, 2), [(3, 3), (3, 3)], dimension_numbers=dn)

        f = jax.jit(conv0)
        y = f(x)
        onp.asarray(y.reshape(-1)[0])

        def t(k):
            t0 = time.perf_counter()
            r = None
            for _ in range(k):
                r = f(x)
            onp.asarray(r.reshape(-1)[0])
            return time.perf_counter() - t0

        diffs = []
        for _ in range(3):
            d1, d2 = t(10), t(110)
            if d2 > d1:
                diffs.append((d2 - d1) / 100)
        diffs.sort()
        dt = diffs[len(diffs) // 2]
        fl = 2 * B * 112 * 112 * 64 * 3 * 49
        print(f"conv0 7x7s2 {lay}: {dt*1e3:.3f} ms {fl/dt/1e12:.1f} TF/s "
              f"({fl/dt/PEAK*100:.1f}%)")
