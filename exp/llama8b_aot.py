#!/usr/bin/env python
"""Llama-3-8B sharding/memory proof (VERDICT r3 item 5).

AOT-lowers ONE full SPMD training step of the true llama3_8b config
(32 layers / 4096 units / 32 heads / 8 KV heads / vocab 128256 — 8.03B
params) through ``ShardedTrainer(abstract=True)`` + ``llama_sharding_rules``
on a virtual 1x8 (dp, tp) mesh: compile + memory-plan only, zero bytes of
parameters ever materialized (``functionalize_abstract``).

The fit claim asserted here (and by tests/test_llama8b_aot.py and the
driver's ``dryrun_multichip``):

    fp32 Adam masters+moments tp-sharded 8-way (11.22 GiB/device) plus the
    XLA heap-simulator temp for a remat'd B=1 T=1024 step fits a v5e chip's
    16 GiB.

Numbers are from XLA's own buffer assignment (``memory_analysis()``), i.e.
the same heap simulation the real compiler allocates with — conservative
for TPU (the CPU thunk scheduler overlaps less, so its peak-live estimate
is an upper bound; the arguments term is backend-independent arithmetic:
8.03e9 x (4+4+4) bytes / 8 devices).

    python exp/llama8b_aot.py            # full matrix, writes llama8b_aot.json
    python exp/llama8b_aot.py --quick    # just the asserted fit config
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    # standalone run only: importers (tests, __graft_entry__) own their
    # platform/mesh setup and jax may already be initialized
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as onp
from jax.sharding import Mesh, PartitionSpec as P

from mxnet_tpu.models.llama import get_llama, llama_sharding_rules
from mxnet_tpu.parallel.functional import ShardedTrainer, ShardingRules

V5E_HBM_GIB = 16.0


def lower_once(mesh, seq_len, amp_dtype, remat=True, batch=1):
    model = get_llama("llama3_8b", remat=remat)

    def loss_fn(out, labels):
        from mxnet_tpu.gluon import loss as gl

        return gl.SoftmaxCrossEntropyLoss(sparse_label=True)(out, labels)

    tr = ShardedTrainer(model, loss_fn, "adam", {"learning_rate": 1e-4},
                        mesh=mesh, rules=ShardingRules(llama_sharding_rules()),
                        batch_spec=P("dp"), dtype=amp_dtype, abstract=True)
    n_params = sum(int(onp.prod(s.shape)) for s in tr.params.values())
    t0 = time.time()
    compiled = tr.aot_lower(
        jax.ShapeDtypeStruct((batch, seq_len), jnp.int32),
        jax.ShapeDtypeStruct((batch, seq_len), jnp.int32))
    dt = time.time() - t0
    ma = compiled.memory_analysis()
    args_gib = ma.argument_size_in_bytes / 2**30
    temp_gib = ma.temp_size_in_bytes / 2**30
    row = {
        "config": "llama3_8b", "params_b": round(n_params / 1e9, 3),
        "mesh": "dp1 x tp8", "batch": batch, "seq_len": seq_len,
        "amp": str(amp_dtype.__name__) if amp_dtype else "fp32",
        "remat": remat,
        "args_gib_per_device": round(args_gib, 3),
        "temp_gib_per_device": round(temp_gib, 3),
        "peak_gib_per_device": round(args_gib + temp_gib, 3),
        "fits_v5e_16gib": bool(args_gib + temp_gib < V5E_HBM_GIB),
        "compile_s": round(dt, 1),
        "flops_per_step_per_device": tr.step_flops,
    }
    hlo = compiled.as_text()
    row["collectives"] = {
        c: hlo.count(c) for c in
        ("all-reduce", "all-gather", "reduce-scatter", "collective-permute")
        if hlo.count(c)}
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="only the asserted fit config")
    args = ap.parse_args()

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit(
            f"needs 8 devices for the v5e-8 proof, have {len(devs)} — set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    mesh = Mesh(onp.array(devs[:8]).reshape(1, 8), ("dp", "tp"))

    rows = []
    # THE asserted config: fp32 end to end, remat, B=1 T=1024
    fit = lower_once(mesh, seq_len=1024, amp_dtype=None)
    rows.append(fit)
    print(json.dumps(fit, indent=2))
    assert fit["params_b"] == 8.03, fit["params_b"]
    assert fit["fits_v5e_16gib"], (
        f"8B step peak {fit['peak_gib_per_device']} GiB exceeds v5e HBM")

    if not args.quick:
        # transparency matrix: where the budget goes at longer context /
        # with AMP (the bf16 step carries extra live low-precision
        # copies on the CPU heap sim; see PERF.md discussion)
        for seq, amp in ((2048, None), (1024, jnp.bfloat16),
                         (2048, jnp.bfloat16)):
            row = lower_once(mesh, seq_len=seq, amp_dtype=amp)
            rows.append(row)
            print(json.dumps(row))

    if args.quick:
        # don't clobber the committed 4-row transparency matrix with a
        # single-row file
        return
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "llama8b_aot.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
