#!/usr/bin/env python
"""Llama-3-8B sharding/memory proof (VERDICT r3 item 5).

AOT-lowers ONE full SPMD training step of the true llama3_8b config
(32 layers / 4096 units / 32 heads / 8 KV heads / vocab 128256 — 8.03B
params) through ``ShardedTrainer(abstract=True)`` + ``llama_sharding_rules``
on a virtual 1x8 (dp, tp) mesh: compile + memory-plan only, zero bytes of
parameters ever materialized (``functionalize_abstract``).

The fit claim asserted here (and by tests/test_llama8b_aot.py and the
driver's ``dryrun_multichip``):

    fp32 Adam masters+moments tp-sharded 8-way (11.22 GiB/device) plus the
    compiler's temp for a remat'd B=1 T=1024 step fits a v5e chip's 16 GiB.

Round-5 backend upgrade: when libtpu is present the matrix compiles
against a **v5e:2x4 topology description** via the PJRT compile-only
client — the memory plan then comes from the REAL TPU compiler and its
memory-bounded latency-hiding scheduler (``memory_backend`` field:
``tpu-aot(v5e:2x4)``). The CPU heap-sim fallback remains for
tests/driver and is markedly pessimistic in two measured ways (PERF.md
round-5): it keeps per-layer AMP bf16 param copies live (~0.1 GiB/layer,
scaling with depth, not vocab) and schedules EVERY layer's fsdp
all-gather up front (full 32 GiB unsharded param set live at once). On
the real TPU plan both artifacts vanish: bf16-AMP temp == fp32 temp
within 0.1 GiB and the ZeRO-dp8 step fits at 13.8 GiB. The arguments
term is backend-independent arithmetic either way: 8.03e9 x (4+4+4)
bytes / 8 devices (or x (2+2+2) after ``Block.cast('bfloat16')``).

    python exp/llama8b_aot.py            # full matrix, writes llama8b_aot.json
    python exp/llama8b_aot.py --quick    # just the asserted fit config
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    # standalone run only: importers (tests, __graft_entry__) own their
    # platform/mesh setup and jax may already be initialized
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as onp
from jax.sharding import Mesh, PartitionSpec as P

from mxnet_tpu.models.llama import get_llama, llama_sharding_rules
from mxnet_tpu.parallel.functional import ShardedTrainer, ShardingRules

V5E_HBM_GIB = 16.0


def lower_once(mesh, seq_len, amp_dtype, remat=True, batch=1,
               sharding="tp8", master_dtype=None, layer_barrier=False):
    """AOT-lower one step; returns the memory-plan row.

    sharding: "tp8" (Megatron tensor-parallel over the 8-way tp axis,
    batch over dp) or "zero_dp8" (ZeRO-3 style: params + Adam moments
    fsdp-sharded over the SAME 8-way axis the batch is data-parallel
    over; XLA inserts the param all-gathers / grad reduce-scatters).
    master_dtype: None keeps fp32 master weights; "bfloat16" casts the
    whole Block first — masters, grads AND Adam moments in bf16 (the
    6-bytes/param regime; a numerics trade documented in PERF.md).
    """
    model = get_llama("llama3_8b", remat=remat,
                      layer_barrier=layer_barrier)
    if master_dtype is not None:
        model.cast(master_dtype)

    def loss_fn(out, labels):
        from mxnet_tpu.gluon import loss as gl

        return gl.SoftmaxCrossEntropyLoss(sparse_label=True)(out, labels)

    if sharding == "tp8":
        rules = ShardingRules(llama_sharding_rules())
        batch_spec = P("dp")
    elif sharding == "zero_dp8":
        rules = ShardingRules((), default_axis="fsdp")
        batch_spec = P("fsdp")
    else:
        raise ValueError(sharding)
    tr = ShardedTrainer(model, loss_fn, "adam", {"learning_rate": 1e-4},
                        mesh=mesh, rules=rules,
                        batch_spec=batch_spec, dtype=amp_dtype,
                        abstract=True)
    n_params = sum(int(onp.prod(s.shape)) for s in tr.params.values())
    t0 = time.time()
    compiled = tr.aot_lower(
        jax.ShapeDtypeStruct((batch, seq_len), jnp.int32),
        jax.ShapeDtypeStruct((batch, seq_len), jnp.int32))
    dt = time.time() - t0
    ma = compiled.memory_analysis()
    args_gib = ma.argument_size_in_bytes / 2**30
    temp_gib = ma.temp_size_in_bytes / 2**30
    row = {
        "config": "llama3_8b", "params_b": round(n_params / 1e9, 3),
        "mesh": "dp1 x tp8" if sharding == "tp8" else "fsdp8 (ZeRO)",
        "batch": batch, "seq_len": seq_len,
        "amp": str(amp_dtype.__name__) if amp_dtype else "fp32",
        "master_dtype": master_dtype or "float32",
        "remat": remat, "layer_barrier": layer_barrier,
        "args_gib_per_device": round(args_gib, 3),
        "temp_gib_per_device": round(temp_gib, 3),
        "peak_gib_per_device": round(args_gib + temp_gib, 3),
        "fits_v5e_16gib": bool(args_gib + temp_gib < V5E_HBM_GIB),
        "compile_s": round(dt, 1),
        "flops_per_step_per_device": tr.step_flops,
    }
    hlo = compiled.as_text()
    row["collectives"] = {
        c: hlo.count(c) for c in
        ("all-reduce", "all-gather", "reduce-scatter", "collective-permute")
        if hlo.count(c)}
    return row


def make_meshes():
    """(tp_mesh, zero_mesh, backend_label). Prefers the REAL TPU AOT
    compiler via a v5e:2x4 topology description (no chips needed — the
    PJRT compile-only client; its memory plan comes from the actual TPU
    latency-hiding scheduler, which is memory-bounded and honors
    optimization_barrier, unlike the CPU heap sim that strips barriers
    before buffer assignment — measured in PERF.md round-5). Falls back
    to the virtual CPU mesh when libtpu is unavailable (tests/driver)."""
    try:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x4")
        devs = list(topo.devices)
        label = "tpu-aot(v5e:2x4)"
    except Exception as e:  # noqa: BLE001
        print(f"# tpu topology unavailable ({type(e).__name__}); "
              "falling back to cpu heap-sim", file=sys.stderr)
        devs = jax.devices()
        if len(devs) < 8:
            raise SystemExit(
                f"needs 8 devices, have {len(devs)} — set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8")
        label = "cpu-heapsim"
    tp = Mesh(onp.array(devs[:8]).reshape(1, 8), ("dp", "tp"))
    zero = Mesh(onp.array(devs[:8]).reshape(8), ("fsdp",))
    return tp, zero, label


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="only the asserted fit config")
    args = ap.parse_args()

    mesh, zero_mesh, backend = make_meshes()
    print(f"# backend: {backend}", file=sys.stderr)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "llama8b_aot.json")

    rows = []

    def add(row):
        row["memory_backend"] = backend
        rows.append(row)
        print(json.dumps(row), flush=True)
        if not args.quick:  # incremental: partial matrix survives
            with open(out, "w") as f:
                json.dump(rows, f, indent=2)

    # THE asserted config: fp32 end to end, remat, B=1 T=1024
    fit = lower_once(mesh, seq_len=1024, amp_dtype=None)
    add(fit)
    assert fit["params_b"] == 8.03, fit["params_b"]
    assert fit["fits_v5e_16gib"], (
        f"8B step peak {fit['peak_gib_per_device']} GiB exceeds v5e HBM")

    if not args.quick:
        # transparency matrix: longer context / AMP / pure-bf16 /
        # ZeRO-dp8 (VERDICT r4 Next #4: configs a user would train)
        for seq, amp in ((2048, None), (1024, jnp.bfloat16),
                         (2048, jnp.bfloat16)):
            add(lower_once(mesh, seq_len=seq, amp_dtype=amp))
        for kw in (
            dict(seq_len=1024, amp_dtype=None, master_dtype="bfloat16"),
            dict(seq_len=2048, amp_dtype=None, master_dtype="bfloat16"),
        ):
            add(lower_once(mesh, **kw))
        for kw in (
            dict(seq_len=1024, amp_dtype=None, batch=8),
            dict(seq_len=1024, amp_dtype=None, batch=8,
                 layer_barrier=True),
            dict(seq_len=1024, amp_dtype=jnp.bfloat16, batch=8,
                 layer_barrier=True),
            dict(seq_len=2048, amp_dtype=None, batch=8,
                 master_dtype="bfloat16", layer_barrier=True),
        ):
            add(lower_once(zero_mesh, sharding="zero_dp8", **kw))

    if not args.quick:
        print(f"wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
