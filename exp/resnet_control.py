#!/usr/bin/env python
"""Framework-free ResNet-50 v1 training control (VERDICT r4 Next #1a).

The question this answers: is the repo's ResNet-50 train MFU
(0.2996-0.3071 in BENCH_r04.json) a ceiling imposed by this framework's
code, or by XLA's conv kernels at these shapes?  The control is an
idiomatic, hand-rolled pure-JAX ResNet-50 v1 train step with ZERO
framework imports — plain dicts of arrays, `lax.conv_general_dilated`,
`value_and_grad`, donated buffers — at the exact bench config:
batch 256 @ 224x224, bf16 compute / fp32 master weights, SGD momentum
0.9 + wd 1e-4, softmax CE, and the same two-loop timing (run k1 steps +
host fetch, then k2, divide the difference — tunnel RTT cancels).

Variants:
  * nchw        — the framework's own layout (gluon NCHW), single dispatch
  * nhwc        — TPU-native layout, single dispatch
  * fused       — 8 steps chained in one `lax.scan` dispatch (mirrors the
                  bench's `step_n` fused8 row: amortizes tunnel dispatch)
  * s2d         — MLPerf-style 2x2 space-to-depth stem: input
                  (B,112,112,12), conv0 re-expressed as a 4x4 s1 matmul-
                  friendly conv (the 7x7s2 stem measures 0.07 MXU in
                  exp/conv_chain_probe.json; this is the known remedy).
                  NOTE round-3's exp/resnet_bound.py s2d variant was
                  wrong (4x4 s2d + stride 2 collapsed the network to
                  1/16 spatial, 1.6 GF/img); this one keeps the true
                  FLOP count (22.4 -> 22.5 GF/img, stem kernel 8x8/49).

MFU accounting matches bench.py: numerator = XLA cost_analysis flops of
the compiled SINGLE step (the fused variant multiplies by the window —
XLA counts a scan body once), denominator = v5e bf16 peak 197 TF/s.

Writes exp/resnet_control.json; interpreted in PERF.md ("ResNet-50
limiter"). Run: python exp/resnet_control.py [all|nchw|nhwc|s2d]
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp

PEAK = float(os.environ.get("MXNET_TPU_PEAK_FLOPS", 197e12))
BATCH = 256
LR, MOM, WD = 0.1, 0.9, 1e-4

# resnet50 v1 stages: (blocks, mid_channels, first_stride)
STAGES = [(3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2)]


def init_params(nhwc, s2d=False):
    p = {}
    rng = onp.random.RandomState(0)

    def conv_w(name, cin, cout, k):
        w = rng.randn(k, k, cin, cout) * (2.0 / (k * k * cin)) ** 0.5
        if not nhwc:
            w = w.transpose(3, 2, 0, 1)  # HWIO -> OIHW
        p[name] = w.astype("float32")

    def bn(name, c):
        p[name + ".g"] = onp.ones(c, "float32")
        p[name + ".b"] = onp.zeros(c, "float32")

    if s2d:
        # 7x7x3 stem padded to 8x8x3, blocked 2x2 -> 4x4x12 on the 112 grid
        conv_w("conv0", 12, 64, 4)
    else:
        conv_w("conv0", 3, 64, 7)
    bn("bn0", 64)
    cin = 64
    for si, (blocks, mid, _stride) in enumerate(STAGES):
        cout = mid * 4
        for bi in range(blocks):
            pre = f"s{si}b{bi}"
            conv_w(pre + ".c1", cin, mid, 1)
            bn(pre + ".n1", mid)
            conv_w(pre + ".c2", mid, mid, 3)
            bn(pre + ".n2", mid)
            conv_w(pre + ".c3", mid, cout, 1)
            bn(pre + ".n3", cout)
            if bi == 0:
                conv_w(pre + ".cd", cin, cout, 1)
                bn(pre + ".nd", cout)
            cin = cout
    p["fc.w"] = (rng.randn(2048, 1000) * 0.01).astype("float32")
    p["fc.b"] = onp.zeros(1000, "float32")
    return {k: jnp.array(v) for k, v in p.items()}


def make_fwd(nhwc, s2d=False):
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    caxis = 3 if nhwc else 1

    def conv(x, w, stride=1, pad=None):
        k = w.shape[0] if nhwc else w.shape[2]
        if pad is None:
            pad = ((k - 1) // 2, (k - 1) // 2)
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [pad, pad], dimension_numbers=dn)

    def bnorm(x, g, b):
        axes = tuple(i for i in range(4) if i != caxis)
        m = jnp.mean(x, axis=axes)
        v = jnp.var(x, axis=axes)
        sh = [1, 1, 1, 1]
        sh[caxis] = x.shape[caxis]
        inv = (g / jnp.sqrt(v + 1e-5)).reshape(sh)
        return (x - m.reshape(sh)) * inv + b.reshape(sh)

    def fwd(p, x):
        if s2d:
            # x is (B,112,112,12); 4x4 s1 conv == padded-to-8x8 7x7s2 on
            # 224. pad (2,1): output j must read rows 2j-3..2j+4 of the
            # original grid = blocks j-2+1..j+2 with the kernel's first
            # block row zero — i.e. two lead blocks of padding, one tail
            x = conv(x, p["conv0"], 1, pad=(2, 1))
        else:
            x = conv(x, p["conv0"], 2, pad=(3, 3))
        x = jax.nn.relu(bnorm(x, p["bn0.g"], p["bn0.b"]))
        if nhwc:
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                [(0, 0), (1, 1), (1, 1), (0, 0)])
        else:
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
                [(0, 0), (0, 0), (1, 1), (1, 1)])
        for si, (blocks, mid, stride) in enumerate(STAGES):
            for bi in range(blocks):
                st = stride if bi == 0 else 1
                pre = f"s{si}b{bi}"
                idn = x
                # v1 bottleneck: stride on the FIRST 1x1 (matches the
                # framework's BottleneckV1, model_zoo/vision/resnet.py:58
                # — v1.5 strides the 3x3 instead and does ~7% more FLOPs)
                y = jax.nn.relu(bnorm(conv(x, p[pre + ".c1"], st),
                                      p[pre + ".n1.g"], p[pre + ".n1.b"]))
                y = jax.nn.relu(bnorm(conv(y, p[pre + ".c2"]),
                                      p[pre + ".n2.g"], p[pre + ".n2.b"]))
                y = bnorm(conv(y, p[pre + ".c3"]),
                          p[pre + ".n3.g"], p[pre + ".n3.b"])
                if bi == 0:
                    idn = bnorm(conv(idn, p[pre + ".cd"], st),
                                p[pre + ".nd.g"], p[pre + ".nd.b"])
                x = jax.nn.relu(y + idn)
        x = jnp.mean(x, axis=(1, 2) if nhwc else (2, 3))
        return x @ p["fc.w"] + p["fc.b"]

    return fwd


def make_step(fwd):
    def loss_of(params, x, y):
        pb = {k: v.astype(jnp.bfloat16) if v.dtype == jnp.float32 else v
              for k, v in params.items()}
        logits = fwd(pb, x.astype(jnp.bfloat16)).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))

    def sgd(params, mom, grads):
        newp, newm = {}, {}
        for k in params:
            m = MOM * mom[k] + grads[k] + WD * params[k]
            newm[k] = m
            newp[k] = params[k] - LR * m
        return newp, newm

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, mom, x, y):
        l, g = jax.value_and_grad(loss_of)(params, x, y)
        newp, newm = sgd(params, mom, g)
        return newp, newm, l

    @functools.partial(jax.jit, donate_argnums=(0, 1), static_argnums=4)
    def step_n(params, mom, x, y, n):
        def body(carry, _):
            p, m = carry
            l, g = jax.value_and_grad(loss_of)(p, x, y)
            return sgd(p, m, g), l

        (p, m), ls = jax.lax.scan(body, (params, mom), None, length=n)
        return p, m, ls[-1]

    return loss_of, step, step_n


def timed_diff(run, fetch, k1, k2, repeats=3):
    def loop(k):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = run()
        fetch(r)
        return time.perf_counter() - t0

    diffs = []
    for _ in range(repeats):
        d1, d2 = loop(k1), loop(k2)
        if d2 > d1:
            diffs.append((d2 - d1) / (k2 - k1))
    if not diffs:
        raise RuntimeError("degenerate timing")
    diffs.sort()
    return diffs


def compile_step(step, params, mom, x, y):
    """AOT-compile once; returns (executable, flops). The executable is
    reused for the timed loop — the plain jit call path would NOT reuse
    it and would pay a second full compile."""
    compiled = step.lower(params, mom, x, y).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return compiled, (ca or {}).get("flops", 0)


def run_variant(nhwc, s2d=False, fuse=8):
    tag = ("nhwc" if nhwc else "nchw") + ("_s2d" if s2d else "")
    fwd = make_fwd(nhwc, s2d)
    params = init_params(nhwc, s2d)
    mom = {k: jnp.zeros_like(v) for k, v in params.items()}
    if s2d:
        shape = (BATCH, 112, 112, 12)
    else:
        shape = (BATCH, 224, 224, 3) if nhwc else (BATCH, 3, 224, 224)
    rng = onp.random.RandomState(1)
    x = jnp.array(rng.uniform(-1, 1, shape).astype("float32"))
    y = jnp.array(rng.randint(0, 1000, (BATCH,)).astype("int32"))
    _, step, step_n = make_step(fwd)

    compiled, flops = compile_step(step, params, mom, x, y)
    rows = []

    # -- single dispatch ---------------------------------------------
    state = [params, mom]

    def run1():
        p, m, l = compiled(state[0], state[1], x, y)
        state[0], state[1] = p, m
        return l

    float(run1())  # drain
    diffs = timed_diff(run1, float, 3, 15)
    dt = diffs[len(diffs) // 2]
    rows.append({
        "variant": tag, "img_s": round(BATCH / dt, 1),
        "ms_per_step": round(dt * 1e3, 2),
        "mfu": round(flops / dt / PEAK, 4),
        "counted_gf_per_img": round(flops / 1e9 / BATCH, 1),
        "n": len(diffs),
        "spread_img_s": [round(BATCH / diffs[-1], 1),
                         round(BATCH / diffs[0], 1)],
    })

    # -- fused: `fuse` steps per dispatch (bench fused8 protocol) ----
    # `state` still holds the live post-step buffers (the originals were
    # donated away by the single-dispatch loop)

    def runf():
        p, m, l = step_n(state[0], state[1], x, y, fuse)
        state[0], state[1] = p, m
        return l

    float(runf())
    diffs = timed_diff(runf, float, 2, 8)
    dt = diffs[len(diffs) // 2] / fuse
    rows.append({
        "variant": f"{tag}_fused{fuse}", "img_s": round(BATCH / dt, 1),
        "ms_per_step": round(dt * 1e3, 2),
        "mfu": round(flops / dt / PEAK, 4),
        "counted_gf_per_img": round(flops / 1e9 / BATCH, 1),
        "n": len(diffs),
        "spread_img_s": [round(fuse * BATCH / diffs[-1], 1),
                         round(fuse * BATCH / diffs[0], 1)],
    })
    for r in rows:
        print(json.dumps(r), flush=True)
    return rows


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which not in ("all", "nchw", "nhwc", "s2d"):
        sys.exit(f"unknown variant {which!r}: use all|nchw|nhwc|s2d")
    dev = jax.devices()[0]
    print(f"# device: {dev.device_kind}", file=sys.stderr)
    rows = []
    if which in ("all", "nchw"):
        rows += run_variant(False)
    if which in ("all", "nhwc"):
        rows += run_variant(True)
    if which in ("all", "s2d"):
        rows += run_variant(True, s2d=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "resnet_control.json")
    prior = []
    if os.path.exists(out) and which != "all":
        with open(out) as f:
            prior = [r for r in json.load(f)
                     if not any(r["variant"] == n["variant"] for n in rows)]
    with open(out, "w") as f:
        json.dump(prior + rows, f, indent=2)
    print(f"# wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
