"""Head-to-head: pallas vs XLA attention at BERT shapes; threefry vs rbg RNG."""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp

sys.path.insert(0, "/root/repo")


def timed(fn, fetch, k1=5, k2=55, reps=3):
    fetch(fn())
    diffs = []
    for _ in range(reps):
        def t(k):
            t0 = time.perf_counter()
            r = None
            for _ in range(k):
                r = fn()
            fetch(r)
            return time.perf_counter() - t0
        d1, d2 = t(k1), t(k2)
        if d2 > d1:
            diffs.append((d2 - d1) / (k2 - k1))
    diffs.sort()
    return diffs[len(diffs) // 2]


def attn_bench(seqs=(128, 512, 2048)):
    from mxnet_tpu.ops.pallas import flash_attention as fa

    B, H, D = 64, 12, 64
    for T in seqs:
        b = B if T <= 512 else 8
        q = jnp.array(onp.random.randn(b, H, T, D) * 0.1, dtype=jnp.bfloat16)
        k = jnp.array(onp.random.randn(b, H, T, D) * 0.1, dtype=jnp.bfloat16)
        v = jnp.array(onp.random.randn(b, H, T, D) * 0.1, dtype=jnp.bfloat16)
        vl = jnp.array(onp.random.randint(T // 2, T + 1, (b,)), dtype=jnp.int32)

        for use_flash in (True, False):
            def loss(q, k, v):
                o = fa.attention(q, k, v, valid_length=vl,
                                 use_flash=use_flash)
                return jnp.sum(o.astype(jnp.float32))

            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            try:
                dt = timed(lambda: g(q, k, v),
                           lambda r: onp.asarray(r[0].reshape(-1)[0]),
                           k1=3, k2=33)
            except Exception as e:
                print(f"T{T} flash={use_flash}: FAIL {e}")
                continue
            fl = 4 * 2 * b * H * T * T * D * 3  # fwd+bwd ~3x, qk+av
            print(f"attn T{T} b{b} flash={use_flash}: {dt*1e3:.3f} ms "
                  f"({fl/dt/1e12:.1f} TF/s)")


def rng_bench():
    shape = (64, 128, 768)
    for impl in ("threefry2x32", "rbg"):
        key = jax.random.PRNGKey(0, impl=impl)

        @jax.jit
        def gen(key):
            k1 = jax.random.fold_in(key, 1)
            xs = [jax.random.bernoulli(jax.random.fold_in(k1, i), 0.9, shape)
                  for i in range(10)]
            s = jnp.zeros(shape[1:], jnp.float32)
            for x in xs:
                s = s + jnp.sum(x, axis=0)
            return s

        dt = timed(lambda: gen(key),
                   lambda r: onp.asarray(r.reshape(-1)[0]), k1=2, k2=22)
        per = dt / 10
        nbytes = 64 * 128 * 768
        print(f"rng {impl}: {per*1e3:.3f} ms per (64,128,768) bernoulli "
              f"({nbytes/per/1e9:.0f} GB/s of mask)")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "attn"):
        attn_bench()
    if which in ("all", "rng"):
        rng_bench()
