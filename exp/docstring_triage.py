"""Triage runner: execute every reference docstring block in a file and
summarize pass/fail, so the conformance tests' skip-lists are built from
evidence. Usage: python exp/docstring_triage.py numpy/multiarray.py [-v]
"""
import os
import sys
import traceback

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

import jax

jax.config.update("jax_platforms", "cpu")

from docstring_harness import collect_blocks, default_globs, run_block, \
    reset_mode, ExampleFailure  # noqa: E402


def main(relpath, verbose=False, legacy=False):
    blocks = collect_blocks(relpath)
    ok, fails = [], []
    for qn, exs in blocks:
        reset_mode(legacy)
        globs = default_globs()
        try:
            run_block(exs, globs)
            ok.append(qn)
        except ExampleFailure as e:
            fails.append((qn, str(e)))
        except Exception:
            fails.append((qn, "HARNESS ERROR\n" + traceback.format_exc()))
    print(f"{relpath}: {len(ok)} blocks pass, {len(fails)} fail "
          f"(of {len(blocks)})")
    for qn, msg in fails:
        first = msg if verbose else msg.split("\n")[0]
        print(f"  FAIL {qn}: {first}")
        if verbose:
            print()
    return 1 if fails else 0


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    sys.exit(main(args[0], verbose="-v" in sys.argv,
                  legacy="--legacy" in sys.argv))
