"""int8 vs bf16 conv rates at each ResNet-50 layer shape (bs32, NHWC)."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as onp


def scan_rate(make_step, x0, flops, m1=20, m2=620, reps=3):
    @functools.partial(jax.jit, static_argnums=1)
    def run(x, m):
        def body(c, _):
            return make_step(c), None
        out, _ = jax.lax.scan(body, x, None, length=m)
        return out

    onp.asarray(jax.tree_util.tree_leaves(run(x0, m1))[0].reshape(-1)[0])
    onp.asarray(jax.tree_util.tree_leaves(run(x0, m2))[0].reshape(-1)[0])

    def t(m):
        t0 = time.perf_counter()
        r = run(x0, m)
        onp.asarray(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0])
        return time.perf_counter() - t0

    diffs = []
    for _ in range(reps):
        d1, d2 = t(m1), t(m2)
        if d2 > d1:
            diffs.append((d2 - d1) / (m2 - m1))
    diffs.sort()
    return diffs[len(diffs) // 2]


B = 32
CASES = [
    ("conv0 7x7s2", 224, 3, 64, 7, 2),
    ("s0 1x1 64-64", 56, 64, 64, 1, 1),
    ("s0 3x3 64-64", 56, 64, 64, 3, 1),
    ("s0 1x1 64-256", 56, 64, 256, 1, 1),
    ("s0 1x1 256-64", 56, 256, 64, 1, 1),
    ("s1 3x3 128", 28, 128, 128, 3, 1),
    ("s1 1x1 512-128", 28, 512, 128, 1, 1),
    ("s2 3x3 256", 14, 256, 256, 3, 1),
    ("s3 3x3 512", 7, 512, 512, 3, 1),
]

for name, H, Ci, Co, k, s in CASES:
    oh = H // s
    fl = 2 * B * oh * oh * Ci * Co * k * k
    row = [name]
    for mode in ("int8", "bf16"):
        dt_ = []
        if mode == "int8":
            x = jnp.array(onp.random.randint(-10, 10, (B, H, H, Ci)),
                          dtype=jnp.int8)
            w = jnp.array(onp.random.randint(-10, 10, (k, k, Ci, Co)),
                          dtype=jnp.int8)

            def step(xx, w=w, k=k, s=s, Ci=Ci, Co=Co, H=H, oh=oh):
                p = (k - 1) // 2 if k > 1 else 0
                pads = [(p, p), (p, p)] if k > 1 else [(0, 0), (0, 0)]
                if k == 7:
                    pads = [(3, 3), (3, 3)]
                acc = jax.lax.conv_general_dilated(
                    xx, w, (s, s), pads,
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    preferred_element_type=jnp.int32)
                y = (acc >> 6).astype(jnp.int8)
                # project back to input shape cheaply for chaining
                m = jnp.mean(y.astype(jnp.float32)) * 1e-9
                return xx + m.astype(jnp.int8)
        else:
            x = jnp.array(onp.random.randn(B, H, H, Ci) * 0.1,
                          dtype=jnp.bfloat16)
            w = jnp.array(onp.random.randn(k, k, Ci, Co) * 0.1,
                          dtype=jnp.bfloat16)

            def step(xx, w=w, k=k, s=s, Ci=Ci, Co=Co, H=H, oh=oh):
                p = (k - 1) // 2 if k > 1 else 0
                pads = [(p, p), (p, p)] if k > 1 else [(0, 0), (0, 0)]
                if k == 7:
                    pads = [(3, 3), (3, 3)]
                y = jax.lax.conv_general_dilated(
                    xx, w, (s, s), pads,
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                m = jnp.mean(y.astype(jnp.float32)) * 1e-9
                return xx + m.astype(xx.dtype)

        dt = scan_rate(step, x, fl)
        row.append(f"{mode} {dt*1e6:7.1f} us {fl/dt/1e12:6.1f} T/s")
    print(" | ".join(row))
