"""Diagnose the eager per-op cost: python dispatch vs tunnel vs device."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp

sys.path.insert(0, "/root/repo")


def rate(fn, n=300, drain=None):
    fn()
    (drain or (lambda: None))()
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    (drain or (lambda: onp.asarray(jax.tree_util.tree_leaves(r)[0]) if r is not None else None))()
    return (time.perf_counter() - t0) / n


x = jnp.ones((64, 128), jnp.float32)
f = jax.jit(lambda a: a + 1)

# 1. raw jitted-call dispatch rate, NO sync until end
per = rate(lambda: f(x), 300, drain=None)
print(f"jit call (async, drain at end): {per*1e6:.0f} us/call")

# 2. with a sync every call
per = rate(lambda: onp.asarray(f(x)[0, 0]), 30)
print(f"jit call + fetch every call:    {per*1e6:.0f} us/call")

# 3. the repo's registry.apply path (eager NDArray op)
from mxnet_tpu import np as mnp  # noqa: E402

a = mnp.ones((64, 128))
per = rate(lambda: a + 1, 300)
print(f"mx eager op (async):            {per*1e6:.0f} us/call")

# 4. LeNet fwd+bwd+step op count estimate: time one full eager step,
#    counting registry.apply invocations
from mxnet_tpu.ops import registry  # noqa: E402

count = [0]
orig = registry.apply


def counting_apply(*args, **kw):
    count[0] += 1
    return orig(*args, **kw)


registry.apply = counting_apply
from mxnet_tpu import autograd, gluon  # noqa: E402

net = gluon.nn.HybridSequential()
net.add(gluon.nn.Conv2D(6, 5, activation="relu"), gluon.nn.MaxPool2D(2),
        gluon.nn.Conv2D(16, 5, activation="relu"), gluon.nn.MaxPool2D(2),
        gluon.nn.Flatten(), gluon.nn.Dense(120, activation="relu"),
        gluon.nn.Dense(84, activation="relu"), gluon.nn.Dense(10))
net.initialize()
xx = mnp.array(onp.random.randn(64, 1, 28, 28).astype("float32"))
yy = mnp.array(onp.random.randint(0, 10, (64,)))
with autograd.predict_mode():
    net(xx)
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})


def step():
    with autograd.record():
        l = loss_fn(net(xx), yy).mean()
    l.backward()
    tr.step(1)
    return l


float(step().asnumpy())
count[0] = 0
t0 = time.perf_counter()
l = step()
n_ops = count[0]
t_host = time.perf_counter() - t0
float(l.asnumpy())
t_total = time.perf_counter() - t0
print(f"lenet step: {n_ops} registry.apply calls, host-side {t_host*1e3:.1f} "
      f"ms, total w/ drain {t_total*1e3:.1f} ms")
registry.apply = orig
