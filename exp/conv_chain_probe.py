#!/usr/bin/env python
"""Independent conv-chain probe at the dominant ResNet-50 layer shapes
(VERDICT r3 item 3a: make the roofline claim adversarially verifiable).

The round-3 ResNet MFU bound (0.294-0.302) was computed from XLA
cost_analysis of the shipped train step — self-referential. This probe
measures the SAME conv shapes in isolation, with bytes and FLOPs counted
from first principles (tensor-size arithmetic, independent of XLA's
accounting):

* each shape runs as an on-device `lax.scan` chain (iteration i's input
  is iteration i-1's output, so XLA cannot elide or parallelize
  iterations), sized to >= ~0.3 s of device time;
* achieved GB/s = analytic bytes / measured time; achieved TF/s =
  analytic FLOPs / time;
* XLA's own cost_analysis bytes for the same compiled chain are reported
  next to the analytic count, so a reader can check the two agree.

If the per-shape achieved bandwidth sits at the HBM roof while MXU
utilization sits far below the compute roof, the ResNet-50 bound is
hardware behavior for these shapes — not an artifact of the end-to-end
program. Writes exp/conv_chain_probe.json; summarized in PERF.md.

    python exp/conv_chain_probe.py             # on the real chip
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as onp

# dominant ResNet-50 bs256 layers. Each spec is a CYCLE of convs whose
# composition is channel-stable (so a lax.scan can chain it): the 3x3
# stage convs cycle alone; the bottleneck 1x1s cycle as the
# expand/reduce pair they form in the real network. Together these
# shapes carry ~85% of the train-step FLOPs (cost_analysis
# decomposition, exp/decomp.py). Entries: (Cin, Cout, k).
SHAPES = [
    ("stage1_3x3", 256, 56, [(64, 64, 3)]),
    ("stage2_3x3", 256, 28, [(128, 128, 3)]),
    ("stage3_3x3", 256, 14, [(256, 256, 3)]),
    ("stage1_1x1_pair", 256, 56, [(64, 256, 1), (256, 64, 1)]),
    ("stage2_1x1_pair", 256, 28, [(512, 128, 1), (128, 512, 1)]),
]

BF16 = jnp.bfloat16


@functools.partial(jax.jit, static_argnums=(2,))
def chain(x, ws, n):
    """n iterations of the conv cycle (NCHW, stride 1, same padding),
    relu after every conv — the real ResNet motif, and load-bearing for
    the measurement twice over: (1) relu + the He-scaled weights keep
    magnitudes stable with NO extra memory sweep (a max-abs
    normalization costs 3 activation sweeps and triples the body's
    traffic — measured, first probe revision); (2) the nonlinearity
    stops XLA from algebraically collapsing a 1x1 expand/reduce pair
    into one composed matmul (measured: the un-relu'd pair read
    1540 "GB/s", i.e. the 256-channel intermediate never left VMEM)."""
    def body(carry, _):
        y = carry
        for w in ws:
            y = jax.lax.conv_general_dilated(
                y, w, (1, 1), "SAME",
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                preferred_element_type=BF16)
            y = jax.nn.relu(y)
        return y, None

    out, _ = jax.lax.scan(body, x, None, length=n)
    return jnp.sum(out.astype(jnp.float32))


def probe_one(name, b, h, convs, target_s=0.4):
    rng = onp.random.RandomState(0)
    cin0 = convs[0][0]
    x = jnp.asarray(rng.randn(b, cin0, h, h).astype("float32") * 0.1,
                    dtype=BF16)
    ws = tuple(
        jnp.asarray(rng.randn(cout, cin, k, k).astype("float32")
                    * (2.0 / (cin * k * k)) ** 0.5, dtype=BF16)
        for cin, cout, k in convs)

    flops = sum(2.0 * b * h * h * cout * cin * k * k
                for cin, cout, k in convs)
    bytes_analytic = sum(
        2.0 * (b * cin * h * h              # read activation
               + b * cout * h * h           # write activation
               + cout * cin * k * k)        # weights (resident)
        for cin, cout, k in convs)

    # size the chain from a short calibration run
    n0 = 8
    onp.asarray(chain(x, ws, n0))  # compile + drain
    t0 = time.perf_counter()
    onp.asarray(chain(x, ws, n0))
    dt0 = time.perf_counter() - t0
    per = max(dt0 / n0, 1e-5)
    n = max(n0, int(target_s / per))

    def run(m):
        t1 = time.perf_counter()
        onp.asarray(chain(x, ws, m))
        return time.perf_counter() - t1

    onp.asarray(chain(x, ws, n))      # compile the big sizes
    onp.asarray(chain(x, ws, 2 * n))
    diffs = []
    for _ in range(5):
        d1, d2 = run(n), run(2 * n)
        if d2 > d1:
            diffs.append((d2 - d1) / n)
    if not diffs:
        raise RuntimeError(f"degenerate timing for {name}")
    diffs.sort()
    per_cycle = diffs[len(diffs) // 2]

    ca = chain.lower(x, ws, n).compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    # XLA counts a scan body ONCE regardless of trip count, so its total
    # is directly the per-cycle figure; the ratio vs the analytic count
    # checks the two byte accountings against each other
    xla_bytes_per = (ca or {}).get("bytes accessed", 0)

    return {
        "shape": name,
        "cycle": [f"{cin}->{cout} k{k}" for cin, cout, k in convs],
        "input": f"B{b} {cin0}x{h}x{h} bf16",
        "ms_per_cycle": round(per_cycle * 1e3, 3),
        "analytic_gbs": round(bytes_analytic / per_cycle / 1e9, 1),
        "xla_bytes_ratio": round(xla_bytes_per / bytes_analytic, 2)
        if bytes_analytic else None,
        "achieved_tfs": round(flops / per_cycle / 1e12, 1),
        "mxu_util": round(flops / per_cycle / 197e12, 3),
        "hbm_util": round(bytes_analytic / per_cycle / 819e9, 3),
        "n_chain": n,
        "n_samples": len(diffs),
        "spread_ms": [round(diffs[0] * 1e3, 3), round(diffs[-1] * 1e3, 3)],
    }


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.device_kind}", file=sys.stderr)
    rows = []
    for spec in SHAPES:
        row = probe_one(*spec)
        rows.append(row)
        print(json.dumps(row), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "conv_chain_probe.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)
    print(f"# wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
