"""Per-op device profile of the repo's BERT pretrain step (bench config)."""
import sys

sys.path.insert(0, "/root/repo")

import numpy as onp  # noqa: E402

from mxnet_tpu import autograd, gluon, profiler  # noqa: E402
from mxnet_tpu import np as mnp  # noqa: E402
from mxnet_tpu.gluon.block import HybridBlock  # noqa: E402
from mxnet_tpu.models.bert import BERTForPretrain, get_bert_model  # noqa: E402

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 64
SEQ = 128


class PretrainStep(HybridBlock):
    def __init__(self, model):
        super().__init__()
        self.model = model

    def forward(self, tokens):
        valid_length = (tokens != 0).sum(axis=1)
        return self.model(tokens, valid_length=valid_length)


net = PretrainStep(BERTForPretrain(get_bert_model("bert_12_768_12")))
net.initialize()
tokens = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
tokens[::4, SEQ - 16:] = 0
with autograd.predict_mode():
    net(mnp.array(tokens[:1, :16]))

ce = gluon.loss.SoftmaxCrossEntropyLoss()


def loss_fn(outs, labels):
    mlm_scores, nsp_scores = outs
    mlm_labels, nsp_labels = labels
    return ce(mlm_scores, mlm_labels).mean() + ce(nsp_scores, nsp_labels).mean()


mlm_labels = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
nsp_labels = onp.random.randint(0, 2, (BATCH,)).astype("int32")

import jax  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from mxnet_tpu.parallel import ShardedTrainer, ShardingRules, make_mesh  # noqa: E402

mesh = make_mesh({"dp": len(jax.devices())})
trainer = ShardedTrainer(net, loss_fn, "adam", {"learning_rate": 1e-4},
                         mesh=mesh, rules=ShardingRules(default_axis=None),
                         dtype="bfloat16")
sh = NamedSharding(mesh, P("dp"))
data = jax.device_put(tokens, sh)
labels = (jax.device_put(mlm_labels, sh), jax.device_put(nsp_labels, sh))
loss = trainer.step(data, labels)
float(loss.asnumpy().reshape(-1)[0])

import time  # noqa: E402

# timed
def t(k):
    t0 = time.perf_counter()
    r = None
    for _ in range(k):
        r = trainer.step(data, labels)
    float(r.asnumpy().reshape(-1)[0])
    return time.perf_counter() - t0


diffs = []
for _ in range(3):
    d1, d2 = t(3), t(15)
    if d2 > d1:
        diffs.append((d2 - d1) / 12)
diffs.sort()
dt = diffs[len(diffs) // 2]
flops = trainer.step_flops or 0
print(f"bert bs{BATCH}: {dt*1e3:.2f} ms {BATCH/dt:.0f} samp/s "
      f"MFU {flops/dt/197e12:.3f} counted {flops/1e9:.0f} GF/step")

profiler.set_config(filename="/tmp/bert_prof.json", profile_xla=True)
profiler.set_state("run")
for _ in range(3):
    loss = trainer.step(data, labels)
float(loss.asnumpy().reshape(-1)[0])
profiler.set_state("stop")
print(profiler.device_op_table(by_category=True, top=15))
print()
print(profiler.device_op_table(top=30))
