"""Profile the fused int8 ResNet inference to find non-conv overhead."""
import sys

sys.path.insert(0, "/root/repo")

import functools  # noqa: E402
import time  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, profiler  # noqa: E402
from mxnet_tpu import np as mnp  # noqa: E402
from mxnet_tpu.contrib.quantization import quantize_net  # noqa: E402
from mxnet_tpu.parallel.functional import functionalize  # noqa: E402

BATCH, SIZE = 32, 224
net = gluon.model_zoo.vision.resnet50_v1()
net.initialize(ctx=mx.cpu())
with autograd.predict_mode():
    net(mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32"), ctx=mx.cpu()))
xc = mnp.array(onp.random.uniform(-1, 1, (8, 3, SIZE, SIZE)).astype("float32"),
               ctx=mx.cpu())
quantize_net(net, calib_data=xc, calib_mode="naive")
net.reset_ctx(mx.tpu())

apply_fn, params = functionalize(net, train_mode=False)
x = jnp.asarray(onp.random.uniform(-1, 1, (BATCH, 3, SIZE, SIZE))
                .astype("float32"))


@functools.partial(jax.jit, static_argnums=2)
def run(params, x, m):
    def body(carry, _):
        out = apply_fn(params, x + carry)
        logits = jax.tree_util.tree_leaves(out)[0]
        return jnp.mean(logits).astype(x.dtype) * 1e-12, None

    c, _ = jax.lax.scan(body, jnp.zeros((), x.dtype), None, length=m)
    return c


with autograd.predict_mode():
    onp.asarray(run(params, x, 16))
    profiler.set_config(filename="/tmp/int8_prof.json", profile_xla=True)
    profiler.set_state("run")
    onp.asarray(run(params, x, 16))
    profiler.set_state("stop")
print(profiler.device_op_table(by_category=True, top=12))
print()
print(profiler.device_op_table(top=25))
