"""Decompose the ResNet step: per-stage conv rates, BN cost, fwd vs train."""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp

PEAK = 197e12
HBM = 819e9


def scan_rate(make_step, x0, m1=20, m2=220, reps=3):
    @functools.partial(jax.jit, static_argnums=1)
    def run(x, m):
        def body(c, _):
            return make_step(c), None
        out, _ = jax.lax.scan(body, x, None, length=m)
        return out

    onp.asarray(jax.tree_util.tree_leaves(run(x0, m1))[0].reshape(-1)[0])
    onp.asarray(jax.tree_util.tree_leaves(run(x0, m2))[0].reshape(-1)[0])

    def t(m):
        t0 = time.perf_counter()
        r = run(x0, m)
        onp.asarray(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0])
        return time.perf_counter() - t0

    diffs = []
    for _ in range(reps):
        d1, d2 = t(m1), t(m2)
        if d2 > d1:
            diffs.append((d2 - d1) / (m2 - m1))
    diffs.sort()
    return diffs[len(diffs) // 2]


def conv_probe():
    B = 256
    cases = [  # (H, Cin, Cout, k, stride-label)
        (56, 64, 64, 3), (56, 64, 256, 1), (56, 256, 64, 1),
        (28, 128, 128, 3), (28, 512, 128, 1),
        (14, 256, 256, 3), (7, 512, 512, 3),
    ]
    for H, Ci, Co, k in cases:
        x = jnp.array(onp.random.randn(B, H, H, Ci), dtype=jnp.bfloat16)
        w = jnp.array(onp.random.randn(k, k, Ci, Co) * 0.05,
                      dtype=jnp.bfloat16)
        wb = jnp.array(onp.random.randn(1, 1, Co, Ci) * 0.05,
                       dtype=jnp.bfloat16)
        p = (k - 1) // 2

        def step(x, w=w, wb=wb, p=p):
            y = jax.lax.conv_general_dilated(
                x, w, (1, 1), [(p, p), (p, p)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            # back to Cin so we can chain
            return jax.lax.conv_general_dilated(
                y, wb, (1, 1), [(0, 0), (0, 0)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        fl = 2 * B * H * H * Ci * Co * k * k + 2 * B * H * H * Ci * Co
        # adapt scan length: target ~0.5s total diff
        est = fl / (0.5 * PEAK)
        m2 = max(40, min(1500, int(0.5 / est)))
        dt = scan_rate(step, x, 20, 20 + m2)
        print(f"conv {H}x{H} {Ci}->{Co} k{k} (+1x1 back): "
              f"{dt*1e3:.3f} ms {fl/dt/1e12:.1f} TF/s ({fl/dt/PEAK*100:.0f}%)")


def bn_probe():
    B = 256
    for H, C in [(56, 256), (28, 512), (14, 1024)]:
        x = jnp.array(onp.random.randn(B, H, H, C), dtype=jnp.bfloat16)
        g = jnp.ones(C, jnp.bfloat16)
        b = jnp.zeros(C, jnp.bfloat16)

        def step(x, g=g, b=b):
            m = jnp.mean(x, axis=(0, 1, 2))
            v = jnp.var(x, axis=(0, 1, 2))
            return (x - m) * (g / jnp.sqrt(v + 1e-5)) + b

        bytes_ = x.size * 2 * 2  # read + write
        est = bytes_ * 3 / HBM  # ~3 passes
        m2 = max(40, min(1000, int(0.5 / est)))
        dt = scan_rate(step, x, 10, 10 + m2)
        print(f"bn {H}x{H}x{C}: {dt*1e3:.3f} ms "
              f"{x.size*2*2/dt/1e9:.0f} GB/s eff (r+w once)")


def fwd_vs_train():
    sys.path.insert(0, "/root/repo/exp")
    from resnet_bound import BATCH, init_params, make_fwd

    fwd = make_fwd(True)
    params = init_params(jax.random.PRNGKey(0), True)
    pb = {k: v.astype(jnp.bfloat16) for k, v in params.items()}
    x = jnp.array(onp.random.uniform(-1, 1, (BATCH, 224, 224, 3)),
                  dtype=jnp.bfloat16)

    f = jax.jit(lambda p, x: fwd(p, x))
    lowered = f.lower(pb, x)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    print("fwd counted GF/img:", ca.get("flops", 0) / 1e9 / BATCH)
    r = compiled(pb, x)
    onp.asarray(r[0, 0])

    def t(k):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = compiled(pb, x)
        onp.asarray(r[0, 0])
        return time.perf_counter() - t0

    diffs = []
    for _ in range(3):
        d1, d2 = t(3), t(23)
        if d2 > d1:
            diffs.append((d2 - d1) / 20)
    diffs.sort()
    dt = diffs[len(diffs) // 2]
    fl = ca.get("flops", 0)
    print(f"fwd only: {dt*1e3:.2f} ms  {BATCH/dt:.0f} img/s  "
          f"MFU {fl/dt/PEAK:.3f}")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "conv"):
        conv_probe()
    if which in ("all", "bn"):
        bn_probe()
    if which in ("all", "fwd"):
        fwd_vs_train()
