"""BERT train with step_n fused windows on chip."""
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as onp  # noqa: E402

from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu import np as mnp  # noqa: E402
from mxnet_tpu.gluon.block import HybridBlock  # noqa: E402
from mxnet_tpu.models.bert import BERTForPretrain, get_bert_model  # noqa: E402

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 64
FUSE = int(sys.argv[2]) if len(sys.argv) > 2 else 8
SEQ = 128


class PretrainStep(HybridBlock):
    def __init__(self, model):
        super().__init__()
        self.model = model

    def forward(self, tokens):
        valid_length = (tokens != 0).sum(axis=1)
        return self.model(tokens, valid_length=valid_length)


net = PretrainStep(BERTForPretrain(get_bert_model("bert_12_768_12")))
net.initialize()
tokens = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
tokens[::4, SEQ - 16:] = 0
with autograd.predict_mode():
    net(mnp.array(tokens[:1, :16]))

ce = gluon.loss.SoftmaxCrossEntropyLoss()


def loss_fn(outs, labels):
    mlm_scores, nsp_scores = outs
    mlm_labels, nsp_labels = labels
    return ce(mlm_scores, mlm_labels).mean() + ce(nsp_scores, nsp_labels).mean()


mlm_labels = onp.random.randint(1, 30000, (BATCH, SEQ)).astype("int32")
nsp_labels = onp.random.randint(0, 2, (BATCH,)).astype("int32")

import jax  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from mxnet_tpu.parallel import ShardedTrainer, ShardingRules, make_mesh  # noqa: E402

mesh = make_mesh({"dp": len(jax.devices())})
trainer = ShardedTrainer(net, loss_fn, "adam", {"learning_rate": 1e-4},
                         mesh=mesh, rules=ShardingRules(default_axis=None),
                         dtype="bfloat16")


def stack(a):
    return onp.broadcast_to(a[None], (FUSE,) + a.shape).copy()


sh = NamedSharding(mesh, P(None, "dp"))
data = jax.device_put(stack(tokens), sh)
labels = (jax.device_put(stack(mlm_labels), sh),
          jax.device_put(stack(nsp_labels), sh))

ls = trainer.step_n(data, labels)
float(ls.asnumpy().reshape(-1)[-1])


def t(k):
    t0 = time.perf_counter()
    r = None
    for _ in range(k):
        r = trainer.step_n(data, labels)
    float(r.asnumpy().reshape(-1)[-1])
    return time.perf_counter() - t0


diffs = []
for _ in range(3):
    d1, d2 = t(2), t(8)
    if d2 > d1:
        diffs.append((d2 - d1) / 6)
diffs.sort()
dt = diffs[len(diffs) // 2] / FUSE
flops = trainer.step_flops or 0
print(f"bert bs{BATCH} fused{FUSE}: {dt*1e3:.2f} ms/step "
      f"{BATCH/dt:.0f} samp/s MFU {flops/dt/197e12:.3f}")
