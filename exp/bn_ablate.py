"""Ablate BN formulation in the hand ResNet: two-pass vs single-pass vs none.

Also: full train-step timing for each, and HLO op census.
"""
import collections
import functools
import re
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp

sys.path.insert(0, "/root/repo/exp")
from resnet_bound import BATCH, STAGES, init_params  # noqa: E402

PEAK = 197e12


def make_fwd(bn_mode):
    dn = ("NHWC", "HWIO", "NHWC")

    def conv(x, w, stride=1, pad=None):
        k = w.shape[0]
        if pad is None:
            pad = (k - 1) // 2
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=dn)

    def bnorm(x, g, b):
        C = x.shape[3]
        if bn_mode == "none":
            return x + b.reshape(1, 1, 1, C)
        if bn_mode == "onepass":
            s = jnp.mean(x.astype(jnp.float32), axis=(0, 1, 2))
            s2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=(0, 1, 2))
            v = s2 - jnp.square(s)
            inv = (g / jnp.sqrt(v + 1e-5).astype(g.dtype)).reshape(1, 1, 1, C)
            return (x - s.astype(x.dtype).reshape(1, 1, 1, C)) * inv \
                + b.reshape(1, 1, 1, C)
        m = jnp.mean(x, axis=(0, 1, 2))
        v = jnp.var(x, axis=(0, 1, 2))
        sh = (1, 1, 1, C)
        inv = (g / jnp.sqrt(v + 1e-5)).reshape(sh)
        return (x - m.reshape(sh)) * inv + b.reshape(sh)

    def fwd(p, x):
        x = conv(x, p["conv0"], 2, pad=3)
        x = jax.nn.relu(bnorm(x, p["bn0.g"], p["bn0.b"]))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            [(0, 0), (1, 1), (1, 1), (0, 0)])
        for si, (blocks, mid, stride) in enumerate(STAGES):
            for bi in range(blocks):
                st = stride if bi == 0 else 1
                pre = f"s{si}b{bi}"
                idn = x
                y = jax.nn.relu(bnorm(conv(x, p[pre + ".c1"]),
                                      p[pre + ".n1.g"], p[pre + ".n1.b"]))
                y = jax.nn.relu(bnorm(conv(y, p[pre + ".c2"], st),
                                      p[pre + ".n2.g"], p[pre + ".n2.b"]))
                y = bnorm(conv(y, p[pre + ".c3"]),
                          p[pre + ".n3.g"], p[pre + ".n3.b"])
                if bi == 0:
                    idn = bnorm(conv(idn, p[pre + ".cd"], st),
                                p[pre + ".nd.g"], p[pre + ".nd.b"])
                x = jax.nn.relu(y + idn)
        x = jnp.mean(x, axis=(1, 2))
        return x @ p["fc.w"] + p["fc.b"]

    return fwd


def train_time(bn_mode, batch=BATCH):
    fwd = make_fwd(bn_mode)
    params = init_params(jax.random.PRNGKey(0), True)
    mom = {k: jnp.zeros_like(v) for k, v in params.items()}
    x = jnp.array(onp.random.uniform(-1, 1, (batch, 224, 224, 3)),
                  dtype=jnp.float32)
    y = jnp.array(onp.random.randint(0, 1000, (batch,)), dtype=jnp.int32)

    def loss_of(params, x, y):
        pb = {k: v.astype(jnp.bfloat16) if v.dtype == jnp.float32 else v
              for k, v in params.items()}
        logits = fwd(pb, x.astype(jnp.bfloat16)).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, mom, x, y):
        l, g = jax.value_and_grad(loss_of)(params, x, y)
        newp, newm = {}, {}
        for k in params:
            m = 0.9 * mom[k] + g[k] + 1e-4 * params[k]
            newm[k] = m
            newp[k] = params[k] - 0.1 * m
        return newp, newm, l

    compiled = step.lower(params, mom, x, y).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    flops = ca.get("flops", 0)
    state = [params, mom]

    def run():
        p, m, l = compiled(state[0], state[1], x, y)
        state[0], state[1] = p, m
        return l

    float(run())

    def t(k):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = run()
        float(r)
        return time.perf_counter() - t0

    diffs = []
    for _ in range(3):
        d1, d2 = t(3), t(13)
        if d2 > d1:
            diffs.append((d2 - d1) / 10)
    diffs.sort()
    dt = diffs[len(diffs) // 2]
    print(f"train bn={bn_mode} bs{batch}: {dt*1e3:.2f} ms  "
          f"{batch/dt:.0f} img/s  MFU {flops/dt/PEAK:.3f} "
          f"({flops/1e9/batch:.1f} GF/img)")
    return compiled


def hlo_census(compiled):
    txt = compiled.as_text()
    ops = collections.Counter()
    bytes_by = collections.Counter()
    for line in txt.splitlines():
        m = re.match(r"\s*(?:ROOT )?%?[\w.-]+ = (\w+)\[([\d,]*)\]", line)
        if not m:
            continue
        mm = re.search(r"= (\w+)\[([\d,]*)\][^ ]* (\w+)\(", line)
        if not mm:
            continue
        dtype, shape, op = mm.group(1), mm.group(2), mm.group(3)
        n = 1
        for s in shape.split(","):
            if s:
                n *= int(s)
        sz = n * (2 if dtype in ("bf16", "f16") else 4)
        ops[op] += 1
        bytes_by[op] += sz
    for op, cnt in ops.most_common(18):
        print(f"  {op:25s} x{cnt:4d}  out {bytes_by[op]/1e6:9.1f} MB")


if __name__ == "__main__":
    for mode in ("twopass", "onepass", "none"):
        c = train_time(mode)
        if mode == "twopass":
            print("HLO census (twopass):")
            hlo_census(c)
    train_time("twopass", batch=512)
