"""Experiment harnesses (perf probes, memory proofs) — importable so the
driver dryrun and tests share one config definition per experiment."""
