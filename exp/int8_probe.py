"""Probe: int8 matmul and conv rates vs bf16 on v5e."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as onp

PEAK_BF16 = 197e12


def scan_rate(make_step, x0, flops, m1=20, m2=320, reps=3):
    @functools.partial(jax.jit, static_argnums=1)
    def run(x, m):
        def body(c, _):
            return make_step(c), None
        out, _ = jax.lax.scan(body, x, None, length=m)
        return out

    onp.asarray(jax.tree_util.tree_leaves(run(x0, m1))[0].reshape(-1)[0])
    onp.asarray(jax.tree_util.tree_leaves(run(x0, m2))[0].reshape(-1)[0])

    def t(m):
        t0 = time.perf_counter()
        r = run(x0, m)
        onp.asarray(jax.tree_util.tree_leaves(r)[0].reshape(-1)[0])
        return time.perf_counter() - t0

    diffs = []
    for _ in range(reps):
        d1, d2 = t(m1), t(m2)
        if d2 > d1:
            diffs.append((d2 - d1) / (m2 - m1))
    diffs.sort()
    return diffs[len(diffs) // 2], flops / (diffs[len(diffs) // 2])


def probe_matmul():
    n = 4096
    w8 = jnp.array(onp.random.randint(-127, 127, (n, n)), dtype=jnp.int8)

    x8 = jnp.array(onp.random.randint(-127, 127, (n, n)), dtype=jnp.int8)

    def step_int8(x):
        acc = jax.lax.dot_general(x, w8, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return (acc >> 8).astype(jnp.int8)

    dt, rate = scan_rate(step_int8, x8, 2 * n**3)
    print(f"int8 matmul {n}: {dt*1e3:.3f} ms {rate/1e12:.1f} TOP/s "
          f"({rate/PEAK_BF16:.2f}x bf16 peak)")


def probe_conv():
    B, C, H, K = 32, 256, 14, 256
    x8 = jnp.array(onp.random.randint(-10, 10, (B, H, H, C)), dtype=jnp.int8)
    w8 = jnp.array(onp.random.randint(-10, 10, (3, 3, C, K)), dtype=jnp.int8)
    wb = jnp.array(onp.random.randint(-10, 10, (1, 1, K, C)), dtype=jnp.int8)

    def step(x):
        acc = jax.lax.conv_general_dilated(
            x, w8, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
        y = (acc >> 6).astype(jnp.int8)
        acc2 = jax.lax.conv_general_dilated(
            y, wb, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
        return (acc2 >> 6).astype(jnp.int8)

    fl = 2 * B * H * H * C * K * 9 + 2 * B * H * H * C * K
    dt, rate = scan_rate(step, x8, fl, m2=620)
    print(f"int8 conv NHWC 14x14x256 b32: {dt*1e3:.3f} ms {rate/1e12:.1f} "
          f"TOP/s ({rate/PEAK_BF16:.2f}x bf16 peak)")

    # bf16 same conv for comparison
    xb = x8.astype(jnp.bfloat16)
    wbf = w8.astype(jnp.bfloat16)
    wbb = wb.astype(jnp.bfloat16)

    def stepb(x):
        y = jax.lax.conv_general_dilated(
            x, wbf, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC")) * 0.01
        return jax.lax.conv_general_dilated(
            y, wbb, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NHWC", "HWIO", "NHWC")) * 0.01

    dt, rate = scan_rate(stepb, xb, fl, m2=620)
    print(f"bf16 conv NHWC 14x14x256 b32: {dt*1e3:.3f} ms {rate/1e12:.1f} "
          f"TF/s")

    # NCHW int8 conv (the repo's current layout)
    x8n = jnp.array(onp.random.randint(-10, 10, (B, C, H, H)), dtype=jnp.int8)
    w8n = jnp.array(onp.random.randint(-10, 10, (K, C, 3, 3)), dtype=jnp.int8)
    wbn = jnp.array(onp.random.randint(-10, 10, (C, K, 1, 1)), dtype=jnp.int8)

    def stepn(x):
        acc = jax.lax.conv_general_dilated(
            x, w8n, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.int32)
        y = (acc >> 6).astype(jnp.int8)
        acc2 = jax.lax.conv_general_dilated(
            y, wbn, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.int32)
        return (acc2 >> 6).astype(jnp.int8)

    dt, rate = scan_rate(stepn, x8n, fl, m2=620)
    print(f"int8 conv NCHW: {dt*1e3:.3f} ms {rate/1e12:.1f} TOP/s")


if __name__ == "__main__":
    probe_matmul()
    probe_conv()
