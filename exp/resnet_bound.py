"""Upper-bound experiment: hand-rolled ResNet-50 v1 train step in pure JAX.

Variants: NCHW vs NHWC layouts, optional space-to-depth conv0.
Mirrors ShardedTrainer's step content (bf16 compute, fp32 master, SGD+mom,
donated buffers) to find what the repo path SHOULD deliver.
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp

PEAK = 197e12
BATCH = 256

# resnet50 v1: stages (blocks, mid_channels, stride)
STAGES = [(3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2)]


def init_params(key, nhwc, s2d=False):
    p = {}
    rng = onp.random.RandomState(0)

    def conv_w(name, cin, cout, k):
        if nhwc:
            w = rng.randn(k, k, cin, cout) * (2.0 / (k * k * cin)) ** 0.5
        else:
            w = rng.randn(cout, cin, k, k) * (2.0 / (k * k * cin)) ** 0.5
        p[name] = w.astype("float32")

    def bn(name, c):
        p[name + ".g"] = onp.ones(c, "float32")
        p[name + ".b"] = onp.zeros(c, "float32")

    if s2d:
        conv_w("conv0", 3 * 16, 64, 2)  # 4x4 space-to-depth: 8x8 kernel -> 2x2
    else:
        conv_w("conv0", 3, 64, 7)
    bn("bn0", 64)
    cin = 64
    for si, (blocks, mid, stride) in enumerate(STAGES):
        cout = mid * 4
        for bi in range(blocks):
            st = stride if bi == 0 else 1
            pre = f"s{si}b{bi}"
            conv_w(pre + ".c1", cin, mid, 1)
            bn(pre + ".n1", mid)
            conv_w(pre + ".c2", mid, mid, 3)
            bn(pre + ".n2", mid)
            conv_w(pre + ".c3", mid, cout, 1)
            bn(pre + ".n3", cout)
            if bi == 0:
                conv_w(pre + ".cd", cin, cout, 1)
                bn(pre + ".nd", cout)
            cin = cout
    p["fc.w"] = (rng.randn(2048, 1000) * 0.01).astype("float32")
    p["fc.b"] = onp.zeros(1000, "float32")
    return {k: jnp.array(v) for k, v in p.items()}


def make_fwd(nhwc, s2d=False):
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    caxis = 3 if nhwc else 1

    def conv(x, w, stride=1, pad=None):
        k = w.shape[0] if nhwc else w.shape[2]
        if pad is None:
            pad = (k - 1) // 2
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=dn)

    def bnorm(x, g, b):
        axes = tuple(i for i in range(4) if i != caxis)
        m = jnp.mean(x, axis=axes)
        v = jnp.var(x, axis=axes)
        sh = [1, 1, 1, 1]
        sh[caxis] = x.shape[caxis]
        inv = (g / jnp.sqrt(v + 1e-5)).reshape(sh)
        return (x - m.reshape(sh)) * inv + b.reshape(sh)

    def fwd(p, x):
        if s2d:
            # x pre-transformed on host: (B,56,56,48) for nhwc
            x = conv(x, p["conv0"], 2, pad=0)
        else:
            x = conv(x, p["conv0"], 2, pad=3)
        x = jax.nn.relu(bnorm(x, p["bn0.g"], p["bn0.b"]))
        # maxpool 3x3 s2
        if nhwc:
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                [(0, 0), (1, 1), (1, 1), (0, 0)])
        else:
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
                [(0, 0), (0, 0), (1, 1), (1, 1)])
        cin = 64
        for si, (blocks, mid, stride) in enumerate(STAGES):
            for bi in range(blocks):
                st = stride if bi == 0 else 1
                pre = f"s{si}b{bi}"
                idn = x
                y = jax.nn.relu(bnorm(conv(x, p[pre + ".c1"]),
                                      p[pre + ".n1.g"], p[pre + ".n1.b"]))
                y = jax.nn.relu(bnorm(conv(y, p[pre + ".c2"], st),
                                      p[pre + ".n2.g"], p[pre + ".n2.b"]))
                y = bnorm(conv(y, p[pre + ".c3"]),
                          p[pre + ".n3.g"], p[pre + ".n3.b"])
                if bi == 0:
                    idn = bnorm(conv(idn, p[pre + ".cd"], st),
                                p[pre + ".nd.g"], p[pre + ".nd.b"])
                x = jax.nn.relu(y + idn)
        x = jnp.mean(x, axis=(1, 2) if nhwc else (2, 3))
        return x @ p["fc.w"] + p["fc.b"]

    return fwd


def main(nhwc=True, s2d=False):
    fwd = make_fwd(nhwc, s2d)
    params = init_params(jax.random.PRNGKey(0), nhwc, s2d)
    mom = {k: jnp.zeros_like(v) for k, v in params.items()}
    if s2d:
        shape = (BATCH, 56, 56, 48) if nhwc else (BATCH, 48, 56, 56)
    else:
        shape = (BATCH, 224, 224, 3) if nhwc else (BATCH, 3, 224, 224)
    x = jnp.array(onp.random.uniform(-1, 1, shape), dtype=jnp.float32)
    y = jnp.array(onp.random.randint(0, 1000, (BATCH,)), dtype=jnp.int32)

    def loss_of(params, x, y):
        pb = {k: v.astype(jnp.bfloat16) if v.dtype == jnp.float32 else v
              for k, v in params.items()}
        logits = fwd(pb, x.astype(jnp.bfloat16)).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, mom, x, y):
        l, g = jax.value_and_grad(loss_of)(params, x, y)
        newp, newm = {}, {}
        for k in params:
            m = 0.9 * mom[k] + g[k] + 1e-4 * params[k]
            newm[k] = m
            newp[k] = params[k] - 0.1 * m
        return newp, newm, l

    lowered = step.lower(params, mom, x, y)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    flops = ca.get("flops", 0)

    state = [params, mom]

    def run():
        p, m, l = compiled(state[0], state[1], x, y)
        state[0], state[1] = p, m
        return l

    float(run())  # drain

    def t(k):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = run()
        float(r)
        return time.perf_counter() - t0

    diffs = []
    for _ in range(3):
        d1, d2 = t(3), t(15)
        if d2 > d1:
            diffs.append((d2 - d1) / 12)
    diffs.sort()
    dt = diffs[len(diffs) // 2]
    tag = ("NHWC" if nhwc else "NCHW") + ("+s2d" if s2d else "")
    print(f"resnet50 {tag}: {dt*1e3:.2f} ms/step  {BATCH/dt:.0f} img/s  "
          f"counted {flops/1e9/BATCH:.1f} GF/img  MFU {flops/dt/PEAK:.3f}")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "nhwc"):
        main(True)
    if which in ("all", "nchw"):
        main(False)
    if which in ("all", "s2d"):
        main(True, True)
