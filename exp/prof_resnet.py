"""Per-op device profile of the hand ResNet train step."""
import sys

import jax
import numpy as onp

sys.path.insert(0, "/root/repo/exp")
sys.path.insert(0, "/root/repo")

from bn_ablate import train_time  # noqa: E402

from mxnet_tpu import profiler  # noqa: E402

mode = sys.argv[1] if len(sys.argv) > 1 else "twopass"
compiled = train_time(mode)  # compiles + times, leaves compiled step

# re-run under trace
import functools  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from resnet_bound import BATCH, init_params  # noqa: E402

params = init_params(jax.random.PRNGKey(0), True)
mom = {k: jnp.zeros_like(v) for k, v in params.items()}
x = jnp.array(onp.random.uniform(-1, 1, (BATCH, 224, 224, 3)),
              dtype=jnp.float32)
y = jnp.array(onp.random.randint(0, 1000, (BATCH,)), dtype=jnp.int32)
p, m, l = compiled(params, mom, x, y)
float(l)

profiler.set_config(filename="/tmp/rn_prof.json", profile_xla=True)
profiler.set_state("run")
for _ in range(3):
    p, m, l = compiled(p, m, x, y)
float(l)
profiler.set_state("stop")
print(profiler.device_op_table(by_category=True, top=20))
print()
print(profiler.device_op_table(top=25))
