#!/usr/bin/env python
"""Pallas attempt on the probe's worst conv shape (VERDICT r4 Next #1b).

`exp/conv_chain_probe.json` names the bottleneck 1x1 expand/reduce
pairs as the shapes where XLA's conv kernels leave the most on the
table (stage2 pair: 0.22 MXU).  This probe measures THREE formulations
of the same relu-chained pair cycle, same protocol as the conv probe
(on-device lax.scan chain so XLA cannot elide iterations, two-loop
timing, 5 samples):

  xla_conv    — NCHW `conv_general_dilated` pair (the baseline the
                framework's ResNet actually runs; re-measured here so
                all arms share one session's tunnel weather)
  xla_matmul  — channels-last (M, C) layout, the pair as two `jnp.dot`s
                (what a layout-rewrite alone would buy, no Pallas)
  pallas      — `mxnet_tpu.ops.pallas.conv1x1.conv1x1_pair`: both
                matmuls in ONE kernel, mid-channel intermediate pinned
                in VMEM (block_rows tuned per shape from a short sweep)

Fused-pair HBM floor: per row the pair does 4*C1*Cm flops against
4*C1 bytes of x-in + y-out traffic — AI = Cm flops/byte.  stage2
(Cm=128, machine balance 240) is HBM-bound with a fused ceiling of
~0.53 MXU; stage1 (Cm=256) sits right at the balance point.  Writes
exp/pallas_1x1_probe.json with the win/loss verdict per shape.

    python exp/pallas_1x1_probe.py
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as onp

from mxnet_tpu.ops.pallas.conv1x1 import conv1x1_pair

BF16 = jnp.bfloat16
PEAK = float(os.environ.get("MXNET_TPU_PEAK_FLOPS", 197e12))

# (name, batch, hw, C1, Cm): pair cycles C1 -> Cm -> C1
SHAPES = [
    ("stage1_1x1_pair", 256, 56, 64, 256),
    ("stage2_1x1_pair", 256, 28, 512, 128),
]


@functools.partial(jax.jit, static_argnums=(3,))
def chain_conv(x, w1, w2, n):
    def body(y, _):
        for w in (w1, w2):
            y = jax.lax.conv_general_dilated(
                y, w, (1, 1), "SAME",
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                preferred_element_type=BF16)
            y = jax.nn.relu(y)
        return y, None

    out, _ = jax.lax.scan(body, x, None, length=n)
    return jnp.sum(out.astype(jnp.float32))


@functools.partial(jax.jit, static_argnums=(3,))
def chain_matmul(x, w1, w2, n):
    def body(y, _):
        h = jax.nn.relu(jnp.dot(y, w1, preferred_element_type=BF16))
        return jax.nn.relu(jnp.dot(h, w2, preferred_element_type=BF16)), None

    out, _ = jax.lax.scan(body, x, None, length=n)
    return jnp.sum(out.astype(jnp.float32))


@functools.partial(jax.jit, static_argnums=(3, 4))
def chain_pallas(x, w1, w2, n, block_rows):
    def body(y, _):
        return conv1x1_pair(y, w1, w2, block_rows=block_rows), None

    out, _ = jax.lax.scan(body, x, None, length=n)
    return jnp.sum(out.astype(jnp.float32))


def measure(run_n, target_s=0.4):
    """Two-loop chain timing, probe protocol: returns (ms, samples)."""
    n0 = 8
    onp.asarray(run_n(n0))
    t0 = time.perf_counter()
    onp.asarray(run_n(n0))
    per = max((time.perf_counter() - t0) / n0, 1e-5)
    n = max(n0, int(target_s / per))
    onp.asarray(run_n(n))
    onp.asarray(run_n(2 * n))

    def t(m):
        t1 = time.perf_counter()
        onp.asarray(run_n(m))
        return time.perf_counter() - t1

    diffs = []
    for _ in range(5):
        d1, d2 = t(n), t(2 * n)
        if d2 > d1:
            diffs.append((d2 - d1) / n)
    if not diffs:
        raise RuntimeError("degenerate timing")
    diffs.sort()
    return diffs[len(diffs) // 2], diffs, n


def probe_shape(name, b, hw, c1, cm):
    rng = onp.random.RandomState(0)
    m = b * hw * hw
    he1 = (2.0 / c1) ** 0.5
    he2 = (2.0 / cm) ** 0.5
    w1 = jnp.asarray(rng.randn(c1, cm) * he1, dtype=BF16)
    w2 = jnp.asarray(rng.randn(cm, c1) * he2, dtype=BF16)
    w1_oihw = jnp.asarray(onp.asarray(w1, "float32").T
                          .reshape(cm, c1, 1, 1), dtype=BF16)
    w2_oihw = jnp.asarray(onp.asarray(w2, "float32").T
                          .reshape(c1, cm, 1, 1), dtype=BF16)
    x_nchw = jnp.asarray(rng.randn(b, c1, hw, hw) * 0.1, dtype=BF16)
    x_rows = jnp.asarray(
        onp.asarray(x_nchw, "float32").transpose(0, 2, 3, 1)
        .reshape(m, c1), dtype=BF16)
    flops = 2.0 * 2 * m * c1 * cm

    rows = {}
    ms, diffs, n = measure(
        lambda k: chain_conv(x_nchw, w1_oihw, w2_oihw, k))
    rows["xla_conv"] = {"ms": round(ms * 1e3, 3),
                        "mxu": round(flops / ms / PEAK, 3),
                        "spread_ms": [round(diffs[0] * 1e3, 3),
                                      round(diffs[-1] * 1e3, 3)],
                        "n_chain": n, "n_samples": len(diffs)}
    ms, diffs, n = measure(
        lambda k: chain_matmul(x_rows, w1, w2, k))
    rows["xla_matmul"] = {"ms": round(ms * 1e3, 3),
                          "mxu": round(flops / ms / PEAK, 3),
                          "spread_ms": [round(diffs[0] * 1e3, 3),
                                        round(diffs[-1] * 1e3, 3)],
                          "n_chain": n, "n_samples": len(diffs)}

    # short block_rows sweep, then the full measurement at the winner
    best_br, best_t = None, None
    for br in (512, 1024, 2048, 4096):
        if m % br:
            continue
        try:
            # warm BOTH static signatures the timed comparison uses —
            # (3,4) are static_argnums, so n=8 and n=24 compile
            # separately and an unwarmed n=24 would time compilation
            onp.asarray(chain_pallas(x_rows, w1, w2, 8, br))
            onp.asarray(chain_pallas(x_rows, w1, w2, 24, br))
        except Exception as e:  # VMEM OOM at large tiles: skip
            print(f"#   block_rows={br}: {type(e).__name__} (skipped)",
                  file=sys.stderr)
            continue
        t0 = time.perf_counter()
        onp.asarray(chain_pallas(x_rows, w1, w2, 24, br))
        dt = time.perf_counter() - t0
        print(f"#   block_rows={br}: {dt*1e3/24:.3f} ms", file=sys.stderr)
        if best_t is None or dt < best_t:
            best_br, best_t = br, dt
    if best_br is None:
        raise RuntimeError(
            f"{name}: no feasible block_rows candidate (M={m})")
    ms, diffs, n = measure(
        lambda k: chain_pallas(x_rows, w1, w2, k, best_br))
    rows["pallas"] = {"ms": round(ms * 1e3, 3),
                      "mxu": round(flops / ms / PEAK, 3),
                      "block_rows": best_br,
                      "spread_ms": [round(diffs[0] * 1e3, 3),
                                    round(diffs[-1] * 1e3, 3)],
                      "n_chain": n, "n_samples": len(diffs)}

    # fused HBM floor: x-in + y-out only
    fused_bytes = 2.0 * 2 * m * c1
    hbm_floor_ms = fused_bytes / 819e9 * 1e3
    out = {
        "shape": name,
        "cycle": f"{c1}->{cm}->{c1}",
        "rows_M": m,
        "flops_per_cycle_G": round(flops / 1e9, 2),
        "fused_hbm_floor_ms": round(hbm_floor_ms, 3),
        "arms": rows,
        "speedup_pallas_vs_conv": round(
            rows["xla_conv"]["ms"] / rows["pallas"]["ms"], 2),
        "speedup_pallas_vs_matmul": round(
            rows["xla_matmul"]["ms"] / rows["pallas"]["ms"], 2),
    }
    out["verdict"] = ("win" if out["speedup_pallas_vs_conv"] > 1.05
                      else "loss" if out["speedup_pallas_vs_conv"] < 0.95
                      else "tie")
    return out


def main():
    print(f"# device: {jax.devices()[0].device_kind}", file=sys.stderr)
    results = []
    for spec in SHAPES:
        r = probe_shape(*spec)
        results.append(r)
        print(json.dumps(r), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "pallas_1x1_probe.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"# wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
