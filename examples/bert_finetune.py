#!/usr/bin/env python
"""BERT classifier fine-tuning — the GluonNLP sentence-classification
flow on the TPU-native stack (flash attention + bf16 SPMD step).

Synthetic "sentiment" task: sequences whose token-id distribution leaks
the label, so convergence is verifiable without a dataset.

    python examples/bert_finetune.py --steps 30
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def synthetic_batch(rng, batch, seq, vocab, num_classes):
    y = rng.randint(0, num_classes, batch)
    # class-dependent token bias: class c draws more tokens near c*vocab/C
    x = rng.randint(1, vocab, (batch, seq))
    for i, c in enumerate(y):
        center = 1 + int((c + 0.5) * (vocab - 1) / num_classes)
        n_bias = seq // 2
        x[i, :n_bias] = rng.randint(max(1, center - 50),
                                    min(vocab, center + 50), n_bias)
    lengths = rng.randint(seq // 2, seq + 1, batch)
    for i, L in enumerate(lengths):
        x[i, L:] = 0  # pad: valid_length masks these in-kernel
    return x.astype("int32"), y.astype("int32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--classes", type=int, default=2)
    ap.add_argument("--layers", type=int, default=2,
                    help="encoder layers (12 = full BERT-base)")
    args = ap.parse_args(argv)

    import jax

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.models.bert import BERTClassifier, get_bert_model
    from mxnet_tpu.parallel import (ShardedTrainer, ShardingRules, make_mesh)

    VOCAB = 1000

    class Step(HybridBlock):
        """valid_length derived from the pad mask inside the trace."""

        def __init__(self, model):
            super().__init__()
            self.model = model

        def forward(self, tokens):
            vl = (tokens != 0).sum(axis=1)
            return self.model(tokens, valid_length=vl)

    bert = get_bert_model("bert_12_768_12", vocab_size=VOCAB,
                          num_layers=args.layers, dropout=0.1)
    net = Step(BERTClassifier(bert, num_classes=args.classes))
    net.initialize()
    rng = onp.random.RandomState(0)
    with autograd.predict_mode():
        net(mnp.array(onp.ones((1, 8), "int32")))

    mesh = make_mesh({"dp": len(jax.devices())})
    trainer = ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 3e-4}, mesh=mesh,
        rules=ShardingRules(default_axis=None), dtype="bfloat16")

    x, y = synthetic_batch(rng, args.batch_size, args.seq_len, VOCAB,
                           args.classes)
    first = last = None
    for step in range(args.steps):
        loss = float(trainer.step(x, y).asnumpy())
        if first is None:
            first = loss
        last = loss
        if step % max(1, args.steps // 5) == 0:
            print(f"step {step}: loss={loss:.4f}")
    print(f"loss {first:.3f} -> {last:.3f}")
    return 0 if last < first else 1


if __name__ == "__main__":
    sys.exit(main())
