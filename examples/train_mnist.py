#!/usr/bin/env python
"""LeNet on MNIST — the reference's example/gluon/mnist flow.

Runs on TPU when a chip is visible (mx.tpu()), else CPU. ``--synthetic``
trains on generated digits so the example works with no dataset or
network access.

    python examples/train_mnist.py --epochs 2 --synthetic
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np


def build_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"),
            gluon.nn.Dense(10))
    return net


def synthetic_digits(n, seed=0):
    """Separable fake digits: class-dependent blob positions + noise."""
    rng = onp.random.RandomState(seed)
    ys = rng.randint(0, 10, n)
    xs = rng.randn(n, 1, 28, 28).astype("float32") * 0.1
    for i, y in enumerate(ys):
        r, c = divmod(int(y), 4)
        xs[i, 0, 4 + r * 7:11 + r * 7, 4 + c * 6:11 + c * 6] += 1.0
    return xs, ys.astype("float32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--samples", type=int, default=2048)
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU even when a TPU is visible")
    args = ap.parse_args(argv)

    if args.cpu:
        ctx = mx.cpu()
    else:
        try:
            ctx = mx.tpu()
            ctx.jax_device()
        except Exception:
            ctx = mx.cpu()
    print(f"training on {ctx}")

    if args.synthetic:
        X, Y = synthetic_digits(args.samples)
        dataset = gluon.data.ArrayDataset(X, Y)
    else:
        from mxnet_tpu.gluon.data.vision import MNIST
        from mxnet_tpu.gluon.data.vision.transforms import ToTensor

        dataset = MNIST(train=True).transform_first(ToTensor())
    loader = gluon.data.DataLoader(dataset, batch_size=args.batch_size,
                                   shuffle=True)

    net = build_net()
    net.initialize(init=mx.init.Xavier(), ctx=ctx)
    net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        t0 = time.perf_counter()
        for data, label in loader:
            data = np.array(data.asnumpy(), ctx=ctx)
            label = np.array(label.asnumpy(), ctx=ctx)
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label).mean()
            loss.backward()
            trainer.step(1)
            metric.update([label], [out])
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.3f} "
              f"loss={float(loss.asnumpy()):.4f} "
              f"({time.perf_counter() - t0:.1f}s)")
    return 0 if acc > 0.5 else 1


if __name__ == "__main__":
    sys.exit(main())
