#!/usr/bin/env python
"""Fast Gradient Sign Method adversarial examples (the reference
``example/adversary`` notebook workflow): train a small classifier, then
take the gradient OF THE LOSS WITH RESPECT TO THE INPUT
(``x.attach_grad()`` — inputs are first-class tape leaves, same as
parameters) and perturb along its sign to flip predictions.

    python examples/adversary_fgsm.py
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mnp
from mxnet_tpu.gluon import nn


def make_data(rng, n):
    """Two gaussian blobs rendered as 8x8 'images' (top vs bottom lit)."""
    imgs = rng.rand(n, 1, 8, 8).astype("float32") * 0.2
    labels = rng.randint(0, 2, n)
    for i, l in enumerate(labels):
        rows = slice(0, 4) if l == 0 else slice(4, 8)
        imgs[i, 0, rows] += 0.5
    return imgs, labels.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--epsilon", type=float, default=0.5)
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (eager per-op dispatch over a "
                         "tunneled TPU is RTT-bound; see PERF.md)")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    rng = onp.random.RandomState(0)
    net = nn.HybridSequential()
    # Flatten, not global pooling: the class signal is WHERE the light is,
    # which a global average erases
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2), nn.Flatten(), nn.Dense(2))
    net.initialize(init=mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})

    for step in range(args.steps):
        imgs, labels = make_data(rng, 64)
        x, y = mnp.array(imgs), mnp.array(labels)
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(64)
    imgs, labels = make_data(rng, 256)
    with autograd.predict_mode():
        acc = (net(mnp.array(imgs)).asnumpy().argmax(1) == labels).mean()
    print(f"clean accuracy: {acc:.3f}")
    assert acc > 0.95, "classifier failed to train"

    # FGSM: x_adv = x + eps * sign(dL/dx)
    x = mnp.array(imgs)
    x.attach_grad()
    with autograd.record():
        loss = loss_fn(net(x), mnp.array(labels)).mean()
    loss.backward()
    x_adv = x + args.epsilon * mx.nd.sign(x.grad)
    with autograd.predict_mode():
        adv_acc = (net(x_adv).asnumpy().argmax(1) == labels).mean()
    print(f"adversarial accuracy (eps={args.epsilon}): {adv_acc:.3f}")
    assert adv_acc < acc - 0.2, (
        "FGSM failed to find adversarial directions — input gradients "
        "may be broken")
    print(f"FGSM dropped accuracy by {acc - adv_acc:.3f}")


if __name__ == "__main__":
    main()
