#!/usr/bin/env python
"""Character-level RNN language model (the reference ``example/rnn``
workflow on the Gluon API): embedding → LSTM → per-step Dense, trained
with truncated BPTT over a synthetic corpus with learnable structure
(repeating key phrases), then sampled autoregressively.

    python examples/char_rnn.py --steps 60
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mnp
from mxnet_tpu.gluon import nn, rnn

CORPUS = ("the quick brown fox jumps over the lazy dog. "
          "pack my box with five dozen liquor jugs. ") * 40


class CharRNN(gluon.block.HybridBlock):
    def __init__(self, vocab, hidden=64, layers=1, **kwargs):
        super().__init__(**kwargs)
        self.embed = nn.Embedding(vocab, 16)
        self.lstm = rnn.LSTM(hidden, num_layers=layers)
        self.head = nn.Dense(vocab, flatten=False)

    def forward(self, x, state=None):
        # x: (T, B) int tokens -> logits (T, B, vocab)
        e = self.embed(x)
        if state is None:
            out = self.lstm(e)
        else:
            out, state = self.lstm(e, state)
        return self.head(out), state


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--bptt", type=int, default=32)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (eager per-op dispatch over a "
                         "tunneled TPU is RTT-bound; see PERF.md)")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    chars = sorted(set(CORPUS))
    stoi = {c: i for i, c in enumerate(chars)}
    data = onp.array([stoi[c] for c in CORPUS], onp.int32)

    net = CharRNN(len(chars))
    net.initialize(init=mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})

    rng = onp.random.RandomState(0)
    first = last = None
    for step in range(args.steps):
        starts = rng.randint(0, len(data) - args.bptt - 1, args.batch)
        x = onp.stack([data[s:s + args.bptt] for s in starts], axis=1)
        y = onp.stack([data[s + 1:s + args.bptt + 1] for s in starts],
                      axis=1)
        with autograd.record():
            logits, _ = net(mnp.array(x))
            loss = loss_fn(logits.reshape(-1, len(chars)),
                           mnp.array(y.reshape(-1))).mean()
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.asnumpy())
        first = v if first is None else first
        last = v
        if step % 10 == 0:
            print(f"step {step:3d} ppl {onp.exp(v):8.2f}")

    print(f"loss {first:.3f} -> {last:.3f}")
    assert last < first * 0.8, "char LM failed to learn"

    # autoregressive sampling: warm the state on the seed once, then feed
    # ONE token per step with the carried LSTM state — fixed (1, 1) input
    # shape means one compile, not one per sequence length
    seed = "the "
    idx = [stoi[c] for c in seed]
    with autograd.predict_mode():
        logits, state = net(mnp.array(
            onp.array(idx, onp.int32).reshape(-1, 1)))
        nxt = int(logits.asnumpy()[-1, 0].argmax())
        for _ in range(40):
            idx.append(nxt)
            logits, state = net(
                mnp.array(onp.array([[nxt]], onp.int32)), state)
            nxt = int(logits.asnumpy()[-1, 0].argmax())
    text = "".join(chars[i] for i in idx)
    print("sample:", repr(text))


if __name__ == "__main__":
    main()
