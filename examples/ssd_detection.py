#!/usr/bin/env python
"""SSD-style detection training step — the reference example-zoo detection
workflow (multibox priors → targets → loss → decode + NMS) on the
TPU-native op family (`npx.multibox_*`, `npx.box_nms`).

Synthetic task: images containing one axis-aligned bright square; the
toy detector learns to localize it. Verifies the full train/infer loop
end to end without a dataset.

    python examples/ssd_detection.py --steps 20
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, npx
from mxnet_tpu import np as mnp


class ToySSD(gluon.block.HybridBlock):
    """Tiny single-scale SSD head: backbone conv -> cls + loc predictions
    per anchor (2 classes incl. background, A anchors per cell)."""

    def __init__(self, num_anchors, num_classes=2):
        super().__init__()
        self.num_anchors = num_anchors
        self.num_classes = num_classes
        self.backbone = gluon.nn.HybridSequential()
        self.backbone.add(
            gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2))
        self.cls_head = gluon.nn.Conv2D(num_anchors * num_classes, 3,
                                        padding=1)
        self.loc_head = gluon.nn.Conv2D(num_anchors * 4, 3, padding=1)

    def forward(self, x):
        feat = self.backbone(x)
        cls = self.cls_head(feat)    # (B, A*C, H, W)
        loc = self.loc_head(feat)    # (B, A*4, H, W)
        b = cls.shape[0]
        h, w = cls.shape[2], cls.shape[3]
        cls = cls.reshape(b, self.num_anchors, self.num_classes, h * w)
        cls = cls.transpose(0, 2, 1, 3).reshape(
            b, self.num_classes, self.num_anchors * h * w)
        loc = loc.reshape(b, self.num_anchors, 4, h * w)
        loc = loc.transpose(0, 3, 1, 2).reshape(b, -1)
        return feat, cls, loc


def synth_batch(rng, batch, size=32):
    """Images with one bright 8px square; labels [cls, x1, y1, x2, y2]."""
    imgs = rng.rand(batch, 1, size, size).astype("float32") * 0.1
    labels = onp.zeros((batch, 1, 5), "float32")
    for i in range(batch):
        cx = rng.randint(4, size - 12)
        cy = rng.randint(4, size - 12)
        imgs[i, 0, cy:cy + 8, cx:cx + 8] = 1.0
        labels[i, 0] = [0, cx / size, cy / size, (cx + 8) / size,
                        (cy + 8) / size]
    return imgs, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    rng = onp.random.RandomState(0)
    sizes, ratios = [0.25, 0.35], [1.0, 2.0]
    na = len(sizes) + len(ratios) - 1
    net = ToySSD(na)
    net.initialize(init=mx.init.Xavier())
    imgs, labels = synth_batch(rng, args.batch)
    with autograd.predict_mode():
        feat, _, _ = net(mnp.array(imgs))
    anchors = npx.multibox_prior(feat, sizes=sizes, ratios=ratios)

    ce = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    l1 = gluon.loss.L1Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})

    first = last = None
    for step in range(args.steps):
        imgs, labels = synth_batch(rng, args.batch)
        x = mnp.array(imgs)
        y = mnp.array(labels)
        with autograd.record():
            _, cls_pred, loc_pred = net(x)
            box_t, box_m, cls_t = npx.multibox_target(anchors, y, cls_pred)
            # mask ignore_label (-1) anchors out of the classification
            # loss (they appear once hard-negative mining is enabled)
            valid = cls_t >= 0
            cls_l = ce(cls_pred, cls_t * valid, sample_weight=valid).mean()
            # box_target is already zero-masked; mask the predictions the
            # same way so unmatched anchors contribute no location loss
            loc_l = l1(loc_pred * box_m, box_t).mean()
            loss = cls_l + loc_l
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.asnumpy())
        first = v if first is None else first
        last = v
        if step % 5 == 0:
            print(f"step {step:3d} loss {v:.4f}")

    print(f"loss {first:.4f} -> {last:.4f}")
    assert last < first, "detection loss failed to decrease"

    # inference: decode + NMS
    with autograd.predict_mode():
        _, cls_pred, loc_pred = net(mnp.array(imgs))
        probs = npx.softmax(cls_pred, axis=1)
        dets = npx.multibox_detection(probs, loc_pred, anchors,
                                      nms_topk=10)
    top = dets.asnumpy()[0][:3]
    print("top detections [id score x1 y1 x2 y2]:")
    for row in top:
        print("  ", onp.round(row, 3))


if __name__ == "__main__":
    main()
