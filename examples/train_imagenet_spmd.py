#!/usr/bin/env python
"""ResNet-50 SPMD training — the reference's
example/image-classification/train_imagenet.py redone TPU-first.

One `ShardedTrainer` step = forward + backward + gradient collectives +
optimizer, compiled into a single pjit program over the device mesh; bf16
AMP by default. Synthetic data keeps the example self-contained; swap in
an `ImageRecordIter` over an im2rec-packed .rec for real ImageNet.

    python examples/train_imagenet_spmd.py --steps 20 --batch-size 256
    # multi-host:
    python tools/launch.py -n 4 python examples/train_imagenet_spmd.py
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50_v1")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--no-amp", action="store_true",
                    help="disable bf16 AMP (fp32 compute)")
    ap.add_argument("--fuse", type=int, default=1,
                    help="steps fused per dispatch (step_n window)")
    args = ap.parse_args(argv)

    import jax

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.parallel import (ShardedTrainer, ShardingRules,
                                    initialize_distributed, make_mesh)

    if os.environ.get("MXNET_TPU_NUM_PROCS"):
        initialize_distributed()  # launched via tools/launch.py
    mesh = make_mesh({"dp": len(jax.devices())})
    print(f"mesh: {mesh.shape} over {len(jax.devices())} device(s)")

    net = getattr(gluon.model_zoo.vision, args.model)()
    net.initialize()
    with autograd.predict_mode():
        net(mnp.array(onp.zeros((1, 3, 64, 64), dtype="float32")))

    trainer = ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9, "wd": 1e-4},
        mesh=mesh, rules=ShardingRules(default_axis=None),
        dtype=None if args.no_amp else "bfloat16")

    rng = onp.random.RandomState(0)
    shape = (args.batch_size, 3, args.image_size, args.image_size)
    x = rng.uniform(-1, 1, shape).astype("float32")
    y = rng.randint(0, 1000, (args.batch_size,)).astype("int32")

    if args.fuse > 1:
        x = onp.broadcast_to(x[None], (args.fuse,) + x.shape).copy()
        y = onp.broadcast_to(y[None], (args.fuse,) + y.shape).copy()

    t0 = time.perf_counter()
    done = 0
    while done < args.steps:
        if args.fuse > 1:
            losses = trainer.step_n(x, y)
            loss = float(losses.asnumpy()[-1])
            done += args.fuse
        else:
            loss = float(trainer.step(x, y).asnumpy())
            done += 1
        if done % max(1, args.steps // 5) < args.fuse:
            dt = time.perf_counter() - t0
            print(f"step {done}: loss={loss:.4f} "
                  f"({done * args.batch_size / dt:.0f} img/s avg)")
    trainer.sync_to_block()
    print(f"trained {done} steps; step FLOPs "
          f"{(trainer.step_flops or 0) / 1e12:.2f}T")
    return 0


if __name__ == "__main__":
    sys.exit(main())
