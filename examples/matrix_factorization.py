#!/usr/bin/env python
"""Matrix-factorization recommender (the reference
``example/recommenders`` workflow): user/item embeddings with
``sparse_grad=True`` — each step's gradient and update touch only the
rows in the batch (the O(nnz) row_sparse path, tests/test_sparse_compute
contract) — trained on a synthetic low-rank rating matrix.

    python examples/matrix_factorization.py --steps 150
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mnp
from mxnet_tpu.gluon import nn


class MFNet(gluon.block.HybridBlock):
    def __init__(self, n_users, n_items, k=16, **kwargs):
        super().__init__(**kwargs)
        self.user = nn.Embedding(n_users, k, sparse_grad=True)
        self.item = nn.Embedding(n_items, k, sparse_grad=True)

    def forward(self, users, items):
        u = self.user(users)
        v = self.item(items)
        return (u * v).sum(axis=-1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--users", type=int, default=100)
    ap.add_argument("--items", type=int, default=80)
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (eager per-op dispatch over a "
                         "tunneled TPU is RTT-bound; see PERF.md)")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    rng = onp.random.RandomState(0)
    # ground-truth rank-4 ratings
    gu = rng.randn(args.users, 4).astype("float32")
    gi = rng.randn(args.items, 4).astype("float32")

    net = MFNet(args.users, args.items)
    net.initialize(init=mx.init.Normal(0.1))
    l2 = gluon.loss.L2Loss()
    # lazy_update: only rows present in the batch get momentum/updates
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05, "lazy_update": True})

    first = last = None
    for step in range(args.steps):
        # sample WITHOUT replacement: the row_sparse gradient's nnz (the
        # unique-index count) is then the full batch size every step, so
        # the O(nnz) kernels keep ONE static shape and compile once —
        # varying nnz would recompile per step (TPU-first discipline:
        # static shapes; same reason detection ops pad to -1)
        u = rng.choice(args.users, args.batch, replace=False)
        i = rng.choice(args.items, args.batch, replace=False)
        r = (gu[u] * gi[i]).sum(axis=1)
        with autograd.record():
            pred = net(mnp.array(u.astype("int64")),
                       mnp.array(i.astype("int64")))
            loss = l2(pred, mnp.array(r)).mean()
        loss.backward()
        g = net.user.weight.grad()
        trainer.step(args.batch)
        v = float(loss.asnumpy())
        first = v if first is None else first
        last = v
        if step % 20 == 0:
            from mxnet_tpu.ndarray.sparse import RowSparseNDArray

            kind = ("row_sparse"
                    if isinstance(g, RowSparseNDArray) else "dense")
            print(f"step {step:3d} loss {v:8.4f}  user-grad: {kind}")

    print(f"loss {first:.4f} -> {last:.4f}")
    assert last < first * 0.5, "MF failed to learn the rating structure"

    # the gradient really is row-sparse and O(nnz)
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray

    assert isinstance(net.user.weight.grad(), RowSparseNDArray)
    assert not net.user.weight.grad().is_materialized()
    print("sparse-grad contract held: grads stayed row_sparse")


if __name__ == "__main__":
    main()
