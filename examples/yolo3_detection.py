#!/usr/bin/env python
"""YOLOv3 end-to-end on synthetic data — the BASELINE.json flagship
detection config (`yolo3_darknet53`) driven the Gluon way: targets from
``yolo3_targets`` (host side, input-pipeline role), the four-part
``YOLOV3Loss`` on device, hybridized NMS inference.

Synthetic task: images containing one bright square, class = small/large.

    python examples/yolo3_detection.py --steps 20            # full darknet53
    python examples/yolo3_detection.py --tiny --steps 30     # CI config
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mnp
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.model_zoo.vision import yolo3_darknet53
from mxnet_tpu.gluon.model_zoo.vision.darknet import _conv2d
from mxnet_tpu.gluon.model_zoo.vision.yolo import (YOLOV3, YOLOV3Loss,
                                                   yolo3_targets)


def tiny_yolo(classes, size):
    """3-stage toy backbone (strides 8/16/32) for CPU-mesh CI runs."""
    def stage(ch, n_down):
        s = nn.HybridSequential()
        for _ in range(n_down):
            s.add(_conv2d(ch, 3, 1, strides=2))
        return s

    anchors = [[(s * 2, s * 2), (s * 4, s * 3), (s * 3, s * 4)]
               for s in (8, 16, 32)]
    return YOLOV3([stage(16, 3), stage(32, 1), stage(64, 1)],
                  channels=(16, 32, 64), classes=classes, anchors=anchors)


def synth_batch(rng, batch, size):
    """One bright square per image; class 0 = small (~s/8), 1 = large
    (~s/4). Labels (B, 2, 5) [cls, x1, y1, x2, y2] normalized, -1 pad."""
    imgs = rng.rand(batch, 3, size, size).astype("float32") * 0.1
    labels = onp.full((batch, 2, 5), -1.0, "float32")
    for i in range(batch):
        cls = rng.randint(0, 2)
        side = size // 8 if cls == 0 else size // 4
        x0 = rng.randint(0, size - side)
        y0 = rng.randint(0, size - side)
        imgs[i, :, y0:y0 + side, x0:x0 + side] = 1.0
        labels[i, 0] = [cls, x0 / size, y0 / size, (x0 + side) / size,
                        (y0 + side) / size]
    return imgs, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--size", type=int, default=128)
    ap.add_argument("--tiny", action="store_true",
                    help="small backbone for CPU CI")
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    rng = onp.random.RandomState(0)
    classes = 2
    net = tiny_yolo(classes, args.size) if args.tiny \
        else yolo3_darknet53(classes=classes)
    net.initialize(init=mx.init.Xavier())
    # net.anchors is scale-ordered [stride8, 16, 32] — do NOT read anchor
    # groups off net.yolo_outputs, which iterates heads deepest-first
    anchors = net.anchors
    loss_fn = YOLOV3Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    first = last = None
    for step in range(args.steps):
        imgs, labels = synth_batch(rng, args.batch, args.size)
        targets = yolo3_targets(labels, args.size, classes,
                                anchors=anchors)
        x = mnp.array(imgs)
        t = [mnp.array(a) for a in targets]
        with autograd.record():
            outs = net(x)
            loss = loss_fn(*outs, *t)
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.asnumpy())
        first = v if first is None else first
        last = v
        if step % 5 == 0:
            print(f"step {step:3d} loss {v:.4f}")

    print(f"loss {first:.4f} -> {last:.4f}")
    assert onp.isfinite(last), "loss diverged"
    assert last < first, "detection loss failed to decrease"

    # hybridized inference: decode + NMS
    net.hybridize()
    imgs, labels = synth_batch(rng, 4, args.size)
    with autograd.predict_mode():
        ids, scores, boxes = net(mnp.array(imgs))
    ids, scores, boxes = (a.asnumpy() for a in (ids, scores, boxes))
    print("top detections [id score box] vs gt:")
    for i in range(4):
        print(f"  img{i}: pred id={ids[i,0,0]:.0f} score={scores[i,0,0]:.3f}"
              f" box={onp.round(boxes[i,0],1)}"
              f"  gt cls={labels[i,0,0]:.0f}"
              f" box={onp.round(labels[i,0,1:]*args.size,1)}")


if __name__ == "__main__":
    main()
