#!/usr/bin/env python
"""Serve a llama-family LM with the `mxnet_tpu.serve` stack.

Demonstrates the full serving vertical slice (SERVING.md):

* ``Generator`` — bucketed KV-cache autoregressive decode: prefill runs
  once per prompt bucket, then every generated token replays ONE
  compiled T=1 executable (no O(n^2) re-prefill);
* warmup compiles the whole (batch x prompt) bucket lattice up front, so
  the traffic loop below triggers **zero** XLA recompiles (asserted);
* ``DynamicBatcher`` — concurrent clients coalesce into batched
  generation calls, with deadline flush and admission control;
* ``serve::*`` SLO metrics — p50/p99 latency, tokens/s, occupancy.

Runs on TPU when a chip is visible, else CPU (~a minute for warmup on a
laptop-class CPU: 2 batch buckets x 2 prompt buckets + decode steps).

    python examples/serve_llama.py --max-new-tokens 24 --temperature 0.8
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.models.llama import get_llama
from mxnet_tpu.serve import DynamicBatcher, Generator


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="llama_serve_12l_test",
                    help="model config name from models/llama.py")
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy; >0 samples through mx.random")
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--clients", type=int, default=8,
                    help="concurrent requests pushed through the batcher")
    args = ap.parse_args()

    mx.random.seed(0)
    net = get_llama(args.config)
    net.initialize()
    gen = Generator(net, max_seq=64, batch_buckets=(1, 4),
                    prompt_buckets=(16,))

    print(f"warming the bucket lattice "
          f"(batch {gen.batch_buckets} x prompt {gen.prompt_buckets})...")
    info = gen.warmup()
    print(f"  compiled {info['signatures']} executables "
          f"in {info['wall_s']:.1f}s\n")

    # -- single batched generate call -----------------------------------
    rng = onp.random.RandomState(0)
    vocab = net.embed.weight.shape[0]  # keep prompts in-vocabulary
    prompts = [rng.randint(1, vocab, size=n).tolist() for n in (5, 9, 12, 7)]
    outs, stats = gen.generate(prompts,
                               max_new_tokens=args.max_new_tokens,
                               temperature=args.temperature,
                               top_k=args.top_k)
    for p, o in zip(prompts, outs):
        print(f"  prompt {p[:4]}...({len(p)} toks) -> {o}")
    print(f"  prefill {stats['prefill_ms']:.1f}ms, "
          f"decode {stats['decode_ms']:.1f}ms "
          f"({stats['tokens_s']:.1f} tokens/s)\n")

    # -- concurrent clients through the DynamicBatcher ------------------
    def runner(batch_prompts):
        outs, _ = gen.generate(list(batch_prompts),
                               max_new_tokens=args.max_new_tokens,
                               temperature=args.temperature,
                               top_k=args.top_k)
        return outs

    t0 = time.perf_counter()
    with DynamicBatcher(runner, max_batch_size=4, timeout_ms=10.0,
                        max_queue=64, metrics=gen.metrics,
                        name="llama") as batcher:
        futs = [batcher.submit(
                    rng.randint(1, vocab,
                                size=int(rng.randint(4, 14))).tolist())
                for _ in range(args.clients)]
        done = [f.result(timeout=300) for f in futs]
    wall = time.perf_counter() - t0
    print(f"served {len(done)} concurrent requests in {wall:.1f}s")

    gen.assert_no_recompiles()  # steady state never compiled
    snap = gen.stats()
    print(f"  p50 {snap['p50_ms']:.1f}ms  p99 {snap['p99_ms']:.1f}ms  "
          f"occupancy {snap['batch_occupancy']:.2f}  "
          f"tokens/s {snap['tokens_s']:.1f}")
    print(f"  cache: {snap['cache']['signatures']} signatures, "
          f"{snap['cache']['serve_hits']} warm serve hits, "
          f"0 recompiles after warmup")
    return 0


if __name__ == "__main__":
    sys.exit(main())
