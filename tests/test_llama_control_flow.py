"""Llama model family + control-flow op tests."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, npx
from mxnet_tpu import np as mnp
from mxnet_tpu.models import get_llama, llama_sharding_rules


def _ids(b=2, t=16, vocab=256):
    return mnp.array(np.random.randint(0, vocab, (b, t)))


def test_llama_forward_backward():
    net = get_llama("llama_tiny_test")
    net.initialize()
    ids = _ids()
    with autograd.record():
        logits = net(ids)
        loss = logits.sum()
    loss.backward()
    assert logits.shape == (2, 16, 256)
    g = net.collect_params()["embed.weight"].grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_llama_is_causal():
    net = get_llama("llama_tiny_test")
    net.initialize()
    ids = _ids()
    with autograd.predict_mode():
        l1 = net(ids).asnumpy()
        arr = ids.asnumpy().copy()
        arr[0, 10] = (arr[0, 10] + 1) % 256
        l2 = net(mnp.array(arr)).asnumpy()
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], rtol=2e-4, atol=1e-5)
    assert np.abs(l1[0, 10:] - l2[0, 10:]).max() > 1e-6


def test_llama_gqa_and_tied_variants():
    net = get_llama("llama_tiny_test", num_kv_heads=1, tie_embeddings=True)
    net.initialize()
    out = net(_ids())
    assert out.shape == (2, 16, 256)
    # no separate lm_head param when tied
    assert not any("lm_head" in n for n in net.collect_params())


def test_llama_rope_rotation_properties():
    from mxnet_tpu.models.llama import _rope_tables, apply_rope

    # norm-preserving and position-dependent
    x = mnp.array(np.random.randn(1, 2, 8, 16).astype("float32"))
    cos_t, sin_t = _rope_tables(8, 16)
    out = apply_rope(x, mnp.array(cos_t), mnp.array(sin_t))
    np.testing.assert_allclose(
        np.linalg.norm(out.asnumpy(), axis=-1),
        np.linalg.norm(x.asnumpy(), axis=-1), rtol=1e-5)
    # position 0 is identity
    np.testing.assert_allclose(out.asnumpy()[:, :, 0], x.asnumpy()[:, :, 0],
                               rtol=1e-6)
    assert np.abs(out.asnumpy()[:, :, 1] - x.asnumpy()[:, :, 1]).max() > 1e-4


def test_llama_sharded_train_step():
    from mxnet_tpu.parallel import ShardedTrainer, ShardingRules, make_mesh

    net = get_llama("llama_tiny_test")
    net.initialize()
    with autograd.predict_mode():
        net(_ids(1, 16))  # materialize deferred shapes before sharding
    mesh = make_mesh({"dp": 4, "tp": 2})
    tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw",
                        {"learning_rate": 1e-3}, mesh=mesh,
                        rules=ShardingRules(llama_sharding_rules(),
                                            default_axis=None))
    X = np.random.randint(0, 256, (8, 16))
    Y = np.random.randint(0, 256, (8, 16))
    loss = float(tr.step(X, Y).asnumpy())
    assert np.isfinite(loss)
    p = tr.params["layer0.attention.q_proj.weight"]
    assert p.sharding.spec == P("tp", None)
    assert tr.params["layer0.attention.o_proj.weight"].sharding.spec \
        == P(None, "tp")


def test_llama_config_registry():
    with pytest.raises(mx.MXNetError):
        get_llama("llama_99t")


# -- control flow ---------------------------------------------------------

def test_foreach_scan_and_grad():
    data = mnp.array(np.arange(12, dtype="float32").reshape(4, 3))
    init = mnp.array(np.zeros(3, "float32"))
    outs, final = npx.foreach(lambda x, s: (x + s, x + s), data, init)
    np.testing.assert_allclose(final.asnumpy(), data.asnumpy().sum(0))
    np.testing.assert_allclose(outs.asnumpy(),
                               np.cumsum(data.asnumpy(), 0))
    w = mnp.array([2.0])
    w.attach_grad()
    with autograd.record():
        _, f = npx.foreach(lambda x, s: (x * w, s + x * w), data, init)
        f.sum().backward()
    np.testing.assert_allclose(w.grad.asnumpy(), [data.asnumpy().sum()])


def test_while_loop():
    out = npx.while_loop(lambda x: x < 100, lambda x: x * 2,
                         mnp.array(1.0))
    assert float(out.asnumpy()) == 128.0
    out = npx.while_loop(lambda x: x < 100, lambda x: x * 2,
                         mnp.array(1.0), max_iterations=3)
    assert float(out.asnumpy()) == 8.0


def test_cond_branches_and_grad():
    x = mnp.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = npx.cond(mnp.array(True), lambda v: v * 2, lambda v: v * 10, x)
        y.sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0])
    z = npx.cond(mnp.array(False), lambda v: v * 2, lambda v: v * 10, x)
    np.testing.assert_allclose(z.asnumpy(), [30.0])


def test_foreach_inside_hybridize():
    class ScanNet(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.dense = gluon.nn.Dense(3, flatten=False, in_units=3)

        def forward(self, seq):
            _, fin = npx.foreach(
                lambda x, s: (self.dense(x) + s, s + x), seq,
                mnp.zeros((2, 3)))
            return fin

    net = ScanNet()
    net.initialize()
    seq = mnp.array(np.random.randn(5, 2, 3).astype("float32"))
    eager = net(seq).asnumpy()
    net.hybridize()
    hybrid = net(seq).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)
