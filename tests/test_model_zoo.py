"""Model zoo tests (reference: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _x(n=2, c=3, s=32):
    return mx.np.array(np.random.randn(n, c, s, s).astype("float32"))


@pytest.mark.parametrize("name", [
    "resnet18_v1", "resnet18_v2", "mobilenet0.25", "mobilenetv2_0.25",
])
def test_small_models_forward(name):
    net = gluon.model_zoo.get_model(name, classes=10)
    net.initialize()
    out = net(_x())
    assert out.shape == (2, 10)
    assert np.isfinite(out.asnumpy()).all()


def test_resnet_thumbnail_train_step():
    net = gluon.model_zoo.vision.get_resnet(1, 18, classes=10, thumbnail=True)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = _x()
    y = mx.np.array(np.array([1, 3]))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    g = net.collect_params()["features.0.weight"].grad()
    assert np.abs(g.asnumpy()).sum() > 0


def test_resnet_hybridize_matches_eager():
    net = gluon.model_zoo.get_model("resnet18_v1", classes=10)
    net.initialize()
    x = _x(1)
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-4)


def test_get_model_unknown_name():
    with pytest.raises(mx.MXNetError):
        gluon.model_zoo.get_model("resnet1000_v9")


def test_pretrained_gated():
    with pytest.raises(mx.MXNetError):
        gluon.model_zoo.get_model("resnet18_v1", pretrained=True)


def test_model_param_counts():
    # canonical ImageNet parameter counts pin the architectures
    expected = {
        "resnet18_v1": 11_699_112,
        "alexnet": 61_100_840,
        "squeezenet1.1": 1_235_496,
    }
    for name, count in expected.items():
        net = gluon.model_zoo.get_model(name)
        net.initialize()
        if name in ("resnet18_v1",):
            net(_x(1, 3, 64))  # materialize deferred shapes
        else:
            net(_x(1, 3, 224))
        total = sum(
            int(np.prod(p.shape)) for p in net.collect_params().values())
        assert total == count, (name, total, count)
