"""Tests for the hardened subsystems: lazy sparse storage, bounded
wait_all, CachedOpThreadSafe, config flag registry, probability
transformations + new distributions."""
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np


def test_row_sparse_is_lazy():
    """Construction must NOT allocate the dense buffer (the whole point of
    row_sparse for embedding-scale grads, kvstore.h PullRowSparse)."""
    vals = onp.ones((3, 4), "float32")
    idx = onp.array([1, 5, 7], "int64")
    rs = mx.nd.sparse.row_sparse_array((vals, idx), shape=(100000, 4))
    assert not rs.is_materialized()
    assert rs.shape == (100000, 4)      # metadata without densifying
    assert rs.dtype == onp.float32
    assert not rs.is_materialized()
    kept = rs.retain(onp.array([5, 7]))  # sparse-path retain
    assert not rs.is_materialized()
    onp.testing.assert_array_equal(kept.indices.asnumpy(), [5, 7])
    dense = rs.tostype("default")        # the storage-fallback moment
    assert rs.is_materialized()
    assert dense.asnumpy()[5].sum() == 4


def test_csr_lazy_and_correct():
    data = onp.array([1.0, 2, 3], "float32")
    indptr = onp.array([0, 2, 3], "int64")
    indices = onp.array([0, 2, 1], "int64")
    csr = mx.nd.sparse.csr_matrix((data, indices, indptr), shape=(2, 3))
    assert not csr.is_materialized()
    want = onp.array([[1, 0, 2], [0, 3, 0]], "float32")
    onp.testing.assert_array_equal(csr.tostype("default").asnumpy(), want)


def test_waitall_bounded_and_correct():
    from mxnet_tpu import engine

    a = np.ones((16, 16))
    for _ in range(5):
        a = np.tanh(a)
    mx.waitall()  # must drain without sweeping every live array
    with engine._pending_lock:
        assert all(len(dq) == 0
                   for _tref, dq in engine._pending_registry.values())
        assert len(engine._pending_orphans) == 0
    onp.testing.assert_allclose(a.asnumpy(),
                                onp.tanh(onp.tanh(onp.tanh(onp.tanh(
                                    onp.tanh(onp.ones((16, 16))))))),
                                rtol=1e-6)


def test_cachedop_threadsafe_cold_start_race():
    """Round-4 probe finding: with NO warmup call, concurrent first calls
    raced the jit trace — _ParamBinding rebinds the shared Parameter
    NDArrays to tracers, and a concurrent p.data() read leaked them
    (UnexpectedTracerError). First-call-per-entry now holds the op lock."""
    from mxnet_tpu.cachedop import CachedOpThreadSafe

    for _ in range(3):
        net = gluon.nn.Dense(2, in_units=2)
        net.initialize()
        op = CachedOpThreadSafe(net)
        outs, errors = [], []

        def worker(op=op, outs=outs, errors=errors):
            try:
                with autograd.predict_mode():
                    outs.append(op(np.array(onp.ones((1, 2),
                                            "float32"))).asnumpy())
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[0]
        for o in outs[1:]:
            onp.testing.assert_allclose(o, outs[0], rtol=1e-6)


def test_cachedop_threadsafe_concurrent_inference():
    from mxnet_tpu.cachedop import CachedOpThreadSafe

    net = gluon.nn.Dense(8, in_units=16)
    net.initialize()
    op = CachedOpThreadSafe(net)
    x = np.array(onp.random.randn(4, 16).astype("float32"))
    with autograd.predict_mode():
        want = op(x).asnumpy()
    results = [None] * 8
    errors = []

    def worker(i):
        try:
            with autograd.predict_mode():
                results[i] = op(x).asnumpy()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for r in results:
        onp.testing.assert_allclose(r, want, rtol=1e-6)


def test_config_registry():
    import io

    from mxnet_tpu import config

    assert "MXNET_ENGINE_TYPE" in config.list_flags()
    assert config.get("MXNET_ENGINE_TYPE") == "ThreadedEnginePerDevice"
    assert config.get("MXNET_EAGER_JIT_CACHE") is True
    buf = io.StringIO()
    config.describe(file=buf)
    text = buf.getvalue()
    assert "MXNET_WAITALL_FULL" in text and "waitall" in text


def test_transformed_distribution_lognormal():
    from mxnet_tpu.gluon.probability import (ExpTransform, Normal,
                                             TransformedDistribution)

    mu, sigma = 0.3, 0.5
    dist = TransformedDistribution(Normal(mu, sigma), ExpTransform())
    mx.random.seed(7)
    s = dist.sample((20000,)).asnumpy()
    assert (s > 0).all()
    # lognormal mean = exp(mu + sigma^2/2)
    onp.testing.assert_allclose(s.mean(), onp.exp(mu + sigma ** 2 / 2),
                                rtol=0.05)
    v = onp.array([0.5, 1.0, 2.0], "float32")
    got = dist.log_prob(np.array(v)).asnumpy()
    want = (-onp.log(v) - onp.log(sigma) - 0.5 * onp.log(2 * onp.pi)
            - (onp.log(v) - mu) ** 2 / (2 * sigma ** 2))
    onp.testing.assert_allclose(got, want, rtol=1e-5)


def test_affine_sigmoid_compose_roundtrip():
    from mxnet_tpu.gluon.probability import (AffineTransform,
                                             ComposeTransform,
                                             SigmoidTransform)

    t = ComposeTransform([AffineTransform(1.0, 2.0), SigmoidTransform()])
    x = np.array(onp.random.randn(10).astype("float32"))
    y = t(x)
    back = t.inv(y)
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy(), rtol=1e-4,
                                atol=1e-5)
    ld = t.log_det_jacobian(x, y)
    assert ld.shape == (10,)


@pytest.mark.parametrize("dist_cls,kwargs,mean_fn", [
    ("StudentT", {"df": 7.0}, lambda k: 0.0),
    ("Cauchy", {"loc": 0.0, "scale": 1.0}, None),
    ("HalfNormal", {"scale": 2.0}, lambda k: 2.0 * onp.sqrt(2 / onp.pi)),
    ("Chi2", {"df": 5.0}, lambda k: 5.0),
    ("Geometric", {"prob": 0.3}, lambda k: 0.7 / 0.3),
    ("Gumbel", {"loc": 1.0, "scale": 2.0},
     lambda k: 1.0 + 2.0 * 0.5772156649),
    ("Weibull", {"concentration": 2.0, "scale": 1.0}, None),
])
def test_new_distributions_sample_and_logprob(dist_cls, kwargs, mean_fn):
    from mxnet_tpu.gluon import probability as prob

    dist = getattr(prob, dist_cls)(**kwargs)
    mx.random.seed(11)
    s = dist.sample((30000,)).asnumpy()
    assert s.shape == (30000,)
    assert onp.isfinite(s).all()
    if mean_fn is not None:
        onp.testing.assert_allclose(s.mean(), mean_fn(kwargs), rtol=0.08,
                                    atol=0.05)
    pts = onp.abs(s[:4]) + 0.1  # positive support safe for all of these
    lp = dist.log_prob(np.array(pts.astype("float32"))).asnumpy()
    assert onp.isfinite(lp).all()


def test_sparse_dense_write_resparsifies():
    """A dense write-through must keep the sparse buffers coherent
    (kvstore row_sparse_pull writes into sparse destinations)."""
    rs = mx.nd.sparse.row_sparse_array(
        (onp.ones((2, 3), "float32"), onp.array([0, 2], "int64")),
        shape=(4, 3))
    new = onp.zeros((4, 3), "float32")
    new[1] = 5.0
    rs._set_data_internal(__import__("jax").numpy.asarray(new))
    onp.testing.assert_array_equal(rs.indices.asnumpy(), [1])
    onp.testing.assert_allclose(rs.values.asnumpy(), [[5, 5, 5]])
    kept = rs.retain(onp.array([1]))
    onp.testing.assert_allclose(kept.values.asnumpy(), [[5, 5, 5]])


def test_quantize_net_dehybridizes_for_calibration():
    from mxnet_tpu.contrib import quantization as q

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8), gluon.nn.Dense(4))
    net.initialize()
    x = np.array(onp.random.randn(2, 16).astype("float32"))
    with autograd.predict_mode():
        net(x)
    net.hybridize()
    with autograd.predict_mode():
        net(x)  # cached trace exists
    q.quantize_net(net, calib_data=x, calib_mode="naive")
    from mxnet_tpu.contrib.quantization import QuantizedDense

    assert isinstance(net[0], QuantizedDense)
    # calibration really ran: the scale is not the bogus default 1/127
    assert abs(net[0]._x_scale - 1.0 / 127) > 1e-9
