"""NDArray semantics tests (reference tests/python/unittest/test_ndarray.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np


def test_creation_and_dtype():
    a = np.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == onp.int64  # numpy default-int parity
    b = np.array([1.0, 2.0])
    assert b.dtype == onp.float32  # MXNet default float dtype
    c = np.zeros((3, 4), dtype="float64")
    assert c.dtype == onp.float64


def test_arithmetic_matches_numpy():
    x = onp.random.rand(5, 7).astype("float32")
    y = onp.random.rand(5, 7).astype("float32")
    a, b = np.array(x), np.array(y)
    onp.testing.assert_allclose((a + b).asnumpy(), x + y, rtol=1e-6)
    onp.testing.assert_allclose((a - b).asnumpy(), x - y, rtol=1e-6)
    onp.testing.assert_allclose((a * b).asnumpy(), x * y, rtol=1e-6)
    onp.testing.assert_allclose((a / (b + 1)).asnumpy(), x / (y + 1), rtol=1e-6)
    onp.testing.assert_allclose((a ** 2).asnumpy(), x ** 2, rtol=1e-6)
    onp.testing.assert_allclose((a @ b.T).asnumpy(), x @ y.T, rtol=1e-5)
    onp.testing.assert_allclose((2 - a).asnumpy(), 2 - x, rtol=1e-6)


def test_inplace_and_version():
    a = np.zeros((3,))
    v0 = a._version
    a += 1
    assert a._version > v0
    onp.testing.assert_allclose(a.asnumpy(), [1, 1, 1])


def test_setitem_getitem():
    a = np.zeros((4, 4))
    a[1] = 7.0
    a[2, 3] = 1.5
    a[0, 1:3] = np.array([9.0, 8.0])
    host = a.asnumpy()
    assert host[1].sum() == 28
    assert host[2, 3] == 1.5
    assert host[0, 1] == 9 and host[0, 2] == 8
    # advanced indexing
    idx = np.array([0, 2])
    sel = a[idx]
    assert sel.shape == (2, 4)
    # boolean mask: four 7s + 9 + 8
    m = a > 5
    assert int((a[m]).size) == 6


def test_reductions_and_methods():
    x = onp.random.rand(3, 4, 5).astype("float32")
    a = np.array(x)
    onp.testing.assert_allclose(a.sum(axis=1).asnumpy(), x.sum(1), rtol=1e-5)
    onp.testing.assert_allclose(a.mean().asnumpy(), x.mean(), rtol=1e-5)
    onp.testing.assert_allclose(a.max(axis=(0, 2)).asnumpy(), x.max((0, 2)))
    onp.testing.assert_allclose(a.transpose(2, 0, 1).asnumpy(),
                                x.transpose(2, 0, 1))
    assert a.reshape(12, 5).shape == (12, 5)
    assert a.reshape((-1,)).shape == (60,)
    assert a.argmax(axis=2).shape == (3, 4)


def test_scalar_protocol():
    a = np.array(3.5)
    assert float(a) == 3.5
    assert a.item() == 3.5
    with pytest.raises(ValueError):
        bool(np.ones((2,)))
    assert int(np.array(7)) == 7


def test_copyto_and_context():
    a = np.ones((2, 2))
    b = np.zeros((2, 2))
    a.copyto(b)
    onp.testing.assert_allclose(b.asnumpy(), 1)
    assert a.ctx.device_type == "cpu"
    c = a.as_in_context(mx.cpu(0))
    assert c is a  # same-context returns self


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.bin")
    arrs = {"w": np.ones((3, 2)), "b": np.arange(4)}
    mx.nd.save(fname, arrs)
    loaded = mx.nd.load(fname)
    assert set(loaded) == {"w", "b"}
    onp.testing.assert_allclose(loaded["w"].asnumpy(), 1)
    onp.testing.assert_allclose(loaded["b"].asnumpy(), [0, 1, 2, 3])
    # list form
    mx.nd.save(fname, [np.zeros((2,))])
    assert isinstance(mx.nd.load(fname), list)


def test_wait_to_read_and_waitall():
    a = np.ones((16, 16)) @ np.ones((16, 16))
    a.wait_to_read()
    mx.waitall()
    assert a.asnumpy()[0, 0] == 16


def test_astype_detach():
    a = np.ones((2,), dtype="float32")
    b = a.astype("float16")
    assert b.dtype == onp.float16
    a.attach_grad()
    d = a.detach()
    assert d.grad is None


def test_sparse_roundtrip():
    dense = onp.zeros((5, 4), "float32")
    dense[1] = 2.0
    dense[3, 2] = 5.0
    a = np.array(dense)
    rs = a.tostype("row_sparse")
    assert rs.stype == "row_sparse"
    onp.testing.assert_allclose(rs.tostype("default").asnumpy(), dense)
    csr = a.tostype("csr")
    assert csr.stype == "csr"
    onp.testing.assert_allclose(csr.tostype("default").asnumpy(), dense)
