"""Contrib-tail op tests (VERDICT r3 item 6): quadratic,
gradientmultiplier, count_sketch, hawkes_ll against numpy oracles, plus
the closed-surface refusal contract for DGL/intgemm names."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu import np as mnp
from mxnet_tpu.base import MXNetError


def test_quadratic_value_and_grad():
    x = onp.random.randn(3, 4).astype(onp.float32)
    a, b, c = 2.0, -1.5, 0.25
    xn = mnp.array(x)
    xn.attach_grad()
    with autograd.record():
        y = nd.contrib.quadratic(xn, a=a, b=b, c=c)
        loss = y.sum()
    loss.backward()
    onp.testing.assert_allclose(y.asnumpy(), a * x * x + b * x + c,
                                rtol=1e-6)
    # reference quadratic_backward: dL/dx = 2a·x + b
    onp.testing.assert_allclose(xn.grad.asnumpy(), 2 * a * x + b, rtol=1e-6)


def test_gradientmultiplier_reverses_gradient():
    x = onp.random.randn(4).astype(onp.float32)
    xn = mnp.array(x)
    xn.attach_grad()
    with autograd.record():
        y = nd.contrib.gradientmultiplier(xn, scalar=-2.5)
        loss = (y * y).sum()
    loss.backward()
    onp.testing.assert_allclose(y.asnumpy(), x, rtol=1e-6)  # identity fwd
    onp.testing.assert_allclose(xn.grad.asnumpy(), -2.5 * 2 * x, rtol=1e-5)


def test_count_sketch_oracle():
    rng = onp.random.RandomState(0)
    n, in_dim, out_dim = 3, 10, 5
    data = rng.randn(n, in_dim).astype(onp.float32)
    h = rng.randint(0, out_dim, in_dim).astype(onp.float32)
    s = rng.choice([-1.0, 1.0], in_dim).astype(onp.float32)
    expect = onp.zeros((n, out_dim), onp.float32)
    for i in range(in_dim):
        expect[:, int(h[i])] += s[i] * data[:, i]
    got = nd.contrib.count_sketch(mnp.array(data), mnp.array(h),
                                  mnp.array(s), out_dim=out_dim)
    onp.testing.assert_allclose(got.asnumpy(), expect, rtol=1e-5)


def _hawkes_oracle(mu, alpha, beta, state0, lags, marks, vl, max_time):
    """Direct transcription of hawkes_ll-inl.h:113-189."""
    n, k = mu.shape
    ll_out = onp.zeros(n)
    state_out = state0.copy().astype(onp.float64)
    for i in range(n):
        ll, t = 0.0, 0.0
        last = onp.zeros(k)
        st = state_out[i]
        for j in range(int(vl[i])):
            ci = int(marks[i, j])
            t += lags[i, j]
            d = t - last[ci]
            ed = onp.exp(-beta[ci] * d)
            lda = mu[i, ci] + alpha[ci] * beta[ci] * st[ci] * ed
            comp = mu[i, ci] * d + alpha[ci] * st[ci] * (1 - ed)
            ll += onp.log(lda) - comp
            st[ci] = 1 + st[ci] * ed
            last[ci] = t
        d = max_time[i] - last
        ed = onp.exp(-beta * d)
        ll -= (mu[i] * d + alpha * st * (1 - ed)).sum()
        state_out[i] = ed * st
        ll_out[i] = ll
    return ll_out, state_out


def test_hawkes_ll_oracle():
    rng = onp.random.RandomState(42)
    n, t, k = 2, 7, 3
    mu = rng.uniform(0.2, 1.0, (n, k)).astype(onp.float32)
    alpha = rng.uniform(0.1, 0.5, k).astype(onp.float32)
    beta = rng.uniform(0.5, 2.0, k).astype(onp.float32)
    state = rng.uniform(0.0, 0.5, (n, k)).astype(onp.float32)
    lags = rng.exponential(0.5, (n, t)).astype(onp.float32)
    marks = rng.randint(0, k, (n, t)).astype(onp.int32)
    vl = onp.array([7, 4], onp.float32)  # ragged: padding must not count
    max_time = onp.array([6.0, 5.0], onp.float32)

    ll_e, st_e = _hawkes_oracle(mu, alpha, beta, state, lags, marks, vl,
                                max_time)
    ll, st = nd.contrib.hawkes_ll(
        mnp.array(mu), mnp.array(alpha), mnp.array(beta), mnp.array(state),
        mnp.array(lags), mnp.array(marks), mnp.array(vl),
        mnp.array(max_time))
    onp.testing.assert_allclose(ll.asnumpy(), ll_e, rtol=1e-4)
    onp.testing.assert_allclose(st.asnumpy(), st_e, rtol=1e-4)


def test_hawkes_ll_gradients_flow():
    """The reference hand-writes backward (hawkes_ll.cc); here autodiff
    through the scan must produce finite grads for mu/alpha/beta."""
    rng = onp.random.RandomState(1)
    n, t, k = 2, 5, 2
    mu = mnp.array(rng.uniform(0.2, 1.0, (n, k)).astype(onp.float32))
    alpha = mnp.array(rng.uniform(0.1, 0.5, k).astype(onp.float32))
    beta = mnp.array(rng.uniform(0.5, 2.0, k).astype(onp.float32))
    for p in (mu, alpha, beta):
        p.attach_grad()
    state = mnp.zeros((n, k))
    lags = mnp.array(rng.exponential(0.5, (n, t)).astype(onp.float32))
    marks = mnp.array(rng.randint(0, k, (n, t)).astype(onp.int32))
    vl = mnp.array(onp.full(n, t, onp.float32))
    mt = mnp.array(onp.full(n, 5.0, onp.float32))
    with autograd.record():
        ll, _ = nd.contrib.hawkes_ll(mu, alpha, beta, state, lags, marks,
                                     vl, mt)
        loss = -ll.sum()
    loss.backward()
    for p in (mu, alpha, beta):
        g = p.grad.asnumpy()
        assert onp.isfinite(g).all()
        assert (g != 0).any()


def test_sym_contrib_exposes_new_ops():
    s = mx.sym.contrib.quadratic(mx.sym.var("x"), a=1.0, b=0.0, c=1.0)
    out = s.eval(x=mnp.array(onp.ones((2, 2), onp.float32)))
    onp.testing.assert_allclose(out[0].asnumpy(), 2 * onp.ones((2, 2)))


def test_dgl_and_intgemm_refuse_with_guidance():
    for name in ("dgl_csr_neighbor_uniform_sample", "dgl_subgraph",
                 "edge_id", "dgl_adjacency", "dgl_graph_compact",
                 "intgemm_fully_connected", "intgemm_prepare_weight"):
        fn = getattr(nd.contrib, name)  # resolves, never AttributeError
        with pytest.raises(MXNetError) as ei:
            fn(mnp.ones((2, 2)))
        assert "host" in str(ei.value) or "quantization" in str(ei.value)


def test_contrib_unknown_name_still_attribute_errors():
    with pytest.raises(AttributeError):
        nd.contrib.definitely_not_an_op  # pylint: disable=pointless-statement


def test_plain_nd_refusals_do_not_pollute_contrib():
    """Feature detection must stay truthful: names that were plain-nd in
    the reference (fused optimizer kernels) never existed under contrib."""
    assert not hasattr(nd.contrib, "multi_sgd_update")
    assert not hasattr(nd.contrib, "rmspropalex_update")
    assert not hasattr(nd.contrib, "reset_arrays")


def test_abstract_trainer_reuse_and_set_data_recovery():
    """Second abstract functionalization of the same block works, and
    set_data() cures a placeholder (review findings r4)."""
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.parallel.functional import functionalize_abstract

    m = get_llama("llama_tiny_test")
    _, s1 = functionalize_abstract(m)
    _, s2 = functionalize_abstract(m)  # idempotent, no poison crash
    assert {n: v.shape for n, v in s1.items()} == \
        {n: v.shape for n, v in s2.items()}
    p = m.collect_params()[sorted(m.collect_params())[0]]
    with pytest.raises(MXNetError):
        p.data()
    p.set_data(mnp.array(onp.zeros(p.shape, "float32")))
    assert p.data().shape == tuple(p.shape)


def test_sym_contrib_refusal_resolves_then_raises():
    fn = mx.sym.contrib.dgl_subgraph  # resolves (closed surface)
    with pytest.raises(MXNetError):
        fn(mx.sym.var("g"))
