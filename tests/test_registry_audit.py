"""Exhaustive legacy-registry audit: EVERY public op name the reference
registers (``NNVM_REGISTER_OP``/``MXNET_OPERATOR_REGISTER_*`` +
``.add_alias``, non-underscore — extracted to
tests/golden/reference_public_ops.txt) must resolve on both ``mx.nd`` and
``mx.sym`` — to working code or a deliberate refusal stub. This is the
"zero silently-absent names" closure of VERDICT r3 item 6, at full
registry scale rather than the curated ~100-name sample.

Plus numpy oracles for the linalg_* family and the samplers added to
close the audit (reference ``src/operator/tensor/la_op.cc``,
``src/operator/random/sample_op.cc``).
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import np as mnp

def _load_golden(fname):
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden",
                     fname)
    with open(p) as f:
        return [l.strip() for l in f if l.strip()]


ALL_PUBLIC_OPS = _load_golden("reference_public_ops.txt")


def test_audit_list_is_complete():
    assert len(ALL_PUBLIC_OPS) >= 200


@pytest.mark.parametrize("name", ALL_PUBLIC_OPS)
def test_every_public_reference_op_resolves(name):
    getattr(nd, name)          # AttributeError = silently-absent = fail
    assert callable(getattr(mx.sym, name))


def _r(shape, seed=0):
    return onp.random.RandomState(seed).randn(*shape).astype(onp.float32)


def test_linalg_gemm_family():
    a, b, c = _r((2, 3, 4)), _r((2, 4, 5), 1), _r((2, 3, 5), 2)
    got = nd.linalg_gemm(mnp.array(a), mnp.array(b), mnp.array(c),
                         alpha=2.0, beta=0.5).asnumpy()
    onp.testing.assert_allclose(got, 2.0 * a @ b + 0.5 * c, rtol=1e-5)
    got = nd.linalg_gemm2(mnp.array(a), mnp.array(b)).asnumpy()
    onp.testing.assert_allclose(got, a @ b, rtol=1e-5)
    got = nd.linalg_gemm2(mnp.array(a), mnp.array(_r((2, 3, 4), 3)),
                          transpose_b=True).asnumpy()
    onp.testing.assert_allclose(
        got, a @ _r((2, 3, 4), 3).transpose(0, 2, 1), rtol=1e-5)
    got = nd.linalg_syrk(mnp.array(a), alpha=1.5).asnumpy()
    onp.testing.assert_allclose(got, 1.5 * a @ a.transpose(0, 2, 1),
                                rtol=1e-5)


def _spd(n, seed=0):
    m = _r((n, n), seed)
    return (m @ m.T + n * onp.eye(n)).astype(onp.float32)


def test_linalg_cholesky_family():
    a = _spd(4)
    l = nd.linalg_potrf(mnp.array(a)).asnumpy()
    onp.testing.assert_allclose(l @ l.T, a, rtol=1e-4)
    assert onp.allclose(l, onp.tril(l))
    inv = nd.linalg_potri(mnp.array(l)).asnumpy()
    onp.testing.assert_allclose(inv, onp.linalg.inv(a), rtol=1e-3,
                                atol=1e-5)
    sld = nd.linalg_sumlogdiag(mnp.array(l)).asnumpy()
    onp.testing.assert_allclose(sld, onp.log(onp.diag(l)).sum(), rtol=1e-5)


def test_linalg_triangular_solves():
    a = onp.tril(_r((4, 4))) + 4 * onp.eye(4, dtype=onp.float32)
    b = _r((4, 3), 1)
    got = nd.linalg_trmm(mnp.array(a), mnp.array(b), alpha=2.0).asnumpy()
    onp.testing.assert_allclose(got, 2.0 * a @ b, rtol=1e-5)
    x = nd.linalg_trsm(mnp.array(a), mnp.array(b), alpha=1.0).asnumpy()
    onp.testing.assert_allclose(a @ x, b, rtol=1e-4, atol=1e-5)
    # rightside: X A = B
    b2 = _r((3, 4), 2)
    x = nd.linalg_trsm(mnp.array(a), mnp.array(b2), rightside=True).asnumpy()
    onp.testing.assert_allclose(x @ a, b2, rtol=1e-4, atol=1e-5)


def test_linalg_gelqf_and_det():
    a = _r((3, 5))
    q, l = nd.linalg_gelqf(mnp.array(a))
    q, l = q.asnumpy(), l.asnumpy()
    onp.testing.assert_allclose(l @ q, a, rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(q @ q.T, onp.eye(3), atol=1e-5)
    assert onp.allclose(l, onp.tril(l), atol=1e-5)
    assert (onp.diag(l) > 0).all()

    m = _spd(3, 5)
    onp.testing.assert_allclose(nd.linalg_det(mnp.array(m)).asnumpy(),
                                onp.linalg.det(m), rtol=1e-4)
    sign, logdet = nd.linalg_slogdet(mnp.array(m))
    s_e, ld_e = onp.linalg.slogdet(m)
    onp.testing.assert_allclose(sign.asnumpy(), s_e, rtol=1e-5)
    onp.testing.assert_allclose(logdet.asnumpy(), ld_e, rtol=1e-4)
    onp.testing.assert_allclose(nd.linalg_inverse(mnp.array(m)).asnumpy(),
                                onp.linalg.inv(m), rtol=1e-3, atol=1e-5)


def test_linalg_diag_trian_packing():
    a = _r((3, 4, 4))
    d = nd.linalg_extractdiag(mnp.array(a)).asnumpy()
    onp.testing.assert_allclose(d, onp.diagonal(a, axis1=-2, axis2=-1))
    back = nd.linalg_makediag(mnp.array(d)).asnumpy()
    for i in range(3):
        onp.testing.assert_allclose(back[i], onp.diag(d[i]))
    packed = nd.linalg_extracttrian(mnp.array(a)).asnumpy()
    assert packed.shape == (3, 10)
    tri = nd.linalg_maketrian(mnp.array(packed)).asnumpy()
    onp.testing.assert_allclose(tri, onp.tril(a), rtol=1e-6)
    # upper triangle with positive offset
    packed_u = nd.linalg_extracttrian(mnp.array(a), offset=1).asnumpy()
    assert packed_u.shape == (3, 6)
    tri_u = nd.linalg_maketrian(mnp.array(packed_u), offset=1).asnumpy()
    onp.testing.assert_allclose(tri_u, onp.triu(a, 1), rtol=1e-6)


def test_samplers_added_for_audit():
    nb = nd.random_negative_binomial(k=5, p=0.5, shape=(500,))
    assert nb.shape == (500,)
    m = float(nb.asnumpy().mean())
    assert 3.0 < m < 7.0  # E[NB(5, .5)] failures = k(1-p)/p = 5
    gnb = nd.random_generalized_negative_binomial(mu=4.0, alpha=0.25,
                                                  shape=(500,))
    m = float(gnb.asnumpy().mean())
    assert 2.5 < m < 5.5

    probs = onp.array([[0.0, 1.0, 0.0], [0.0, 0.0, 1.0]], onp.float32)
    s = nd.sample_multinomial(mnp.array(probs), shape=8)
    assert s.shape == (2, 8)
    got = s.asnumpy()
    assert (got[0] == 1).all() and (got[1] == 2).all()
    s, logp = nd.sample_multinomial(mnp.array(probs), shape=4,
                                    get_prob=True)
    onp.testing.assert_allclose(logp.asnumpy(), onp.zeros((2, 4)),
                                atol=1e-5)

    x = onp.arange(12, dtype=onp.float32).reshape(6, 2)
    sh = nd.shuffle(mnp.array(x))
    assert sorted(sh.asnumpy()[:, 0].tolist()) == x[:, 0].tolist()


def test_alias_semantics():
    a = mnp.array(_r((3, 4)))
    onp.testing.assert_allclose(nd.max_axis(a, axis=1).asnumpy(),
                                a.asnumpy().max(axis=1), rtol=1e-6)
    onp.testing.assert_allclose(nd.sum_axis(a, axis=0).asnumpy(),
                                a.asnumpy().sum(axis=0), rtol=1e-5)
    idx = mnp.array(onp.array([0, 1, 0], onp.float32))
    onp.testing.assert_allclose(
        nd.choose_element_0index(a, idx, axis=1).asnumpy(),
        a.asnumpy()[onp.arange(3), [0, 1, 0]], rtol=1e-6)


@pytest.mark.parametrize("name", _load_golden("reference_np_all.txt"))
def test_np_all_surface_complete(name):
    """Every name the reference exports in mx.np's __all__
    (python/mxnet/numpy/*.py, extracted to the golden list) exists here —
    the primary 2.x API surface, closed the same way as the legacy one.
    Usability, not mere presence: a None placeholder fails (the
    nd.waitall lesson). The reference exports no None-valued names in
    __all__ (newaxis lives outside it), so the check is unconditional."""
    attr = getattr(mx.np, name)  # AttributeError = missing = fail
    assert attr is not None, name


@pytest.mark.parametrize("name", _load_golden("reference_npx_all.txt"))
def test_npx_all_surface_complete(name):
    assert getattr(mx.npx, name) is not None, name


@pytest.mark.parametrize("name", _load_golden("reference_np_linalg_all.txt"))
def test_np_linalg_surface_complete(name):
    assert getattr(mx.np.linalg, name) is not None, name


@pytest.mark.parametrize("name", _load_golden("reference_np_random_all.txt"))
def test_np_random_surface_complete(name):
    assert getattr(mx.np.random, name) is not None, name
