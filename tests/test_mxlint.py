"""mxlint conformance (``tools/mxlint``): each rule catches its known-bad
fixture snippet and stays quiet on the known-good twin, the baseline
suppression machinery round-trips, inline ``# mxlint: disable=`` works,
and — the actual gate — a self-scan of the real tree reports zero
non-baselined findings (the same invocation tier-1 runs via
``TIER1_LINT=1``). Rule catalog lives in TOOLING.md.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import engine as mxengine  # noqa: E402
from tools.mxlint import hygiene, locks, registry  # noqa: E402


def _scan(tmp_path, files, rules, baseline_path=None):
    """Write ``files`` ({relpath: source}) under tmp_path and run the
    given rule set; returns the non-suppressed findings."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    paths = sorted({rel.split("/", 1)[0] for rel in files})
    findings, _sup, _unused = mxengine.run(
        paths, str(tmp_path), baseline_path=baseline_path, rules=rules)
    return findings


def _keys(findings):
    return {f.key for f in findings}


# ---------------------------------------------------------------------------
# L001 lock-order cycles
# ---------------------------------------------------------------------------

_CYCLE_SRC = """
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()
            self.b = B()

        def f(self):
            with self._lock:
                with self.b._other_lock:
                    pass

    class B:
        def __init__(self):
            self._other_lock = threading.Lock()
            self.a = A()

        def g(self):
            with self._other_lock:
                with self.a._lock:
                    pass
    """


def test_l001_flags_ab_ba_cycle(tmp_path):
    findings = _scan(tmp_path, {"pkg/mod.py": _CYCLE_SRC}, (locks.check,))
    cycles = [f for f in findings if f.rule == "L001"]
    assert len(cycles) == 1
    assert cycles[0].key.startswith("cycle:")
    assert "A._lock" in cycles[0].message
    assert "B._other_lock" in cycles[0].message


def test_l001_consistent_order_is_clean(tmp_path):
    src = """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._inner_lock = threading.Lock()

            def f(self):
                with self._lock:
                    with self._inner_lock:
                        pass

            def g(self):
                with self._lock:
                    with self._inner_lock:
                        pass
        """
    findings = _scan(tmp_path, {"pkg/mod.py": src}, (locks.check,))
    assert [f for f in findings if f.rule == "L001"] == []


def test_l001_reentrant_same_lock_is_not_a_cycle(tmp_path):
    # two instances of one class taking each other's (same-named) RLock
    # is self-edge territory, not a reportable cycle
    src = """
        import threading

        class Node:
            def __init__(self):
                self._lock = threading.RLock()

            def link(self, other):
                with self._lock:
                    with other._lock:
                        pass
        """
    findings = _scan(tmp_path, {"pkg/mod.py": src}, (locks.check,))
    assert [f for f in findings if f.rule == "L001"] == []


# ---------------------------------------------------------------------------
# L002 blocking under a held lock
# ---------------------------------------------------------------------------

def test_l002_blocking_ops_under_lock(tmp_path):
    src = """
        import threading
        import time

        class Srv:
            def __init__(self):
                self._lock = threading.Lock()

            def bad_sleep(self):
                with self._lock:
                    time.sleep(0.1)

            def bad_result(self, fut):
                with self._lock:
                    return fut.result(timeout=5)

            def bad_join(self, t):
                with self._lock:
                    t.join()

            def bad_sync(self, arr):
                with self._lock:
                    return arr.asnumpy()

            def bad_settle(self, fut):
                with self._lock:
                    fut.set_result(1)
        """
    keys = _keys(_scan(tmp_path, {"pkg/srv.py": src}, (locks.check,)))
    assert "sleep:Srv.bad_sleep" in keys
    assert "future-result:Srv.bad_result" in keys
    assert "join:Srv.bad_join" in keys
    assert "device-sync:asnumpy:Srv.bad_sync" in keys
    assert "future-settle:Srv.bad_settle" in keys


def test_l002_outside_lock_is_clean(tmp_path):
    src = """
        import threading
        import time

        class Srv:
            def __init__(self):
                self._lock = threading.Lock()

            def ok(self, fut, arr):
                with self._lock:
                    pending = True
                time.sleep(0.1)
                fut.set_result(arr.asnumpy())

            def ok_nonblocking_result(self, fut):
                with self._lock:
                    return fut.result(timeout=0)
        """
    findings = _scan(tmp_path, {"pkg/srv.py": src}, (locks.check,))
    assert [f for f in findings if f.rule == "L002"] == []


def test_l002_one_hop_interprocedural(tmp_path):
    # the fleet pattern this PR fixed: the blocking op hides one call
    # away — bookkeeping helper settles a future, caller holds the lock
    src = """
        import threading

        class Router:
            def __init__(self):
                self._lock = threading.Lock()

            def _finish(self, fut):
                fut.set_result(1)

            def dispatch(self, fut):
                with self._lock:
                    self._finish(fut)
        """
    keys = _keys(_scan(tmp_path, {"pkg/router.py": src}, (locks.check,)))
    assert "via-future-settle:Router.dispatch->Router._finish" in keys


# ---------------------------------------------------------------------------
# L003 registry drift
# ---------------------------------------------------------------------------

_L003_FILES = {
    "mxnet_tpu/config.py": """
        def register_flag(name, default, doc, parse=None):
            pass

        register_flag("MXNET_USED_FLAG", 0, "documented and read")
        register_flag("MXNET_DEAD_FLAG", 0, "registered but never read")
        register_flag("MXNET_UNDOC_FLAG", 0, "read but not in any doc")
        """,
    "mxnet_tpu/resilience/faults.py": """
        KNOWN_SITES = ("good:site",)
        """,
    "mxnet_tpu/user.py": """
        import os
        from . import config
        from .resilience import fault_point
        from .profiler import core as prof

        def f():
            config.get("MXNET_USED_FLAG")
            config.get("MXNET_UNDOC_FLAG")
            config.get("MXNET_NOT_REGISTERED")
            os.environ.get("MXNET_RAW_READ")
            fault_point("good:site")
            fault_point("rogue:site")
            prof.incr_counter("serve.requests")
            prof.incr_counter("unnamespaced_counter")
        """,
}


@pytest.fixture()
def l003_root(tmp_path):
    (tmp_path / "README.md").write_text(
        "| `MXNET_USED_FLAG` | documented |\n")
    (tmp_path / "RESILIENCE.md").write_text("`good:site` documented\n")
    export = tmp_path / "mxnet_tpu" / "profiler"
    export.mkdir(parents=True)
    nss = " ".join("%s." % ns for ns in registry.COUNTER_NAMESPACES)
    (export / "export.py").write_text('"""merges: %s"""\n' % nss)
    return tmp_path


def test_l003_drift_findings(tmp_path, l003_root):
    keys = _keys(_scan(tmp_path, _L003_FILES, (registry.check,)))
    assert "dead-flag:MXNET_DEAD_FLAG" in keys
    assert "undocumented-flag:MXNET_UNDOC_FLAG" in keys
    assert "unknown-flag:MXNET_NOT_REGISTERED" in keys
    assert "unregistered-read:MXNET_RAW_READ" in keys
    assert "undeclared-site:rogue:site" in keys
    assert "bad-counter:unnamespaced_counter" in keys
    # the good citizens stay quiet
    assert "dead-flag:MXNET_USED_FLAG" not in keys
    assert "undocumented-flag:MXNET_USED_FLAG" not in keys
    assert "undeclared-site:good:site" not in keys
    assert "undocumented-site:good:site" not in keys
    assert not any(k.startswith("bad-counter:serve.") for k in keys)


def test_l003_undocumented_site(tmp_path, l003_root):
    files = dict(_L003_FILES)
    files["mxnet_tpu/resilience/faults.py"] = """
        KNOWN_SITES = ("good:site", "undoc:site")
        """
    files["mxnet_tpu/user.py"] += (
        "\n        def g():\n"
        "            fault_point(\"undoc:site\")\n")
    keys = _keys(_scan(tmp_path, files, (registry.check,)))
    assert "undocumented-site:undoc:site" in keys


# ---------------------------------------------------------------------------
# L004 thread hygiene
# ---------------------------------------------------------------------------

def test_l004_findings(tmp_path):
    src = """
        import threading

        def swallow():
            try:
                work()
            except BaseException:
                pass

        def rethrow_later():
            try:
                work()
            except BaseException as exc:
                record(exc)

        def spawn():
            t = threading.Thread(target=loop, daemon=True)
            t.start()
        """
    keys = _keys(_scan(tmp_path, {"mxnet_tpu/mod.py": src},
                       (hygiene.check,)))
    assert "baseexcept:swallow" in keys
    assert "baseexcept:rethrow_later" not in keys
    assert "unnamed-thread:spawn" in keys
    assert "daemon-liveness:spawn" in keys


def test_l004_good_module_is_clean(tmp_path):
    src = """
        import threading
        from .profiler import register_thread_name

        def spawn(stop):
            def body():
                register_thread_name()
                loop()
            t = threading.Thread(target=body, daemon=True)
            t.start()
            assert t.is_alive()
        """
    findings = _scan(tmp_path, {"mxnet_tpu/mod.py": src},
                     (hygiene.check,))
    assert [f for f in findings if f.rule == "L004"] == []


def test_l004_only_applies_inside_mxnet_tpu(tmp_path):
    src = """
        def swallow():
            try:
                work()
            except BaseException:
                pass
        """
    findings = _scan(tmp_path, {"tools/helper.py": src}, (hygiene.check,))
    assert findings == []


# ---------------------------------------------------------------------------
# engine mechanics: inline disables, baseline round-trip
# ---------------------------------------------------------------------------

def test_inline_disable_suppresses_that_line(tmp_path):
    src = """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    time.sleep(0.1)  # mxlint: disable=L002
        """
    findings = _scan(tmp_path, {"pkg/s.py": src}, (locks.check,))
    assert [f for f in findings if f.rule == "L002"] == []


def test_baseline_round_trip(tmp_path):
    files = {"pkg/s.py": """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    time.sleep(0.1)
        """}
    # 1) unsuppressed: the finding is visible
    findings = _scan(tmp_path, files, (locks.check,))
    assert _keys(findings) == {"sleep:S.f"}
    # 2) write a baseline from the finding; same scan is now clean
    f = findings[0]
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"suppressions": [
        {"rule": f.rule, "path": f.path, "key": f.key, "why": "fixture"}]}))
    findings2, suppressed, unused = mxengine.run(
        ["pkg"], str(tmp_path), baseline_path=str(bl),
        rules=(locks.check,))
    assert findings2 == []
    assert len(suppressed) == 1 and unused == []
    # 3) stale entries are reported as unused, not silently kept
    bl.write_text(json.dumps({"suppressions": [
        {"rule": "L002", "path": "pkg/s.py", "key": "sleep:S.gone",
         "why": "stale"}]}))
    findings3, _sup, unused3 = mxengine.run(
        ["pkg"], str(tmp_path), baseline_path=str(bl),
        rules=(locks.check,))
    assert _keys(findings3) == {"sleep:S.f"}
    assert len(unused3) == 1


def test_baseline_entries_require_why(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"suppressions": [
        {"rule": "L002", "path": "x.py", "key": "sleep:f"}]}))
    with pytest.raises(ValueError, match="why"):
        mxengine.load_baseline(str(bl))


def test_syntax_error_reports_l000(tmp_path):
    findings = _scan(tmp_path, {"pkg/broken.py": "def f(:\n"}, ())
    assert _keys(findings) == {"syntax-error"}


# ---------------------------------------------------------------------------
# the actual gate: the real tree is clean
# ---------------------------------------------------------------------------

def test_repo_self_scan_is_clean():
    findings, suppressed, unused = mxengine.run(
        ["mxnet_tpu", "tools", "bench.py"], REPO)
    assert findings == [], "non-baselined mxlint findings:\n" + "\n".join(
        f.render() for f in findings)
    assert unused == [], "stale baseline entries: %r" % unused
    # the checked-in baseline stays small and justified
    entries = mxengine.load_baseline(mxengine.DEFAULT_BASELINE)
    assert len(entries) <= 10
    assert all(e["why"].strip() for e in entries)


def test_cli_exit_status():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mxlint",
         "mxnet_tpu", "tools", "bench.py"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "mxlint: clean" in proc.stderr
