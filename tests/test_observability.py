"""Observability surface tests (PR 9): atomic profiler counters under
thread pressure, thread_name metadata in dumps, request-scoped tracing
(async/flow event round-trips validated with tools/trace_check.py), the
always-on flight recorder and its escalation dump hooks, the unified
export snapshot / Prometheus / HTTP endpoint, and the disabled-path
overhead bound for tracing + recorder."""
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, profiler
from mxnet_tpu import np as mnp
from mxnet_tpu.profiler import core, export, recorder, trace
from tools.trace_check import check_trace


@pytest.fixture(autouse=True)
def _clean_observability_state():
    """Every test starts and ends with stopped profiler, disabled tracer,
    and an empty (but enabled) recorder ring."""
    profiler.set_state("stop")
    profiler.reset()
    trace.disable()
    trace.reset()
    recorder.enable()
    recorder.reset()
    yield
    profiler.set_state("stop")
    profiler.reset()
    trace.disable()
    trace.reset()
    recorder.enable()
    recorder.reset()
    export.stop_http()


# -- satellite: counter atomicity + dump under concurrency -------------------


@pytest.mark.parametrize("recording", [False, True])
def test_incr_counter_concurrent_exact(recording):
    """N threads x M increments == exactly N*M, recording or not (the
    read-modify-write now happens under the bus lock)."""
    if recording:
        profiler.set_state("run")
    n_threads, n_incr = 8, 500
    start = threading.Barrier(n_threads)

    def worker():
        start.wait()
        for _ in range(n_incr):
            core.incr_counter("obs::hammer", 1, "test")

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert core.counters_snapshot()["obs::hammer"] == n_threads * n_incr


def test_dump_parseable_while_writers_hammer(tmp_path, monkeypatch):
    """dump() copies the event list under the lock: dumping repeatedly
    while other threads append must always yield parseable JSON."""
    # full-speed writers hit the 2M event cap between dumps; a small cap
    # keeps each dump's serialization bounded without changing the race
    monkeypatch.setattr(core, "_MAX_EVENTS", 20_000)
    profiler.set_state("run")
    stop = threading.Event()

    def writer(i):
        while not stop.is_set():
            core.incr_counter(f"obs::w{i}", 1, "test")
            t = time.perf_counter_ns()
            core.record_duration(f"obs::d{i}", "test", t - 1000, t)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(10):
            p = tmp_path / f"dump{i}.json"
            core.dump(str(p))
            doc = json.loads(p.read_text())  # must never be torn
            assert isinstance(doc["traceEvents"], list)
    finally:
        stop.set()
        for t in threads:
            t.join()


def test_dump_carries_thread_name_metadata(tmp_path):
    """register_thread_name() + live threads show up as chrome 'M'
    thread_name rows so Perfetto lanes are labelled."""
    profiler.set_state("run")
    done = threading.Event()

    def named():
        core.register_thread_name()
        core.incr_counter("obs::named", 1, "test")
        done.wait()

    t = threading.Thread(target=named, name="obs-worker-7")
    t.start()
    try:
        time.sleep(0.05)
        p = tmp_path / "meta.json"
        core.dump(str(p))
    finally:
        done.set()
        t.join()
    evs = json.loads(p.read_text())["traceEvents"]
    meta = [e for e in evs if e.get("ph") == "M"]
    names = {e["args"]["name"] for e in meta
             if e.get("name") == "thread_name"}
    assert "obs-worker-7" in names
    assert any(e.get("name") == "process_name" for e in meta)


# -- tentpole 1: request-scoped tracing --------------------------------------


def test_trace_disabled_is_none_and_ambient_noop():
    assert trace.start_trace("x") is None
    with trace.activate(None):
        with trace.span("nothing"):
            pass
    assert trace.current() is None


def test_trace_spans_summary_and_error_tagging():
    trace.enable()
    tr = trace.start_trace("req", args={"k": 1})
    with tr.span("phase_a"):
        pass
    with pytest.raises(ValueError):
        with tr.span("phase_b"):
            raise ValueError("boom")
    tr.finish(error="boom")
    s = trace.summary(tr.trace_id)
    assert s["finished"] and s["error"] == "boom"
    assert [sp["name"] for sp in s["spans"]] == ["phase_a", "phase_b"]
    assert s["spans"][1]["args"]["error"] == "ValueError"
    assert s["by_name"]["phase_a"]["calls"] == 1
    # sealed: later spans are ignored
    tr.span_at("late", 0, 10)
    assert len(trace.summary(tr.trace_id)["spans"]) == 2


def test_trace_registry_bounded_eviction():
    trace.enable(max_traces=4)
    ids = [trace.start_trace(f"t{i}").trace_id for i in range(7)]
    assert trace.get(ids[0]) is None and trace.get(ids[2]) is None
    assert trace.get(ids[-1]) is not None
    assert len(trace.summaries(limit=100)) == 4
    trace.enable(max_traces=1024)  # restore default for later tests


def test_trace_ambient_activation_nests():
    trace.enable()
    outer, inner = trace.start_trace("outer"), trace.start_trace("inner")
    with trace.activate(outer):
        assert trace.current() is outer
        with trace.activate(inner):
            assert trace.current() is inner
            with trace.span("work"):
                pass
        assert trace.current() is outer
    assert trace.current() is None
    assert inner.summary()["spans"][0]["name"] == "work"
    assert outer.summary()["spans"] == []


def test_trace_events_round_trip_valid(tmp_path):
    """Span/flow emission produces a trace_check-clean dump: matched
    async b/e per id, every flow id exactly one s + one f."""
    trace.enable()
    profiler.set_state("run")
    tr = trace.start_trace("req")
    with tr.span("client_side"):
        fid = tr.flow_out("handoff")
    done = threading.Event()

    def other_thread():
        tr.flow_in(fid, "handoff")
        with trace.activate(tr), trace.span("worker_side"):
            pass
        done.set()

    threading.Thread(target=other_thread).start()
    assert done.wait(10)
    tr.finish()
    profiler.set_state("stop")
    p = tmp_path / "trace.json"
    core.dump(str(p))
    failures = check_trace(str(p), expect_lane=True, min_spans=2,
                           min_threads=2)
    assert not failures, failures
    evs = json.loads(p.read_text())["traceEvents"]
    sid = str(tr.trace_id)
    lane = [e for e in evs if e.get("id") == sid and e["ph"] in "be"]
    assert {e["name"] for e in lane} == {"client_side", "worker_side"}
    assert len({e["tid"] for e in lane}) == 2


def test_batcher_emits_connected_request_lane(tmp_path):
    """End to end: a traced serving request reads as one connected lane
    (admit -> queue -> execute across client + flusher threads), and shed
    /expired paths leave no orphan flow arrows."""
    from mxnet_tpu.serve import DynamicBatcher

    trace.enable()
    profiler.set_state("run")
    with DynamicBatcher(lambda xs: [x * 2 for x in xs], max_batch_size=4,
                        timeout_ms=2.0, name="obs") as b:
        futs = [b.submit(np.float32(i)) for i in range(6)]
        assert [f.result(timeout=30) for f in futs] == \
            [np.float32(i) * 2 for i in range(6)]
    profiler.set_state("stop")
    p = tmp_path / "serve_trace.json"
    core.dump(str(p))
    failures = check_trace(str(p), expect_lane=True, min_spans=3,
                           min_threads=2)
    assert not failures, failures
    # in-process summary agrees: every request saw all three stages
    summaries = [s for s in trace.summaries(limit=100)
                 if s["name"].startswith("serve.request")]
    assert len(summaries) == 6
    for s in summaries:
        names = {sp["name"] for sp in s["spans"]}
        assert {"serve::admit", "serve::queue",
                "serve::execute"} <= names, names
        assert s["finished"] and s["error"] is None
        assert s["threads"] >= 2


def test_batcher_failed_request_trace_carries_error():
    from mxnet_tpu.serve import DynamicBatcher

    trace.enable()

    def bad_runner(xs):
        raise RuntimeError("injected")

    with DynamicBatcher(bad_runner, max_batch_size=2, timeout_ms=1.0,
                        name="obs-err") as b:
        with pytest.raises(Exception):
            b.submit(np.float32(1)).result(timeout=30)
    s = [x for x in trace.summaries(limit=10)
         if x["name"].startswith("serve.request")][-1]
    assert s["finished"] and s["error"]
    ex = [sp for sp in s["spans"] if sp["name"] == "serve::execute"]
    assert ex and ex[0]["args"]["ok"] is False


def test_generator_decode_lane():
    """A direct generate() call (no batcher) opens its own
    serve.generate lane carrying prefill + per-token decode spans."""
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import Generator

    trace.enable()
    net = get_llama("llama_tiny_test")
    net.initialize()
    gen = Generator(net, max_seq=32, batch_buckets=(1, 2),
                    prompt_buckets=(8,), name="obs-gen")
    gen.warmup()  # pre-trace: warmup compiles stay off the request lane
    outs, _ = gen.generate([[3, 5, 7]], max_new_tokens=4)
    assert len(outs[0]) == 4
    s = [x for x in trace.summaries(limit=50)
         if x["name"] == "serve.generate[obs-gen]"][-1]
    names = [sp["name"] for sp in s["spans"]]
    assert "serve::prefill" in names
    assert names.count("serve::decode_step") >= 3
    assert any(n.startswith("serve::session_run") for n in names)
    assert s["finished"] and s["error"] is None


def test_training_step_spans_and_step_tagging():
    """estimator.fit wraps each batch in train::step and bumps the global
    step tag that dist_tpu collectives stamp into their args."""
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    trace.enable()
    x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    y = np.zeros((8,), np.float32)
    loader = DataLoader(ArrayDataset(x, y), batch_size=4)
    net = gluon.nn.Dense(1)
    net.initialize()
    est = Estimator(net, loss=gluon.loss.L2Loss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.01}))
    est.fit(loader, epochs=1)
    assert trace.current_step() == 2  # 8 samples / batch 4
    fits = [s for s in trace.summaries(limit=10)
            if s["name"].startswith("train.fit")]
    assert fits and fits[-1]["finished"]
    steps = [sp for sp in fits[-1]["spans"] if sp["name"] == "train::step"]
    assert [sp["args"]["step"] for sp in steps] == [1, 2]


# -- tentpole 2: flight recorder ---------------------------------------------


def test_recorder_ring_bounded_and_disable_is_noop():
    for i in range(recorder._ring.maxlen + 50):
        recorder.note("test", f"n{i}")
    ring = recorder.snapshot()
    assert len(ring) == recorder._ring.maxlen
    assert ring[-1]["name"] == f"n{recorder._ring.maxlen + 49}"
    recorder.disable()
    recorder.note("test", "ignored")
    assert recorder.snapshot()[-1]["name"] != "ignored"
    assert recorder.dump("nope") is None


def test_recorder_dump_contents_and_rate_limit(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    recorder.note("fault", "serve:execute", {"kind": "fatal"})
    p1 = recorder.dump("unit_test", args={"why": "testing"})
    assert p1 and os.path.dirname(p1) == str(tmp_path)
    doc = json.loads(open(p1).read())
    assert doc["reason"] == "unit_test" and doc["args"]["why"] == "testing"
    assert any(e["name"] == "serve:execute" and e["kind"] == "fault"
               for e in doc["ring"])
    assert "counters" in doc and "resilience_counters" in doc
    # same-reason dumps are rate-limited to 1/s...
    assert recorder.dump("unit_test") is None
    # ...unless forced or under a different reason
    assert recorder.dump("unit_test", force=True) is not None
    assert recorder.dump_count() == 2
    assert recorder.last_dump_path() != p1


def test_breaker_open_dumps_flight_recorder(tmp_path, monkeypatch):
    """Tripping a circuit breaker open writes a breaker_open dump whose
    ring carries the failures (and their fault sites) that tripped it."""
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.retry import CircuitBreaker

    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    faults.install_plan({"rules": [{"site": "obs:site", "kind": "fatal",
                                    "times": 3}]})
    try:
        br = CircuitBreaker(failure_threshold=3, name="obs-breaker")
        for _ in range(3):
            with pytest.raises(Exception):
                faults.fault_point("obs:site")
            br.record_failure()
    finally:
        faults.clear_plan()
    assert br.state == "open"
    p = recorder.last_dump_path()
    assert p and os.path.basename(p).endswith("-breaker_open.json")
    doc = json.loads(open(p).read())
    assert doc["args"]["breaker"] == "obs-breaker"
    assert sum(1 for e in doc["ring"]
               if e["kind"] == "fault" and e["name"] == "obs:site") == 3


def test_watchdog_timeout_dumps_flight_recorder(tmp_path, monkeypatch):
    from mxnet_tpu.resilience.retry import (CollectiveTimeoutError,
                                            run_with_watchdog)

    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    release = threading.Event()
    with pytest.raises(CollectiveTimeoutError):
        run_with_watchdog(lambda: release.wait(5), timeout_s=0.05,
                          site="obs:slow")
    release.set()
    p = recorder.last_dump_path()
    assert p and "watchdog_timeout" in os.path.basename(p)
    assert json.loads(open(p).read())["args"]["site"] == "obs:slow"


def test_divergence_error_dumps_flight_recorder(tmp_path, monkeypatch):
    from mxnet_tpu.resilience.guardrails import DivergenceError

    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    recorder.note("warn", "guardrail.skip", {"step": 12})
    err = DivergenceError("loss diverged at step 12")
    p = recorder.last_dump_path()
    assert p and "divergence" in os.path.basename(p)
    doc = json.loads(open(p).read())
    assert "diverged" in doc["args"]["message"]
    assert any(e["name"] == "guardrail.skip" for e in doc["ring"])
    assert isinstance(err, Exception)


def test_recorder_dump_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_MAX_DUMPS", "2")
    assert recorder.dump("r1") is not None
    assert recorder.dump("r2") is not None
    assert recorder.dump("r3") is None  # capped
    assert recorder.dump_count() == 2


# -- tentpole 3: unified export ----------------------------------------------


def _serve_one_request():
    from mxnet_tpu.serve import DynamicBatcher, InferenceSession

    net = gluon.nn.Dense(2)
    net.initialize()
    sess = InferenceSession(net, batch_buckets=(1, 2), name="obs-exp")
    sess.warmup(np.zeros((1, 3), np.float32))

    def runner(payloads):
        out = sess.predict(np.stack(payloads)).asnumpy()
        return [out[i] for i in range(len(payloads))]

    with DynamicBatcher(runner, max_batch_size=2, timeout_ms=1.0,
                        metrics=sess.metrics, name="obs-exp") as b:
        b.submit(np.ones(3, np.float32)).result(timeout=30)
    return sess


def test_snapshot_unifies_subsystem_namespaces():
    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync

    sess = _serve_one_request()
    kv = KVStoreDistTPUSync()
    kv.allreduce([mnp.ones((4,)), mnp.ones((4,))])
    mx.waitall()
    core.incr_counter("obs::snap", 3, "test")
    snap = export.snapshot()
    # one flat dict, every subsystem under its own prefix
    assert snap["obs::snap"] == 3
    assert snap["serve.obs-exp.requests"] >= 1
    assert "serve.obs-exp.p99_ms" in snap
    assert snap["cachedop.serve_hits"] >= 1
    assert snap["kvstore.allreduce_calls"] >= 1
    assert snap["kvstore.breaker_state"] == "closed"
    assert "resilience.faults_injected" in snap
    assert "engine.dispatches" in snap
    assert snap["recorder.enabled"] == 1 and snap["trace.enabled"] == 0
    assert "profiler.dropped_events" in snap
    del sess


def test_render_prometheus_format():
    core.incr_counter("obs::prom", 2, "test")
    text = export.render_prometheus()
    lines = [ln for ln in text.strip().splitlines()]
    assert "mxnet_obs__prom 2" in lines
    for ln in lines:  # every row: name[{label}] value
        name, _, val = ln.rpartition(" ")
        assert name and (val.lstrip("-").replace(".", "", 1)
                         .replace("e-", "", 1).replace("e+", "", 1)
                         .replace("inf", "0").isdigit()
                         or val in ("1",)), ln


def test_health_merges_providers():
    sess = _serve_one_request()
    h = export.health()
    assert "obs-exp" in h["sessions"]
    assert h["ready"] is True
    assert h["sessions"]["obs-exp"]["state"]
    del sess


def test_http_endpoint_metrics_healthz_snapshot():
    sess = _serve_one_request()
    port = export.start_http(port=0)
    assert export.server_port() == port
    assert export.start_http(port=0) == port  # idempotent
    base = f"http://127.0.0.1:{port}"
    with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        body = r.read().decode()
    assert "mxnet_serve_obs_exp_requests" in body
    with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
        doc = json.loads(r.read())
        assert r.status == 200 and doc["ready"] is True
    with urllib.request.urlopen(f"{base}/snapshot", timeout=10) as r:
        assert "serve.obs-exp.requests" in json.loads(r.read())
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{base}/nope", timeout=10)
    assert ei.value.code == 404
    export.stop_http()
    assert export.server_port() is None
    del sess


def test_trace_check_tool_flags_broken_traces(tmp_path):
    """The validator itself: orphan flows, unmatched async, bad ts."""
    good = {"traceEvents": [
        {"ph": "b", "cat": "t", "id": "1", "name": "a", "pid": 1,
         "tid": 1, "ts": 1.0},
        {"ph": "e", "cat": "t", "id": "1", "name": "a", "pid": 1,
         "tid": 1, "ts": 2.0},
        {"ph": "s", "cat": "f", "id": "9", "name": "h", "pid": 1,
         "tid": 1, "ts": 1.0},
        {"ph": "f", "bp": "e", "cat": "f", "id": "9", "name": "h",
         "pid": 1, "tid": 2, "ts": 1.5}]}
    p = tmp_path / "good.json"
    p.write_text(json.dumps(good))
    assert check_trace(str(p)) == []
    bad = {"traceEvents": [
        {"ph": "b", "cat": "t", "id": "1", "name": "a", "pid": 1,
         "tid": 1, "ts": 1.0},
        {"ph": "s", "cat": "f", "id": "9", "name": "h", "pid": 1,
         "tid": 1, "ts": 1.0},
        {"ph": "X", "name": "x", "pid": 1, "tid": 1, "ts": 3.0,
         "dur": -1}]}
    p2 = tmp_path / "bad.json"
    p2.write_text(json.dumps(bad))
    failures = check_trace(str(p2))
    assert any("begin vs" in f or "begin" in f for f in failures)
    assert any("flow id" in f for f in failures)
    assert any("bad dur" in f for f in failures)
    assert check_trace(str(tmp_path / "missing.json"))


# -- overhead bound ----------------------------------------------------------


@pytest.mark.serial
def test_disabled_trace_and_recorder_overhead_under_5pct():
    """Eager microloop with tracing disabled + recorder enabled (the
    always-on production default) must stay within 5% of the fully
    unhooked baseline — the flight recorder's cost contract."""
    from mxnet_tpu import engine
    from mxnet_tpu.ops import registry

    x = mnp.ones((4,))

    def loop(n=10_000):
        y = x
        t0 = time.perf_counter()
        for _ in range(n):
            y = y + 1.0
        y.wait_to_read()
        return time.perf_counter() - t0

    saved = registry._PROF, engine._PROF

    def measure(rounds=7):
        base = hooked = float("inf")
        for _ in range(rounds):
            registry._PROF = None
            engine._PROF = None
            trace.disable()
            recorder.disable()
            base = min(base, loop())
            profiler.set_state("run")
            profiler.set_state("stop")
            recorder.enable()  # always-on default; trace stays disabled
            hooked = min(hooked, loop())
        return base, hooked

    try:
        loop(2000)  # warm caches before either arm
        base, hooked = measure()
        if hooked > base * 1.05:  # timing noise: one clean re-measure
            base, hooked = measure(rounds=9)
    finally:
        registry._PROF, engine._PROF = saved
        recorder.enable()
    assert hooked <= base * 1.05, (
        f"disabled trace+recorder overhead {hooked / base - 1:.1%} "
        f"(baseline {base:.3f}s, hooked {hooked:.3f}s)")
