"""Regression tests for the round-3 advisor findings (ADVICE.md r3)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, np
from mxnet_tpu.ndarray import sparse


def test_sgd_lazy_update_defaults_false():
    """Reference 2.x default (python/mxnet/optimizer/sgd.py:95) is
    lazy_update=False; lazy is opt-in and incompatible with
    multi_precision (sgd.py:105)."""
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    assert opt.lazy_update is False
    with pytest.raises(ValueError):
        mx.optimizer.create("sgd", learning_rate=0.1, lazy_update=True,
                            multi_precision=True)


def test_sparse_dot_recorded_fallback_honors_transpose_a():
    """advisor: the recorded dense fallback for a tracked CSR lhs computed
    lhs@rhs instead of lhs.T@rhs."""
    import scipy.sparse as sp

    a = onp.random.rand(3, 4).astype(onp.float32)
    a[a < 0.5] = 0
    rhs = onp.random.rand(3, 2).astype(onp.float32)
    a_sp = sp.csr_matrix(a)
    csr = sparse.csr_matrix(
        (a_sp.data, a_sp.indices.astype(onp.int64),
         a_sp.indptr.astype(onp.int64)), shape=a.shape)
    # track the csr lhs so the dense recorded fallback runs
    csr.attach_grad()
    r = np.array(rhs)
    with autograd.record():
        out = sparse.dot(csr, r, transpose_a=True)
        loss = out.sum()
    assert out.shape == (4, 2)
    onp.testing.assert_allclose(out.asnumpy(), a.T @ rhs, rtol=1e-5)
    # the fallback must stay ON the tape: L = sum(A^T R) so
    # dL/dA[i,j] = sum_k R[i,k] — each row of grad(A) is R's row-sum
    loss.backward()
    expect = onp.broadcast_to(rhs.sum(axis=1, keepdims=True), a.shape)
    onp.testing.assert_allclose(csr.grad.asnumpy(), expect, rtol=1e-5)


def test_multibox_target_negative_mining_ranks_by_bg_prob():
    """advisor: negatives must be mined by ASCENDING softmax background
    probability (multibox_target.cc:219-237), not max foreground logit."""
    from mxnet_tpu.ops import detection

    # 4 anchors, no overlap with the single gt except anchor 0
    anchors = onp.array([[[0.0, 0.0, 0.5, 0.5],
                          [0.6, 0.6, 0.7, 0.7],
                          [0.8, 0.8, 0.9, 0.9],
                          [0.1, 0.6, 0.2, 0.7]]], onp.float32)
    label = onp.array([[[0.0, 0.0, 0.0, 0.5, 0.5]]], onp.float32)
    # logits (batch, classes=2, anchors). Candidate negatives: anchors
    # 1,2,3. Background probs: anchor1 lowest (hardest), anchor2 highest.
    cls_pred = onp.zeros((1, 2, 4), onp.float32)
    cls_pred[0, 0, 1] = -5.0   # anchor1: bg logit low  -> hardest negative
    cls_pred[0, 0, 2] = +5.0   # anchor2: bg logit high -> easiest negative
    # quota = ratio*num_pos = 1 (with minimum_negative_samples=0) ->
    # exactly anchor1 must be kept as negative, others ignored
    _, _, cls_t = detection.multibox_target(
        np.array(anchors), np.array(label), np.array(cls_pred),
        overlap_threshold=0.5, negative_mining_ratio=1.0,
        negative_mining_thresh=0.5, minimum_negative_samples=0,
        ignore_label=-1)
    got = cls_t.asnumpy()[0]
    assert got[0] == 1.0           # matched -> class 0 + 1
    assert got[1] == 0.0           # hardest negative trains as background
    assert got[2] == -1.0          # easy negative ignored
    assert got[3] == -1.0


def test_batch_norm_training_stats_are_fp32_under_bf16():
    """advisor: batch mean/var feed the running-stat update and must stay
    fp32 under AMP (reference keeps BN aux states fp32)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import nn as nn_ops

    x = np.array(onp.random.rand(4, 3, 2, 2).astype(onp.float32)).astype(
        jnp.bfloat16)
    gamma = np.ones((3,)).astype(jnp.bfloat16)
    beta = np.zeros((3,)).astype(jnp.bfloat16)
    rm, rv = np.zeros((3,)), np.ones((3,))
    with autograd.train_mode():
        out, mean, var = nn_ops.batch_norm(x, gamma, beta, rm, rv,
                                           output_mean_var=True)
    assert out.dtype == jnp.bfloat16          # activations stay bf16
    assert mean.dtype == onp.float32          # stats full precision
    assert var.dtype == onp.float32


def test_gamma_sign_on_negative_axis():
    """advisor: Γ(x) must carry the alternating sign for negative
    non-integer x even without jax gammasgn."""
    import math

    from mxnet_tpu.ops import nn as nn_ops

    x = onp.array([-0.5, -1.5, -2.5, 0.5, 3.0], onp.float32)
    got = nn_ops.gamma(np.array(x)).asnumpy()
    expect = onp.array([math.gamma(v) for v in x], onp.float32)
    onp.testing.assert_allclose(got, expect, rtol=1e-4)
    # the explicit floor-parity fallback agrees with gammasgn
    import jax.numpy as jnp

    sign_fallback = onp.where(
        (x < 0) & (onp.floor(x) % 2 != 0), -1.0, 1.0)
    onp.testing.assert_array_equal(sign_fallback, onp.sign(expect))
    del jnp
