"""YOLOv3 family tests (VERDICT r3 item 2): darknet53 backbone, target
assignment oracle, loss finite + decreasing, hybridized inference, zoo
exposure. Architecture per 1804.02767; reference flagship config naming
per BASELINE.json ("GluonCV: ResNet-50 / YOLOv3")."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mnp
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.vision.darknet import _conv2d
from mxnet_tpu.gluon.model_zoo.vision.yolo import (
    _DEFAULT_ANCHORS, YOLOV3, YOLOV3Loss, yolo3_targets)


def _tiny(classes=3):
    def stage(ch, n_down):
        s = nn.HybridSequential()
        for _ in range(n_down):
            s.add(_conv2d(ch, 3, 1, strides=2))
        return s

    anchors = [[(16, 16), (32, 24), (24, 32)],
               [(48, 48), (64, 48), (48, 64)],
               [(96, 96), (128, 96), (96, 128)]]
    net = YOLOV3([stage(8, 3), stage(16, 1), stage(32, 1)],
                 channels=(8, 16, 32), classes=classes, anchors=anchors)
    net.initialize(init=mx.init.Xavier())
    return net, anchors


def test_net_anchors_are_scale_ordered():
    """net.anchors must stay [stride8, 16, 32] even though the heads are
    built deepest-first (the example reads it for target generation)."""
    net, anchors = _tiny()
    assert net.anchors == [list(map(tuple, g)) for g in anchors]
    assert net.strides == [8, 16, 32]
    # the deepest-first head order is the reverse
    head_anchors = [tuple(map(tuple, h._anchors)) for h in net.yolo_outputs]
    assert list(head_anchors) == [tuple(map(tuple, g))
                                  for g in reversed(anchors)]


def test_zoo_exposes_yolo3_and_darknet():
    net = vision.get_model("yolo3_darknet53", classes=5)
    assert isinstance(net, YOLOV3)
    assert len(net.yolo_outputs) == 3
    clf = vision.get_model("darknet53", classes=7)
    # darknet53 trunk: 29 feature blocks (stem + 5 stages)
    assert len(clf.features) == 29


def test_darknet53_stage_strides_and_channels():
    """The yolo3_darknet53 stage split must tap strides 8/16/32 with
    channels 256/512/1024 (1804.02767 Table 1)."""
    net = vision.get_model("yolo3_darknet53", classes=2)
    net.initialize(init=mx.init.Xavier())
    x = mnp.array(onp.random.rand(1, 3, 64, 64).astype("float32"))
    with autograd.predict_mode():
        feats = []
        for stage in net.stages:
            x = stage(x)
            feats.append(x.shape)
    assert feats == [(1, 256, 8, 8), (1, 512, 4, 4), (1, 1024, 2, 2)]


def test_train_output_shapes():
    net, _ = _tiny()
    x = mnp.array(onp.random.rand(2, 3, 64, 64).astype("float32"))
    with autograd.train_mode():
        (raw_c, raw_s, obj, cls, anc, off, strd) = net(x)
    n = (8 * 8 + 4 * 4 + 2 * 2) * 3
    assert raw_c.shape == (2, n, 2)
    assert raw_s.shape == (2, n, 2)
    assert obj.shape == (2, n, 1)
    assert cls.shape == (2, n, 3)
    assert anc.shape == (1, n, 2)
    assert off.shape == (1, n, 2)
    assert strd.shape == (1, n, 1)


def test_target_assignment_oracle():
    """A gt box whose shape equals anchor (30, 61) of scale 1 must land at
    exactly that scale/cell/anchor slot with the documented encodings."""
    size = 128
    labels = onp.full((1, 2, 5), -1.0, "float32")
    # gt: 30x61px box centered at (70, 50) -> stride-16 cell (4, 3)
    cx, cy, gw, gh = 70.0, 50.0, 30.0, 61.0
    labels[0, 0] = [2, (cx - gw / 2) / size, (cy - gh / 2) / size,
                    (cx + gw / 2) / size, (cy + gh / 2) / size]
    obj, ctr, scl, wgt, cls, gtb = yolo3_targets(labels, size, 4)
    n8, n16 = 16 * 16 * 3, 8 * 8 * 3
    pos = onp.flatnonzero(obj[0, :, 0])
    assert len(pos) == 1
    idx = pos[0]
    # scale 1 (stride 16), cell ci=4, cj=3, anchor 0 of that scale
    ci, cj = int(cx / 16), int(cy / 16)
    assert idx == n8 + (cj * 8 + ci) * 3 + 0
    onp.testing.assert_allclose(ctr[0, idx], [cx / 16 - ci, cy / 16 - cj],
                                atol=1e-5)
    onp.testing.assert_allclose(scl[0, idx], [0.0, 0.0], atol=1e-5)
    assert cls[0, idx].tolist() == [0.0, 0.0, 1.0, 0.0]
    onp.testing.assert_allclose(wgt[0, idx],
                                [2.0 - gw * gh / size / size] * 2,
                                rtol=1e-5)
    onp.testing.assert_allclose(
        gtb[0, 0], [cx - gw / 2, cy - gh / 2, cx + gw / 2, cy + gh / 2])
    # padded row stays invalid
    onp.testing.assert_array_equal(gtb[0, 1], [-1, -1, -1, -1])
    del n16


def test_target_best_anchor_selection():
    """gt shaped exactly like the largest default anchor must pick scale 2."""
    size = 416
    a_w, a_h = _DEFAULT_ANCHORS[2][2]  # (373, 326)
    labels = onp.full((1, 1, 5), -1.0, "float32")
    labels[0, 0] = [0, 0.5 - a_w / size / 2, 0.5 - a_h / size / 2,
                    0.5 + a_w / size / 2, 0.5 + a_h / size / 2]
    obj, _, scl, _, _, _ = yolo3_targets(labels, size, 1)
    n8 = 52 * 52 * 3
    n16 = 26 * 26 * 3
    pos = onp.flatnonzero(obj[0, :, 0])
    assert len(pos) == 1
    assert pos[0] >= n8 + n16, "largest gt must land on the stride-32 head"
    onp.testing.assert_allclose(scl[0, pos[0]], [0.0, 0.0], atol=1e-5)


def test_loss_finite_and_decreases():
    rng = onp.random.RandomState(3)
    net, anchors = _tiny(classes=2)
    loss_fn = YOLOV3Loss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 2e-3})
    size, batch = 64, 4
    imgs = rng.rand(batch, 3, size, size).astype("float32")
    labels = onp.full((batch, 1, 5), -1.0, "float32")
    for i in range(batch):
        labels[i, 0] = [i % 2, 0.25, 0.25, 0.75, 0.75]
    targets = [mnp.array(t)
               for t in yolo3_targets(labels, size, 2,
                                      anchors=anchors)]
    x = mnp.array(imgs)
    losses = []
    for _ in range(6):
        with autograd.record():
            loss = loss_fn(*net(x), *targets)
        loss.backward()
        tr.step(batch)
        v = float(loss.asnumpy())
        assert onp.isfinite(v)
        losses.append(v)
    assert losses[-1] < losses[0], losses


def test_hybrid_matches_eager_train_outputs():
    net, _ = _tiny()
    x = mnp.array(onp.random.rand(1, 3, 64, 64).astype("float32"))
    with autograd.train_mode():
        eager = [o.asnumpy() for o in net(x)]
    net.hybridize()
    with autograd.train_mode():
        hybrid = [o.asnumpy() for o in net(x)]
    for e, h in zip(eager, hybrid):
        onp.testing.assert_allclose(e, h, rtol=2e-5, atol=2e-5)


def test_inference_shapes_and_nms_contract():
    net, _ = _tiny(classes=3)
    net.hybridize()
    x = mnp.array(onp.random.rand(2, 3, 64, 64).astype("float32"))
    with autograd.predict_mode():
        ids, scores, boxes = net(x)
    n = (8 * 8 + 4 * 4 + 2 * 2) * 3 * 3  # anchors × classes
    assert ids.shape == (2, n, 1)
    assert scores.shape == (2, n, 1)
    assert boxes.shape == (2, n, 4)
    s = scores.asnumpy()[:, :, 0]
    # box_nms contract: rows sorted by descending score, pruned rows -1
    valid = s >= 0
    for b in range(2):
        sv = s[b][valid[b]]
        assert (onp.diff(sv) <= 1e-6).all()


def test_box_iou_oracle():
    from mxnet_tpu import npx

    a = onp.array([[[0, 0, 2, 2], [1, 1, 3, 3]]], "float32")
    b = onp.array([[[0, 0, 2, 2], [2, 2, 4, 4], [-1, -1, -1, -1]]],
                  "float32")
    got = npx.box_iou(mnp.array(a), mnp.array(b)).asnumpy()
    assert got.shape == (1, 2, 3)
    onp.testing.assert_allclose(got[0, 0], [1.0, 0.0, 0.0], atol=1e-6)
    onp.testing.assert_allclose(got[0, 1], [1 / 7, 1 / 7, 0.0], rtol=1e-5)
