"""Llama-3-8B AOT sharding/memory proof + remat/inner-AMP correctness
(VERDICT r3 item 5). The 8B config is NEVER materialized: the abstract
trainer lowers from ShapeDtypeStructs (parallel/functional.py
``functionalize_abstract`` / ``ShardedTrainer(abstract=True)``)."""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import np as mnp
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.llama import get_llama, llama_sharding_rules
from mxnet_tpu.parallel.functional import ShardedTrainer, ShardingRules


def _mesh8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return Mesh(onp.array(devs[:8]).reshape(1, 8), ("dp", "tp"))


def _loss_fn(out, labels):
    from mxnet_tpu.gluon import loss as gl

    return gl.SoftmaxCrossEntropyLoss(sparse_label=True)(out, labels)


def _tiny_trainer(mesh, remat, amp, seed=0, optimizer="sgd"):
    m = get_llama("llama_tiny_test", remat=remat)
    m.initialize(init=mx.init.Xavier(), force_reinit=True)
    onp.random.seed(seed)
    for _, p in sorted(m.collect_params().items()):
        p.set_data(mnp.array(
            onp.random.randn(*p.shape).astype("float32") * 0.02))
    return ShardedTrainer(m, _loss_fn, optimizer, {"learning_rate": 0.1},
                          mesh=mesh, rules=ShardingRules(
                              llama_sharding_rules()),
                          batch_spec=P("dp"), dtype=amp)


def test_llama8b_aot_fits_v5e():
    """THE proof: 8.03B params, tp=8 fp32 Adam, remat, B=1 T=1024 —
    per-device args+temp from XLA's buffer assignment < 16 GiB."""
    mesh = _mesh8()
    model = get_llama("llama3_8b", remat=True)
    tr = ShardedTrainer(model, _loss_fn, "adam", {"learning_rate": 1e-4},
                        mesh=mesh,
                        rules=ShardingRules(llama_sharding_rules()),
                        batch_spec=P("dp"), abstract=True)
    n_params = sum(int(onp.prod(s.shape)) for s in tr.params.values())
    assert abs(n_params / 1e9 - 8.03) < 0.01
    # fp32 Adam arithmetic: 8.03e9 * 12 bytes / 8 devices = 11.22 GiB
    args_expect = n_params * 12 / 8 / 2**30
    compiled = tr.aot_lower(jax.ShapeDtypeStruct((1, 1024), jnp.int32),
                            jax.ShapeDtypeStruct((1, 1024), jnp.int32))
    ma = compiled.memory_analysis()
    args_gib = ma.argument_size_in_bytes / 2**30
    assert abs(args_gib - args_expect) < 0.2, (args_gib, args_expect)
    peak = args_gib + ma.temp_size_in_bytes / 2**30
    assert peak < 16.0, f"peak {peak:.2f} GiB exceeds v5e HBM"
    # Megatron TP must communicate: partial-sum activations all-reduce
    assert compiled.as_text().count("all-reduce") > 0


def test_abstract_trainer_refuses_to_run():
    mesh = _mesh8()
    model = get_llama("llama_tiny_test")
    tr = ShardedTrainer(model, _loss_fn, "sgd", {"learning_rate": 0.1},
                        mesh=mesh,
                        rules=ShardingRules(llama_sharding_rules()),
                        batch_spec=P("dp"), abstract=True)
    ids = onp.zeros((1, 16), "int32")
    with pytest.raises(MXNetError):
        tr.step(ids, ids)


def test_remat_step_matches_plain_step():
    """jax.checkpoint per decoder layer must not change the math."""
    mesh = _mesh8()
    ids = (onp.arange(32).reshape(1, 32) % 256).astype("int32")
    results = []
    for remat in (False, True):
        tr = _tiny_trainer(mesh, remat=remat, amp=None)
        loss = float(tr.step(ids, ids).asnumpy())
        w = onp.asarray(tr.params[sorted(tr.params)[0]])
        results.append((loss, w))
    (l0, w0), (l1, w1) = results
    assert abs(l0 - l1) < 1e-5
    onp.testing.assert_allclose(w0, w1, atol=1e-7)


def test_inner_amp_matches_outer_amp():
    """Cast-at-use inside the remat boundary (supports_inner_amp) must
    agree with the trainer's whole-tree pre-cast to bf16 tolerance."""
    mesh = _mesh8()
    ids = (onp.arange(32).reshape(1, 32) % 256).astype("int32")
    results = []
    for remat in (False, True):  # False -> outer pre-cast; True -> inner
        tr = _tiny_trainer(mesh, remat=remat, amp=jnp.bfloat16)
        loss = float(tr.step(ids, ids).asnumpy())
        w = onp.asarray(tr.params[sorted(tr.params)[0]])
        results.append((loss, w))
    (l0, w0), (l1, w1) = results
    assert abs(l0 - l1) < 1e-3
    onp.testing.assert_allclose(w0, w1, atol=1e-4)


def test_abstract_placeholders_are_poisoned():
    """After an abstract functionalization, eager param access and silent
    re-initialize must fail loudly; force_reinit recovers the block."""
    from mxnet_tpu.parallel.functional import functionalize_abstract

    m = get_llama("llama_tiny_test")
    functionalize_abstract(m)
    p = m.collect_params()[sorted(m.collect_params())[0]]
    with pytest.raises(MXNetError):
        p.data()
    with pytest.raises(MXNetError):
        m.initialize()
    m.initialize(force_reinit=True)
    out = m(mnp.array(onp.zeros((1, 8), dtype="int32")))
    assert out.shape == (1, 8, 256)


def test_amp_dtype_does_not_leak_across_trainers():
    """Two trainers with different AMP dtypes on the SAME block: each
    trainer's RE-trace (new batch signature) must keep ITS dtype — the
    inner-AMP attribute is trace-scoped, not persistent block state."""
    mesh = _mesh8()
    m = get_llama("llama_tiny_test", remat=True)
    m.initialize(init=mx.init.Xavier())
    tr_bf16 = ShardedTrainer(m, _loss_fn, "sgd", {"learning_rate": 0.1},
                             mesh=mesh,
                             rules=ShardingRules(llama_sharding_rules()),
                             batch_spec=P("dp"), dtype=jnp.bfloat16)
    tr_fp32 = ShardedTrainer(m, _loss_fn, "sgd", {"learning_rate": 0.1},
                             mesh=mesh,
                             rules=ShardingRules(llama_sharding_rules()),
                             batch_spec=P("dp"), dtype=None)
    ids16 = (onp.arange(16).reshape(1, 16) % 256).astype("int32")
    ids32 = (onp.arange(32).reshape(1, 32) % 256).astype("int32")
    tr_bf16.step(ids16, ids16)
    tr_fp32.step(ids16, ids16)   # would have clobbered a persistent attr
    tr_bf16.step(ids32, ids32)   # fresh signature -> fresh trace
    assert "bf16" in tr_bf16._last_compiled.as_text()
    tr_fp32.step(ids32, ids32)
    assert "bf16" not in tr_fp32._last_compiled.as_text()
    # the attribute itself is restored after every trace
    assert getattr(m, "_amp_dtype", None) is None


def test_functionalize_abstract_requires_static_shapes():
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel.functional import functionalize_abstract

    net = gluon.nn.Dense(4)  # deferred in_units
    with pytest.raises(MXNetError):
        functionalize_abstract(net)


def test_llama_static_shapes_at_construction():
    """All llama params must be statically shaped (the abstract path's
    precondition) — pins the explicit in_units wiring."""
    m = get_llama("llama_tiny_test")
    for n, p in m.collect_params().items():
        assert p.shape is not None and all(s > 0 for s in p.shape), (n, p.shape)


def test_zero_dp8_sharding_lowers_with_gathers():
    """ZeRO-3-style lowering (r5): params + Adam moments sharded over
    the SAME 8-way axis the batch is data-parallel over. The compiled
    step must gather params (all-gather) and reduce gradients
    (reduce-scatter or all-reduce) — pins that the fsdp default rules
    actually shard instead of replicating. Fit is NOT asserted here:
    the CPU heap sim schedules every layer's gather up front (measured
    34 GiB artifact); the real TPU compiler's plan is 13.8 GiB
    (exp/llama8b_aot.json, memory_backend=tpu-aot)."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(onp.array(devs[:8]).reshape(8), ("fsdp",))
    model = get_llama("llama_tiny_test", remat=True)
    tr = ShardedTrainer(model, _loss_fn, "adam", {"learning_rate": 1e-4},
                        mesh=mesh,
                        rules=ShardingRules((), default_axis="fsdp"),
                        batch_spec=P("fsdp"), abstract=True)
    compiled = tr.aot_lower(
        jax.ShapeDtypeStruct((8, 64), jnp.int32),
        jax.ShapeDtypeStruct((8, 64), jnp.int32))
    txt = compiled.as_text()
    assert txt.count("all-gather") > 0, "ZeRO lowering gathered nothing"
    assert txt.count("reduce-scatter") + txt.count("all-reduce") > 0


@pytest.mark.slow
def test_zero_dp8_bucketed_gather_count_is_bucket_proportional():
    """THE PR-15 pin: the llama-8B ZeRO-dp8 step used to lower with 1829
    all-gathers (one per param); with flat fusion buffers at the default
    200 MB target it must collapse to ONE all-gather instruction per
    bucket — ~131 for 8B, comfortably under the 200 budget. Counted at
    the instruction level (``= <id> all-gather(``): plain
    ``count("all-gather")`` also matches sharding metadata and
    overcounts ~30x. Marked slow (~45s of 8B abstract lowering) to keep
    the tier-1 wall under its timeout; the tiny-config collapse pin in
    tests/test_bucketing.py enforces the same invariant in tier-1."""
    import re

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(onp.array(devs[:8]).reshape(8), ("fsdp",))
    model = get_llama("llama3_8b", remat=True)
    tr = ShardedTrainer(model, _loss_fn, "adam", {"learning_rate": 1e-4},
                        mesh=mesh,
                        rules=ShardingRules((), default_axis="fsdp"),
                        batch_spec=P("fsdp"), abstract=True,
                        zero_bucket_mb=200)
    n_buckets = len(tr._zb_specs)
    assert 1 < n_buckets <= 200, n_buckets
    compiled = tr.aot_lower(jax.ShapeDtypeStruct((8, 64), jnp.int32),
                            jax.ShapeDtypeStruct((8, 64), jnp.int32))
    gathers = len(re.findall(r"= \S+ all-gather(?:-start)?\(",
                             compiled.as_text()))
    assert gathers == n_buckets, (gathers, n_buckets)
    assert gathers <= 200, gathers


def test_layer_barrier_is_threaded_into_the_trace():
    """layer_barrier=True must put one optimization_barrier per decoder
    layer into the lowered module (visible in StableHLO; backends may
    fold it after scheduling)."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(onp.array(devs[:8]).reshape(8), ("fsdp",))
    model = get_llama("llama_tiny_test", remat=True, layer_barrier=True)
    tr = ShardedTrainer(model, _loss_fn, "sgd", {"learning_rate": 0.1},
                        mesh=mesh,
                        rules=ShardingRules((), default_axis="fsdp"),
                        batch_spec=P("fsdp"), abstract=True)
    lowered = tr.aot_lowered(
        jax.ShapeDtypeStruct((8, 32), jnp.int32),
        jax.ShapeDtypeStruct((8, 32), jnp.int32))
    n = lowered.as_text().count("optimization_barrier")
    assert n >= 2, n  # one per decoder layer (tiny config: 2 layers)


def test_bf16_master_cast_halves_argument_bytes():
    """Block.cast('bfloat16') -> 6 B/param (bf16 masters + 2 Adam
    moments) vs fp32's 12 B/param, visible in the abstract lowering's
    argument size."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    mesh = Mesh(onp.array(devs[:8]).reshape(1, 8), ("dp", "tp"))
    sizes = {}
    for cast in (False, True):
        model = get_llama("llama_tiny_test", remat=True)
        if cast:
            model.cast("bfloat16")
        tr = ShardedTrainer(model, _loss_fn, "adam",
                            {"learning_rate": 1e-4}, mesh=mesh,
                            rules=ShardingRules(llama_sharding_rules()),
                            batch_spec=P("dp"), abstract=True)
        c = tr.aot_lower(jax.ShapeDtypeStruct((1, 64), jnp.int32),
                         jax.ShapeDtypeStruct((1, 64), jnp.int32))
        sizes[cast] = c.memory_analysis().argument_size_in_bytes
    ratio = sizes[True] / sizes[False]
    assert 0.45 < ratio < 0.58, ratio
