"""Pipeline + expert parallelism tests (SURVEY §2.3 design-fresh list),
on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.parallel import (MoEBlock, make_mesh, moe_dispatch_combine,
                                moe_sharding_rules, pipeline_apply,
                                stack_stage_params)


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _stages(n, d, seed=0):
    rng = onp.random.RandomState(seed)
    return [(jnp.asarray(rng.randn(d, d).astype("float32") * 0.3),
             jnp.asarray(rng.randn(d).astype("float32") * 0.1))
            for _ in range(n)]


def test_pipeline_matches_sequential():
    mesh = make_mesh({"pp": 4})
    d = 16
    stages = _stages(4, d)
    stacked = stack_stage_params(stages, mesh, "pp")
    x = jnp.asarray(onp.random.RandomState(1).randn(8, d).astype("float32"))
    got = pipeline_apply(_stage_fn, stacked, x, mesh, "pp",
                         num_microbatches=4)
    want = x
    for p in stages:
        want = _stage_fn(p, want)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-5, atol=1e-5)


def test_pipeline_microbatch_counts():
    mesh = make_mesh({"pp": 2})
    d = 8
    stages = _stages(2, d, seed=3)
    stacked = stack_stage_params(stages, mesh, "pp")
    x = jnp.asarray(onp.random.randn(12, d).astype("float32"))
    for m in (2, 3, 6):
        got = pipeline_apply(_stage_fn, stacked, x, mesh, "pp",
                             num_microbatches=m)
        want = _stage_fn(stages[1], _stage_fn(stages[0], x))
        onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                    rtol=1e-5, atol=1e-5)


def test_pipeline_differentiable():
    mesh = make_mesh({"pp": 4})
    d = 8
    stages = _stages(4, d, seed=5)
    stacked = stack_stage_params(stages, mesh, "pp")
    x = jnp.asarray(onp.random.randn(4, d).astype("float32"))

    def loss(params, x):
        return pipeline_apply(_stage_fn, params, x, mesh, "pp").sum()

    g = jax.grad(loss)(stacked, x)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(onp.isfinite(onp.asarray(l)).all() for l in leaves)
    assert sum(float(jnp.abs(l).sum()) for l in leaves) > 0
    # numerical check against the sequential program's grad
    def seq_loss(params, x):
        out = x
        for i in range(4):
            out = _stage_fn(jax.tree_util.tree_map(lambda p: p[i], params),
                            out)
        return out.sum()

    g2 = jax.grad(seq_loss)(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g2)):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_bad_config():
    mesh = make_mesh({"dp": 8})
    with pytest.raises(mx.MXNetError, match="no 'pp' axis"):
        pipeline_apply(_stage_fn, [], jnp.zeros((4, 2)), mesh, "pp")


def test_moe_dispatch_matches_manual_top1():
    """With generous capacity, top-1 MoE == routing each token through its
    argmax expert."""
    rng = onp.random.RandomState(0)
    n, d, e, c = 32, 8, 4, 32
    x = jnp.asarray(rng.randn(n, d).astype("float32"))
    logits = jnp.asarray(rng.randn(n, e).astype("float32"))
    w = jnp.asarray(rng.randn(e, d, d).astype("float32"))

    def experts(inp):
        return jnp.einsum("ecd,edh->ech", inp, w)

    out, aux = moe_dispatch_combine(x, logits, experts, e, c)
    probs = onp.asarray(jax.nn.softmax(logits, -1))
    idx = probs.argmax(-1)
    want = onp.stack([
        probs[i, idx[i]] * (onp.asarray(x)[i] @ onp.asarray(w)[idx[i]])
        for i in range(n)])
    onp.testing.assert_allclose(onp.asarray(out), want, rtol=1e-4,
                                atol=1e-5)
    assert float(aux) > 0


def test_moe_capacity_drops_overflow():
    """Tokens beyond an expert's capacity fall out (output rows zero)."""
    n, d, e, c = 8, 4, 2, 2
    x = jnp.ones((n, d), "float32")
    logits = jnp.zeros((n, e), "float32").at[:, 0].set(10.0)  # all -> e0

    def experts(inp):
        return inp

    out, _ = moe_dispatch_combine(x, logits, experts, e, c)
    nonzero_rows = (onp.abs(onp.asarray(out)).sum(-1) > 1e-6).sum()
    assert nonzero_rows == c  # only capacity-many tokens got through


def test_moe_block_trains_and_shards():
    mesh = make_mesh({"dp": 2, "ep": 4})
    from mxnet_tpu.parallel import ShardedTrainer, ShardingRules

    class Net(gluon.block.HybridBlock):
        def __init__(self):
            super().__init__()
            self.moe = MoEBlock(16, 32, num_experts=4, activation="relu")
            self.head = gluon.nn.Dense(4, flatten=False)

        def forward(self, x):
            return self.head(self.moe(x).sum(axis=1))

    from mxnet_tpu.parallel import mesh as mesh_mod

    with mesh_mod.mesh_scope(mesh):
        net = Net()
        net.initialize()
        with autograd.predict_mode():
            net(np.array(onp.zeros((2, 6, 16), "float32")))
        rules = ShardingRules(moe_sharding_rules(), default_axis=None)
        tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "adam", {"learning_rate": 5e-3}, mesh=mesh,
                            rules=rules)
        X = onp.random.RandomState(2).randn(16, 6, 16).astype("float32")
        Y = onp.random.RandomState(3).randint(0, 4, (16,))
        losses = [float(tr.step(X, Y).asnumpy()) for _ in range(12)]
        assert losses[-1] < losses[0]
        w1 = tr.params["moe.w1"]
        assert w1.sharding.spec[0] == "ep"  # experts live on their devices
