"""Pipeline + expert parallelism tests (SURVEY §2.3 design-fresh list),
on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.parallel import (MoEBlock, make_mesh, moe_dispatch_combine,
                                moe_sharding_rules, pipeline_apply,
                                stack_stage_params)


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _stages(n, d, seed=0):
    rng = onp.random.RandomState(seed)
    return [(jnp.asarray(rng.randn(d, d).astype("float32") * 0.3),
             jnp.asarray(rng.randn(d).astype("float32") * 0.1))
            for _ in range(n)]


def test_pipeline_matches_sequential():
    mesh = make_mesh({"pp": 4})
    d = 16
    stages = _stages(4, d)
    stacked = stack_stage_params(stages, mesh, "pp")
    x = jnp.asarray(onp.random.RandomState(1).randn(8, d).astype("float32"))
    got = pipeline_apply(_stage_fn, stacked, x, mesh, "pp",
                         num_microbatches=4)
    want = x
    for p in stages:
        want = _stage_fn(p, want)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-5, atol=1e-5)


def test_pipeline_microbatch_counts():
    mesh = make_mesh({"pp": 2})
    d = 8
    stages = _stages(2, d, seed=3)
    stacked = stack_stage_params(stages, mesh, "pp")
    x = jnp.asarray(onp.random.randn(12, d).astype("float32"))
    for m in (2, 3, 6):
        got = pipeline_apply(_stage_fn, stacked, x, mesh, "pp",
                             num_microbatches=m)
        want = _stage_fn(stages[1], _stage_fn(stages[0], x))
        onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                    rtol=1e-5, atol=1e-5)


def test_pipeline_differentiable():
    mesh = make_mesh({"pp": 4})
    d = 8
    stages = _stages(4, d, seed=5)
    stacked = stack_stage_params(stages, mesh, "pp")
    x = jnp.asarray(onp.random.randn(4, d).astype("float32"))

    def loss(params, x):
        return pipeline_apply(_stage_fn, params, x, mesh, "pp").sum()

    g = jax.grad(loss)(stacked, x)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(onp.isfinite(onp.asarray(l)).all() for l in leaves)
    assert sum(float(jnp.abs(l).sum()) for l in leaves) > 0
    # numerical check against the sequential program's grad
    def seq_loss(params, x):
        out = x
        for i in range(4):
            out = _stage_fn(jax.tree_util.tree_map(lambda p: p[i], params),
                            out)
        return out.sum()

    g2 = jax.grad(seq_loss)(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g2)):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_bad_config():
    mesh = make_mesh({"dp": 8})
    with pytest.raises(mx.MXNetError, match="no 'pp' axis"):
        pipeline_apply(_stage_fn, [], jnp.zeros((4, 2)), mesh, "pp")


def test_moe_dispatch_matches_manual_top1():
    """With generous capacity, top-1 MoE == routing each token through its
    argmax expert."""
    rng = onp.random.RandomState(0)
    n, d, e, c = 32, 8, 4, 32
    x = jnp.asarray(rng.randn(n, d).astype("float32"))
    logits = jnp.asarray(rng.randn(n, e).astype("float32"))
    w = jnp.asarray(rng.randn(e, d, d).astype("float32"))

    def experts(inp):
        return jnp.einsum("ecd,edh->ech", inp, w)

    out, aux = moe_dispatch_combine(x, logits, experts, e, c)
    probs = onp.asarray(jax.nn.softmax(logits, -1))
    idx = probs.argmax(-1)
    want = onp.stack([
        probs[i, idx[i]] * (onp.asarray(x)[i] @ onp.asarray(w)[idx[i]])
        for i in range(n)])
    onp.testing.assert_allclose(onp.asarray(out), want, rtol=1e-4,
                                atol=1e-5)
    assert float(aux) > 0


def test_moe_capacity_drops_overflow():
    """Tokens beyond an expert's capacity fall out (output rows zero)."""
    n, d, e, c = 8, 4, 2, 2
    x = jnp.ones((n, d), "float32")
    logits = jnp.zeros((n, e), "float32").at[:, 0].set(10.0)  # all -> e0

    def experts(inp):
        return inp

    out, _ = moe_dispatch_combine(x, logits, experts, e, c)
    nonzero_rows = (onp.abs(onp.asarray(out)).sum(-1) > 1e-6).sum()
    assert nonzero_rows == c  # only capacity-many tokens got through


def test_moe_block_trains_and_shards():
    mesh = make_mesh({"dp": 2, "ep": 4})
    from mxnet_tpu.parallel import ShardedTrainer, ShardingRules

    class Net(gluon.block.HybridBlock):
        def __init__(self):
            super().__init__()
            self.moe = MoEBlock(16, 32, num_experts=4, activation="relu")
            self.head = gluon.nn.Dense(4, flatten=False)

        def forward(self, x):
            return self.head(self.moe(x).sum(axis=1))

    from mxnet_tpu.parallel import mesh as mesh_mod

    with mesh_mod.mesh_scope(mesh):
        net = Net()
        net.initialize()
        with autograd.predict_mode():
            net(np.array(onp.zeros((2, 6, 16), "float32")))
        rules = ShardingRules(moe_sharding_rules(), default_axis=None)
        tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "adam", {"learning_rate": 5e-3}, mesh=mesh,
                            rules=rules)
        X = onp.random.RandomState(2).randn(16, 6, 16).astype("float32")
        Y = onp.random.RandomState(3).randint(0, 4, (16,))
        losses = [float(tr.step(X, Y).asnumpy()) for _ in range(12)]
        assert losses[-1] < losses[0]
        w1 = tr.params["moe.w1"]
        assert w1.sharding.spec[0] == "ep"  # experts live on their devices


def test_pipelined_block_trainer_loss_parity():
    """A real transformer (not a toy stage_fn) trained through
    ShardedTrainer over a pp mesh matches single-device training losses
    step for step (r2 verdict Next #7 Done criterion)."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.parallel import (
        PipelinedBlock,
        ShardedTrainer,
        ShardingRules,
        make_mesh,
    )

    D, L, B, T = 16, 4, 8, 6

    class FFBlock(gluon.block.HybridBlock):
        """Shape-preserving transformer-ish layer: LN + MLP residual."""

        def __init__(self):
            super().__init__()
            self.ln = gluon.nn.LayerNorm()
            self.f1 = gluon.nn.Dense(D * 2, flatten=False)
            self.f2 = gluon.nn.Dense(D, flatten=False)

        def forward(self, x):
            from mxnet_tpu import npx

            return x + self.f2(npx.relu(self.f1(self.ln(x))))

    def build(seed):
        mx.random.seed(seed)
        prefix = gluon.nn.Dense(D, flatten=False)
        layers = [FFBlock() for _ in range(L)]
        suffix = gluon.nn.Dense(4, flatten=False)
        net = PipelinedBlock(layers, prefix=prefix, suffix=suffix,
                             num_microbatches=4)
        net.initialize()
        with autograd.predict_mode():
            net(mnp.array(onp.zeros((2, T, 8), "float32")))
        return net

    rng = onp.random.RandomState(3)
    x = rng.randn(B, T, 8).astype("float32")
    y = rng.randint(0, 4, (B, T)).astype("int32")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def losses_for(mesh_axes):
        net = build(42)
        mesh = make_mesh(mesh_axes)
        tr = ShardedTrainer(net, loss_fn, "sgd", {"learning_rate": 0.2},
                            mesh=mesh,
                            rules=ShardingRules(default_axis=None))
        out = []
        for _ in range(4):
            out.append(float(tr.step(x, y).asnumpy().reshape(-1)[0]))
        return out

    pp_losses = losses_for({"pp": 4})
    ref_losses = losses_for({"dp": 1})  # single-logical-device baseline
    onp.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4, atol=2e-5)
    assert pp_losses[-1] < pp_losses[0]  # it actually trains


def test_pipelined_block_sync_to_block_roundtrip():
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.parallel import PipelinedBlock, ShardedTrainer, \
        ShardingRules, make_mesh

    D = 8

    class Lay(gluon.block.HybridBlock):
        def __init__(self):
            super().__init__()
            self.f = gluon.nn.Dense(D, flatten=False)

        def forward(self, x):
            return x + self.f(x)

    mx.random.seed(9)
    net = PipelinedBlock([Lay() for _ in range(2)])
    net.initialize()
    x = onp.random.randn(4, D).astype("float32")
    with autograd.predict_mode():
        net(mnp.array(x))
    loss_fn = gluon.loss.L2Loss()
    tr = ShardedTrainer(net, loss_fn, "sgd", {"learning_rate": 0.1},
                        mesh=make_mesh({"pp": 2}),
                        rules=ShardingRules(default_axis=None))
    y = onp.zeros((4, D), "float32")
    tr.step(x, y)
    tr.sync_to_block()
    # every per-layer Parameter now holds its slice of the TRAINED stack
    for n, arr in tr.params.items():
        if not n.startswith("pp::"):
            continue
        host = onp.asarray(arr).reshape((-1,) + arr.shape[2:])
        for li, pname in enumerate(tr._pp_meta[n]):
            onp.testing.assert_allclose(
                net.collect_params()[pname].data().asnumpy(), host[li],
                rtol=1e-6)
    # and the weights really changed from init
    assert any(
        onp.abs(onp.asarray(v)).sum() > 0
        for k, v in tr.params.items() if k.startswith("pp::"))


def test_pipelined_block_frozen_layer_not_updated():
    """grad_req='null' body layers are carried as frozen leaves: they
    flow through forward/backward but the optimizer never moves them
    (and no misleading BatchNorm error is raised)."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.parallel import PipelinedBlock, ShardedTrainer, \
        ShardingRules, make_mesh

    D = 8

    class Lay(gluon.block.HybridBlock):
        def __init__(self):
            super().__init__()
            self.f = gluon.nn.Dense(D, flatten=False)

        def forward(self, x):
            return x + self.f(x)

    mx.random.seed(5)
    net = PipelinedBlock([Lay() for _ in range(2)])
    net.initialize()
    x = onp.random.randn(4, D).astype("float32")
    with autograd.predict_mode():
        net(mnp.array(x))
    # freeze the whole body (standard fine-tune workflow)
    net.collect_params().setattr("grad_req", "null")
    tr = ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                        {"learning_rate": 0.5},
                        mesh=make_mesh({"pp": 2}),
                        rules=ShardingRules(default_axis=None))
    before = {k: onp.asarray(v).copy() for k, v in tr.params.items()}
    tr.step(x, onp.zeros((4, D), "float32"))
    for k, v in tr.params.items():
        onp.testing.assert_array_equal(onp.asarray(v), before[k],
                                       err_msg=f"frozen {k} moved")


def test_pipelined_block_remat_matches_plain():
    """remat=True (jax.checkpoint per stage: the 1F1B memory benefit
    delivered compiler-natively) trains to the same losses."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.parallel import PipelinedBlock, ShardedTrainer, \
        ShardingRules, make_mesh

    D = 8

    class Lay(gluon.block.HybridBlock):
        def __init__(self):
            super().__init__()
            self.f = gluon.nn.Dense(D, flatten=False)

        def forward(self, x):
            from mxnet_tpu import np as xnp

            return x + xnp.tanh(self.f(x))

    def run(remat):
        mx.random.seed(21)
        net = PipelinedBlock([Lay() for _ in range(2)], remat=remat)
        net.initialize()
        x = onp.random.RandomState(2).randn(4, D).astype("float32")
        with autograd.predict_mode():
            net(mnp.array(x))
        tr = ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                            {"learning_rate": 0.2},
                            mesh=make_mesh({"pp": 2}),
                            rules=ShardingRules(default_axis=None))
        y = onp.zeros((4, D), "float32")
        return [float(tr.step(x, y).asnumpy().reshape(-1)[0])
                for _ in range(3)]

    onp.testing.assert_allclose(run(True), run(False), rtol=1e-5)
