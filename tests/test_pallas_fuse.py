"""Pallas pair-fusion inference transform vs the original model.

Runs the real fused program (incl. the conv1x1_pair TPU kernel) through
the Pallas interpreter on CPU and compares logits against the plain
gluon forward — end-to-end numerics for the whole rewrite: NHWC layout,
BN folding, strided-slice 1x1s, and the boundary kernels.
"""
import jax
import numpy as onp
import pytest

from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mnp
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import pallas_fuse


@pytest.fixture(autouse=True)
def _interpret():
    pallas_fuse.use_interpret(True)
    yield
    pallas_fuse.use_interpret(False)


def _burned_in_resnet(seed=0):
    net = gluon.model_zoo.vision.resnet50_v1()
    net.initialize(init="xavier")
    rng = onp.random.RandomState(seed)
    # a train-mode pass moves the BN running stats off their init values
    # so the folding is exercised on non-trivial (mean, var)
    with autograd.record():
        net(mnp.array(rng.uniform(-1, 1, (2, 3, 64, 64)).astype("f")))
    return net, rng


@pytest.mark.parametrize("use_pallas", [True, False])
def test_fused_matches_reference_forward(use_pallas):
    net, rng = _burned_in_resnet()
    x = rng.uniform(-1, 1, (2, 3, 64, 64)).astype("float32")
    with autograd.predict_mode():
        ref = net(mnp.array(x)).asnumpy()
    fused = pallas_fuse.fuse_resnet_v1(net, dtype="float32",
                                       block_rows=32,
                                       use_pallas=use_pallas)
    with jax.default_matmul_precision("highest"):
        got = fused(mnp.array(x)).asnumpy()
    err = onp.abs(got - ref).max() / (onp.abs(ref).max() + 1e-9)
    assert err < 1e-4, err


def test_fused_bf16_smoke():
    net, rng = _burned_in_resnet(1)
    x = rng.uniform(-1, 1, (1, 3, 64, 64)).astype("float32")
    with autograd.predict_mode():
        ref = net(mnp.array(x)).asnumpy()
    # bf16 + the kernel arm (the non-default flag stays covered)
    fused = pallas_fuse.fuse_resnet_v1(net, block_rows=32,
                                       use_pallas=True)
    got = fused(mnp.array(x)).asnumpy()
    assert got.dtype == onp.float32  # logits cast back
    # bf16 end to end: agreement is loose but the argmax should hold
    assert (onp.argmax(got, -1) == onp.argmax(ref, -1)).all()


def test_unfusable_models_raise():
    v2 = gluon.model_zoo.vision.resnet50_v2()
    v2.initialize()
    with pytest.raises(MXNetError):
        pallas_fuse.fuse_resnet_v1(v2)
    basic = gluon.model_zoo.vision.resnet18_v1()
    basic.initialize()
    with pytest.raises(MXNetError):
        pallas_fuse.fuse_resnet_v1(basic)
    thumb = gluon.model_zoo.vision.get_resnet(1, 50, thumbnail=True)
    thumb.initialize()
    with pytest.raises(MXNetError):
        pallas_fuse.fuse_resnet_v1(thumb)
