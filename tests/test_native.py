"""Native C++ runtime component tests (native/recordio.cc via ctypes)."""
import os

import numpy as np
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.lib import recordio_native

pytestmark = pytest.mark.skipif(
    not recordio_native.available(),
    reason="native toolchain unavailable")


@pytest.fixture
def recfile(tmp_path):
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    payloads = [os.urandom(np.random.randint(10, 3000)) for _ in range(50)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()
    return rec, idx, payloads


def test_native_index_matches_python(recfile):
    rec, idx, payloads = recfile
    offs, sizes = recordio_native.build_index(rec)
    assert len(offs) == len(payloads)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert [r.idx[i] for i in range(len(payloads))] == [int(o) for o in offs]
    assert [int(s) for s in sizes] == [len(p) for p in payloads]


def test_native_read_at_and_batch(recfile):
    rec, _, payloads = recfile
    offs, sizes = recordio_native.build_index(rec)
    assert recordio_native.read_at(rec, int(offs[7])) == payloads[7]
    batch = recordio_native.read_batch(rec, offs[10:20], sizes[10:20])
    assert batch == payloads[10:20]
    # undersized hint path (forces probe + retry)
    assert recordio_native.read_at(rec, int(offs[3]), size_hint=1) \
        == payloads[3]


def test_native_prefetch_stream(recfile):
    rec, _, payloads = recfile
    reader = recordio_native.NativePrefetchReader(rec, queue_depth=4)
    assert list(reader) == payloads
    reader.close()


def test_index_rebuild_without_idx(recfile):
    rec, idx, payloads = recfile
    os.remove(idx)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(42) == payloads[42]
    assert len(r.keys) == len(payloads)


def test_native_multipart_records(tmp_path, monkeypatch):
    rec = str(tmp_path / "mp.rec")
    w = recordio.MXRecordIO(rec, "w")
    big = os.urandom(100)
    monkeypatch.setattr(recordio, "_LREC_MASK", 0xF)
    w.write(big)
    w.write(b"x")
    monkeypatch.undo()
    w.close()
    offs, sizes = recordio_native.build_index(rec)
    assert [int(s) for s in sizes] == [100, 1]
    assert recordio_native.read_at(rec, int(offs[0])) == big


def test_native_rejects_corrupt_file(tmp_path):
    bad = str(tmp_path / "bad.rec")
    with open(bad, "wb") as f:
        f.write(b"not a recordio file at all....")
    with pytest.raises(mx.MXNetError):
        recordio_native.build_index(bad)


def test_native_csv_parse_matches_numpy(tmp_path):
    from mxnet_tpu.lib import textparse_native

    if not textparse_native.available():
        pytest.skip("no native toolchain")
    rng = onp.random.RandomState(0)
    arr = rng.randn(500, 7).astype("float32")
    p = tmp_path / "d.csv"
    onp.savetxt(p, arr, delimiter=",", fmt="%.6g")
    got = textparse_native.load_csv(str(p))
    want = onp.loadtxt(p, delimiter=",", dtype=onp.float32, ndmin=2)
    onp.testing.assert_allclose(got, want, rtol=1e-5)


def test_native_csv_rejects_ragged(tmp_path):
    from mxnet_tpu.lib import textparse_native

    if not textparse_native.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "bad.csv"
    p.write_text("1,2,3\n4,5\n")
    with pytest.raises(mx.MXNetError, match="malformed"):
        textparse_native.load_csv(str(p))


def test_native_libsvm_parse(tmp_path):
    from mxnet_tpu.lib import textparse_native

    if not textparse_native.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "d.svm"
    p.write_text("1 0:1.5 3:-2.0\n0 2:7\n2 1:0.25 4:4\n")
    data, label = textparse_native.load_libsvm(str(p), 5)
    onp.testing.assert_allclose(label, [1, 0, 2])
    want = onp.zeros((3, 5), "float32")
    want[0, 0], want[0, 3] = 1.5, -2.0
    want[1, 2] = 7
    want[2, 1], want[2, 4] = 0.25, 4
    onp.testing.assert_allclose(data, want)


def test_csviter_native_and_libsvmiter(tmp_path):
    import mxnet_tpu.io as mio

    rng = onp.random.RandomState(1)
    arr = rng.randn(20, 4).astype("float32")
    p = tmp_path / "d.csv"
    onp.savetxt(p, arr, delimiter=",", fmt="%.6g")
    it = mio.CSVIter(str(p), data_shape=(4,), batch_size=5)
    batches = list(it)
    assert len(batches) == 4
    onp.testing.assert_allclose(batches[0].data[0].asnumpy(), arr[:5],
                                rtol=1e-5)

    svm = tmp_path / "d.svm"
    svm.write_text("".join(
        f"{i % 3} 0:{i}.5 2:{i}\n" for i in range(8)))
    it = mio.LibSVMIter(str(svm), data_shape=(4,), batch_size=4)
    b = next(iter(it))
    onp.testing.assert_allclose(b.label[0].asnumpy(), [0, 1, 2, 0])
    onp.testing.assert_allclose(b.data[0].asnumpy()[1],
                                [1.5, 0, 1, 0])


def test_native_csv_comments_blank_and_pagesize(tmp_path):
    from mxnet_tpu.lib import textparse_native

    if not textparse_native.available():
        pytest.skip("no native toolchain")
    # comments + blank lines behave like numpy.loadtxt
    p = tmp_path / "c.csv"
    p.write_text("# header comment\n\n1,2,3\n# mid comment\n4,5,6\n")
    got = textparse_native.load_csv(str(p))
    onp.testing.assert_allclose(got, [[1, 2, 3], [4, 5, 6]])
    # exactly page-sized file without trailing newline must not crash:
    # build EXACTLY page bytes ending in a digit
    page = os.sysconf("SC_PAGE_SIZE")
    row = "1.5,2.5\n"
    content = row * (page // len(row))
    content = content[:page - 4].rstrip("\n,") + "\n"
    content = content + "1" * (page - len(content))
    assert len(content) == page and content[-1].isdigit()
    p2 = tmp_path / "exact.csv"
    p2.write_bytes(content.encode())
    try:
        textparse_native.load_csv(str(p2))  # ragged -> error is fine
    except mx.MXNetError:
        pass  # must raise cleanly, not SIGBUS


def test_native_libsvm_crlf(tmp_path):
    from mxnet_tpu.lib import textparse_native

    if not textparse_native.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "w.svm"
    p.write_bytes(b"1 0:1.5 2:3\r\n0 1:2\r\n")
    data, label = textparse_native.load_libsvm(str(p), 3)
    onp.testing.assert_allclose(label, [1, 0])
    onp.testing.assert_allclose(data, [[1.5, 0, 3], [0, 2, 0]])


def test_libsvmiter_label_file_without_native(tmp_path, monkeypatch):
    """label_libsvm works through the shared fallback parser."""
    import mxnet_tpu.io as mio
    from mxnet_tpu.lib import textparse_native

    svm = tmp_path / "d.svm"
    svm.write_text("0 0:1\n0 1:2\n")
    lab = tmp_path / "l.svm"
    lab.write_text("0 0:5\n0 0:7\n")
    monkeypatch.setattr(textparse_native, "available", lambda: False)
    it = mio.LibSVMIter(str(svm), data_shape=(3,), label_libsvm=str(lab),
                        batch_size=2)
    b = next(iter(it))
    onp.testing.assert_allclose(b.label[0].asnumpy(), [5, 7])


def test_native_csv_separator_only_line_errors(tmp_path):
    """A ',,' line must raise cleanly, never return uninitialized rows."""
    from mxnet_tpu.lib import textparse_native

    if not textparse_native.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "sep.csv"
    p.write_text("1,2\n,,\n3,4\n")
    got = textparse_native.load_csv(str(p))
    # separator-only line carries no values -> skipped like a blank line
    onp.testing.assert_allclose(got, [[1, 2], [3, 4]])
