"""Native C++ runtime component tests (native/recordio.cc via ctypes)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.lib import recordio_native

pytestmark = pytest.mark.skipif(
    not recordio_native.available(),
    reason="native toolchain unavailable")


@pytest.fixture
def recfile(tmp_path):
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    payloads = [os.urandom(np.random.randint(10, 3000)) for _ in range(50)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()
    return rec, idx, payloads


def test_native_index_matches_python(recfile):
    rec, idx, payloads = recfile
    offs, sizes = recordio_native.build_index(rec)
    assert len(offs) == len(payloads)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert [r.idx[i] for i in range(len(payloads))] == [int(o) for o in offs]
    assert [int(s) for s in sizes] == [len(p) for p in payloads]


def test_native_read_at_and_batch(recfile):
    rec, _, payloads = recfile
    offs, sizes = recordio_native.build_index(rec)
    assert recordio_native.read_at(rec, int(offs[7])) == payloads[7]
    batch = recordio_native.read_batch(rec, offs[10:20], sizes[10:20])
    assert batch == payloads[10:20]
    # undersized hint path (forces probe + retry)
    assert recordio_native.read_at(rec, int(offs[3]), size_hint=1) \
        == payloads[3]


def test_native_prefetch_stream(recfile):
    rec, _, payloads = recfile
    reader = recordio_native.NativePrefetchReader(rec, queue_depth=4)
    assert list(reader) == payloads
    reader.close()


def test_index_rebuild_without_idx(recfile):
    rec, idx, payloads = recfile
    os.remove(idx)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(42) == payloads[42]
    assert len(r.keys) == len(payloads)


def test_native_multipart_records(tmp_path, monkeypatch):
    rec = str(tmp_path / "mp.rec")
    w = recordio.MXRecordIO(rec, "w")
    big = os.urandom(100)
    monkeypatch.setattr(recordio, "_LREC_MASK", 0xF)
    w.write(big)
    w.write(b"x")
    monkeypatch.undo()
    w.close()
    offs, sizes = recordio_native.build_index(rec)
    assert [int(s) for s in sizes] == [100, 1]
    assert recordio_native.read_at(rec, int(offs[0])) == big


def test_native_rejects_corrupt_file(tmp_path):
    bad = str(tmp_path / "bad.rec")
    with open(bad, "wb") as f:
        f.write(b"not a recordio file at all....")
    with pytest.raises(mx.MXNetError):
        recordio_native.build_index(bad)
