"""Round-5 verdict Next #5: probability constraint machinery +
exponential family entropy/KL + the 4 missing metrics + estimator
batch_processor.

Reference semantics:
``python/mxnet/gluon/probability/distributions/constraint.py`` (548 LoC),
``exp_family.py`` (68), ``gluon/metric.py:815,876,1197,1263``,
``gluon/contrib/estimator/batch_processor.py`` (105).
"""
import math

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import probability as P
from mxnet_tpu.gluon.probability import constraint as C


# -- constraint classes ----------------------------------------------------

def test_constraint_primitives():
    jnp_ok = C.Real().check(onp.array([1.0, 2.0]))
    assert jnp_ok is not None
    with pytest.raises(ValueError):
        C.Real().check(onp.array([1.0, onp.nan]))
    with pytest.raises(ValueError):
        C.Boolean().check(onp.array([0.0, 2.0]))
    C.Boolean().check(onp.array([0.0, 1.0]))
    C.Interval(0, 1).check(0.5)
    with pytest.raises(ValueError):
        C.OpenInterval(0, 1).check(0.0)
    C.HalfOpenInterval(0, 1).check(0.0)
    with pytest.raises(ValueError):
        C.HalfOpenInterval(0, 1).check(1.0)
    C.UnitInterval().check(1.0)
    with pytest.raises(ValueError):
        C.IntegerInterval(0, 5).check(2.5)
    C.IntegerInterval(0, 5).check(3.0)
    with pytest.raises(ValueError):
        C.GreaterThan(0).check(0.0)
    C.GreaterThanEq(0).check(0.0)
    with pytest.raises(ValueError):
        C.LessThan(1).check(1.0)
    C.LessThanEq(1).check(1.0)
    C.Positive().check(0.1)
    with pytest.raises(ValueError):
        C.Positive().check(-0.1)
    C.NonNegative().check(0.0)
    C.PositiveInteger().check(2.0)
    with pytest.raises(ValueError):
        C.PositiveInteger().check(0.0)
    C.NonNegativeInteger().check(0.0)
    with pytest.raises(ValueError):
        C.IntegerGreaterThanEq(2).check(1.0)
    with pytest.raises(ValueError):
        C.IntegerLessThan(3).check(3.0)
    C.IntegerLessThanEq(3).check(3.0)
    with pytest.raises(ValueError):
        C.IntegerOpenInterval(0, 2).check(2.0)
    C.IntegerHalfOpenInterval(0, 2).check(0.0)


def test_constraint_matrix_and_simplex():
    C.Simplex().check(onp.array([0.2, 0.8]))
    with pytest.raises(ValueError):
        C.Simplex().check(onp.array([0.5, 0.6]))
    tri = onp.array([[1.0, 0.0], [2.0, 3.0]])
    C.LowerTriangular().check(tri)
    with pytest.raises(ValueError):
        C.LowerTriangular().check(onp.array([[1.0, 1.0], [0.0, 1.0]]))
    C.LowerCholesky().check(tri)
    with pytest.raises(ValueError):  # negative diagonal
        C.LowerCholesky().check(onp.array([[1.0, 0.0], [1.0, -2.0]]))
    C.PositiveDefinite().check(onp.array([[2.0, 0.5], [0.5, 1.0]]))
    with pytest.raises(ValueError):
        C.PositiveDefinite().check(onp.array([[1.0, 2.0], [2.0, 1.0]]))


def test_constraint_cat_stack_dependent():
    cat = C.Cat([C.Positive(), C.LessThan(0)], axis=0, lengths=[2, 1])
    cat.check(onp.array([1.0, 2.0, -3.0]))
    with pytest.raises(ValueError):
        cat.check(onp.array([1.0, -2.0, -3.0]))
    st = C.Stack([C.Positive(), C.NonNegative()], axis=0)
    st.check(onp.array([[1.0], [0.0]]))
    with pytest.raises(ValueError):
        st.check(onp.array([[-1.0], [0.0]]))
    assert C.is_dependent(C._Dependent())
    with pytest.raises(ValueError):
        C._Dependent().check(1.0)


# -- ctor validation on distributions --------------------------------------

@pytest.mark.parametrize("bad_ctor", [
    lambda: P.Normal(0.0, -1.0, validate_args=True),
    lambda: P.Normal(onp.nan, 1.0, validate_args=True),
    lambda: P.Gamma(shape=-2.0, scale=1.0, validate_args=True),
    lambda: P.Bernoulli(prob=1.5, validate_args=True),
    lambda: P.Exponential(-1.0, validate_args=True),
    lambda: P.Beta(0.0, 1.0, validate_args=True),
    lambda: P.Poisson(-1.0, validate_args=True),
    lambda: P.Dirichlet(onp.array([-1.0, 2.0]), validate_args=True),
    lambda: P.Geometric(1.5, validate_args=True),
    lambda: P.Weibull(-1.0, 1.0, validate_args=True),
    lambda: P.HalfNormal(-1.0, validate_args=True),
    lambda: P.StudentT(-1.0, validate_args=True),
    lambda: P.Categorical(prob=onp.array([0.5, 0.9]), validate_args=True),
])
def test_invalid_params_raise(bad_ctor):
    with pytest.raises(ValueError):
        bad_ctor()


def test_valid_params_pass_and_default_off():
    # validation off by default: invalid params do NOT raise (reference
    # default _validate_args = False)
    P.Normal(0.0, -1.0)
    # valid params + validation on: fine
    P.Normal(0.0, 2.0, validate_args=True)
    P.Gamma(shape=2.0, scale=1.0, validate_args=True)
    P.Bernoulli(logit=-3.0, validate_args=True)
    P.Uniform(0.0, 1.0, validate_args=True)
    # process-wide default toggle
    P.Distribution.set_default_validate_args(True)
    try:
        with pytest.raises(ValueError):
            P.Exponential(-2.0)
    finally:
        P.Distribution.set_default_validate_args(False)
    P.Exponential(-2.0)  # off again


def test_support_validation_in_log_prob():
    with pytest.raises(ValueError):
        P.Exponential(1.0, validate_args=True).log_prob(-3.0)
    with pytest.raises(ValueError):
        P.Beta(2.0, 2.0, validate_args=True).log_prob(1.5)
    # dependent support resolves on the instance (Uniform)
    with pytest.raises(ValueError):
        P.Uniform(0.0, 1.0, validate_args=True).log_prob(2.0)
    P.Uniform(0.0, 1.0, validate_args=True).log_prob(0.5)
    # without validation, no raise
    P.Exponential(1.0).log_prob(-3.0)


def test_wrapper_class_params_are_validated():
    """review finding: params stored behind properties/_base wrappers
    were silently skipped — dead validation."""
    with pytest.raises(ValueError):
        P.OneHotCategorical(prob=onp.array([0.5, 0.9]),
                            validate_args=True)
    with pytest.raises(ValueError):
        P.RelaxedBernoulli(T=0.5, prob=1.7, validate_args=True)
    with pytest.raises(ValueError):  # negative diagonal tril
        P.MultivariateNormal(
            onp.zeros(2, "float32"),
            scale_tril=onp.array([[1.0, 0.0], [1.0, -2.0]], "float32"),
            validate_args=True)
    # valid wrapper params pass
    P.OneHotCategorical(prob=onp.array([0.4, 0.6], "float32"),
                        validate_args=True)
    P.MultivariateNormal(
        onp.zeros(2, "float32"),
        scale_tril=onp.array([[1.0, 0.0], [0.5, 2.0]], "float32"),
        validate_args=True)


def test_unmapped_constraint_raises_loudly():
    """review finding: a declared constraint that maps to no storage
    must be a programming error, not a silent skip."""
    class Broken(P.Distribution):
        arg_constraints = {"nonexistent": C.Positive()}

        def __init__(self, **kwargs):
            super().__init__(**kwargs)

    with pytest.raises(TypeError):
        Broken(validate_args=True)
    Broken()  # validation off: no probe, no raise


def test_cauchy_studentt_scale_real_matches_reference():
    """The reference constrains Cauchy/StudentT scale with Real(), not
    Positive() (cauchy.py:48, studentT.py:48) — parity means a negative
    scale passes validation there too; pinned so a future 'fix' is a
    conscious divergence."""
    P.Cauchy(0.0, -1.0, validate_args=True)
    P.StudentT(3.0, 0.0, -1.0, validate_args=True)


# -- exponential family ----------------------------------------------------

def test_bregman_entropy_matches_closed_forms():
    from scipy import stats

    cases = [
        (P.Normal(1.0, 2.0), 0.5 * math.log(2 * math.pi * math.e * 4.0)),
        (P.Exponential(2.0), 1 + math.log(2.0)),
        (P.Beta(2.0, 3.0), stats.beta(2, 3).entropy()),
        (P.Gamma(shape=3.0, scale=2.0), stats.gamma(3, scale=2).entropy()),
        (P.Dirichlet(onp.array([1.0, 2.0, 3.0], "float32")),
         stats.dirichlet([1.0, 2.0, 3.0]).entropy()),
        (P.Bernoulli(prob=0.3), stats.bernoulli(0.3).entropy()),
    ]
    for dist, want in cases:
        got = float(P.ExponentialFamily.entropy(dist).asnumpy())
        assert abs(got - float(want)) < 1e-3, (type(dist).__name__, got, want)


def test_bregman_kl_matches_registered_closed_forms():
    pairs = [
        (P.Normal(0.0, 1.0), P.Normal(1.0, 2.0)),
        (P.Gamma(shape=2.0, scale=1.5), P.Gamma(shape=3.0, scale=0.5)),
        (P.Beta(2.0, 3.0), P.Beta(4.0, 1.5)),
        (P.Exponential(1.0), P.Exponential(3.0)),
        (P.Bernoulli(prob=0.3), P.Bernoulli(prob=0.7)),
        (P.Dirichlet(onp.array([1.0, 2.0], "float32")),
         P.Dirichlet(onp.array([3.0, 1.0], "float32"))),
    ]
    for p, q in pairs:
        closed = float(P.kl_divergence(p, q).asnumpy())
        bregman = float(p._kl_same_family(q).asnumpy())
        assert abs(closed - bregman) < 1e-3, (type(p).__name__,
                                              closed, bregman)


def test_exp_family_module_reexport():
    from mxnet_tpu.gluon.probability.exp_family import ExponentialFamily
    assert ExponentialFamily is P.ExponentialFamily
    assert issubclass(P.Normal, ExponentialFamily)
    assert issubclass(P.Poisson, ExponentialFamily)


# -- the 4 missing metrics (reference docstring oracles) -------------------

def test_fbeta_reference_oracle():
    from mxnet_tpu.gluon import metric

    fbeta = metric.Fbeta(beta=2)
    fbeta.update([mx.nd.array([0., 1., 1.])],
                 [mx.nd.array([[0.3, 0.7], [0., 1.], [0.4, 0.6]])])
    assert abs(fbeta.get()[1] - 0.9090909090909091) < 1e-9


def test_binary_accuracy_reference_oracle():
    from mxnet_tpu.gluon import metric

    bacc = metric.BinaryAccuracy(threshold=0.6)
    bacc.update([mx.nd.array([0., 1., 0.])], [mx.nd.array([0.7, 1, 0.55])])
    assert abs(bacc.get()[1] - 2 / 3) < 1e-9


def test_mean_pairwise_distance_reference_oracle():
    from mxnet_tpu.gluon import metric

    mpd = metric.MeanPairwiseDistance()
    mpd.update([mx.nd.array([[1., 0.], [4., 2.]])],
               [mx.nd.array([[1., 2.], [3., 4.]])])
    assert abs(mpd.get()[1] - (2.0 + math.sqrt(5.0)) / 2) < 1e-6


def test_mean_cosine_similarity_reference_oracle():
    from mxnet_tpu.gluon import metric

    mcs = metric.MeanCosineSimilarity()
    mcs.update([mx.nd.array([[3., 4.], [2., 2.]])],
               [mx.nd.array([[1., 0.], [1., 1.]])])
    assert abs(mcs.get()[1] - 0.8) < 1e-6


def test_new_metrics_registered_for_create():
    from mxnet_tpu.gluon import metric

    for name in ("fbeta", "binaryaccuracy", "meanpairwisedistance",
                 "meancosinesimilarity"):
        m = metric.create(name)
        assert isinstance(m, metric.EvalMetric)


# -- estimator batch processor ---------------------------------------------

def test_estimator_custom_batch_processor():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.estimator import BatchProcessor, Estimator

    calls = {"fit": 0, "eval": 0}

    class DoubledLossProcessor(BatchProcessor):
        def fit_batch(self, estimator, train_batch, batch_axis=0):
            calls["fit"] += 1
            return super().fit_batch(estimator, train_batch, batch_axis)

        def evaluate_batch(self, estimator, val_batch, batch_axis=0):
            calls["eval"] += 1
            return super().evaluate_batch(estimator, val_batch, batch_axis)

    net = gluon.nn.Dense(1)
    net.initialize()
    est = Estimator(net, gluon.loss.L2Loss(),
                    train_metrics=[gluon.metric.MSE()],
                    batch_processor=DoubledLossProcessor())
    x = mx.np.ones((8, 3))
    y = mx.np.ones((8, 1))
    est.fit([(x, y)] * 3, val_data=[(x, y)], epochs=1)
    assert calls["fit"] == 3
    assert calls["eval"] >= 1
    with pytest.raises(Exception):
        Estimator(net, gluon.loss.L2Loss(), batch_processor=object())


def test_unused_dual_side_is_not_materialized():
    """r5 review finding: the property fallback must not materialize
    DERIVED parameters (softmax of logits, Cholesky of cov) just to
    re-validate them — the unused side of a dual parameterization is
    skipped via its _base/self storage, mirroring direct classes.
    float32 softmax over many classes can miss Simplex's 1e-6 sum
    tolerance on perfectly valid logits."""
    rng = onp.random.RandomState(0)
    # 4096-class logits: softmax sum error is O(1e-6) in float32 — a
    # materialize-and-check would flake; the skip must make it exact
    logits = (rng.randn(4096) * 4).astype("float32")
    P.OneHotCategorical(logit=onp.asarray(logits), validate_args=True)
    P.Categorical(logit=onp.asarray(logits), validate_args=True)
    # MVN given cov: validates cov (PositiveDefinite), must NOT take a
    # Cholesky for a tautological LowerCholesky check
    cov = onp.array([[2.0, 0.3], [0.3, 1.0]], "float32")
    mvn = P.MultivariateNormal(onp.zeros(2, "float32"), cov=cov,
                               validate_args=True)
    assert mvn._scale_tril is None  # construction left the dual unset
    with pytest.raises(ValueError):  # non-PD cov still rejected
        P.MultivariateNormal(
            onp.zeros(2, "float32"),
            cov=onp.array([[1.0, 2.0], [2.0, 1.0]], "float32"),
            validate_args=True)
