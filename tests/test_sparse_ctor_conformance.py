"""Sparse constructor conformance: the reference's OWN docstring examples,
executed verbatim against the public ``mx.nd.sparse`` surface.

Round-4 verdict Weak #2 / Next #3: the round-4 suite pinned op *names*
(registry audit) but never ran a reference docstring example against the
public sparse constructors, so ``csr_matrix`` shipped with its triple in
the wrong order.  These tests pin *signatures and semantics*: every
snippet below is copied from a docstring in
``/root/reference/python/mxnet/ndarray/sparse.py`` (line cited per test)
and must produce the documented output.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray.sparse import CSRNDArray, RowSparseNDArray


def test_csr_matrix_docstring_example():
    """reference sparse.py:932-937."""
    a = mx.nd.sparse.csr_matrix(([1, 2, 3], [1, 0, 2], [0, 1, 2, 2, 3]),
                                shape=(4, 3))
    onp.testing.assert_array_equal(
        a.asnumpy(),
        onp.array([[0., 1., 0.],
                   [2., 0., 0.],
                   [0., 0., 0.],
                   [0., 0., 3.]], dtype=onp.float32))
    assert a.asnumpy().dtype == onp.float32  # list input defaults float32


def test_row_sparse_array_docstring_example():
    """reference sparse.py:1106-1113."""
    a = mx.nd.sparse.row_sparse_array(([[1, 2], [3, 4]], [1, 4]),
                                      shape=(6, 2))
    onp.testing.assert_array_equal(
        a.asnumpy(),
        onp.array([[0., 0.],
                   [1., 2.],
                   [0., 0.],
                   [0., 0.],
                   [3., 4.],
                   [0., 0.]], dtype=onp.float32))


def test_csrndarray_class_docstring_example():
    """reference sparse.py:363-375 — definition triple + row slicing."""
    indptr = onp.array([0, 2, 3, 6])
    indices = onp.array([0, 2, 2, 0, 1, 2])
    data = onp.array([1, 2, 3, 4, 5, 6])
    a = mx.nd.sparse.csr_matrix((data, indices, indptr), shape=(3, 3))
    onp.testing.assert_array_equal(
        a.asnumpy(), [[1, 0, 2], [0, 0, 3], [4, 5, 6]])
    onp.testing.assert_array_equal(a[1:2].asnumpy(), [[0, 0, 3]])
    onp.testing.assert_array_equal(a[1].asnumpy(), [[0, 0, 3]])
    onp.testing.assert_array_equal(a[-1].asnumpy(), [[4, 5, 6]])


def test_tostype_exposes_csr_triple():
    """reference sparse.py:314-320 — data/indices/indptr properties."""
    a = mx.nd.array([[0, 1, 0], [2, 0, 0], [0, 0, 0], [0, 0, 3]])
    a = a.tostype('csr')
    onp.testing.assert_array_equal(a.data.asnumpy(), [1., 2., 3.])
    onp.testing.assert_array_equal(a.indices.asnumpy(), [1, 0, 2])
    onp.testing.assert_array_equal(a.indptr.asnumpy(), [0, 1, 2, 2, 3])


def test_row_sparse_tostype_properties():
    """reference sparse.py:590-599 — indices/data of a dense→row_sparse."""
    dense = mx.nd.array([[0, 1, 0], [0, 0, 0], [2, 3, 0]])
    rsp = dense.tostype('row_sparse')
    onp.testing.assert_array_equal(rsp.indices.asnumpy(), [0, 2])
    onp.testing.assert_array_equal(rsp.data.asnumpy(),
                                   [[0., 1., 0.], [2., 3., 0.]])


def test_sparse_zeros_and_astype():
    """reference sparse.py:225-227 — astype keeps the storage type."""
    x = mx.nd.sparse.zeros('row_sparse', (2, 3), dtype='float32')
    y = x.astype('int32')
    assert y.dtype == onp.int32
    assert isinstance(y, RowSparseNDArray)
    onp.testing.assert_array_equal(y.asnumpy(), onp.zeros((2, 3)))


def test_csr_asscipy():
    """reference sparse.py:558-562."""
    import scipy.sparse as spsp

    x = mx.nd.sparse.zeros('csr', (2, 3))
    y = x.asscipy()
    assert isinstance(y, spsp.csr_matrix)
    onp.testing.assert_array_equal(y.toarray(), onp.zeros((2, 3)))


def test_csr_add_stays_csr():
    """reference sparse.py:1239-1248 — csr + csr keeps csr storage."""
    a = mx.nd.ones((2, 3)).tostype('csr')
    b = mx.nd.ones((2, 3)).tostype('csr')
    out = a + b
    assert isinstance(out, CSRNDArray)
    onp.testing.assert_array_equal(out.asnumpy(), onp.full((2, 3), 2.))


def test_row_sparse_add_stays_sparse():
    """reference sparse.py:1250-1259."""
    c = mx.nd.ones((2, 3)).tostype('row_sparse')
    d = mx.nd.ones((2, 3)).tostype('row_sparse')
    out = c + d
    assert isinstance(out, RowSparseNDArray)
    onp.testing.assert_array_equal(out.asnumpy(), onp.full((2, 3), 2.))


def test_csr_matrix_from_dense_and_shape_check():
    """reference form csr_matrix(D) (sparse.py:844-852) + _check_shape."""
    d = onp.array([[1., 0.], [0., 2.]], dtype=onp.float32)
    a = mx.nd.sparse.csr_matrix(d)
    assert isinstance(a, CSRNDArray)
    onp.testing.assert_array_equal(a.asnumpy(), d)
    with pytest.raises(ValueError):
        mx.nd.sparse.csr_matrix(d, shape=(3, 3))


def test_csr_matrix_from_scipy():
    """reference form csr_matrix(S) with a scipy matrix (sparse.py:854-860)."""
    import scipy.sparse as spsp

    host = onp.array([[0, 1.5, 0], [0, 0, 2.5]], dtype=onp.float32)
    s = spsp.csr_matrix(host)
    a = mx.nd.sparse.csr_matrix(s)
    assert a.dtype == onp.float32  # scipy input keeps its dtype
    onp.testing.assert_array_equal(a.asnumpy(), host)
    i = spsp.csr_matrix(host.astype(onp.int32))
    assert mx.nd.sparse.csr_matrix(i).dtype == onp.int32


def test_csr_matrix_empty_mn():
    """reference form csr_matrix((M, N)) (sparse.py:862-869)."""
    a = mx.nd.sparse.csr_matrix((2, 3))
    assert isinstance(a, CSRNDArray)
    assert a.shape == (2, 3)
    onp.testing.assert_array_equal(a.asnumpy(), onp.zeros((2, 3)))


def test_csr_matrix_coo_form():
    """reference form csr_matrix((data, (row, col))) (sparse.py:893-911)."""
    a = mx.nd.sparse.csr_matrix(
        ([7., 8.], ([0, 2], [1, 0])), shape=(3, 2))
    onp.testing.assert_array_equal(
        a.asnumpy(), [[0., 7.], [0., 0.], [8., 0.]])


def test_csr_matrix_shape_inference():
    """shape=None infers (len(indptr)-1, max(indices)+1)
    (reference _csr_matrix_from_definition, sparse.py:1020-1023)."""
    a = mx.nd.sparse.csr_matrix(
        (onp.array([1., 2.]), onp.array([0, 4]), onp.array([0, 1, 2])))
    assert a.shape == (2, 5)


def test_csr_matrix_rejects_row_sparse_and_bad_tuple():
    rs = mx.nd.ones((2, 3)).tostype('row_sparse')
    with pytest.raises(ValueError):
        mx.nd.sparse.csr_matrix(rs)
    with pytest.raises(ValueError):
        mx.nd.sparse.csr_matrix((1, 2, 3, 4))
    with pytest.raises(ValueError):  # 2-D data in the definition triple
        mx.nd.sparse.csr_matrix(
            (onp.ones((2, 2)), onp.array([0, 1]), onp.array([0, 1, 2])),
            shape=(2, 2))


def test_row_sparse_array_forms():
    """reference forms D / S / (D0..Dn) (sparse.py:1043-1067)."""
    d = onp.array([[1., 0.], [0., 0.], [0., 2.]], dtype=onp.float32)
    a = mx.nd.sparse.row_sparse_array(d)
    assert isinstance(a, RowSparseNDArray)
    onp.testing.assert_array_equal(a.asnumpy(), d)
    b = mx.nd.sparse.row_sparse_array(a)     # from RowSparseNDArray
    onp.testing.assert_array_equal(b.asnumpy(), d)
    e = mx.nd.sparse.row_sparse_array((4, 2))  # empty with shape
    assert e.shape == (4, 2)
    onp.testing.assert_array_equal(e.asnumpy(), onp.zeros((4, 2)))
    e3 = mx.nd.sparse.row_sparse_array((2, 3, 4))  # n-dim empty
    assert e3.shape == (2, 3, 4)
    with pytest.raises(ValueError):
        mx.nd.sparse.row_sparse_array(mx.nd.ones((2, 2)).tostype('csr'))


def test_row_sparse_array_shape_inference():
    a = mx.nd.sparse.row_sparse_array(
        (onp.ones((2, 3), onp.float32), onp.array([1, 5])))
    assert a.shape == (6, 3)


def test_csr_matrix_does_not_mutate_scipy_input():
    """review finding: tocsr() on a csr input returns self, so sorting
    in place would rewrite the caller's buffers."""
    import scipy.sparse as spsp

    m = spsp.csr_matrix((onp.array([1., 2.], onp.float32),
                         onp.array([2, 0]), onp.array([0, 2, 2])),
                        shape=(2, 3))
    before = m.indices.copy()
    mx.nd.sparse.csr_matrix(m)
    onp.testing.assert_array_equal(m.indices, before)


def test_csr_empty_slice_keeps_valid_indptr():
    a = mx.nd.sparse.csr_matrix(([1., 2.], [0, 1], [0, 1, 2]), shape=(2, 3))
    e = a[2:1]
    assert e.shape == (0, 3)
    onp.testing.assert_array_equal(e.indptr.asnumpy(), [0])
    e.asscipy()  # must be a well-formed (if empty) csr


def test_row_sparse_numpy_integer_shape():
    e = mx.nd.sparse.row_sparse_array((onp.int64(4), onp.int64(2)))
    assert e.shape == (4, 2)
    onp.testing.assert_array_equal(e.asnumpy(), onp.zeros((4, 2)))


def test_csr_add_recorded_stays_on_tape():
    """review finding: a recorded csr+csr must not take the untracked
    host path — gradients flow like the pre-existing dense fallback."""
    from mxnet_tpu import autograd

    a = mx.nd.ones((2, 3)).tostype('csr')
    b = mx.nd.ones((2, 3)).tostype('csr')
    a.attach_grad()
    with autograd.record():
        loss = (a + b).sum()
    loss.backward()
    onp.testing.assert_array_equal(a.grad.asnumpy(), onp.ones((2, 3)))


def test_definition_forms_honor_dtype_for_ndarray_data():
    """review finding: dtype was silently ignored when data was already
    an NDArray."""
    d = mx.nd.array([1., 2.])
    a = mx.nd.sparse.csr_matrix(
        (d, onp.array([0, 1]), onp.array([0, 1, 2])),
        shape=(2, 3), dtype='int32')
    assert a.dtype == onp.int32
    r = mx.nd.sparse.row_sparse_array(
        (mx.nd.ones((1, 2)), onp.array([0])), shape=(2, 2), dtype='int32')
    assert r.dtype == onp.int32


def test_copy_construct_does_not_alias_source():
    """review finding: csr_matrix(CSRNDArray) shared buffer handles, so
    in-place writes on the copy leaked into the source."""
    a = mx.nd.sparse.csr_matrix(([1., 2.], [0, 1], [0, 1, 2]), shape=(2, 3))
    b = mx.nd.sparse.csr_matrix(a)
    b.data[:] = 99.
    onp.testing.assert_array_equal(a.data.asnumpy(), [1., 2.])


def test_setitem_broadcast_assign_to_sparse():
    """reference sparse.py:413-427 / :684-692 — full-slice assignment."""
    src = mx.nd.sparse.csr_matrix(([1., 2.], [1, 0], [0, 1, 2, 2]),
                                  shape=(3, 3))
    x = mx.nd.ones((3, 3)).tostype('csr')
    x[:] = src
    onp.testing.assert_array_equal(x.asnumpy(), src.asnumpy())
    y = mx.nd.sparse.zeros('row_sparse', (3, 3))
    y[:] = mx.nd.ones((3, 3))
    onp.testing.assert_array_equal(y.asnumpy(), onp.ones((3, 3)))
