"""Pallas fused 1x1-conv-pair kernel vs a numpy oracle.

Runs the real TPU kernel through the Pallas interpreter on CPU
(reference test style: numpy-oracle per-op checks). The kernel's
on-chip verdict lives in exp/pallas_1x1_probe.json (stage2 pair:
1.87x over the XLA conv formulation).
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from mxnet_tpu.ops.pallas.conv1x1 import conv1x1_pair


def _oracle(x, w1, w2, s1, b1, s2, b2, res=None):
    h = x.astype("float32") @ w1.astype("float32")
    h = h * s1 + b1
    if res is not None:
        h = h + res.astype("float32")
    h = onp.maximum(h, 0.0)
    y = h @ w2.astype("float32")
    y = y * s2 + b2
    return onp.maximum(y, 0.0)


CASES = [
    # lead, c1, cm, cout, block_rows, affine, residual
    ((256,), 512, 128, 512, 64, False, False),   # stage2 pair shape
    ((64,), 64, 256, 64, 64, False, False),      # stage1 pair shape
    ((4, 49,), 512, 128, 512, 64, True, False),  # folded-BN affines
    ((200,), 128, 512, 128, 64, True, True),     # boundary motif + skip
    ((33,), 256, 128, 192, 32, True, False),     # cout != c1, pad rows
]


@pytest.mark.parametrize("lead,c1,cm,cout,br,affine,residual", CASES)
def test_conv1x1_pair_matches_oracle(lead, c1, cm, cout, br, affine,
                                     residual):
    rng = onp.random.RandomState(0)
    x = rng.randn(*lead, c1).astype("float32") * 0.5
    w1 = (rng.randn(c1, cm) * (2.0 / c1) ** 0.5).astype("float32")
    w2 = (rng.randn(cm, cout) * (2.0 / cm) ** 0.5).astype("float32")
    if affine:
        s1 = (rng.rand(cm) + 0.5).astype("float32")
        b1 = (rng.randn(cm) * 0.1).astype("float32")
        s2 = (rng.rand(cout) + 0.5).astype("float32")
        b2 = (rng.randn(cout) * 0.1).astype("float32")
    else:
        s1 = b1 = s2 = b2 = None
    res = (rng.randn(*lead, cm).astype("float32") * 0.5
           if residual else None)

    with jax.default_matmul_precision("highest"):
        got = conv1x1_pair(
            jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2),
            None if s1 is None else jnp.asarray(s1),
            None if b1 is None else jnp.asarray(b1),
            None if s2 is None else jnp.asarray(s2),
            None if b2 is None else jnp.asarray(b2),
            None if res is None else jnp.asarray(res),
            block_rows=br, interpret=True)
    want = _oracle(
        x.reshape(-1, c1), w1, w2,
        1.0 if s1 is None else s1, 0.0 if b1 is None else b1,
        1.0 if s2 is None else s2, 0.0 if b2 is None else b2,
        None if res is None else res.reshape(-1, cm))
    assert got.shape == (*lead, cout)
    onp.testing.assert_allclose(
        onp.asarray(got, "float32").reshape(-1, cout), want,
        rtol=2e-5, atol=2e-5)


def test_conv1x1_pair_bf16():
    rng = onp.random.RandomState(1)
    x = jnp.asarray(rng.randn(96, 512) * 0.5, dtype=jnp.bfloat16)
    w1 = jnp.asarray(rng.randn(512, 128) * 0.06, dtype=jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(128, 512) * 0.12, dtype=jnp.bfloat16)
    got = conv1x1_pair(x, w1, w2, block_rows=32, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _oracle(onp.asarray(x, "float32"), onp.asarray(w1, "float32"),
                   onp.asarray(w2, "float32"), 1.0, 0.0, 1.0, 0.0)
    err = onp.abs(onp.asarray(got, "float32") - want)
    assert err.max() / (onp.abs(want).max() + 1e-9) < 0.05
