"""Model-scale reference-artifact round-trip (VERDICT r4 Next #8).

Builds a conv-net as a legacy Symbol graph, writes BOTH halves of a
reference checkpoint pair with this repo's OWN writers —
``model-symbol.json`` in the reference's nnvm graph JSON
(``Symbol.save(fmt='nnvm')``) and ``model-0000.params`` in the
reference's magic-tagged V2 binary with ``arg:``/``aux:`` keys
(``ndarray.utils.save(fmt='reference')``) — then loads the pair back
through ``SymbolBlock.imports`` (the reference-format reader path that
also loads ``tests/golden/``'s genuine artifacts) and checks inference
parity at model scale, not tensor scale.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import np as mnp
from mxnet_tpu import sym
from mxnet_tpu.base import MXNetError


def _convnet():
    """LeNet-scale conv-net WITH BatchNorm (exercises aux: states)."""
    data = sym.var("data")
    w = {}

    def v(name):
        w[name] = None
        return sym.var(name)

    x = sym.Convolution(data, v("conv0_weight"), v("conv0_bias"),
                        kernel=(5, 5), num_filter=32, name="conv0")
    x = sym.BatchNorm(x, v("bn0_gamma"), v("bn0_beta"),
                      v("bn0_moving_mean"), v("bn0_moving_var"),
                      fix_gamma=False, name="bn0")
    x = sym.Activation(x, act_type="relu", name="relu0")
    x = sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool0")
    x = sym.Convolution(x, v("conv1_weight"), v("conv1_bias"),
                        kernel=(3, 3), num_filter=64, name="conv1")
    x = sym.Activation(x, act_type="relu", name="relu1")
    x = sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    x = sym.Flatten(x, name="flat")
    x = sym.FullyConnected(x, v("fc0_weight"), v("fc0_bias"),
                           num_hidden=128, name="fc0")
    x = sym.Activation(x, act_type="relu", name="relu2")
    x = sym.FullyConnected(x, v("fc1_weight"), v("fc1_bias"),
                           num_hidden=10, name="fc1")
    return x, list(w)


def _init_params(rng):
    shapes = {
        "conv0_weight": (32, 1, 5, 5), "conv0_bias": (32,),
        "bn0_gamma": (32,), "bn0_beta": (32,),
        "bn0_moving_mean": (32,), "bn0_moving_var": (32,),
        "conv1_weight": (64, 32, 3, 3), "conv1_bias": (64,),
        "fc0_weight": (128, 64 * 5 * 5), "fc0_bias": (128,),
        "fc1_weight": (10, 128), "fc1_bias": (10,),
    }
    out = {}
    for n, s in shapes.items():
        if n.endswith("moving_var"):
            a = onp.abs(rng.randn(*s)).astype("float32") + 0.5
        elif n.endswith(("gamma",)):
            a = onp.abs(rng.randn(*s)).astype("float32") * 0.3 + 0.8
        else:
            a = (rng.randn(*s) * 0.1).astype("float32")
        out[n] = mnp.array(a)
    return out


def test_nnvm_export_reference_params_roundtrip(tmp_path):
    net_sym, names = _convnet()
    rng = onp.random.RandomState(0)
    params = _init_params(rng)
    x = mnp.array(rng.uniform(-1, 1, (4, 1, 28, 28)).astype("float32"))

    # in-memory oracle: executor forward on the original graph
    exe = net_sym.bind(args={"data": x, **params})
    ref = exe.forward(is_train=False)[0].asnumpy()
    assert ref.shape == (4, 10)

    # write the checkpoint pair with the repo's own writers, in the
    # REFERENCE formats (nnvm graph JSON; V2 params, arg:/aux: keys)
    sym_file = os.path.join(tmp_path, "model-symbol.json")
    par_file = os.path.join(tmp_path, "model-0000.params")
    net_sym.save(sym_file, fmt="nnvm")
    keyed = {}
    for n, a in params.items():
        prefix = "aux:" if "moving_" in n else "arg:"
        keyed[prefix + n] = a
    from mxnet_tpu.ndarray.utils import save

    save(par_file, keyed, fmt="reference")

    # sanity: both artifacts really are reference-format bytes
    import json as _json

    with open(sym_file) as f:
        doc = _json.load(f)
    assert "arg_nodes" in doc and "heads" in doc
    assert "mxnet_tpu_symbol" not in doc
    with open(par_file, "rb") as f:
        magic = f.read(8)
    assert magic[:4] == b"\x12\x01\x00\x00"  # NDArray list magic 0x112

    # reload THROUGH the reference-artifact reader path and run
    net = gluon.SymbolBlock.imports(sym_file, ["data"], par_file)
    got = net(x).asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_nnvm_writer_rejects_literal_positional_args(tmp_path):
    s = sym.var("a") * 2.0  # scalar binop holds a literal positional arg
    with pytest.raises(MXNetError):
        s.tojson(fmt="nnvm")
    with pytest.raises(MXNetError):
        sym.var("a").tojson(fmt="bogus")
    # a multi-output Group has no single-head nnvm encoding: refuse
    # loudly rather than write a '_group' node no reference install
    # could load (review finding r5)
    g = sym.Group([sym.var("a"), sym.var("b")])
    with pytest.raises(MXNetError):
        g.tojson(fmt="nnvm")


def test_nnvm_json_loads_in_fresh_symbol_module(tmp_path):
    """The written JSON replays through symbol.load's nnvm branch (the
    same code path the golden reference artifact uses)."""
    net_sym, _ = _convnet()
    f = os.path.join(tmp_path, "m-symbol.json")
    net_sym.save(f, fmt="nnvm")
    from mxnet_tpu import symbol as sym_mod

    loaded = sym_mod.load(f)
    assert sorted(loaded.list_arguments()) == \
        sorted(net_sym.list_arguments())
