"""Numerical guardrail tests (mxnet_tpu/resilience/guardrails.py): the
`nan` fault kind and trainer:grad poisoning site, non-finite sentinels
with attribution, clip_by_global_norm + the fused/eager clip-ordering
regression, the SpikeDetector, hardened LossScaler clamps and Trainer
integration, the dist_tpu pre-collective NaN quarantine, GuardrailHandler
skip-step / rewind-and-skip loss parity vs uninterrupted runs (the
acceptance scenarios), escalation to DivergenceError, counters/trace
accounting, and the disabled-guardrail eager-microloop overhead bound."""
import logging
import os
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon
from mxnet_tpu import np as mnp
from mxnet_tpu.base import MXNetError
from mxnet_tpu.profiler import core as _prof
from mxnet_tpu.resilience import (counters, faults, guardrails,
                                  resilience_stats)
from mxnet_tpu.resilience.guardrails import (DivergenceError,
                                             GuardrailHandler,
                                             NonFiniteGradError,
                                             SpikeDetector, all_finite,
                                             attribute_nonfinite,
                                             clip_by_global_norm,
                                             nonfinite_count)


@pytest.fixture(autouse=True)
def _clean_guardrail_state():
    """Every test starts/ends with no fault plan, reset counters, and no
    leftover guardrail env knobs."""
    faults.clear_plan()
    _prof.reset()
    counters.reset()
    saved = {k: os.environ.pop(k, None)
             for k in ("MXNET_FAULT_PLAN", "MXNET_NAN_QUARANTINE",
                       "MXNET_NAN_QUARANTINE_MODE",
                       "MXNET_GUARDRAIL_MAX_SKIPS",
                       "MXNET_GUARDRAIL_MAX_REWINDS",
                       "MXNET_GUARDRAIL_SPIKE_WINDOW",
                       "MXNET_GUARDRAIL_SPIKE_ZSCORE",
                       "MXNET_GUARDRAIL_WARMUP",
                       "MXNET_LOSS_SCALE_MIN", "MXNET_LOSS_SCALE_MAX")}
    logging.getLogger("mxnet_tpu.estimator").setLevel(logging.ERROR)
    yield
    faults.clear_plan()
    _prof.reset()
    counters.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# nan fault kind + trainer:grad site
# ---------------------------------------------------------------------------


def test_nan_fault_kind_returns_marker_not_raise():
    plan = faults.install_plan({"rules": [
        {"site": "s", "kind": "nan", "at": [1]}]})
    assert plan.check("s") is None
    assert plan.check("s") == "nan"
    assert plan.check("s") is None
    assert plan.fired_total() == 1
    assert resilience_stats()["faults_injected"] == 1


def test_nan_rule_on_non_corrupting_site_is_harmless():
    """A nan rule on a site that doesn't implement corruption fires (and
    counts) but has no effect — engine.wait_all ignores the marker."""
    from mxnet_tpu import engine

    plan = faults.install_plan({"rules": [
        {"site": "engine:wait", "kind": "nan", "times": 1}]})
    engine.wait_all()  # must not raise
    assert plan.fired_total() == 1


def _dense_trainer(units=3, out=2, **trainer_kw):
    net = gluon.nn.Dense(out, in_units=units)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, **trainer_kw)
    return net, tr


def test_trainer_grad_site_poisons_all_grads():
    """A 'nan' rule at trainer:grad corrupts every gradient at exactly the
    planned step — without guardrails the weights go NaN, the corruption
    the GuardrailHandler exists to stop."""
    net, tr = _dense_trainer()
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [1]}]})
    finite_after = []
    for _ in range(3):
        with autograd.record():
            loss = (net(mnp.ones((2, 3))) ** 2).sum()
        loss.backward()
        tr.step(1)
        finite_after.append(
            all_finite([p.data() for p in net.collect_params().values()]))
    assert finite_after == [True, False, False]


# ---------------------------------------------------------------------------
# sentinels
# ---------------------------------------------------------------------------


def test_sentinels_finite_and_count():
    a = mnp.ones((4,))
    b = mnp.array([1.0, float("nan"), float("inf"), 2.0])
    assert all_finite([a]) and all_finite([])
    assert not all_finite([a, b])
    assert nonfinite_count([a]) == 0
    assert nonfinite_count([a, b]) == 2
    # integer arrays are trivially finite, not an error
    assert all_finite([mnp.array([1, 2, 3])])


def test_attribute_nonfinite_blames_the_right_params():
    blame = attribute_nonfinite({
        "w": mnp.ones((4,)),
        "b": mnp.array([float("nan"), 1.0]),
        "m": mnp.array([float("inf")] * 3),
    })
    assert ("b", 1, 2) in blame and ("m", 3, 3) in blame
    assert not any(n == "w" for n, _, _ in blame)


# ---------------------------------------------------------------------------
# clip_by_global_norm + trainer wiring
# ---------------------------------------------------------------------------


def test_clip_by_global_norm_math_and_nonfinite_passthrough():
    arrs = [mnp.ones((3,)) * 3.0, mnp.ones((3,)) * 4.0]
    _, norm = clip_by_global_norm(arrs, 1.0)
    assert norm == pytest.approx(onp.sqrt(75.0))
    total = sum(float(onp.square(a.asnumpy()).sum()) for a in arrs)
    assert onp.sqrt(total) == pytest.approx(1.0, rel=1e-6)
    # under the threshold: untouched
    arrs2 = [mnp.ones((2,))]
    _, norm2 = clip_by_global_norm(arrs2, 10.0)
    assert norm2 == pytest.approx(onp.sqrt(2.0))
    onp.testing.assert_allclose(arrs2[0].asnumpy(), onp.ones((2,)))
    # non-finite norm: scaling can't fix it — arrays left alone
    bad = [mnp.array([float("nan"), 1.0])]
    _, norm3 = clip_by_global_norm(bad, 1.0)
    assert not onp.isfinite(norm3)
    assert onp.isnan(bad[0].asnumpy()[0]) and bad[0].asnumpy()[1] == 1.0


def test_clip_by_global_norm_preserves_none_holes():
    """Non-in-place results keep positions (incl. None) so callers can
    zip against the original parameter list."""
    import jax.numpy as jnp

    out, norm = clip_by_global_norm(
        [jnp.ones((3,)) * 3.0, None, jnp.ones((3,)) * 4.0], 1.0,
        in_place=False)
    assert len(out) == 3 and out[1] is None
    assert norm == pytest.approx(onp.sqrt(75.0))
    total = float(onp.square(out[0]).sum() + onp.square(out[2]).sum())
    assert onp.sqrt(total) == pytest.approx(1.0, rel=1e-6)


def test_gluon_utils_clip_global_norm_delegates():
    """The reference util and the guardrail util are one implementation."""
    arrs = [mnp.ones((4,)) * 2.0]
    norm = gluon.utils.clip_global_norm(arrs, 1.0)
    assert norm == pytest.approx(4.0)
    assert float(onp.linalg.norm(arrs[0].asnumpy())) \
        == pytest.approx(1.0, rel=1e-6)
    with pytest.warns(UserWarning, match="nan or inf"):
        gluon.utils.clip_global_norm([mnp.array([float("nan")])], 1.0)


def _same_init_pair(**kw2):
    """Two Dense nets with identical weights (independent buffers: the
    fused update donates, so sharing would invalidate one net's params)."""
    n1 = gluon.nn.Dense(2, in_units=3)
    n1.initialize()
    n1(mnp.ones((1, 3)))
    n2 = gluon.nn.Dense(2, in_units=3)
    n2.initialize()
    n2(mnp.ones((1, 3)))
    for p1, p2 in zip(n1.collect_params().values(),
                      n2.collect_params().values()):
        p2.set_data(mnp.array(p1.data().asnumpy()))
    return n1, n2


def test_trainer_clip_global_norm_matches_manual():
    n1, n2 = _same_init_pair()
    t1 = gluon.Trainer(n1.collect_params(), "sgd", {"learning_rate": 0.1})
    t2 = gluon.Trainer(n2.collect_params(), "sgd", {"learning_rate": 0.1},
                       clip_global_norm=0.5)
    x = mnp.array(onp.random.randn(4, 3).astype("float32"))
    with autograd.record():
        (n1(x) ** 2).sum().backward()
    # manual: reference-style clip then step
    gluon.utils.clip_global_norm(
        [p.grad() for p in n1.collect_params().values()], 0.5)
    t1.step(4)
    with autograd.record():
        (n2(x) ** 2).sum().backward()
    t2.step(4)
    for p1, p2 in zip(n1.collect_params().values(),
                      n2.collect_params().values()):
        onp.testing.assert_allclose(p2.data().asnumpy(),
                                    p1.data().asnumpy(), rtol=1e-6)


def test_fused_vs_eager_clip_ordering_parity():
    """Satellite: the fused multi-tensor path's rescale-then-clip must
    match Optimizer._prep_grad's non-fused ordering on the same grads —
    with rescale != 1 and grads straddling the clip threshold, any
    ordering difference shows up immediately."""
    n1, n2 = _same_init_pair()
    kw = {"learning_rate": 0.1, "momentum": 0.9, "clip_gradient": 0.05,
          "rescale_grad": 0.25}
    t_fused = gluon.Trainer(n1.collect_params(), "sgd", dict(kw))
    t_eager = gluon.Trainer(n2.collect_params(), "sgd", dict(kw))
    # force the reference eager per-param path on the second trainer
    t_eager._optimizer.fused_safe = False
    x = mnp.array(onp.random.randn(8, 3).astype("float32") * 5.0)
    for _ in range(3):  # momentum state must agree across steps too
        with autograd.record():
            (n1(x) ** 2).sum().backward()
        t_fused.step(2)
        with autograd.record():
            (n2(x) ** 2).sum().backward()
        t_eager.step(2)
    for p1, p2 in zip(n1.collect_params().values(),
                      n2.collect_params().values()):
        onp.testing.assert_allclose(p2.data().asnumpy(),
                                    p1.data().asnumpy(),
                                    rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# spike detector
# ---------------------------------------------------------------------------


def test_spike_detector_flags_spike_after_warmup():
    d = SpikeDetector(window=8, zscore=4.0, warmup=4)
    series = [1.0, 0.9, 0.8, 0.85, 0.82, 0.81, 0.8, 0.79]
    assert all(d.update(v) is None for v in series)
    assert d.update(50.0) == "spike"
    assert d.update(float("nan")) == "nonfinite"
    assert d.update(float("inf")) == "nonfinite"
    # the spike was NOT absorbed: a follow-up ordinary value is clean
    assert d.update(0.78) is None


def test_spike_detector_warmup_and_noise_tolerance():
    d = SpikeDetector(window=8, zscore=4.0, warmup=4)
    # a 100x jump during warmup is tolerated (initial transients)
    assert d.update(100.0) is None
    assert d.update(1.0) is None
    # gaussian noise around a level never flags at z=4 with the relative
    # floor in place
    rng = onp.random.RandomState(0)
    d2 = SpikeDetector(window=16, zscore=6.0, warmup=4)
    verdicts = [d2.update(1.0 + 0.05 * rng.randn()) for _ in range(200)]
    assert all(v is None for v in verdicts)


def test_spike_detector_reset():
    d = SpikeDetector(window=4, zscore=3.0, warmup=2)
    for v in (1.0, 1.0, 1.0, 1.0):
        d.update(v)
    d.reset()
    assert d.seen == 0
    assert d.update(1000.0) is None  # back in warmup


# ---------------------------------------------------------------------------
# hardened LossScaler (satellite)
# ---------------------------------------------------------------------------


def test_loss_scaler_overflow_streak_clamps_at_min():
    s = amp.LossScaler(init_scale=8.0, scale_factor=2.0, min_scale=1.0,
                       max_scale=2.0 ** 20)
    for _ in range(50):
        assert s.update(True) is True
    assert s.loss_scale == 1.0  # never 0, never negative
    assert s.overflows == 50 and s.skipped_steps == 50


def test_loss_scaler_growth_clamps_at_max():
    s = amp.LossScaler(init_scale=4.0, scale_factor=2.0, scale_window=1,
                       min_scale=1.0, max_scale=64.0)
    for _ in range(100):
        s.update(False)
    assert s.loss_scale == 64.0  # never inf


def test_loss_scaler_repairs_nonfinite_scale():
    s = amp.LossScaler(init_scale=4.0, min_scale=2.0, max_scale=64.0)
    s.loss_scale = float("inf")  # e.g. restored from a corrupt source
    s.update(True)
    assert onp.isfinite(s.loss_scale) and 2.0 <= s.loss_scale <= 64.0
    s.loss_scale = float("nan")
    s.update(False)
    assert onp.isfinite(s.loss_scale) and 2.0 <= s.loss_scale <= 64.0


def test_loss_scaler_rejects_bad_construction():
    with pytest.raises(MXNetError, match="init_scale"):
        amp.LossScaler(init_scale=float("inf"))
    with pytest.raises(MXNetError, match="init_scale"):
        amp.LossScaler(init_scale=0.0)
    with pytest.raises(MXNetError, match="min_scale"):
        amp.LossScaler(min_scale=8.0, max_scale=2.0)
    with pytest.raises(MXNetError, match="scale_factor"):
        amp.LossScaler(scale_factor=1.0)


def test_loss_scaler_env_clamp_defaults():
    os.environ["MXNET_LOSS_SCALE_MIN"] = "4.0"
    os.environ["MXNET_LOSS_SCALE_MAX"] = "16.0"
    s = amp.LossScaler(init_scale=1024.0)
    assert s.loss_scale == 16.0  # init clamped into the env range
    for _ in range(10):
        s.update(True)
    assert s.loss_scale == 4.0


# ---------------------------------------------------------------------------
# Trainer + LossScaler integration
# ---------------------------------------------------------------------------


def test_trainer_overflow_skips_update_and_scales_down():
    net, tr = _dense_trainer(loss_scaler=amp.LossScaler(init_scale=8.0))
    before = {k: v.data().asnumpy().copy()
              for k, v in net.collect_params().items()}
    with autograd.record():
        loss = tr.scale_loss((net(mnp.ones((2, 3))) ** 2).sum())
    loss.backward()
    for p in tr._params:  # force the overflow the scaler must catch
        g = p.grad()
        g._set_data_internal(g._data * float("nan"))
    tr.step(1)
    after = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    for k in before:  # the update was skipped — weights untouched
        onp.testing.assert_array_equal(after[k], before[k])
    assert tr.loss_scaler.loss_scale == 4.0
    assert tr.loss_scaler.skipped_steps == 1
    assert resilience_stats()["loss_scale_overflows"] == 1


def test_trainer_update_on_kvstore_rejects_guardrails():
    """Server-side updates never see the scaler's unscale or the clip —
    the combination must fail loudly, not push loss_scale-times-too-large
    updates."""
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    net(mnp.ones((1, 3)))
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       kvstore="local", update_on_kvstore=True,
                       loss_scaler=amp.LossScaler())
    with autograd.record():
        (net(mnp.ones((2, 3))) ** 2).sum().backward()
    with pytest.raises(MXNetError, match="update_on_kvstore"):
        tr.step(2)


@pytest.mark.integration
def test_estimator_with_scaler_matches_estimator_without():
    """The estimator's fit_batch scales the loss through the trainer's
    scaler and step() unscales — end to end the updates must be identical
    to an unscaled run (the regression: an unscaled backward + unscaling
    step silently divides every update by loss_scale)."""
    batches = _make_batches(n=6)

    def run(scaler):
        mx.random.seed(7)
        onp.random.seed(7)
        net = gluon.nn.Dense(1)
        net.initialize()
        net(mnp.ones((4, 3)))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, loss_scaler=scaler)
        from mxnet_tpu.gluon.contrib.estimator import Estimator

        est = Estimator(net, gluon.loss.L2Loss(), trainer=tr,
                        train_metrics=[gluon.metric.MAE()])
        est.fit(batches, batches=len(batches))
        return {k: v.data().asnumpy()
                for k, v in net.collect_params().items()}, tr

    ref, _ = run(None)
    got, tr = run(amp.LossScaler(init_scale=64.0))
    assert tr.loss_scaler.overflows == 0
    for k in ref:
        onp.testing.assert_allclose(got[k], ref[k], rtol=1e-5, atol=1e-7)


def test_guardrail_defers_nonfinite_grads_to_loss_scaler():
    """With a LossScaler attached, non-finite grads are the scaler's
    overflow signal: the guardrail must NOT veto the step (that would
    starve scaler.update and the scale would never adapt) — the scaler
    skips the update and halves the scale instead."""
    batches = _make_batches(n=6)
    mx.random.seed(7)
    onp.random.seed(7)
    net = gluon.nn.Dense(1)
    net.initialize()
    net(mnp.ones((4, 3)))
    scaler = amp.LossScaler(init_scale=8.0)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, loss_scaler=scaler)
    from mxnet_tpu.gluon.contrib.estimator import Estimator

    est = Estimator(net, gluon.loss.L2Loss(), trainer=tr,
                    train_metrics=[gluon.metric.MAE()])
    guard = GuardrailHandler(check_grads=True)
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [2]}]})
    est.fit(batches, batches=len(batches), event_handlers=[guard])
    faults.clear_plan()
    assert scaler.skipped_steps == 1 and scaler.loss_scale == 4.0
    assert guard.stats["skips"] == 0  # the guardrail stayed out of it
    assert all_finite([p.data() for p in tr._params])


def test_trainer_scaled_clean_step_matches_unscaled():
    """Scale-by-S at the loss + unscale folded into the update must land
    on the same weights as a plain unscaled step."""
    n1, n2 = _same_init_pair()
    t1 = gluon.Trainer(n1.collect_params(), "sgd", {"learning_rate": 0.1})
    t2 = gluon.Trainer(n2.collect_params(), "sgd", {"learning_rate": 0.1},
                       loss_scaler=amp.LossScaler(init_scale=16.0))
    x = mnp.array(onp.random.randn(4, 3).astype("float32"))
    with autograd.record():
        (n1(x) ** 2).sum().backward()
    t1.step(4)
    with autograd.record():
        l2 = t2.scale_loss((n2(x) ** 2).sum())
    l2.backward()
    t2.step(4)
    for p1, p2 in zip(n1.collect_params().values(),
                      n2.collect_params().values()):
        onp.testing.assert_allclose(p2.data().asnumpy(),
                                    p1.data().asnumpy(),
                                    rtol=1e-5, atol=1e-7)
    assert t2.loss_scaler.overflows == 0


# ---------------------------------------------------------------------------
# pre-collective NaN quarantine (dist_tpu)
# ---------------------------------------------------------------------------


def _per_device_ones(shape=(4,)):
    import jax
    import jax.numpy as jnp

    return [mx.nd.NDArray(jax.device_put(jnp.ones(shape), d))
            for d in jax.devices()]


def _poison_replica(arrs, idx):
    import jax.numpy as jnp

    arrs[idx]._set_data_internal(arrs[idx]._data * jnp.nan)
    return arrs


def test_quarantine_skip_mode_raises_before_the_collective():
    os.environ["MXNET_NAN_QUARANTINE"] = "1"
    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync

    kv = KVStoreDistTPUSync()
    arrs = _poison_replica(_per_device_ones(), 2)
    with pytest.warns(RuntimeWarning, match="NaN quarantine"):
        with pytest.raises(NonFiniteGradError, match="would poison"):
            kv.allreduce(arrs)
    s = kv.collective_stats()
    assert s["quarantined"] == 1
    # NOT a fast-path failure: no degradation, breaker untouched
    assert s["degradations"] == 0
    assert s["breaker"]["consecutive_failures"] == 0
    assert resilience_stats()["nan_quarantined"] == 1


def test_quarantine_drop_mode_sums_clean_replicas():
    os.environ["MXNET_NAN_QUARANTINE"] = "1"
    os.environ["MXNET_NAN_QUARANTINE_MODE"] = "drop"
    import jax

    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync

    kv = KVStoreDistTPUSync()
    n = len(jax.devices())
    arrs = _poison_replica(_per_device_ones(), 1)
    with pytest.warns(RuntimeWarning, match="NaN quarantine"):
        out = kv.allreduce(arrs)
    # n-1 clean ones, rescaled by n/(n-1): the unbiased full-mesh estimate
    onp.testing.assert_allclose(out[0].asnumpy(), onp.full((4,), float(n)),
                                rtol=1e-6)
    assert all_finite(out)
    # every replica keeps its original device placement
    for a, o in zip(arrs, out):
        assert a._data.devices() == o._data.devices()


def test_quarantine_drop_mode_all_bad_still_raises():
    os.environ["MXNET_NAN_QUARANTINE"] = "1"
    os.environ["MXNET_NAN_QUARANTINE_MODE"] = "drop"
    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync

    kv = KVStoreDistTPUSync()
    arrs = _per_device_ones()
    for i in range(len(arrs)):
        _poison_replica(arrs, i)
    with pytest.warns(RuntimeWarning, match="NaN quarantine"):
        # the message must not advise the mode that's already set
        with pytest.raises(NonFiniteGradError, match="every replica"):
            kv.allreduce(arrs)


def test_quarantine_mode_validated_at_construction():
    os.environ["MXNET_NAN_QUARANTINE_MODE"] = "Drop"  # typo'd case
    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync

    with pytest.raises(MXNetError, match="skip.*drop|drop.*skip"):
        KVStoreDistTPUSync()


def test_quarantine_off_by_default_no_check():
    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync

    kv = KVStoreDistTPUSync()
    assert not kv._nan_quarantine
    arrs = _poison_replica(_per_device_ones(), 0)
    out = kv.allreduce(arrs)  # poison flows through (production default)
    assert not all_finite(out)
    assert kv.collective_stats()["quarantined"] == 0


# ---------------------------------------------------------------------------
# estimator recovery: the acceptance scenarios
# ---------------------------------------------------------------------------


def _make_batches(n=10, batch=4, dim=3, seed=0):
    rng = onp.random.RandomState(seed)
    return [(mnp.array(rng.randn(batch, dim).astype("float32")),
             mnp.array(rng.randn(batch, 1).astype("float32")))
            for _ in range(n)]


def _fresh_estimator(seed=7):
    mx.random.seed(seed)
    onp.random.seed(seed)
    net = gluon.nn.Dense(1)
    net.initialize()
    net(mnp.ones((4, 3)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    from mxnet_tpu.gluon.contrib.estimator import Estimator

    return Estimator(net, gluon.loss.L2Loss(), trainer=tr,
                     train_metrics=[gluon.metric.MAE()])


def _params_np(est):
    return {k: v.data().asnumpy()
            for k, v in est.net.collect_params().items()}


def _probe_loss(est, batches):
    with autograd.predict_mode():
        pred = est.net(batches[0][0])
        return float(est.loss(pred, batches[0][1]).mean().asnumpy())


K = 5  # the poisoned batch in the parity scenarios


def _clean_reference(batches):
    """The comparison run: same seed, never sees batch K."""
    est = _fresh_estimator()
    clean = batches[:K] + batches[K + 1:]
    est.fit(clean, batches=len(clean))
    return est


@pytest.mark.integration
def test_skip_step_parity_exact():
    """NaN grads at batch K, caught by the pre-step grad sentinel: the
    update is vetoed, and the final weights EXACTLY match a clean run
    that never saw batch K (same seed)."""
    batches = _make_batches()
    ref = _params_np(_clean_reference(batches))

    est = _fresh_estimator()
    guard = GuardrailHandler(check_grads=True)
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [K]}]})
    with pytest.warns(RuntimeWarning, match="skipping optimizer update"):
        est.fit(batches, batches=len(batches), event_handlers=[guard])
    faults.clear_plan()
    got = _params_np(est)
    for k in ref:
        onp.testing.assert_array_equal(got[k], ref[k])
    assert guard.stats["skips"] == 1
    assert guard.stats["rewinds"] == 0
    assert "nonfinite_grad" in guard.stats["last_trip"]
    assert resilience_stats()["guardrail_skips"] == 1


@pytest.mark.integration
def test_rewind_and_skip_parity_exact(tmp_path):
    """The acceptance scenario: NaN grads at batch K slip past (grad
    sentinel off), corrupt the weights, are detected post-update by the
    parameter sentinel, and recovery rewinds to the last checkpoint +
    skips the batch window — landing EXACTLY on the loss trajectory of a
    clean run that never saw batch K (same seed)."""
    from mxnet_tpu.gluon.contrib.estimator import ResilientCheckpointHandler

    batches = _make_batches()
    ref_est = _clean_reference(batches)
    ref = _params_np(ref_est)
    ref_loss = _probe_loss(ref_est, batches)

    est = _fresh_estimator()
    ck = ResilientCheckpointHandler(str(tmp_path), batch_period=1)
    guard = GuardrailHandler(manager=ck, check_grads=False,
                            check_params=True)
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [K]}]})
    with pytest.warns(RuntimeWarning, match="rewound to checkpoint"):
        est.fit(batches, batches=len(batches), event_handlers=[ck, guard])
    faults.clear_plan()

    got = _params_np(est)
    for k in ref:
        onp.testing.assert_array_equal(got[k], ref[k])
    assert _probe_loss(est, batches) == ref_loss
    assert guard.stats["rewinds"] == 1
    assert guard.stats["skips"] == 0
    assert resilience_stats()["guardrail_rewinds"] == 1


@pytest.mark.integration
def test_rewind_quarantines_poisoned_checkpoint(tmp_path):
    """When the checkpoint handler runs BEFORE the guardrail (priority
    flipped), the corrupting batch's checkpoint is saved with NaN weights;
    the rewind must detect that, quarantine it as .poisoned, and roll back
    to the older clean one — still landing on exact parity."""
    from mxnet_tpu.gluon.contrib.estimator import ResilientCheckpointHandler

    batches = _make_batches()
    ref = _params_np(_clean_reference(batches))

    est = _fresh_estimator()
    ck = ResilientCheckpointHandler(str(tmp_path), batch_period=1)
    guard = GuardrailHandler(manager=ck, check_grads=False,
                            check_params=True, priority=100)  # after ck
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [K]}]})
    with pytest.warns(RuntimeWarning):
        est.fit(batches, batches=len(batches), event_handlers=[ck, guard])
    faults.clear_plan()

    poisoned = [f for f in os.listdir(tmp_path) if f.endswith(".poisoned")]
    assert len(poisoned) == 1
    got = _params_np(est)
    for k in ref:
        onp.testing.assert_array_equal(got[k], ref[k])
    assert guard.stats["rewinds"] == 1


@pytest.mark.integration
def test_rewind_unquarantinable_poisoned_checkpoint_diverges(tmp_path):
    """If the poisoned checkpoint cannot be renamed, the rewind loop must
    raise DivergenceError instead of reloading the same NaN file
    forever."""
    from mxnet_tpu.gluon.contrib.estimator import ResilientCheckpointHandler

    batches = _make_batches()
    est = _fresh_estimator()
    ck = ResilientCheckpointHandler(str(tmp_path), batch_period=1)
    guard = GuardrailHandler(manager=ck, check_grads=False,
                            check_params=True, priority=100)  # after ck
    ck.manager.quarantine = lambda *a, **k: False  # rename always fails
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [K]}]})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(DivergenceError,
                           match="could not be quarantined"):
            est.fit(batches, batches=len(batches),
                    event_handlers=[ck, guard])
    faults.clear_plan()


def test_nonfinite_loss_with_clean_weights_skips_not_rewinds():
    """A NaN in the DATA makes the loss non-finite while the weights are
    still healthy: the guardrail attributes it to the batch (skip), not
    the state (rewind)."""
    batches = _make_batches(n=6)
    x_bad = batches[2][0].asnumpy().copy()
    x_bad[0, 0] = float("nan")
    batches[2] = (mnp.array(x_bad), batches[2][1])

    est = _fresh_estimator()
    guard = GuardrailHandler(check_grads=True)
    with pytest.warns(RuntimeWarning, match="skipping optimizer update"):
        est.fit(batches, batches=len(batches), event_handlers=[guard])
    assert guard.stats["skips"] >= 1
    assert guard.stats["rewinds"] == 0
    assert "nonfinite_loss" in guard.stats["last_trip"]
    assert all_finite([p.data() for p in est.trainer._params])


@pytest.mark.integration
def test_escalation_consecutive_skips_then_rewinds_then_diverges(tmp_path):
    """Persistent corruption escalates: skip-step x max_consecutive_skips,
    then rewind, then (budget exhausted) DivergenceError."""
    from mxnet_tpu.gluon.contrib.estimator import ResilientCheckpointHandler

    batches = _make_batches(n=24)
    est = _fresh_estimator()
    ck = ResilientCheckpointHandler(str(tmp_path), batch_period=1)
    guard = GuardrailHandler(manager=ck, check_grads=True,
                            max_consecutive_skips=2, max_rewinds=1)
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "times": 1000}]})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(DivergenceError, match="rewind budget"):
            est.fit(batches, batches=len(batches),
                    event_handlers=[ck, guard])
    faults.clear_plan()
    # 2 skips -> rewind #1 -> 2 skips -> rewind #2 refused (budget 1)
    assert guard.stats["rewinds"] == 1
    assert guard.stats["skips"] == 4
    # every skip kept the weights finite (the veto worked each time)
    assert all_finite([p.data() for p in est.trainer._params])


def test_divergence_error_without_manager():
    """Corrupted weights with no checkpoint manager: nothing to rewind to,
    the run must fail loudly instead of training on NaNs."""
    batches = _make_batches(n=6)
    est = _fresh_estimator()
    guard = GuardrailHandler(check_grads=False, check_params=True)
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [1]}]})
    with pytest.raises(DivergenceError, match="no CheckpointManager"):
        est.fit(batches, batches=len(batches), event_handlers=[guard])
    faults.clear_plan()


def test_step_error_absorbs_quarantine_trips():
    """A NonFiniteGradError from inside trainer.step (the dist_tpu
    quarantine) is absorbed as a skip by the handler; anything else
    propagates."""
    est = _fresh_estimator()
    guard = GuardrailHandler(check_grads=False)
    with pytest.warns(RuntimeWarning, match="skipping optimizer update"):
        assert guard.step_error(est, NonFiniteGradError("quarantined")) \
            is True
    assert guard.stats["skips"] == 1
    assert "quarantine" in guard.stats["last_trip"]
    assert guard.step_error(est, MXNetError("something else")) is False


# ---------------------------------------------------------------------------
# accounting: counters + profiler bus
# ---------------------------------------------------------------------------


def test_guardrail_counters_in_resilience_stats():
    s = resilience_stats()
    assert set(s) >= {"sentinel_trips", "guardrail_skips",
                      "guardrail_rewinds", "nan_quarantined",
                      "loss_scale_overflows"}
    assert all(s[k] == 0 for k in ("sentinel_trips", "guardrail_skips",
                                   "guardrail_rewinds"))


def test_guardrail_events_on_profiler_bus():
    """Trips/skips land as resilience::* instants while the bus runs."""
    from mxnet_tpu import profiler

    batches = _make_batches(n=4)
    est = _fresh_estimator()
    guard = GuardrailHandler(check_grads=True)
    faults.install_plan({"rules": [
        {"site": "trainer:grad", "kind": "nan", "at": [1]}]})
    profiler.set_state("run")
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est.fit(batches, batches=len(batches), event_handlers=[guard])
    finally:
        profiler.set_state("stop")
        faults.clear_plan()
    names = {e["name"] for e in _prof.snapshot_events()}
    assert "resilience::sentinel_trip" in names
    assert "resilience::guardrail(skip)" in names


# ---------------------------------------------------------------------------
# overhead bound + tier-1 gate script
# ---------------------------------------------------------------------------


def test_disabled_guardrail_overhead_under_5pct():
    """Guardrails present-but-disabled (no scaler, no clip, an installed
    plan whose rules never match the loop's sites — the production
    default) must stay within the PR-1/PR-2 5% eager-microloop overhead
    bound. Mirrors test_stopped_resilience_overhead's measurement
    discipline, including the 15% hard-fail threshold for suite-load
    noise."""
    import time as _time

    x = mnp.ones((4,))

    def loop(n=10_000):
        y = x
        t0 = _time.perf_counter()
        for _ in range(n):
            y = y + 1.0
        y.wait_to_read()
        return _time.perf_counter() - t0

    guard = GuardrailHandler(check_grads=True, check_params=True)  # idle

    def measure(rounds=7):
        base = active = float("inf")
        for _ in range(rounds):
            faults.clear_plan()
            base = min(base, loop())
            faults.install_plan({"rules": [
                {"site": "trainer:grad", "kind": "nan", "times": 1}]})
            active = min(active, loop())
        faults.clear_plan()
        return base, active

    loop(2000)  # warm jit/op caches
    base, active = measure()
    if active > base * 1.05:
        base, active = measure(rounds=9)
    if active > base * 1.05:
        base, active = measure(rounds=11)
    assert active <= base * 1.15, (
        f"disabled-guardrail overhead {active / base - 1:.1%} "
        f"(no-plan {base:.3f}s, idle-guardrail {active:.3f}s)")
    assert guard.stats["sentinel_trips"] == 0


def test_run_tier1_script_matches_roadmap_gate():
    """Satellite: tools/run_tier1.sh is the tier-1 gate — it must carry
    the ROADMAP command's load-bearing pieces (pipefail, the slow-marker
    exclusion, the plugin pins, the DOTS_PASSED report) and be runnable."""
    import subprocess

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "run_tier1.sh")
    assert os.path.exists(path)
    assert os.access(path, os.X_OK)
    src = open(path).read()
    for piece in ("set -o pipefail", "not slow", "DOTS_PASSED",
                  "--continue-on-collection-errors", "no:cacheprovider",
                  "no:xdist", "no:randomly", "JAX_PLATFORMS=cpu"):
        assert piece in src, f"run_tier1.sh lost {piece!r}"
    r = subprocess.run(["bash", "-n", path], capture_output=True)
    assert r.returncode == 0, r.stderr
