"""Regression tests from the round-4 fresh-process idiom sweep: user-facing
API points the reference documents that broke or were missing here. Each
probe is the exact user spelling, several in fresh subprocesses (the
round-3 lesson: warm imports hide init-order bugs)."""
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu import np as mnp
from mxnet_tpu.base import MXNetError


def test_nd_waitall_is_callable_fresh_process():
    """Round-4 bug: a module-level `waitall = None` placeholder pre-empted
    __getattr__, so nd.waitall() raised TypeError in every process."""
    code = ("import mxnet_tpu as mx\n"
            "mx.nd.waitall()\n"
            "assert callable(mx.nd.waitall)\n"
            "print('WAITALL_OK')\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "WAITALL_OK" in r.stdout


def test_sym_group_multi_output():
    a, b = mx.sym.var("a"), mx.sym.var("b")
    g = mx.sym.Group([a.exp(), (a + b).tanh()])
    outs = g.eval(a=mnp.zeros((2,)), b=mnp.ones((2,)))
    assert len(outs) == 2
    onp.testing.assert_allclose(outs[0].asnumpy(), [1.0, 1.0])
    onp.testing.assert_allclose(outs[1].asnumpy(),
                                onp.tanh([1.0, 1.0]), rtol=1e-6)
    assert len(g.list_outputs()) == 2
    with pytest.raises(MXNetError):
        mx.sym.Group([])
    # infer_shape through a group (review finding r4)
    _, out_shapes, _ = g.infer_shape(a=(2,), b=(2,))
    assert out_shapes == [(2,), (2,)]
    # nested groups flatten: list_outputs length == eval length
    g2 = mx.sym.Group([g, a])
    assert len(g2.list_outputs()) == 3
    assert len(g2.eval(a=mnp.zeros((2,)), b=mnp.ones((2,)))) == 3
    # save/load round-trip keeps the multi-output contract
    import os
    import tempfile

    f = tempfile.mktemp(suffix=".json")
    g.save(f)
    g3 = mx.sym.load(f)
    os.unlink(f)
    assert len(g3.list_outputs()) == 2
    outs3 = g3.eval(a=mnp.zeros((2,)), b=mnp.ones((2,)))
    onp.testing.assert_allclose(outs3[0].asnumpy(), [1.0, 1.0])
    # initdesc registration survives (review finding r4: the decorator
    # must not be stolen by a class inserted above it)
    from mxnet_tpu.initializer import _REGISTRY

    assert "initdesc" in _REGISTRY and "mixed" in _REGISTRY


def test_init_mixed_dispatches_by_pattern():
    from mxnet_tpu.ndarray.ndarray import NDArray

    init = mx.init.Mixed(["bias", ".*"],
                         [mx.init.Constant(7.0), mx.init.Zero()])
    a = NDArray(onp.empty((4,), onp.float32))
    init("fc1_bias", a)
    onp.testing.assert_allclose(a.asnumpy(), [7.0] * 4)
    b = NDArray(onp.empty((4, 3), onp.float32))
    init("fc1_weight", b)
    onp.testing.assert_allclose(b.asnumpy(), onp.zeros((4, 3)))
    # first matching pattern wins, and the matched initializer's own
    # fill applies (no base-class role-suffix shortcut)
    with pytest.raises(MXNetError):
        mx.init.Mixed(["bias"], [mx.init.Zero()])("fc1_weight", b)

    # gluon precedence unchanged: a layer-level bias_initializer still
    # beats the block-level Mixed (reference semantics); Mixed governs
    # params without their own init — the weight here
    net = gluon.nn.Dense(4, in_units=3)
    net.initialize(init=mx.init.Mixed(
        ["weight", ".*"], [mx.init.Constant(3.0), mx.init.Zero()]))
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                onp.full((4, 3), 3.0))
    onp.testing.assert_allclose(net.bias.data().asnumpy(), onp.zeros(4))


def test_engine_bulk_api():
    prev = mx.engine.set_bulk_size(32)
    assert mx.engine.set_bulk_size(prev) == 32
    with mx.engine.bulk(10):
        x = nd.zeros((2,)) + 1
    assert x.asnumpy().tolist() == [1.0, 1.0]


def test_v1_hybrid_forward_blocks():
    """Gluon-v1 user blocks define hybrid_forward(self, F, x, <params>) —
    the dominant idiom of pre-2.x scripts (reference block.py:926
    _get_graph_v1). F is the legacy nd namespace (with F.np/F.npx for the
    dual-dispatch idiom); registered params arrive as kwargs."""
    from mxnet_tpu.gluon.parameter import Parameter

    class V1(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.dense = gluon.nn.Dense(4, in_units=3)

        def hybrid_forward(self, F, x):
            return F.relu(self.dense(x)) + F.ones_like(x[:, :1])

    net = V1()
    net.initialize()
    x = mnp.array(onp.random.randn(2, 3).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    onp.testing.assert_allclose(net(x).asnumpy(), eager, rtol=1e-5)

    class V1Param(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.weight = Parameter("weight", shape=(4, 3))

        def hybrid_forward(self, F, x, weight):
            return F.npx.fully_connected(x, weight, None, num_hidden=4,
                                         no_bias=True)

    net2 = V1Param()
    net2.initialize()
    out = net2(x).asnumpy()
    onp.testing.assert_allclose(
        out, x.asnumpy() @ net2.weight.data().asnumpy().T, rtol=1e-5)
    # trains: gradients flow through the kwarg-passed parameter
    from mxnet_tpu import autograd

    tr = gluon.Trainer(net2.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    with autograd.record():
        loss = gluon.loss.L2Loss()(net2(x), mnp.ones((2, 4))).mean()
    loss.backward()
    g = net2.weight.grad().asnumpy()
    assert (g != 0).any()
    tr.step(2)

    class NoForward(gluon.nn.HybridBlock):
        pass

    with pytest.raises(NotImplementedError):
        NoForward()(x)


def test_v1_hybrid_forward_deferred_shapes():
    """Deferred-shape v1 params resolve through the block's infer_shape
    (the reference 2.x _deferred_infer_shape contract); without it, the
    error says what to implement."""
    from mxnet_tpu.gluon.parameter import Parameter

    class Deferred(gluon.nn.HybridBlock):
        def __init__(self, units, **kw):
            super().__init__(**kw)
            self._units = units
            self.weight = Parameter("weight", shape=(units, 0))

        def infer_shape(self, x):
            self.weight.shape = (self._units, x.shape[1])

        def hybrid_forward(self, F, x, weight):
            return F.npx.fully_connected(x, weight, None,
                                         num_hidden=self._units,
                                         no_bias=True)

    net = Deferred(4)
    net.initialize()
    x = mnp.array(onp.ones((2, 3), "float32"))
    out = net(x)
    assert out.shape == (2, 4)
    assert net.weight.shape == (4, 3)

    class NoInfer(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.weight = Parameter("weight", shape=(4, 0))

        def hybrid_forward(self, F, x, weight):
            return x

    bad = NoInfer()
    bad.initialize()
    with pytest.raises(MXNetError, match="infer_shape"):
        bad(x)


def test_sym_dir_parity_with_nd():
    """Round-4 verdict Missing #5: the reference materializes every op on
    mx.sym at import (symbol/register.py:268) so dir()/tab-completion
    work; here __dir__ must enumerate the shared resolver surface."""
    sym_names = dir(mx.sym)
    nd_names = dir(mx.nd)
    assert len(sym_names) > 400
    # every op name nd enumerates, sym enumerates too (namespace symmetry;
    # the non-op module helpers differ by design)
    from mxnet_tpu.ops import legacy

    ops = set(legacy.all_names())
    assert ops <= set(sym_names)
    assert ops <= set(nd_names)


def test_sym_resolved_op_metadata_and_star_import_fresh_process():
    """Resolved constructors carry __name__/__doc__; `from mxnet_tpu
    import symbol` star-import exposes ops (lazy __all__)."""
    code = (
        "import mxnet_tpu as mx\n"
        "fc = mx.sym.FullyConnected\n"
        "assert fc.__name__ == 'FullyConnected'\n"
        "assert fc.__doc__\n"
        "assert len(dir(mx.sym)) > 400\n"
        "assert 'FullyConnected' in mx.sym.__all__\n"
        "ns = {}\n"
        "exec('from mxnet_tpu.symbol import *', ns)\n"
        "s = ns['FullyConnected'](ns['var']('x'), num_hidden=4)\n"
        # reference contract: missing layer params auto-create variables
        # (symbol/register.py behavior compose and simple_bind rely on)
        "assert s.list_arguments() == "
        "['x', 'fullyconnected0_weight', 'fullyconnected0_bias']\n"
        "print('SYM_DIR_OK')\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "SYM_DIR_OK" in r.stdout


def test_sym_random_is_symbolic_not_eager():
    """review finding: mx.sym.random must build graph nodes (resampled
    every forward), never return the eager numpy module baked at
    graph-build time."""
    s = mx.sym.var("x") + mx.sym.random.normal(0, 1, shape=(4, 4))
    ex = s.bind(mx.cpu(), {"x": mx.nd.zeros((4, 4))})
    a = ex.forward()
    b = ex.forward()
    a = (a[0] if isinstance(a, list) else a).asnumpy()
    b = (b[0] if isinstance(b, list) else b).asnumpy()
    assert not onp.allclose(a, b)  # resampled per forward, not constant
    assert mx.sym.linalg.gemm2.__name__ == "linalg_gemm2"
    with pytest.raises(AttributeError):
        mx.sym.fallback  # eager modules must not leak into sym


def test_sym_all_excludes_module_plumbing():
    """review finding: star-importing mx.sym must not bind json /
    MXNetError / __future__ features into the user's namespace."""
    al = mx.sym.__all__
    for bad in ("json", "MXNetError", "annotations"):
        assert bad not in al, bad
    for good in ("FullyConnected", "random", "linalg", "var", "Symbol"):
        assert good in al, good
