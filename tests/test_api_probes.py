"""Regression tests from the round-4 fresh-process idiom sweep: user-facing
API points the reference documents that broke or were missing here. Each
probe is the exact user spelling, several in fresh subprocesses (the
round-3 lesson: warm imports hide init-order bugs)."""
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu import np as mnp
from mxnet_tpu.base import MXNetError


def test_nd_waitall_is_callable_fresh_process():
    """Round-4 bug: a module-level `waitall = None` placeholder pre-empted
    __getattr__, so nd.waitall() raised TypeError in every process."""
    code = ("import mxnet_tpu as mx\n"
            "mx.nd.waitall()\n"
            "assert callable(mx.nd.waitall)\n"
            "print('WAITALL_OK')\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "WAITALL_OK" in r.stdout


def test_sym_group_multi_output():
    a, b = mx.sym.var("a"), mx.sym.var("b")
    g = mx.sym.Group([a.exp(), (a + b).tanh()])
    outs = g.eval(a=mnp.zeros((2,)), b=mnp.ones((2,)))
    assert len(outs) == 2
    onp.testing.assert_allclose(outs[0].asnumpy(), [1.0, 1.0])
    onp.testing.assert_allclose(outs[1].asnumpy(),
                                onp.tanh([1.0, 1.0]), rtol=1e-6)
    assert len(g.list_outputs()) == 2
    with pytest.raises(MXNetError):
        mx.sym.Group([])
    # infer_shape through a group (review finding r4)
    _, out_shapes, _ = g.infer_shape(a=(2,), b=(2,))
    assert out_shapes == [(2,), (2,)]
    # nested groups flatten: list_outputs length == eval length
    g2 = mx.sym.Group([g, a])
    assert len(g2.list_outputs()) == 3
    assert len(g2.eval(a=mnp.zeros((2,)), b=mnp.ones((2,)))) == 3
    # save/load round-trip keeps the multi-output contract
    import os
    import tempfile

    f = tempfile.mktemp(suffix=".json")
    g.save(f)
    g3 = mx.sym.load(f)
    os.unlink(f)
    assert len(g3.list_outputs()) == 2
    outs3 = g3.eval(a=mnp.zeros((2,)), b=mnp.ones((2,)))
    onp.testing.assert_allclose(outs3[0].asnumpy(), [1.0, 1.0])
    # initdesc registration survives (review finding r4: the decorator
    # must not be stolen by a class inserted above it)
    from mxnet_tpu.initializer import _REGISTRY

    assert "initdesc" in _REGISTRY and "mixed" in _REGISTRY


def test_init_mixed_dispatches_by_pattern():
    from mxnet_tpu.ndarray.ndarray import NDArray

    init = mx.init.Mixed(["bias", ".*"],
                         [mx.init.Constant(7.0), mx.init.Zero()])
    a = NDArray(onp.empty((4,), onp.float32))
    init("fc1_bias", a)
    onp.testing.assert_allclose(a.asnumpy(), [7.0] * 4)
    b = NDArray(onp.empty((4, 3), onp.float32))
    init("fc1_weight", b)
    onp.testing.assert_allclose(b.asnumpy(), onp.zeros((4, 3)))
    # first matching pattern wins, and the matched initializer's own
    # fill applies (no base-class role-suffix shortcut)
    with pytest.raises(MXNetError):
        mx.init.Mixed(["bias"], [mx.init.Zero()])("fc1_weight", b)

    # gluon precedence unchanged: a layer-level bias_initializer still
    # beats the block-level Mixed (reference semantics); Mixed governs
    # params without their own init — the weight here
    net = gluon.nn.Dense(4, in_units=3)
    net.initialize(init=mx.init.Mixed(
        ["weight", ".*"], [mx.init.Constant(3.0), mx.init.Zero()]))
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                onp.full((4, 3), 3.0))
    onp.testing.assert_allclose(net.bias.data().asnumpy(), onp.zeros(4))


def test_engine_bulk_api():
    prev = mx.engine.set_bulk_size(32)
    assert mx.engine.set_bulk_size(prev) == 32
    with mx.engine.bulk(10):
        x = nd.zeros((2,)) + 1
    assert x.asnumpy().tolist() == [1.0, 1.0]
