"""Reference docstring conformance: the reference's OWN ``>>>`` examples,
executed verbatim against this build's public surfaces.

Round-4 verdict, Next #3 generalized: the registry audit pins op *names*
and ``test_sparse_ctor_conformance`` pins the sparse ctor docstrings; this
suite sweeps whole reference source files through
:mod:`docstring_harness`, so *signatures and semantics* documented in the
reference are executed, not just resolvable.  Each parametrized case is
one docstring (examples inside a docstring share state).

``SKIPS`` is the documented divergence surface: every entry is either a
reference-side doctest defect (typos, missing ``...`` continuations, py2
reprs the comparator cannot normalize) or a justified redesign with its
rationale stated inline.  An entry may be a ``qualname`` (whole block) or
``(qualname, example_idx)``.

Legacy files run under ``mx.util.set_np(array=False)``, the reference's
default mode for the ``mx.nd`` era (this build defaults to numpy mode).
"""
import pytest

import mxnet_tpu as mx
from docstring_harness import (ExampleFailure, collect_blocks,
                               default_globs, reset_mode, run_block)


def _ndarray_extra_globs():
    from mxnet_tpu.ndarray.ndarray import indexing_key_expand_implicit_axes
    return {"indexing_key_expand_implicit_axes":
            indexing_key_expand_implicit_axes}


def _linalg_extra_globs():
    return {"LA": mx.np.linalg}


def _batchify_extra_globs():
    from mxnet_tpu.gluon.data import batchify
    return {"batchify": batchify, "Stack": batchify.Stack,
            "Pad": batchify.Pad, "Append": batchify.Append,
            "Group": batchify.Group, "AsList": batchify.AsList}


FILES = {
    "context.py": dict(legacy=True, skips={}, extra=None),
    "ndarray/ndarray.py": dict(
        legacy=True,
        extra=_ndarray_extra_globs,
        skips={
            "NDArray._sync_copyfrom":
                "reference docstring typo: the output line is prefixed "
                "'>> ' so doctest attaches the want to the assignment",
            "NDArray.dtype":
                "legacy .dtype returns the np.dtype instance, not the "
                "numpy scalar class; == comparisons with either spelling "
                "behave identically",
            "NDArray.astype": "same np.dtype-instance repr as NDArray.dtype",
            "NDArray.to_dlpack_for_read":
                "returns a live __dlpack__ exporter (keeps the buffer "
                "alive across consumers) instead of a consumed-once "
                "PyCapsule — documented redesign, mxnet_tpu/dlpack.py",
            "NDArray.to_dlpack_for_write": "same exporter redesign",
            ("indexing_key_expand_implicit_axes", 5):
                "malformed doctest in the reference: array literal "
                "continued without '...' markers",
            ("indexing_key_expand_implicit_axes", 6):
                "depends on the malformed example above",
        }),
    "ndarray/sparse.py": dict(
        legacy=True, extra=None,
        skips={
            "BaseSparseNDArray.astype":
                "np.dtype-instance repr, same as NDArray.dtype",
            ("CSRNDArray.__setitem__", 4):
                "reference docstring bug: assigns the zeros array into x "
                "yet documents x as all-ones; the reference's own "
                "implementation (sparse.py:437 value.copyto(self)) "
                "produces zeros",
            ("CSRNDArray.asscipy", 3):
                "scipy repr format drift: modern scipy prints 'with 0 "
                "stored elements and shape (2, 3)', the want predates it",
            "RowSparseNDArray":
                "reference docstring defect: the example block reads a "
                "variable `dense` never defined in any example",
            "RowSparseNDArray.__setitem__":
                "reference docstring bug: calls mx.nd.row_sparse(), a "
                "function that does not exist in the reference either "
                "(the ctor is row_sparse_array)",
            ("divide", 11): "reference docstring typo: 'mx.nd.sprase'",
            ("divide", 12): "continues the typo'd example",
        }),
    "numpy/multiarray.py": dict(
        legacy=False, extra=None,
        skips=dict({
            "empty": "uninitialized-memory contents are arbitrary by "
                     "contract (this build zero-fills)",
            "empty_like": "same arbitrary-memory want as empty",
            "divide": "reference docstring defect: the single example "
                      "reads an undefined variable x",
            ("tanh", 0): "complex input: the reference raises TypeError, "
                         "this build computes it (superset)",
            ("tanh", 1): "malformed doctest: unmatched ')'",
            ("fabs", 1): "malformed doctest in the reference",
            ("expm1", 2): "reference docstring bug: shows np.exp "
                          "returning expm1's values",
            ("rint", 1): "reference docstring bug: claims rint(1.5)=1 "
                         "while rint(-1.5)=-2 — no rounding rule does "
                         "both; numpy/jax round-half-even gives 2",
            ("arcsinh", 1): "reference docstring bug: values are not "
                            "arcsinh of any plausible input",
            ("arcsinh", 2): "reference docstring bug: claims arcsinh(1)=0",
            "logspace": "reference docstring defect: examples read "
                        "undefined start/stop/num variables",
            ("tile", 9): "reference want carries a stray extra value",
            ("split", 2): "reference doc bug: copied numpy's arange(8) "
                          "example output against its own arange(9) input",
            ("array_split", 2): "same copied-output bug as split",
            ("max", 7): "reference kernel ignores NaN in max/min "
                        "reductions (kernel accident its doc enshrines); "
                        "this build follows numpy: NaN propagates",
            ("min", 7): "same NaN-ignoring kernel divergence",
            ("amax", 7): "same NaN-ignoring kernel divergence",
            ("amin", 7): "same NaN-ignoring kernel divergence",
            ("argmin", 8): "argmax/argmin over NaN: numpy returns the "
                           "NaN position, the reference kernel skips it",
            ("indices", 3): "reference doc copy-paste bug: grid[1] shown "
                            "with grid[0]'s row-index output",
            ("bitwise_and", 2): "reference doc bug: shows [26, 5] for "
                                "14&13, 3&13 (correct: [12, 1], as "
                                "numpy's own docs show)",
            "equal": "malformed doctest: unmatched ')' cascades",
            "not_equal": "malformed doctest: unmatched ')' cascades",
            "greater": "malformed doctest: unmatched ')' cascades",
            "less": "malformed doctest: unmatched ')' cascades",
            "greater_equal": "malformed doctest: unmatched ')' cascades",
            "less_equal": "malformed doctest: unmatched ')' cascades",
            ("hsplit", 6): "reference want merged with following "
                           "narrative by a missing blank line",
            ("may_share_memory", 2): "column slices are copies in this "
                                     "functional build (non-contiguous "
                                     "keys never alias) — documented "
                                     "redesign, so may_share_memory is "
                                     "honestly False",
            ("sum", 5): "sum(dtype=int32) on floats: numpy/jax cast the "
                        "input first (0.5->0), the reference kernel "
                        "accumulates in float then casts",
            ("pad", 11): "reference doc drops numpy's pad_with example "
                         "definition it then calls",
            ("pad", 12): "continues the undefined pad_with example",
            **{("einsum", i): "timing-narrative examples (ms figures "
                              "as wants)" for i in range(27, 60)},
        }),
    ),
    "numpy/linalg.py": dict(
        legacy=False, extra=_linalg_extra_globs,
        skips={
            "matrix_rank":
                "reference doc calls np.matrix_rank, which exists only "
                "under np.linalg in the reference too — the example "
                "cannot run there either",
            ("inv", 1): "reference doc shows LA.inv's output under the "
                        "preceding array-construction line",
            ("eigvals", 8): "eigenvalue order is unspecified; the values "
                            "match as a set ([-1, 1] vs [1, -1])",
            "eigvalsh": "malformed doctest: array literal continued "
                        "without '...' markers",
            "eig": "same malformed array-literal doctest",
            "eigh": "same malformed array-literal doctest",
        }),
    "numpy/random.py": dict(
        legacy=False, extra=None,
        skips={
            "weibull": "malformed doctest: '(' never closed",
            "pareto": "malformed doctest: '(' never closed",
            "power": "malformed doctest: '(' never closed",
        }),
    "initializer.py": dict(
        legacy=True, extra=None,
        skips={
            "register": "reference example decorates with a bare `alias` "
                        "name and calls block.initialize on a `block` "
                        "defined only in prose",
            "Mixed": "example references a `block` defined only in prose",
            "Zero": "example references a Module-API `module` object "
                    "defined only in prose",
            "One": "same prose-only `module` object",
            "Uniform": "same prose-only `module` object",
            "Normal": "same prose-only `module` object",
        }),
    "ndarray/random.py": dict(legacy=True, extra=None, skips={}),
    "ndarray/contrib.py": dict(
        legacy=True, extra=None,
        skips={
            ("rand_zipfian", 2):
                "reference docstring predates the *num_sampled factor "
                "its own code applies to expected_count_true "
                "(contrib.py:91: exp_count formula x4 vs doc 0.1245)",
            ("rand_zipfian", 3): "same stale expected-count figures",
            ("rand_zipfian", 4): "same stale expected-count figures",
        }),
    "util.py": dict(
        legacy=False, extra=None,
        skips={
            "set_np_shape":
                "documented redesign: this build is numpy-native, the "
                "shape flag defaults ON (util.py module docstring)",
            "is_np_shape": "same np-native default",
            "set_np": "same np-native default",
        }),
    "gluon/data/batchify.py": dict(
        legacy=False, extra=_batchify_extra_globs, skips={}),
    "symbol/symbol.py": dict(
        legacy=True, extra=None,
        skips={
            "Symbol.__neg__":
                "reference doc defects: the negation auto-name differs "
                "(_mulscalar vs negative) and later examples read a "
                "variable `b` no example defines",
            ("Symbol.list_arguments", 3):
                "reference doc defect: references the method without "
                "parentheses yet shows the call's result",
            ("Symbol.debug_str", 5):
                "debug_str emits this build's own dump format (node "
                "order/attr layout differ; content equivalent)",
        }),
    "gluon/metric.py": dict(
        legacy=False, extra=None,
        skips={
            "CompositeEvalMetric":
                "malformed doctest in the reference: for-loop body "
                "continued without '...' markers; subsequent examples "
                "are its orphaned continuation lines",
            ("TopKAccuracy", 6):
                "reference docstring predates the '_%d' name suffix its "
                "own __init__ appends (reference metric.py:472)",
            "MCC": "malformed doctest: array literals continued without "
                   "'...' markers ('(' never closed), cascading into "
                   "every later example of the block",
            "PCC": "same malformed array-literal doctest as MCC",
        }),
}


def _cases():
    for relpath, cfg in FILES.items():
        for qn, exs in collect_blocks(relpath):
            yield pytest.param(relpath, qn, exs, cfg,
                               id=f"{relpath}::{qn}")


@pytest.mark.parametrize("relpath,qualname,examples,cfg", _cases())
def test_reference_docstring(relpath, qualname, examples, cfg):
    skips = cfg["skips"]
    if qualname in skips:
        pytest.skip(skips[qualname])
    skip_idx = {idx for (qn, idx) in
                [k for k in skips if isinstance(k, tuple)] if qn == qualname}
    globs = default_globs()
    if cfg["extra"] is not None:
        globs.update(cfg["extra"]())
    reset_mode(cfg["legacy"])
    try:
        run_block(examples, globs, skip_idx=skip_idx)
    except ExampleFailure as e:
        pytest.fail(f"{relpath}::{qualname}: {e}")
    finally:
        reset_mode(legacy=False)
