"""Reference docstring conformance: the reference's OWN ``>>>`` examples,
executed verbatim against this build's public surfaces.

Round-4 verdict, Next #3 generalized: the registry audit pins op *names*
and ``test_sparse_ctor_conformance`` pins the sparse ctor docstrings; this
suite sweeps whole reference source files through
:mod:`docstring_harness`, so *signatures and semantics* documented in the
reference are executed, not just resolvable.  Each parametrized case is
one docstring (examples inside a docstring share state).

``SKIPS`` is the documented divergence surface: every entry is either a
reference-side doctest defect (typos, missing ``...`` continuations, py2
reprs the comparator cannot normalize) or a justified redesign with its
rationale stated inline.  An entry may be a ``qualname`` (whole block) or
``(qualname, example_idx)``.

Legacy files run under ``mx.util.set_np(array=False)``, the reference's
default mode for the ``mx.nd`` era (this build defaults to numpy mode).
"""
import pytest

import mxnet_tpu as mx
from docstring_harness import (ExampleFailure, collect_blocks,
                               default_globs, run_block)


def _ndarray_extra_globs():
    from mxnet_tpu.ndarray.ndarray import indexing_key_expand_implicit_axes
    return {"indexing_key_expand_implicit_axes":
            indexing_key_expand_implicit_axes}


FILES = {
    "context.py": dict(legacy=True, skips={}, extra=None),
    "ndarray/ndarray.py": dict(
        legacy=True,
        extra=_ndarray_extra_globs,
        skips={
            "NDArray._sync_copyfrom":
                "reference docstring typo: the output line is prefixed "
                "'>> ' so doctest attaches the want to the assignment",
            "NDArray.dtype":
                "legacy .dtype returns the np.dtype instance, not the "
                "numpy scalar class; == comparisons with either spelling "
                "behave identically",
            "NDArray.astype": "same np.dtype-instance repr as NDArray.dtype",
            "NDArray.to_dlpack_for_read":
                "returns a live __dlpack__ exporter (keeps the buffer "
                "alive across consumers) instead of a consumed-once "
                "PyCapsule — documented redesign, mxnet_tpu/dlpack.py",
            "NDArray.to_dlpack_for_write": "same exporter redesign",
            ("indexing_key_expand_implicit_axes", 5):
                "malformed doctest in the reference: array literal "
                "continued without '...' markers",
            ("indexing_key_expand_implicit_axes", 6):
                "depends on the malformed example above",
        }),
}


def _cases():
    for relpath, cfg in FILES.items():
        for qn, exs in collect_blocks(relpath):
            yield pytest.param(relpath, qn, exs, cfg,
                               id=f"{relpath}::{qn}")


@pytest.mark.parametrize("relpath,qualname,examples,cfg", _cases())
def test_reference_docstring(relpath, qualname, examples, cfg):
    skips = cfg["skips"]
    if qualname in skips:
        pytest.skip(skips[qualname])
    skip_idx = {idx for (qn, idx) in
                [k for k in skips if isinstance(k, tuple)] if qn == qualname}
    globs = default_globs()
    if cfg["extra"] is not None:
        globs.update(cfg["extra"]())
    prev = None
    if cfg["legacy"]:
        prev = mx.util.set_np(array=False)
    try:
        run_block(examples, globs, skip_idx=skip_idx)
    except ExampleFailure as e:
        pytest.fail(f"{relpath}::{qualname}: {e}")
    finally:
        if cfg["legacy"]:
            mx.util.set_np(array=prev)
