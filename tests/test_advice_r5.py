"""Regression tests for the ADVICE round-5 findings fixed in the
telemetry PR: Convolution shape inference (dilate/num_group), the
fromjson/tojson round-trip, set_np(dtype=True) scalar creation,
NDArray.__getattr__ restriction, and host-side multinomial."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mnp
from mxnet_tpu import sym, util
from mxnet_tpu.base import MXNetError


# -- Convolution shape inference: dilate + num_group ----------------------

def test_conv_infer_shape_dilate():
    """k_eff = dilate*(k-1)+1: a dilated conv feeding FC must infer the
    FC weight from the DILATED output shape (ADVICE r5 #1)."""
    data = sym.var("data")
    c = sym.Convolution(data, num_filter=6, kernel=(3, 3), dilate=(2, 2))
    fc = sym.FullyConnected(sym.Flatten(c), num_hidden=3)
    shapes, outs = fc._infer_missing_arg_shapes({"data": (1, 4, 8, 8)})
    # k_eff = 2*(3-1)+1 = 5 -> spatial (8-5)//1+1 = 4
    assert outs == [(1, 3)]
    fc_weight = [n for n in shapes if n.endswith("_weight")
                 and "fullyconnected" in n]
    assert shapes[fc_weight[0]] == (3, 6 * 4 * 4)


def test_conv_infer_shape_num_group():
    """Grouped conv weight is (num_filter, C//num_group) + kernel."""
    data = sym.var("data")
    c = sym.Convolution(data, num_filter=6, kernel=(3, 3), num_group=2)
    shapes, outs = c._infer_missing_arg_shapes({"data": (2, 4, 8, 8)})
    w = [n for n in shapes if n.endswith("_weight")][0]
    assert shapes[w] == (6, 2, 3, 3)
    assert outs == [(2, 6, 6, 6)]


def test_conv_dilated_grouped_simple_bind_executes():
    net = sym.FullyConnected(
        sym.Flatten(sym.Convolution(sym.var("data"), num_filter=4,
                                    kernel=(3, 3), dilate=(2, 2),
                                    num_group=2)),
        num_hidden=2)
    exe = net.simple_bind(data=(1, 4, 9, 9))
    (out,) = exe.forward()
    assert out.shape == (1, 2)


# -- fromjson consumes this build's own tojson ----------------------------

def test_fromjson_roundtrips_default_tojson():
    """sym.fromjson(net.tojson()) — the reference round-trip idiom — must
    accept the default (tpu v2) format (ADVICE r5 #2)."""
    net = sym.FullyConnected(sym.var("x"), num_hidden=5)
    rt = sym.fromjson(net.tojson())
    assert rt.list_arguments() == net.list_arguments()
    assert rt._op == net._op


def test_fromjson_roundtrip_evaluates_identically():
    a = sym.var("a")
    net = sym.FullyConnected(a * 2.0 + 1.0, num_hidden=3)
    rt = sym.fromjson(net.tojson())
    names = net.list_arguments()
    args = {names[0]: mnp.ones((2, 4)),
            names[1]: mnp.ones((3, 4)) * 0.1,
            names[2]: mnp.zeros((3,))}
    got = rt.eval(**args)[0].asnumpy()
    want = net.eval(**args)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_fromjson_still_reads_nnvm_format():
    net = sym.FullyConnected(sym.var("x"), num_hidden=5)
    rt = sym.fromjson(net.tojson(fmt="nnvm"))
    assert rt.list_arguments() == net.list_arguments()


# -- set_np(dtype=True) python float scalars ------------------------------

def test_set_np_dtype_scalar_and_sequence_agree():
    prev = util.set_np_default_dtype(True)
    try:
        assert mnp.array(1.5).dtype == np.float64
        assert mnp.array([1.5]).dtype == np.float64
    finally:
        util.set_np_default_dtype(prev)
    # default mode: both float32
    assert mnp.array(1.5).dtype == np.float32
    assert mnp.array([1.5]).dtype == np.float32


# -- NDArray.__getattr__ restricted to the op table -----------------------

def test_getattr_typo_raises_attribute_error():
    x = mnp.ones((3,))
    with pytest.raises(AttributeError):
        x.arrray  # pylint: disable=pointless-statement
    # namespace utilities / creation ops must not bind as methods
    for bad in ("array", "zeros", "arange", "empty", "random_uniform"):
        with pytest.raises(AttributeError):
            getattr(x, bad)


def test_getattr_still_resolves_registered_ops():
    x = mnp.ones((2, 3))
    np.testing.assert_allclose(x.exp().asnumpy(), np.exp(np.ones((2, 3))),
                               rtol=1e-6)
    assert x.relu().shape == (2, 3)
    assert x.log_softmax().shape == (2, 3)
    # legacy FUNCS table entries keep working
    assert x.slice_axis(axis=1, begin=0, end=2).shape == (2, 2)
    # data-first creation-like ops stay methods (reference registry has them)
    assert float(x.zeros_like().asnumpy().sum()) == 0.0
    assert float(x.ones_like().asnumpy().sum()) == 6.0
    # deliberate refusals still raise with guidance, not AttributeError
    with pytest.raises(MXNetError):
        x.SoftmaxOutput()


# -- host-side multinomial ------------------------------------------------

def test_multinomial_host_side_sampling():
    counts = mnp.random.multinomial(20, [0.3, 0.7])
    assert counts.shape == (2,)
    assert int(counts.asnumpy().sum()) == 20

    batched = mnp.random.multinomial(8, [0.25, 0.25, 0.5], size=(4, 2))
    assert batched.shape == (4, 2, 3)
    np.testing.assert_array_equal(batched.asnumpy().sum(axis=-1), 8)


def test_multinomial_deterministic_under_seed():
    mx.random.seed(7)
    a = mnp.random.multinomial(100, [0.5, 0.5], size=(3,)).asnumpy()
    mx.random.seed(7)
    b = mnp.random.multinomial(100, [0.5, 0.5], size=(3,)).asnumpy()
    np.testing.assert_array_equal(a, b)
