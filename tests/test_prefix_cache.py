"""Conformance tests for cross-request KV reuse (PR-14,
``mxnet_tpu/serve/prefix_cache.py`` + the refcounted
``PagedKVPool``): allocator refcount invariants (shared assign,
incref/decref, atomic exhaustion, live pages never freed), radix-trie
semantics (full-page matching capped one token short of the prompt,
LRU reclaim that skips live and just-matched pages), and the headline
contract — greedy decode with the prefix cache ON is **token
identical** to cache-off on the ContinuousEngine, the paged Generator,
and the speculative stack, including under pool-pressure eviction,
with ``prefix_hit_rate > 0`` and zero recompiles.
"""
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.llama import get_llama
from mxnet_tpu.serve import ContinuousEngine, Generator, PagedKVPool, \
    PoolExhausted, PrefixCache, SpeculativeGenerator


def _tiny_llama(config="llama_tiny_test", **over):
    net = get_llama(config, **over)
    net.initialize()
    return net


@pytest.fixture(scope="module")
def net():
    return _tiny_llama()


def _row(pool, slot):
    return [int(p) for p in pool.table()[slot] if p != 0]


# ---------------------------------------------------------------------------
# allocator refcounts
# ---------------------------------------------------------------------------


class TestRefcounts:
    def test_shared_assign_and_staged_release(self, net):
        pool = PagedKVPool(net, num_slots=4, max_seq=64, page_size=16)
        pool.assign(0, 40)                       # 3 pages
        shared = _row(pool, 0)[:2]
        pool.assign_with_prefix(1, 40, shared)   # 2 shared + 1 fresh
        row1 = _row(pool, 1)
        assert row1[:2] == shared
        assert row1[2] not in _row(pool, 0)      # the tail page is private
        assert pool.refcount(shared[0]) == 2
        assert pool.pages_shared == 2
        # slot 0 releases: the shared pages stay live (slot 1 pins them)
        pool.release(0)
        assert pool.refcount(shared[0]) == 1
        assert pool.refcount(shared[1]) == 1
        pool.release(1)
        assert pool.pages_used == 0

    def test_incref_decref_and_live_page_never_freed(self, net):
        pool = PagedKVPool(net, num_slots=2, max_seq=64, page_size=16)
        pool.assign(0, 20)                       # 2 pages
        pages = _row(pool, 0)
        pool.incref(pages)                       # a trie adopting them
        pool.release(0)                          # slot gone, trie holds
        assert [pool.refcount(p) for p in pages] == [1, 1]
        assert pool.pages_used == 2              # NOT recycled
        pool.decref(pages)
        assert pool.pages_used == 0
        # decref below zero is corruption, loudly
        with pytest.raises(MXNetError, match="decref"):
            pool.decref(pages)

    def test_shared_prefix_page_must_be_live(self, net):
        pool = PagedKVPool(net, num_slots=2, max_seq=64, page_size=16)
        with pytest.raises(MXNetError, match="not all live"):
            pool.assign_with_prefix(0, 40, (3,))  # page 3 is on the free list

    def test_exhaustion_is_atomic_with_shared_pages(self, net):
        pool = PagedKVPool(net, num_slots=2, max_seq=64, page_size=16,
                           num_pages=4)          # null + 3 usable
        pool.assign(0, 20)                       # 2 pages
        shared = _row(pool, 0)
        # slot 1 wants 2 shared + 2 fresh but only 1 page is free:
        # nothing must be increfed or installed
        with pytest.raises(PoolExhausted):
            pool.assign_with_prefix(1, 64, shared)
        assert [pool.refcount(p) for p in shared] == [1, 1]
        assert _row(pool, 1) == []
        assert pool.exhausted_count == 1


# ---------------------------------------------------------------------------
# radix trie
# ---------------------------------------------------------------------------


class TestTrie:
    def test_match_insert_full_pages_only(self, net):
        pool = PagedKVPool(net, num_slots=2, max_seq=64, page_size=16)
        trie = PrefixCache(pool, name="t_trie")
        toks = list(range(100, 140))             # 40 tokens
        pool.assign(0, len(toks))
        pages = _row(pool, 0)
        assert trie.insert(toks, pages) == 2     # 40 // 16 full pages
        assert [pool.refcount(p) for p in pages[:2]] == [2, 2]
        m, got = trie.match(toks)
        assert m == 32 and list(got) == pages[:2]
        # page-aligned prompt: the match is capped one page short so at
        # least one token always prefills (its logits seed sampling)
        m, got = trie.match(toks[:32])
        assert m == 16 and len(got) == 1
        m, got = trie.match(toks[:16])
        assert m == 0 and not len(got)
        m, got = trie.match([1, 2, 3])
        assert m == 0 and not len(got)
        s = trie.stats()
        assert s["pages_held"] == 2 and s["hits"] == 2 and s["misses"] == 2

    def test_reclaim_lru_skips_live_and_excluded(self, net):
        pool = PagedKVPool(net, num_slots=2, max_seq=128, page_size=16)
        trie = PrefixCache(pool, name="t_reclaim")
        a, b = list(range(200, 232)), list(range(300, 332))
        pool.assign(0, 32)
        pa = _row(pool, 0)
        trie.insert(a, pa)
        pool.release(0)
        pool.assign(0, 32)
        pb = _row(pool, 0)
        trie.insert(b, pb)
        pool.release(0)
        trie.match(a)                            # touch a: b is now LRU
        # a live in-flight reference pins b's leaf against the sweep —
        # and an interior node is never evicted from under its child,
        # so the whole b chain survives: only a's chain (2 pages) frees
        pool.incref([pb[1]])
        assert trie.reclaim(4) == 2
        assert pool.refcount(pb[1]) == 2         # untouched
        assert trie.stats()["evictions"] == 2
        m, _ = trie.match(a)
        assert m == 0                            # a was swept (leaves first)
        pool.decref([pb[1]])
        # exclude: pages the admitting request just matched are immune,
        # while the now-unpinned b chain sweeps clean
        pool.assign(0, 32)
        pc = _row(pool, 0)
        trie.insert(list(range(400, 432)), pc)
        pool.release(0)
        assert trie.reclaim(8, exclude=set(pc)) == 2
        assert trie.pages_held == 2


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------


def _engine(net, prefix_on, **over):
    kw = dict(max_seq=64, num_slots=2, page_size=8, prefill_chunk=8,
              decode_path="baseline", prefix_cache=prefix_on,
              max_queue=64, name=f"px_eng_{int(bool(prefix_on))}")
    kw.update(over)
    return ContinuousEngine(net, **kw)


def _drive(eng, prompts, max_new=6):
    first = eng.submit(prompts[0], max_new_tokens=max_new).result(60)
    futs = [eng.submit(p, max_new_tokens=max_new) for p in prompts[1:]]
    return [first["tokens"]] + [f.result(60)["tokens"] for f in futs]


class TestEnginePrefix:
    def test_on_off_token_identity_and_hit_rate(self, net):
        system = list(range(3, 23))              # 20-token shared prompt
        prompts = [system + [40 + i, 60 + i] for i in range(6)]
        with _engine(net, False) as off:
            ref = _drive(off, prompts)
            off.assert_no_recompiles()
        with _engine(net, True) as on:
            got = _drive(on, prompts)
            on.assert_no_recompiles()
            snap = on.metrics.snapshot()
            st = on.stats()
        assert got == ref
        assert snap["prefix_hit_rate"] > 0
        assert snap["prefix_tokens_skipped"] > 0
        # after every request retired, the only non-free pages are the
        # trie's — nothing leaks past the refcounts
        assert st["pool"]["pages_owned"] == 0
        assert st["pool"]["pages_used"] == st["prefix"]["pages_held"]

    def test_eviction_pressure_keeps_outputs_identical(self, net):
        # zero-headroom pool (exactly the exhaustion-free floor): every
        # trie-held page past the current match must be LRU-swept at
        # admission instead of 503ing, and outputs must not move
        families = [list(range(3, 19)), list(range(50, 66)),
                    list(range(80, 96))]
        prompts = [fam + [100 + 7 * i + j for j in range(3)]
                   for i, fam in enumerate(families * 4)]
        # 8 usable pages = exactly two live 4-page budgets: any page the
        # trie retains past the current match MUST be swept at admission
        kw = dict(num_pages=9)
        with _engine(net, False, **kw) as off:
            ref = _drive(off, prompts)
        with _engine(net, True, **kw) as on:
            got = _drive(on, prompts)
            on.assert_no_recompiles()
            st = on.stats()
        assert got == ref                        # every future resolved OK
        assert st["prefix"]["hits"] > 0
        assert st["prefix"]["evictions"] > 0     # pressure really swept

    def test_in_flight_pages_survive_eviction_pressure(self, net):
        # a slow request decodes while later admissions sweep the trie:
        # its shared pages are pinned by the pool refcount, so its
        # output must equal the unshared reference
        shared = list(range(3, 19))
        slow = shared + [200]
        with _engine(net, False, num_pages=17) as off:
            want = off.submit(slow, max_new_tokens=24).result(60)["tokens"]
        with _engine(net, True, num_pages=17) as on:
            on.submit(slow, max_new_tokens=2).result(60)  # seed the trie
            f = on.submit(slow, max_new_tokens=24)        # shares 2 pages
            churn = [on.submit(list(range(50 + 11 * i, 66 + 11 * i)),
                               max_new_tokens=2) for i in range(6)]
            for c in churn:
                c.result(60)
            assert f.result(60)["tokens"] == want
            on.assert_no_recompiles()


# ---------------------------------------------------------------------------
# generator / speculative integration
# ---------------------------------------------------------------------------


class TestGeneratorPrefix:
    def test_paged_generator_prefix_identity(self, net):
        prompts = [list(range(3, 23)) + [40 + i] for i in range(2)]
        ref_gen = Generator(net, max_seq=64, batch_buckets=(2,),
                            prompt_buckets=(8, 16, 32),
                            decode_path="baseline", name="px_gen_off")
        ref, _ = ref_gen.generate(prompts, max_new_tokens=6)
        gen = Generator(net, max_seq=64, batch_buckets=(2,),
                        prompt_buckets=(8, 16, 32), decode_path="baseline",
                        prefix_cache=True, page_size=8, name="px_gen_on")
        gen.warmup()
        first, _ = gen.generate(prompts, max_new_tokens=6)  # seeds trie
        again, _ = gen.generate(prompts, max_new_tokens=6)  # hits it
        assert first == ref and again == ref
        gen.assert_no_recompiles()
        trie = next(iter(gen._prefix.values()))
        assert trie.stats()["hits"] > 0

    def test_prefix_requires_paged(self, net):
        with pytest.raises(MXNetError, match="paged"):
            Generator(net, max_seq=64, batch_buckets=(1,),
                      prompt_buckets=(8,), paged=False, prefix_cache=True,
                      name="px_gen_bad")

    def test_speculative_prefix_identity(self, net):
        draft = _tiny_llama(num_layers=1)
        prompts = [list(range(3, 23)) + [40 + i] for i in range(2)]
        ref_spec = SpeculativeGenerator(
            net, draft, k=2, max_seq=64, batch_buckets=(2,),
            prompt_buckets=(8, 16, 32), name="px_spec_off")
        ref, _ = ref_spec.generate(prompts, max_new_tokens=6)
        spec = SpeculativeGenerator(
            net, draft, k=2, max_seq=64, batch_buckets=(2,),
            prompt_buckets=(8, 16, 32), prefix_cache=True, page_size=8,
            name="px_spec_on")
        first, _ = spec.generate(prompts, max_new_tokens=6)
        again, _ = spec.generate(prompts, max_new_tokens=6)
        assert first == ref and again == ref
        # draft and target each consult their own trie: the shared
        # system prompt prefills at most once per model
        for gen in (spec.target, spec.draft):
            trie = next(iter(gen._prefix.values()))
            assert trie.stats()["hits"] > 0
