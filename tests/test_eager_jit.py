"""Eager per-op jit cache (SURVEY §7 hard part 2: the `SetShapeType`
signature-cache role, done the XLA way — one compiled executable per
(op, static config), reused across imperative calls)."""
import contextlib

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine
from mxnet_tpu import np
from mxnet_tpu.ops import registry


@contextlib.contextmanager
def _no_bulk():
    """Pin deferred bulk dispatch off: these tests assert on the PER-OP
    jit cache, which a bulk segment legitimately bypasses (ops compile
    through engine._SEG_CACHE instead) — they must stay meaningful under
    the tier-1 MXNET_ENGINE_BULK_SIZE=16 second pass."""
    prev = engine.set_bulk_size(0)
    try:
        yield
    finally:
        engine.set_bulk_size(prev)


def _cache_delta(fn, *calls):
    before = registry.eager_jit_cache_size()
    outs = [fn(*c) for c in calls]
    return registry.eager_jit_cache_size() - before, outs


def test_repeat_op_hits_cache():
    with _no_bulk():
        a = np.array(onp.random.randn(8, 8).astype("float32"))
        registry._EAGER_JIT_CACHE.clear()
        np.tanh(a)
        n1 = registry.eager_jit_cache_size()
        assert n1 >= 1
        for _ in range(5):
            np.tanh(a)
        assert registry.eager_jit_cache_size() == n1  # no growth: hits
        out = np.tanh(a).asnumpy()
        onp.testing.assert_allclose(out, onp.tanh(a.asnumpy()), rtol=1e-6)


def test_distinct_static_config_distinct_entries():
    with _no_bulk():
        a = np.array(onp.random.randn(4, 6).astype("float32"))
        registry._EAGER_JIT_CACHE.clear()
        s0 = np.sum(a, axis=0)
        n1 = registry.eager_jit_cache_size()
        s1 = np.sum(a, axis=1)
        n2 = registry.eager_jit_cache_size()
        assert n2 > n1  # axis is static config -> its own executable
        onp.testing.assert_allclose(s0.asnumpy(), a.asnumpy().sum(0),
                                    rtol=1e-6)
        onp.testing.assert_allclose(s1.asnumpy(), a.asnumpy().sum(1),
                                    rtol=1e-6)


def test_rng_ops_never_cached_and_stay_random():
    """Dropout draws a key per call; a cached trace would freeze the mask."""
    from mxnet_tpu.ops import nn as _nn

    a = np.ones((64, 64))
    with autograd.train_mode():
        d1 = _nn.dropout(a, p=0.5).asnumpy()
        d2 = _nn.dropout(a, p=0.5).asnumpy()
    assert (d1 != d2).any(), "dropout mask froze: RNG op was jit-cached"


def test_grad_through_cached_op():
    a = np.array(onp.random.randn(5, 5).astype("float32"))
    a.attach_grad()
    np.exp(a)  # populate cache
    with autograd.record():
        y = np.exp(a)
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                onp.exp(a.asnumpy()), rtol=1e-5)


def test_disable_flag():
    registry.set_eager_jit(False)
    try:
        registry._EAGER_JIT_CACHE.clear()
        a = np.array(onp.ones((3, 3), "float32"))
        np.tanh(a)
        assert registry.eager_jit_cache_size() == 0
    finally:
        registry.set_eager_jit(True)


def test_cached_vjp_matches_eager_backward():
    """A verified-cacheable op's backward runs through the compiled-vjp
    cache (registry._EAGER_BWD_CACHE); gradients must match the eager
    jax.vjp path bit-for-bit-ish across repeated steps."""
    from mxnet_tpu import gluon

    def run_steps(flag):
        import mxnet_tpu as mx

        registry.set_eager_jit(flag)
        registry._EAGER_JIT_CACHE.clear()
        registry._EAGER_BWD_CACHE.clear()
        mx.random.seed(11)  # identical init weights across both runs
        rng = onp.random.RandomState(7)
        net = gluon.nn.Dense(4)
        net.initialize()
        x = np.array(rng.randn(8, 6).astype("float32"))
        grads = []
        for _ in range(3):  # step 1 = first-encounter path, 2-3 = cached
            with autograd.record():
                l = (net(x) ** 2).sum()
            l.backward()
            grads.append(net.weight.grad().asnumpy().copy())
        return grads

    try:
        with _no_bulk():
            cached = run_steps(True)
            # the cached-vjp path must actually have been exercised
            assert len(registry._EAGER_BWD_CACHE) > 0
            eager = run_steps(False)
    finally:
        registry.set_eager_jit(True)
    for c, e in zip(cached, eager):
        onp.testing.assert_allclose(c, e, rtol=1e-5, atol=1e-6)


def test_cached_vjp_int_input_gets_no_cotangent():
    """float0 cotangents (int inputs) must not leak out of the compiled
    vjp — embedding-style gather: grad flows to the table, not indices."""
    emb = np.array(onp.random.randn(10, 4).astype("float32"))
    idx = np.array(onp.array([1, 3, 3], "int64"))
    emb.attach_grad()
    for _ in range(2):  # second pass hits the cached fwd + compiled vjp
        with autograd.record():
            y = np.take(emb, idx, axis=0)
        y.backward()
    g = emb.grad.asnumpy()
    assert g[3].sum() != 0 and g[0].sum() == 0
