"""Eager per-op jit cache (SURVEY §7 hard part 2: the `SetShapeType`
signature-cache role, done the XLA way — one compiled executable per
(op, static config), reused across imperative calls)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu import np
from mxnet_tpu.ops import registry


def _cache_delta(fn, *calls):
    before = registry.eager_jit_cache_size()
    outs = [fn(*c) for c in calls]
    return registry.eager_jit_cache_size() - before, outs


def test_repeat_op_hits_cache():
    a = np.array(onp.random.randn(8, 8).astype("float32"))
    registry._EAGER_JIT_CACHE.clear()
    np.tanh(a)
    n1 = registry.eager_jit_cache_size()
    assert n1 >= 1
    for _ in range(5):
        np.tanh(a)
    assert registry.eager_jit_cache_size() == n1  # no growth: cache hits
    out = np.tanh(a).asnumpy()
    onp.testing.assert_allclose(out, onp.tanh(a.asnumpy()), rtol=1e-6)


def test_distinct_static_config_distinct_entries():
    a = np.array(onp.random.randn(4, 6).astype("float32"))
    registry._EAGER_JIT_CACHE.clear()
    s0 = np.sum(a, axis=0)
    n1 = registry.eager_jit_cache_size()
    s1 = np.sum(a, axis=1)
    n2 = registry.eager_jit_cache_size()
    assert n2 > n1  # axis is static config -> its own executable
    onp.testing.assert_allclose(s0.asnumpy(), a.asnumpy().sum(0), rtol=1e-6)
    onp.testing.assert_allclose(s1.asnumpy(), a.asnumpy().sum(1), rtol=1e-6)


def test_rng_ops_never_cached_and_stay_random():
    """Dropout draws a key per call; a cached trace would freeze the mask."""
    from mxnet_tpu.ops import nn as _nn

    a = np.ones((64, 64))
    with autograd.train_mode():
        d1 = _nn.dropout(a, p=0.5).asnumpy()
        d2 = _nn.dropout(a, p=0.5).asnumpy()
    assert (d1 != d2).any(), "dropout mask froze: RNG op was jit-cached"


def test_grad_through_cached_op():
    a = np.array(onp.random.randn(5, 5).astype("float32"))
    a.attach_grad()
    np.exp(a)  # populate cache
    with autograd.record():
        y = np.exp(a)
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                onp.exp(a.asnumpy()), rtol=1e-5)


def test_disable_flag():
    registry.set_eager_jit(False)
    try:
        registry._EAGER_JIT_CACHE.clear()
        a = np.array(onp.ones((3, 3), "float32"))
        np.tanh(a)
        assert registry.eager_jit_cache_size() == 0
    finally:
        registry.set_eager_jit(True)
