"""Conformance tests for multi-tenant serving (PR-14,
``mxnet_tpu/serve/tenancy.py``): ``ModelRegistry`` routing by model
name, the ``MXNET_SERVE_MAX_MODELS`` residency budget with LRU
(idle-first) eviction, transparent reload of an evicted tenant with
token-identical output, PR-6 admission semantics passing through the
tenant's own engine (deadlines -> 504, priority classes), and the
``tenancy.*`` export surface.
"""
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models.llama import get_llama
from mxnet_tpu.serve import DeadlineExceeded, ModelRegistry, ServeError, \
    registry_stats


def _factory(seed=0):
    def build():
        mx.random.seed(seed)
        net = get_llama("llama_tiny_test")
        net.initialize()
        return net
    return build


def _registry(**over):
    kw = dict(max_models=1, name="t_reg", max_seq=48, num_slots=2,
              page_size=8, prefill_chunk=8, decode_path="baseline",
              prefix_cache=True)
    kw.update(over)
    return ModelRegistry(**kw)


PROMPT = [5, 9, 2, 7]


class TestRegistry:
    def test_routing_eviction_and_warm_reload_identity(self):
        with _registry() as reg:
            reg.load("a", factory=_factory(0))
            ra = reg.submit("a", PROMPT, max_new_tokens=4).result(60)
            assert len(ra["tokens"]) == 4
            # budget is 1: loading b evicts a (idle LRU victim)
            reg.load("b", factory=_factory(1))
            assert reg.resident() == ["b"]
            assert reg.get("a") is None          # evicted, factory kept
            s = reg.summary()
            assert s["evictions"] == 1 and s["known"] == 2
            # routing to the evicted tenant transparently reloads it —
            # same factory, same weights, token-identical output
            again = reg.submit("a", PROMPT, max_new_tokens=4).result(60)
            assert again["tokens"] == ra["tokens"]
            assert reg.resident() == ["a"]
            s = reg.summary()
            assert s["loads"] == 3 and s["evictions"] == 2
            assert s["kv_cache_bytes"]["a"] > 0

    def test_lru_order_and_touch(self):
        with _registry(max_models=2) as reg:
            reg.load("a", factory=_factory(0))
            reg.load("b", factory=_factory(1))
            reg.load("a")                        # touch: b is now LRU
            reg.load("c", factory=_factory(2))
            assert reg.resident() == ["a", "c"]

    def test_unknown_model_is_a_serve_error(self):
        with _registry() as reg:
            with pytest.raises(ServeError, match="unknown model"):
                reg.load("nope")
            with pytest.raises(ServeError, match="unknown model"):
                reg.submit("nope", PROMPT)

    def test_admission_semantics_pass_through(self):
        with _registry() as reg:
            reg.load("a", factory=_factory(0))
            # deadline -> 504 from the tenant engine, partial preserved
            fut = reg.submit("a", PROMPT, max_new_tokens=8,
                             priority="batch", deadline_ms=0.01)
            with pytest.raises(DeadlineExceeded):
                fut.result(60)
            # the engine still serves afterwards
            ok = reg.submit("a", PROMPT, max_new_tokens=2,
                            priority="interactive").result(60)
            assert len(ok["tokens"]) == 2

    def test_explicit_evict_and_close(self):
        reg = _registry()
        try:
            reg.load("a", factory=_factory(0))
            assert reg.evict("a") is True
            assert reg.evict("a") is False       # already cold
            assert reg.resident() == []
        finally:
            reg.close()
        with pytest.raises(ServeError, match="closed"):
            reg.load("a")

    def test_registry_stats_and_export_surface(self):
        with _registry(name="t_export") as reg:
            reg.load("a", factory=_factory(0))
            assert registry_stats()["t_export"]["resident"] == 1
            st = reg.stats()
            assert "models" in st and "a" in st["models"]
            from mxnet_tpu.profiler import export

            snap = export.snapshot()
            assert snap["tenancy.t_export.resident"] == 1
            assert "tenancy.t_export.kv_cache_bytes.a" in snap

    def test_max_models_validated(self):
        with pytest.raises(ServeError, match=">= 1"):
            ModelRegistry(max_models=0)
