"""Conformance tests for the inference serving subsystem
(``mxnet_tpu/serve/``): KV-cache decode parity, dynamic batching,
admission control, zero-recompile steady state, fault isolation, and the
serve metrics surface.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import numpy as mnp
from mxnet_tpu.models.llama import get_llama
from mxnet_tpu.resilience import faults
from mxnet_tpu.serve import (DynamicBatcher, Generator, InferenceSession,
                             KVCache, ServeError, ServeMetrics,
                             ServiceUnavailable, pick_bucket, sample_tokens)


def _tiny_llama(config="llama_tiny_test", **over):
    net = get_llama(config, **over)
    net.initialize()
    return net


@pytest.fixture
def no_faults():
    yield
    faults.clear_plan()


# ---------------------------------------------------------------------------
# KV-cache decode parity
# ---------------------------------------------------------------------------


class TestDecodeParity:
    def test_decode_matches_full_prefill_bitwise_12l(self):
        """THE acceptance invariant: >= 32 greedily generated tokens on
        the 12-layer llama config, each decode step's logits bitwise
        equal to re-running the full prefill (same cache path) over the
        whole prefix."""
        net = _tiny_llama("llama_serve_12l_test")
        max_seq = 64
        # the bitwise contract is the strict rung's; the fast rungs
        # (default decode_path) carry tolerance parity instead
        # (tests/test_decode_paths.py)
        gen = Generator(net, max_seq=max_seq, batch_buckets=(1,),
                        prompt_buckets=(max_seq,), decode_path="baseline")
        prompt = [3, 141, 59, 26, 5]
        n_new = 32

        tokens = list(prompt)
        lens = np.array([len(prompt)], np.int32)
        cache = KVCache.alloc(net, 1, max_seq)
        toks = np.zeros((1, max_seq), np.int32)
        toks[0, :len(prompt)] = prompt
        logits, cache = gen.prefill(toks, lens, cache)

        for step in range(n_new):
            nxt = int(np.argmax(logits.asnumpy()[0]))
            tokens.append(nxt)
            pos = np.array([len(tokens) - 1], np.int32)
            logits, cache = gen.decode_step(np.array([nxt], np.int32),
                                            pos, cache)
            # full prefill of the whole prefix, fresh cache, same bucket
            ref_cache = KVCache.alloc(net, 1, max_seq)
            ref_toks = np.zeros((1, max_seq), np.int32)
            ref_toks[0, :len(tokens)] = tokens
            ref_logits, _ = gen.prefill(
                ref_toks, np.array([len(tokens)], np.int32), ref_cache)
            a = logits.asnumpy()
            b = ref_logits.asnumpy()
            assert np.array_equal(a, b), (
                f"step {step}: decode logits diverge from full prefill "
                f"(max abs diff {np.abs(a - b).max()})")

    def test_cache_prefill_matches_standard_forward(self):
        """The cache path is numerically the same model as the training
        path: cache-prefill last-position logits ~= plain forward."""
        net = _tiny_llama()
        t = 6
        prompt = np.array([[7, 3, 250, 11, 99, 42]], np.int32)
        with autograd.predict_mode():
            ref = net(mnp.array(prompt)).asnumpy()[0, t - 1]
        gen = Generator(net, max_seq=16, batch_buckets=(1,),
                        prompt_buckets=(8,))
        cache = KVCache.alloc(net, 1, 16)
        toks = np.zeros((1, 8), np.int32)
        toks[0, :t] = prompt[0]
        logits, _ = gen.prefill(toks, np.array([t], np.int32), cache)
        np.testing.assert_allclose(logits.asnumpy()[0], ref,
                                   rtol=2e-4, atol=2e-4)

    def test_batched_mixed_length_decode_parity(self):
        """Rows with different prompt lengths share one decode executable;
        each row still bitwise-matches its own full prefill."""
        net = _tiny_llama()
        max_seq = 32
        gen = Generator(net, max_seq=max_seq, batch_buckets=(2,),
                        prompt_buckets=(max_seq,), decode_path="baseline")
        prompts = [[5, 6, 7], [9, 3, 4, 4, 8, 1, 2]]
        outs, _ = gen.generate(prompts, max_new_tokens=4, temperature=0.0)
        for i, p in enumerate(prompts):
            seq = list(p)
            for tok in outs[i]:
                ref_cache = KVCache.alloc(net, 2, max_seq)
                ref_toks = np.zeros((2, max_seq), np.int32)
                ref_toks[i, :len(seq)] = seq
                ref_toks[1 - i, 0] = 1
                lens = np.ones(2, np.int32)
                lens[i] = len(seq)
                ref_logits, _ = gen.prefill(ref_toks, lens, ref_cache)
                assert int(np.argmax(ref_logits.asnumpy()[i])) == tok
                seq.append(tok)

    def test_generate_greedy_deterministic(self):
        net = _tiny_llama()
        gen = Generator(net, max_seq=32, batch_buckets=(1,),
                        prompt_buckets=(8,))
        o1, _ = gen.generate([[5, 6, 7]], max_new_tokens=6)
        o2, _ = gen.generate([[5, 6, 7]], max_new_tokens=6)
        assert o1 == o2
        assert len(o1[0]) == 6

    def test_generate_skips_trailing_decode_step(self):
        """Sampling token k uses the logits from step k-1, so max_new
        tokens need only max_new - 1 decode steps — the final step's
        logits would be discarded."""
        net = _tiny_llama()
        gen = Generator(net, max_seq=32, batch_buckets=(1,),
                        prompt_buckets=(8,))
        outs, info = gen.generate([[4, 5]], max_new_tokens=4)
        assert len(outs[0]) == 4
        assert info["decode_steps"] == 3

    def test_kv_cache_nbytes_tracks_dtype(self):
        net = _tiny_llama()
        f32 = KVCache.alloc(net, 1, 16)
        bf16 = KVCache.alloc(net, 1, 16, dtype="bfloat16")
        assert bf16.nbytes() * 2 == f32.nbytes()

    def test_kv_cache_geometry(self):
        net = _tiny_llama()
        cache = KVCache.alloc(net, 2, 16)
        assert cache.num_layers == 2
        assert cache.batch == 2
        # kv_heads=2, head_dim=64/4=16
        assert cache.layer(0).k.shape == (2, 2, 16, 16)
        flat = cache.flat()
        assert len(flat) == 4
        rt = KVCache.from_flat(flat, 16)
        assert rt.max_seq == 16 and rt.num_layers == 2


# ---------------------------------------------------------------------------
# Sampling
# ---------------------------------------------------------------------------


class TestSampling:
    def test_greedy_is_argmax(self):
        logits = mnp.array(np.array([[0.1, 3.0, -1.0], [9.0, 0.0, 1.0]],
                                    np.float32))
        out = sample_tokens(logits, temperature=0.0)
        assert out.tolist() == [1, 0]

    def test_topk_restricts_support(self):
        mx.random.seed(3)
        logits = mnp.array(
            np.array([[5.0, 4.0, -50.0, -50.0]] * 8, np.float32))
        for _ in range(16):
            out = sample_tokens(logits, temperature=1.0, top_k=2)
            assert set(out.tolist()) <= {0, 1}

    def test_seeded_sampling_reproduces(self):
        logits = mnp.array(np.random.randn(4, 32).astype(np.float32))
        mx.random.seed(11)
        a = sample_tokens(logits, temperature=0.8)
        mx.random.seed(11)
        b = sample_tokens(logits, temperature=0.8)
        assert a.tolist() == b.tolist()


# ---------------------------------------------------------------------------
# Zero recompiles after warmup
# ---------------------------------------------------------------------------


class TestNoRecompiles:
    def test_mixed_traffic_zero_recompiles_after_warmup(self):
        """100 mixed-length requests after warmup: signature_count() is
        frozen and every call lands as a serve-path cache hit."""
        net = _tiny_llama()
        gen = Generator(net, max_seq=32, batch_buckets=(1, 2),
                        prompt_buckets=(8, 16))
        gen.warmup()
        sigs = gen.session.signature_count()
        hits0 = gen.session.cache_stats()["serve_hits"]
        rng = np.random.RandomState(0)
        for i in range(100):
            n_prompts = int(rng.randint(1, 3))
            prompts = [rng.randint(1, 255,
                                   size=int(rng.randint(1, 15))).tolist()
                       for _ in range(n_prompts)]
            gen.generate(prompts, max_new_tokens=2)
        gen.assert_no_recompiles()
        stats = gen.session.cache_stats()
        assert stats["signatures"] == sigs
        # every post-warmup execution was a warm serve hit
        assert stats["serve_hits"] > hits0
        assert stats["misses"] == sigs  # only warmup compiled

    def test_warmup_compiles_full_lattice(self):
        net = _tiny_llama()
        gen = Generator(net, max_seq=32, batch_buckets=(1, 2),
                        prompt_buckets=(8, 16))
        info = gen.warmup()
        # per batch bucket: one prefill per prompt bucket + one decode
        assert info["signatures"] == 2 * (2 + 1)

    def test_assert_no_recompiles_catches_cold_bucket(self):
        net = _tiny_llama()
        gen = Generator(net, max_seq=32, batch_buckets=(1, 2),
                        prompt_buckets=(8,))
        # warm only bucket (1, 8)
        gen.generate([[4, 5]], max_new_tokens=1)
        gen.session.freeze_signatures()
        gen.generate([[4, 5], [6]], max_new_tokens=1)  # cold batch=2
        with pytest.raises(Exception, match="recompiled after warmup"):
            gen.assert_no_recompiles()

    def test_bucket_keys_exposed(self):
        net = _tiny_llama()
        gen = Generator(net, max_seq=16, batch_buckets=(1,),
                        prompt_buckets=(8,))
        gen.generate([[4, 5]], max_new_tokens=2)  # prefill + one decode
        keys = gen.session._op.bucket_keys()
        assert len(keys) == gen.session.signature_count() == 2


# ---------------------------------------------------------------------------
# InferenceSession generic bucketing
# ---------------------------------------------------------------------------


def _make_classifier():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize()
    return net


class TestInferenceSession:
    def test_pick_bucket(self):
        assert pick_bucket(1, (1, 2, 4)) == 1
        assert pick_bucket(3, (1, 2, 4)) == 4
        with pytest.raises(Exception, match="exceeds the largest"):
            pick_bucket(5, (1, 2, 4))

    def test_predict_pads_and_slices(self):
        net = _make_classifier()
        sess = InferenceSession(net, batch_buckets=(4,))
        x = np.random.randn(3, 8).astype(np.float32)
        out = sess.predict(x)
        assert out.shape == (3, 4)
        with autograd.predict_mode():
            ref = net(mnp.array(x)).asnumpy()
        np.testing.assert_array_equal(out.asnumpy(), ref)

    def test_predict_unpads_seq_axis(self):
        """A seq-bucketed predict must not hand back pad-position rows:
        outputs that preserve the padded seq extent are sliced to the
        real length."""
        net = _tiny_llama()
        sess = InferenceSession(net, batch_buckets=(2,), seq_buckets=(16,))
        x = np.random.randint(1, 255, size=(2, 10)).astype(np.int32)
        out = sess.predict(x)
        assert out.shape[:2] == (2, 10)
        # same executable, unsliced: predict must return its [:, :10]
        ref = sess.run(mnp.array(np.pad(x, [(0, 0), (0, 6)]))).asnumpy()
        assert ref.shape[:2] == (2, 16)
        np.testing.assert_array_equal(out.asnumpy(), ref[:, :10])

    def test_warmup_then_zero_recompiles(self):
        net = _make_classifier()
        sess = InferenceSession(net, batch_buckets=(1, 2, 4))
        sess.warmup(np.random.randn(1, 8).astype(np.float32))
        for b in (1, 2, 3, 4):
            sess.predict(np.random.randn(b, 8).astype(np.float32))
        sess.assert_no_recompiles()
        assert sess.cache_stats()["serve_hits"] >= 4

    def test_breaker_opens_and_fast_rejects(self, no_faults):
        net = _make_classifier()
        sess = InferenceSession(net, batch_buckets=(1,), name="brk")
        sess.warmup(np.random.randn(1, 8).astype(np.float32))
        faults.install_plan({"seed": 0, "rules": [
            {"site": "serve:execute", "kind": "fatal", "times": 3}]})
        x = np.random.randn(1, 8).astype(np.float32)
        for _ in range(3):
            with pytest.raises(Exception):
                sess.predict(x)
        assert sess.breaker.state == "open"
        with pytest.raises(ServiceUnavailable, match="circuit breaker"):
            sess.predict(x)
        faults.clear_plan()
        # cooldown: open denials advance the call count, then half-open
        for _ in range(16):
            try:
                sess.predict(x)
            except ServiceUnavailable:
                continue
            break
        assert sess.breaker.state == "closed"
        assert sess.predict(x).shape == (1, 4)


# ---------------------------------------------------------------------------
# DynamicBatcher
# ---------------------------------------------------------------------------


class TestDynamicBatcher:
    def test_flush_on_full(self):
        seen = []

        def runner(batch):
            seen.append(len(batch))
            return batch

        with DynamicBatcher(runner, max_batch_size=4, timeout_ms=10_000.0,
                            max_queue=64) as b:
            futs = [b.submit(i) for i in range(4)]
            assert [f.result(timeout=5) for f in futs] == [0, 1, 2, 3]
        assert seen == [4]  # one full batch, no deadline needed

    def test_flush_on_deadline(self):
        seen = []

        def runner(batch):
            seen.append(len(batch))
            return batch

        with DynamicBatcher(runner, max_batch_size=64, timeout_ms=30.0,
                            max_queue=64) as b:
            t0 = time.monotonic()
            f = b.submit("only")
            assert f.result(timeout=5) == "only"
            waited = time.monotonic() - t0
        assert seen == [1]
        assert waited >= 0.02  # the deadline, not an immediate flush

    def test_fast_reject_when_queue_full(self):
        release = threading.Event()

        def runner(batch):
            release.wait(5)
            return batch

        b = DynamicBatcher(runner, max_batch_size=1, timeout_ms=0.0,
                           max_queue=2, name="rej")
        try:
            futs = [b.submit(0)]
            deadline = time.monotonic() + 5
            while b.queue_depth() > 0:  # wait until 0 is in flight
                assert time.monotonic() < deadline
                time.sleep(0.005)
            futs += [b.submit(i) for i in (1, 2)]  # fills the queue
            with pytest.raises(ServiceUnavailable, match="queue is full"):
                b.submit(99)
            assert b.metrics.rejects == 1
            release.set()
            for f in futs:
                f.result(timeout=5)
        finally:
            release.set()
            b.close()

    def test_runner_error_is_per_request_not_fatal(self):
        calls = {"n": 0}

        def runner(batch):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom")
            return batch

        with DynamicBatcher(runner, max_batch_size=2, timeout_ms=5.0,
                            max_queue=8) as b:
            f1 = b.submit("a")
            with pytest.raises(RuntimeError, match="boom"):
                f1.result(timeout=5)
            # the server survived: next request succeeds
            f2 = b.submit("b")
            assert f2.result(timeout=5) == "b"
        assert b.metrics.errors >= 1

    def test_injected_dispatch_fault_is_per_request_error(self, no_faults):
        """An op:dispatch fault inside the runner surfaces on the affected
        request's future; the flusher keeps serving."""
        faults.install_plan({"seed": 0, "rules": [
            {"site": "op:dispatch", "kind": "transient", "at": [0]}]})

        def runner(batch):
            x = mnp.array(np.asarray(batch, np.float32))
            return (x * 2).asnumpy().tolist()

        with DynamicBatcher(runner, max_batch_size=4, timeout_ms=5.0,
                            max_queue=8) as b:
            f1 = b.submit(1.0)
            with pytest.raises(Exception, match="injected"):
                f1.result(timeout=5)
            faults.clear_plan()
            f2 = b.submit(2.0)
            assert f2.result(timeout=5) == 4.0

    def test_zero_max_queue_rejects_every_submit(self):
        """max_queue=0 is a real reject-all configuration, not a falsy
        value silently replaced by the config default."""
        with DynamicBatcher(lambda b: b, max_batch_size=2, timeout_ms=5.0,
                            max_queue=0) as b:
            with pytest.raises(ServiceUnavailable, match="queue is full"):
                b.submit("x")

    def test_zero_max_batch_size_rejected_loudly(self):
        with pytest.raises(ServeError, match="max_batch_size"):
            DynamicBatcher(lambda b: b, max_batch_size=0, timeout_ms=5.0)

    def test_close_drains_and_rejects_late_submit(self):
        with DynamicBatcher(lambda b: b, max_batch_size=2,
                            timeout_ms=5.0) as b:
            f = b.submit("x")
            assert f.result(timeout=5) == "x"
        with pytest.raises(ServiceUnavailable, match="shut down"):
            b.submit("late")


# ---------------------------------------------------------------------------
# End-to-end: batcher over a session, concurrent clients
# ---------------------------------------------------------------------------


class TestServeEndToEnd:
    def test_concurrent_requests_through_batched_session(self):
        net = _make_classifier()
        sess = InferenceSession(net, batch_buckets=(1, 2, 4, 8),
                                name="e2e")
        sess.warmup(np.random.randn(1, 8).astype(np.float32))

        def runner(payloads):
            out = sess.predict(np.stack(payloads))
            arr = out.asnumpy()
            sess.metrics.observe_batch(len(payloads), 8)
            return [arr[i] for i in range(len(payloads))]

        with DynamicBatcher(runner, max_batch_size=8, timeout_ms=5.0,
                            max_queue=64, metrics=sess.metrics) as b:
            rng = np.random.RandomState(1)
            xs = [rng.randn(8).astype(np.float32) for _ in range(32)]
            futs = [b.submit(x) for x in xs]
            outs = [f.result(timeout=30) for f in futs]
        with autograd.predict_mode():
            ref = net(mnp.array(np.stack(xs))).asnumpy()
        np.testing.assert_allclose(np.stack(outs), ref, rtol=1e-5,
                                   atol=1e-6)
        sess.assert_no_recompiles()
        snap = sess.metrics.snapshot()
        assert snap["requests"] == 32
        assert snap["errors"] == 0
        assert snap["p99_ms"] >= snap["p50_ms"] >= 0
        assert 0 < snap["batch_occupancy"] <= 1.0


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


class TestServeMetrics:
    def test_percentiles(self):
        m = ServeMetrics("t", window=128)
        for v in range(1, 101):
            m.observe_request(queue_ms=0.0, exec_ms=float(v))
        p = m.latency_percentiles()
        assert p["p50_ms"] == 50.0
        assert p["p95_ms"] == 95.0
        assert p["p99_ms"] == 99.0

    def test_percentile_nearest_rank_table(self):
        """Table-driven pin of ceil-based nearest-rank percentiles.
        ``int(round(...))`` banker's rounding put even-window ranks off
        by one (p50 of [1, 2] came out 2); the definition is rank
        ``ceil(pct/100 * n)``, 1-based."""
        from mxnet_tpu.serve import percentile

        cases = [
            # (samples, pct, expected)
            ([1, 2], 50, 1),          # THE regression: round() gave 2
            ([1, 2], 51, 2),
            ([1, 2], 100, 2),
            ([1, 2, 3, 4], 25, 1),    # round(1.0)=1 was right by luck
            ([1, 2, 3, 4], 50, 2),    # round(2.0)=2 ok; ceil agrees
            ([1, 2, 3, 4], 75, 3),
            ([1, 2, 3, 4], 76, 4),
            ([15, 20, 35, 40, 50], 30, 20),  # classic nearest-rank table
            ([15, 20, 35, 40, 50], 40, 20),
            ([15, 20, 35, 40, 50], 50, 35),
            ([15, 20, 35, 40, 50], 100, 50),
            ([7], 1, 7),
            ([7], 99, 7),
            ([3, 1, 2], 50, 2),       # unsorted input
            ([], 99, 0.0),            # empty window -> dashboard zero
        ]
        for samples, pct, want in cases:
            got = percentile(samples, pct)
            assert got == want, (samples, pct, got, want)

    def test_snapshot_counts(self):
        m = ServeMetrics("t", window=8)
        m.observe_request(1.0, 2.0, ok=True)
        m.observe_request(1.0, 2.0, ok=False)
        m.observe_batch(3, 4)
        m.observe_reject()
        m.observe_tokens(30, 1.5)
        m.set_queue_depth(5)
        s = m.snapshot()
        assert s["requests"] == 2 and s["errors"] == 1
        assert s["rejects"] == 1 and s["batches"] == 1
        assert s["mean_batch_size"] == 3 and s["batch_occupancy"] == 0.75
        assert s["tokens"] == 30 and abs(s["tokens_s"] - 20.0) < 1e-9
        assert s["queue_depth"] == 5

    def test_serve_events_on_profiler_bus(self):
        from mxnet_tpu import profiler
        from mxnet_tpu.profiler import core as _prof_core

        net = _make_classifier()
        sess = InferenceSession(net, batch_buckets=(1,), name="prof")
        profiler.set_state("run")
        try:
            sess.predict(np.random.randn(1, 8).astype(np.float32))
            sess.metrics.observe_request(0.5, 1.0)
            sess.metrics.set_queue_depth(2)
            names = [e.get("name", "")
                     for e in _prof_core.snapshot_events()]
        finally:
            profiler.set_state("stop")
        assert any(n.startswith("serve::execute") for n in names)
        assert any(n.startswith("serve::request") for n in names)
        assert any(n.startswith("serve.queue_depth") for n in names)


# ---------------------------------------------------------------------------
# Timeout -> 503
# ---------------------------------------------------------------------------


class TestServeTimeout:
    def test_hung_execution_becomes_503(self, no_faults, monkeypatch):
        net = _make_classifier()
        sess = InferenceSession(net, batch_buckets=(1,), name="hang")
        x = np.random.randn(1, 8).astype(np.float32)
        sess.warmup(x)
        monkeypatch.setenv("MXNET_SERVE_TIMEOUT_MS", "50")
        faults.install_plan({"seed": 0, "rules": [
            {"site": "serve:execute", "kind": "delay", "seconds": 1.0,
             "times": 1}]})
        t0 = time.monotonic()
        with pytest.raises(ServiceUnavailable, match="MXNET_SERVE_TIMEOUT"):
            sess.predict(x)
        assert time.monotonic() - t0 < 0.9  # fast 503, not the full hang
