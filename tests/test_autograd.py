"""Autograd tests (reference tests/python/unittest/test_autograd.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, np


def test_simple_grad():
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = (x * x + 2 * x).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy() + 2)


def test_chain_and_fanout():
    w = np.array([2.0])
    w.attach_grad()
    with autograd.record():
        a = w * 3
        b = w * 5
        y = a * b  # y = 15 w^2, dy/dw = 30w = 60
    y.backward()
    onp.testing.assert_allclose(w.grad.asnumpy(), [60.0])


def test_grad_req_modes():
    x = np.ones((3,))
    x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            (x * x).sum().backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 4.0)  # accumulated

    y = np.ones((3,))
    y.attach_grad(grad_req="write")
    for _ in range(2):
        with autograd.record():
            (y * y).sum().backward()
    onp.testing.assert_allclose(y.grad.asnumpy(), 2.0)  # overwritten

    z = np.ones((3,))
    z.attach_grad(grad_req="null")
    with autograd.record():
        (z * z).sum().backward()
    onp.testing.assert_allclose(z.grad.asnumpy(), 0.0)  # untouched


def test_head_grads():
    x = np.ones((2, 2))
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(np.array([[1.0, 2.0], [3.0, 4.0]]))
    onp.testing.assert_allclose(x.grad.asnumpy(), [[3, 6], [9, 12]])


def test_grad_function():
    x = np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (np.exp(x)).sum()
    (g,) = autograd.grad([y], [x])
    onp.testing.assert_allclose(g.asnumpy(), onp.exp(x.asnumpy()), rtol=1e-5)


def test_pause_inside_record():
    x = np.ones((2,))
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = x * 100  # not recorded
        out = (y + z.detach()).sum()
    out.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2.0)


def test_training_flags():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training() and autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.pause(train_mode=True):
        assert autograd.is_training() and not autograd.is_recording()


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            import mxnet_tpu.numpy as mnp

            y = 1 / (1 + mnp.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = np.array([0.0, 1.0, -1.0])
    x.attach_grad()
    with autograd.record():
        y = f(x)
    y.backward(np.ones((3,)))
    s = 1 / (1 + onp.exp(-x.asnumpy()))
    onp.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_multi_output_op_grad():
    x = np.array(onp.arange(6, dtype="float32").reshape(2, 3))
    x.attach_grad()
    with autograd.record():
        a, b = np.split(x, 2, axis=0)
        y = (a * 2 + b * 3).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(),
                                [[2, 2, 2], [3, 3, 3]])


def test_exception_on_disconnected():
    x = np.ones((2,))
    y = x * 2  # outside record
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_gradient_through_setitem():
    x = np.zeros((3,))
    v = np.array([1.0, 2.0, 3.0])
    v.attach_grad()
    with autograd.record():
        x[:] = v * 2
        loss = (x * x).sum()
    loss.backward()
    onp.testing.assert_allclose(v.grad.asnumpy(), 8 * v.asnumpy())


def test_retain_graph():
    x = np.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), g1)
