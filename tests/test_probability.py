"""New-distribution coverage (reference
``python/mxnet/gluon/probability/distributions/`` — binomial, multinomial,
negative_binomial, fishersnedecor, half_cauchy, pareto, one_hot_categorical,
relaxed_bernoulli, relaxed_one_hot_categorical, independent — and the
full ``divergence.py`` KL registration set)."""
import numpy as np
import pytest
from scipy import stats

import mxnet_tpu as mx
from mxnet_tpu import numpy as mnp
from mxnet_tpu.gluon import probability as prob


def test_binomial_logp_and_moments():
    d = prob.Binomial(n=10, prob=0.3)
    np.testing.assert_allclose(
        float(d.log_prob(mnp.array(4.0)).asnumpy()),
        stats.binom.logpmf(4, 10, 0.3), rtol=1e-5)
    mx.random.seed(3)
    s = d.sample((4000,)).asnumpy()
    assert abs(s.mean() - 3.0) < 0.15
    assert abs(float(d.mean.asnumpy()) - 3.0) < 1e-6
    assert abs(float(d.variance.asnumpy()) - 2.1) < 1e-5
    # logit parameterization agrees
    dl = prob.Binomial(n=10, logit=float(np.log(0.3 / 0.7)))
    np.testing.assert_allclose(
        float(dl.log_prob(mnp.array(4.0)).asnumpy()),
        stats.binom.logpmf(4, 10, 0.3), rtol=1e-5)


def test_negative_binomial_logp_and_moments():
    d = prob.NegativeBinomial(n=5, prob=0.4)
    # scipy nbinom counts failures with success prob; our p is the
    # per-trial "failure" weight: P(X=k) = C(k+n-1,k)(1-p)^n p^k
    np.testing.assert_allclose(
        float(d.log_prob(mnp.array(3.0)).asnumpy()),
        stats.nbinom.logpmf(3, 5, 0.6), rtol=1e-5)
    mx.random.seed(4)
    s = d.sample((6000,)).asnumpy()
    expect = 5 * 0.4 / 0.6
    assert abs(s.mean() - expect) < 0.2


def test_multinomial_logp_and_sampling():
    p = np.array([0.2, 0.3, 0.5])
    d = prob.Multinomial(num_events=3, prob=p.tolist(), total_count=8)
    v = np.array([2.0, 2.0, 4.0])
    np.testing.assert_allclose(
        float(d.log_prob(mnp.array(v)).asnumpy()),
        stats.multinomial.logpmf(v, 8, p), rtol=1e-5)
    mx.random.seed(5)
    s = d.sample((2000,)).asnumpy()
    assert s.shape == (2000, 3)
    np.testing.assert_array_equal(s.sum(-1), np.full(2000, 8.0))
    np.testing.assert_allclose(s.mean(0), 8 * p, atol=0.2)


def test_fishersnedecor_logp():
    d = prob.FisherSnedecor(df1=4.0, df2=7.0)
    np.testing.assert_allclose(
        float(d.log_prob(mnp.array(1.5)).asnumpy()),
        stats.f.logpdf(1.5, 4, 7), rtol=1e-5)
    mx.random.seed(6)
    s = d.sample((8000,)).asnumpy()
    assert abs(s.mean() - 7.0 / 5.0) < 0.2


def test_half_cauchy_and_pareto():
    hc = prob.HalfCauchy(scale=2.0)
    np.testing.assert_allclose(
        float(hc.log_prob(mnp.array(1.0)).asnumpy()),
        stats.halfcauchy.logpdf(1.0, scale=2.0), rtol=1e-5)
    assert float(hc.log_prob(mnp.array(-1.0)).asnumpy()) == -np.inf
    pa = prob.Pareto(alpha=3.0, scale=2.0)
    np.testing.assert_allclose(
        float(pa.log_prob(mnp.array(4.0)).asnumpy()),
        stats.pareto.logpdf(4.0, 3.0, scale=2.0), rtol=1e-5)
    mx.random.seed(7)
    s = pa.sample((6000,)).asnumpy()
    assert abs(s.mean() - 3.0) < 0.1
    np.testing.assert_allclose(float(pa.mean.asnumpy()), 3.0, rtol=1e-6)


def test_one_hot_categorical():
    p = np.array([0.1, 0.6, 0.3])
    d = prob.OneHotCategorical(num_events=3, prob=p.tolist())
    np.testing.assert_allclose(
        float(d.log_prob(mnp.array([0.0, 1.0, 0.0])).asnumpy()),
        np.log(0.6), rtol=1e-5)
    mx.random.seed(8)
    s = d.sample((3000,)).asnumpy()
    assert s.shape == (3000, 3)
    np.testing.assert_array_equal(s.sum(-1), np.ones(3000))
    np.testing.assert_allclose(s.mean(0), p, atol=0.05)


def test_relaxed_distributions_sample_in_simplex():
    mx.random.seed(9)
    rb = prob.RelaxedBernoulli(T=0.5, logit=0.3)
    s = rb.sample((500,)).asnumpy()
    assert ((s > 0) & (s < 1)).all()
    lp = rb.log_prob(mnp.array(0.7)).asnumpy()
    assert np.isfinite(lp)
    roc = prob.RelaxedOneHotCategorical(
        T=0.7, num_events=3, logit=[0.1, 0.2, -0.1])
    s = roc.sample((400,)).asnumpy()
    assert s.shape == (400, 3)
    np.testing.assert_allclose(s.sum(-1), np.ones(400), rtol=1e-5)
    # density integrates: spot-check finiteness + temperature dependence
    v = mnp.array([0.2, 0.5, 0.3])
    assert np.isfinite(float(roc.log_prob(v).asnumpy()))


def test_independent_sums_event_dims():
    base = prob.Normal(loc=mnp.array(np.zeros((4, 3), "float32")),
                       scale=mnp.array(np.ones((4, 3), "float32")))
    d = prob.Independent(base, 1)
    v = mnp.array(np.ones((4, 3), "float32"))
    lp = d.log_prob(v).asnumpy()
    assert lp.shape == (4,)
    np.testing.assert_allclose(
        lp, base.log_prob(v).asnumpy().sum(-1), rtol=1e-6)
    ent = d.entropy().asnumpy()
    assert ent.shape == (4,)


KL_CASES = [
    (prob.Exponential(2.0), prob.Exponential(3.0)),
    (prob.Uniform(0.0, 1.0), prob.Uniform(-0.5, 2.0)),
    (prob.Cauchy(0.0, 1.0), prob.Cauchy(1.0, 2.0)),
    (prob.Laplace(0.0, 1.0), prob.Laplace(0.5, 2.0)),
    (prob.Poisson(2.0), prob.Poisson(3.5)),
    (prob.Geometric(0.3), prob.Geometric(0.5)),
    (prob.Pareto(3.0, 2.0), prob.Pareto(2.0, 1.0)),
    (prob.Gumbel(0.0, 1.0), prob.Gumbel(0.5, 1.5)),
    (prob.Gamma(2.0, 1.5), prob.Gamma(3.0, 1.0)),
    (prob.Beta(2.0, 3.0), prob.Beta(1.0, 1.0)),
    (prob.HalfNormal(1.0), prob.HalfNormal(2.0)),
    (prob.HalfCauchy(1.0), prob.HalfCauchy(2.0)),
    (prob.Binomial(8, prob=0.3), prob.Binomial(8, prob=0.5)),
    (prob.Uniform(0.0, 1.0), prob.Normal(0.0, 1.0)),
    (prob.Uniform(0.0, 1.0), prob.Gumbel(0.0, 1.0)),
    (prob.Exponential(1.5), prob.Normal(0.0, 2.0)),
    (prob.Exponential(1.5), prob.Gumbel(0.5, 2.0)),
    (prob.Exponential(1.5), prob.Gamma(2.0, 1.0)),
]


@pytest.mark.parametrize("p,q", KL_CASES,
                         ids=[f"{type(p).__name__}-{type(q).__name__}-{i}"
                              for i, (p, q) in enumerate(KL_CASES)])
def test_kl_closed_form_vs_monte_carlo(p, q):
    mx.random.seed(11)
    closed = float(np.asarray(prob.kl_divergence(p, q).asnumpy()))
    assert np.isfinite(closed) and closed >= -1e-6
    est = float(np.asarray(prob.empirical_kl(p, q, 20000).asnumpy()))
    # MC error scales with the distribution's variance; generous tolerance
    assert abs(closed - est) < max(0.1, 0.15 * abs(closed))


def test_kl_dirichlet_and_mvn_and_onehot():
    mx.random.seed(12)
    p = prob.Dirichlet(mnp.array([1.0, 2.0, 3.0]))
    q = prob.Dirichlet(mnp.array([2.0, 2.0, 2.0]))
    closed = float(prob.kl_divergence(p, q).asnumpy())
    est = float(np.asarray(prob.empirical_kl(p, q, 20000).asnumpy()))
    assert abs(closed - est) < 0.05
    mp = prob.MultivariateNormal(
        loc=mnp.array([0.0, 0.0]), cov=mnp.array([[1.0, 0.2], [0.2, 1.0]]))
    mq = prob.MultivariateNormal(
        loc=mnp.array([1.0, -1.0]), cov=mnp.array([[2.0, 0.0], [0.0, 2.0]]))
    closed = float(prob.kl_divergence(mp, mq).asnumpy())
    est = float(np.asarray(prob.empirical_kl(mp, mq, 20000).asnumpy()))
    assert abs(closed - est) < 0.1
    op = prob.OneHotCategorical(prob=[0.2, 0.8])
    oq = prob.OneHotCategorical(prob=[0.5, 0.5])
    expect = 0.2 * np.log(0.2 / 0.5) + 0.8 * np.log(0.8 / 0.5)
    np.testing.assert_allclose(
        float(prob.kl_divergence(op, oq).asnumpy()), expect, rtol=1e-5)


def test_uniform_uniform_kl_outside_support_is_inf():
    kl = prob.kl_divergence(prob.Uniform(0.0, 2.0), prob.Uniform(0.5, 1.0))
    assert float(kl.asnumpy()) == np.inf


def test_multinomial_zero_prob_category_logp():
    # 0 * log(0) must contribute 0, not NaN (xlogy semantics)
    d = prob.Multinomial(num_events=3, prob=[0.5, 0.5, 0.0], total_count=4)
    got = float(d.log_prob(mnp.array([2.0, 2.0, 0.0])).asnumpy())
    np.testing.assert_allclose(
        got, stats.multinomial.logpmf([2, 2, 0], 4, [0.5, 0.5, 0.0]),
        rtol=1e-5)


def test_binomial_kl_count_mismatch():
    # disjoint support -> inf; n1 < n2 has no closed form -> nan (decided
    # inside the traced computation: no host sync, jit-safe)
    kl = prob.kl_divergence(prob.Binomial(10, prob=0.3),
                            prob.Binomial(5, prob=0.3))
    assert float(kl.asnumpy()) == np.inf
    kl = prob.kl_divergence(prob.Binomial(5, prob=0.3),
                            prob.Binomial(10, prob=0.3))
    assert np.isnan(float(kl.asnumpy()))


def test_glove_vocabulary_mode(tmp_path):
    import collections

    from mxnet_tpu.contrib import text

    root = tmp_path / "emb"
    (root / "glove").mkdir(parents=True)
    (root / "glove" / "glove.6B.50d.txt").write_text(
        "hello 0.1 0.2\nworld 0.3 0.4\nextra 0.5 0.6\n")
    voc = text.Vocabulary(collections.Counter(["hello", "world"]))
    emb = text.embedding.create(
        "glove", pretrained_file_name="glove.6B.50d.txt",
        embedding_root=str(root), vocabulary=voc)
    # vocabulary tokens got their file vectors
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [0.1, 0.2], rtol=1e-6)
    # out-of-vocabulary file tokens were NOT indexed
    assert "extra" not in emb.token_to_idx
    assert len(emb) == len(voc)


def test_sample_shape_broadcasts_across_params():
    # array n with scalar prob, array scale with scalar loc, etc.
    mx.random.seed(13)
    s = prob.Binomial(n=mnp.array([5.0, 10.0]), prob=0.5).sample()
    assert s.shape == (2,)
    s = prob.Normal(0.0, mnp.array([1.0, 2.0, 3.0])).sample((4,))
    assert s.shape == (4, 3)
    s = prob.FisherSnedecor(df1=mnp.array([4.0, 6.0]), df2=8.0).sample()
    assert s.shape == (2,)
    s = prob.NegativeBinomial(n=mnp.array([2.0, 4.0]), prob=0.3).sample()
    assert s.shape == (2,)
    s = prob.Gamma(shape=2.0, scale=mnp.array([1.0, 2.0])).sample()
    assert s.shape == (2,)


def test_fishersnedecor_out_of_support():
    d = prob.FisherSnedecor(df1=4.0, df2=7.0)
    assert float(d.log_prob(mnp.array(-1.0)).asnumpy()) == -np.inf
