"""Sharded RecordIO input pipeline tests (mxnet_tpu/io/pipeline.py +
the recordio growth): extended crc-bearing index round-trip, loud index
integrity checks, ``tools/recordio_check.py`` validate/repair,
ShardedRecordDataset shard-disjointness + DataLoader composition,
RecordPipeline exactly-once delivery (worker-count independent order,
fault quarantine, worker-death respawn, resume + reshard), PrefetchIter
true queue depth + ``prefetch_stats()``, DeviceFeeder double-buffering,
and the ``io.*`` / ``input``-phase export surface."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io.pipeline import (DeviceFeeder, RecordPipeline,
                                   ShardedRecordDataset)
from mxnet_tpu.resilience import counters, faults


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.clear_plan()
    counters.reset()
    yield
    faults.clear_plan()
    counters.reset()


def _write_rec(dirpath, n=32, crc=True):
    """Synthetic pair; payload encodes the sample id."""
    rec = str(dirpath / "t.rec")
    idx = str(dirpath / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        w.write_idx(i, b"%d" % i)
    w.close()
    if crc:
        import tools.recordio_check as rcheck

        assert rcheck.main([rec, "--repair", "--crc"]) == 0
    return rec, idx


def _drain_ids(pipe):
    return [int(x) for batch in pipe for x in batch]


# ---------------------------------------------------------------------------
# recordio: crc index + integrity check + repair CLI
# ---------------------------------------------------------------------------


def test_crc_index_roundtrip(tmp_path):
    rec, idx = _write_rec(tmp_path, n=8)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert len(r.crcs) == 8
    for i in range(8):
        assert r.read_idx(i) == b"%d" % i
    r.close()


def test_crc_mismatch_raises(tmp_path):
    rec, idx = _write_rec(tmp_path, n=4)
    lines = open(idx).read().splitlines()
    key, pos, _ = lines[2].split("\t")
    lines[2] = f"{key}\t{pos}\t12345"
    open(idx, "w").write("\n".join(lines) + "\n")
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    with pytest.raises(MXNetError, match="CRC mismatch"):
        r.read_idx(2)
    r.close()


def test_index_integrity_check_names_file(tmp_path):
    rec, idx = _write_rec(tmp_path, n=4, crc=False)
    lines = open(idx).read().splitlines()
    key, pos = lines[1].split("\t")
    lines[1] = f"{key}\t{int(pos) + 2}"  # misaligned offset
    open(idx, "w").write("\n".join(lines) + "\n")
    with pytest.raises(MXNetError, match="t.idx"):
        recordio.MXIndexedRecordIO(idx, rec, "r")


def test_truncated_index_detected_at_open(tmp_path):
    # a .idx missing its tail entries silently drops training data — the
    # open-time coverage probe must refuse it (while a torn .rec tail,
    # the normal crash-recovery shape, stays tolerated)
    rec, idx = _write_rec(tmp_path, n=6, crc=False)
    lines = open(idx).read().splitlines()
    open(idx, "w").write("\n".join(lines[:-1]) + "\n")
    with pytest.raises(MXNetError, match="after the last indexed"):
        recordio.MXIndexedRecordIO(idx, rec, "r")
    open(idx, "w").write("\n".join(lines) + "\n")
    with open(rec, "ab") as fh:
        fh.write(b"\x0a\x23\xd7\xce\xff")  # torn tail: half a header
    r = recordio.MXIndexedRecordIO(idx, rec, "r")  # tolerated
    assert r.read_idx(5) == b"5"
    r.close()


def test_lazy_public_surface_resolves_in_fresh_process():
    # mx.io.RecordPipeline resolves through io/__init__.__getattr__; the
    # from-import form there recursed via importlib's hasattr probe on
    # FIRST access in a fresh process (tests import the dotted path and
    # never saw it), so pin the public path in a subprocess
    import subprocess
    import sys

    code = ("import mxnet_tpu as mx; "
            "assert mx.io.RecordPipeline is not None; "
            "assert mx.io.ShardedRecordDataset is not None; "
            "assert mx.io.DeviceFeeder is not None")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_recordio_check_cli_repairs(tmp_path):
    import tools.recordio_check as rcheck

    rec, idx = _write_rec(tmp_path, n=6, crc=False)
    os.remove(idx)
    assert rcheck.main([rec]) == 1          # missing index: problems
    assert rcheck.main([rec, "--repair", "--crc"]) == 0
    assert rcheck.main([rec]) == 0          # now verifies, crc included
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(3) == b"3"
    r.close()


def test_recordio_check_detects_torn_tail(tmp_path):
    import tools.recordio_check as rcheck

    rec, idx = _write_rec(tmp_path, n=6, crc=False)
    with open(rec, "ab") as fh:
        fh.write(b"\x0a\x23\xd7\xce\xff")  # half a header
    assert rcheck.main([rec]) == 1


# ---------------------------------------------------------------------------
# ShardedRecordDataset + DataLoader composition
# ---------------------------------------------------------------------------


def test_sharded_dataset_disjoint_union(tmp_path):
    rec, _ = _write_rec(tmp_path, n=20)
    shards = [ShardedRecordDataset([rec], shard_index=s, num_shards=3)
              for s in range(3)]
    seen = [sorted(int(ds[i]) for i in range(len(ds))) for ds in shards]
    flat = [i for part in seen for i in part]
    assert len(flat) == len(set(flat)) == 20
    assert sorted(flat) == list(range(20))
    for ds in shards:
        ds.close()


def test_sharded_dataset_dataloader_composition(tmp_path):
    from mxnet_tpu.gluon.data import DataLoader

    rec, _ = _write_rec(tmp_path, n=12)
    ds = ShardedRecordDataset(
        [rec], shard_index=0, num_shards=2,
        transform=lambda p: onp.array([int(p)], dtype="float32"))
    dl = DataLoader(ds, batch_size=2, shuffle=False)
    got = sorted(float(v) for b in dl for v in b.asnumpy().ravel())
    assert got == [float(v) for v in range(0, 12, 2)]
    ds.close()


def test_pipeline_last_batch_semantics(tmp_path):
    rec, _ = _write_rec(tmp_path, n=10)
    keep = RecordPipeline([rec], batch_size=4, last_batch="keep",
                          num_workers=1)
    sizes = [len(b) for b in keep]
    assert sizes == [4, 4, 2]
    keep.close()
    disc = RecordPipeline([rec], batch_size=4, last_batch="discard",
                          num_workers=1)
    assert [len(b) for b in disc] == [4, 4]
    disc.close()


# ---------------------------------------------------------------------------
# RecordPipeline: exactly-once, determinism, faults, resume, reshard
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("workers", [1, 4])
def test_pipeline_exactly_once(tmp_path, workers):
    rec, _ = _write_rec(tmp_path, n=32)
    p = RecordPipeline([rec], batch_size=4, num_workers=workers,
                       shuffle=True, seed=2)
    seen = _drain_ids(p)
    p.close()
    assert sorted(seen) == list(range(32))


def test_pipeline_order_worker_count_independent(tmp_path):
    rec, _ = _write_rec(tmp_path, n=32)
    orders = []
    for workers in (1, 4):
        p = RecordPipeline([rec], batch_size=4, num_workers=workers,
                           shuffle=True, seed=5)
        orders.append(_drain_ids(p))
        p.close()
    assert orders[0] == orders[1]
    p = RecordPipeline([rec], batch_size=4, num_workers=4,
                       shuffle=True, seed=6)
    assert _drain_ids(p) != orders[0]
    p.close()


def test_pipeline_quarantines_torn_and_transient(tmp_path):
    rec, _ = _write_rec(tmp_path, n=24)
    faults.install_plan({"seed": 3, "rules": [
        {"site": "io:read", "kind": "transient", "at": [2]},
        {"site": "io:read", "kind": "torn", "at": [7]},
    ]})
    p = RecordPipeline([rec], batch_size=4, num_workers=2, seed=1)
    with pytest.warns(RuntimeWarning, match="quarantined"):
        seen = _drain_ids(p)
    st = p.stats()
    p.close()
    assert st["records_quarantined"] == 2
    assert len(seen) == 22 and len(set(seen)) == 22
    assert counters.snapshot()["resilience.io_records_quarantined"] == 2


def test_pipeline_worker_death_respawns_exactly_once(tmp_path):
    rec, _ = _write_rec(tmp_path, n=32)
    faults.install_plan({"seed": 9, "rules": [
        {"site": "io:read", "kind": "die", "at": [5]},
    ]})
    p = RecordPipeline([rec], batch_size=4, num_workers=2, seed=4)
    seen = _drain_ids(p)
    st = p.stats()
    p.close()
    assert sorted(seen) == list(range(32))  # killed range requeued
    assert st["worker_respawns"] >= 1


@pytest.mark.parametrize("cut", [1, 3])
def test_pipeline_resume_sample_exact(tmp_path, cut):
    rec, _ = _write_rec(tmp_path, n=32)

    def make():
        return RecordPipeline([rec], batch_size=4, num_workers=2,
                              shuffle=True, seed=8)

    ref_pipe = make()
    ref = _drain_ids(ref_pipe)
    ref_pipe.close()

    p1 = make()
    head = [int(x) for _ in range(cut) for x in next(p1)]
    state = p1.state_dict()
    p1.close()
    p2 = make()
    p2.load_state_dict(state)
    tail = _drain_ids(p2)
    p2.close()
    assert head + tail == ref


def test_pipeline_reshard_4_to_2_exactly_once(tmp_path):
    rec, _ = _write_rec(tmp_path, n=48)

    def mk(shard, shards):
        return RecordPipeline([rec], batch_size=4, shard_index=shard,
                              num_shards=shards, num_workers=2,
                              shuffle=True, seed=7)

    pipes = [mk(s, 4) for s in range(4)]
    head = []
    for p in pipes:
        head.extend(int(x) for x in next(p))
    states = [p.state_dict() for p in pipes]
    for p in pipes:
        p.close()
    merged = RecordPipeline.merge_states(states)
    tail = []
    for s in range(2):
        surv = mk(s, 2)
        surv.load_state_dict(merged)
        tail.extend(_drain_ids(surv))
        surv.close()
    assert sorted(head + tail) == list(range(48))
    assert len(head) + len(tail) == 48


def test_pipeline_state_rejects_foreign_config(tmp_path):
    rec, _ = _write_rec(tmp_path, n=16)
    p1 = RecordPipeline([rec], batch_size=4, seed=1)
    state = p1.state_dict()
    p1.close()
    p2 = RecordPipeline([rec], batch_size=8, seed=1)
    with pytest.raises(MXNetError, match="different dataset"):
        p2.load_state_dict(state)
    p2.close()


# ---------------------------------------------------------------------------
# PrefetchIter: true depth + stats
# ---------------------------------------------------------------------------


def test_prefetchiter_true_depth_and_stats():
    x = onp.arange(64, dtype="float32").reshape(32, 2)
    it = mx.io.PrefetchIter(mx.io.NDArrayIter(x, batch_size=4),
                            num_prefetch=3)
    batches = 0
    while True:
        try:
            it.next()
        except StopIteration:
            break
        batches += 1
    assert batches == 8
    st = it.prefetch_stats()
    assert st["served"] == 8
    assert st["depth"] == 3
    assert 1 <= st["queue_highwater"] <= 3
    assert set(st) == {"served", "stalls", "stall_ms",
                       "queue_highwater", "depth"}


def test_prefetchiter_rejects_bad_depth():
    x = onp.zeros((8, 2), "float32")
    with pytest.raises(MXNetError, match="num_prefetch"):
        mx.io.PrefetchIter(mx.io.NDArrayIter(x, batch_size=4),
                           num_prefetch=0)


# ---------------------------------------------------------------------------
# DeviceFeeder + export surface
# ---------------------------------------------------------------------------


def test_device_feeder_double_buffers(tmp_path):
    rec, _ = _write_rec(tmp_path, n=24)
    p = RecordPipeline(
        [rec], batch_size=4, num_workers=2,
        decode_fn=lambda payload: onp.array([int(payload)], "float32"),
        batchify_fn=lambda items: onp.stack(items))
    feeder = DeviceFeeder(p, depth=2)
    total = sorted(float(v) for b in feeder for v in onp.asarray(b).ravel())
    assert total == [float(v) for v in range(24)]
    st = feeder.stats()
    assert st["batches"] == 6 and st["depth"] == 2
    p.close()


def test_export_snapshot_carries_io_gauges(tmp_path):
    from mxnet_tpu.profiler import export

    rec, _ = _write_rec(tmp_path, n=8)
    p = RecordPipeline([rec], batch_size=4, num_workers=1,
                       name="t-export")
    _drain_ids(p)
    snap = export.snapshot()
    assert snap["io.t-export.batches_served"] == 2
    assert snap["io.t-export.records_read"] == 8
    assert "io.t-export.worker_utilization" in snap
    p.close()


def test_input_phase_registered():
    from mxnet_tpu.profiler import attribution

    assert "input" in attribution.PHASES
