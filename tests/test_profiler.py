"""Telemetry subsystem tests (mxnet_tpu/profiler/): chrome-trace JSON
validity, aggregate tables, instrumentation hooks (CachedOp compile /
engine waits / kvstore collectives / imperative op counters), the
recompile-storm counter, step-level TrainingMetrics, and the
stopped-profiler overhead bound."""
import json
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler
from mxnet_tpu import np as mnp
from mxnet_tpu.profiler import core


@pytest.fixture(autouse=True)
def _clean_profiler_state():
    """Every test starts and ends with a stopped, empty profiler."""
    profiler.set_state("stop")
    profiler.reset()
    profiler.set_config()  # restore default config
    yield
    profiler.set_state("stop")
    profiler.reset()
    profiler.set_config()


def _run_hybrid_train_step():
    """One hybridized Gluon train step + a kvstore allreduce + waits —
    the acceptance scenario's workload."""
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = mnp.ones((2, 3))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    loss.wait_to_read()
    # second shape: a fresh CachedOp signature -> a compile event
    net(mnp.ones((5, 3))).wait_to_read()

    from mxnet_tpu.kvstore.dist_tpu import KVStoreDistTPUSync

    kv = KVStoreDistTPUSync()
    kv.allreduce([mnp.ones((8,)), mnp.ones((8,))])
    mx.waitall()


def test_trace_json_contains_subsystem_events(tmp_path):
    """set_state('run') during a hybridized train step produces valid
    chrome://tracing JSON with CachedOp compile, engine wait, and kvstore
    allreduce events (the ISSUE acceptance scenario)."""
    out = tmp_path / "profile.json"
    profiler.set_config(filename=str(out), aggregate_stats=True)
    profiler.set_state("run")
    _run_hybrid_train_step()
    profiler.set_state("stop")
    path = profiler.dump()
    assert path == str(out)

    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    # chrome trace contract: complete events carry ph/ts/dur/pid/tid
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs
    for e in xs:
        assert {"name", "ts", "dur", "pid", "tid"} <= set(e)
    names = {e["name"] for e in events}
    assert any("CachedOp::compile" in n for n in names)
    assert any(n.startswith("engine::wait") for n in names)
    assert any("kvstore::allreduce" in n for n in names)


def test_aggregate_table_contents():
    profiler.set_state("run")
    _run_hybrid_train_step()
    profiler.set_state("stop")
    table = profiler.dumps()
    assert "CachedOp::compile" in table
    assert "kvstore::allreduce" in table
    assert "engine::wait" in table
    # get_summary is the same table (reference API parity)
    assert profiler.get_summary() == table
    # reset=True clears the aggregate STATS only: the chrome-trace events
    # survive for a later dump() (pre-package dumps(reset) contract)
    n_events = len(core.snapshot_events())
    profiler.dumps(reset=True)
    assert "CachedOp::compile" not in profiler.dumps()
    assert len(core.snapshot_events()) == n_events


def test_imperative_op_counters():
    profiler.set_config(profile_imperative=True)
    profiler.set_state("run")
    a = mnp.ones((4,))
    for _ in range(3):
        a = a + 1.0
    profiler.set_state("stop")
    counts = core.op_counts()
    assert counts.get("add", 0) >= 3
    assert "Operator (imperative)" in profiler.dumps()


def test_imperative_counters_off_by_default():
    profiler.set_state("run")
    (mnp.ones((4,)) + 1.0).wait_to_read()
    profiler.set_state("stop")
    assert core.op_counts() == {}


def test_recompile_storm_warning_and_counter(monkeypatch):
    monkeypatch.setenv("MXNET_CACHEDOP_SIG_LIMIT", "2")
    profiler.set_state("run")
    net = gluon.nn.Dense(3)
    net.initialize()
    net.hybridize()
    with pytest.warns(RuntimeWarning, match="recompile storm"):
        # every batch size is a distinct CachedOp signature
        for bs in range(1, 7):
            net(mnp.ones((bs, 2)))
    profiler.set_state("stop")
    assert core.get_counter("cachedop.recompile_storms") >= 1
    op = net._cached_op if hasattr(net, "_cached_op") else None
    if op is not None:
        stats = op.cache_stats()
        assert stats["misses"] >= 3
        assert stats["compile_ms"] > 0


def test_cachedop_cache_hit_stats():
    from mxnet_tpu.cachedop import CachedOp

    net = gluon.nn.Dense(3, in_units=2)
    net.initialize()
    op = CachedOp(net)
    x = mnp.ones((2, 2))
    op(x)
    op(x)
    op(x)
    stats = op.cache_stats()
    assert stats["misses"] == 1 and stats["hits"] == 2
    assert stats["signatures"] == 1


def test_scope_and_task_feed_aggregates_when_stopped():
    """Pre-package behavior kept: scope()/Task aggregate without run."""
    with profiler.scope("unit_test_scope"):
        (mnp.ones((4, 4)) * 2).wait_to_read()
    t = profiler.Domain("d").new_task("t")
    t.start()
    t.stop()
    table = profiler.dumps()
    assert "unit_test_scope" in table and "d::t" in table


def test_counter_object_records_gauge():
    profiler.set_state("run")
    c = profiler.Counter(profiler.Domain("kv"), "bytes", 0)
    c.increment(42)
    profiler.set_state("stop")
    assert core.get_counter("kv::bytes") == 42
    evs = [e for e in core.snapshot_events() if e.get("ph") == "C"]
    assert any(e["name"] == "kv::bytes" for e in evs)


def test_training_metrics_math():
    tm = profiler.TrainingMetrics(flops_per_step=1e9, samples_per_step=32,
                                  tokens_per_step=4096, peak_flops=1e12)
    for _ in range(5):
        tm.record_step(0.01)
    assert tm.steps == 5
    assert tm.median_step_s == pytest.approx(0.01)
    assert tm.mfu == pytest.approx(0.1)          # 1e9 / (0.01 * 1e12)
    assert tm.samples_per_sec == pytest.approx(3200.0)
    assert tm.tokens_per_sec == pytest.approx(409600.0)
    s = tm.summary()
    assert s["steps"] == 5 and s["mfu"] == pytest.approx(0.1)
    tm.reset()
    assert tm.steps == 0 and tm.mfu is None


def test_step_marker_records_steps_and_trace_event():
    tm = profiler.TrainingMetrics(peak_flops=1e12)
    profiler.set_state("run")
    assert tm.step_marker() is None              # first call starts clock
    time.sleep(0.01)
    dt = tm.step_marker(samples=8, flops=1e6)
    profiler.set_state("stop")
    assert dt is not None and dt > 0
    assert tm.steps == 1 and tm.total_samples == 8
    assert any(e["name"] == "train::step"
               for e in core.snapshot_events() if e.get("ph") == "X")


def test_autostart_env_var():
    """MXNET_PROFILER_AUTOSTART=1 starts the bus at import (fresh
    interpreter; the reference autostart env contract)."""
    import os
    import subprocess
    import sys

    code = ("from mxnet_tpu import profiler; "
            "print(profiler.state(), profiler.core.IMPERATIVE)")
    env = {**os.environ, "MXNET_PROFILER_AUTOSTART": "1",
           "MXNET_PROFILER_IMPERATIVE": "1", "JAX_PLATFORMS": "cpu"}
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.split() == ["run", "True"]


def test_device_memory_stats_shape():
    mem = profiler.device_memory_stats()
    assert isinstance(mem, list) and mem
    assert all("device" in m for m in mem)       # CPU: no byte counters


def test_bench_consumes_training_metrics():
    """bench.py's MFU accounting goes through TrainingMetrics now."""
    import bench

    assert bench.TrainingMetrics is profiler.TrainingMetrics
    assert bench._peak_flops is profiler.peak_flops


@pytest.mark.serial
def test_stopped_profiler_overhead_under_5pct():
    """10k-iteration eager microloop: with hooks installed but the
    profiler stopped, overhead vs the never-profiled baseline (hook slots
    None) must stay under 5%."""
    from mxnet_tpu import engine
    from mxnet_tpu.ops import registry

    x = mnp.ones((4,))

    def loop(n=10_000):
        y = x
        t0 = time.perf_counter()
        for _ in range(n):
            y = y + 1.0
        y.wait_to_read()
        return time.perf_counter() - t0

    saved = registry._PROF, engine._PROF

    def measure(rounds=7):
        """Interleave the two arms (min-of-rounds each) so machine drift
        during the measurement hits both equally."""
        base = stopped = float("inf")
        for _ in range(rounds):
            # never-profiled baseline: hook slots empty
            registry._PROF = None
            engine._PROF = None
            base = min(base, loop())
            # hooks installed, profiler stopped (the post-first-run state)
            profiler.set_state("run")
            profiler.set_state("stop")
            stopped = min(stopped, loop())
        return base, stopped

    try:
        loop(2000)  # warm the jit/op caches before either measurement
        base, stopped = measure()
        if stopped > base * 1.05:  # timing noise: one clean re-measure
            base, stopped = measure(rounds=9)
    finally:
        registry._PROF, engine._PROF = saved
    assert stopped <= base * 1.05, (
        f"stopped-profiler overhead {stopped / base - 1:.1%} "
        f"(baseline {base:.3f}s, stopped {stopped:.3f}s)")
