"""Tooling parity tests: tools/launch.py (reference tools/launch.py),
tools/im2rec.py (reference tools/im2rec.py), benchmark/opperf.py
(reference benchmark/opperf/). The launcher test is the reference's
multi-process-on-one-host distributed smoke
(tests/nightly/test_distributed_training*.sh done the JAX way)."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = dict(os.environ)
    # the conftest pins a virtual CPU mesh via XLA_FLAGS; subprocesses set up
    # their own platform, and the distributed smoke needs 1 device/proc
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k in list(env):
        if k.startswith(("MXNET_TPU_", "DMLC_")):
            del env[k]
    return env


def test_launch_local_two_process_pushpull(tmp_path):
    """2 processes: initialize_distributed from launcher env, then a
    dist_tpu_sync pushpull must sum contributions ACROSS processes."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import jax
        jax.config.update('jax_platforms', 'cpu')
        import mxnet_tpu as mx
        from mxnet_tpu import np
        from mxnet_tpu.parallel import initialize_distributed

        initialize_distributed()  # reads MXNET_TPU_* from the launcher
        rank = jax.process_index()
        assert jax.process_count() == 2
        kv = mx.kv.create('dist_tpu_sync')
        assert kv.num_workers == 2
        val = np.ones((4,)) * (rank + 1)
        out = np.zeros((4,))
        kv.pushpull('g', [val], out=[out])
        got = out.asnumpy()
        assert (got == 3.0).all(), got   # 1 + 2 across ranks
        kv.barrier()
        print(f'RANK{rank}_OK', flush=True)
    """))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_clean_env(),
        cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RANK0_OK" in r.stdout and "RANK1_OK" in r.stdout


def test_launch_requires_command():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"), "-n", "2"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode != 0


def test_im2rec_list_and_pack_roundtrip(tmp_path):
    from PIL import Image

    root = tmp_path / "imgs"
    for cls, color in (("cat", (255, 0, 0)), ("dog", (0, 255, 0))):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            Image.new("RGB", (32, 24), color).save(
                root / cls / f"{i}.png")
    prefix = str(tmp_path / "data")
    import tools.im2rec as im2rec

    assert im2rec.main([prefix, str(root), "--list", "--no-shuffle"]) == 0
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 6
    assert im2rec.main([prefix, str(root), "--resize", "16"]) == 0

    from mxnet_tpu import recordio

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    labels = set()
    for idx in rec.keys:
        header, img = recordio.unpack_img(rec.read_idx(idx))
        labels.add(float(header.label))
        assert img.shape[2] == 3 and min(img.shape[:2]) == 16
    assert labels == {0.0, 1.0}


def test_opperf_runs_and_reports(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark", "opperf.py"),
         "--ops", "add,tanh", "--shape", "64,64", "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stderr
    rows = [json.loads(l) for l in r.stdout.splitlines() if l.startswith("{")]
    assert {row["op"] for row in rows} == {"add", "tanh"}
    for row in rows:
        assert row.get("fwd_us", 0) > 0
