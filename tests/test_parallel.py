"""Parallelism tests on the virtual 8-device mesh: ShardedTrainer (dp/tp),
ring attention (sp). The SURVEY.md §2.3 'absent in reference' list — built
fresh here."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.parallel import (ShardedTrainer, ShardingRules, make_mesh)
from mxnet_tpu.parallel.ring_attention import ring_attention, sequence_sharded
from mxnet_tpu.ops.pallas.flash_attention import _reference_attention


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize()
    with autograd.predict_mode():
        net(mx.np.array(np.zeros((2, 20), dtype="float32")))
    return net


def test_sharded_trainer_dp_tp_converges():
    mesh = make_mesh({"dp": 4, "tp": 2})
    rules = ShardingRules([(r"2\.weight", P("tp", None))], default_axis=None)
    net = _mlp()
    tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
                        {"learning_rate": 1e-2}, mesh=mesh, rules=rules)
    np.random.seed(0)
    X = np.random.randn(32, 20).astype("float32")
    Y = np.random.randint(0, 10, (32,))
    losses = [float(tr.step(X, Y).asnumpy()) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.5
    p = tr.params["2.weight"]
    assert p.sharding.spec == P("tp", None)
    assert p.addressable_shards[0].data.shape == (16, 64)
    tr.sync_to_block()  # weights flow back into the Block
    assert np.allclose(np.asarray(tr.params["2.weight"]),
                       net.collect_params()["2.weight"].data().asnumpy())


def test_sharded_trainer_matches_eager_sgd():
    """One SPMD sgd step == one eager Trainer step (same weights/batch)."""
    mesh = make_mesh({"dp": 8})
    net_a = _mlp()
    net_b = _mlp()
    # copy a's weights into b
    pa, pb = net_a.collect_params(), net_b.collect_params()
    for n in pa:
        pb[n].set_data(pa[n].data())
    X = np.random.randn(16, 20).astype("float32")
    Y = np.random.randint(0, 10, (16,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    tr_a = ShardedTrainer(net_a, loss_fn, "sgd", {"learning_rate": 0.1},
                          mesh=mesh, rules=ShardingRules(default_axis=None))
    tr_a.step(X, Y)
    tr_a.sync_to_block()

    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1})
    with autograd.record():
        # eager loss uses mean to match the SPMD step's jnp.mean
        l = loss_fn(net_b(mx.np.array(X)), mx.np.array(Y)).mean()
    l.backward()
    tr_b.step(1)

    for n in pa:
        np.testing.assert_allclose(pa[n].data().asnumpy(),
                                   pb[n].data().asnumpy(), rtol=2e-5,
                                   atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh({"sp": 8})
    np.random.seed(1)
    q = np.random.randn(2, 4, 64, 16).astype("float32")
    k = np.random.randn(2, 4, 64, 16).astype("float32")
    v = np.random.randn(2, 4, 64, 16).astype("float32")
    qs = sequence_sharded(jnp.asarray(q), mesh)
    ks = sequence_sharded(jnp.asarray(k), mesh)
    vs = sequence_sharded(jnp.asarray(v), mesh)
    out = ring_attention(qs, ks, vs, mesh=mesh, causal=causal)
    ref = _reference_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=causal)
    assert out.sharding.spec == P(None, None, "sp", None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_ring_attention_grad_flows():
    mesh = make_mesh({"sp": 4})
    q = sequence_sharded(jnp.asarray(
        np.random.randn(1, 2, 32, 8).astype("float32")), mesh)

    def loss(q_):
        return ring_attention(q_, q_, q_, mesh=mesh, causal=True).sum()

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_ring_attention_rejects_bad_axis():
    mesh = make_mesh({"dp": 8})
    x = jnp.zeros((1, 1, 8, 4))
    with pytest.raises(mx.MXNetError):
        ring_attention(x, x, x, mesh=mesh, axis="sp")
