"""Parallelism tests on the virtual 8-device mesh: ShardedTrainer (dp/tp),
ring attention (sp). The SURVEY.md §2.3 'absent in reference' list — built
fresh here."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.parallel import (ShardedTrainer, ShardingRules, make_mesh)
from mxnet_tpu.parallel.ring_attention import ring_attention, sequence_sharded
from mxnet_tpu.ops.pallas.flash_attention import _reference_attention


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize()
    with autograd.predict_mode():
        net(mx.np.array(np.zeros((2, 20), dtype="float32")))
    return net


def test_sharded_trainer_dp_tp_converges():
    mesh = make_mesh({"dp": 4, "tp": 2})
    rules = ShardingRules([(r"2\.weight", P("tp", None))], default_axis=None)
    net = _mlp()
    tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
                        {"learning_rate": 1e-2}, mesh=mesh, rules=rules)
    np.random.seed(0)
    X = np.random.randn(32, 20).astype("float32")
    Y = np.random.randint(0, 10, (32,))
    losses = [float(tr.step(X, Y).asnumpy()) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.5
    p = tr.params["2.weight"]
    assert p.sharding.spec == P("tp", None)
    assert p.addressable_shards[0].data.shape == (16, 64)
    tr.sync_to_block()  # weights flow back into the Block
    assert np.allclose(np.asarray(tr.params["2.weight"]),
                       net.collect_params()["2.weight"].data().asnumpy())


def test_sharded_trainer_matches_eager_sgd():
    """One SPMD sgd step == one eager Trainer step (same weights/batch)."""
    mesh = make_mesh({"dp": 8})
    net_a = _mlp()
    net_b = _mlp()
    # copy a's weights into b
    pa, pb = net_a.collect_params(), net_b.collect_params()
    for n in pa:
        pb[n].set_data(pa[n].data())
    X = np.random.randn(16, 20).astype("float32")
    Y = np.random.randint(0, 10, (16,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    tr_a = ShardedTrainer(net_a, loss_fn, "sgd", {"learning_rate": 0.1},
                          mesh=mesh, rules=ShardingRules(default_axis=None))
    tr_a.step(X, Y)
    tr_a.sync_to_block()

    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1})
    with autograd.record():
        # eager loss uses mean to match the SPMD step's jnp.mean
        l = loss_fn(net_b(mx.np.array(X)), mx.np.array(Y)).mean()
    l.backward()
    tr_b.step(1)

    for n in pa:
        np.testing.assert_allclose(pa[n].data().asnumpy(),
                                   pb[n].data().asnumpy(), rtol=2e-5,
                                   atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh({"sp": 8})
    np.random.seed(1)
    q = np.random.randn(2, 4, 64, 16).astype("float32")
    k = np.random.randn(2, 4, 64, 16).astype("float32")
    v = np.random.randn(2, 4, 64, 16).astype("float32")
    qs = sequence_sharded(jnp.asarray(q), mesh)
    ks = sequence_sharded(jnp.asarray(k), mesh)
    vs = sequence_sharded(jnp.asarray(v), mesh)
    out = ring_attention(qs, ks, vs, mesh=mesh, causal=causal)
    ref = _reference_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=causal)
    assert out.sharding.spec == P(None, None, "sp", None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_ring_attention_grad_flows():
    mesh = make_mesh({"sp": 4})
    q = sequence_sharded(jnp.asarray(
        np.random.randn(1, 2, 32, 8).astype("float32")), mesh)

    def loss(q_):
        return ring_attention(q_, q_, q_, mesh=mesh, causal=True).sum()

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_ring_attention_rejects_bad_axis():
    mesh = make_mesh({"dp": 8})
    x = jnp.zeros((1, 1, 8, 4))
    with pytest.raises(mx.MXNetError):
        ring_attention(x, x, x, mesh=mesh, axis="sp")


def test_fsdp_zero_shards_memory_and_matches_dp():
    """ZeRO/fsdp (SURVEY §2.3 'design fresh'): params + optimizer state
    sharded over the data axis, XLA all-gathers weights at their use sites
    and reduce-scatters grads into the sharded update. Asserts (a) the
    collectives are really in the compiled step, (b) per-device param+state
    memory drops ~N×, (c) the loss trajectory matches pure dp."""
    def make_net():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(256, activation="relu", use_bias=False),
                gluon.nn.Dense(256, activation="relu", use_bias=False),
                gluon.nn.Dense(8, use_bias=False))
        net.initialize()
        with autograd.predict_mode():
            net(mx.np.array(np.zeros((2, 64), dtype="float32")))
        return net

    np.random.seed(2)
    net_dp = make_net()
    net_fs = make_net()
    pd, pf = net_dp.collect_params(), net_fs.collect_params()
    for n in pd:
        pf[n].set_data(pd[n].data())
    X = np.random.randn(16, 64).astype("float32")
    Y = np.random.randint(0, 8, (16,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": 8})

    tr_dp = ShardedTrainer(net_dp, loss_fn, "adam", {"learning_rate": 1e-2},
                           mesh=mesh, rules=ShardingRules(default_axis=None))
    # fsdp = the default rule sharding every param's largest dim over dp
    tr_fs = ShardedTrainer(net_fs, loss_fn, "adam", {"learning_rate": 1e-2},
                           mesh=mesh, rules=ShardingRules(default_axis="dp"))

    losses_dp = [float(tr_dp.step(X, Y).asnumpy()) for _ in range(5)]
    losses_fs = [float(tr_fs.step(X, Y).asnumpy()) for _ in range(5)]
    np.testing.assert_allclose(losses_dp, losses_fs, rtol=1e-4, atol=1e-5)

    # (a) gather-for-compute / scatter-for-update in the compiled program.
    # The CPU backend lowers reduce-scatter as all-reduce + dynamic-slice
    # (same sharded-grad semantics); TPU emits the fused reduce-scatter.
    hlo = tr_fs.step_hlo
    assert "all-gather" in hlo
    assert "reduce-scatter" in hlo or (
        "all-reduce" in hlo and "dynamic-slice" in hlo)
    # (b) params + adam (m, v) state per device: dp holds full copies,
    # fsdp holds 1/8 shards (all dims here divide 8)
    mem_dp = tr_dp.device_memory_bytes()
    mem_fs = tr_fs.device_memory_bytes()
    assert mem_fs < mem_dp / 6
    # (c) a param really is sharded
    w = tr_fs.params["0.weight"]
    assert w.addressable_shards[0].data.shape[0] * 8 == w.shape[0]


def test_step_n_matches_sequential_steps():
    """One fused scan window == the same steps dispatched one by one
    (bulk-exec semantics, engine.h:311-317)."""
    np.random.seed(4)
    net_a = _mlp()
    net_b = _mlp()
    pa, pb = net_a.collect_params(), net_b.collect_params()
    for n in pa:
        pb[n].set_data(pa[n].data())
    mesh = make_mesh({"dp": 8})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr_a = ShardedTrainer(net_a, loss_fn, "sgd",
                          {"learning_rate": 0.05, "momentum": 0.9},
                          mesh=mesh, rules=ShardingRules(default_axis=None))
    tr_b = ShardedTrainer(net_b, loss_fn, "sgd",
                          {"learning_rate": 0.05, "momentum": 0.9},
                          mesh=mesh, rules=ShardingRules(default_axis=None))
    X = np.random.randn(4, 16, 20).astype("float32")
    Y = np.random.randint(0, 10, (4, 16))
    losses_fused = tr_a.step_n(X, Y).asnumpy()
    losses_seq = [float(tr_b.step(X[i], Y[i]).asnumpy()) for i in range(4)]
    np.testing.assert_allclose(losses_fused, losses_seq, rtol=1e-5,
                               atol=1e-6)
    for n in tr_a.params:
        np.testing.assert_allclose(
            np.asarray(tr_a.params[n]), np.asarray(tr_b.params[n]),
            rtol=2e-5, atol=2e-5)


def test_step_n_then_step_interleave():
    """step_n and step share optimizer bookkeeping (update counts)."""
    net = _mlp()
    mesh = make_mesh({"dp": 8})
    tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
                        {"learning_rate": 1e-2}, mesh=mesh,
                        rules=ShardingRules(default_axis=None))
    X = np.random.randn(3, 8, 20).astype("float32")
    Y = np.random.randint(0, 10, (3, 8))
    tr.step_n(X, Y)
    loss = tr.step(X[0], Y[0])
    assert np.isfinite(float(loss.asnumpy()))
    assert tr._step_count == 4


def test_step_n_validates_num_steps_and_keeps_flops_per_step():
    net = _mlp()
    mesh = make_mesh({"dp": 8})
    tr = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                        {"learning_rate": 0.05}, mesh=mesh,
                        rules=ShardingRules(default_axis=None))
    X = np.random.randn(3, 8, 20).astype("float32")
    Y = np.random.randint(0, 10, (3, 8))
    with pytest.raises(mx.MXNetError, match="num_steps"):
        tr.step_n(X, Y, num_steps=5)  # only 3 stacked batches
    with pytest.raises(mx.MXNetError, match="num_steps"):
        tr.step_n(X, Y, num_steps=0)
    tr.step_n(X, Y, num_steps=2)
    assert tr._step_count == 2
    flops_window = tr.step_flops
    tr.step(X[0], Y[0])
    # the property stays per-step across both paths
    assert abs(tr.step_flops - flops_window) / tr.step_flops < 0.2


def test_ulysses_attention_matches_reference():
    """All-to-all sequence parallelism == single-device attention, incl.
    causal; sharding preserved (T stays sharded on sp)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas.flash_attention import _reference_attention
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.ring_attention import (
        sequence_sharded,
        ulysses_attention,
    )

    mesh = make_mesh({"sp": 4})
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 8, 32, 16
    q = jnp.asarray(rng.randn(B, H, T, D).astype("float32") * 0.3)
    k = jnp.asarray(rng.randn(B, H, T, D).astype("float32") * 0.3)
    v = jnp.asarray(rng.randn(B, H, T, D).astype("float32") * 0.3)
    for causal in (False, True):
        qs = sequence_sharded(q, mesh)
        ks = sequence_sharded(k, mesh)
        vs = sequence_sharded(v, mesh)
        got = ulysses_attention(qs, ks, vs, mesh=mesh, causal=causal)
        want = _reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                    rtol=2e-4, atol=2e-5)


def test_sharded_trainer_checkpoint_resume(tmp_path):
    """save_checkpoint/load_checkpoint: bit-exact resume of the SPMD
    training trajectory (params + Adam state + step count) across a new
    trainer instance, with shardings restored."""
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    rng = np.random.RandomState(4)
    X = rng.randn(8, 16).astype("float32")
    Y = rng.randn(8, 8).astype("float32")

    def build():
        mx.random.seed(17)
        net = gluon.nn.Dense(8, flatten=False)
        net.initialize()
        with autograd.predict_mode():
            net(mx.np.array(np.zeros((1, 16), "float32")))
        return ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                              {"learning_rate": 1e-2}, mesh=mesh,
                              rules=ShardingRules())

    tr = build()
    for _ in range(2):
        tr.step(X, Y)
    ckpt = str(tmp_path / "state.ckpt")
    tr.save_checkpoint(ckpt)
    cont = [float(tr.step(X, Y).asnumpy().reshape(-1)[0])
            for _ in range(2)]

    tr2 = build()
    tr2.load_checkpoint(ckpt)
    resumed = [float(tr2.step(X, Y).asnumpy().reshape(-1)[0])
               for _ in range(2)]
    np.testing.assert_allclose(resumed, cont, rtol=1e-6)
    # shardings restored, not gathered-to-one-device
    any_sharded = any(
        len(a.sharding.device_set) > 1 for a in tr2.params.values())
    assert any_sharded


def test_checkpoint_rejects_mismatched_optimizer():
    mesh = make_mesh({"dp": 8})

    def build(opt):
        mx.random.seed(17)
        net = gluon.nn.Dense(8, flatten=False)
        net.initialize()
        with autograd.predict_mode():
            net(mx.np.array(np.zeros((1, 16), "float32")))
        return ShardedTrainer(net, gluon.loss.L2Loss(), opt,
                              {"learning_rate": 1e-2}, mesh=mesh,
                              rules=ShardingRules(default_axis=None))

    import pytest as _pytest

    tr = build("adam")
    tr.step(np.zeros((8, 16), "float32"), np.zeros((8, 8), "float32"))
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        ckpt = d + "/s.ckpt"
        tr.save_checkpoint(ckpt)
        tr2 = build("sgd")
        with _pytest.raises(mx.MXNetError, match="optimizer"):
            tr2.load_checkpoint(ckpt)


def test_checkpoint_restores_rng_stream(tmp_path):
    """A model WITH dropout resumes the exact loss trajectory: the RNG
    key is part of the checkpoint."""
    mesh = make_mesh({"dp": 2})
    X = np.random.RandomState(1).randn(8, 16).astype("float32")
    Y = np.zeros((8, 8), "float32")

    def build():
        mx.random.seed(23)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, flatten=False), gluon.nn.Dropout(0.5),
                gluon.nn.Dense(8, flatten=False))
        net.initialize()
        with autograd.predict_mode():
            net(mx.np.array(np.zeros((1, 16), "float32")))
        return ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                              {"learning_rate": 1e-2}, mesh=mesh,
                              rules=ShardingRules(default_axis=None))

    tr = build()
    for _ in range(2):
        tr.step(X, Y)
    ckpt = str(tmp_path / "rng.ckpt")
    tr.save_checkpoint(ckpt)
    cont = [float(tr.step(X, Y).asnumpy().reshape(-1)[0]) for _ in range(3)]
    tr2 = build()
    tr2.load_checkpoint(ckpt)
    resumed = [float(tr2.step(X, Y).asnumpy().reshape(-1)[0])
               for _ in range(3)]
    np.testing.assert_allclose(resumed, cont, rtol=1e-6)
