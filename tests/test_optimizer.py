"""Optimizer tests: each rule vs a NumPy re-implementation on one step."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np
from mxnet_tpu.optimizer import create


def _run_steps(opt_name, kwargs, steps=3):
    onp.random.seed(0)
    w0 = onp.random.rand(4, 3).astype("float32")
    grads = [onp.random.rand(4, 3).astype("float32") - 0.5 for _ in range(steps)]
    opt = create(opt_name, **kwargs)
    w = np.array(w0)
    state = opt.create_state_multi_precision(0, w)
    for g in grads:
        opt.update_multi_precision(0, w, np.array(g), state)
    return w0, grads, w.asnumpy()


def test_sgd_matches_manual():
    w0, grads, got = _run_steps("sgd", {"learning_rate": 0.1, "momentum": 0.9,
                                        "wd": 0.01})
    w = w0.copy()
    mom = onp.zeros_like(w)
    for g in grads:
        g = g + 0.01 * w
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    onp.testing.assert_allclose(got, w, rtol=1e-5)


def test_adam_matches_manual():
    w0, grads, got = _run_steps("adam", {"learning_rate": 0.01})
    w = w0.copy()
    m = onp.zeros_like(w)
    v = onp.zeros_like(w)
    for t, g in enumerate(grads, 1):
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** t)
        vh = v / (1 - 0.999 ** t)
        w = w - 0.01 * mh / (onp.sqrt(vh) + 1e-8)
    onp.testing.assert_allclose(got, w, rtol=1e-5)


def test_adamw_decoupled_decay():
    w0, grads, got = _run_steps("adamw", {"learning_rate": 0.01, "wd": 0.1})
    w = w0.copy()
    m = onp.zeros_like(w)
    v = onp.zeros_like(w)
    for t, g in enumerate(grads, 1):
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** t)
        vh = v / (1 - 0.999 ** t)
        w = w - 0.01 * (mh / (onp.sqrt(vh) + 1e-8) + 0.1 * w)
    onp.testing.assert_allclose(got, w, rtol=1e-5)


@pytest.mark.parametrize("name,kwargs", [
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.05}),
    ("adadelta", {}),
    ("adamax", {"learning_rate": 0.05}),
    ("nadam", {"learning_rate": 0.05}),
    ("ftrl", {}),
    ("ftml", {"learning_rate": 0.05}),
    ("signum", {"learning_rate": 0.01}),
    ("lars", {"learning_rate": 0.05}),
    ("lamb", {"learning_rate": 0.05}),
    ("lans", {"learning_rate": 0.05}),
    ("sgld", {"learning_rate": 0.01}),
    ("dcasgd", {"learning_rate": 0.01}),
])
def test_optimizer_decreases_quadratic(name, kwargs):
    """Every optimizer must make progress on a simple quadratic."""
    target = onp.array([1.0, -2.0, 3.0], "float32")
    w = np.array(onp.zeros(3, "float32"))
    opt = create(name, **kwargs)
    state = opt.create_state(0, w)
    loss0 = float(((w.asnumpy() - target) ** 2).sum())
    for _ in range(400):
        g = 2 * (w.asnumpy() - target)
        opt.update(0, w, np.array(g), state)
    loss1 = float(((w.asnumpy() - target) ** 2).sum())
    assert loss1 < loss0 * 0.5, f"{name}: {loss0} -> {loss1}"


def test_multi_precision_fp16():
    opt = create("sgd", learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = np.array(onp.ones(4, "float16"))
    state = opt.create_state_multi_precision(0, w)
    assert isinstance(state, tuple) and state[0].dtype == onp.float32
    opt.update_multi_precision(0, w, np.array(onp.ones(4, "float16")), state)
    assert w.dtype == onp.float16


def test_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert sched(0) == 1.0
    assert sched(10) == 0.5
    assert sched(25) == 0.25
    cos = mx.lr_scheduler.CosineScheduler(100, base_lr=1.0, final_lr=0.0)
    assert cos(0) == pytest.approx(1.0)
    assert cos(50) == pytest.approx(0.5, abs=1e-6)
    assert cos(100) == 0.0
    warm = mx.lr_scheduler.PolyScheduler(100, base_lr=1.0, warmup_steps=10)
    assert warm(5) < 1.0
