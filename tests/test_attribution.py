"""Decode critical-path attribution (PR 16 tentpole, layer 1): the
four-way phase ledger (host / dispatch / device / wait partitioning each
``serve::decode_step`` span), phase-tagged ``engine:wait`` accounting,
per-request ``attribution.report(trace_id)`` over a live
ContinuousEngine, the ``ServeMetrics`` ``(ms, live)`` ITL pairs +
attribution gauges, and the <5% disabled-path overhead contract."""
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mnp
from mxnet_tpu import profiler
from mxnet_tpu.profiler import attribution, core, export, trace


@pytest.fixture(autouse=True)
def _clean_attribution_state():
    profiler.set_state("stop")
    profiler.reset()
    trace.disable()
    trace.reset()
    attribution.disable()
    attribution.reset()
    yield
    profiler.set_state("stop")
    profiler.reset()
    trace.disable()
    trace.reset()
    attribution.disable()
    attribution.reset()


# -- phase scopes + wait capture ---------------------------------------------


def test_phase_scope_nests_and_restores():
    assert attribution.current_phase() == "other"
    with attribution.phase_scope("decode"):
        assert attribution.current_phase() == "decode"
        with attribution.phase_scope("prefill"):
            assert attribution.current_phase() == "prefill"
        assert attribution.current_phase() == "decode"
    assert attribution.current_phase() == "other"


def test_note_wait_buckets_by_phase_and_thread_total():
    attribution.enable()
    w0 = attribution.thread_wait_ns()
    with attribution.phase_scope("decode"):
        attribution.note_wait(2_000_000)          # 2 ms, tagged decode
    attribution.note_wait(1_000_000, "train")     # explicit phase wins
    attribution.note_wait(500_000)                # unlabeled -> other
    by_phase = attribution.wait_ms_by_phase()
    assert by_phase["decode"] == pytest.approx(2.0)
    assert by_phase["train"] == pytest.approx(1.0)
    assert by_phase["other"] == pytest.approx(0.5)
    # the thread accumulator is monotone (loops difference snapshots)
    assert attribution.thread_wait_ns() - w0 == 3_500_000
    # disabled note_wait is a no-op
    attribution.disable()
    attribution.note_wait(10_000_000, "decode")
    assert attribution.wait_ms_by_phase()["decode"] == pytest.approx(2.0)


def test_engine_wait_hook_feeds_phase_tagged_ledger():
    """A real blocking engine wait inside a phase scope lands in that
    phase's bucket via the ``engine._ATTR`` slot."""
    attribution.enable()
    x = mnp.ones((64, 64))
    with attribution.phase_scope("decode"):
        y = (x @ x).sum()
        y.wait_to_read()
    assert attribution.wait_ms_by_phase().get("decode", 0.0) >= 0.0
    assert attribution.thread_wait_ns() > 0


# -- the Ledger --------------------------------------------------------------


def test_ledger_math_and_bounds():
    led = attribution.Ledger("t", window=4)
    assert led.host_overhead_fraction() == 0.0
    assert led.device_ms_per_token() == 0.0
    led.observe_step(1.0, 2.0, 6.0, 1.0, live=2)
    led.observe_step(0.0, 1.0, 7.0, 0.0, live=2)
    led.observe_schedule(2.0)
    snap = led.snapshot()
    # hof = (sched + host + dispatch + wait) / total
    assert snap["host_overhead_fraction"] == pytest.approx(7.0 / 20.0)
    assert snap["device_ms_per_token"] == pytest.approx(13.0 / 4.0)
    assert snap["steps"] == 2 and snap["tokens"] == 4
    assert 0.0 <= snap["host_overhead_fraction"] <= 1.0
    # bounded window: old rows fall out, lifetime step count doesn't
    for _ in range(6):
        led.observe_step(0.0, 0.0, 1.0, 0.0, live=1)
    snap = led.snapshot()
    assert snap["window"] == 4 and snap["steps"] == 8
    assert snap["device_ms"] == pytest.approx(4.0)


def test_ledger_exports_through_snapshot_and_serve_gauges():
    from mxnet_tpu.serve.metrics import ServeMetrics

    attribution.enable()
    led = attribution.Ledger("exp_test")
    led.observe_step(1.0, 1.0, 8.0, 0.0, live=2)
    m = ServeMetrics("exp_test")
    m.set_attribution(led.host_overhead_fraction(),
                      led.device_ms_per_token())
    snap = export.snapshot()
    assert snap["attribution.exp_test.device_ms_per_token"] == \
        pytest.approx(4.0)
    assert snap["serve.exp_test.host_overhead_fraction"] == \
        pytest.approx(0.2)
    assert 0.0 <= snap["attribution.exp_test.host_overhead_fraction"] <= 1.0


# -- ServeMetrics (ms, live) ITL pairs ---------------------------------------


def test_observe_itl_records_live_pairs_backward_compatible():
    from mxnet_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics("itl_pairs")
    m.observe_itl(5.0)            # old single-arg call keeps working
    m.observe_itl(7.0, live=4)
    assert m.itl_samples() == [(5.0, 1), (7.0, 4)]
    snap = m.snapshot()
    assert snap["itl_p50_ms"] > 0.0          # percentile surface intact
    assert snap["itl_live_mean"] == pytest.approx(2.5)


# -- end to end over a live ContinuousEngine ---------------------------------


def _tiny_engine(**over):
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import ContinuousEngine

    net = get_llama("llama_tiny_test")
    net.initialize()
    kw = dict(max_seq=64, num_slots=4, page_size=16, prefill_chunk=16,
              decode_path="baseline", name="attr_cb")
    kw.update(over)
    return ContinuousEngine(net, **kw)


@pytest.mark.serial
def test_report_e2e_continuous_engine():
    """The acceptance path: a traced request through the iteration-level
    scheduler yields a critical-path report whose decode phase carries
    ledger args summing within 10% of the span walls."""
    attribution.enable()
    trace.enable()
    with _tiny_engine() as eng:
        futs = [eng.submit([5, 6, 7], max_new_tokens=8),
                eng.submit([9, 10, 11, 12], max_new_tokens=8)]
        for f in futs:
            assert len(f.result(timeout=60)["tokens"]) == 8
        snap = eng.ledger.snapshot()
        assert snap["steps"] > 0
        assert 0.0 < snap["host_overhead_fraction"] <= 1.0
        assert snap["device_ms_per_token"] > 0.0
        ms = eng.metrics.snapshot()
        assert ms["device_ms_per_token"] > 0.0
        assert ms["itl_live_mean"] >= 1.0

    tid = [s["trace_id"] for s in trace.summaries(limit=50)
           if s["name"].startswith("serve.request")][-1]
    rep = attribution.report(tid)
    assert rep is not None and rep["finished"]
    assert rep["decode_steps"] > 0
    assert rep["ledger_steps"] == rep["decode_steps"]
    assert rep["prefill_chunks"] >= 1
    lsum = sum(rep["phase_ledger"].values())
    assert lsum == pytest.approx(rep["decode_ms"],
                                 rel=0.10, abs=1.0)
    # every decode_step span's four args reconcile with ITS wall
    for sp in trace.summary(tid)["spans"]:
        if sp["name"] != "serve::decode_step":
            continue
        a = sp["args"]
        s = sum(a[k] for k in ("host_ms", "dispatch_ms", "device_ms",
                               "wait_ms"))
        assert abs(s - sp["dur_ms"]) <= max(0.10 * sp["dur_ms"], 0.05), \
            (s, sp["dur_ms"], a)


def test_report_unknown_trace_is_none():
    assert attribution.report(999_999) is None


def test_disabled_engine_records_nothing():
    """ENABLED=False: no span args, empty ledger, zero cost branches."""
    trace.enable()
    with _tiny_engine(name="attr_off") as eng:
        eng.submit([5, 6, 7], max_new_tokens=4).result(timeout=60)
        assert eng.ledger.snapshot()["steps"] == 0
    tid = [s["trace_id"] for s in trace.summaries(limit=50)
           if s["name"].startswith("serve.request")][-1]
    rep = attribution.report(tid)
    assert rep["decode_steps"] > 0 and rep["ledger_steps"] == 0


# -- overhead bound ----------------------------------------------------------


@pytest.mark.serial
def test_disabled_attribution_overhead_under_5pct():
    """Eager microloop with the attribution slot installed but ENABLED
    False must stay within 5% of the slot-removed baseline — the same
    cost contract as the profiler/trace hooks."""
    from mxnet_tpu import engine

    x = mnp.ones((4,))

    def loop(n=10_000):
        y = x
        t0 = time.perf_counter()
        for _ in range(n):
            y = y + 1.0
        y.wait_to_read()
        return time.perf_counter() - t0

    saved = engine._ATTR

    def measure(rounds=7):
        base = hooked = float("inf")
        for _ in range(rounds):
            engine._ATTR = None
            base = min(base, loop())
            attribution._install_engine_slot()
            attribution.disable()  # slot present, ledger off
            hooked = min(hooked, loop())
        return base, hooked

    try:
        loop(2000)  # warm caches before either arm
        base, hooked = measure()
        if hooked > base * 1.05:  # timing noise: one clean re-measure
            base, hooked = measure(rounds=9)
    finally:
        engine._ATTR = saved
    assert hooked <= base * 1.05, (
        f"disabled attribution overhead {hooked / base - 1:.1%} "
        f"(baseline {base:.3f}s, hooked {hooked:.3f}s)")
