#!/usr/bin/env python
"""Generate golden reference-format artifacts for the compat tests.

This is a deliberate, INDEPENDENT byte-level transcription of the
reference writers — it shares no code with
``mxnet_tpu/ndarray/legacy_serialization.py`` (the library reader under
test), so a bug in the library's understanding of the format cannot
cancel out in the tests. Sources transcribed:

* list container + per-array payload: ``/root/reference/src/ndarray/
  ndarray.cc:1693-1776, 1935-1945`` (NDArray::Save, V2 magic 0xF993fac9,
  list magic 0x112), TShape = int32 ndim + int64 dims
  (``include/mxnet/tuple.h:731``), Context = int32 dev_type + int32
  dev_id (``include/mxnet/base.h:145``), mshadow type flags
  (``3rdparty/mshadow/mshadow/base.h:339``)
* the pre-V1 payload where the magic word IS the ndim followed by
  uint32 dims (``ndarray.cc:1778-1800`` LegacyTShapeLoad default case)
* 1.x-era symbol JSON with attrs under ``"param"`` and ``"attr"``
  (upgraded by ``src/nnvm/legacy_json_util.cc``)

Deterministic: all values are arange-derived literals. Re-running must
reproduce the committed files byte-for-byte (asserted by the test).
"""
import json
import os
import struct
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
# optional output dir (tests regenerate into a tmp dir to compare hashes
# without touching the committed artifacts)
OUT = sys.argv[1] if len(sys.argv) > 1 else HERE

LIST_MAGIC = 0x112
V2_MAGIC = 0xF993FAC9
F32, I64 = 0, 6  # mshadow type flags


def tshape(shape):
    return struct.pack("<i", len(shape)) + struct.pack(
        f"<{len(shape)}q", *shape)


def dense_v2(arr):
    out = struct.pack("<I", V2_MAGIC)
    out += struct.pack("<i", 0)                    # kDefaultStorage
    out += tshape(arr.shape)
    out += struct.pack("<ii", 1, 0)                # cpu:0
    out += struct.pack("<i", F32)
    out += np.ascontiguousarray(arr, np.float32).tobytes()
    return out


def dense_prev1(arr):
    """Ancient payload: magic word IS ndim, dims are uint32."""
    out = struct.pack("<I", arr.ndim)
    out += struct.pack(f"<{arr.ndim}I", *arr.shape)
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", F32)
    out += np.ascontiguousarray(arr, np.float32).tobytes()
    return out


def csr_v2(values, indptr, indices, shape):
    out = struct.pack("<I", V2_MAGIC)
    out += struct.pack("<i", 2)                    # kCSRStorage
    out += tshape(values.shape)                    # storage shape
    out += tshape(shape)
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", F32)
    out += struct.pack("<i", I64) + tshape(indptr.shape)
    out += struct.pack("<i", I64) + tshape(indices.shape)
    out += np.ascontiguousarray(values, np.float32).tobytes()
    out += np.ascontiguousarray(indptr, np.int64).tobytes()
    out += np.ascontiguousarray(indices, np.int64).tobytes()
    return out


def row_sparse_v2(values, indices, shape):
    out = struct.pack("<I", V2_MAGIC)
    out += struct.pack("<i", 1)                    # kRowSparseStorage
    out += tshape(values.shape)
    out += tshape(shape)
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", F32)
    out += struct.pack("<i", I64) + tshape(indices.shape)
    out += np.ascontiguousarray(values, np.float32).tobytes()
    out += np.ascontiguousarray(indices, np.int64).tobytes()
    return out


def list_file(payloads, names):
    out = struct.pack("<QQ", LIST_MAGIC, 0)
    out += struct.pack("<Q", len(payloads))
    out += b"".join(payloads)
    out += struct.pack("<Q", len(names))
    for n in names:
        out += struct.pack("<Q", len(n.encode())) + n.encode()
    return out


def mlp_params():
    """Deterministic MLP weights (see golden-symbol.json)."""
    w1 = (np.arange(12, dtype=np.float32).reshape(3, 4) - 5.0) / 10.0
    b1 = np.array([0.1, -0.2, 0.3], np.float32)
    w2 = (np.arange(6, dtype=np.float32).reshape(2, 3) - 2.0) / 5.0
    b2 = np.array([-0.5, 0.5], np.float32)
    return w1, b1, w2, b2


def main():
    w1, b1, w2, b2 = mlp_params()
    with open(os.path.join(OUT, "golden_mlp.params"), "wb") as f:
        f.write(list_file(
            [dense_v2(w1), dense_v2(b1), dense_v2(w2), dense_v2(b2)],
            ["arg:fc1_weight", "arg:fc1_bias", "arg:fc2_weight",
             "arg:fc2_bias"]))

    # unnamed list holding one modern + one pre-V1 ancient payload
    anc = np.arange(6, dtype=np.float32).reshape(2, 3)
    with open(os.path.join(OUT, "golden_legacy.nd"), "wb") as f:
        f.write(list_file([dense_v2(anc * 2.0), dense_prev1(anc)], []))

    # sparse pair: the 4x5 csr of [[0,1,0,2,0],[0,0,3,0,0],[0]*5,[4,0,0,0,5]]
    vals = np.array([1, 2, 3, 4, 5], np.float32)
    indptr = np.array([0, 2, 3, 3, 5], np.int64)
    indices = np.array([1, 3, 2, 0, 4], np.int64)
    rs_vals = np.array([[1, 2, 3], [4, 5, 6]], np.float32)
    rs_idx = np.array([1, 3], np.int64)
    with open(os.path.join(OUT, "golden_sparse.params"), "wb") as f:
        f.write(list_file(
            [csr_v2(vals, indptr, indices, (4, 5)),
             row_sparse_v2(rs_vals, rs_idx, (4, 3))],
            ["csr0", "rs0"]))

    # 1.x-era symbol JSON: "param" (pre-0.9) on fc1, "attr" (pre-1.0) on
    # the Activation, hidden keys (lr_mult) that the upgrade must drop
    sym = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc1_weight", "inputs": [],
             "attr": {"__shape__": "(3, 4)"}},
            {"op": "null", "name": "fc1_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc1",
             "param": {"num_hidden": "3", "lr_mult": "0.1"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "relu1",
             "attr": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
            {"op": "null", "name": "fc2_weight", "inputs": []},
            {"op": "null", "name": "fc2_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc2",
             "attrs": {"num_hidden": "2"},
             "inputs": [[4, 0, 0], [5, 0, 0], [6, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2, 5, 6],
        "node_row_ptr": list(range(9)),
        "heads": [[7, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10700]},
    }
    with open(os.path.join(OUT, "golden-symbol.json"), "w") as f:
        json.dump(sym, f, indent=2)
    print("golden files written to", OUT)


if __name__ == "__main__":
    main()
