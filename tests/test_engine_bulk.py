"""Deferred-dispatch conformance suite (engine bulk segments).

Contract under test: inside ``engine.bulk(N)`` imperative ops record into
a per-thread segment flushed as ONE compiled executable — with results
(values, gradients, updated params) BITWISE identical to unbulked per-op
dispatch, flush-on-materialize/tape semantics, NaiveEngine forced to
size 1, fault plans still tripping per recorded op, and the default-off
path inside the established <5% eager-microloop overhead bound.
"""
import contextlib
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon
from mxnet_tpu import np
from mxnet_tpu.ops import registry
from mxnet_tpu.resilience import faults


@contextlib.contextmanager
def _unbulked():
    """Pin deferral OFF for a comparison arm — the suite must stay
    meaningful under the tier-1 MXNET_ENGINE_BULK_SIZE=16 second pass,
    where a bare nullcontext would silently bulk both arms."""
    prev = engine.set_bulk_size(0)
    try:
        yield
    finally:
        engine.set_bulk_size(prev)


def setup_function(_fn):
    # tests assert on flush/dispatch counters: start each from zero
    engine.flush_current("manual")
    engine.bulk_stats(reset=True)
    engine.reset_dispatch_count()


# ---------------------------------------------------------------------------
# Laziness + flush semantics
# ---------------------------------------------------------------------------


def test_ops_defer_and_flush_on_materialize():
    a = np.array(onp.arange(6.0, dtype="float32").reshape(2, 3))
    with engine.bulk(16):
        b = np.tanh((a + 1) * 2)
        # pending: lazy placeholder, shape/dtype answered WITHOUT a flush
        assert type(b._buf) is engine._LazyRef
        assert b.shape == (2, 3) and str(b.dtype) == "float32"
        assert engine.bulk_stats()["flushes"] == 0
        out = b.asnumpy()  # materialization flushes
        stats = engine.bulk_stats()
        assert stats["flushes"] == 1
        assert stats["reasons"] == {"materialize": 1}
        assert stats["ops_flushed"] == 3
    ref = np.tanh((a + 1) * 2).asnumpy()
    onp.testing.assert_array_equal(out, ref)


def test_segment_flushes_at_size_cap():
    a = np.array(onp.ones((4,), "float32"))
    with engine.bulk(3):
        b = a + 1
        c = b + 1
        d = c + 1  # 3rd op: cap reached, flush without materialization
        assert engine.bulk_stats()["reasons"].get("size") == 1
        assert type(d._buf) is not engine._LazyRef or d._buf.value is not None
        e = d + 1  # lands in a fresh segment
        assert type(e._buf) is engine._LazyRef and e._buf.value is None
    onp.testing.assert_array_equal(e.asnumpy(), onp.full((4,), 5.0, "f4"))


def test_flush_on_tape_boundary_and_backward_parity():
    xv = onp.random.randn(5, 4).astype("float32")

    def run(bulked):
        x = np.array(xv)
        x.attach_grad()
        scope = engine.bulk(16) if bulked else _unbulked()
        with scope:
            with autograd.record():
                y = ((x * 2 + 1) ** 2).sum()
            y.backward()  # tape boundary: flush installs the segment node
            return x.grad.asnumpy().copy()

    g_plain = run(False)
    engine.bulk_stats(reset=True)
    g_bulk = run(True)
    assert engine.bulk_stats()["reasons"].get("tape") == 1
    onp.testing.assert_array_equal(g_plain, g_bulk)


def test_segment_cache_hits_in_steady_state():
    a = np.array(onp.ones((8,), "float32"))
    for _ in range(4):
        with engine.bulk(16):
            out = np.tanh((a + 1) * 2).asnumpy()
    stats = engine.bulk_stats()
    assert stats["flushes"] == 4
    # one compile, then replay of the cached segment executable
    assert stats["cache_hits"] >= 3
    onp.testing.assert_array_equal(
        out, np.tanh((a + 1) * 2).asnumpy())


def test_wait_all_flushes_pending_segment():
    a = np.array(onp.ones((4,), "float32"))
    with engine.bulk(16):
        b = a * 3
        assert type(b._buf) is engine._LazyRef
        engine.wait_all()
        assert engine.bulk_stats()["reasons"].get("wait") == 1
        assert b._buf.value is not None
    onp.testing.assert_array_equal(b.asnumpy(), onp.full((4,), 3.0, "f4"))


def test_wait_all_drains_other_threads_segments():
    """wait_all's drain-all contract covers segments recorded on OTHER
    threads: their deferred ops must be submitted (and any errors
    surfaced) before wait_all returns."""
    recorded = threading.Event()
    release = threading.Event()
    out = {}

    def worker():
        engine.set_bulk_size(16)
        a = np.array(onp.ones((4,), "float32"))
        b = a + 5
        out["ref"] = b._buf
        out["handle"] = b
        recorded.set()
        release.wait(timeout=10)

    t = threading.Thread(target=worker)
    t.start()
    assert recorded.wait(timeout=10)
    assert type(out["ref"]) is engine._LazyRef and out["ref"].value is None
    engine.wait_all()  # must flush the WORKER's pending segment too
    assert out["ref"].value is not None, \
        "wait_all returned with another thread's segment still pending"
    release.set()
    t.join()
    onp.testing.assert_array_equal(out["handle"].asnumpy(),
                                   onp.full((4,), 6.0, "f4"))


def test_undeferrable_rng_op_flushes_then_dispatches():
    """Dropout draws a key per call: never deferred (a cached segment
    would bake the mask) — it flushes the pending segment, dispatches
    directly, and randomness survives."""
    from mxnet_tpu.ops import nn as _nn

    a = np.ones((32, 32))
    with engine.bulk(32):
        b = a * 2  # pending
        with autograd.train_mode():
            d1 = _nn.dropout(b, p=0.5).asnumpy()
            d2 = _nn.dropout(b, p=0.5).asnumpy()
    assert (d1 != d2).any(), "dropout mask froze under bulking"
    assert engine.bulk_stats()["reasons"].get("undeferrable", 0) >= 1


# ---------------------------------------------------------------------------
# Bitwise parity: eager LeNet training step + >=5x dispatch collapse
# ---------------------------------------------------------------------------


def _lenet():
    mx.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 5, activation="relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(120, activation="relu"),
            gluon.nn.Dense(84, activation="relu"), gluon.nn.Dense(10))
    net.initialize()
    return net


def _lenet_steps(bulk_n, xv, yv, n_steps=2):
    net = _lenet()
    x = np.array(xv)
    y = np.array(yv)
    with autograd.predict_mode():
        net(x)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})
    losses = []
    dispatches = []
    for _ in range(n_steps):
        scope = engine.bulk(bulk_n) if bulk_n else _unbulked()
        before = engine.dispatch_count()
        with scope:
            with autograd.record():
                l = loss_fn(net(x), y).mean()
            l.backward()
            tr.step(1)
            losses.append(float(l.asnumpy()))
        dispatches.append(engine.dispatch_count() - before)
    params = {k: v.data().asnumpy().copy()
              for k, v in net.collect_params().items()}
    return losses, params, dispatches


@pytest.mark.serial
def test_lenet_step_bitwise_parity_and_5x_dispatch_drop():
    """The PR's acceptance gate: with engine.bulk(16) an eager LeNet
    train step makes >=5x fewer executable invocations than unbulked,
    with bitwise-identical losses and updated parameters."""
    rng = onp.random.RandomState(0)
    xv = rng.randn(8, 1, 28, 28).astype("float32")
    yv = rng.randint(0, 10, (8,)).astype("int64")
    l_plain, p_plain, d_plain = _lenet_steps(0, xv, yv)
    l_bulk, p_bulk, d_bulk = _lenet_steps(16, xv, yv)
    assert l_plain == l_bulk, f"loss drift: {l_plain} vs {l_bulk}"
    for k in p_plain:
        onp.testing.assert_array_equal(
            p_plain[k], p_bulk[k],
            err_msg=f"param {k} not bitwise identical under bulk(16)")
    # steady-state step (step 2: caches warm on both arms)
    assert d_plain[-1] >= 5 * d_bulk[-1], (
        f"dispatch drop below 5x: {d_plain[-1]} unbulked vs "
        f"{d_bulk[-1]} bulked")
    stats = engine.bulk_stats()
    assert stats["flushes"] >= 2 and stats["ops_flushed"] >= 20


def test_autograd_train_step_bitwise_parity():
    """Plain (non-gluon) autograd train step: forward under record,
    backward, manual SGD — gradients and weights bulk-vs-unbulked must
    agree BITWISE across steps. The per-op fences pin every op's
    numerics, so the only sanctioned divergence is the loss SCALAR: XLA
    may pick a different reduce emitter for a reduction inside a fused
    segment module than for its standalone executable (<= 1 ulp)."""
    rng = onp.random.RandomState(3)
    xv = rng.randn(16, 8).astype("float32")
    wv = rng.randn(8, 4).astype("float32")

    def run(bulked):
        x = np.array(xv)
        w = np.array(wv)
        w.attach_grad()
        outs, grads = [], []
        for _ in range(3):
            scope = engine.bulk(16) if bulked else _unbulked()
            with scope:
                with autograd.record():
                    h = np.tanh(x @ w)
                    l = (h * h).mean()
                l.backward()
                grads.append(w.grad.asnumpy().copy())
                w -= 0.1 * w.grad
                outs.append(float(l.asnumpy()))
        return outs, grads, w.asnumpy().copy()

    l_plain, g_plain, w_plain = run(False)
    l_bulk, g_bulk, w_bulk = run(True)
    for gp, gb in zip(g_plain, g_bulk):
        onp.testing.assert_array_equal(gp, gb)
    onp.testing.assert_array_equal(w_plain, w_bulk)
    onp.testing.assert_allclose(l_plain, l_bulk, rtol=3e-7, atol=0)


def test_pause_inside_bulk_blocks_gradient():
    """An op recorded under autograd.pause() is a CONSTANT on the tape;
    the segment vjp must not conduct gradient through it (stop_gradient
    fences in the replay), matching unbulked eager exactly."""
    xv = onp.random.RandomState(5).rand(4).astype("float32") + 0.5

    def run(bulked):
        x = np.array(xv)
        x.attach_grad()
        scope = engine.bulk(16) if bulked else _unbulked()
        with scope:
            with autograd.record():
                y = x * x
                with autograd.pause():
                    s = y * 3.0  # constant w.r.t. the tape
                z = (y * s).sum()
            z.backward()
            return x.grad.asnumpy().copy()

    g_plain = run(False)
    g_bulk = run(True)
    onp.testing.assert_array_equal(g_plain, g_bulk)
    # and both equal d/dx (y * const) = 2x * (3x^2) = 6x^3
    onp.testing.assert_allclose(g_plain, 6 * xv ** 3, rtol=1e-5)


def test_seeded_rng_stream_identical_bulk_vs_unbulked():
    """The recorder's eval_shape probe must not burn RNG keys: a seeded
    program draws the SAME random stream with bulking on or off (the
    probe rewinds any keys an RNG op consumed during abstract tracing)."""
    from mxnet_tpu.ops import nn as _nn

    def draws(bulked):
        mx.random.seed(123)
        a = np.ones((16, 16))
        scope = engine.bulk(16) if bulked else _unbulked()
        with scope:
            with autograd.train_mode():
                d1 = _nn.dropout(a * 1.0, p=0.5).asnumpy()
            r = np.random.uniform(size=(8,)).asnumpy()
        return d1, r

    d_plain, r_plain = draws(False)
    d_bulk, r_bulk = draws(True)
    onp.testing.assert_array_equal(d_plain, d_bulk)
    onp.testing.assert_array_equal(r_plain, r_bulk)


# ---------------------------------------------------------------------------
# NaiveEngine + thread-local bulk size
# ---------------------------------------------------------------------------


def test_naive_engine_forces_segment_size_one():
    prev = engine.engine_type()
    engine.set_engine_type("NaiveEngine")
    try:
        a = np.array(onp.ones((4,), "float32"))
        with engine.bulk(16):
            b = a + 1
            # synchronous semantics preserved: nothing deferred
            assert type(b._buf) is not engine._LazyRef
        assert engine.bulk_stats()["flushes"] == 0
    finally:
        engine.set_engine_type(prev)


def test_bulk_size_is_thread_local():
    """Satellite: a bulk() scope on one thread must not change another
    thread's flush threshold mid-step (each thread sees only ITS size,
    whatever the process default)."""
    seen = {}
    barrier = threading.Barrier(2)

    def bulky():
        with engine.bulk(64):
            barrier.wait()
            seen["bulky"] = engine._active_bulk_size()
            barrier.wait()
            seen["bulky_after"] = engine._active_bulk_size()

    def plain():
        engine.set_bulk_size(0)  # this thread opts out, others unaffected
        barrier.wait()
        seen["plain"] = engine._active_bulk_size()
        a = np.array(onp.ones((2,), "float32"))
        b = a + 1  # must dispatch eagerly: bulking is off on THIS thread
        seen["plain_lazy"] = type(b._buf) is engine._LazyRef
        barrier.wait()

    t1 = threading.Thread(target=bulky)
    t2 = threading.Thread(target=plain)
    t1.start(); t2.start(); t1.join(); t2.join()
    assert seen["bulky"] == 64, "bulk scope size lost on its own thread"
    assert seen["bulky_after"] == 64, "another thread's opt-out leaked in"
    assert seen["plain"] == 0, "bulk scope leaked across threads"
    assert seen["plain_lazy"] is False


def test_set_bulk_size_returns_previous_and_flushes():
    prev = engine.set_bulk_size(32)
    try:
        assert engine.set_bulk_size(prev) == 32
    finally:
        engine.set_bulk_size(prev)


# ---------------------------------------------------------------------------
# Fault injection through deferral
# ---------------------------------------------------------------------------


def test_dispatch_fault_site_fires_per_recorded_op_at_flush():
    """The op:dispatch fault site must hit once per RECORDED op when the
    segment flushes — deferral cannot make injected faults vanish — and
    the error surfaces at the materialization point."""
    plan = faults.install_plan({"seed": 1, "rules": [
        {"site": "op:dispatch", "kind": "transient", "at": [2]}]})
    try:
        a = np.array(onp.ones((4,), "float32"))
        with engine.bulk(16):
            b = a + 1
            c = b * 2
            d = c - 3
            with pytest.raises(mx.base.MXNetError):
                d.asnumpy()  # flush fires op:dispatch x3; rule trips at #2
        st = plan.stats()[0]
        assert st["hits"] == 3, "one op:dispatch hit per recorded op"
        assert st["fired"] == 1
        # every poisoned lazy handle re-surfaces the failure
        with pytest.raises(mx.base.MXNetError):
            b.asnumpy()
    finally:
        faults.clear_plan()


def test_wait_for_var_fires_engine_wait_fault_site():
    """Satellite: wait_for_var previously skipped the engine:wait fault
    check that wait_all performs; both wait points must surface injected
    async errors (contract (c))."""
    plan = faults.install_plan({"seed": 1, "rules": [
        {"site": "engine:wait", "kind": "fatal", "times": 1}]})
    try:
        a = np.array(onp.ones((2,), "float32"))
        with pytest.raises(mx.base.MXNetError):
            a.wait_to_read()
        assert plan.stats()[0]["fired"] == 1
    finally:
        faults.clear_plan()


# ---------------------------------------------------------------------------
# Registry cache-clear observability (satellite)
# ---------------------------------------------------------------------------


def test_eager_jit_clear_counter_and_warning():
    stats = registry.cache_stats()
    assert set(stats) >= {"size", "bwd_size", "skips", "clears", "limit"}
    before = stats["clears"]
    saved_max = registry._EAGER_JIT_MAX
    saved_clears = registry._EAGER_JIT_CLEARS
    prev_bulk = engine.set_bulk_size(0)  # exercise the per-op cache path
    try:
        registry._EAGER_JIT_MAX = registry.eager_jit_cache_size() + 1
        registry._EAGER_JIT_CLEARS = 0
        a = np.array(onp.ones((3,), "float32"))
        with pytest.warns(RuntimeWarning, match="runaway"):
            for i in range(4):  # distinct static configs force new entries
                np.sum(a * 1.0, axis=0)
                np.clip(a, 0.0, float(i + 2))
        assert registry.cache_stats()["clears"] >= 1
    finally:
        registry._EAGER_JIT_MAX = saved_max
        registry._EAGER_JIT_CLEARS = max(saved_clears, before)
        engine.set_bulk_size(prev_bulk)


# ---------------------------------------------------------------------------
# Default-off overhead bound
# ---------------------------------------------------------------------------


@pytest.mark.serial
def test_disabled_bulk_overhead_under_5pct():
    """10k-iteration eager microloop: with the bulk machinery present but
    disabled (the production default), overhead vs a loop that never
    consults the gate must stay under the established 5% bound."""
    x = np.ones((4,))

    def loop(n=10_000):
        y = x
        t0 = time.perf_counter()
        for _ in range(n):
            y = y + 1.0
        y.wait_to_read()
        return time.perf_counter() - t0

    saved = engine._BULK_POSSIBLE

    def measure(rounds=7):
        base = gated = float("inf")
        for _ in range(rounds):
            engine._BULK_POSSIBLE = False  # gate short-circuits in apply
            base = min(base, loop())
            engine._BULK_POSSIBLE = True   # gate consulted, bulking off
            engine.set_bulk_size(0)
            gated = min(gated, loop())
        return base, gated

    try:
        loop(2000)  # warm jit caches before either measurement
        base, gated = measure()
        if gated > base * 1.05:  # timing noise: one clean re-measure
            base, gated = measure(rounds=9)
    finally:
        engine._BULK_POSSIBLE = saved
    assert gated <= base * 1.05, (
        f"disabled-bulk overhead {gated / base - 1:.1%} "
        f"(baseline {base:.3f}s, gated {gated:.3f}s)")
