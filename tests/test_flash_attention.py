"""Pallas flash-attention kernels vs the XLA reference implementation.

Runs the *real* TPU kernels through the Pallas interpreter on CPU, so the
flash forward, the valid-length masking, and both backward kernels are
exercised by CI on the virtual device mesh (reference test style:
numpy-oracle per-op checks, ``tests/python/unittest/test_numpy_op.py``).
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from mxnet_tpu.ops.pallas import flash_attention as fa


@pytest.fixture(autouse=True)
def _interpret_mode():
    fa.use_interpret(True)
    yield
    fa.use_interpret(False)


def _rand(shape, dtype="float32", seed=0):
    rng = onp.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(dtype))


CASES = [
    # tq, tk, d, causal, valid_length
    (128, 128, 64, False, None),          # BERT-base shape
    (128, 128, 64, False, [37, 128]),     # BERT valid_length path
    (128, 128, 64, True, None),           # causal
    (256, 256, 128, True, None),          # lane-width head dim
    (100, 100, 64, True, [77, 100]),      # unaligned T -> padding path
    (128, 256, 64, False, None),          # cross attention tq != tk
    (64, 192, 80, True, [100, 192]),      # everything irregular at once
]


@pytest.mark.parametrize("tq,tk,d,causal,vl", CASES)
def test_flash_forward_matches_reference(tq, tk, d, causal, vl):
    b, h = 2, 3
    q, k, v = (_rand((b, h, tq, d), seed=i) for i in range(3))
    vla = None if vl is None else jnp.asarray(vl, jnp.int32)
    ref = fa._reference_attention(q, k, v, causal=causal, valid_length=vla)
    out = fa.attention(q, k, v, causal=causal, valid_length=vla)
    assert fa.last_path() == "pallas"
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


@pytest.mark.parametrize("tq,tk,d,causal,vl", CASES)
def test_flash_backward_matches_reference(tq, tk, d, causal, vl):
    b, h = 2, 3
    q, k, v = (_rand((b, h, tq, d), seed=i) for i in range(3))
    vla = None if vl is None else jnp.asarray(vl, jnp.int32)

    def loss(f):
        return jax.grad(
            lambda q_, k_, v_: jnp.sum(jnp.sin(f(q_, k_, v_))),
            argnums=(0, 1, 2))(q, k, v)

    gref = loss(lambda q_, k_, v_: fa._reference_attention(
        q_, k_, v_, causal=causal, valid_length=vla))
    gout = loss(lambda q_, k_, v_: fa.attention(
        q_, k_, v_, causal=causal, valid_length=vla))
    assert fa.last_path() == "pallas"
    for a, b_ in zip(gref, gout):
        assert float(jnp.max(jnp.abs(a - b_))) < 5e-4


def test_dense_mask_falls_back_to_xla():
    q = _rand((2, 2, 128, 64))
    mask = jnp.ones((2, 1, 128, 128), bool)
    out = fa.attention(q, q, q, mask=mask)
    assert fa.last_path() == "xla"
    ref = fa._reference_attention(q, q, q, mask=mask)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-6


def test_tiny_sequences_use_xla():
    # below half a block the XLA path is faster and exact
    q = _rand((2, 2, 16, 64))
    fa.attention(q, q, q)
    assert fa.last_path() == "xla"


def test_block_picker_bounds_waste():
    assert fa._pick_block(128, 1024) == 128
    assert fa._pick_block(8192, 1024) == 1024
    assert fa._pick_block(8192, 512) == 512
    for t in (100, 300, 1500, 1664, 5000):
        blk = fa._pick_block(t, 1024)
        tp = fa._round_up(t, 128)
        assert fa._round_up(tp, blk) <= 1.125 * tp


def test_valid_length_zero_row_is_zero():
    # fully-masked rows emit exactly zero (and zero gradient), not a
    # uniform average over the keys the mask excluded
    q = _rand((2, 2, 128, 64))
    vl = jnp.asarray([0, 128], jnp.int32)
    out = fa.attention(q, q, q, valid_length=vl)
    assert bool(jnp.isfinite(out).all())
    assert float(jnp.max(jnp.abs(out[0]))) == 0.0
    ref = fa._reference_attention(q, q, q, valid_length=vl)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


@pytest.mark.parametrize("tq,tk", [(128, 130), (8, 512), (130, 128),
                                   (256, 128), (300, 1000)])
def test_causal_offset_with_asymmetric_padding(tq, tk):
    """Causal diagonal must come from UNPADDED lengths: tq/tk that pad by
    different amounts shift the block-padded diagonal (regression: fwd was
    off by up to 1.75 and tq>tk head rows had garbage gradients)."""
    q = _rand((1, 2, tq, 64), seed=1)
    k = _rand((1, 2, tk, 64), seed=2)
    v = _rand((1, 2, tk, 64), seed=3)
    ref = fa._reference_attention(q, k, v, causal=True)
    out = fa.attention(q, k, v, causal=True)
    assert fa.last_path() == "pallas"
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
    g1 = jax.grad(lambda q_: jnp.sum(jnp.sin(
        fa.attention(q_, k, v, causal=True))))(q)
    g2 = jax.grad(lambda q_: jnp.sum(jnp.sin(
        fa._reference_attention(q_, k, v, causal=True))))(q)
    assert float(jnp.max(jnp.abs(g1 - g2))) < 5e-4


def test_models_use_flash_path_under_interpret():
    """BERT forward+backward routes attention through the Pallas kernels."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import np as mnp
    from mxnet_tpu.models import get_bert_model
    from mxnet_tpu.models.bert import BERTClassifier

    bert = get_bert_model(units=64, hidden_size=128, num_layers=1,
                          num_heads=1, vocab_size=64, max_length=128,
                          dropout=0.0)
    net = BERTClassifier(bert, num_classes=2, dropout=0.0)
    net.initialize()
    ids = mnp.array(onp.random.randint(0, 64, (2, 128)))
    vl = mnp.array(onp.array([100, 128]))
    with autograd.record():
        out = net(ids, None, vl)
        loss = out.sum()
    loss.backward()
    assert fa.last_path() == "pallas"


def test_force_path_invalidates_eager_op_cache():
    """force_path() must actually flip the traced path even when the
    attention op was already compiled into the eager jit cache at the
    same shapes (r5 bench-ablation bug: the cache keys on (code,
    closure), so the routing globals must live in the closure — a stale
    hit would silently replay the previously-traced kernel)."""
    from mxnet_tpu import np as mnp
    from mxnet_tpu.ops import nn as ops_nn

    q = mnp.array(_rand((1, 1, 128, 64)))
    ops_nn.attention(q, q, q, causal=True)
    assert fa.last_path() == "pallas"
    fa.force_path("xla")
    try:
        ops_nn.attention(q, q, q, causal=True)
        assert fa.last_path() == "xla"
    finally:
        fa.force_path(None)
    # restored routing picks pallas again on a FRESH trace (new shape —
    # last_path() reports trace-time decisions; a cache-hit replay of
    # the original shape correctly executes pallas but does not re-run
    # the Python that records it)
    q2 = mnp.array(_rand((1, 1, 256, 64)))
    ops_nn.attention(q2, q2, q2, causal=True)
    assert fa.last_path() == "pallas"


def test_force_path_rejects_unknown():
    with pytest.raises(ValueError):
        fa.force_path("cuda")
