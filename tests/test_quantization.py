"""INT8 quantization tests (reference src/operator/quantization/ +
contrib/quantization.py quantize_net; calibration per calibrate.cc)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np
from mxnet_tpu.contrib import quantization as q


def test_quantize_dequantize_roundtrip():
    a = np.array(onp.random.uniform(-3, 3, (4, 5)).astype("float32"))
    qd, lo, hi = q.quantize(a)
    assert qd.dtype == onp.int8
    back = q.dequantize(qd, lo, hi)
    onp.testing.assert_allclose(back.asnumpy(), a.asnumpy(),
                                atol=3.0 / 127 + 1e-6)


def test_requantize():
    acc = np.array(onp.array([[1000, -2000], [500, 0]], "int32"))
    out = q.requantize(acc, in_scale=0.01, out_scale=0.1)
    assert out.dtype == onp.int8
    onp.testing.assert_allclose(out.asnumpy(), [[100, -127], [50, 0]])


def test_kl_threshold_prefers_clipping_outliers():
    rng = onp.random.RandomState(0)
    v = rng.randn(100000).astype("float32")
    v[0] = 50.0  # one extreme outlier
    r = float(onp.abs(v).max())
    hist, edges = onp.histogram(v, bins=onp.linspace(-r, r, 2050))
    th = q._kl_optimal_threshold(hist, edges)
    assert th < 25.0  # clips the outlier rather than wasting range on it


def test_kl_threshold_keeps_relu_bulk():
    """A zero-heavy ReLU histogram must NOT collapse the threshold."""
    rng = onp.random.RandomState(3)
    v = onp.maximum(rng.randn(200000), 0).astype("float32")
    r = float(v.max())
    hist, edges = onp.histogram(v, bins=onp.linspace(-r, r, 2050))
    th = q._kl_optimal_threshold(hist, edges)
    assert th > 0.6 * r


@pytest.mark.parametrize("calib_mode", ["naive", "entropy"])
def test_quantize_net_mlp_accuracy(calib_mode):
    rng = onp.random.RandomState(1)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize()
    x = np.array(rng.randn(64, 20).astype("float32"))
    with autograd.predict_mode():
        ref = net(x).asnumpy()
    # entropy/KL calibration needs a non-sparse histogram: feed several
    # batches (the reference docs recommend the same for calib_mode entropy)
    calib = [x] + [np.array(rng.randn(64, 20).astype("float32"))
                   for _ in range(9)]
    qnet = q.quantize_net(net, calib_data=calib, calib_mode=calib_mode)
    with autograd.predict_mode():
        got = qnet(x).asnumpy()
    # int8 fidelity: strong linear agreement + matching predictions
    corr = onp.corrcoef(got.ravel(), ref.ravel())[0, 1]
    assert corr > 0.98
    agree = (got.argmax(1) == ref.argmax(1)).mean()
    assert agree > 0.85


def test_quantize_net_conv_and_hybridize():
    rng = onp.random.RandomState(2)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2), gluon.nn.Flatten(), gluon.nn.Dense(5))
    net.initialize()
    x = np.array(rng.randn(4, 3, 16, 16).astype("float32"))
    with autograd.predict_mode():
        ref = net(x).asnumpy()
    # exclude_first_conv=False: this net's only conv IS the stem; the test
    # pins the conv path, so quantize it (the default leaves it float)
    qnet = q.quantize_net(net, calib_data=[x], calib_mode="naive",
                          exclude_first_conv=False)
    from mxnet_tpu.contrib.quantization import QuantizedConv, QuantizedDense

    kinds = [type(c) for c in qnet]
    assert QuantizedConv in kinds and QuantizedDense in kinds
    qnet.hybridize()
    with autograd.predict_mode():
        got = qnet(x).asnumpy()
    assert onp.abs(got - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.1


def test_quantize_net_attribute_rebind():
    """Attr-held children (self.fc) must be swapped too, not just
    _children entries."""
    class Model(gluon.block.HybridBlock):
        def __init__(self):
            super().__init__()
            self.fc = gluon.nn.Dense(4)

        def forward(self, x):
            return self.fc(x)

    m = Model()
    m.initialize()
    x = np.array(onp.random.randn(2, 8).astype("float32"))
    with autograd.predict_mode():
        m(x)
    q.quantize_net(m, calib_data=x, calib_mode="naive")
    from mxnet_tpu.contrib.quantization import QuantizedDense

    assert isinstance(m.fc, QuantizedDense)
    with autograd.predict_mode():
        out = m(x)
    assert out.shape == (2, 4)


def test_exclude_layers_and_errors():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    x = np.array(onp.random.randn(2, 8).astype("float32"))
    with autograd.predict_mode():
        net(x)
    q.quantize_net(net, calib_data=x, exclude_layers={"0"})
    assert isinstance(net[0], gluon.nn.Dense)  # untouched
    with pytest.raises(mx.MXNetError):
        q.quantize_net(net, calib_data=x, calib_mode="bogus")
    with pytest.raises(mx.MXNetError):
        q.quantize_net(net, calib_data=x, quantized_dtype="uint4")


def test_quantize_net_exclude_options():
    """exclude_first_conv default keeps the stem float; exclude_layers_match
    regexes skip matching paths (reference quantize_net parameters)."""
    rng = onp.random.RandomState(3)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1), gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.Flatten(), gluon.nn.Dense(5))
    net.initialize()
    x = np.array(rng.randn(2, 3, 8, 8).astype("float32"))
    with autograd.predict_mode():
        net(x)
    from mxnet_tpu.contrib.quantization import QuantizedConv, QuantizedDense
    from mxnet_tpu.gluon import nn as gnn

    qnet = q.quantize_net(net, calib_data=[x], calib_mode="naive",
                          exclude_layers_match=[r"\b3\b"])
    kinds = [type(c) for c in qnet]
    assert kinds[0] is gnn.Conv2D          # stem stays float (default)
    assert kinds[1] is QuantizedConv       # second conv quantized
    assert QuantizedDense not in kinds     # '3' (the Dense) matched exclude


def test_quantize_net_bf16_activations_accuracy():
    """activation_dtype='bfloat16' keeps predictions close to fp32: the
    int8 path's TPU deployment mode (bf16 inter-layer traffic)."""
    rng = onp.random.RandomState(4)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.Flatten(), gluon.nn.Dense(10))
    net.initialize()
    x = np.array(rng.randn(8, 3, 16, 16).astype("float32"))
    with autograd.predict_mode():
        ref = net(x).asnumpy()
    q.quantize_net(net, calib_data=[x], calib_mode="naive",
                   activation_dtype="bfloat16")
    with autograd.predict_mode():
        got = net(x.astype("bfloat16")).asnumpy().astype("float32")
    corr = onp.corrcoef(got.ravel(), ref.ravel())[0, 1]
    assert corr > 0.98
    assert (got.argmax(1) == ref.argmax(1)).mean() > 0.8
