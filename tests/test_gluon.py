"""Gluon Block/HybridBlock/Parameter/Trainer tests (reference test_gluon.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    return net


def test_parameter_lifecycle():
    p = gluon.Parameter("weight", shape=(3, 0))
    p.initialize()  # deferred: shape incomplete
    with pytest.raises(mx.gluon.parameter.DeferredInitializationError):
        p.data()
    p.shape = (3, 5)
    assert p.data().shape == (3, 5)
    assert p.grad().shape == (3, 5)
    p.set_data(np.ones((3, 5)))
    onp.testing.assert_allclose(p.data().asnumpy(), 1)


def test_collect_params_names():
    net = _mlp()
    names = list(net.collect_params())
    assert names == ["0.weight", "0.bias", "1.weight", "1.bias"]


def test_deferred_shape_inference():
    net = _mlp()
    net.initialize()
    out = net(np.ones((2, 7)))
    assert out.shape == (2, 4)
    assert net[0].weight.shape == (16, 7)


def test_hybridize_consistency():
    net = _mlp()
    net.initialize()
    x = np.array(onp.random.rand(3, 5).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    onp.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)
    # gradient agreement
    w = net[0].weight
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    g_hybrid = w.grad().asnumpy().copy()
    net.hybridize(False)
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    onp.testing.assert_allclose(w.grad().asnumpy(), g_hybrid, rtol=1e-4,
                                atol=1e-6)


def test_hybridize_polymorphic_shapes():
    net = _mlp()
    net.initialize()
    net.hybridize()
    assert net(np.ones((2, 5))).shape == (2, 4)  # eager: finalizes shapes
    assert net(np.ones((8, 5))).shape == (8, 4)
    assert net(np.ones((3, 5))).shape == (3, 4)
    assert len(net._cached_op._cache) >= 2  # one compiled entry per signature


def test_batchnorm_state_updates_in_hybrid():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8), gluon.nn.BatchNorm())
    net.initialize()
    net.hybridize()
    x = np.array(onp.random.rand(4, 3).astype("float32"))
    with autograd.record():
        net(x)
    bn = net[1]
    rm = bn.running_mean.data().asnumpy()
    assert onp.abs(rm).sum() > 0


def test_trainer_sgd_momentum_matches_manual():
    w0 = onp.array([[1.0, 2.0]], dtype="float32")
    p = gluon.Parameter("w", shape=(1, 2))
    p.initialize()
    p.set_data(np.array(w0))
    tr = gluon.Trainer([p], "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    g = onp.array([[0.5, -0.5]], dtype="float32")
    mom = onp.zeros_like(w0)
    w = w0.copy()
    for _ in range(3):
        p.grad()._set_data_internal(np.array(g)._data)
        tr.step(1)
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    onp.testing.assert_allclose(p.data().asnumpy(), w, rtol=1e-5)


def test_save_load_parameters(tmp_path):
    net = _mlp()
    net.initialize()
    net(np.ones((1, 6)))
    f = str(tmp_path / "mlp.params")
    net.save_parameters(f)
    net2 = _mlp()
    net2.initialize()
    net2(np.ones((1, 6)))
    net2.load_parameters(f)
    x = np.array(onp.random.rand(2, 6).astype("float32"))
    onp.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(), rtol=1e-6)


def test_losses_against_reference_math():
    pred = onp.random.randn(4, 5).astype("float32")
    label = onp.array([0, 2, 1, 4])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(np.array(pred), np.array(label))
    # manual
    e = onp.exp(pred - pred.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    want = -onp.log(p[onp.arange(4), label])
    onp.testing.assert_allclose(l.asnumpy(), want, rtol=1e-5)

    a = onp.random.rand(3, 2).astype("float32")
    b = onp.random.rand(3, 2).astype("float32")
    l2 = gluon.loss.L2Loss()(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(l2, ((a - b) ** 2 / 2).mean(1), rtol=1e-5)
    l1 = gluon.loss.L1Loss()(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(l1, onp.abs(a - b).mean(1), rtol=1e-5)


def test_metrics():
    m = gluon.metric.Accuracy()
    m.update(np.array([0, 1, 1]), np.array([[0.9, 0.1], [0.3, 0.7], [0.8, 0.2]]))
    assert m.get()[1] == pytest.approx(2 / 3)
    rmse = gluon.metric.RMSE()
    rmse.update(np.array([1.0, 2.0]), np.array([1.0, 4.0]))
    assert rmse.get()[1] == pytest.approx(onp.sqrt(2.0))
    comp = gluon.metric.create(["accuracy", "crossentropy"])
    comp.update(np.array([1]), np.array([[0.2, 0.8]]))
    names, vals = comp.get()
    assert len(names) == 2


def test_convergence_mlp():
    """End-to-end convergence (reference tests/python/train style)."""
    onp.random.seed(0)
    X = onp.random.randn(256, 10).astype("float32")
    w = onp.random.randn(10).astype("float32")
    y = (X @ w > 0).astype("float32")
    net = _mlp()
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    xb, yb = np.array(X), np.array(y)
    for _ in range(60):
        with autograd.record():
            l = loss_fn(net(xb), yb)
        l.backward()
        tr.step(256)
    acc = (net(xb).asnumpy().argmax(1) == y).mean()
    assert acc > 0.95


def test_conv_layers_shapes():
    x = np.ones((2, 3, 16, 16))
    c = gluon.nn.Conv2D(8, 3, padding=1)
    c.initialize()
    assert c(x).shape == (2, 8, 16, 16)
    ct = gluon.nn.Conv2DTranspose(4, 2, strides=2)
    ct.initialize()
    assert ct(c(x)).shape == (2, 4, 32, 32)
    p = gluon.nn.MaxPool2D(2)
    assert p(x).shape == (2, 3, 8, 8)
    g = gluon.nn.GlobalAvgPool2D()
    assert g(x).shape == (2, 3, 1, 1)


def test_summary_and_repr():
    net = _mlp()
    net.initialize()
    net(np.ones((1, 4)))
    text = net.summary(np.ones((1, 4)))
    assert "Dense" in text
    assert "Dense" in repr(net)


def test_export_symbolblock_roundtrip(tmp_path):
    net = _mlp()
    net.initialize()
    net.hybridize()
    x = np.array(onp.random.rand(2, 6).astype("float32"))
    want = net(x).asnumpy()
    prefix = str(tmp_path / "model")
    sym_file, param_file = net.export(prefix)
    loaded = gluon.SymbolBlock.imports(sym_file, param_file=param_file)
    got = loaded(x).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_gluon_contrib_nn_layers():
    """contrib.nn: Concurrent branches, Identity, SparseEmbedding,
    PixelShuffle (reference gluon/contrib/nn/basic_layers.py)."""
    from mxnet_tpu.gluon.contrib import nn as cnn
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray

    con = cnn.HybridConcurrent(axis=-1)
    con.add(gluon.nn.Dense(3), cnn.Identity(), gluon.nn.Dense(2))
    con.initialize()
    x = np.array(onp.random.randn(4, 5).astype("float32"))
    out = con(x)
    assert out.shape == (4, 3 + 5 + 2)

    ps = cnn.PixelShuffle2D(2)
    y = ps(np.array(onp.arange(32, dtype="float32").reshape(1, 8, 2, 2)))
    assert y.shape == (1, 2, 4, 4)
    # channel blocks interleave into space: exact layout oracle
    xin = onp.arange(16, dtype="float32").reshape(1, 4, 2, 2)
    got = cnn.PixelShuffle2D(2)(np.array(xin)).asnumpy()
    assert got.shape == (1, 1, 4, 4)
    # out[0,0,h*2+i, w*2+j] == xin[0, i*2+j, h, w]
    for h in range(2):
        for w in range(2):
            for i in range(2):
                for j in range(2):
                    assert got[0, 0, h * 2 + i, w * 2 + j] == \
                        xin[0, i * 2 + j, h, w]

    emb = cnn.SparseEmbedding(50, 4)
    emb.initialize()
    with autograd.record():
        emb(np.array(onp.array([1, 2], "int64"))).sum().backward()
    assert isinstance(emb.weight.grad(), RowSparseNDArray)
