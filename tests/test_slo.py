"""SLO burn-rate guard (PR 16 tentpole, layer 2): declarative
objectives over the serving metric families, multi-window burn-rate
math, the min-events gate, the edge-triggered ``slo_burn``
flight-recorder escalation (exactly ONE dump under a sustained
delay-fault storm, none on a clean run), and degraded-not-dead
``/healthz``."""
import json

import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.profiler import export, recorder, trace
from mxnet_tpu.profiler.slo import SLO, SLOMonitor
from mxnet_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_slo_state():
    recorder.reset()
    faults.clear_plan()
    yield
    recorder.reset()
    recorder.ENABLED = False
    faults.clear_plan()
    trace.disable()
    trace.reset()


def _itl_slo(target=100.0, threshold=10.0):
    return SLO("itl_p99_ms", target, window=60.0, fast_window=5.0,
               threshold=threshold)


def _monitor(objectives, min_events=10):
    # eval never auto-fires: the table tests drive evaluate() by hand
    return SLOMonitor("t", objectives, eval_interval=1e9,
                      min_events=min_events)


# -- objective declaration ---------------------------------------------------


def test_unknown_metric_raises():
    with pytest.raises(MXNetError, match="unknown SLO metric"):
        SLO("throughput_p50", 1.0)


def test_budget_semantics_per_family():
    assert _itl_slo().budget == pytest.approx(0.01)
    assert SLO("ttft_p99_ms", 1000.0).budget == pytest.approx(0.01)
    assert SLO("goodput", 0.95).budget == pytest.approx(0.05)
    assert SLO("error_rate", 0.05).budget == pytest.approx(0.05)
    # fast window defaults to the SRE 1h/5m shape scaled to the window
    assert SLO("itl_p99_ms", 50.0, window=60.0).fast_window == \
        pytest.approx(5.0)


def test_good_event_judgement():
    lat = _itl_slo(target=100.0)
    assert lat.good(value=100.0) and not lat.good(value=100.1)
    gp = SLO("goodput", 0.9)
    assert gp.good(ok=True, deadline_ok=True)
    assert not gp.good(ok=True, deadline_ok=False)   # late != good
    er = SLO("error_rate", 0.1)
    assert er.good(ok=True, deadline_ok=False)       # late != error
    assert not er.good(ok=False)


# -- burn-rate math (explicit timestamps, manual evaluate) -------------------


def test_healthy_stream_does_not_burn():
    mon = _monitor([_itl_slo()])
    for k in range(20):
        mon.observe("itl_ms", 50.0, ts=1000.0 + 0.01 * k)
    (row,) = mon.evaluate(now=1000.5)
    assert row["burn_rate_fast"] == 0.0 and not row["burning"]
    assert row["budget_remaining"] == pytest.approx(1.0)
    assert mon.state == "ok" and mon.burns == 0


def test_sustained_violation_burns_once_and_recovers():
    mon = _monitor([_itl_slo()])
    for k in range(20):
        mon.observe("itl_ms", 500.0, ts=1000.0 + 0.01 * k)
    (row,) = mon.evaluate(now=1000.5)
    # all-bad stream: burn = 1.0 / 0.01 budget = 100x on both windows
    assert row["burn_rate_fast"] == pytest.approx(100.0)
    assert row["burn_rate_slow"] == pytest.approx(100.0)
    assert row["burning"] and row["budget_remaining"] == 0.0
    assert mon.state == "degraded" and mon.burns == 1
    assert mon.health() == {"state": "degraded",
                            "violations": ["itl_p99_ms"],
                            "burns": 1}
    # still burning: degraded persists, NO new edge
    mon.evaluate(now=1001.0)
    assert mon.burns == 1
    # both windows drain -> ok; a fresh storm is a fresh edge
    (row,) = mon.evaluate(now=2000.0)
    assert not row["burning"] and mon.state == "ok"
    for k in range(20):
        mon.observe("itl_ms", 500.0, ts=3000.0 + 0.01 * k)
    mon.evaluate(now=3000.5)
    assert mon.burns == 2


def test_min_events_gate_blocks_sparse_false_alarm():
    mon = _monitor([_itl_slo()], min_events=10)
    for k in range(5):    # 5 terrible samples < min_events
        mon.observe("itl_ms", 9999.0, ts=1000.0 + 0.1 * k)
    (row,) = mon.evaluate(now=1001.0)
    assert row["burn_rate_fast"] == pytest.approx(100.0)
    assert not row["burning"] and mon.state == "ok"


def test_burn_requires_both_windows():
    """An old (slow-window-only) violation with a clean fast window must
    not page — the multi-window rule."""
    mon = _monitor([_itl_slo()])
    for k in range(20):
        mon.observe("itl_ms", 500.0, ts=1000.0 + 0.01 * k)   # old, bad
    for k in range(20):
        mon.observe("itl_ms", 10.0, ts=1050.0 + 0.01 * k)    # fresh, good
    (row,) = mon.evaluate(now=1051.0)
    assert row["burn_rate_fast"] == 0.0
    assert row["burn_rate_slow"] == pytest.approx(50.0)
    assert not row["burning"] and mon.state == "ok"


def test_completion_families_route_independently():
    mon = SLOMonitor("t", [SLO("goodput", 0.5, window=60.0,
                               fast_window=5.0, threshold=1.5),
                           SLO("error_rate", 0.5, window=60.0,
                               fast_window=5.0, threshold=1.5)],
                     eval_interval=1e9, min_events=5)
    # ok-but-late completions: bad for goodput, good for error_rate
    for k in range(10):
        mon.observe("completion", ok=True, deadline_ok=False,
                    ts=1000.0 + 0.01 * k)
    rows = {r["metric"]: r for r in mon.evaluate(now=1000.2)}
    assert rows["goodput"]["burning"]
    assert rows["goodput"]["burn_rate_fast"] == pytest.approx(2.0)
    assert not rows["error_rate"]["burning"]
    assert rows["error_rate"]["burn_rate_fast"] == 0.0
    assert mon.health()["violations"] == ["goodput"]


def test_snapshot_rides_export_surface():
    mon = _monitor([_itl_slo()])
    mon.observe("itl_ms", 50.0, ts=1000.0)
    mon.evaluate(now=1000.1)
    snap = export.snapshot()
    assert snap["slo.t.state"] == "ok"
    assert snap["slo.t.burns"] == 0
    assert snap["slo.t.itl_p99_ms.burning"] == 0
    assert "slo.t.itl_p99_ms.budget_remaining" in snap


# -- the flight-recorder escalation ------------------------------------------


def test_burn_edge_dumps_flight_recorder_once(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    recorder.enable()
    recorder.reset()
    mon = _monitor([_itl_slo()])
    for k in range(20):
        mon.observe("itl_ms", 500.0, ts=1000.0 + 0.01 * k)
    mon.evaluate(now=1000.5)
    assert recorder.dump_count() == 1
    # sustained storm: state stays degraded, edge never re-fires
    for k in range(20):
        mon.observe("itl_ms", 500.0, ts=1001.0 + 0.01 * k)
    mon.evaluate(now=1001.5)
    mon.evaluate(now=1002.0)
    assert recorder.dump_count() == 1 and mon.burns == 1
    doc = json.loads(open(recorder.last_dump_path()).read())
    assert doc["reason"] == "slo_burn"
    assert doc["args"]["monitor"] == "t"
    assert doc["args"]["objective"] == "itl_p99_ms"
    assert doc["args"]["burn_rate_fast"] == pytest.approx(100.0)
    assert any(e["kind"] == "escalation" and e["name"] == "slo.burn(t)"
               for e in doc["ring"])


# -- end to end over a live engine -------------------------------------------


def _tiny_engine(name):
    from mxnet_tpu.models.llama import get_llama
    from mxnet_tpu.serve import ContinuousEngine

    net = get_llama("llama_tiny_test")
    net.initialize()
    return ContinuousEngine(net, max_seq=64, num_slots=4, page_size=16,
                            prefill_chunk=16, decode_path="baseline",
                            name=name)


@pytest.mark.serial
def test_delay_fault_storm_trips_exactly_one_dump(tmp_path, monkeypatch):
    """The acceptance storm: a sustained serve:decode delay fault pushes
    every token-to-token gap over a tight ITL objective; the monitor
    pages ONCE (edge-triggered + recorder rate limit), and the engine
    keeps serving (degraded, not dead)."""
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    recorder.enable()
    recorder.reset()
    with _tiny_engine("slo_storm") as eng:
        mon = SLOMonitor("storm", [
            SLO("itl_p99_ms", 1.0, window=60.0, fast_window=5.0,
                threshold=2.0)], eval_interval=0.0, min_events=5)
        mon.attach(eng.metrics)
        faults.install_plan({"rules": [{"site": "serve:decode",
                                        "kind": "delay",
                                        "seconds": 0.02,
                                        "prob": 1.0}]})
        try:
            futs = [eng.submit([3, 4, 5], max_new_tokens=12),
                    eng.submit([6, 7], max_new_tokens=12)]
            for f in futs:
                assert len(f.result(timeout=120)["tokens"]) == 12
        finally:
            faults.clear_plan()
    assert mon.state == "degraded"
    assert mon.burns == 1
    assert recorder.dump_count() == 1
    doc = json.loads(open(recorder.last_dump_path()).read())
    assert doc["reason"] == "slo_burn"
    assert doc["args"]["objective"] == "itl_p99_ms"


@pytest.mark.serial
def test_clean_run_trips_no_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHT_RECORDER_DIR", str(tmp_path))
    recorder.enable()
    recorder.reset()
    with _tiny_engine("slo_clean") as eng:
        mon = SLOMonitor("clean", [
            SLO("itl_p99_ms", 60_000.0, window=60.0, fast_window=5.0,
                threshold=2.0)], eval_interval=0.0, min_events=5)
        mon.attach(eng.metrics)
        assert len(eng.submit([3, 4, 5], max_new_tokens=12)
                   .result(timeout=120)["tokens"]) == 12
    rows = mon.evaluate()
    assert not any(r["burning"] for r in rows)
    assert mon.state == "ok" and mon.burns == 0
    assert recorder.dump_count() == 0


# -- degraded-not-dead /healthz ----------------------------------------------


@pytest.mark.serial
def test_healthz_degraded_not_dead():
    from mxnet_tpu import np as mnp
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.serve import InferenceSession

    net = nn.Dense(4)
    net.initialize()
    sess = InferenceSession(net, batch_buckets=(1,), name="slo_health")
    sess.warmup(mnp.ones((1, 4)))
    mon = _monitor([_itl_slo()]).attach(sess.metrics)
    assert sess.ready() and sess.health()["state"] != "degraded"
    for k in range(20):
        mon.observe("itl_ms", 500.0, ts=1000.0 + 0.01 * k)
    mon.evaluate(now=1000.5)
    h = sess.health()
    assert h["state"] == "degraded"
    assert h["slo"]["violations"] == ["itl_p99_ms"]
    assert sess.ready()   # a burn is a page, not a kill switch
