"""KVStore tests (reference test_kvstore_custom.py + dist_sync_kvstore.py
exact-numeric style, run on the virtual 8-device CPU mesh)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np


def test_local_init_push_pull():
    kv = mx.kv.create("local")
    kv.init("w", np.ones((2, 2)))
    out = np.zeros((2, 2))
    kv.pull("w", out=out)
    onp.testing.assert_allclose(out.asnumpy(), 1)
    kv.push("w", [np.ones((2, 2)) * 2, np.ones((2, 2)) * 3])
    kv.pull("w", out=out)
    onp.testing.assert_allclose(out.asnumpy(), 6)  # 1 + (2+3)


def test_local_update_on_kvstore():
    kv = mx.kv.create("device")
    assert kv.is_capable(mx.kv.KVStoreBase.OPTIMIZER)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv.init(0, np.ones((3,)))
    kv.push(0, [np.ones((3,))])
    out = np.zeros((3,))
    kv.pull(0, out=out)
    onp.testing.assert_allclose(out.asnumpy(), 0.5)  # 1 - 0.5*1


def test_dist_tpu_sync_pushpull_exact():
    kv = mx.kv.create("dist_tpu_sync")
    n = 4
    vals = [np.ones((8,)) * (i + 1) for i in range(n)]
    outs = [np.zeros((8,)) for _ in range(n)]
    kv.pushpull("g", vals, out=outs)
    for o in outs:
        onp.testing.assert_allclose(o.asnumpy(), 10.0)  # 1+2+3+4 exact


def test_dist_tpu_sync_broadcast_and_barrier():
    kv = mx.kv.create("dist_tpu_sync")
    outs = [np.zeros((4,)) for _ in range(3)]
    kv.broadcast("p", np.arange(4).astype("float32"), out=outs)
    for o in outs:
        onp.testing.assert_allclose(o.asnumpy(), [0, 1, 2, 3])
    kv.barrier()
    assert kv.rank == 0
    assert kv.num_workers == 1


def test_dist_aliases_and_async_rejection():
    kv = mx.kv.create("dist_sync")
    assert kv.type == "dist_tpu_sync"
    with pytest.raises(mx.NotSupportedForTPUError):
        mx.kv.create("dist_async")
    with pytest.raises(mx.MXNetError):
        mx.kv.create("no_such_store")


def test_gradient_compression_error_feedback():
    gc = mx.kvstore.GradientCompression(threshold=1.0)
    g = np.array([0.6, -0.6, 0.2, 1.5])
    c1 = gc.decompress("k", gc.compress("k", g)).asnumpy()
    onp.testing.assert_allclose(c1, [0, 0, 0, 1.0])  # |0.6|<1 -> 0 + residual
    c2 = gc.decompress("k", gc.compress("k", g)).asnumpy()
    # residual 0.6 + new 0.6 = 1.2 -> quantizes to 1.0 now
    onp.testing.assert_allclose(c2, [1.0, -1.0, 0, 1.0])


def test_gradient_compression_really_packs():
    """The wire buffer must be 2 bits/value (16x smaller than fp32)."""
    gc = mx.kvstore.GradientCompression(threshold=0.5)
    g = np.array(onp.random.randn(1024).astype("float32"))
    packed = gc.compress("w", g)
    assert packed.dtype == onp.uint8
    assert packed.asnumpy().nbytes == 1024 // 4  # 4 values per byte
    dense = gc.decompress("w", packed).asnumpy()
    assert dense.shape == (1024,)
    assert set(onp.unique(dense)).issubset({-0.5, 0.0, 0.5})
    # roundtrip matches the dense quantization exactly
    gc2 = mx.kvstore.GradientCompression(threshold=0.5)
    q = gc2.quantize("w", g).asnumpy()
    onp.testing.assert_allclose(dense, q)


def test_optimizer_states_save_load(tmp_path):
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.Adam())
    kv.init(0, np.ones((2,)))
    kv.push(0, [np.ones((2,))])
    f = str(tmp_path / "opt.states")
    kv.save_optimizer_states(f)
    kv.load_optimizer_states(f)


def test_trainer_with_dist_tpu_sync():
    from mxnet_tpu import autograd, gluon

    net = gluon.nn.Dense(2, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="dist_tpu_sync")
    x = np.ones((8, 4))
    y = np.zeros((8,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        l = loss_fn(net(x), y)
    l.backward()
    w0 = net.weight.data().asnumpy().copy()
    tr.step(8)
    assert onp.abs(net.weight.data().asnumpy() - w0).sum() > 0


def test_dist_tpu_sync_compiled_collective():
    """Per-device lists covering the mesh take the COMPILED collective path:
    one jitted XLA all-reduce with replicated out-sharding (the role of
    `kvstore_dist.h:578` PushPullDefault), not an eager gather."""
    import jax

    kv = mx.kv.create("dist_tpu_sync")
    devs = list(kv._mesh.devices.flatten())
    n = len(devs)
    assert n == 8  # virtual CPU mesh from conftest
    from mxnet_tpu.ndarray.ndarray import NDArray

    vals = [NDArray(jax.device_put(onp.full((4, 3), i + 1.0, "float32"), d))
            for i, d in enumerate(devs)]
    outs = [np.zeros((4, 3)) for _ in range(n)]
    kv.pushpull("g", vals, out=outs)
    expect = sum(range(1, n + 1))
    for o in outs:
        onp.testing.assert_allclose(o.asnumpy(), expect)
    assert kv.last_path == "collective"
    assert "all-reduce" in kv.last_hlo
    # results stay on their source devices (no gather-to-one-device)
    for v, o in zip(vals, outs):
        assert v._data.devices() == o._data.devices()


def test_dist_tpu_sync_eager_fallback_same_device():
    """Same-device lists (no per-device layout) fall back to the eager path
    with identical numerics."""
    kv = mx.kv.create("dist_tpu_sync")
    vals = [np.ones((8,)) * (i + 1) for i in range(4)]
    outs = [np.zeros((8,)) for _ in range(4)]
    kv.pushpull("g", vals, out=outs)
    for o in outs:
        onp.testing.assert_allclose(o.asnumpy(), 10.0)
    assert kv.last_path == "eager"


def test_bandwidth_probe_plausible():
    """The pushpull bandwidth probe returns a finite, physically bounded
    figure on the virtual mesh (the r2 number was a degenerate-timer
    artifact: bytes/1e-9 — this pins the raise-don't-clamp fix)."""
    from mxnet_tpu.kvstore.dist_tpu import measure_pushpull_bandwidth

    gbs = measure_pushpull_bandwidth(size_mb=4, iters=4)
    assert 0.0 < gbs < 1e4
