"""Test harness configuration.

Reference test strategy (SURVEY.md §4): pytest with per-test seeds and
reproducibility logging. TPU adaptation: all tests run on a virtual
8-device CPU mesh (``xla_force_host_platform_device_count``) so sharding /
collective paths execute without TPU hardware — the reference's
multi-process-on-one-host trick done the JAX way.
"""
import os

# must be set before jax initializes
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

# the axon sitecustomize pins JAX_PLATFORMS=axon; override to CPU for tests
jax.config.update("jax_platforms", "cpu")

import numpy as _np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def seed_rngs(request):
    """Seed numpy + framework RNGs per test (reference conftest.py:40-91)."""
    seed = abs(hash(request.node.nodeid)) % (2**31)
    marker = request.node.get_closest_marker("seed")
    if marker is not None:
        seed = marker.args[0]
    _np.random.seed(seed)
    import mxnet_tpu as mx

    mx.random.seed(seed)
    yield


def pytest_configure(config):
    config.addinivalue_line("markers", "seed(n): fix the RNG seed for a test")
    config.addinivalue_line("markers", "serial: run without xdist")
    config.addinivalue_line("markers", "integration: slower end-to-end test")
