"""Test harness configuration.

Reference test strategy (SURVEY.md §4): pytest with per-test seeds and
reproducibility logging. TPU adaptation: all tests run on a virtual
8-device CPU mesh (``xla_force_host_platform_device_count``) so sharding /
collective paths execute without TPU hardware — the reference's
multi-process-on-one-host trick done the JAX way.
"""
import os
import zlib

# must be set before jax initializes; append so a user-supplied XLA_FLAGS
# (e.g. --xla_dump_to) doesn't silently collapse the virtual mesh to 1 device
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

import jax  # noqa: E402

# the axon sitecustomize pins JAX_PLATFORMS=axon; override to CPU for tests.
# MXNET_TEST_PLATFORM=tpu keeps the real chip visible so the tpu-marked
# smoke tests (tests/test_tpu_smoke.py) exercise real hardware:
#   MXNET_TEST_PLATFORM=tpu python -m pytest tests/test_tpu_smoke.py
if os.environ.get("MXNET_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as _np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def seed_rngs(request):
    """Seed numpy + framework RNGs per test (reference conftest.py:40-91)."""
    # crc32, not hash(): str hashing is randomized per process, which would
    # defeat the reproducibility this fixture exists to provide
    seed = zlib.crc32(request.node.nodeid.encode()) % (2**31)
    marker = request.node.get_closest_marker("seed")
    if marker is not None:
        seed = marker.args[0]
    _np.random.seed(seed)
    import mxnet_tpu as mx

    mx.random.seed(seed)
    yield


def pytest_configure(config):
    config.addinivalue_line("markers", "seed(n): fix the RNG seed for a test")
    config.addinivalue_line("markers", "serial: run without xdist")
    config.addinivalue_line("markers", "integration: slower end-to-end test")
    config.addinivalue_line(
        "markers", "slow: excluded from tier-1 (-m 'not slow'); the "
        "fault-injection stress loop and other long soak tests")
