"""Overload-safety conformance for ``mxnet_tpu/serve/``: deadline
propagation across every stage boundary, priority-aware load shedding,
graceful drain / hot swap / health probes, the close-timeout leak fix,
and the chaos soak harness (``tools/chaos_soak.py``) as a pytest surface.

The soak's acceptance invariants — exactly-once settle, no silent late
completions, batch-class-only sheds, bounded interactive p99, clean
drain, warm same-signature swap — run as a short smoke in tier-1 and as
the full-length soak behind ``-m slow``.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — registers config flags
from mxnet_tpu import gluon
from mxnet_tpu.models.llama import get_llama
from mxnet_tpu.resilience import faults
from mxnet_tpu.serve import (DeadlineExceeded, DynamicBatcher, Generator,
                             InferenceSession, ServiceUnavailable,
                             TokenBucket)

from tools.chaos_soak import run_soak


@pytest.fixture
def no_faults():
    yield
    faults.clear_plan()


def _make_classifier(out=4):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(out))
    net.initialize()
    return net


def _warm_session(name, out=4):
    net = _make_classifier(out)
    sess = InferenceSession(net, batch_buckets=(1, 2, 4), name=name)
    sess.warmup(np.zeros((1, 8), np.float32))
    return net, sess


class _BlockedRunner:
    """A runner wedged on an event — the queue backs up behind it."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = []

    def __call__(self, batch):
        self.release.wait(10)
        self.calls.append(len(batch))
        return list(batch)


def _wait_until(cond, timeout=5.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, msg
        time.sleep(0.002)


# ---------------------------------------------------------------------------
# Deadline propagation: cancelled at every stage boundary
# ---------------------------------------------------------------------------


class TestDeadlinePropagation:
    def test_expired_at_admission_rejects_synchronously(self):
        with DynamicBatcher(lambda b: b, max_batch_size=2, timeout_ms=5.0,
                            max_queue=8, name="adm") as b:
            with pytest.raises(DeadlineExceeded, match="before admission"):
                b.submit("x", deadline_ms=1e-6)
        assert b.metrics.deadline_expired == {"admit": 1}

    def test_expired_in_queue_settles_504(self):
        """A queued request whose deadline passes is swept out and its
        future settles with DeadlineExceeded — the flusher wakes for the
        nearest deadline, not just the batch-assembly timeout."""
        with DynamicBatcher(lambda b: b, max_batch_size=8,
                            timeout_ms=10_000.0, max_queue=8,
                            name="qexp") as b:
            f = b.submit("x", deadline_ms=40.0)
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded, match="in queue"):
                f.result(timeout=5)
            # swept near the deadline, not at the 10s batch timeout
            assert time.monotonic() - t0 < 2.0
        assert b.metrics.deadline_expired == {"queue": 1}
        assert b.queue_depth() == 0

    def test_completion_past_deadline_plus_grace_is_504(self):
        """The client's budget ran out mid-execution: the result is
        discarded and the future carries a 504, never a silent late
        delivery."""
        def slow_runner(batch):
            time.sleep(0.12)
            return list(batch)

        with DynamicBatcher(slow_runner, max_batch_size=1, timeout_ms=0.0,
                            max_queue=8, name="late") as b:
            assert b.deadline_grace_s == 0.0  # default: no grace
            f = b.submit("x", deadline_ms=30.0)
            with pytest.raises(DeadlineExceeded, match="past deadline"):
                f.result(timeout=5)
        assert b.metrics.deadline_expired == {"execute": 1}

    def test_completion_within_grace_is_delivered_but_counted_late(self):
        def slow_runner(batch):
            time.sleep(0.08)
            return list(batch)

        with DynamicBatcher(slow_runner, max_batch_size=1, timeout_ms=0.0,
                            max_queue=8, name="grace") as b:
            b.deadline_grace_s = 10.0
            f = b.submit("x", deadline_ms=20.0)
            assert f.result(timeout=5) == "x"  # delivered...
        assert b.metrics.late_completions == 1  # ...but not goodput
        assert b.metrics.goodput == 0
        assert b.metrics.deadline_expired == {}

    def test_no_deadline_means_no_checks(self):
        """Off-by-default: a deadline-free submit never sees deadline
        machinery — original semantics, and every on-time completion is
        goodput."""
        with DynamicBatcher(lambda b: b, max_batch_size=2, timeout_ms=2.0,
                            max_queue=8, name="nodl") as b:
            assert b.submit("x").result(timeout=5) == "x"
        assert b.metrics.deadline_expired == {}
        assert b.metrics.late_completions == 0
        assert b.metrics.goodput == 1

    def test_decode_retires_expired_row_mid_stream(self):
        """A generation row whose deadline passes is retired between
        decode steps (keeps its partial output, stops burning T=1 passes)
        while live rows decode to completion."""
        net = get_llama("llama_tiny_test")
        net.initialize()
        gen = Generator(net, max_seq=32, batch_buckets=(2,),
                        prompt_buckets=(8,), name="dl_decode")
        now = time.monotonic()
        outs, info = gen.generate([[3, 5, 7], [9, 2]], max_new_tokens=6,
                                  deadlines=[now, now + 60.0])
        assert info["deadline_expired"] == [0]
        assert len(outs[1]) == 6  # the live row is unaffected
        assert gen.metrics.deadline_expired["decode"] >= 1

    def test_decode_without_deadlines_is_unchanged(self):
        net = get_llama("llama_tiny_test")
        net.initialize()
        gen = Generator(net, max_seq=32, batch_buckets=(1,),
                        prompt_buckets=(8,), name="nodl_decode")
        outs, info = gen.generate([[3, 5, 7]], max_new_tokens=4)
        assert info["deadline_expired"] == []
        assert len(outs[0]) == 4


# ---------------------------------------------------------------------------
# Priority-aware load shedding
# ---------------------------------------------------------------------------


class TestPriorityShedding:
    def test_interactive_displaces_newest_batch_request(self):
        runner = _BlockedRunner()
        b = DynamicBatcher(runner, max_batch_size=1, timeout_ms=0.0,
                           max_queue=2, name="shed")
        try:
            first = b.submit(0, priority="batch")   # goes in flight
            _wait_until(lambda: b.queue_depth() == 0)
            b1 = b.submit(1, priority="batch")
            b2 = b.submit(2, priority="batch")      # queue now full
            hi = b.submit(3, priority="interactive")
            # the NEWEST batch request was shed to admit the interactive
            with pytest.raises(ServiceUnavailable, match="shed under"):
                b2.result(timeout=5)
            assert not b1.done()
            runner.release.set()
            assert first.result(timeout=5) == 0
            assert b1.result(timeout=5) == 1
            assert hi.result(timeout=5) == 3
        finally:
            runner.release.set()
            b.close()
        assert dict(b.metrics.sheds) == {"batch": 1}

    def test_full_queue_of_equal_priority_rejects(self):
        runner = _BlockedRunner()
        b = DynamicBatcher(runner, max_batch_size=1, timeout_ms=0.0,
                           max_queue=1, name="eqfull")
        try:
            b.submit(0, priority="interactive")
            _wait_until(lambda: b.queue_depth() == 0)
            b.submit(1, priority="interactive")     # fills the queue
            # no lower-priority victim -> even interactive rejects
            with pytest.raises(ServiceUnavailable, match="queue is full"):
                b.submit(2, priority="interactive")
            with pytest.raises(ServiceUnavailable, match="queue is full"):
                b.submit(3, priority="batch")
        finally:
            runner.release.set()
            b.close()
        assert b.metrics.sheds.get("interactive", 0) == 0

    def test_batch_queue_share_cap_shed(self):
        runner = _BlockedRunner()
        b = DynamicBatcher(runner, max_batch_size=1, timeout_ms=0.0,
                           max_queue=8, name="share")
        b.batch_queue_cap = 1
        try:
            b.submit(0, priority="interactive")
            _wait_until(lambda: b.queue_depth() == 0)
            b.submit(1, priority="batch")           # within the share
            with pytest.raises(ServiceUnavailable, match="queue share"):
                b.submit(2, priority="batch")
            # interactive traffic still finds headroom
            b.submit(3, priority="interactive")
        finally:
            runner.release.set()
            b.close()
        assert dict(b.metrics.sheds) == {"batch": 1}

    def test_token_bucket_rate_limits_batch_only(self):
        with DynamicBatcher(lambda b: b, max_batch_size=4, timeout_ms=2.0,
                            max_queue=16, name="rate") as b:
            b.rate_limiter = TokenBucket(rate=1.0, burst=1.0)
            assert b.submit("b0", priority="batch").result(timeout=5) == "b0"
            with pytest.raises(ServiceUnavailable, match="token bucket"):
                b.submit("b1", priority="batch")
            # interactive is never rate-limited
            f = b.submit("i0", priority="interactive")
            assert f.result(timeout=5) == "i0"
        assert b.metrics.rate_limited == 1
        assert dict(b.metrics.sheds) == {"batch": 1}

    def test_token_bucket_refills(self):
        tb = TokenBucket(rate=10.0, burst=1.0)
        assert tb.take()
        assert not tb.take()
        time.sleep(0.25)
        assert tb.take()  # ~2.5 tokens refilled, capped at burst=1

    def test_unknown_priority_rejected_loudly(self):
        with DynamicBatcher(lambda b: b, max_batch_size=2,
                            timeout_ms=2.0, name="prio") as b:
            with pytest.raises(Exception, match="unknown priority"):
                b.submit("x", priority="urgent")

    def test_batches_assemble_interactive_first(self):
        """When a mixed queue flushes, interactive requests occupy the
        batch slots first; overflow batch-class work waits."""
        runner = _BlockedRunner()
        b = DynamicBatcher(runner, max_batch_size=2, timeout_ms=0.0,
                           max_queue=8, name="order")
        try:
            b.submit(0, priority="batch")  # alone -> in flight first
            _wait_until(lambda: b.queue_depth() == 0)
            lo = b.submit("lo", priority="batch")
            hi1 = b.submit("hi1", priority="interactive")
            hi2 = b.submit("hi2", priority="interactive")
            runner.release.set()
            assert hi1.result(timeout=5) == "hi1"
            assert hi2.result(timeout=5) == "hi2"
            assert lo.result(timeout=5) == "lo"
            # flush 2 was the two interactive requests, not FIFO order
            assert runner.calls[1] == 2
        finally:
            runner.release.set()
            b.close()


# ---------------------------------------------------------------------------
# serve:queue fault site
# ---------------------------------------------------------------------------


class TestQueueFaultSite:
    def test_injected_admission_fault_surfaces_synchronously(self, no_faults):
        faults.install_plan({"seed": 0, "rules": [
            {"site": "serve:queue", "kind": "transient", "at": [0]}]})
        with DynamicBatcher(lambda b: b, max_batch_size=2,
                            timeout_ms=2.0, name="qfault") as b:
            with pytest.raises(Exception, match="[Ii]njected"):
                b.submit("x")
            faults.clear_plan()
            assert b.submit("y").result(timeout=5) == "y"


# ---------------------------------------------------------------------------
# Graceful drain / hot swap / health probes
# ---------------------------------------------------------------------------


class TestDrainSwapHealth:
    def test_batcher_drain_settles_everything_then_blocks_admission(self):
        done = []

        def runner(batch):
            time.sleep(0.01)
            done.append(len(batch))
            return list(batch)

        b = DynamicBatcher(runner, max_batch_size=4, timeout_ms=50.0,
                           max_queue=32, name="drain")
        try:
            futs = [b.submit(i) for i in range(6)]
            assert b.drain(timeout=10)
            assert b.queue_depth() == 0
            assert all(f.done() for f in futs)
            assert [f.result() for f in futs] == list(range(6))
            with pytest.raises(ServiceUnavailable, match="draining"):
                b.submit("late")
            b.resume()
            assert b.submit("after").result(timeout=5) == "after"
        finally:
            b.close()

    def test_drain_wakes_fast_when_sweep_empties_queue(self):
        """A queue emptied by the expired-deadline sweep must wake
        drain() immediately, not leave it sleeping to its timeout."""
        runner = _BlockedRunner()
        b = DynamicBatcher(runner, max_batch_size=1, timeout_ms=0.0,
                           max_queue=8, name="sweepdrain")
        try:
            b.submit(0)                         # dispatches, wedges
            _wait_until(lambda: b.queue_depth() == 0)
            f = b.submit(1, deadline_ms=30.0)   # queued behind the wedge
            t0 = time.monotonic()
            done = []
            waiter = threading.Thread(
                target=lambda: done.append(b.drain(timeout=30.0)),
                daemon=True)
            waiter.start()
            time.sleep(0.1)                     # let the sweep fire
            runner.release.set()                # settle the wedged batch
            waiter.join(10)
            assert done == [True]
            assert time.monotonic() - t0 < 5.0  # NOT the 30s timeout
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=1)
        finally:
            runner.release.set()
            b.close()

    def test_session_drain_blocks_and_resume_reopens(self):
        _, sess = _warm_session("sdrain")
        x = np.zeros((1, 8), np.float32)
        assert sess.drain(timeout=5)
        assert sess.health()["state"] == "draining"
        assert not sess.ready()
        with pytest.raises(ServiceUnavailable, match="draining"):
            sess.predict(x)
        sess.resume()
        assert sess.ready()
        assert sess.predict(x).shape == (1, 4)
        sess.assert_no_recompiles()

    def test_warm_swap_same_signature_zero_recompiles(self):
        _, sess = _warm_session("wswap")
        net2 = _make_classifier()
        x = np.ones((2, 8), np.float32)
        from mxnet_tpu import autograd
        from mxnet_tpu import numpy as mnp

        with autograd.predict_mode():
            ref2 = net2(mnp.array(x)).asnumpy()
        assert sess.swap(net2, example=np.zeros((1, 8), np.float32)) \
            == "warm"
        # the swapped weights serve through the ORIGINAL executables
        np.testing.assert_allclose(sess.predict(x).asnumpy(), ref2,
                                   rtol=1e-5, atol=1e-6)
        sess.assert_no_recompiles()
        assert sess.ready()
        assert sess.metrics.swaps == 1

    def test_cold_swap_different_architecture_rewarms(self):
        _, sess = _warm_session("cswap")
        net2 = _make_classifier(out=7)  # different output width
        assert sess.swap(net2, example=np.zeros((1, 8), np.float32)) \
            == "cold"
        assert sess.ready()  # example given -> re-warmed + frozen
        assert sess.predict(np.zeros((2, 8), np.float32)).shape == (2, 7)
        sess.assert_no_recompiles()

    def test_swap_timeout_aborts_and_keeps_old_model(self, no_faults):
        _, sess = _warm_session("tswap")
        x = np.zeros((1, 8), np.float32)
        faults.install_plan({"seed": 0, "rules": [
            {"site": "serve:execute", "kind": "delay", "seconds": 0.6,
             "times": 1}]})
        slow = threading.Thread(target=lambda: sess.predict(x),
                                daemon=True)
        slow.start()
        _wait_until(lambda: sess.health()["inflight"] > 0)
        with pytest.raises(ServiceUnavailable, match="swap aborted"):
            sess.swap(_make_classifier(), timeout=0.05)
        slow.join(10)
        # admission was resumed: the OLD model still serves
        assert sess.predict(x).shape == (1, 4)
        sess.assert_no_recompiles()

    def test_health_ready_contract(self):
        net = _make_classifier()
        sess = InferenceSession(net, batch_buckets=(1,), name="probe")
        h = sess.health()
        assert {"state", "ready", "warm", "inflight", "breaker",
                "error_rate", "watchdog_orphans"} <= set(h)
        assert not sess.ready()            # not warmed yet
        sess.warmup(np.zeros((1, 8), np.float32))
        assert sess.ready()
        for _ in range(sess.breaker.failure_threshold):
            sess.breaker.record_failure()
        assert sess.breaker.state == "open"
        assert not sess.ready()            # breaker open -> route around
        sess.breaker.record_success()
        assert sess.ready()


# ---------------------------------------------------------------------------
# close(timeout) leak fix (satellite): wedged runner, no stranded futures
# ---------------------------------------------------------------------------


class TestCloseTimeout:
    def test_close_with_wedged_runner_fails_futures_503(self):
        runner = _BlockedRunner()
        b = DynamicBatcher(runner, max_batch_size=1, timeout_ms=0.0,
                           max_queue=8, name="wedge")
        inflight = b.submit("inflight")
        _wait_until(lambda: b.queue_depth() == 0)  # it reached the runner
        queued = b.submit("queued")
        with pytest.warns(RuntimeWarning, match="wedged"):
            b.close(timeout=0.3)
        # BOTH the wedged batch's future and the queued one fail fast
        # with 503 — before this fix they hung forever
        for f in (inflight, queued):
            with pytest.raises(ServiceUnavailable, match="shut down"):
                f.result(timeout=1)
        # the runner eventually un-wedges: its settle attempt must be
        # dropped (exactly-once), and the flusher thread must exit
        runner.release.set()
        _wait_until(lambda: not b._thread.is_alive(), timeout=10,
                    msg="flusher never exited after un-wedge")
        with pytest.raises(ServiceUnavailable, match="shut down"):
            inflight.result(timeout=1)  # still the 503, not the result

    def test_clean_close_needs_no_timeout_path(self):
        import warnings

        with warnings.catch_warnings(record=True) as record:
            warnings.simplefilter("always")
            b = DynamicBatcher(lambda b: b, max_batch_size=2,
                               timeout_ms=2.0, name="clean")
            f = b.submit("x")
            assert f.result(timeout=5) == "x"
            b.close(timeout=5)
        assert not any(isinstance(w.message, RuntimeWarning)
                       for w in record)


# ---------------------------------------------------------------------------
# Chaos soak: the acceptance invariants, smoke in tier-1, full behind slow
# ---------------------------------------------------------------------------


def _assert_soak_invariants(report):
    assert report["ok"], "\n".join(report["violations"])
    assert report["outcomes"]["unexpected"] == 0
    assert report["outcomes"]["ok"] > 0
    # exactly-once settle: the client books balance
    assert sum(report["outcomes"].values()) >= report["admitted"]
    assert report["late_completions_client"] == 0
    assert all(k == "batch" for k in report["sheds"])
    assert report["interactive_p99_ms"] <= report["p99_bound_ms"]
    assert report["swap_mode"] == "warm"
    assert report["faults_fired"] > 0  # chaos actually happened


class TestChaosSoak:
    def test_soak_smoke_64_clients(self):
        """~3s of 64 concurrent mixed-priority clients under the seeded
        fault plan: every acceptance invariant, tier-1 sized."""
        report = run_soak(duration_s=2.5, clients=64, seed=11,
                          decode=False, verbose=False)
        _assert_soak_invariants(report)

    @pytest.mark.slow
    def test_soak_full_with_decode_leg(self):
        """The full-length soak: more clients, longer duration, plus the
        Generator/serve:decode leg with mid-decode deadline retirement."""
        report = run_soak(duration_s=20.0, clients=96, seed=7,
                          decode=True, verbose=False)
        _assert_soak_invariants(report)
        assert report["decode"]["faulted"] > 0
        assert report["decode"]["expired_rows"] == 1

    @pytest.mark.slow
    def test_soak_seed_sweep(self):
        """Different seeds fire different fault schedules; the invariants
        are seed-independent."""
        for seed in (1, 23):
            report = run_soak(duration_s=6.0, clients=64, seed=seed,
                              decode=False, verbose=False)
            _assert_soak_invariants(report)
