"""Conformance tests for continuous batching
(``mxnet_tpu/serve/scheduler.py``): iteration-level admission/retirement
over the fixed slot lattice, chunked prefill, trace-static steady state
(>= 100 admit/retire cycles with zero recompiles), PR-6 deadline and
priority semantics through the scheduler, pool-exhaustion backpressure,
and the TTFT/ITL + kv-page metrics surface.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.llama import get_llama
from mxnet_tpu.resilience import faults
from mxnet_tpu.serve import ContinuousEngine, DeadlineExceeded, Generator, \
    ServiceUnavailable


def _tiny_llama(config="llama_tiny_test", **over):
    net = get_llama(config, **over)
    net.initialize()
    return net


@pytest.fixture
def no_faults():
    yield
    faults.clear_plan()


@pytest.fixture(scope="module")
def net():
    return _tiny_llama()


def _engine(net, **over):
    kw = dict(max_seq=64, num_slots=4, page_size=16, prefill_chunk=16,
              decode_path="baseline")
    kw.update(over)
    return ContinuousEngine(net, **kw)


class TestScheduler:
    def test_two_signatures_and_token_parity(self, net):
        """The engine compiles exactly TWO executables — one chunked
        prefill, one full-width decode — and its greedy output matches
        the plain Generator token-for-token (short, long, and
        multi-chunk prompts)."""
        with _engine(net, name="cb_parity") as eng:
            assert eng.session.signature_count() == 2
            ref = Generator(net, max_seq=64, batch_buckets=(1,),
                            prompt_buckets=(16, 32),
                            decode_path="baseline", name="cb_ref")
            prompts = [[5, 6, 7], [9, 10, 11, 12, 13], [3] * 20]
            futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            for p, f in zip(prompts, futs):
                want, _ = ref.generate([p], max_new_tokens=6)
                assert f.result(timeout=60)["tokens"] == want[0]
            eng.assert_no_recompiles()
            assert eng.session.signature_count() == 2

    def test_hundred_admit_retire_cycles_zero_recompiles(self, net):
        """THE acceptance invariant: >= 100 admit/retire cycles through
        every occupancy (the engine has 2 slots, requests of varying
        prompt/output lengths churn constantly) and the signature set
        never grows."""
        with _engine(net, num_slots=2, name="cb_churn",
                     max_queue=128) as eng:
            futs = [eng.submit([1 + i % 50, 2 + i % 30],
                               max_new_tokens=1 + i % 4)
                    for i in range(110)]
            for i, f in enumerate(futs):
                r = f.result(timeout=120)
                assert len(r["tokens"]) == 1 + i % 4
            eng.assert_no_recompiles()
            st = eng.stats()
            assert st["pool"]["pages_owned"] == 0  # all recycled
            assert st["requests"] >= 110

    def test_interactive_preempts_queued_batch_work(self, net):
        """PR-6 class semantics at the iteration boundary: with one slot
        and a backlog of batch-class work, an interactive arrival is
        admitted before every queued batch request."""
        with _engine(net, num_slots=1, name="cb_prio") as eng:
            order = []
            lock = threading.Lock()

            def tag(name):
                def cb(_f):
                    with lock:
                        order.append(name)
                return cb

            # slot occupied by a long batch job; more batch work queued
            eng.submit([5] * 8, max_new_tokens=40,
                       priority="batch").add_done_callback(tag("b0"))
            time.sleep(0.05)  # let it occupy the slot
            for i in range(3):
                eng.submit([6, 7], max_new_tokens=4,
                           priority="batch").add_done_callback(
                               tag(f"b{i + 1}"))
            fi = eng.submit([8, 9], max_new_tokens=2,
                            priority="interactive")
            fi.add_done_callback(tag("i"))
            fi.result(timeout=60)
            eng.drain(timeout=60)
            with lock:
                # the interactive request finished before every QUEUED
                # batch request (b0 already held the slot)
                assert order.index("i") < order.index("b1")
                assert order.index("i") < order.index("b2")
                assert order.index("i") < order.index("b3")
            eng.resume()
            eng.assert_no_recompiles()

    def test_deadline_mid_decode_is_504_with_partial(self, net):
        with _engine(net, num_slots=2, name="cb_dl") as eng:
            f = eng.submit([9, 9, 9], max_new_tokens=40, deadline_ms=60)
            with pytest.raises(DeadlineExceeded) as ei:
                f.result(timeout=60)
            assert ei.value.status == 504
            assert 0 < len(ei.value.partial) < 40
            snap = eng.metrics.snapshot()
            assert snap["deadline_expired"].get("decode", 0) >= 1
            eng.assert_no_recompiles()

    def test_pool_exhaustion_queues_not_crashes(self, net):
        """Undersized pool (pages for ~1 request): admissions beyond
        capacity wait for retirements to recycle pages; every request
        still completes and the exhaustion shows in pool stats."""
        with _engine(net, num_slots=2, num_pages=4,
                     name="cb_tight") as eng:
            futs = [eng.submit([3, 4, 5], max_new_tokens=30)
                    for _ in range(4)]
            for f in futs:
                assert len(f.result(timeout=120)["tokens"]) == 30
            st = eng.stats()
            assert st["pool"]["exhausted_count"] > 0
            assert st["pool"]["pages_owned"] == 0
            eng.assert_no_recompiles()

    def test_submit_validation(self, net):
        with _engine(net, name="cb_val") as eng:
            with pytest.raises(MXNetError, match="empty prompt"):
                eng.submit([])
            with pytest.raises(MXNetError, match="exceeds max_seq"):
                eng.submit([1] * 40, max_new_tokens=40)
            with pytest.raises(MXNetError, match="max_new_tokens"):
                eng.submit([1], max_new_tokens=0)

    def test_close_fails_live_and_queued_with_503(self, net):
        eng = _engine(net, num_slots=1, name="cb_close")
        eng.start()
        f_live = eng.submit([5] * 8, max_new_tokens=40)
        time.sleep(0.05)
        f_q = eng.submit([6, 7], max_new_tokens=4)
        eng.close()
        for f in (f_live, f_q):
            with pytest.raises(ServiceUnavailable):
                f.result(timeout=5)

    def test_decode_fault_fails_requests_not_engine(self, net, no_faults):
        """An injected serve:decode fault is a per-request 5xx; the
        scheduler keeps serving the next submission."""
        with _engine(net, num_slots=2, name="cb_fault") as eng:
            faults.install_plan({"seed": 0, "rules": [
                {"site": "serve:decode", "kind": "fatal", "times": 1}]})
            f = eng.submit([5, 6], max_new_tokens=8)
            with pytest.raises(Exception):
                f.result(timeout=60)
            faults.clear_plan()
            r = eng.submit([5, 6], max_new_tokens=4).result(timeout=60)
            assert len(r["tokens"]) == 4
            st = eng.stats()
            assert st["pool"]["pages_owned"] == 0  # fault freed its pages

    def test_idempotency_key_exactly_once(self, net):
        with _engine(net, name="cb_key") as eng:
            f1 = eng.submit([5, 6, 7], max_new_tokens=4, key="req-1")
            f2 = eng.submit([5, 6, 7], max_new_tokens=4, key="req-1")
            assert f1 is f2
            f1.result(timeout=60)
            assert eng.stats()["duplicate_submits"] == 1


class TestServeMetricsCB:
    def test_ttft_itl_and_gauges_flow_to_export(self, net):
        from mxnet_tpu.profiler import export

        with _engine(net, name="cb_metrics") as eng:
            futs = [eng.submit([1 + i, 2], max_new_tokens=4)
                    for i in range(6)]
            results = [f.result(timeout=60) for f in futs]
            assert all(r["ttft_ms"] > 0 for r in results)
            snap = eng.metrics.snapshot()
            assert snap["ttft_p99_ms"] > 0
            assert snap["itl_p99_ms"] > 0
            assert snap["itl_p50_ms"] <= snap["itl_p99_ms"]
            assert snap["slots_total"] == 4
            assert snap["kv_pages_used"] == 0  # all retired by now
            assert snap["kv_pages_free"] == eng.pool.pages_total
            # unified export surface: serve.<name>.* flattening
            flat = export.snapshot()
            assert flat["serve.cb_metrics.ttft_p99_ms"] == \
                snap["ttft_p99_ms"]
            assert flat["serve.cb_metrics.itl_p99_ms"] == \
                snap["itl_p99_ms"]
            assert "serve.cb_metrics.kv_pages_free" in flat
            assert "serve.cb_metrics.slot_occupancy" in flat

    def test_admit_wait_bounded_by_one_step_with_free_slots(self, net):
        """The headline scheduling property: while a long decode holds
        one slot, a short request entering a FREE slot waits at most one
        scheduler iteration for admission."""
        with _engine(net, num_slots=4, name="cb_wait") as eng:
            f_long = eng.submit([5] * 8, max_new_tokens=48)
            time.sleep(0.05)  # the long decode is mid-flight
            shorts = [eng.submit([6, 7], max_new_tokens=2)
                      for _ in range(3)]
            waits = [f.result(timeout=60)["admit_wait_steps"]
                     for f in shorts]
            assert all(w <= 1 for w in waits), waits
            assert not f_long.done()  # they finished UNDER the long one
            f_long.result(timeout=120)
            eng.assert_no_recompiles()
