"""Legacy top-level module parity: callback, model checkpoints, name
scopes, attribute scopes, typed errors, symbol JSON round-trip, and the
NumPy dispatch protocol (reference: ``python/mxnet/{callback,model,name,
attribute,error,numpy_dispatch_protocol}.py``)."""
import logging

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mnp
from mxnet_tpu.base import MXNetError


def test_name_manager_and_prefix():
    before = mx.sym.var("x").tanh().name
    nxt = mx.sym.var("x").tanh().name
    # auto names are distinct and hint-based
    assert before != nxt and before.startswith("tanh")
    with mx.name.Prefix("stage1_"):
        assert mx.sym.var("z").relu().name.startswith("stage1_relu")
    # user-specified names always win
    assert mx.sym.var("q").tanh(name="myact").name == "myact"


def test_attr_scope_merging():
    with mx.attribute.AttrScope(group="enc"):
        s = mx.sym.var("w").tanh()
        assert s.attr["group"] == "enc"
        with mx.attribute.AttrScope(lr_mult="2"):
            inner = mx.sym.var("v").tanh()
            assert inner.attr == {"group": "enc", "lr_mult": "2"}
    after = mx.sym.var("u").tanh()
    assert "group" not in after.attr
    with pytest.raises(MXNetError):
        mx.attribute.AttrScope(bad=1)


def test_symbol_json_round_trip_with_consts(tmp_path):
    sym = ((mx.sym.var("a") + 2.0) * mx.sym.var("b")).sum(axis=1)
    path = str(tmp_path / "s.json")
    sym.save(path)
    back = mx.sym.load(path)
    a = mnp.array([[1.0, 2.0], [3.0, 4.0]])
    b = mnp.array([[2.0, 2.0], [0.5, 0.5]])
    onp.testing.assert_allclose(back.eval(a=a, b=b)[0].asnumpy(),
                                sym.eval(a=a, b=b)[0].asnumpy())
    assert back.list_arguments() == sym.list_arguments()


def test_symbol_load_accepts_nnvm_json_rejects_unknown(tmp_path):
    """Round 4: genuine nnvm graph JSON now loads through the
    legacy_json_util upgrade path (tests/test_reference_artifacts.py);
    non-symbol JSON still gets a clear rejection."""
    p = tmp_path / "legacy.json"
    p.write_text('{"nodes": [{"op": "null", "name": "x", "inputs": []},'
                 '{"op": "exp", "name": "e", "inputs": [[0, 0, 0]]}],'
                 '"arg_nodes": [0], "heads": [[1, 0, 0]]}')
    s = mx.sym.load(str(p))
    out = s.eval(x=mnp.zeros((2,)))
    onp.testing.assert_allclose(out[0].asnumpy(), [1.0, 1.0])
    q = tmp_path / "notasymbol.json"
    q.write_text('{"something": 1}')
    with pytest.raises(MXNetError):
        mx.sym.load(str(q))


def test_model_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "model")
    sym = mx.sym.var("a").tanh()
    arg = {"w": mnp.array([1.0, 2.0])}
    aux = {"running_mean": mnp.array([0.5])}
    mx.model.save_checkpoint(prefix, 3, sym, arg, aux)
    s, a2, x2 = mx.model.load_checkpoint(prefix, 3)
    onp.testing.assert_allclose(a2["w"].asnumpy(), [1.0, 2.0])
    onp.testing.assert_allclose(x2["running_mean"].asnumpy(), [0.5])
    assert s.list_arguments() == ["a"]
    # params-only load
    a3, x3 = mx.model.load_params(prefix, 3)
    assert set(a3) == {"w"} and set(x3) == {"running_mean"}


def test_do_checkpoint_period(tmp_path):
    import os

    prefix = str(tmp_path / "ck")
    cb = mx.callback.do_checkpoint(prefix, period=2)
    arg = {"w": mnp.array([1.0])}
    cb(0, None, arg, {})   # epoch 1: not a multiple of 2
    cb(1, None, arg, {})   # epoch 2: saved
    assert not os.path.exists(prefix + "-0001.params")
    assert os.path.exists(prefix + "-0002.params")


def test_speedometer_and_log_callbacks(caplog):
    class Param:
        def __init__(self, nbatch, metric=None):
            self.epoch = 0
            self.nbatch = nbatch
            self.eval_metric = metric

    sp = mx.callback.Speedometer(batch_size=32, frequent=10)
    with caplog.at_level(logging.INFO):
        sp(Param(0))
        sp(Param(10))
    assert any("samples/sec" in r.message for r in caplog.records)

    from mxnet_tpu.gluon import metric as metric_mod

    m = metric_mod.Accuracy()
    m.update(mnp.array([1.0, 0.0]), mnp.array([1.0, 1.0]))
    caplog.clear()
    with caplog.at_level(logging.INFO):
        mx.callback.log_train_metric(5)(Param(5, m))
        mx.callback.LogValidationMetricsCallback()(Param(5, m))
    msgs = [r.getMessage() for r in caplog.records]
    assert any("Train-accuracy" in s for s in msgs)
    assert any("Validation-accuracy" in s for s in msgs)


def test_error_registry():
    assert mx.error.error_class("ValueError") is ValueError
    assert mx.error.error_class("unknown-kind") is MXNetError
    with pytest.raises(mx.error.InternalError, match="hint"):
        raise mx.error.InternalError("boom")

    @mx.error.register
    class CustomError(MXNetError):
        pass

    assert mx.error.error_class("CustomError") is CustomError


def test_numpy_dispatch_protocol():
    a = mnp.array([1.0, 2.0, 3.0])
    # numpy functions dispatch to mx.np and stay NDArray
    r = onp.sum(a)
    assert type(r).__name__ == "NDArray" and float(r.asnumpy()) == 6.0
    r = onp.concatenate([a, a])
    assert type(r).__name__ == "NDArray" and r.shape == (6,)
    # ufuncs too
    r = onp.exp(a)
    assert type(r).__name__ == "NDArray"
    onp.testing.assert_allclose(r.asnumpy(), onp.exp([1.0, 2.0, 3.0]),
                                rtol=1e-6)
    # mixed numpy-array + NDArray arithmetic returns NDArray
    r = onp.ones(3, "float32") + a
    assert type(r).__name__ == "NDArray"
    # ufunc .reduce falls back to host numpy values
    assert float(onp.add.reduce(a)) == 6.0
