"""BERT / Transformer model tests (targets from BASELINE.json configs)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.models import (BERTClassifier, BERTForPretrain, Transformer,
                              get_bert_model)


def _bert_tiny(**kw):
    cfg = dict(units=32, hidden_size=64, num_layers=2, num_heads=4,
               vocab_size=100, max_length=64, dropout=0.0)
    cfg.update(kw)
    return get_bert_model(**cfg)


def _ids(b=2, t=16, vocab=100):
    return mx.np.array(np.random.randint(0, vocab, (b, t)))


def test_bert_backbone_shapes():
    bert = _bert_tiny()
    bert.initialize()
    seq, pooled = bert(_ids(), None, mx.np.array(np.array([16, 9])))
    assert seq.shape == (2, 16, 32)
    assert pooled.shape == (2, 32)


def test_bert_valid_length_masks_padding():
    bert = _bert_tiny()
    bert.initialize()
    ids = _ids(1, 8)
    vl = mx.np.array(np.array([5]))
    with autograd.predict_mode():
        seq_full, _ = bert(ids, None, vl)
        # changing tokens beyond valid_length must not change valid outputs
        arr = ids.asnumpy().copy()
        arr[0, 5:] = 1
        seq_mod, _ = bert(mx.np.array(arr), None, vl)
    np.testing.assert_allclose(seq_full.asnumpy()[0, :5],
                               seq_mod.asnumpy()[0, :5], rtol=1e-4,
                               atol=1e-5)


def test_bert_pretrain_backward_ties_embedding():
    bert = _bert_tiny()
    pre = BERTForPretrain(bert)
    pre.initialize()
    ids = _ids()
    with autograd.record():
        mlm, nsp = pre(ids)
        loss = mlm.sum() + nsp.sum()
    loss.backward()
    assert mlm.shape == (2, 16, 100)
    assert nsp.shape == (2, 2)
    g = bert.collect_params()["word_embed.weight"].grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_bert_classifier_train_step():
    bert = _bert_tiny()
    net = BERTClassifier(bert, num_classes=3, dropout=0.0)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    ids = _ids(4)
    y = mx.np.array(np.random.randint(0, 3, (4,)))
    losses = []
    for _ in range(3):
        with autograd.record():
            l = loss_fn(net(ids), y).mean()
        l.backward()
        trainer.step(4)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0]


def test_bert_hybridize_matches_eager():
    bert = _bert_tiny()
    bert.initialize()
    ids = _ids()
    with autograd.predict_mode():
        seq_e, pooled_e = bert(ids)
    bert.hybridize()
    with autograd.predict_mode():
        seq_h, pooled_h = bert(ids)
    np.testing.assert_allclose(pooled_e.asnumpy(), pooled_h.asnumpy(),
                               rtol=2e-5, atol=2e-5)


def test_bert_config_registry():
    with pytest.raises(mx.MXNetError):
        get_bert_model("bert_nonexistent")
    with pytest.raises(mx.MXNetError):
        get_bert_model(pretrained=True)


def test_transformer_mt_forward_backward():
    net = Transformer(src_vocab_size=50, tgt_vocab_size=60, units=32,
                      hidden_size=64, num_heads=4, num_encoder_layers=2,
                      num_decoder_layers=2, dropout=0.0)
    net.initialize()
    src = _ids(2, 10, 50)
    tgt = _ids(2, 7, 60)
    svl = mx.np.array(np.array([10, 6]))
    with autograd.record():
        out = net(src, tgt, svl)
        loss = out.sum()
    loss.backward()
    assert out.shape == (2, 7, 60)
    g = net.collect_params()["src_embed.weight"].grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_transformer_decoder_is_causal():
    net = Transformer(src_vocab_size=50, units=32, hidden_size=64,
                      num_heads=4, num_encoder_layers=1,
                      num_decoder_layers=1, dropout=0.0)
    net.initialize()
    src = _ids(1, 6, 50)
    tgt = _ids(1, 8, 50)
    with autograd.predict_mode():
        out1 = net(src, tgt).asnumpy()
        # changing a later target token must not affect earlier outputs
        arr = tgt.asnumpy().copy()
        arr[0, 5] = (arr[0, 5] + 1) % 50
        out2 = net(src, mx.np.array(arr)).asnumpy()
    np.testing.assert_allclose(out1[0, :5], out2[0, :5], rtol=1e-4,
                               atol=1e-5)
    assert np.abs(out1[0, 5:] - out2[0, 5:]).max() > 1e-6
