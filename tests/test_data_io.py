"""gluon.data + io + recordio tests (reference:
tests/python/unittest/test_gluon_data.py, test_io.py, test_recordio.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.data import (ArrayDataset, BatchSampler, DataLoader,
                                  RandomSampler, SequentialSampler,
                                  SimpleDataset)
from mxnet_tpu.gluon.data.vision import transforms


def test_array_dataset_and_transform():
    X = np.random.randn(10, 3).astype("float32")
    y = np.arange(10).astype("int32")
    ds = ArrayDataset(X, y)
    assert len(ds) == 10
    x0, y0 = ds[3]
    np.testing.assert_allclose(x0, X[3])
    assert y0 == 3
    ds2 = ds.transform_first(lambda x: x * 2)
    np.testing.assert_allclose(ds2[3][0], X[3] * 2)


def test_dataset_combinators():
    ds = SimpleDataset(list(range(20)))
    assert list(ds.take(5)) == [0, 1, 2, 3, 4]
    assert list(ds.filter(lambda x: x % 2 == 0)) == list(range(0, 20, 2))
    sh = ds.shard(3, 0)
    assert len(sh) == 7  # ceil(20/3), wraps
    assert sh[0] == 0 and sh[1] == 3


def test_samplers():
    assert list(SequentialSampler(5)) == [0, 1, 2, 3, 4]
    assert sorted(RandomSampler(7)) == list(range(7))
    bs = BatchSampler(SequentialSampler(7), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 1]
    bs = BatchSampler(SequentialSampler(7), 3, "discard")
    assert [len(b) for b in bs] == [3, 3]
    bs = BatchSampler(SequentialSampler(7), 3, "rollover")
    assert [len(b) for b in bs] == [3, 3]
    assert [len(b) for b in bs] == [3, 3]  # 1 rolled + 7 = 8 -> 2 full + 2 roll


def test_dataloader_single_process():
    X = np.random.randn(11, 4).astype("float32")
    y = np.arange(11).astype("int32")
    loader = DataLoader(ArrayDataset(X, y), batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 4)
    assert batches[2][0].shape == (3, 4)
    np.testing.assert_allclose(batches[1][1].asnumpy(), y[4:8])


def test_dataloader_shuffle_covers_all():
    X = np.arange(16).astype("float32").reshape(16, 1)
    loader = DataLoader(ArrayDataset(X), batch_size=4, shuffle=True)
    seen = np.concatenate([b.asnumpy().ravel() for b in loader])
    assert sorted(seen) == list(range(16))


def test_dataloader_multiworker():
    X = np.arange(24).astype("float32").reshape(24, 1)
    y = np.arange(24).astype("int32")
    loader = DataLoader(ArrayDataset(X, y), batch_size=5, num_workers=2)
    seen = np.concatenate([b[1].asnumpy().ravel() for b in loader])
    assert sorted(seen.tolist()) == list(range(24))


def test_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio

    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(f"record-{i}".encode() * (i + 1))
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert r.read() == f"record-{i}".encode() * (i + 1)
    assert r.read() is None


def test_indexed_recordio_and_dataset(tmp_path):
    from mxnet_tpu import recordio
    from mxnet_tpu.gluon.data import RecordFileDataset

    rec = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(7):
        w.write_idx(i, f"payload{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(5) == b"payload5"
    assert r.read_idx(0) == b"payload0"
    ds = RecordFileDataset(rec)
    assert len(ds) == 7
    assert ds[3] == b"payload3"


def test_pack_unpack_img(tmp_path):
    from mxnet_tpu import recordio

    img = (np.random.rand(32, 32, 3) * 255).astype("uint8")
    header = recordio.IRHeader(0, 7.0, 42, 0)
    s = recordio.pack_img(header, img, img_fmt=".png")
    h2, img2 = recordio.unpack_img(s)
    assert h2.label == 7.0 and h2.id == 42
    np.testing.assert_array_equal(img, img2)
    # multi-label pack
    s = recordio.pack(recordio.IRHeader(0, [1.0, 2.0, 3.0], 1, 0), b"x")
    h3, payload = recordio.unpack(s)
    np.testing.assert_allclose(h3.label, [1, 2, 3])
    assert payload == b"x"


def test_transforms_pipeline():
    img = (np.random.rand(40, 60, 3) * 255).astype("uint8")
    t = transforms.Compose([
        transforms.Resize((32, 32)),
        transforms.ToTensor(),
        transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25)),
    ])
    out = t(img)
    assert out.shape == (3, 32, 32)
    assert out.dtype == np.float32
    cc = transforms.CenterCrop(24)(img)
    assert cc.shape == (24, 24, 3)
    rrc = transforms.RandomResizedCrop(16)(img)
    assert rrc.shape == (16, 16, 3)
    jit = transforms.RandomColorJitter(0.4, 0.4, 0.4, 0.1)(img)
    assert jit.shape == img.shape


def test_ndarray_iter():
    from mxnet_tpu.io import NDArrayIter

    X = np.random.randn(10, 2, 2).astype("float32")
    y = np.arange(10).astype("float32")
    it = NDArrayIter(X, y, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 2, 2)
    assert batches[3].pad == 2
    it.reset()
    assert len(list(it)) == 4
    it2 = NDArrayIter(X, y, batch_size=3, last_batch_handle="discard")
    assert len(list(it2)) == 3


def test_csv_iter(tmp_path):
    from mxnet_tpu.io import CSVIter

    data = np.random.rand(8, 6).astype("float32")
    path = str(tmp_path / "d.csv")
    np.savetxt(path, data, delimiter=",")
    it = CSVIter(path, data_shape=(6,), batch_size=4)
    batches = list(it)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4],
                               rtol=1e-6)


def test_image_record_iter(tmp_path):
    from mxnet_tpu import recordio
    from mxnet_tpu.io import ImageRecordIter

    rec = str(tmp_path / "img.rec")
    idx = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(6):
        img = (np.random.rand(36, 36, 3) * 255).astype("uint8")
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    w.close()
    it = ImageRecordIter(rec, data_shape=(3, 32, 32), batch_size=4)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4,)


def test_dataloader_with_transform_end_to_end():
    imgs = (np.random.rand(12, 28, 28, 1) * 255).astype("uint8")
    labels = np.arange(12).astype("int32")
    ds = ArrayDataset(imgs, labels).transform_first(
        transforms.Compose([transforms.ToTensor()]))
    loader = DataLoader(ds, batch_size=6)
    x, y = next(iter(loader))
    assert x.shape == (6, 1, 28, 28)
    assert float(x.asnumpy().max()) <= 1.0


def test_shard_iteration_terminates():
    ds = SimpleDataset(list(range(20))).shard(3, 1)
    items = [x for x in ds]
    assert len(items) == 7
    assert items[0] == 1


def test_ndarray_iter_roll_over():
    from mxnet_tpu.io import NDArrayIter

    X = np.arange(10).astype("float32").reshape(10, 1)
    it = NDArrayIter(X, None, batch_size=4, last_batch_handle="roll_over")
    first = [b.data[0].asnumpy().ravel() for b in it]
    assert [len(b) for b in first] == [4, 4]  # tail of 2 rolled over
    it.reset()
    second = np.concatenate(
        [b.data[0].asnumpy().ravel() for b in it])
    # second epoch leads with the rolled-over samples 8, 9
    np.testing.assert_allclose(second[:2], [8, 9])
    assert len(second) == 12  # 2 leftover + 10


def test_dataloader_thread_pool_isolation():
    ds1 = SimpleDataset([np.full((2,), 1.0, dtype="float32")] * 8)
    ds2 = SimpleDataset([np.full((2,), 2.0, dtype="float32")] * 8)
    a = DataLoader(ds1, batch_size=4, num_workers=2, thread_pool=True)
    b = DataLoader(ds2, batch_size=4, num_workers=2, thread_pool=True)
    assert float(next(iter(a)).asnumpy().mean()) == 1.0
    assert float(next(iter(b)).asnumpy().mean()) == 2.0


def test_recordio_multipart_write(tmp_path, monkeypatch):
    from mxnet_tpu import recordio

    # shrink the 29-bit length cap so multi-part splitting triggers cheaply
    monkeypatch.setattr(recordio, "_LREC_MASK", 0xF)
    path = str(tmp_path / "mp.rec")
    w = recordio.MXRecordIO(path, "w")
    payload = bytes(range(50))  # 4 parts at cap 15
    w.write(payload)
    w.write(b"tail")
    w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == payload
    assert r.read() == b"tail"


def test_transforms_crop_resize_and_rotation():
    import numpy as np_

    from mxnet_tpu.gluon.data.vision import transforms as T

    img = np_.arange(10 * 12 * 3, dtype="uint8").reshape(10, 12, 3)
    c = T.CropResize(2, 1, 6, 5)(img)
    np_.testing.assert_array_equal(np_.asarray(c), img[1:6, 2:8])
    c2 = T.CropResize(2, 1, 6, 5, size=(4, 4))(img)
    assert np_.asarray(c2).shape == (4, 4, 3)
    # RandomRotation is the reference's post-ToTensor CHW transform:
    # content check — a ~90-degree rotation turns a vertical stripe
    # (mass concentrated in one column) into a horizontal one
    sq = np_.zeros((1, 8, 8), "float32")
    sq[0, :, 2] = 1.0  # vertical stripe at x=2 (CHW)
    rot = np_.asarray(T.RandomRotation((89.999, 90.0))(sq))[0]
    assert rot.shape == (8, 8)
    row_mass = rot.sum(axis=1).max()
    col_mass = rot.sum(axis=0).max()
    assert row_mass > 2 * col_mass
    r = T.RandomRotation((-30, 30))(sq)
    assert np_.asarray(r).shape == (1, 8, 8)
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError as _Err
    with _pytest.raises(_Err, match="float32"):
        T.RandomRotation((-30, 30))(img)  # uint8 HWC: reference raises
    with _pytest.raises(_Err, match="out of bounds"):
        T.CropResize(8, 8, 6, 5)(img)
    # rotate_with_proba=0: identity
    r0 = T.RandomRotation((-30, 30), rotate_with_proba=0.0)(
        img.astype("float32"))
    np_.testing.assert_array_equal(np_.asarray(r0), img.astype("float32"))


def test_ndarray_iter_last_batch_pad_roundtrip():
    """Regression: len(data) % batch_size != 0 must report a correct
    getpad() on the final batch and round-trip every sample exactly once
    per epoch (wrap rows are duplicates, identified by batch.index)."""
    from mxnet_tpu.io import NDArrayIter

    X = np.arange(10, dtype="float32").reshape(10, 1)
    it = NDArrayIter(X, None, batch_size=4, last_batch_handle="pad")
    seen, pads = [], []
    for batch in it:
        vals = batch.data[0].asnumpy().ravel()
        assert batch.data[0].shape == (4, 1)  # fixed shape incl. tail
        assert len(batch.index) == 4
        np.testing.assert_array_equal(vals, X[batch.index].ravel())
        real = 4 - batch.pad
        seen.extend(vals[:real].tolist())
        pads.append(batch.pad)
    assert pads == [0, 0, 2]  # only the final batch pads
    assert sorted(seen) == list(range(10))  # no sample dropped, none twice


def test_ndarray_iter_pad_wraps_repeatedly():
    """batch_size > num_data: the pad wrap must repeat until the batch is
    full (a single wrap used to emit a short, shape-breaking batch)."""
    from mxnet_tpu.io import NDArrayIter

    X = np.arange(3, dtype="float32").reshape(3, 1)
    it = NDArrayIter(X, None, batch_size=8, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 1
    assert batches[0].data[0].shape == (8, 1)
    assert batches[0].pad == 5
    np.testing.assert_array_equal(
        batches[0].data[0].asnumpy().ravel(),
        [0, 1, 2, 0, 1, 2, 0, 1])


def test_prefetch_iter_matches_wrapped_iter():
    from mxnet_tpu.io import NDArrayIter, PrefetchIter

    X = np.random.randn(10, 3).astype("float32")
    y = np.arange(10).astype("float32")
    ref = [(b.data[0].asnumpy(), b.label[0].asnumpy())
           for b in NDArrayIter(X, y, batch_size=3)]
    pf = PrefetchIter(NDArrayIter(X, y, batch_size=3), num_prefetch=2)
    assert pf.batch_size == 3
    assert [d.name for d in pf.provide_data] == ["data"]
    for epoch in range(2):  # reset() must restart cleanly
        got = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in pf]
        assert len(got) == len(ref)
        for (gd, gl), (rd, rl) in zip(got, ref):
            np.testing.assert_array_equal(gd, rd)
            np.testing.assert_array_equal(gl, rl)
        pf.reset()


def test_prefetch_iter_propagates_producer_error():
    from mxnet_tpu.io import DataIter, PrefetchIter

    class Boom(DataIter):
        def __init__(self):
            super().__init__(2)
            self.n = 0

        def iter_next(self):
            self.n += 1
            if self.n > 2:
                raise RuntimeError("producer exploded")
            return True

        def getdata(self):
            return [mx.np.zeros((2, 1))]

        def getlabel(self):
            return []

        def getpad(self):
            return 0

        @property
        def provide_data(self):
            return []

        @property
        def provide_label(self):
            return []

    pf = PrefetchIter(Boom(), num_prefetch=2)
    next(pf)
    next(pf)
    with pytest.raises(RuntimeError, match="producer exploded"):
        next(pf)
    # the error is sticky, not a deadlock: the producer thread has
    # exited, so a blocking queue.get() here would hang forever
    with pytest.raises(RuntimeError, match="producer exploded"):
        next(pf)


def test_prefetch_iter_repeats_stop_iteration_after_exhaustion():
    from mxnet_tpu.io import NDArrayIter, PrefetchIter

    pf = PrefetchIter(NDArrayIter(np.zeros((4, 1), "float32"),
                                  batch_size=2), num_prefetch=2)
    assert len(list(pf)) == 2
    for _ in range(3):  # regression: this used to block forever
        with pytest.raises(StopIteration):
            next(pf)
    pf.reset()
    assert len(list(pf)) == 2


def test_prefetching_iter_legacy_wrapper():
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter

    X = np.arange(12, dtype="float32").reshape(6, 2)
    ref = [b.data[0].asnumpy() for b in NDArrayIter(X, batch_size=2)]
    it = PrefetchingIter([NDArrayIter(X, batch_size=2)])
    got = [b.data[0].asnumpy() for b in it]
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g, r)
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_iter_rejects_bad_depth():
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.io import NDArrayIter, PrefetchIter

    with pytest.raises(MXNetError, match="num_prefetch"):
        PrefetchIter(NDArrayIter(np.zeros((4, 1), "float32"),
                                 batch_size=2), num_prefetch=0)
