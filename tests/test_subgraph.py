"""Subgraph backend / optimize_for pass registry tests (reference
subgraph_property.h partition API, redesigned as function-transform
passes over the traced forward)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu import subgraph


def _net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(8))
    net.initialize()
    return net


def test_builtin_backends_listed():
    assert {"remat", "bf16"} <= set(subgraph.list_backends())
    with pytest.raises(mx.MXNetError, match="unknown subgraph backend"):
        subgraph.get_backend_passes("nope")


def test_optimize_for_remat_matches_plain():
    net = _net()
    x = np.array(onp.random.randn(4, 16).astype("float32"))
    with autograd.predict_mode():
        want = net(x).asnumpy()
        net.optimize_for(x, backend="remat")
        got = net(x).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_optimize_for_bf16_casts_compute():
    net = _net()
    x = np.array(onp.random.randn(4, 16).astype("float32"))
    with autograd.predict_mode():
        want = net(x).asnumpy()
        net.optimize_for(x, backend="bf16")
        got = net(x)
        assert got.dtype == onp.float32  # cast back at the boundary
        got = got.asnumpy()
    # bf16 compute: close but not bit-identical
    onp.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    assert (got != want).any()


def test_custom_registered_pass_applies():
    calls = []

    @subgraph.register_pass("test_double")
    def double_pass(fn):
        def wrapped(*args):
            calls.append(1)
            out, states = fn(*args)
            return [o * 2 for o in out], states
        return wrapped

    net = _net()
    x = np.array(onp.random.randn(4, 16).astype("float32"))
    with autograd.predict_mode():
        want = net(x).asnumpy()
        net.optimize_for(x, backend="test_double")
        got = net(x).asnumpy()
    onp.testing.assert_allclose(got, want * 2, rtol=1e-5)
    assert calls  # the pass really wrapped the trace


def test_remat_trains():
    net = _net()
    x = np.array(onp.random.randn(8, 16).astype("float32"))
    y = np.array(onp.random.randint(0, 8, (8,)))
    with autograd.predict_mode():
        net(x)
    net.optimize_for(x, backend="remat")
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(8):
        with autograd.record():
            l = loss_fn(net(x), y).mean()
        l.backward()
        tr.step(1)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0]
